"""Fleet federation: a front-door router over N CheckService replicas.

Every robustness primitive the service grew — poison quarantine,
circuit breaker, admission journal, idempotency map, drain-to-
checkpoint — protects exactly one process; a single SIGKILL still
takes down the whole front door.  This module federates N replicas
(in-process ``CheckService`` instances or subprocess HTTP workers,
each with its own journal/evidence/drain dirs) behind one router so a
replica death is a degraded-capacity event, not an outage:

  * **Geometry-affinity routing** — a request hashes by its padded
    batch geometry (``affinity_key``: the same ``wgl.pack`` +
    ``bucket_geometry`` key the service groups batches by; graph work
    by ``graph_batch_key``) onto a rendezvous (highest-random-weight)
    ordering of the replicas.  Compile caches are the expensive
    per-replica state, so requests route to the replica whose cache is
    already warm for their bucket — the hash-bucketed locality idea
    batched beam search uses on accelerators.  Rendezvous hashing
    means fencing a replica moves only ITS keys.
  * **Power-of-two-choices spill** — when the owner's queue depth
    fraction or SLO burn rate (serve.slo) crosses a threshold, the
    router compares the owner against the second rendezvous choice and
    routes to the less-loaded of the two (``fleet.spilled``).
  * **Failure containment** — ``probe()`` health-checks every replica
    (readiness + forward-progress staleness: pending work with no
    completed batches for ``stale_after_s`` reads as wedged); a dead
    or wedged replica is FENCED and its in-flight requests are
    resubmitted through the router under their history-scoped
    idempotency keys.  The shared ``IdempotencyMap`` (``shared=True``,
    per-key advisory file locks) makes that exactly-once: a request
    the dying replica already settled answers from the map, one it
    never finished rebinds to the new replica, and a zombie replica's
    late verdict loses the ``settle`` req-id CAS instead of
    overwriting the binding of record.
  * **Fleet-wide blast-radius isolation** — replicas share one
    ``SharedQuarantine`` dir: a history that poisoned a launch on
    replica A is refused at admission on replica B on its first local
    offense, with zero launches spent.
  * **Zero-downtime rollout** — ``rollout()`` cycles replicas one at a
    time: stop routing to the old one, drain it to checkpoint
    (serve.service shutdown drain), start the successor (journal
    replay via ``recover()``), finish the checkpointed work with
    ``resume_drained`` and deliver those verdicts to the original
    futures, then swap the successor in.  The front door never 5xxes:
    requests arriving mid-swap route to the other replicas or park
    until the successor is live.

Telemetry (documented in README / doc/tutorial.md; the graftlint
telemetry inventory enforces the list): counters ``fleet.routed``
``fleet.spilled`` ``fleet.resubmitted`` ``fleet.fenced``
``fleet.parked`` ``fleet.rollouts`` ``fleet.quarantine_hits``, gauges
``fleet.replicas`` ``fleet.replicas_healthy``, spans ``fleet.rollout``
plus the per-request routing spans ``fleet.route`` ``fleet.spill``
``fleet.fence`` ``fleet.resubmit`` — the routing spans are stamped
with the request's trace id (the router MINTS the id at the front
door), so a merged multi-recorder timeline
(``obs.fleetview.merge_trace_events``) links a request's router hop to
its replica-side ``serve.request`` span.  Surfaced on /metrics as
``jepsen_tpu_fleet_*``; with a fleet mounted, ``GET /metrics``
additionally federates live replica scrapes (``replica=`` labels +
``jepsen_tpu_fleet_*`` rollups — obs.fleetview) and ``GET /alerts``
carries fleet-level SLO burn aggregated across replicas.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import logging
import random
import shutil
import subprocess
import sys
import threading
import time
import uuid
from concurrent.futures import TimeoutError as _FutureTimeout
from pathlib import Path
from typing import Mapping, Sequence

from jepsen_tpu import faults, obs, store
from jepsen_tpu import models as m
from jepsen_tpu.serve import health as _health
from jepsen_tpu.serve.sched import admission as _sched_adm
from jepsen_tpu.serve.service import (
    CheckService,
    QueueFull,
    ServiceClosed,
    ServiceUnavailable,
    resume_drained,
)

logger = logging.getLogger(__name__)

__all__ = [
    "FleetFuture",
    "FleetRouter",
    "HttpReplica",
    "LocalReplica",
    "ReplicaDown",
    "affinity_key",
    "spawn_replica",
]


class ReplicaDown(Exception):
    """A replica can't take or answer requests at the transport level
    (process dead, socket refused, service closed) — fence-worthy, as
    opposed to backpressure (QueueFull) or a breaker (503)."""

    def __init__(self, replica: str, cause=None):
        super().__init__(f"replica {replica!r} is down"
                         + (f": {cause}" if cause else ""))
        self.replica = replica
        self.cause = cause


def affinity_key(history, *, model=None, checker=None) -> str:
    """The warm-cache routing key of one request: the SAME grouping
    the service batches by (``CheckService._group_of``) rendered as a
    stable string — model name + padded ``bucket_geometry`` for ladder
    work, the column-shape ``graph_batch_key`` for graph checkers.
    Two requests with equal keys share a compiled kernel, so they
    belong on the same replica."""
    if checker is not None:
        return f"graph:{_sched_adm.graph_batch_key(checker)!r}"
    from jepsen_tpu.ops import wgl
    from jepsen_tpu.parallel import batch

    model = model if model is not None else m.CASRegister()
    try:
        p = wgl.pack(model, list(history))
    except wgl.NotTensorizable:
        return f"{model.name}:untensorizable"
    if p["B"] == 0:
        return f"{model.name}:trivial"
    geom = batch.bucket_geometry(p["B"], p["P"], p["G"])
    return f"{model.name}:{geom}"


def _rendezvous(key: str, names: Sequence[str]) -> list[str]:
    """Highest-random-weight ordering of ``names`` for ``key``: every
    router instance agrees on the owner without coordination, and
    removing a name reshuffles only that name's keys."""
    return sorted(
        names,
        key=lambda n: hashlib.sha256(f"{key}|{n}".encode()).digest(),
        reverse=True,
    )


class FleetFuture:
    """The router-owned future a fleet submission resolves: survives
    resubmission across replicas (the per-replica CheckFutures come
    and go underneath).  ``id`` tracks the CURRENT replica request id
    (preserved across journal replay; fresh after a rebind)."""

    def __init__(self):
        self._ev = threading.Event()
        self._lock = threading.Lock()
        self._result = None
        self._exc: BaseException | None = None
        self._cbs: list = []
        self.id: str | None = None

    def done(self) -> bool:
        return self._ev.is_set()

    def cancelled(self) -> bool:
        return False

    def _settle(self, result=None, exc: BaseException | None = None) -> bool:
        """First write wins; returns whether THIS write won."""
        with self._lock:
            if self._ev.is_set():
                return False
            self._result, self._exc = result, exc
            cbs, self._cbs = self._cbs, []
            self._ev.set()
        for fn in cbs:
            try:
                fn(self)
            except Exception:  # noqa: BLE001 — callbacks are best-effort
                logger.exception("fleet future callback failed")
        return True

    def set_result(self, result) -> bool:
        return self._settle(result=result)

    def set_exception(self, exc: BaseException) -> bool:
        return self._settle(exc=exc)

    def result(self, timeout: float | None = None):
        if not self._ev.wait(timeout):
            raise _FutureTimeout()
        if self._exc is not None:
            raise self._exc
        return self._result

    def add_done_callback(self, fn) -> None:
        with self._lock:
            if not self._ev.is_set():
                self._cbs.append(fn)
                return
        fn(self)


class _Entry:
    """One routed request: everything needed to resubmit it verbatim
    (same history, same idempotency key) if its replica is fenced."""

    __slots__ = (
        "eid", "history", "model", "priority", "deadline", "client",
        "trace_id", "class_", "checker", "idem_key", "affinity",
        "future", "replica", "rep_id", "rep_ids", "resubmits",
        "suspended", "route_s",
    )

    def __init__(self, *, history, model, priority, deadline, client,
                 trace_id, class_, checker, idem_key, affinity):
        self.eid = uuid.uuid4().hex[:12]
        self.history = history
        self.model = model
        self.priority = priority
        self.deadline = deadline
        self.client = client
        self.trace_id = trace_id
        self.class_ = class_
        self.checker = checker
        self.idem_key = idem_key
        self.affinity = affinity
        self.future = FleetFuture()
        self.replica: str | None = None
        self.rep_id: str | None = None
        self.rep_ids: list[str] = []   # every id this entry ever held
        self.resubmits = 0
        self.suspended = False
        #: router-side seconds spent getting this entry ACCEPTED by a
        #: replica (admission → accept, summed across resubmissions) —
        #: stamped into the settled result's latency block as route_s.
        self.route_s = 0.0


# ---------------------------------------------------------------------------
# Replica transports
# ---------------------------------------------------------------------------


class LocalReplica:
    """An in-process ``CheckService`` behind the router."""

    kind = "local"

    def __init__(self, name: str, svc: CheckService):
        self.name = str(name)
        self.svc = svc
        self.router: "FleetRouter | None" = None
        self._stats_cache: tuple[float, dict] | None = None

    def submit(self, entry: _Entry) -> str:
        try:
            fut = self.svc.submit(
                entry.history, model=entry.model, priority=entry.priority,
                deadline=entry.deadline, client=entry.client,
                trace_id=entry.trace_id, class_=entry.class_,
                checker=entry.checker, idempotency_key=entry.idem_key,
            )
        except ServiceClosed as e:
            raise ReplicaDown(self.name, e) from e
        router, name = self.router, self.name

        def _cb(f, entry=entry, name=name):
            try:
                res = f.result(timeout=0)
            except BaseException as e:  # noqa: BLE001 — routed to the
                # fleet future as-is below
                router._on_error(entry, name, e)
                return
            router._on_result(entry, name, res)

        fut.add_done_callback(_cb)
        return str(fut.id)

    def ready(self) -> tuple[bool, dict, bool]:
        """(accepting-new-work, info, fatal).  fatal marks fence-worthy
        states (closed); a breaker-open replica is unready but ALIVE —
        fencing it would churn resubmissions for nothing."""
        if self.svc._closed:
            return False, {"reason": "closed"}, True
        br = self.svc.breaker.describe()
        if br.get("state") == "open":
            return False, {"reason": "breaker open", "breaker": br}, False
        return True, {"breaker": br}, False

    def stats(self, max_age_s: float = 0.25) -> dict:
        now = time.monotonic()
        c = self._stats_cache
        if c is not None and now - c[0] < max_age_s:
            return c[1]
        st = self.svc.stats()
        self._stats_cache = (now, st)
        return st

    def burn(self) -> float:
        """The worst fast-window burn fraction across SLOs (>=1.0
        means a firing-level burn)."""
        try:
            rows = self.svc.slo.evaluate()
        except Exception:  # noqa: BLE001 — routing hint only
            return 0.0
        worst = 0.0
        for r in rows:
            thr = float(r.get("burn_threshold") or 0) or 1.0
            worst = max(worst, float(r.get("burn_fast") or 0.0) / thr)
        return worst

    def alerts(self) -> dict:
        return self.svc.slo.alerts()

    def scrape_metrics(self) -> str:
        """A minimal per-replica exposition synthesized from this
        service's stats.  In-process replicas all mirror into the ONE
        process-global registry — re-exporting that registry once per
        local replica would multiply every series by N — so the
        ``replica=``-labeled view for a local replica carries only the
        per-service totals the service itself attributes (the shared
        registry already IS their fleet aggregate and passes through
        ``federate()`` unlabeled)."""
        st = self.svc.stats()
        lines = []
        for key in ("submitted", "completed", "rejected", "expired",
                    "batches"):
            if st.get(key) is not None:
                n = f"jepsen_tpu_serve_{key}_total"
                lines += [f"# TYPE {n} counter", f"{n} {int(st[key])}"]
        for key, gname in (("queue_depth", "queue_depth"),
                           ("running", "running")):
            if st.get(key) is not None:
                n = f"jepsen_tpu_serve_{gname}"
                lines += [f"# TYPE {n} gauge", f"{n} {int(st[key])}"]
        return "\n".join(lines) + ("\n" if lines else "")

    def telemetry_info(self) -> dict | None:
        """Recorder-stream discovery for the timeline merger.  A local
        replica shares the router process's recorder (one stream for
        the whole in-process side), flagged ``shared`` so the merger
        doesn't read the same file N times."""
        rec = obs._RECORDER
        if rec is None:
            return None
        return {
            "shared": True, "dir": str(rec.dir), "jsonl": str(rec.path),
            "t0": next((e.get("t0") for e in rec.events[:1]), None),
        }

    def metrics_url(self) -> str | None:
        return None  # in-process: series live in the router's registry

    def get(self, rep_id: str) -> dict | None:
        req = self.svc.get(rep_id)
        return req.describe() if req is not None else None

    def get_evidence(self, rep_id: str) -> dict | None:
        return self.svc.get_evidence(rep_id)

    def close(self, *, drain: bool = False) -> None:
        with contextlib.suppress(Exception):
            self.svc.shutdown(drain=drain)


class HttpReplica:
    """A subprocess/remote replica spoken to over the HTTP surface
    (POST /check with ``wait: false``; completion via a GET
    /check/<id> poller thread).  Graph-checker submissions aren't
    expressible over the wire — the router keeps those on local
    replicas."""

    kind = "http"

    def __init__(self, name: str, base_url: str, *, poll_s: float = 0.02,
                 timeout_s: float = 10.0):
        self.name = str(name)
        self.base_url = str(base_url).rstrip("/")
        self.poll_s = float(poll_s)
        self.timeout_s = float(timeout_s)
        self.router: "FleetRouter | None" = None
        self._plock = threading.Lock()
        self._pending: dict[str, _Entry] = {}    # guarded-by: _plock [rw]
        self._poller: threading.Thread | None = None
        self._stop = threading.Event()
        self._stats_cache: tuple[float, dict] | None = None
        host, _, port = self.base_url.rpartition("//")[2].partition(":")
        self._host, self._port = host, int(port or 80)

    def _request(self, method: str, path: str, body=None) -> tuple[int, dict]:
        import http.client

        conn = http.client.HTTPConnection(
            self._host, self._port, timeout=self.timeout_s
        )
        try:
            data = json.dumps(body).encode() if body is not None else None
            headers = {"Content-Type": "application/json"} if data else {}
            conn.request(method, path, body=data, headers=headers)
            resp = conn.getresponse()
            raw = resp.read()
            try:
                payload = json.loads(raw) if raw else {}
            except ValueError:
                payload = {}
            return resp.status, payload
        except OSError as e:
            raise ReplicaDown(self.name, e) from e
        finally:
            with contextlib.suppress(Exception):
                conn.close()

    def _request_text(self, path: str) -> tuple[int, str]:
        """Raw-text GET (the Prometheus exposition is not JSON)."""
        import http.client

        conn = http.client.HTTPConnection(
            self._host, self._port, timeout=self.timeout_s
        )
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            return resp.status, resp.read().decode("utf-8", "replace")
        except OSError as e:
            raise ReplicaDown(self.name, e) from e
        finally:
            with contextlib.suppress(Exception):
                conn.close()

    def scrape_metrics(self) -> str:
        """This replica's raw ``GET /metrics`` exposition — the
        federation and fleet-burn input."""
        status, text = self._request_text("/metrics")
        if status != 200:
            raise ReplicaDown(self.name, f"GET /metrics -> {status}")
        return text

    def telemetry_info(self) -> dict | None:
        """The replica's recorder-stream announcement (GET /telemetry):
        jsonl path + t0 epoch, or None when it records nothing."""
        try:
            status, data = self._request("GET", "/telemetry")
        except ReplicaDown:
            return None
        if status != 200 or not data.get("recording"):
            return None
        return {"shared": False, "dir": data.get("dir"),
                "jsonl": data.get("jsonl"), "t0": data.get("t0"),
                "pid": data.get("pid"), "host": data.get("host")}

    def metrics_url(self) -> str | None:
        return f"{self.base_url}/metrics"

    def submit(self, entry: _Entry) -> str:
        if entry.checker is not None:
            raise QueueFull(0, 0, 1.0, tier=entry.class_ or "batch")
        payload: dict = {
            "history": store._jsonable(list(entry.history)),
            "client": entry.client,
            "priority": entry.priority,
            "wait": False,
        }
        if entry.model is not None:
            payload["model"] = entry.model.name
        if entry.class_ is not None:
            payload["class"] = entry.class_
        if entry.trace_id is not None:
            payload["trace_id"] = entry.trace_id
        if entry.idem_key is not None:
            payload["idempotency_key"] = entry.idem_key
        if entry.deadline is not None:
            payload["deadline"] = entry.deadline.remaining()
        status, data = self._request("POST", "/check", payload)
        if status == 429:
            raise QueueFull(
                int(data.get("depth") or 0), int(data.get("limit") or 0),
                float(data.get("retry_after_s") or 1.0),
                tier=entry.class_ or "batch",
            )
        if status == 503:
            raise ServiceUnavailable(float(data.get("retry_after_s") or 1.0))
        if status not in (200, 202) or not data.get("id"):
            raise ReplicaDown(self.name, f"POST /check -> {status}")
        rep_id = str(data["id"])
        if data.get("result") is not None:
            self.router._on_result(entry, self.name, data["result"])
            return rep_id
        with self._plock:
            self._pending[rep_id] = entry
        self._ensure_poller()
        return rep_id

    def _ensure_poller(self) -> None:
        if self._poller is not None and self._poller.is_alive():
            return
        self._stop.clear()
        self._poller = threading.Thread(
            target=self._poll_loop, name=f"fleet-poll-{self.name}",
            daemon=True,
        )
        self._poller.start()

    def _poll_loop(self) -> None:
        misses: dict[str, int] = {}
        while not self._stop.is_set():
            with self._plock:
                items = list(self._pending.items())
            if not items:
                # idle poller exits; the next submit restarts it
                return
            for rep_id, entry in items:
                if self._stop.is_set():
                    return
                try:
                    status, data = self._request("GET", f"/check/{rep_id}")
                except ReplicaDown:
                    router = self.router
                    if router is not None:
                        router.fence(self.name, reason="poll transport down")
                    return
                if status == 200 and data.get("result") is not None:
                    with self._plock:
                        self._pending.pop(rep_id, None)
                    self.router._on_result(entry, self.name, data["result"])
                elif status == 404:
                    # the request evaporated (e.g. replica restarted
                    # without its journal): after a grace of a few
                    # polls, hand it back to the router to resubmit
                    misses[rep_id] = misses.get(rep_id, 0) + 1
                    if misses[rep_id] >= 5:
                        with self._plock:
                            self._pending.pop(rep_id, None)
                        misses.pop(rep_id, None)
                        self.router._on_gone(entry, self.name)
            self._stop.wait(self.poll_s)

    def drop_pending(self) -> list[_Entry]:
        """Forget every in-flight poll target (the router fenced us);
        returns the entries so the router can resubmit them."""
        with self._plock:
            out = list(self._pending.values())
            self._pending.clear()
        return out

    def ready(self) -> tuple[bool, dict, bool]:
        try:
            status, data = self._request("GET", "/readyz")
        except ReplicaDown as e:
            return False, {"reason": str(e)}, True
        if status == 200:
            return True, data, False
        fatal = "shutting down" in str(data.get("reason") or "")
        return False, data, fatal

    def stats(self, max_age_s: float = 0.25) -> dict:
        now = time.monotonic()
        c = self._stats_cache
        if c is not None and now - c[0] < max_age_s:
            return c[1]
        status, data = self._request("GET", "/queue")
        if status != 200:
            raise ReplicaDown(self.name, f"GET /queue -> {status}")
        self._stats_cache = (now, data)
        return data

    def burn(self) -> float:
        try:
            status, data = self._request("GET", "/alerts")
        except ReplicaDown:
            return 0.0
        worst = 0.0
        for r in data.get("slos") or []:
            thr = float(r.get("burn_threshold") or 0) or 1.0
            worst = max(worst, float(r.get("burn_fast") or 0.0) / thr)
        return worst

    def alerts(self) -> dict:
        status, data = self._request("GET", "/alerts")
        return data if status == 200 else {"error": status}

    def get(self, rep_id: str) -> dict | None:
        try:
            status, data = self._request("GET", f"/check/{rep_id}")
        except ReplicaDown:
            return None
        return data if status == 200 else None

    def get_evidence(self, rep_id: str) -> dict | None:
        try:
            status, data = self._request("GET", f"/evidence/{rep_id}")
        except ReplicaDown:
            return None
        return data if status == 200 else None

    def close(self, *, drain: bool = False) -> None:
        self._stop.set()


# ---------------------------------------------------------------------------
# The front-door router
# ---------------------------------------------------------------------------


class FleetRouter:
    """The front door over N replicas.  Duck-types enough of the
    ``CheckService`` surface (``submit``/``stats``/``get``/
    ``get_evidence``) that the web layer can mount it, while the
    fleet-only verbs (``fence``/``probe``/``rollout``) manage replica
    lifecycle.

    ``spill_depth_frac``: owner queue-depth fraction above which the
    power-of-two spill engages.  ``spill_burn``: owner SLO fast-burn
    fraction (burn/threshold) with the same effect.  ``fence_after``:
    consecutive failed probes before a fatal-unhealthy replica is
    fenced.  ``stale_after_s``: pending work with no forward progress
    for this long reads as wedged (launch-EWMA-scale staleness).
    ``load_hint_age_s``: how stale a replica's cached queue-depth
    snapshot may be when the spill comparison reads it — tighten it
    (loadgen uses 0.02) when launch latency is on the order of the
    default 0.25s cache, or the power-of-two choice compares last
    epoch's depths and sheds into yesterday's short queue.
    ``mint_keys``: mint a history-scoped idempotency key for keyless
    submits (the default — it is what makes SIGKILL-mid-load
    resubmission exactly-once even for clients that never heard of
    idempotency keys); False skips the mint, trading the keyless
    exactly-once guard for one less durable claim per request.
    ``successor_factory(name, old_svc) -> CheckService`` powers
    ``rollout()``.  ``slo_specs`` (spec list or a specs-file path;
    None → serve.slo.DEFAULT_SLOS) configures the FLEET-level burn
    engine evaluated in ``alerts()`` over federated replica scrapes."""

    def __init__(self, *, spill_depth_frac: float = 0.5,
                 spill_burn: float = 1.0, fence_after: int = 3,
                 stale_after_s: float = 120.0,
                 load_hint_age_s: float = 0.25,
                 mint_keys: bool = True,
                 probe_every_s: float | None = None,
                 successor_factory=None,
                 slo_specs=None):
        self.spill_depth_frac = float(spill_depth_frac)
        self.spill_burn = float(spill_burn)
        self.load_hint_age_s = float(load_hint_age_s)
        self.mint_keys = bool(mint_keys)
        self.fence_after = int(fence_after)
        self.stale_after_s = float(stale_after_s)
        self.probe_every_s = probe_every_s
        self.successor_factory = successor_factory
        self._lock = threading.RLock()
        self._replicas: dict[str, object] = {}   # guarded-by: _lock [rw]
        self._fenced: set[str] = set()           # guarded-by: _lock [rw]
        self._rolling: set[str] = set()          # guarded-by: _lock [rw]
        self._unready: set[str] = set()          # guarded-by: _lock [rw]
        self._entries: dict[str, _Entry] = {}    # guarded-by: _lock [rw]
        self._parked: list[_Entry] = []          # guarded-by: _lock [rw]
        self._probe_state: dict[str, dict] = {}  # guarded-by: _lock [rw]
        self._totals = {                         # guarded-by: _lock [rw]
            "routed": 0, "spilled": 0, "resubmitted": 0, "fenced": 0,
            "parked": 0, "rollouts": 0, "completed": 0, "rejected": 0,
            "errors": 0, "duplicate_settles": 0,
        }
        self._t_start = time.monotonic()
        self._rng = random.Random(0x5EED)        # guarded-by: _lock [rw]
        self._probe_thread: threading.Thread | None = None
        self._stop = threading.Event()
        self._closed = False
        # Fleet-level SLO burn over federated replica scrapes
        # (obs.fleetview).  Built NOW, not lazily at the first alerts
        # call: the engine's construction-time baseline must predate
        # traffic or pre-existing replica counts read as in-window
        # burn.  The base registry folds the in-process side in —
        # LocalReplica observations land in the process-global registry,
        # which already IS their aggregate.
        from jepsen_tpu.obs import fleetview as _fleetview
        from jepsen_tpu.obs import metrics as _metrics
        self._fleet_slo = _fleetview.FleetSlo(
            slo_specs, base_registry=_metrics.REGISTRY)
        self._fleet_slo_lock = threading.Lock()

    # -- replica lifecycle ---------------------------------------------

    def add_replica(self, replica) -> "FleetRouter":
        with self._lock:
            replica.router = self
            self._replicas[replica.name] = replica
            self._fenced.discard(replica.name)
        self._gauge_health()
        self._drain_parked()
        return self

    def add_local(self, name: str, svc: CheckService) -> "FleetRouter":
        return self.add_replica(LocalReplica(name, svc))

    def replicas(self) -> dict:
        with self._lock:
            return dict(self._replicas)

    def start(self) -> "FleetRouter":
        """Start the background health-probe loop (``probe_every_s``;
        no-op when None — step-driven callers invoke ``probe()``
        themselves)."""
        if self.probe_every_s and self._probe_thread is None:
            self._stop.clear()
            self._probe_thread = threading.Thread(
                target=self._probe_loop, name="fleet-probe", daemon=True
            )
            self._probe_thread.start()
        self._gauge_health()
        return self

    def _probe_loop(self) -> None:
        while not self._stop.wait(self.probe_every_s):
            try:
                self.probe()
            except Exception:  # noqa: BLE001 — the probe loop must
                # outlive any single replica's weird failure mode
                logger.exception("fleet probe failed")

    def shutdown(self, *, drain: bool = False) -> None:
        self._closed = True
        self._stop.set()
        if self._probe_thread is not None:
            self._probe_thread.join(timeout=10.0)
            self._probe_thread = None
        for rep in self.replicas().values():
            rep.close(drain=drain)

    # -- admission ------------------------------------------------------

    def submit(self, history, *, model=None, priority: int = 0,
               deadline=None, client: str = "anon",
               trace_id: str | None = None, class_: str | None = None,
               checker=None, idempotency_key: str | None = None
               ) -> FleetFuture:
        """Route one request to its affinity owner (spilling when the
        owner is hot); returns a ``FleetFuture``.  Raises ``QueueFull``
        re-quoted with the MIN retry-after across live replicas — a
        full replica is not a full fleet — and ``ServiceUnavailable``
        only when EVERY replica's breaker is open."""
        if self._closed:
            raise ServiceClosed("fleet router is shutting down")
        if checker is None and model is None:
            model = m.CASRegister()
        # Mint the trace id at the FRONT DOOR: the router's routing
        # spans and the replica's serve.request span must share one id
        # for the merged timeline to link the hop (an HTTP replica
        # would otherwise mint its own on the far side).
        if trace_id is None:
            trace_id = obs.new_trace_id()
        key = affinity_key(history, model=model, checker=checker)
        if idempotency_key is None and self.mint_keys:
            # History-scoped by construction: the fingerprint prefix
            # ties the key to THIS history (the map rejects fp-mismatch
            # reuse), the suffix keeps logical requests distinct.
            fp = (_health.history_fingerprint(history)
                  if checker is None else "graph")
            idempotency_key = f"fleet-{fp[:16]}-{uuid.uuid4().hex[:12]}"
        entry = _Entry(
            history=list(history), model=model, priority=int(priority),
            deadline=faults.Deadline.coerce(deadline), client=str(client),
            trace_id=trace_id, class_=class_, checker=checker,
            idem_key=(None if idempotency_key is None
                      else str(idempotency_key)),
            affinity=key,
        )
        self._route(entry, raise_on_reject=True)
        return entry.future

    def _candidates(self, entry: _Entry) -> list[str]:
        with self._lock:
            alive = [n for n in self._replicas if n not in self._fenced]
            unready = set(self._unready)
            local = {n for n, r in self._replicas.items()
                     if getattr(r, "kind", "") == "local"}
        if entry.checker is not None:
            alive = [n for n in alive if n in local]
        order = _rendezvous(entry.affinity, alive)
        # ready replicas first (rendezvous order), unready (alive but
        # e.g. breaker-open) as last resorts so their 503 quotes still
        # aggregate into the fleet-level answer
        return ([n for n in order if n not in unready]
                + [n for n in order if n in unready])

    def _load_frac(self, name: str) -> float:
        with self._lock:
            rep = self._replicas.get(name)
        if rep is None:
            return 1.0
        try:
            st = rep.stats(max_age_s=self.load_hint_age_s)
        except Exception:  # noqa: BLE001 — routing hint only
            return 1.0
        depth = int(st.get("queue_depth") or 0) + int(st.get("running") or 0)
        return depth / max(1, int(st.get("max_queue") or 1))

    def _route(self, entry: _Entry, *, raise_on_reject: bool) -> bool:
        order = self._candidates(entry)
        if not order:
            with self._lock:
                rolling = bool(self._rolling) or bool(self._replicas)
            if rolling and not self._closed:
                # every replica is mid-rollout/fenced but the fleet
                # exists: park — the work flows when a replica returns
                # (this is what keeps a rollout 5xx-free)
                self._park(entry)
                return False
            raise ServiceUnavailable(1.0)
        choice = order[0]
        spilled = False
        t_admit = time.monotonic()
        if len(order) > 1:
            with self._lock:
                rep0 = self._replicas.get(order[0])
            owner_frac = self._load_frac(order[0])
            owner_burn = rep0.burn() if rep0 is not None else 0.0
            if (owner_frac >= self.spill_depth_frac
                    or owner_burn >= self.spill_burn):
                # canonical power-of-two-choices: the alternate is a
                # RANDOM non-owner, not the rendezvous runner-up — a
                # fixed runner-up starves every replica that is rank-3+
                # for all hot keys (observed: one of three replicas
                # pinned near-idle under a 5-key workload)
                with self._lock:
                    alt = self._rng.choice(order[1:])
                with obs.attach(trace=entry.trace_id), \
                        obs.span("fleet.spill", owner=order[0], alt=alt,
                                 owner_frac=round(owner_frac, 4),
                                 owner_burn=round(owner_burn, 4)) as sp:
                    shed = self._load_frac(alt) < owner_frac
                    sp.set(shed=shed)
                if shed:
                    choice, spilled = alt, True
        quotes: list[float] = []
        depths, limits = 0, 0
        all_breaker = True
        for name in [choice] + [n for n in order if n != choice]:
            with self._lock:
                rep = self._replicas.get(name)
                if rep is None or name in self._fenced:
                    continue
                entry.suspended = False
                entry.replica = name
                self._entries[entry.eid] = entry
            try:
                # the route span covers router admission → replica
                # ACCEPT for this attempt, under the request's trace id
                # (the cross-process link to the replica-side
                # serve.request span)
                with obs.attach(trace=entry.trace_id), \
                        obs.span("fleet.route", replica=name,
                                 affinity=entry.affinity,
                                 spilled=spilled and name == choice,
                                 resubmit=entry.resubmits):
                    rep_id = rep.submit(entry)
            except QueueFull as e:
                all_breaker = False
                quotes.append(float(e.retry_after))
                depths += int(getattr(e, "depth", 0) or 0)
                limits += int(getattr(e, "limit", 0) or 0)
                continue
            except ServiceUnavailable as e:
                quotes.append(float(e.retry_after))
                continue
            except ReplicaDown:
                self.fence(name, reason="submit transport down")
                continue
            except BaseException:
                with self._lock:
                    self._entries.pop(entry.eid, None)
                raise
            entry.route_s += time.monotonic() - t_admit
            entry.rep_id = rep_id
            entry.rep_ids.append(rep_id)
            entry.future.id = rep_id
            with self._lock:
                self._totals["routed"] += 1
                if spilled and name == choice:
                    self._totals["spilled"] += 1
            obs.counter("fleet.routed", replica=name)
            if spilled and name == choice:
                obs.counter("fleet.spilled")
            return True
        with self._lock:
            self._entries.pop(entry.eid, None)
        if not raise_on_reject:
            self._park(entry)
            return False
        with self._lock:
            self._totals["rejected"] += 1
        retry_after = min(quotes) if quotes else 1.0
        if all_breaker and quotes:
            # every live replica answered 503: the FLEET is unavailable
            raise ServiceUnavailable(retry_after)
        raise QueueFull(depths, limits or depths, retry_after,
                        tier=entry.class_ or "batch")

    def _park(self, entry: _Entry) -> None:
        with self._lock:
            self._parked.append(entry)
            self._totals["parked"] += 1
        obs.counter("fleet.parked")

    def _drain_parked(self) -> None:
        with self._lock:
            parked, self._parked = self._parked, []
        for e in parked:
            if not e.future.done():
                self._route(e, raise_on_reject=False)

    # -- completion delivery -------------------------------------------

    def _on_result(self, entry: _Entry, name: str, result) -> None:
        with self._lock:
            if entry.suspended or entry.replica != name:
                return  # fenced/zombie source: the resubmission owns it
            self._entries.pop(entry.eid, None)
            self._totals["completed"] += 1
        # Name the hop cost: the replica's latency block covers its own
        # submit→resolve; the router adds the admission→accept seconds
        # it measured on ITS side as a route_s stage and grows total_s
        # by exactly that, so the stages still sum to the total.
        if isinstance(result, Mapping) and entry.route_s > 0:
            lat = result.get("latency")
            if isinstance(lat, Mapping) and "route_s" not in lat:
                r = round(entry.route_s, 6)
                result = {**result, "latency": {
                    **lat, "route_s": r,
                    "total_s": round(float(lat.get("total_s") or 0.0) + r,
                                     6),
                }}
        if not entry.future.set_result(result):
            with self._lock:
                self._totals["duplicate_settles"] += 1

    def _on_error(self, entry: _Entry, name: str, exc: BaseException) -> None:
        with self._lock:
            if entry.suspended or entry.replica != name:
                return
            self._entries.pop(entry.eid, None)
            self._totals["errors"] += 1
        entry.future.set_exception(exc)

    def _on_gone(self, entry: _Entry, name: str) -> None:
        """The replica no longer knows the request (restart without a
        journal, eviction): resubmit under the same idempotency key —
        if it actually settled, the shared map answers."""
        with self._lock:
            if entry.suspended or entry.replica != name \
                    or entry.future.done():
                return
        self._resubmit(entry)

    # -- failure containment -------------------------------------------

    def fence(self, name: str, *, resubmit: bool = True,
              reason: str = "") -> list:
        """Stop routing to ``name`` and (by default) resubmit its
        in-flight requests through the router under their original
        idempotency keys — the exactly-once handoff."""
        with self._lock:
            if name in self._fenced:
                return []
            self._fenced.add(name)
            self._unready.discard(name)
            self._totals["fenced"] += 1
            victims = [e for e in self._entries.values()
                       if e.replica == name and not e.future.done()]
            for e in victims:
                e.suspended = True
            rep = self._replicas.get(name)
        logger.warning("fencing replica %r%s (%d in-flight)", name,
                       f": {reason}" if reason else "", len(victims))
        obs.counter("fleet.fenced", replica=name)
        # the fence span rides the router lane (it is fleet-scoped, not
        # one request's); the victims' trace ids travel in attrs so the
        # timeline can jump from the fence to each re-routed request
        with obs.span("fleet.fence", replica=name, reason=reason,
                      victims=len(victims),
                      trace_ids=[e.trace_id for e in victims[:32]
                                 if e.trace_id]):
            if rep is not None and hasattr(rep, "drop_pending"):
                rep.drop_pending()
            self._gauge_health()
            if resubmit:
                for e in victims:
                    self._resubmit(e)
        return victims

    def unfence(self, name: str) -> None:
        with self._lock:
            self._fenced.discard(name)
            ps = self._probe_state.get(name)
            if ps is not None:
                ps["fails"] = 0
        self._gauge_health()
        self._drain_parked()

    def _resubmit(self, entry: _Entry) -> None:
        if entry.future.done():
            return
        entry.resubmits += 1
        with self._lock:
            self._totals["resubmitted"] += 1
        obs.counter("fleet.resubmitted")
        entry.suspended = False
        with obs.attach(trace=entry.trace_id), \
                obs.span("fleet.resubmit", attempt=entry.resubmits,
                         from_replica=entry.replica):
            self._route(entry, raise_on_reject=False)

    def probe(self) -> dict:
        """One health pass over every replica: readiness plus forward-
        progress staleness.  ``fence_after`` consecutive FATAL failures
        fence a replica (and resubmit its work); non-fatal unreadiness
        (breaker open) only demotes it in routing order."""
        now = time.monotonic()
        out: dict[str, dict] = {}
        for name, rep in self.replicas().items():
            with self._lock:
                if name in self._fenced:
                    out[name] = {"state": "fenced"}
                    continue
                ps = self._probe_state.setdefault(
                    name, {"fails": 0, "prog": None, "t_prog": now}
                )
            ok, info, fatal = rep.ready()
            if ok:
                try:
                    st = rep.stats()
                except ReplicaDown as e:
                    ok, info, fatal = False, {"reason": str(e)}, True
                except Exception:  # noqa: BLE001 — stats is advisory
                    st = None
                else:
                    # service totals are spread at the stats top level
                    pending = (int(st.get("queue_depth") or 0)
                               + int(st.get("running") or 0))
                    prog = (st.get("completed"), st.get("batches"),
                            st.get("graph_batches"))
                    if prog != ps["prog"]:
                        ps["prog"], ps["t_prog"] = prog, now
                    elif pending and now - ps["t_prog"] > self.stale_after_s:
                        ok, fatal = False, True
                        info = {"reason": "stale: pending work, no "
                                          "progress for "
                                          f"{now - ps['t_prog']:.0f}s"}
            with self._lock:
                if ok:
                    ps["fails"] = 0
                    self._unready.discard(name)
                else:
                    ps["fails"] += 1
                    self._unready.add(name)
            if not ok and fatal and ps["fails"] >= self.fence_after:
                self.fence(name, reason=str(info.get("reason") or "probe"))
                out[name] = {"state": "fenced", "info": info}
                continue
            out[name] = {"state": "up" if ok else "unready", "info": info}
        self._gauge_health()
        self._drain_parked()
        return out

    def _gauge_health(self) -> None:
        with self._lock:
            total = len(self._replicas)
            healthy = len([n for n in self._replicas
                           if n not in self._fenced
                           and n not in self._unready])
        obs.gauge("fleet.replicas", total)
        obs.gauge("fleet.replicas_healthy", healthy)

    # -- zero-downtime rollout -----------------------------------------

    def rollout(self, factory=None, names: Sequence[str] | None = None
                ) -> dict:
        """Cycle replicas one at a time with no 5xx and no verdict
        loss: fence-for-rollout (new work routes elsewhere or parks),
        drain the old service to checkpoint, build the successor
        (``factory(name, old_svc) -> CheckService``; its ``recover()``
        replays the shared journal dir), finish the drained work with
        ``resume_drained`` and deliver those verdicts to the ORIGINAL
        futures, then swap the successor in.  Only local replicas roll
        (an HTTP worker's lifecycle belongs to its supervisor)."""
        factory = factory or self.successor_factory
        if factory is None:
            raise ValueError("rollout requires a successor factory")
        with self._lock:
            targets = [n for n in (names or list(self._replicas))
                       if getattr(self._replicas.get(n), "kind", "")
                       == "local" and n not in self._fenced]
        rolled, skipped = [], []
        with obs.span("fleet.rollout", replicas=len(targets)):
            for name in targets:
                with self._lock:
                    rep = self._replicas.get(name)
                    if rep is None or name in self._fenced:
                        skipped.append(name)
                        continue
                    self._fenced.add(name)
                    self._rolling.add(name)
                    victims = [e for e in self._entries.values()
                               if e.replica == name and not e.future.done()]
                    for e in victims:
                        e.suspended = True
                try:
                    old_svc = rep.svc
                    old_svc.shutdown(drain=True)
                    succ = factory(name, old_svc)
                    # journal replay: idempotent if the factory already
                    # start()ed the successor
                    succ.recover()
                    results_by_id: dict[str, Mapping] = {}
                    if old_svc.drain_dir is not None \
                            and old_svc.drain_dir.is_dir():
                        for g in resume_drained(
                                old_svc.drain_dir,
                                capacity=old_svc.capacity,
                                **old_svc._check_opts):
                            if "error" in g:
                                logger.warning("rollout resume failed for "
                                               "%s: %s", g.get("dir"),
                                               g["error"])
                                continue
                            for rid, res in zip(g["ids"], g["results"]):
                                results_by_id[str(rid)] = res
                            # consumed: a later drain into the same dir
                            # must not re-run this group's work
                            shutil.rmtree(g["dir"], ignore_errors=True)
                    with self._lock:
                        self._replicas[name] = LocalReplica(name, succ)
                        self._replicas[name].router = self
                        self._fenced.discard(name)
                        self._probe_state.pop(name, None)
                finally:
                    with self._lock:
                        self._rolling.discard(name)
                        self._fenced.discard(name)
                # deliver: checkpointed verdicts to their original
                # futures; anything else (journal-replayed or finished
                # mid-drain) re-attaches through its idempotency key —
                # affinity routes it back to the successor, where the
                # replayed request or the settled map entry answers
                for e in victims:
                    if e.future.done():
                        continue
                    res = results_by_id.get(str(e.rep_id))
                    if res is not None:
                        with self._lock:
                            self._entries.pop(e.eid, None)
                            self._totals["completed"] += 1
                        e.future.set_result(res)
                    else:
                        self._resubmit(e)
                rolled.append(name)
                with self._lock:
                    self._totals["rollouts"] += 1
                obs.counter("fleet.rollouts", replica=name)
                self._gauge_health()
                self._drain_parked()
        return {"rolled": rolled, "skipped": skipped}

    # -- observation ----------------------------------------------------

    def get(self, request_id: str) -> dict | None:
        """Router-wide request lookup: the entry table first (covers
        every id a resubmitted request ever held), then each live
        replica."""
        rid = str(request_id)
        with self._lock:
            entry = next((e for e in self._entries.values()
                          if rid in e.rep_ids), None)
        if entry is not None and entry.replica is not None:
            with self._lock:
                rep = self._replicas.get(entry.replica)
            if rep is not None:
                with contextlib.suppress(Exception):
                    got = rep.get(entry.rep_id)
                    if got is not None:
                        return got
        for rep in self.replicas().values():
            with contextlib.suppress(Exception):
                got = rep.get(rid)
                if got is not None:
                    return got
        return None

    def get_evidence(self, request_id: str) -> dict | None:
        rid = str(request_id)
        for rep in self.replicas().values():
            with contextlib.suppress(Exception):
                got = rep.get_evidence(rid)
                if got is not None:
                    return got
        return None

    def ready(self) -> tuple[bool, dict]:
        """Fleet readiness: ready while ANY replica can take work."""
        with self._lock:
            states = {
                n: ("fenced" if n in self._fenced
                    else "unready" if n in self._unready else "up")
                for n in self._replicas
            }
        ok = any(s == "up" for s in states.values()) and not self._closed
        return ok, {"replicas": states}

    def alerts(self) -> dict:
        per = {}
        firing: list = []
        for name, rep in self.replicas().items():
            try:
                a = rep.alerts()
            except Exception as e:  # noqa: BLE001 — one replica's
                # alert surface failing must not hide the others'
                a = {"error": str(e)}
            per[name] = a
            for al in a.get("alerts") or []:
                firing.append(dict(al, replica=name))
        doc = {"alerts": firing, "replicas": per, "fleet": True}
        fleet_rows = self._evaluate_fleet_slo()
        if fleet_rows is not None:
            doc["fleet_slos"] = fleet_rows
            for r in fleet_rows:
                if r.get("state") == "firing":
                    firing.append(dict(r, replica="fleet"))
        return doc

    def _fleet_scrapes(self) -> dict[str, str]:
        """Raw expositions from every live HTTP replica (local replicas
        ride in through the shared base registry instead — scraping
        them too would double-count)."""
        out: dict[str, str] = {}
        with self._lock:
            reps = [(n, r) for n, r in self._replicas.items()
                    if n not in self._fenced]
        for name, rep in reps:
            if getattr(rep, "kind", "") != "http":
                continue
            try:
                out[name] = rep.scrape_metrics()
            except Exception:  # noqa: BLE001 — a dying replica's scrape
                # failing must not take fleet burn evaluation down
                continue
        return out

    def _evaluate_fleet_slo(self) -> list | None:
        """One fleet-level burn pass: aggregate bad/total counts across
        replicas (obs.fleetview.FleetSlo), so a one-replica brownout
        burns the fleet budget proportionally to its traffic share
        instead of only tripping that replica's local alert."""
        try:
            with self._fleet_slo_lock:
                return self._fleet_slo.evaluate(self._fleet_scrapes())
        except Exception:  # noqa: BLE001 — burn evaluation is advisory;
            # the per-replica alert merge above must still answer
            logger.exception("fleet SLO evaluation failed")
            return None

    def stats(self) -> dict:
        per = {}
        for name, rep in self.replicas().items():
            row: dict = {"kind": rep.kind}
            with self._lock:
                row["state"] = ("fenced" if name in self._fenced
                                else "unready" if name in self._unready
                                else "up")
            try:
                row["stats"] = rep.stats()
            except Exception as e:  # noqa: BLE001 — a dead replica
                # still gets a stats row, with the error in it
                row["error"] = str(e)
            # stream discovery: where this replica's metrics and
            # recorder live, so the timeline merger and operators find
            # the N streams without guessing paths
            if row["state"] != "fenced":
                with contextlib.suppress(Exception):
                    row["metrics_url"] = rep.metrics_url()
                with contextlib.suppress(Exception):
                    row["telemetry"] = rep.telemetry_info()
            per[name] = row
        with self._lock:
            totals = dict(self._totals)
            inflight = len(self._entries)
            parked = len(self._parked)
        rec = obs._RECORDER
        router_tele = None
        if rec is not None:
            router_tele = {
                "dir": str(rec.dir), "jsonl": str(rec.path),
                "t0": next((e.get("t0") for e in rec.events[:1]), None),
            }
        return {
            "fleet": True,
            "replicas": per,
            "totals": totals,
            "inflight": inflight,
            "parked": parked,
            "router_telemetry": router_tele,
            "uptime_s": round(time.monotonic() - self._t_start, 3),
        }


# ---------------------------------------------------------------------------
# Subprocess workers
# ---------------------------------------------------------------------------

#: the subprocess replica program: one CheckService behind the real
#: HTTP surface, options as a JSON literal.  READY line carries the
#: bound port (callers pass 0 to let the OS pick).
_WORKER_SRC = """\
import json, os, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
opts = json.loads({opts!r})
opts["capacity"] = tuple(opts.get("capacity") or (64, 256))
telemetry_dir = opts.pop("telemetry_dir", None)
inject_latency_s = float(opts.pop("inject_latency_s", 0) or 0)
from jepsen_tpu import web
from jepsen_tpu.serve.service import CheckService
if telemetry_dir:
    # per-replica recorder stream: entered for the process lifetime;
    # the meta header's t0/host/pid is what the fleet timeline merger
    # aligns on, and GET /telemetry announces the path
    from jepsen_tpu import obs as _obs
    from jepsen_tpu.obs import metrics as _metrics
    # keep a reference: these are generator-based context managers, and
    # an unreferenced suspended generator gets GC-finalised — which runs
    # its cleanup and silently tears the recorder back down
    _rec_cm = _obs.recording(telemetry_dir)
    _rec_cm.__enter__()
    _metrics.enable_mirror()
if inject_latency_s:
    # fault hook for fleet-burn drills: every launch in THIS replica
    # dawdles, so exactly one replica's latency histogram goes bad
    from jepsen_tpu import faults as _faults
    import time as _time
    _inj_cm = _faults.inject_scope(
        lambda *a, **k: _time.sleep(inject_latency_s))
    _inj_cm.__enter__()
svc = CheckService(**opts).start()
srv = web.make_server("127.0.0.1", {port}, check_service=svc)
print("FLEET-REPLICA-READY", srv.server_address[1], flush=True)
srv.serve_forever()
"""


def spawn_replica(name: str, *, port: int = 0, opts: Mapping | None = None,
                  ready_timeout_s: float = 180.0,
                  env: Mapping | None = None) -> tuple:
    """Start one subprocess worker replica (its own process, its own
    jax runtime) and wait for its HTTP surface.  ``opts`` are
    CheckService kwargs (JSON-encodable: capacity as a list, dirs as
    strings — point ``idempotency_dir``/``quarantine_dir`` at the
    fleet-shared stores with ``idempotency_shared=True``), plus two
    worker-level extras the service never sees: ``telemetry_dir``
    (open a per-replica obs recording there and enable the metrics
    mirror — the recorder stream the fleet timeline merger consumes)
    and ``inject_latency_s`` (a per-launch sleep fault for fleet-burn
    drills).  Returns ``(Popen, base_url)``; kill the Popen to kill
    the replica."""
    import os

    src = _WORKER_SRC.format(opts=json.dumps(dict(opts or {})),
                             port=int(port))
    child_env = dict(os.environ)
    child_env.setdefault("JAX_PLATFORMS", "cpu")
    if env:
        child_env.update({str(k): str(v) for k, v in env.items()})
    proc = subprocess.Popen(
        [sys.executable, "-c", src],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, env=child_env,
        cwd=str(Path(__file__).resolve().parents[2]),
    )
    deadline = time.monotonic() + float(ready_timeout_s)
    bound = None
    for line in proc.stdout:  # type: ignore[union-attr]
        if line.startswith("FLEET-REPLICA-READY"):
            bound = int(line.split()[1])
            break
        if time.monotonic() > deadline or proc.poll() is not None:
            break
    if bound is None:
        with contextlib.suppress(Exception):
            proc.kill()
        raise ReplicaDown(name, "worker never became ready")

    # keep draining the child's stdout (request logs) so its pipe
    # buffer never fills and wedges it
    def _drain(p=proc):
        with contextlib.suppress(Exception):
            for _ in p.stdout:  # type: ignore[union-attr]
                pass

    threading.Thread(target=_drain, name=f"fleet-worker-log-{name}",
                     daemon=True).start()
    return proc, f"http://127.0.0.1:{bound}"
