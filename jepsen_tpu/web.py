"""Web UI: browse stored test runs.

Mirrors ``jepsen.web`` (reference: jepsen/src/jepsen/web.clj): a tiny HTTP
app over the store directory — a home table of runs colored by validity
(web.clj:25-41,128-158), directory listings and file serving with a
path-traversal guard (web.clj:235-284, 328-333), and zip download of a
whole test directory (web.clj:286-327).  stdlib http.server; no deps.
"""

from __future__ import annotations

import html
import io
import json
import logging
import mimetypes
import zipfile
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path
from urllib.parse import unquote

from jepsen_tpu import store

logger = logging.getLogger(__name__)

VALID_COLORS = {True: "#6DB6FE", False: "#FFAA26", "unknown": "#FEB5DA"}


def _valid_of(run_dir: Path):
    """Cheap validity peek: the run.jepsen footer index when present
    (store/format.py — nothing but the footer block is read), else
    results.json's valid? key — the role
    of the reference's PartialMap lazy reads (web.clj:61-94,
    store/format.clj:113-129)."""
    run = run_dir / "run.jepsen"
    if run.exists():
        from jepsen_tpu.store import format as fmt

        try:
            return fmt.read_index(run).get("valid?")
        except (fmt.CorruptFile, OSError):
            pass
    p = run_dir / "results.json"
    if not p.exists():
        return None
    try:
        return json.loads(p.read_text()).get("valid?")
    except Exception:  # noqa: BLE001
        return "unknown"


def home_html(store_dir=None) -> str:
    rows = []
    for name, runs in sorted(store.tests(store_dir=store_dir).items()):
        for ts, d in sorted(runs.items(), reverse=True):
            v = _valid_of(d)
            color = VALID_COLORS.get(v, "#eee")
            rows.append(
                f"<tr style='background:{color}'>"
                f"<td>{html.escape(name)}</td>"
                f"<td><a href='/files/{html.escape(name)}/{html.escape(ts)}/'>"
                f"{html.escape(ts)}</a></td>"
                f"<td>{html.escape(str(v))}</td>"
                f"<td><a href='/zip/{html.escape(name)}/{html.escape(ts)}'>zip</a></td>"
                f"</tr>"
            )
    return (
        "<html><head><title>jepsen-tpu</title>"
        "<style>body{font-family:sans-serif}table{border-collapse:collapse}"
        "td,th{padding:4px 12px;text-align:left}</style></head><body>"
        "<h1>jepsen-tpu results</h1>"
        "<p><a href='/suite'>suite overview</a></p>"
        "<table><tr><th>test</th><th>time</th><th>valid?</th><th></th></tr>"
        + "".join(rows)
        + "</table></body></html>"
    )


def suite_html(store_dir=None) -> str:
    """The test-all comparison view: one row per test NAME, its runs as
    a compact validity strip (latest first) — scanning a suite's health
    at a glance, the role of the reference's test-all summary over the
    home table's run-by-run listing."""
    rows = []
    for name, runs in sorted(store.tests(store_dir=store_dir).items()):
        cells = []
        ordered = sorted(runs.items(), reverse=True)
        n_valid = 0
        for ts, d in ordered:
            v = _valid_of(d)
            n_valid += v is True
            color = VALID_COLORS.get(v, "#eee")
            cells.append(
                f"<a href='/files/{html.escape(name)}/{html.escape(ts)}/' "
                f"title='{html.escape(ts)}: {html.escape(str(v))}' "
                f"style='display:inline-block;width:14px;height:22px;"
                f"background:{color};margin-right:2px'></a>"
            )
        rows.append(
            f"<tr><td><a href='/files/{html.escape(name)}/'>{html.escape(name)}</a></td>"
            f"<td>{n_valid}/{len(ordered)} valid</td>"
            f"<td>{''.join(cells)}</td></tr>"
        )
    return (
        "<html><head><title>jepsen-tpu suite</title>"
        "<style>body{font-family:sans-serif}table{border-collapse:collapse}"
        "td,th{padding:4px 12px;text-align:left;vertical-align:middle}</style>"
        "</head><body><h1>suite overview</h1>"
        "<p><a href='/'>all runs</a></p>"
        "<table><tr><th>test</th><th>record</th><th>runs (newest first)</th></tr>"
        + "".join(rows)
        + "</table></body></html>"
    )


def _safe_resolve(base: Path, rel: str) -> Path | None:
    """Path-traversal guard (web.clj:328-333)."""
    target = (base / rel).resolve()
    base = base.resolve()
    if base == target or base in target.parents:
        return target
    return None


def _telemetry_table(headers: list, rows: list[list]) -> str:
    head = "".join(f"<th>{html.escape(str(c))}</th>" for c in headers)
    body = "".join(
        "<tr>" + "".join(f"<td>{html.escape(str(c))}</td>" for c in r) + "</tr>"
        for r in rows
    )
    return (
        "<table style='border-collapse:collapse;margin-bottom:12px'>"
        f"<tr>{head}</tr>{body}</table>"
    )


def telemetry_html(run_dir: Path) -> str:
    """The run page's phase / checker / ladder-stage timing tables,
    rendered from the run's ``telemetry.json`` (the obs.summary rollup).
    Empty string when the run carries no telemetry."""
    p = Path(run_dir) / "telemetry.json"
    if not p.exists():
        return ""
    try:
        s = json.loads(p.read_text())
    except (OSError, ValueError):
        return ""
    parts = [f"<h2>telemetry</h2><p>total wall: {s.get('wall_s', 0)} s</p>"]
    if s.get("phases"):
        parts.append("<h3>phases</h3>")
        parts.append(_telemetry_table(
            ["phase", "wall (s)", "count"],
            [[p_["phase"], p_["wall_s"], p_["count"]] for p_ in s["phases"]],
        ))
    if s.get("checkers"):
        parts.append("<h3>checkers</h3>")
        parts.append(_telemetry_table(
            ["checker", "seconds", "count", "valid?"],
            [[c["checker"], c["seconds"], c["count"], c.get("valid")]
             for c in s["checkers"]],
        ))
    if s.get("ladder"):
        parts.append("<h3>ladder stages</h3>")
        parts.append(_telemetry_table(
            ["stage", "engine", "capacity", "lanes", "seconds", "resolved",
             "refuted", "unknowns left", "launches", "compile (s)",
             "execute (s)", "peak frontier", "lossy", "dedup"],
            [[r.get("stage"), r.get("engine"), r.get("capacity"),
              r.get("lanes"), r.get("seconds"), r.get("resolved", ""),
              r.get("refuted", ""), r.get("unknowns_remaining", ""),
              r.get("launches", ""), r.get("compile_s", ""),
              r.get("execute_s", ""), r.get("peak_frontier", ""),
              r.get("lossy", ""), r.get("dedup", "")] for r in s["ladder"]],
        ))
    if s.get("dedup"):
        parts.append("<h3>dedup rounds (sort vs bucket probe)</h3>")
        parts.append(_telemetry_table(
            ["backend", "candidates", "capacity", "probes", "per round (µs)"],
            [[d.get("backend"), d.get("candidates"), d.get("capacity"),
              d.get("probes"), d.get("per_round_us")] for d in s["dedup"]],
        ))
    if s.get("faults"):
        parts.append("<h3>faults (retries / degradations / checkpoints / deadline)</h3>")
        parts.append(_telemetry_table(
            ["fault", "count", "seconds", "detail"],
            [[f.get("fault"), f.get("count"), f.get("seconds", ""),
              f.get("detail", "")] for f in s["faults"]],
        ))
    if s.get("counters"):
        parts.append("<h3>counters</h3>")
        parts.append(_telemetry_table(
            ["counter", "total"], sorted(s["counters"].items())
        ))
    return "".join(parts)


class Handler(BaseHTTPRequestHandler):
    store_dir = None

    def log_message(self, fmt, *args):  # quiet
        logger.debug("web: " + fmt, *args)

    def _send(self, code: int, body: bytes, ctype="text/html; charset=utf-8"):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 - stdlib API
        try:
            path = unquote(self.path.split("?")[0])
            base = store.base_dir({"store-dir": self.store_dir} if self.store_dir else None)
            if path in ("/", "/index.html"):
                self._send(200, home_html(self.store_dir).encode())
            elif path == "/suite":
                self._send(200, suite_html(self.store_dir).encode())
            elif path.startswith("/files/"):
                target = _safe_resolve(base, path[len("/files/"):])
                if target is None or not target.exists():
                    self._send(404, b"not found")
                elif target.is_dir():
                    entries = sorted(target.iterdir())
                    items = "".join(
                        f"<li><a href='{html.escape(e.name)}{'/' if e.is_dir() else ''}'>"
                        f"{html.escape(e.name)}</a></li>"
                        for e in entries
                    )
                    # The run page: a run dir with telemetry renders its
                    # phase/stage timing tables above the file listing.
                    tele = telemetry_html(target)
                    self._send(
                        200,
                        (
                            "<html><head><style>body{font-family:sans-serif}"
                            "td,th{padding:2px 10px;text-align:left;"
                            "border-bottom:1px solid #ddd}</style></head>"
                            f"<body>{tele}<ul>{items}</ul></body></html>"
                        ).encode(),
                    )
                else:
                    guessed, _ = mimetypes.guess_type(str(target))
                    if guessed is None or guessed.startswith("text/"):
                        # Serve unknown/plain files readably in-browser,
                        # but html (timeline.html!) as real html.
                        guessed = guessed or "text/plain"
                        ctype = f"{guessed}; charset=utf-8"
                    else:
                        ctype = guessed
                    self._send(200, target.read_bytes(), ctype)
            elif path.startswith("/zip/"):
                target = _safe_resolve(base, path[len("/zip/"):])
                if target is None or not target.is_dir():
                    self._send(404, b"not found")
                else:
                    buf = io.BytesIO()
                    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
                        for f in sorted(target.rglob("*")):
                            if f.is_file():
                                z.write(f, f.relative_to(target.parent))
                    self._send(200, buf.getvalue(), "application/zip")
            else:
                self._send(404, b"not found")
        except BrokenPipeError:  # pragma: no cover
            pass
        except Exception:  # noqa: BLE001 - pragma: no cover
            logger.exception("web handler error")
            self._send(500, b"internal error")


def make_server(host="0.0.0.0", port=8080, store_dir=None) -> ThreadingHTTPServer:
    handler = type("BoundHandler", (Handler,), {"store_dir": store_dir})
    return ThreadingHTTPServer((host, port), handler)


def serve(host="0.0.0.0", port=8080, store_dir=None):
    """Blocking server (web.clj:385-390)."""
    srv = make_server(host, port, store_dir)
    logger.info("serving store on http://%s:%d", host, port)
    try:
        srv.serve_forever()
    finally:
        srv.server_close()


if __name__ == "__main__":  # pragma: no cover — exercised via subprocess
    import argparse

    ap = argparse.ArgumentParser(description="Serve the store web UI.")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=8080)
    ap.add_argument("--store-dir", default=None)
    a = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    serve(a.host, a.port, a.store_dir)
