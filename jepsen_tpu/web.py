"""Web UI: browse stored test runs + the check-serving HTTP API.

Mirrors ``jepsen.web`` (reference: jepsen/src/jepsen/web.clj): a tiny HTTP
app over the store directory — a home table of runs colored by validity
(web.clj:25-41,128-158), directory listings and file serving with a
path-traversal guard (web.clj:235-284, 328-333), and zip download of a
whole test directory (web.clj:286-327).  stdlib http.server; no deps.

When a ``jepsen_tpu.serve.CheckService`` is mounted (``make_server(...,
check_service=svc)`` / ``jepsen-tpu serve --check``) the app also serves
the check API:

  POST /check        submit a history ({"history": [...], "model": ...,
                     "priority", "deadline", "client", "trace_id",
                     "class", "wait", "idempotency_key"}); "class"
                     picks the latency tier ("interactive": the
                     speculative greedy fast path; "batch": the
                     continuous ladder — the default);
                     "idempotency_key" makes resubmission safe: a
                     duplicate submit (retry after a timeout / 429 /
                     503, even across a service restart) attaches to
                     the original request — same id — or returns its
                     settled result instead of re-running the check;
                     202 + request id + trace id, 200 + result with
                     "wait": true, 429 + Retry-After on backpressure
                     (the estimate is computed per latency class)
  GET  /check/<id>   request status / result (includes the trace_id and
                     the per-request "latency" decomposition block)
  GET  /evidence/<id>  the request's verdict-provenance evidence bundle
                     (obs.provenance): decision path, engine resolution,
                     witness, config + machine fingerprint — same id as
                     /check/<id>; audit with tools/evidence.py
  GET  /queue        queue-status JSON incl. per-class queue depths and
                     retry-after EWMAs (the home page shows a panel)
  POST /stream       open an incremental checking stream
                     (checker.streaming).  Body is NDJSON: a header
                     line ({"model": ..., "stream_id": ..., "resume":
                     bool, "client", "trace_id"}), then zero or more op
                     lines, then an optional {"end": true} trailer —
                     one POST can open, feed, and close a whole
                     replayed history.  A single JSON object with an
                     inline "ops" list works too.  Returns the stream
                     status doc: "valid?" goes False/True the MOMENT a
                     verdict exists (verdict-on-violation), honest
                     "unknown" before that.  429 + Retry-After when
                     the stream lane is full — quoted from the stream
                     lane's own session-duration EWMA, never the batch
                     ladder's
  POST /stream/<id>  feed one epoch of ops (NDJSON op lines with an
                     optional leading {"seq": N} offset line, or JSON
                     {"ops": [...], "seq": N}).  "seq" = ops the client
                     already delivered: overlap is dropped (idempotent
                     re-feed after kill/resume), a gap is refused 409
  POST /stream/<id>/close   end of stream: finalize (pending invokes
                     classify as crashed, exactly post-hoc), emit the
                     evidence bundle, return the final result
  GET  /stream/<id>  stream status (ops consumed, settled barriers,
                     verdict + detection metadata once terminal).
                     Streams are replica-sticky (carried device state):
                     the fleet router does NOT front this surface
  GET  /alerts       the live SLO burn-rate engine's alert document
                     (jepsen_tpu.serve.slo): firing alerts + the
                     per-objective fast/slow-window burn table (the
                     home page shows a panel)

When a ``jepsen_tpu.serve.fleet.FleetRouter`` is mounted instead
(``make_server(..., fleet=router)`` / ``jepsen-tpu serve --check
--replicas N``) the SAME check API fronts the whole replica fleet:
submissions route by geometry affinity, 429 re-quotes Retry-After as
the MIN across live replicas, 503 means every replica's breaker is
open, /readyz is 200 while ANY replica can take work, and two admin
endpoints appear:

  GET  /fleet          fleet status: per-replica state/stats + router
                       totals (routed/spilled/fenced/resubmitted/
                       rollouts/parked)
  POST /fleet/rollout  zero-downtime rollout: cycle local replicas
                       through drain → successor (journal replay +
                       resume_drained) → swap; body may name specific
                       replicas ({"names": [...]})

Oversized ``POST /check`` bodies are rejected 413 BEFORE the JSON parse
(``make_server(..., max_request_mb=)`` / ``serve --max-request-mb``) so
one hostile payload can't balloon the process ahead of admission
validation; an open circuit breaker (``serve.health``) rejects 503 with
a Retry-After distinct from the backpressure 429.

Operational endpoints (always mounted):

  GET  /healthz          liveness: 200 while the process serves HTTP
  GET  /readyz           readiness: 200 when a check service is
                         mounted, admitting, and its circuit breaker
                         is not open; 503 (with the reason) otherwise
                         — the probe pair an orchestrator points at a
                         serving pod

Observability endpoints (always mounted):

  GET  /metrics          live Prometheus text (jepsen_tpu.obs.metrics):
                         queue depth, batch occupancy/padding waste,
                         admission + end-to-end latency histograms,
                         fault/retry counters, verdicts by outcome,
                         device-buffer bytes — the home page shows a
                         self-refreshing panel
  GET  /trace/<t>/<ts>   a run's telemetry.jsonl as Chrome/Perfetto
                         trace-event JSON (one lane per request trace
                         id; linked from the run page)
  GET  /perf             the perf trajectory: per-metric history over
                         the perf-regression ledger (obs.regress —
                         bench / loadgen / tier-1-budget records,
                         sparkline + recent values per metric, plus
                         perfwatch compete verdicts); the newest
                         record per kind also rides /metrics as
                         jepsen_tpu_perf_headline{kind,metric} gauges
  GET  /profile          jax.profiler capture-hook status; POST
  POST /profile/start    /profile/start {"seconds": n} and POST
  POST /profile/stop     /profile/stop drive a bounded device-profile
                         capture (serve --profile-dir)

The home/suite run index is cached keyed on store-directory mtimes so
the dashboard stays cheap while the service is under load: validity is
re-read for a run only when its directory's mtime changes (results.json
and run.jepsen land via rename, which bumps it).
"""

from __future__ import annotations

import html
import io
import json
import logging
import math
import mimetypes
import os
import threading
import time
import zipfile
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path
from urllib.parse import unquote

from jepsen_tpu import faults, obs, store
from jepsen_tpu.obs import fleetview as obs_fleetview
from jepsen_tpu.obs import metrics as obs_metrics
from jepsen_tpu.obs import regress as obs_regress
from jepsen_tpu.obs import trace as obs_trace
from jepsen_tpu.obs.summary import _mb

logger = logging.getLogger(__name__)

VALID_COLORS = {True: "#6DB6FE", False: "#FFAA26", "unknown": "#FEB5DA"}

#: run-index caches: full index keyed on the store dir's mtime signature,
#: per-run validity keyed on that run dir's mtime (see run_index).  The
#: lock serializes rebuilds — dashboard requests run on
#: ThreadingHTTPServer threads.
_INDEX_CACHE: dict[str, tuple[tuple, list]] = {}
_VALID_CACHE: dict[str, tuple[int, object]] = {}
_INDEX_LOCK = threading.Lock()


def _valid_of(run_dir: Path):
    """Cheap validity peek: the run.jepsen footer index when present
    (store/format.py — nothing but the footer block is read), else
    results.json's valid? key — the role
    of the reference's PartialMap lazy reads (web.clj:61-94,
    store/format.clj:113-129)."""
    run = run_dir / "run.jepsen"
    if run.exists():
        from jepsen_tpu.store import format as fmt

        try:
            return fmt.read_index(run).get("valid?")
        except (fmt.CorruptFile, OSError):
            pass
    p = run_dir / "results.json"
    if not p.exists():
        return None
    try:
        return json.loads(p.read_text()).get("valid?")
    except Exception:  # noqa: BLE001
        return "unknown"


def run_index(store_dir=None) -> list[tuple[str, str, Path, object]]:
    """(name, timestamp, run_dir, valid?) rows for every stored run,
    cached on mtimes: the home/suite pages used to rescan the store dir
    AND re-open every run's footer/results.json per request — under
    serving load that made the dashboard the most expensive endpoint.
    The full index is reused while the directory tree's mtime signature
    is unchanged; a run's validity is re-read only when its own dir
    mtime moves (artifacts land via rename, which bumps it)."""
    base = store.base_dir({"store-dir": store_dir} if store_dir else None)
    sig: list = []
    entries: list[tuple[str, str, Path, int]] = []
    if base.exists():
        try:
            sig.append(base.stat().st_mtime_ns)
        except OSError:
            pass
        for name, ts, run, mt in store.iter_runs(store_dir=store_dir):
            sig.append((name, ts, mt))
            entries.append((name, ts, run, mt))
    key = str(base)
    with _INDEX_LOCK:
        cached = _INDEX_CACHE.get(key)
        if cached is not None and cached[0] == tuple(sig):
            return cached[1]
        rows = []
        live = set()
        now_ns = time.time_ns()
        for name, ts, run, mt in entries:
            ck = str(run)
            live.add(ck)
            vc = _VALID_CACHE.get(ck)
            if vc is not None and vc[0] == mt:
                v = vc[1]
            else:
                v = _valid_of(run)
                # Don't cache a validity read off a just-modified run
                # dir: a second artifact landing within the same mtime
                # tick would be indistinguishable, baking a stale
                # verdict in forever.  Quiet-for-2s runs cache normally.
                if now_ns - mt > 2_000_000_000:
                    _VALID_CACHE[ck] = (mt, v)
            rows.append((name, ts, run, v))
        # Evict deleted runs on each rebuild so a long-lived server
        # watching a churning store doesn't leak cache entries.  The
        # separator-suffixed prefix keeps a sibling store ("store2")
        # from being evicted by "store"'s rebuilds.
        prefix = key.rstrip(os.sep) + os.sep
        for ck in [k for k in _VALID_CACHE
                   if k.startswith(prefix) and k not in live]:
            del _VALID_CACHE[ck]
        if not entries or now_ns - max(mt for *_e, mt in entries) > 2_000_000_000:
            _INDEX_CACHE[key] = (tuple(sig), rows)
        else:
            # An actively-written run shares the stale-tick hazard at
            # the index level too: keep rebuilding (cheap — quiet runs'
            # validity stays cached) until the store is 2s quiet, and
            # drop any older cached index so its stale sig can't serve.
            _INDEX_CACHE.pop(key, None)
        return rows


def queue_panel_html(service) -> str:
    """The home page's check-service queue-status panel: the process
    totals plus one row per latency class (queue depth and retry-after
    EWMA are PER CLASS — an interactive rejection is quoted in
    fast-path waves, a batch one in ladder batches)."""
    if service is None:
        return ""
    s = service.stats()
    cells = "".join(
        f"<td><b>{html.escape(str(s.get(k)))}</b><br>"
        f"<small>{html.escape(label)}</small></td>"
        for k, label in (
            ("queue_depth", "queued"), ("running", "running"),
            ("submitted", "submitted"), ("completed", "completed"),
            ("rejected", "rejected"), ("expired", "expired"),
            ("batches", "batches"), ("batch_ewma_s", "batch ewma (s)"),
            ("continuous_occupancy", "rung occupancy"),
            ("fastpath_resolved", "fastpath"),
            ("graph_queue_depth", "graphs queued"),
            ("graph_batches", "graph batches"),
        )
    )
    class_rows = ""
    for tier, c in sorted((s.get("classes") or {}).items()):
        class_rows += (
            f"<tr><td>{html.escape(tier)}</td>"
            f"<td>{html.escape(str(c.get('queued')))}</td>"
            f"<td>{html.escape(str(c.get('ewma_s')))}</td>"
            f"<td>{html.escape(str(c.get('retry_after_hint_s')))}</td></tr>"
        )
    placement = s.get("placement") or {}
    return (
        "<h2>check service</h2>"
        "<table style='border:1px solid #ddd'><tr>"
        + cells
        + "</tr></table>"
        "<table style='border:1px solid #ddd;margin-top:6px'>"
        "<tr><th>class</th><th>queued</th><th>cycle ewma (s)</th>"
        "<th>retry-after (s)</th></tr>"
        + class_rows
        + "</table>"
        f"<p>placement: {html.escape(str(placement.get('devices', 1)))} "
        f"device(s){' (lane-sharded)' if placement.get('sharded') else ''}"
        " — <a href='/queue'>queue JSON</a></p>"
    )


def slo_panel_html(service) -> str:
    """The home page's SLO burn-rate panel: one row per objective with
    its fast/slow-window burn and alert state (firing rows red)."""
    if service is None or getattr(service, "slo", None) is None:
        return ""
    doc = service.slo.alerts()
    if not doc["slos"]:
        return ""
    rows = ""
    for r in doc["slos"]:
        color = {"firing": "#FFAA26", "no-data": "#eee"}.get(r["state"], "")
        style = f" style='background:{color}'" if color else ""
        rows += (
            f"<tr{style}><td>{html.escape(r['slo'])}</td>"
            f"<td>{html.escape(r['kind'])}</td>"
            f"<td>{r['target']}</td>"
            f"<td>{r['burn_fast']}</td><td>{r['burn_slow']}</td>"
            f"<td>{html.escape(r['state'])}</td></tr>"
        )
    firing = len(doc["alerts"])
    head = (f"{firing} alert(s) FIRING" if firing else "all objectives ok")
    return (
        "<h2>SLO burn rates</h2>"
        f"<p>{head} — <a href='/alerts'>alerts JSON</a> "
        f"(fast window {doc['fast_window_s']:.0f}s, slow "
        f"{doc['slow_window_s']:.0f}s; burn 1.0 = eating budget exactly "
        "as fast as allowed)</p>"
        "<table style='border:1px solid #ddd'>"
        "<tr><th>slo</th><th>kind</th><th>target</th>"
        "<th>burn (fast)</th><th>burn (slow)</th><th>state</th></tr>"
        + rows + "</table>"
    )


def metrics_panel_html() -> str:
    """The home page's live-metrics panel: the current Prometheus text,
    self-refreshing via a tiny fetch loop (the server-rendered snapshot
    stands in when JS is off).  Rendered only when the live registry is
    enabled (a serving process)."""
    if not obs_metrics.MIRROR:
        return ""
    snap = html.escape(obs_metrics.render() or "(no samples yet)")
    return (
        "<h2>live metrics</h2>"
        "<details open><summary><a href='/metrics'>/metrics</a> "
        "(refreshes every 2s)</summary>"
        "<pre id='live-metrics' style='background:#f6f6f6;padding:8px;"
        f"max-height:340px;overflow:auto'>{snap}</pre></details>"
        "<script>async function _lm(){try{const r=await fetch('/metrics');"
        "document.getElementById('live-metrics').textContent="
        "await r.text();}catch(e){}}setInterval(_lm,2000);</script>"
    )


def home_html(store_dir=None, check_service=None) -> str:
    rows = []
    by_name: dict[str, list] = {}
    for name, ts, d, v in run_index(store_dir):
        by_name.setdefault(name, []).append((ts, d, v))
    for name in sorted(by_name):
        for ts, d, v in sorted(by_name[name], reverse=True):
            color = VALID_COLORS.get(v, "#eee")
            rows.append(
                f"<tr style='background:{color}'>"
                f"<td>{html.escape(name)}</td>"
                f"<td><a href='/files/{html.escape(name)}/{html.escape(ts)}/'>"
                f"{html.escape(ts)}</a></td>"
                f"<td>{html.escape(str(v))}</td>"
                f"<td><a href='/zip/{html.escape(name)}/{html.escape(ts)}'>zip</a></td>"
                f"</tr>"
            )
    return (
        "<html><head><title>jepsen-tpu</title>"
        "<style>body{font-family:sans-serif}table{border-collapse:collapse}"
        "td,th{padding:4px 12px;text-align:left}</style></head><body>"
        "<h1>jepsen-tpu results</h1>"
        + queue_panel_html(check_service)
        + slo_panel_html(check_service)
        + metrics_panel_html()
        + "<p><a href='/suite'>suite overview</a> — "
        "<a href='/perf'>perf trajectory</a></p>"
        "<table><tr><th>test</th><th>time</th><th>valid?</th><th></th></tr>"
        + "".join(rows)
        + "</table></body></html>"
    )


def suite_html(store_dir=None) -> str:
    """The test-all comparison view: one row per test NAME, its runs as
    a compact validity strip (latest first) — scanning a suite's health
    at a glance, the role of the reference's test-all summary over the
    home table's run-by-run listing."""
    rows = []
    by_name: dict[str, dict] = {}
    for name, ts, d, v in run_index(store_dir):
        by_name.setdefault(name, {})[ts] = (d, v)
    for name in sorted(by_name):
        runs = by_name[name]
        cells = []
        ordered = sorted(runs.items(), reverse=True)
        n_valid = 0
        for ts, (d, v) in ordered:
            n_valid += v is True
            color = VALID_COLORS.get(v, "#eee")
            cells.append(
                f"<a href='/files/{html.escape(name)}/{html.escape(ts)}/' "
                f"title='{html.escape(ts)}: {html.escape(str(v))}' "
                f"style='display:inline-block;width:14px;height:22px;"
                f"background:{color};margin-right:2px'></a>"
            )
        rows.append(
            f"<tr><td><a href='/files/{html.escape(name)}/'>{html.escape(name)}</a></td>"
            f"<td>{n_valid}/{len(ordered)} valid</td>"
            f"<td>{''.join(cells)}</td></tr>"
        )
    return (
        "<html><head><title>jepsen-tpu suite</title>"
        "<style>body{font-family:sans-serif}table{border-collapse:collapse}"
        "td,th{padding:4px 12px;text-align:left;vertical-align:middle}</style>"
        "</head><body><h1>suite overview</h1>"
        "<p><a href='/'>all runs</a></p>"
        "<table><tr><th>test</th><th>record</th><th>runs (newest first)</th></tr>"
        + "".join(rows)
        + "</table></body></html>"
    )


def _sparkline(values: list[float], width: int = 260, height: int = 36) -> str:
    """An inline-SVG trend line for a metric's ledger history (oldest to
    newest, left to right).  Flat series render as a midline."""
    if not values:
        return ""
    lo, hi = min(values), max(values)
    span = (hi - lo) or 1.0
    n = len(values)
    pts = " ".join(
        f"{(i * (width - 4) / max(1, n - 1) + 2):.1f},"
        f"{(height - 4 - (v - lo) / span * (height - 8) + 2):.1f}"
        for i, v in enumerate(values)
    )
    return (
        f"<svg width='{width}' height='{height}' "
        "style='background:#f6f6f6;vertical-align:middle'>"
        f"<polyline points='{pts}' fill='none' stroke='#4477aa' "
        "stroke-width='1.5'/>"
        f"<circle cx='{(width - 2):.1f}' "
        f"cy='{(height - 4 - (values[-1] - lo) / span * (height - 8) + 2):.1f}'"
        " r='2.5' fill='#cc3311'/></svg>"
    )


def perf_html(store_dir=None) -> str:
    """The perf-trajectory page: per-metric history over the perf ledger
    (obs.regress), one sparkline + recent-values table per (kind,
    metric), grouped by machine fingerprint — the BENCH_r0*.json
    trajectory, readable instead of write-only.  Competition verdicts
    (``perfwatch compete``) list below the trends."""
    base = store.base_dir({"store-dir": store_dir} if store_dir else None)
    path = obs_regress.ledger_path(store_dir=base)
    records = obs_regress.read_records(path)
    parts = ["<html><head><title>jepsen-tpu perf trajectory</title>"
             "<style>body{font-family:sans-serif}table{border-collapse:"
             "collapse}td,th{padding:2px 10px;text-align:left;"
             "border-bottom:1px solid #ddd}</style></head><body>"
             "<h1>perf trajectory</h1>"
             f"<p><a href='/'>all runs</a> — ledger: "
             f"<code>{html.escape(str(path))}</code> "
             f"({len(records)} records)</p>"]
    if not records:
        parts.append("<p>(empty ledger — run bench.py, tools/loadgen.py or "
                     "the tier-1 budget gate to populate it)</p>")
        return "".join(parts) + "</body></html>"
    # (kind, fingerprint_key, metric) -> [(ts, value, sha)] oldest-first
    series: dict[tuple, list] = {}
    competes = []
    for r in records:
        if r.get("kind") == "compete":
            competes.append(r)
            continue
        if r.get("outage"):
            continue
        sha = (r.get("git") or {}).get("sha", "?")[:10]
        axes = r.get("axes") or {}
        ax = ",".join(f"{k}={v}" for k, v in sorted(axes.items()))
        for name, v in (r.get("metrics") or {}).items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                # str() everything: a hand-written/foreign record missing
                # fingerprint_key must not make sorted() compare None
                # against str and 500 the whole page
                key = (str(r.get("kind")), str(r.get("fingerprint_key")),
                       ax, str(name))
                series.setdefault(key, []).append((r.get("ts"), float(v), sha))
    last_kind = None
    for (kind, fkey, ax, name) in sorted(series):
        pts = series[(kind, fkey, ax, name)]
        if kind != last_kind:
            parts.append(f"<h2>{html.escape(str(kind))}</h2>")
            last_kind = kind
        vals = [v for _, v, _ in pts]
        label = html.escape(name) + (f" <small>[{html.escape(ax)}]</small>"
                                     if ax else "")
        newest = pts[-1]
        parts.append(
            f"<p><b>{label}</b> <small>on {html.escape(str(fkey))}</small>"
            f"<br>{_sparkline(vals)} latest <b>{newest[1]:.6g}</b> "
            f"@ {html.escape(newest[2])} ({len(pts)} points, "
            f"min {min(vals):.6g}, max {max(vals):.6g})</p>"
        )
        rows = "".join(
            f"<tr><td>{time.strftime('%Y-%m-%d %H:%M', time.localtime(ts or 0))}"
            f"</td><td>{html.escape(sha)}</td><td>{v:.6g}</td></tr>"
            for ts, v, sha in reversed(pts[-10:])
        )
        parts.append(
            "<details><summary>recent values</summary>"
            "<table><tr><th>time</th><th>git</th><th>value</th></tr>"
            + rows + "</table></details>"
        )
    if competes:
        parts.append("<h2>competition verdicts</h2>"
                     "<table><tr><th>time</th><th>axis</th><th>winner</th>"
                     "<th>margin</th><th>decisive?</th><th>git</th></tr>")
        for r in reversed(competes):
            v = r.get("extra") or {}
            parts.append(
                "<tr><td>"
                + time.strftime("%Y-%m-%d %H:%M",
                                time.localtime(float(r.get("ts") or 0)))
                + f"</td><td>{html.escape(str(v.get('axis')))}</td>"
                f"<td>{html.escape(str(v.get('winner')))}</td>"
                f"<td>{html.escape(str(v.get('margin_pct')))}%</td>"
                f"<td>{'yes' if v.get('decisive') else 'no (within noise)'}"
                "</td>"
                f"<td>{html.escape((r.get('git') or {}).get('sha', '?')[:10])}"
                "</td></tr>"
            )
        parts.append("</table>")
    return "".join(parts) + "</body></html>"


def _serve_mod():
    """Lazy jepsen_tpu.serve import: plain store browsing must not drag
    in the checker stack (serve pulls parallel.batch pulls jax)."""
    from jepsen_tpu import serve

    return serve


def _parse_stream_body(raw: bytes) -> tuple[dict, list, bool, int | None]:
    """Parse a ``POST /stream`` body into ``(header, ops, end, seq)``.

    The body is NDJSON — one JSON object per line.  Lines carrying a
    ``type``/``process`` key are history ops; ``{"end": true}`` marks
    end-of-stream; anything else is a header/control line whose keys
    merge into the header (``ops`` may inline an op list, ``seq`` sets
    the idempotent feed offset).  A single JSON document like
    ``{"model": ..., "ops": [...], "end": true}`` is therefore parsed
    by the same rules.  Raises ``ValueError`` on malformed input."""
    header: dict = {}
    ops: list = []
    end = False
    seq: int | None = None
    for ln in raw.decode("utf-8", "replace").splitlines():
        ln = ln.strip()
        if not ln:
            continue
        try:
            obj = json.loads(ln)
        except json.JSONDecodeError as e:
            raise ValueError(f"bad NDJSON line: {e}") from None
        if not isinstance(obj, dict):
            raise ValueError("each NDJSON line must be a JSON object")
        if "type" in obj or "process" in obj:
            ops.append(obj)
            continue
        obj = dict(obj)
        inline = obj.pop("ops", None)
        if inline is not None:
            if not isinstance(inline, list):
                raise ValueError("ops must be a list of op maps")
            ops.extend(dict(o) for o in inline)
        if obj.pop("end", False):
            end = True
        s = obj.pop("seq", None)
        if s is not None:
            seq = int(s)
        header.update(obj)
    return header, ops, end, seq


def _safe_resolve(base: Path, rel: str) -> Path | None:
    """Path-traversal guard (web.clj:328-333)."""
    target = (base / rel).resolve()
    base = base.resolve()
    if base == target or base in target.parents:
        return target
    return None


def _telemetry_table(headers: list, rows: list[list]) -> str:
    head = "".join(f"<th>{html.escape(str(c))}</th>" for c in headers)
    body = "".join(
        "<tr>" + "".join(f"<td>{html.escape(str(c))}</td>" for c in r) + "</tr>"
        for r in rows
    )
    return (
        "<table style='border-collapse:collapse;margin-bottom:12px'>"
        f"<tr>{head}</tr>{body}</table>"
    )


def telemetry_html(run_dir: Path, rel: str | None = None) -> str:
    """The run page's phase / checker / ladder-stage timing tables,
    rendered from the run's ``telemetry.json`` (the obs.summary rollup).
    ``rel`` (the run's path under the store root) adds the Perfetto
    trace-export download link.  Empty string when the run carries no
    telemetry."""
    p = Path(run_dir) / "telemetry.json"
    if not p.exists():
        return ""
    try:
        s = json.loads(p.read_text())
    except (OSError, ValueError):
        return ""
    parts = [f"<h2>telemetry</h2><p>total wall: {s.get('wall_s', 0)} s</p>"]
    if rel and (Path(run_dir) / "telemetry.jsonl").exists():
        href = "/trace/" + html.escape(rel.strip("/"))
        parts.append(
            f"<p><a href='{href}'>trace.json</a> — Perfetto/Chrome "
            "trace-event export (one lane per request; load at "
            "ui.perfetto.dev)</p>"
        )
    ev_dir = Path(run_dir) / "evidence"
    if rel and ev_dir.is_dir():
        n_ev = sum(1 for _ in ev_dir.glob("*.json"))
        if n_ev:
            href = "/files/" + html.escape(rel.strip("/")) + "/evidence/"
            parts.append(
                f"<p><a href='{href}'>evidence bundles</a> — {n_ev} "
                "verdict provenance bundle(s): decision path, engine "
                "resolution, and witness per verdict (audit with "
                "<code>tools/evidence.py verify|replay</code>)</p>"
            )
    if s.get("phases"):
        parts.append("<h3>phases</h3>")
        parts.append(_telemetry_table(
            ["phase", "wall (s)", "count"],
            [[p_["phase"], p_["wall_s"], p_["count"]] for p_ in s["phases"]],
        ))
    if s.get("checkers"):
        parts.append("<h3>checkers</h3>")
        parts.append(_telemetry_table(
            ["checker", "seconds", "count", "valid?"],
            [[c["checker"], c["seconds"], c["count"], c.get("valid")]
             for c in s["checkers"]],
        ))
    if s.get("serve"):
        sv = s["serve"]
        parts.append("<h3>check service</h3>")
        rows = [[k, sv[k]] for k in (
            "batches", "requests", "batch_wall_s", "avg_batch_requests",
            "avg_occupancy", "avg_padding_waste", "submitted", "completed",
            "rejected", "expired", "drained") if k in sv]
        for key, label in (("admission", "admission wait"),
                           ("request", "request latency")):
            if key in sv:
                rows.append([f"{label} mean (s)", sv[key]["mean_s"]])
                rows.append([f"{label} max (s)", sv[key]["max_s"]])
        parts.append(_telemetry_table(["serve", "value"], rows))
    if s.get("ladder"):
        parts.append("<h3>ladder stages</h3>")
        parts.append(_telemetry_table(
            ["stage", "engine", "capacity", "lanes", "seconds", "resolved",
             "refuted", "unknowns left", "launches", "compile (s)",
             "execute (s)", "peak frontier", "lossy", "dedup",
             "device MB (peak)"],
            [[r.get("stage"), r.get("engine"), r.get("capacity"),
              r.get("lanes"), r.get("seconds"), r.get("resolved", ""),
              r.get("refuted", ""), r.get("unknowns_remaining", ""),
              r.get("launches", ""), r.get("compile_s", ""),
              r.get("execute_s", ""), r.get("peak_frontier", ""),
              r.get("lossy", ""), r.get("dedup", ""),
              _mb(r.get("device_bytes_peak"))] for r in s["ladder"]],
        ))
    if s.get("critpath", {}).get("spans"):
        cp = s["critpath"]
        parts.append(
            f"<h3>critical path ({cp.get('total_s', 0)} s on-path of "
            f"{cp.get('wall_s', 0)} s wall)</h3>")
        parts.append(_telemetry_table(
            ["span", "critpath (s)", "inclusive (s)", "count", "slack (s)"],
            [[r.get("span"), r.get("cp_s"), r.get("total_s"),
              r.get("count"), r.get("slack_s")]
             for r in cp["spans"]],
        ))
    if s.get("dedup"):
        parts.append("<h3>dedup rounds (sort vs bucket probe)</h3>")
        parts.append(_telemetry_table(
            ["backend", "candidates", "capacity", "probes", "per round (µs)"],
            [[d.get("backend"), d.get("candidates"), d.get("capacity"),
              d.get("probes"), d.get("per_round_us")] for d in s["dedup"]],
        ))
    if s.get("elle"):
        parts.append("<h3>elle inference (column-native substages)</h3>")
        parts.append(_telemetry_table(
            ["stage", "seconds", "count", "max (s)"],
            [[e.get("stage"), e.get("seconds"), e.get("count"),
              e.get("max_s")] for e in s["elle"]],
        ))
    if s.get("faults"):
        parts.append("<h3>faults (retries / degradations / checkpoints / deadline)</h3>")
        parts.append(_telemetry_table(
            ["fault", "count", "seconds", "detail"],
            [[f.get("fault"), f.get("count"), f.get("seconds", ""),
              f.get("detail", "")] for f in s["faults"]],
        ))
    if s.get("counters"):
        parts.append("<h3>counters</h3>")
        parts.append(_telemetry_table(
            ["counter", "total"], sorted(s["counters"].items())
        ))
    return "".join(parts)


class Handler(BaseHTTPRequestHandler):
    store_dir = None
    check_service = None  # a jepsen_tpu.serve.CheckService, or None
    #: a jepsen_tpu.serve.fleet.FleetRouter, or None.  When mounted it
    #: fronts /check, /queue, /alerts, /readyz and the /fleet admin
    #: surface; 429s re-quote Retry-After as the MIN across live
    #: replicas and 503 means EVERY replica's breaker is open.
    fleet = None
    profiler = None  # a jepsen_tpu.obs.profiler.ProfilerHook, or None
    #: request-body bound for POST /check, enforced on Content-Length
    #: BEFORE the body is read or parsed (413 beyond it).
    max_request_bytes = 32 * 1024 * 1024
    t_start = time.monotonic()

    def log_message(self, fmt, *args):  # quiet
        logger.debug("web: " + fmt, *args)

    def _send(self, code: int, body: bytes, ctype="text/html; charset=utf-8",
              headers=None):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, str(v))
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, code: int, obj, headers=None):
        self._send(
            code, json.dumps(obj, default=str).encode(),
            "application/json; charset=utf-8", headers,
        )

    # ------------------------------------------------------------------
    # Check-serving API (jepsen_tpu.serve)
    # ------------------------------------------------------------------

    def do_POST(self):  # noqa: N802 - stdlib API
        try:
            path = unquote(self.path.split("?")[0])
            if path in ("/profile/start", "/profile/stop"):
                self._handle_profile(path)
                return
            if path == "/fleet/rollout":
                self._handle_rollout()
                return
            if path == "/stream" or path.startswith("/stream/"):
                self._handle_stream(path)
                return
            if path != "/check":
                self._send(404, b"not found")
                return
            svc = self.fleet or self.check_service
            if svc is None:
                self._send_json(
                    503, {"error": "no check service mounted "
                                   "(start with serve --check)"})
                return
            try:
                length = int(self.headers.get("Content-Length") or 0)
            except ValueError:
                self._send_json(400, {"error": "bad Content-Length"})
                return
            if length < 0:
                # rfile.read(-1) would read until EOF — a hostile
                # keep-alive client could wedge this handler thread
                # with no size bound at all
                self._send_json(400, {"error": "bad Content-Length"})
                return
            if length > self.max_request_bytes:
                # Reject BEFORE reading/parsing: one hostile payload
                # must not balloon the process ahead of admission
                # validation.  The connection is closed (the unread
                # body would otherwise wedge keep-alive).
                obs_metrics.inc("serve.oversized_rejected")
                self._send_json(
                    413,
                    {"error": "request body too large",
                     "bytes": length, "limit": self.max_request_bytes},
                    headers={"Connection": "close"},
                )
                self.close_connection = True
                return
            try:
                body = json.loads(self.rfile.read(length) or b"{}")
                history = body["history"]
                if not isinstance(history, list):
                    raise TypeError("history must be a list of op maps")
                model = _serve_mod().model_by_name(
                    body.get("model", "cas-register"))
                priority = int(body.get("priority") or 0)
                client = str(body.get("client") or "http")
                latency_class = body.get("class")
                if latency_class is not None:
                    latency_class = str(latency_class)
                trace_id = body.get("trace_id")
                if trace_id is not None:
                    trace_id = str(trace_id)
                idem_key = body.get("idempotency_key")
                if idem_key is not None:
                    idem_key = str(idem_key)
                deadline = body.get("deadline")
                if deadline is not None:
                    deadline = faults.Deadline.coerce(float(deadline))
                wait_timeout = body.get("wait_timeout")
                wait_timeout = (
                    300.0 if wait_timeout is None
                    else min(float(wait_timeout), 3600.0)
                )
            except (KeyError, TypeError, ValueError) as e:
                self._send_json(400, {"error": f"bad request: {e}"})
                return
            try:
                # idempotency_key makes the retry behavior this API
                # actively instructs (429/503 Retry-After, 202-then-poll
                # timeouts) safe: a duplicate submit attaches to the
                # original request — same id — instead of re-running it.
                fut = svc.submit(
                    history, model=model, priority=priority,
                    deadline=deadline, client=client, trace_id=trace_id,
                    class_=latency_class, idempotency_key=idem_key,
                )
            except (KeyError, TypeError, ValueError, IndexError) as e:
                # malformed op dicts surface from pack() at admission —
                # client input, not an internal error
                self._send_json(400, {"error": f"bad history: {e!r}"})
                return
            except _serve_mod().QueueFull as e:
                # The 429-style contract: bounded queue, explicit
                # rejection with a retry hint — never unbounded buffering.
                self._send_json(
                    429,
                    {"error": "queue full", "depth": e.depth,
                     "limit": e.limit, "retry_after_s": e.retry_after},
                    headers={"Retry-After": max(1, math.ceil(e.retry_after))},
                )
                return
            except _serve_mod().ServiceUnavailable as e:
                # Circuit breaker open: the DEVICE isn't serving (K
                # consecutive batch failures) — distinct from the 429
                # backpressure case where the queue is merely full.
                self._send_json(
                    503,
                    {"error": "circuit breaker open",
                     "retry_after_s": e.retry_after},
                    headers={"Retry-After": max(1, math.ceil(e.retry_after))},
                )
                return
            except _serve_mod().ServiceClosed:
                self._send_json(503, {"error": "service shutting down"})
                return
            req = svc.get(fut.id)
            # the fleet router's get() returns the describe() document
            # directly; the single service returns the request object
            tid = (req.get("trace_id") if isinstance(req, dict)
                   else req.trace_id if req is not None else None)
            if body.get("wait"):
                import concurrent.futures

                # A request deadline bounds the HTTP wait too (plus a
                # short grace so the queue-expiry unknown lands).
                timeout = wait_timeout
                if deadline is not None:
                    timeout = deadline.clamp(wait_timeout) + 1.0
                try:
                    result = fut.result(timeout=timeout)
                except concurrent.futures.TimeoutError:
                    self._send_json(
                        202, {"id": fut.id, "status": "pending",
                              "trace_id": tid, "href": f"/check/{fut.id}"})
                    return
                self._send_json(
                    200, {"id": fut.id, "trace_id": tid, "result": result})
            elif fut.done():
                # Already settled at submit time: an idempotent
                # duplicate of a finished request (whose original may
                # have been evicted — the 202 href would 404 forever),
                # or a trivially-valid history.  Hand the result over.
                self._send_json(
                    200, {"id": fut.id, "trace_id": tid,
                          "result": fut.result()})
            else:
                self._send_json(
                    202, {"id": fut.id, "status": "queued",
                          "trace_id": tid, "href": f"/check/{fut.id}"})
        except BrokenPipeError:  # pragma: no cover
            pass
        except Exception:  # noqa: BLE001 - pragma: no cover
            logger.exception("web POST handler error")
            self._send_json(500, {"error": "internal error"})

    def _read_body(self) -> bytes | None:
        """Bounded request-body read (the POST /check Content-Length
        rules: 400 on a bad length, 413 + connection close beyond
        ``max_request_bytes`` BEFORE any parse).  Replies itself and
        returns None when the body was refused."""
        try:
            length = int(self.headers.get("Content-Length") or 0)
        except ValueError:
            self._send_json(400, {"error": "bad Content-Length"})
            return None
        if length < 0:
            self._send_json(400, {"error": "bad Content-Length"})
            return None
        if length > self.max_request_bytes:
            obs_metrics.inc("serve.oversized_rejected")
            self._send_json(
                413,
                {"error": "request body too large",
                 "bytes": length, "limit": self.max_request_bytes},
                headers={"Connection": "close"},
            )
            self.close_connection = True
            return None
        return self.rfile.read(length)

    def _handle_stream(self, path: str) -> None:
        """POST /stream[/<id>[/close]] — the streaming lane (NDJSON op
        ingestion into ``CheckService.stream_*``; protocol in the
        module docstring).  Streams are replica-sticky (each holds a
        carried frontier), so this surface always talks to the LOCAL
        check service, never the fleet router."""
        svc = self.check_service
        if svc is None:
            self._send_json(
                503, {"error": "no check service mounted (start with "
                               "serve --check; streams are replica-"
                               "sticky and never fleet-routed)"})
            return
        raw = self._read_body()
        if raw is None:
            return
        try:
            header, ops, end, seq = _parse_stream_body(raw)
        except ValueError as e:
            self._send_json(400, {"error": f"bad stream body: {e}"})
            return
        serve = _serve_mod()
        try:
            if path == "/stream":
                try:
                    status = svc.stream_open(
                        model=header.get("model"),
                        stream_id=header.get("stream_id"),
                        resume=bool(header.get("resume")),
                        client=str(header.get("client") or "http"),
                        trace_id=header.get("trace_id"),
                    )
                except (KeyError, ValueError) as e:
                    # unknown model / malformed header — client input
                    self._send_json(400, {"error": f"bad stream: {e}"})
                    return
                sid = status["stream-id"]
                if ops:
                    status = svc.stream_feed(sid, ops, seq=seq)
                if end:
                    status = svc.stream_close(sid)
                status.setdefault("href", f"/stream/{sid}")
                self._send_json(200, status)
                return
            parts = [p for p in path.split("/") if p]
            if len(parts) == 3 and parts[2] == "close":
                self._send_json(200, svc.stream_close(parts[1]))
                return
            if len(parts) != 2:
                self._send(404, b"not found")
                return
            status = svc.stream_feed(parts[1], ops, seq=seq)
            if end:
                status = svc.stream_close(parts[1])
            self._send_json(200, status)
        except KeyError as e:
            self._send_json(404, {"error": str(e)})
        except ValueError as e:
            # closed stream / sequence gap: the stream exists but the
            # feed conflicts with its state
            self._send_json(409, {"error": str(e)})
        except serve.QueueFull as e:
            # Stream-lane backpressure: same 429 contract as /check,
            # but the quote comes from the STREAM lane's session EWMA.
            self._send_json(
                429,
                {"error": "stream lane full", "depth": e.depth,
                 "limit": e.limit, "retry_after_s": e.retry_after,
                 "tier": e.tier},
                headers={"Retry-After": max(1, math.ceil(e.retry_after))},
            )
        except serve.ServiceClosed:
            self._send_json(503, {"error": "service shutting down"})

    def _handle_profile(self, path: str) -> None:
        """POST /profile/start|stop — the bounded jax.profiler capture
        hook (obs.profiler, mounted via serve --profile-dir)."""
        if self.profiler is None:
            self._send_json(
                503, {"error": "no profiler mounted "
                               "(start with serve --profile-dir)"})
            return
        if path.endswith("/start"):
            try:
                length = int(self.headers.get("Content-Length") or 0)
                body = json.loads(self.rfile.read(length) or b"{}")
            except ValueError:
                self._send_json(400, {"error": "bad JSON body"})
                return
            doc = self.profiler.start(body.get("seconds"))
        else:
            doc = self.profiler.stop()
        self._send_json(409 if doc.get("error") else 200, doc)

    def _handle_rollout(self) -> None:
        """POST /fleet/rollout — cycle the fleet's local replicas with
        zero downtime (serve.fleet.FleetRouter.rollout): drain each to
        checkpoint, start its successor (journal replay +
        resume_drained), swap, no 5xx, no verdict loss.  Body may name
        specific replicas: {"names": ["r0"]}."""
        if self.fleet is None:
            self._send_json(503, {"error": "no fleet mounted "
                                           "(start with serve --replicas N)"})
            return
        try:
            length = int(self.headers.get("Content-Length") or 0)
            body = json.loads(self.rfile.read(length) or b"{}")
        except ValueError:
            self._send_json(400, {"error": "bad JSON body"})
            return
        names = body.get("names")
        try:
            doc = self.fleet.rollout(names=names)
        except ValueError as e:
            self._send_json(409, {"error": str(e)})
            return
        self._send_json(200, doc)

    def _federated_metrics(self, base_text: str) -> str:
        """The fleet-wide exposition: this process's registry (router
        counters + the in-process replicas' shared series) plus one
        scrape per live replica, re-labeled and rolled up by
        obs.fleetview.federate.  A replica whose scrape fails is marked
        down (jepsen_tpu_fleet_scrape_up 0), never a 500 — the scrape
        endpoint must outlive any single replica."""
        scrapes: dict[str, str] = {}
        errors: dict[str, str] = {}
        try:
            replicas = self.fleet.replicas()
        except Exception:  # noqa: BLE001 — federation is additive only
            return base_text
        for name, rep in replicas.items():
            try:
                scrapes[name] = rep.scrape_metrics()
            except Exception as e:  # noqa: BLE001 — mark it down
                errors[name] = str(e)
        try:
            return obs_fleetview.federate(base_text, scrapes,
                                          errors=errors)
        except Exception:  # noqa: BLE001 — a malformed scrape must not
            logger.exception("metrics federation failed")
            return base_text

    def do_GET(self):  # noqa: N802 - stdlib API
        try:
            path = unquote(self.path.split("?")[0])
            base = store.base_dir({"store-dir": self.store_dir} if self.store_dir else None)
            if path == "/metrics":
                # Prometheus text exposition: the live registry, fed by
                # the obs mirror + the serving layer's explicit series.
                # The perf ledger's newest record per kind rides along as
                # jepsen_tpu_perf_headline{kind,metric} gauges (refreshed
                # only when the ledger file changed).  With a fleet
                # mounted the page FEDERATES: live replica scrapes are
                # re-exported with replica= labels plus
                # jepsen_tpu_fleet_* rollups (obs.fleetview), so one
                # scrape covers the whole fleet.
                try:
                    obs_regress.publish_gauges(store_dir=base)
                except Exception:  # noqa: BLE001 — a corrupt ledger must
                    pass  # not take down the scrape endpoint
                text = obs_metrics.render()
                if self.fleet is not None:
                    text = self._federated_metrics(text)
                self._send(
                    200, text.encode(),
                    "text/plain; version=0.0.4; charset=utf-8",
                )
            elif path == "/telemetry":
                # Recorder-stream discovery: where THIS process's
                # telemetry.jsonl lives + the t0 epoch the merger
                # clock-aligns on.  Subprocess replicas answer this so
                # the router's GET /fleet can announce every stream.
                rec = obs._RECORDER
                if rec is None:
                    self._send_json(200, {"recording": False})
                else:
                    meta = rec.events[0] if rec.events else {}
                    self._send_json(200, {
                        "recording": True, "dir": str(rec.dir),
                        "jsonl": str(rec.path), "t0": meta.get("t0"),
                        "pid": meta.get("pid"), "host": meta.get("host"),
                    })
            elif path == "/healthz":
                # Liveness: this handler running IS the signal.
                self._send_json(
                    200,
                    {"ok": True,
                     "uptime_s": round(time.monotonic() - self.t_start, 3)},
                )
            elif path == "/readyz":
                # Readiness: mounted + admitting + breaker not open.
                # With a fleet mounted, ready while ANY replica can
                # take work — one replica's breaker is not an outage.
                if self.fleet is not None:
                    ok, info = self.fleet.ready()
                    self._send_json(
                        200 if ok else 503, {"ready": ok, **info})
                    return
                svc = self.check_service
                if svc is None:
                    self._send_json(
                        503, {"ready": False, "reason": "no check service"})
                elif getattr(svc, "_closed", False):
                    self._send_json(
                        503, {"ready": False, "reason": "shutting down"})
                else:
                    br = svc.breaker.describe()
                    if br["state"] == "open":
                        self._send_json(
                            503,
                            {"ready": False, "reason": "circuit breaker open",
                             "breaker": br},
                            headers={"Retry-After":
                                     max(1, math.ceil(br["retry_after_s"]))},
                        )
                    else:
                        self._send_json(
                            200, {"ready": True, "breaker": br})
            elif path == "/profile":
                if self.profiler is None:
                    self._send_json(503, {"error": "no profiler mounted"})
                else:
                    self._send_json(200, self.profiler.status())
            elif path.startswith("/trace/"):
                target = _safe_resolve(base, path[len("/trace/"):])
                jsonl = target / "telemetry.jsonl" if target else None
                if jsonl is None or not jsonl.is_file():
                    self._send(404, b"not found")
                else:
                    try:
                        events, skipped = obs_trace.read_jsonl_events(jsonl)
                    except (OSError, ValueError) as e:
                        self._send_json(500, {"error": f"unreadable "
                                                       f"telemetry: {e}"})
                        return
                    body = json.dumps(
                        obs_trace.to_trace_events(
                            events, skipped_lines=skipped),
                        separators=(",", ":"), default=str,
                    ).encode()
                    self._send(
                        200, body, "application/json; charset=utf-8",
                        headers={"Content-Disposition":
                                 'attachment; filename="trace.json"'},
                    )
            elif path in ("/", "/index.html"):
                self._send(
                    200, home_html(self.store_dir, self.check_service).encode()
                )
            elif path == "/suite":
                self._send(200, suite_html(self.store_dir).encode())
            elif path == "/perf":
                self._send(200, perf_html(self.store_dir).encode())
            elif path == "/queue":
                front = self.fleet or self.check_service
                if front is None:
                    self._send_json(503, {"error": "no check service mounted"})
                else:
                    self._send_json(200, front.stats())
            elif path == "/fleet":
                # Fleet status: per-replica state/stats + router totals
                # (routed/spilled/fenced/resubmitted/rollouts/parked).
                if self.fleet is None:
                    self._send_json(
                        503, {"error": "no fleet mounted "
                                       "(start with serve --replicas N)"})
                else:
                    self._send_json(200, self.fleet.stats())
            elif path == "/alerts":
                # The live SLO burn-rate engine's alert document:
                # currently-firing alerts plus the full per-SLO burn
                # table (fast/slow windows) — loadgen's acceptance
                # gates and operators' pagers both read this.  A fleet
                # answers the merged per-replica document.
                if self.fleet is not None:
                    self._send_json(200, self.fleet.alerts())
                    return
                svc = self.check_service
                if svc is None or getattr(svc, "slo", None) is None:
                    self._send_json(503, {"error": "no check service mounted"})
                else:
                    self._send_json(200, svc.slo.alerts())
            elif path.startswith("/check/"):
                front = self.fleet or self.check_service
                if front is None:
                    self._send_json(503, {"error": "no check service mounted"})
                else:
                    req = front.get(path[len("/check/"):])
                    if req is None:
                        self._send_json(404, {"error": "unknown request id"})
                    else:
                        self._send_json(
                            200,
                            req if isinstance(req, dict) else req.describe())
            elif path.startswith("/stream/"):
                # Replica-sticky: streams hold carried frontier state,
                # so status always reads the LOCAL service (no fleet).
                svc = self.check_service
                if svc is None:
                    self._send_json(503, {"error": "no check service mounted"})
                else:
                    try:
                        self._send_json(
                            200, svc.stream_status(path[len("/stream/"):]))
                    except KeyError:
                        self._send_json(404, {"error": "unknown stream id"})
            elif path.startswith("/evidence/"):
                # The verdict's evidence bundle (obs.provenance): the
                # full decision path + witness for one served request,
                # keyed by the SAME id as GET /check/<id>.  Audit it
                # offline with tools/evidence.py verify / replay.
                front = self.fleet or self.check_service
                if front is None:
                    self._send_json(503, {"error": "no check service mounted"})
                else:
                    bundle = front.get_evidence(
                        path[len("/evidence/"):])
                    if bundle is None:
                        self._send_json(
                            404, {"error": "no evidence bundle for that "
                                           "request id"})
                    else:
                        self._send_json(200, bundle)
            elif path.startswith("/files/"):
                target = _safe_resolve(base, path[len("/files/"):])
                if target is None or not target.exists():
                    self._send(404, b"not found")
                elif target.is_dir():
                    entries = sorted(target.iterdir())
                    items = "".join(
                        f"<li><a href='{html.escape(e.name)}{'/' if e.is_dir() else ''}'>"
                        f"{html.escape(e.name)}</a></li>"
                        for e in entries
                    )
                    # The run page: a run dir with telemetry renders its
                    # phase/stage timing tables above the file listing
                    # (+ the Perfetto trace-export link).
                    tele = telemetry_html(target, rel=path[len("/files/"):])
                    self._send(
                        200,
                        (
                            "<html><head><style>body{font-family:sans-serif}"
                            "td,th{padding:2px 10px;text-align:left;"
                            "border-bottom:1px solid #ddd}</style></head>"
                            f"<body>{tele}<ul>{items}</ul></body></html>"
                        ).encode(),
                    )
                else:
                    guessed, _ = mimetypes.guess_type(str(target))
                    if guessed is None or guessed.startswith("text/"):
                        # Serve unknown/plain files readably in-browser,
                        # but html (timeline.html!) as real html.
                        guessed = guessed or "text/plain"
                        ctype = f"{guessed}; charset=utf-8"
                    else:
                        ctype = guessed
                    self._send(200, target.read_bytes(), ctype)
            elif path.startswith("/zip/"):
                target = _safe_resolve(base, path[len("/zip/"):])
                if target is None or not target.is_dir():
                    self._send(404, b"not found")
                else:
                    buf = io.BytesIO()
                    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
                        for f in sorted(target.rglob("*")):
                            if f.is_file():
                                z.write(f, f.relative_to(target.parent))
                    self._send(200, buf.getvalue(), "application/zip")
            else:
                self._send(404, b"not found")
        except BrokenPipeError:  # pragma: no cover
            pass
        except Exception:  # noqa: BLE001 - pragma: no cover
            logger.exception("web handler error")
            self._send(500, b"internal error")


def make_server(host="0.0.0.0", port=8080, store_dir=None,
                check_service=None, profiler=None,
                max_request_mb: float = 32.0,
                fleet=None) -> ThreadingHTTPServer:
    # A mounted web server IS a serving process: turn the live metrics
    # registry on so /metrics (and the home panel) have data to show.
    obs_metrics.enable_mirror()
    handler = type(
        "BoundHandler", (Handler,),
        {"store_dir": store_dir, "check_service": check_service,
         "fleet": fleet, "profiler": profiler,
         "max_request_bytes": int(max_request_mb * 1024 * 1024),
         "t_start": time.monotonic()},
    )
    return ThreadingHTTPServer((host, port), handler)


def serve(host="0.0.0.0", port=8080, store_dir=None, check_service=None,
          profiler=None, max_request_mb: float = 32.0, fleet=None):
    """Blocking server (web.clj:385-390).  With a ``check_service`` the
    check API mounts and shutdown drains it (checkpointing queued work);
    with a ``fleet`` (serve.fleet.FleetRouter) the check API fronts the
    whole replica fleet instead (+ GET /fleet, POST /fleet/rollout);
    with a ``profiler`` (obs.profiler.ProfilerHook) the /profile
    endpoints drive bounded device captures."""
    srv = make_server(host, port, store_dir, check_service, profiler,
                      max_request_mb=max_request_mb, fleet=fleet)
    logger.info("serving store on http://%s:%d", host, port)
    try:
        srv.serve_forever()
    finally:
        srv.server_close()
        if profiler is not None:
            profiler.stop()
        if fleet is not None:
            fleet.shutdown(drain=True)
        elif check_service is not None:
            check_service.shutdown(drain=True)


if __name__ == "__main__":  # pragma: no cover — exercised via subprocess
    import argparse

    ap = argparse.ArgumentParser(description="Serve the store web UI.")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=8080)
    ap.add_argument("--store-dir", default=None)
    a = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    serve(a.host, a.port, a.store_dir)
