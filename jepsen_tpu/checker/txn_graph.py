"""Transaction dependency-graph inference (Elle-style).

Builds write-write / write-read / read-write (anti-)dependency graphs over
the committed transactions of a history, for the two workload families the
reference checks through Elle (jepsen/src/jepsen/tests/cycle/append.clj,
wr.clj; elle 0.1.3 is an external dep per jepsen/project.clj:13):

* **list-append** — every write is an append to a per-key list; reads observe
  the whole list.  Version orders are directly recoverable from reads
  (the longest observed list), which makes inference exact.
* **rw-register** — writes are unique register values.  Only write-read
  edges are directly observable; version orders (hence ww/rw edges) are
  inferred under optional assumptions (``linearizable_keys``,
  ``sequential_keys``), mirroring elle.rw-register's options surfaced at
  tests/cycle/wr.clj:20-29.

The graphs come out as dense boolean adjacency matrices over transaction
nodes — the TPU-native representation: cycle detection is batched boolean
matrix powering on the MXU (jepsen_tpu.ops.closure), not pointer-chasing
Tarjan.  Non-cycle anomalies (G1a aborted read, G1b intermediate read,
internal, duplicates, incompatible orders) are detected host-side during
inference, since they are single-pass folds.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Mapping, Sequence

import numpy as np

from jepsen_tpu import history as h
from jepsen_tpu import obs
from jepsen_tpu import txn as t

#: engine selection: per-call arg > env > the vectorized default.  The
#: "columns" engine (jepsen_tpu.checker.txn_columns) runs inference as
#: flat int64 column operations and falls back to "loops" (the retained
#: per-op reference below, also the differential oracle) whenever a
#: history's values can't ride int64 columns.
ENGINE_ENV = "JEPSEN_TPU_ELLE_ENGINE"
DEFAULT_ENGINE = "columns"
ENGINES = ("columns", "loops")


def resolve_engine(engine: str | None = None) -> str:
    e = engine or os.environ.get(ENGINE_ENV) or DEFAULT_ENGINE
    if e not in ENGINES:
        raise ValueError(f"unknown elle engine {e!r}; expected one of {ENGINES}")
    return e

# ---------------------------------------------------------------------------
# Transaction nodes
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class TxnNode:
    """One committed (ok) or indeterminate (info) transaction."""

    id: int  # node index in the graph
    op: dict  # the completion op (carries the observed values)
    invoke_index: int
    complete_index: int
    ok: bool  # True for ok, False for info (writes *may* have happened)

    @property
    def value(self) -> Sequence:
        return self.op["value"] or []


@dataclasses.dataclass
class TxnGraph:
    """Dense dependency graph over transaction nodes.

    ``ww``/``wr``/``rw`` are [n, n] bool adjacency matrices; ``extra`` holds
    additional-graph edges (realtime/process — elle's ``additional-graphs``
    option, tests/cycle/wr.clj:18-20) which participate in cycles but are
    dependency-type-neutral.
    """

    nodes: list[TxnNode]
    ww: np.ndarray
    wr: np.ndarray
    rw: np.ndarray
    extra: np.ndarray
    #: (type, i, j) → human-readable explanation of why edge i→j exists —
    #: a string, or a zero-arg callable producing one.  Inference stores
    #: CALLABLES for per-edge prose: a 10k-txn history has ~37k edges
    #: whose eager f-strings (the ww ones repr the key's whole version
    #: order) measured 1.3 s of the 2.7 s inference, while only the
    #: handful of edges on a witness cycle are ever rendered.
    explanations: dict[tuple[str, int, int], Any]
    #: non-cycle anomalies found during inference: name → [explanation dict]
    anomalies: dict[str, list]
    #: optional sparse edge cache: type → (E, 2) int64 (i, j) rows in
    #: ``np.argwhere`` order.  The columns engine fills it at build time
    #: so classification never scans the dense matrices; ``edge_arrays``
    #: computes (and caches) it by argwhere otherwise.
    edges: dict | None = None

    @property
    def n(self) -> int:
        return len(self.nodes)

    def edge_arrays(self) -> dict:
        """Sparse (i, j) edge rows per type ("ww"/"wr"/"rw"/"extra"),
        argwhere-ordered; cached."""
        if self.edges is None:
            self.edges = {
                "ww": np.argwhere(self.ww),
                "wr": np.argwhere(self.wr),
                "rw": np.argwhere(self.rw),
                "extra": np.argwhere(self.extra),
            }
        return self.edges

    def explain(self, et: str, i: int, j: int) -> str:
        """Render the explanation for edge (et, i, j), forcing a lazy
        one; the bare edge type when no explanation was recorded."""
        v = self.explanations.get((et, i, j))
        if v is None:
            return et
        return v() if callable(v) else v


def _t(nd: TxnNode) -> str:
    """Name a transaction in explanation prose by its history index
    (elle names them T1, T2, … — the completion op's :index is our
    stable equivalent)."""
    return f"T{nd.op.get('index', nd.id)}"


def _empty(n: int) -> np.ndarray:
    return np.zeros((n, n), dtype=bool)


def txn_nodes(history: Sequence[dict], pairs=None) -> list[TxnNode]:
    """Extract transaction nodes: ok txns (fully trusted) and info txns
    (indeterminate — their writes may be visible, so they join the graph as
    writers; their reads are not evidence).  Failed txns are excluded — their
    writes must never be visible (observing one is G1a).

    ``pairs`` lets a caller that already holds ``h.pair_index(history)``
    thread it through instead of paying the per-op pairing walk again
    (batched checks used to recompute it per history per call)."""
    if pairs is None:
        pairs = h.pair_index(history)
    nodes: list[TxnNode] = []
    for i, op in enumerate(history):
        if h.is_invoke(op) or not h.is_client_op(op):
            continue
        if h.is_ok(op) or h.is_info(op):
            j = int(pairs[i])
            inv = j if j != -1 else i
            # Info completions may carry no value; fall back to the invocation.
            o = op
            if h.is_info(op) and op.get("value") is None and j != -1:
                o = {**op, "value": history[j].get("value")}
            nodes.append(
                TxnNode(
                    id=len(nodes),
                    op=o,
                    invoke_index=inv,
                    complete_index=i,
                    ok=h.is_ok(op),
                )
            )
    return nodes


def _failed_writes(history: Sequence[dict], append: bool) -> dict:
    """(key, value) → failed op, for G1a detection (elle: aborted reads)."""
    out = {}
    fname = "append" if append else "w"
    for op in history:
        if h.is_fail(op) and h.is_client_op(op):
            for mop in op["value"] or ():
                if mop[0] == fname:
                    out[(mop[1], mop[2])] = op
    return out


def _intermediate_writes(nodes: list[TxnNode]) -> dict:
    """(key, value) → (node, next-value) for every non-final write a txn made
    to a key.  Observing one (without its successor) is G1b."""
    out = {}
    for node in nodes:
        writes: dict = {}
        for mop in node.value:
            if mop[0] != "r":
                writes.setdefault(mop[1], []).append(mop[2])
        for k, vs in writes.items():
            for a, b in zip(vs, vs[1:]):
                out[(k, a)] = (node, b)
    return out


# ---------------------------------------------------------------------------
# Additional graphs: realtime & process (elle's additional-graphs)
# ---------------------------------------------------------------------------


def realtime_edges(nodes: list[TxnNode]) -> np.ndarray:
    """i→j iff txn i completed before txn j was invoked.  Dense O(n²) — the
    TPU closure kernel wants the dense form anyway.  Only ok nodes get
    realtime edges *out* (an info txn has no known completion time)."""
    n = len(nodes)
    comp = np.array(
        [nd.complete_index if nd.ok else np.iinfo(np.int64).max for nd in nodes]
    )
    inv = np.array([nd.invoke_index for nd in nodes])
    return comp[:, None] < inv[None, :]


def process_edges(nodes: list[TxnNode]) -> np.ndarray:
    """i→j iff same process and i immediately precedes j for that process."""
    adj = _empty(len(nodes))
    last: dict[Any, int] = {}
    for nd in sorted(nodes, key=lambda x: x.invoke_index):
        p = nd.op["process"]
        if p in last:
            adj[last[p], nd.id] = True
        last[p] = nd.id
    return adj


def build_extra(nodes: list[TxnNode], additional_graphs: Sequence[str]) -> np.ndarray:
    extra = _empty(len(nodes))
    for g in additional_graphs:
        if g == "realtime":
            extra |= realtime_edges(nodes)
        elif g == "process":
            extra |= process_edges(nodes)
        else:
            raise ValueError(f"unknown additional graph {g!r}")
    return extra


# ---------------------------------------------------------------------------
# Internal consistency (shared by both workloads)
# ---------------------------------------------------------------------------


def _internal_anomalies_append(node: TxnNode) -> list:
    """A txn must observe its own prior reads plus its own appends
    (elle.list-append internal checking)."""
    out = []
    expected: dict = {}  # key -> known list state within the txn
    for mop in node.value:
        f, k, v = mop[0], mop[1], mop[2]
        if f == "r":
            if k in expected and list(v or []) != expected[k]:
                out.append(
                    {
                        "op": node.op,
                        "mop": list(mop),
                        "expected": expected[k],
                    }
                )
            expected[k] = list(v or [])
        else:  # append
            if k in expected:
                expected[k] = expected[k] + [v]
    return out


def _internal_anomalies_wr(node: TxnNode) -> list:
    out = []
    known: dict = {}  # key -> last value this txn wrote or read
    for mop in node.value:
        f, k, v = mop[0], mop[1], mop[2]
        if f == "r":
            if k in known and v != known[k]:
                out.append({"op": node.op, "mop": list(mop), "expected": known[k]})
            known[k] = v
        else:
            known[k] = v
    return out


# ---------------------------------------------------------------------------
# list-append inference (elle.list-append equivalent)
# ---------------------------------------------------------------------------


def list_append_graph(
    history: Sequence[dict],
    additional_graphs: Sequence[str] = (),
    engine: str | None = None,
    pairs=None,
) -> TxnGraph:
    """Infer the dependency graph for a list-append history.

    Version order per key is recovered from reads: every observed read must
    be a prefix of the longest observed read (else ``incompatible-order``),
    so the longest read *is* the version order of observed values
    (elle's core trick — the paper's "recoverability").

    ``engine`` routes between the vectorized column engine (the default;
    see ``resolve_engine``) and the retained per-op loop reference
    (``list_append_graph_loops``) — identical results either way,
    differential-tested.  Histories whose values can't ride int64
    columns fall back to the loops automatically."""
    if resolve_engine(engine) == "columns":
        from jepsen_tpu.checker import txn_columns as tc

        try:
            return tc.list_append_graph_columns(
                history, additional_graphs, pairs=pairs
            )
        except tc.NotColumnizable:
            obs.counter("elle.columns_fallback", workload="list-append")
    return list_append_graph_loops(history, additional_graphs, pairs=pairs)


def list_append_graph_loops(
    history: Sequence[dict],
    additional_graphs: Sequence[str] = (),
    pairs=None,
) -> TxnGraph:
    """The per-op/per-mop loop reference for ``list_append_graph`` —
    retained as the differential oracle and the fallback for histories
    the column engine can't pack."""
    nodes = txn_nodes(history, pairs)
    n = len(nodes)
    ww, wr, rw = _empty(n), _empty(n), _empty(n)
    expl: dict = {}
    anomalies: dict[str, list] = {}

    def add_anom(name: str, item) -> None:
        anomalies.setdefault(name, []).append(item)

    # -- Per-txn (internal, duplicate in-txn appends handled via appender map)
    for nd in nodes:
        if nd.ok:
            for a in _internal_anomalies_append(nd):
                add_anom("internal", a)

    # -- Appender map + duplicate appends
    appender: dict = {}  # (k, v) -> node
    for nd in nodes:
        for mop in nd.value:
            if mop[0] == "append":
                kv = (mop[1], mop[2])
                if kv in appender:
                    add_anom(
                        "duplicate-elements",
                        {"key": mop[1], "element": mop[2], "ops": [appender[kv].op, nd.op]},
                    )
                else:
                    appender[kv] = nd

    failed = _failed_writes(history, append=True)
    inter = _intermediate_writes(nodes)

    # -- Collect external reads per key (ok txns only: info reads aren't
    #    evidence) and all observed elements
    reads_by_key: dict[Any, list[tuple[TxnNode, list]]] = {}
    for nd in nodes:
        if not nd.ok:
            continue
        for k, v in t.ext_reads(nd.value).items():
            reads_by_key.setdefault(k, []).append((nd, list(v or [])))

    # -- G1a / G1b from read contents
    for k, pairs in reads_by_key.items():
        for nd, lst in pairs:
            for x in lst:
                if (k, x) in failed:
                    add_anom(
                        "G1a",
                        {"op": nd.op, "key": k, "element": x, "writer": failed[(k, x)]},
                    )
            for pos, x in enumerate(lst):
                if (k, x) in inter:
                    wnode, nxt = inter[(k, x)]
                    if pos + 1 >= len(lst) or lst[pos + 1] != nxt:
                        add_anom(
                            "G1b",
                            {"op": nd.op, "key": k, "element": x, "writer": wnode.op},
                        )

    # -- Version order per key = longest observed read; prefix check
    for k, pairs in reads_by_key.items():
        longest: list = []
        for _, lst in pairs:
            if len(lst) > len(longest):
                longest = lst
        ok_order = True
        for nd, lst in pairs:
            if lst != longest[: len(lst)]:
                add_anom(
                    "incompatible-order",
                    {"key": k, "read": lst, "longest": longest, "op": nd.op},
                )
                ok_order = False
        if not ok_order:
            continue  # no trustworthy version order for this key

        order = longest
        # ww: consecutive observed appends
        for a, b in zip(order, order[1:]):
            na, nb = appender.get((k, a)), appender.get((k, b))
            if na is not None and nb is not None and na.id != nb.id:
                ww[na.id, nb.id] = True
                expl[("ww", na.id, nb.id)] = lambda na=na, nb=nb, a=a, b=b, k=k, order=order: (
                    f"{_t(na)} appended {a!r} to {k!r} ([:append {k!r} {a!r}]) "
                    f"and {_t(nb)} appended {b!r} immediately after it in "
                    f"{k!r}'s version order {order!r}"
                )
        # wr / rw per read
        for nd, lst in pairs:
            if lst:
                wn = appender.get((k, lst[-1]))
                if wn is not None and wn.id != nd.id:
                    wr[wn.id, nd.id] = True
                    expl[("wr", wn.id, nd.id)] = lambda nd=nd, wn=wn, k=k, lst=lst: (
                        f"{_t(nd)}'s read of {k!r} ([:r {k!r} {lst!r}]) observed "
                        f"{lst[-1]!r} as its final element, which {_t(wn)} "
                        f"appended ([:append {k!r} {lst[-1]!r}])"
                    )
            pos = len(lst)
            if pos < len(order):
                nxt = appender.get((k, order[pos]))
                if nxt is not None and nxt.id != nd.id:
                    rw[nd.id, nxt.id] = True
                    expl[("rw", nd.id, nxt.id)] = lambda nd=nd, nxt=nxt, k=k, lst=lst, nv=order[pos]: (
                        f"{_t(nd)}'s read of {k!r} ([:r {k!r} {lst!r}]) did not "
                        f"observe {nv!r}, which {_t(nxt)} appended next "
                        f"in the version order ([:append {k!r} {nv!r}])"
                    )

    return TxnGraph(
        nodes=nodes,
        ww=ww,
        wr=wr,
        rw=rw,
        extra=build_extra(nodes, additional_graphs),
        explanations=expl,
        anomalies=anomalies,
    )


# ---------------------------------------------------------------------------
# rw-register inference (elle.rw-register equivalent)
# ---------------------------------------------------------------------------


def rw_register_graph(
    history: Sequence[dict],
    additional_graphs: Sequence[str] = (),
    sequential_keys: bool = False,
    linearizable_keys: bool = False,
    engine: str | None = None,
    pairs=None,
) -> TxnGraph:
    """Infer the dependency graph for unique-write register transactions.

    Only wr edges are directly observable.  With ``linearizable_keys`` (per
    tests/cycle/wr.clj:25-27) each key is assumed independently
    linearizable, so the realtime completion order of its writers yields a
    version order (hence ww/rw edges); ``sequential_keys`` uses invocation
    order instead (weaker: per-process program order lifted to a total
    order).

    ``engine`` routes like ``list_append_graph``'s (vectorized columns by
    default, loop reference on fallback — identical results)."""
    if resolve_engine(engine) == "columns":
        from jepsen_tpu.checker import txn_columns as tc

        try:
            return tc.rw_register_graph_columns(
                history, additional_graphs,
                sequential_keys=sequential_keys,
                linearizable_keys=linearizable_keys, pairs=pairs,
            )
        except tc.NotColumnizable:
            obs.counter("elle.columns_fallback", workload="rw-register")
    return rw_register_graph_loops(
        history, additional_graphs, sequential_keys=sequential_keys,
        linearizable_keys=linearizable_keys, pairs=pairs,
    )


def rw_register_graph_loops(
    history: Sequence[dict],
    additional_graphs: Sequence[str] = (),
    sequential_keys: bool = False,
    linearizable_keys: bool = False,
    pairs=None,
) -> TxnGraph:
    """The loop reference for ``rw_register_graph`` (differential oracle
    + fallback; see ``list_append_graph_loops``)."""
    nodes = txn_nodes(history, pairs)
    n = len(nodes)
    ww, wr, rw = _empty(n), _empty(n), _empty(n)
    expl: dict = {}
    anomalies: dict[str, list] = {}

    def add_anom(name: str, item) -> None:
        anomalies.setdefault(name, []).append(item)

    for nd in nodes:
        if nd.ok:
            for a in _internal_anomalies_wr(nd):
                add_anom("internal", a)

    writer: dict = {}  # (k, v) -> node
    for nd in nodes:
        for k, v in t.ext_writes(nd.value).items():
            if (k, v) in writer:
                add_anom(
                    "duplicate-writes",
                    {"key": k, "value": v, "ops": [writer[(k, v)].op, nd.op]},
                )
            else:
                writer[(k, v)] = nd

    failed = _failed_writes(history, append=False)
    inter = _intermediate_writes(nodes)

    reads: list[tuple[TxnNode, Any, Any]] = []  # (node, key, value)
    for nd in nodes:
        if not nd.ok:
            continue
        for k, v in t.ext_reads(nd.value).items():
            reads.append((nd, k, v))

    for nd, k, v in reads:
        if v is None:
            continue
        if (k, v) in failed:
            add_anom("G1a", {"op": nd.op, "key": k, "value": v, "writer": failed[(k, v)]})
            continue
        if (k, v) in inter:
            wnode, _ = inter[(k, v)]
            add_anom("G1b", {"op": nd.op, "key": k, "value": v, "writer": wnode.op})
        wn = writer.get((k, v))
        if wn is not None and wn.id != nd.id:
            wr[wn.id, nd.id] = True
            expl[("wr", wn.id, nd.id)] = lambda nd=nd, wn=wn, k=k, v=v: (
                f"{_t(nd)}'s read of {k!r} ([:r {k!r} {v!r}]) observed the "
                f"value {_t(wn)} wrote ([:w {k!r} {v!r}])"
            )

    # -- Version orders under per-key ordering assumptions
    if sequential_keys or linearizable_keys:
        by_key: dict[Any, list[tuple[int, Any, TxnNode]]] = {}
        for (k, v), nd in writer.items():
            sort_key = nd.complete_index if linearizable_keys else nd.invoke_index
            by_key.setdefault(k, []).append((sort_key, v, nd))
        readers: dict[Any, list[tuple[TxnNode, Any]]] = {}
        for nd, k, v in reads:
            readers.setdefault(k, []).append((nd, v))
        for k, writes in by_key.items():
            writes.sort(key=lambda x: x[0])
            order = [None] + [v for _, v, _ in writes]
            wnodes = {v: nd for _, v, nd in writes}
            for a, b in zip(order, order[1:]):
                na, nb = wnodes.get(a), wnodes.get(b)
                if na is not None and nb is not None and na.id != nb.id:
                    ww[na.id, nb.id] = True
                    expl[("ww", na.id, nb.id)] = lambda na=na, nb=nb, a=a, b=b, k=k: (
                        f"{_t(na)} wrote {k!r} = {a!r} ([:w {k!r} {a!r}]) and "
                        f"{_t(nb)} overwrote it with {b!r} ([:w {k!r} {b!r}]) "
                        f"in {k!r}'s version order"
                    )
            pos_of = {v: i for i, v in enumerate(order)}
            for nd, v in readers.get(k, ()):
                if v not in pos_of:
                    continue
                pos = pos_of[v]
                if pos + 1 < len(order):
                    nxt = wnodes.get(order[pos + 1])
                    if nxt is not None and nxt.id != nd.id:
                        rw[nd.id, nxt.id] = True
                        expl[("rw", nd.id, nxt.id)] = lambda nd=nd, nxt=nxt, k=k, v=v, nv=order[pos + 1]: (
                            f"{_t(nd)}'s read of {k!r} ([:r {k!r} {v!r}]) did "
                            f"not observe {nv!r}, which {_t(nxt)} "
                            f"wrote next in the version order "
                            f"([:w {k!r} {nv!r}])"
                        )

    return TxnGraph(
        nodes=nodes,
        ww=ww,
        wr=wr,
        rw=rw,
        extra=build_extra(nodes, additional_graphs),
        explanations=expl,
        anomalies=anomalies,
    )


# ---------------------------------------------------------------------------
# Batched inference (the shared pass the CheckService's graph lane and
# independent.checker's check_batch route through)
# ---------------------------------------------------------------------------


def list_append_graphs(
    histories: Sequence[Sequence[dict]],
    additional_graphs: Sequence[str] = (),
    engine: str | None = None,
) -> list[TxnGraph]:
    """Infer MANY list-append histories under one shared pass: the
    engine is resolved once, one ``elle.infer_batch`` span covers the
    whole batch, and every graph comes out carrying its sparse edge
    arrays so the batch classification sweep never scans a dense
    matrix."""
    engine = resolve_engine(engine)
    with obs.span(
        "elle.infer_batch", histories=len(histories),
        workload="list-append", engine=engine,
    ):
        return [
            list_append_graph(hh, additional_graphs, engine=engine)
            for hh in histories
        ]


def rw_register_graphs(
    histories: Sequence[Sequence[dict]],
    additional_graphs: Sequence[str] = (),
    sequential_keys: bool = False,
    linearizable_keys: bool = False,
    engine: str | None = None,
) -> list[TxnGraph]:
    """Batched form of ``rw_register_graph`` (see
    ``list_append_graphs``)."""
    engine = resolve_engine(engine)
    with obs.span(
        "elle.infer_batch", histories=len(histories),
        workload="rw-register", engine=engine,
    ):
        return [
            rw_register_graph(
                hh, additional_graphs, sequential_keys=sequential_keys,
                linearizable_keys=linearizable_keys, engine=engine,
            )
            for hh in histories
        ]
