"""Checker framework: validates a history against a consistency claim.

Mirrors the contract of ``jepsen.checker`` (reference:
jepsen/src/jepsen/checker.clj:52-116): a checker's ``check(test, history,
opts)`` returns a result dict with at least ``"valid?"`` ∈ {True, False,
"unknown"}; ``check_safe`` converts exceptions into ``"unknown"`` results;
``compose`` runs a map of checkers in parallel and merges validity with
false > unknown > true priority (checker.clj:29-50).

This module is the seam the TPU backend slots into: CPU-oracle checkers and
TPU-kernel checkers implement the same protocol and are interchangeable,
like the reference's ``:algorithm`` switch between knossos backends
(checker.clj:199-203).
"""

from __future__ import annotations

import threading
import traceback
from typing import Any, Callable, Mapping, Sequence

from jepsen_tpu import faults, obs
from jepsen_tpu.utils import bounded_pmap

UNKNOWN = "unknown"

#: checker.clj:29-34 — larger numbers dominate when composing.
VALID_PRIORITIES = {True: 0, False: 1, UNKNOWN: 0.5}


def merge_valid(valids) -> Any:
    """Merge validity verdicts, highest priority wins (checker.clj:36-50)."""
    result = True
    for v in valids:
        if v not in VALID_PRIORITIES:
            raise ValueError(f"{v!r} is not a known valid? value")
        if VALID_PRIORITIES[v] > VALID_PRIORITIES[result]:
            result = v
    return result


class Checker:
    """Base checker protocol (checker.clj:52-67).

    ``opts`` keys include ``subdirectory`` — a directory within the test's
    store directory for output files.
    """

    def check(self, test: Mapping, history: Sequence[dict], opts: Mapping) -> dict | None:
        raise NotImplementedError

    def __call__(self, test, history, opts=None):
        return self.check(test, history, opts or {})


class FnChecker(Checker):
    """Adapt a plain function ``(test, history, opts) -> result`` to Checker."""

    def __init__(self, fn: Callable, name: str | None = None):
        self.fn = fn
        self.name = name or getattr(fn, "__name__", "fn-checker")

    def check(self, test, history, opts):
        return self.fn(test, history, opts)

    def __repr__(self):
        return f"FnChecker({self.name})"


def checker(fn: Callable) -> Checker:
    """Decorator form of FnChecker."""
    return FnChecker(fn)


def checker_name(chk: Checker) -> str:
    """A human-attributable name for a checker: its ``name`` attribute
    (FnChecker, or anything that sets one) else the class name."""
    n = getattr(chk, "name", None)
    if n:
        return str(n)
    return type(chk).__name__


def resolve_opts(opts: Mapping | None) -> dict:
    """Normalize checker opts for the fault-tolerance keys: a raw
    ``"check-deadline"`` seconds value is wrapped ONCE into a shared
    ``faults.Deadline`` under ``"deadline"`` — Compose normalizes before
    fanning out, so every composed checker polls the same wall-clock
    budget instead of each starting its own."""
    opts = dict(opts or {})
    if opts.get("deadline") is None and opts.get("check-deadline") is not None:
        opts["deadline"] = faults.Deadline(float(opts["check-deadline"]))
    else:
        opts["deadline"] = faults.Deadline.coerce(opts.get("deadline"))
    return opts


def check_safe(chk: Checker, test, history, opts=None, name: str | None = None) -> dict:
    """check, but exceptions become ``{"valid?": "unknown", "error": ...}``
    (checker.clj:74-85).

    The failure names WHICH checker raised (``"checker"`` key) so composed
    results stay attributable, and each check emits a telemetry span with
    the checker's name, duration, and verdict (``name`` lets Compose pass
    the map key the caller knows the checker by).  Opts are normalized
    through ``resolve_opts`` so a ``"check-deadline"`` budget reaches the
    checker as a live ``"deadline"`` object."""
    name = name or checker_name(chk)
    opts = resolve_opts(opts)
    with obs.span("checker.check", checker=name) as sp:
        try:
            result = chk.check(test, history, opts)
            if result is None:
                result = {"valid?": True}
        except Exception:  # noqa: BLE001 - contract: never propagate
            obs.counter("checker.errors", checker=name)
            result = {
                "valid?": UNKNOWN,
                "checker": name,
                "error": traceback.format_exc(),
            }
        sp.set(valid=result.get("valid?"))
        return result


class Noop(Checker):
    """Empty checker returning nothing (checker.clj:68-72)."""

    def check(self, test, history, opts):
        return None


def noop() -> Checker:
    return Noop()


class UnbridledOptimism(Checker):
    """Everything is awesome (checker.clj:118-122)."""

    def check(self, test, history, opts):
        return {"valid?": True}


def unbridled_optimism() -> Checker:
    return UnbridledOptimism()


class Compose(Checker):
    """Run named checkers (in parallel) and merge results (checker.clj:87-99)."""

    def __init__(self, checker_map: Mapping[str, Checker]):
        self.checker_map = dict(checker_map)

    def check(self, test, history, opts):
        items = list(self.checker_map.items())
        # normalize ONCE so every composed checker shares one deadline
        # budget (resolve_opts in each check_safe then passes it through)
        opts = resolve_opts(opts)
        results = bounded_pmap(
            lambda kv: (kv[0], check_safe(kv[1], test, history, opts, name=kv[0])),
            items,
        )
        out = dict(results)
        out["valid?"] = merge_valid(r["valid?"] for _, r in results)
        return out


def compose(checker_map: Mapping[str, Checker]) -> Checker:
    return Compose(checker_map)


class ConcurrencyLimit(Checker):
    """Bound concurrent executions of a memory-hungry checker
    (checker.clj:101-116)."""

    def __init__(self, limit: int, chk: Checker):
        self.sem = threading.Semaphore(limit)
        self.chk = chk

    def check(self, test, history, opts):
        with self.sem:
            return self.chk.check(test, history, opts)


def concurrency_limit(limit: int, chk: Checker) -> Checker:
    return ConcurrencyLimit(limit, chk)


# ---------------------------------------------------------------------------
# Stats & exceptions
# ---------------------------------------------------------------------------


def _stats_of(history) -> dict:
    """Counts for one (sub)history (checker.clj:153-164)."""
    from jepsen_tpu import history as h

    ok = sum(1 for o in history if h.is_ok(o))
    fail = sum(1 for o in history if h.is_fail(o))
    info = sum(1 for o in history if h.is_info(o))
    return {
        "valid?": ok > 0,
        "count": ok + fail + info,
        "ok-count": ok,
        "fail-count": fail,
        "info-count": info,
    }


class Stats(Checker):
    """Success/failure rates overall and by :f; valid iff every f has some ok
    ops (checker.clj:166-183)."""

    def check(self, test, history, opts):
        from jepsen_tpu import history as h

        completions = [o for o in history if not h.is_invoke(o) and o["process"] != h.NEMESIS]
        by_f: dict[Any, dict] = {}
        for f in sorted({o["f"] for o in completions}, key=str):
            by_f[f] = _stats_of([o for o in completions if o["f"] == f])
        out = _stats_of(completions)
        out["by-f"] = by_f
        out["valid?"] = merge_valid(g["valid?"] for g in by_f.values())
        return out


def stats() -> Checker:
    return Stats()


class UnhandledExceptions(Checker):
    """Descending-frequency summary of exceptions embedded in :info ops
    (checker.clj:124-151).  Ops carry exceptions as an ``exception`` key —
    either an Exception instance or a dict with a ``class`` key."""

    @staticmethod
    def _class_of(e) -> str:
        if isinstance(e, BaseException):
            return type(e).__name__
        if isinstance(e, Mapping):
            return str(e.get("class", "unknown"))
        return str(type(e).__name__)

    def check(self, test, history, opts):
        from jepsen_tpu import history as h

        groups: dict[str, list] = {}
        for o in history:
            if h.is_info(o) and o.get("exception") is not None:
                groups.setdefault(self._class_of(o["exception"]), []).append(o)
        exes = [
            {"count": len(ops), "class": cls, "example": ops[0]}
            for cls, ops in sorted(groups.items(), key=lambda kv: -len(kv[1]))
        ]
        return {"valid?": True, "exceptions": exes} if exes else {"valid?": True}


def unhandled_exceptions() -> Checker:
    return UnhandledExceptions()
