"""Clock-offset plot checker.

Mirrors ``jepsen.checker.clock`` (reference: jepsen/src/jepsen/checker/
clock.clj:13-75): collects the ``clock-offsets`` maps the clock nemesis
embeds in its completions (jepsen_tpu.nemesis.time), draws one line per
node over test time into ``clock-skew.svg``, and always reports valid —
it's an observability aid, not a judgment.
"""

from __future__ import annotations

from jepsen_tpu import history as h
from jepsen_tpu.checker import Checker, checker as as_checker
from jepsen_tpu.checker.perf import SERIES_COLORS, SvgPlot, _shade, _write


def offset_series(history) -> dict:
    """{node: [(time_s, offset_s)]} from nemesis completions
    (clock.clj:13-24)."""
    out: dict = {}
    for o in history:
        offsets = o.get("clock-offsets")
        if offsets is None or o["type"] == h.INVOKE:
            continue
        t = o["time"] / 1e9
        for node, off in offsets.items():
            out.setdefault(node, []).append((t, off))
    return out


@as_checker
def _clock_plot(test, history, opts):
    plot = SvgPlot(f"{test.get('name', 'test')} clock offsets", "time (s)", "offset (s)")
    _shade(plot, test, history)
    for i, (node, pts) in enumerate(sorted(offset_series(history).items())):
        plot.line(node, pts, SERIES_COLORS[i % len(SERIES_COLORS)])
    out: dict = {"valid?": True}
    _write(test, opts, "clock-skew.svg", plot.render(), out)
    return out


def clock_plot() -> Checker:
    """The clock-offset plot checker (checker.clj:831-837)."""
    return _clock_plot
