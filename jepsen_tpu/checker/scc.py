"""Host-side SCC cycle classification for dependency graphs.

This is the elle checkers' DEFAULT cycle backend (round-5 chip-day
measurement): sparse O(V+E) beats the dense MXU closure's O(n³ log n)
at every single-chip shape, batched per-key graphs included — 1024
48-txn graphs classify in 0.96 s here vs 3.4 s on the vmapped device
closure, and the gap widens with graph size (64×700-txn: 1.2 s vs
10.5 s).  The device kernels (jepsen_tpu.ops.closure) remain as an
explicit ``backend="device"`` opt-in and as the mesh-sharded closure
for giant graphs across a multi-chip mesh.  The elle checkers pick per
measurement, the way the reference's competition checker picks
algorithms (checker.clj:199-203).

Classification is exact, matching ops/closure.py's semantics:

  G0        some SCC of (ww ∪ extra) contains a cycle
  G1c       some wr edge (a, b) has a return path b→a in (ww ∪ wr ∪ extra)
            — equivalently both endpoints sit in one SCC of that graph
  G-single  some rw edge (a, b) has a return path b→a in (ww ∪ wr ∪ extra)
            (reachability over the rw-free graph: condensation + bitset
            DAG closure)
  G2        some rw edge (a, b) has a return path b→a in the full graph —
            both endpoints in one SCC of it

Returns the same (flags, hints) shape as ops/closure.classify_graph so
witness recovery (host BFS) is shared.
"""

from __future__ import annotations

import numpy as np


def tarjan_scc(n: int, adj_lists) -> np.ndarray:
    """SCC id per node (iterative Tarjan). ``adj_lists[v]`` = successor
    list."""
    UNVISITED = -1
    index = np.full(n, UNVISITED, dtype=np.int64)
    low = np.zeros(n, dtype=np.int64)
    on_stack = np.zeros(n, dtype=bool)
    comp = np.full(n, -1, dtype=np.int64)
    stack: list[int] = []
    counter = 0
    n_comps = 0
    for root in range(n):
        if index[root] != UNVISITED:
            continue
        work = [(root, 0)]
        while work:
            v, pi = work[-1]
            if pi == 0:
                index[v] = low[v] = counter
                counter += 1
                stack.append(v)
                on_stack[v] = True
            recurse = False
            succs = adj_lists[v]
            for j in range(pi, len(succs)):
                w = succs[j]
                if index[w] == UNVISITED:
                    work[-1] = (v, j + 1)
                    work.append((w, 0))
                    recurse = True
                    break
                if on_stack[w]:
                    low[v] = min(low[v], index[w])
            if recurse:
                continue
            work.pop()
            if low[v] == index[v]:
                while True:
                    w = stack.pop()
                    on_stack[w] = False
                    comp[w] = n_comps
                    if w == v:
                        break
                n_comps += 1
            if work:
                u, _ = work[-1]
                low[u] = min(low[u], low[v])
    return comp


def _adj_lists(n: int, edges: np.ndarray):
    out: list[list[int]] = [[] for _ in range(n)]
    for a, b in edges:
        out[a].append(int(b))
    return out


def _first_edge_in_cycle(edges: np.ndarray, comp: np.ndarray):
    """(a, b) of some edge whose endpoints share an SCC (a cycle passes
    through it), else None.  Self-loops qualify."""
    if len(edges) == 0:
        return None
    same = comp[edges[:, 0]] == comp[edges[:, 1]]
    sizes = np.bincount(comp, minlength=comp.max() + 1 if len(comp) else 0)
    real = same & ((edges[:, 0] == edges[:, 1]) | (sizes[comp[edges[:, 0]]] > 1))
    idx = np.flatnonzero(real)
    if len(idx) == 0:
        return None
    a, b = edges[idx[0]]
    return int(a), int(b)


def _dag_reach_pairs(n: int, comp: np.ndarray, edges: np.ndarray, queries: np.ndarray):
    """For each query edge (a, b): is there a NONEMPTY path b→a in the
    graph?  Bitset closure over the SCC condensation (O(C·E/64)).

    Nonempty matters for self-loop queries (a == b): the dense backend's
    ``closure(wwr)[a, a]`` is true only for a real cycle through a, so a
    bare rw self-loop on an otherwise-acyclic node must NOT read as a
    return path here either (it is G2 territory, not G-single — both
    backends must agree regardless of graph size)."""
    if len(queries) == 0:
        return np.zeros(0, dtype=bool)
    C = int(comp.max()) + 1 if n else 0
    words = (C + 63) // 64
    reach = np.zeros((C, words), dtype=np.uint64)
    reach[np.arange(C), np.arange(C) // 64] |= np.uint64(1) << (
        np.arange(C) % 64
    ).astype(np.uint64)
    # A component contains a nonempty internal path between any two of its
    # nodes iff it is cyclic: size > 1, or a singleton with a self-loop.
    cyclic = np.bincount(comp, minlength=C) > 1
    if len(edges):
        self_loops = edges[edges[:, 0] == edges[:, 1], 0]
        cyclic[comp[self_loops]] = True
    cedges = np.unique(comp[edges], axis=0) if len(edges) else np.zeros((0, 2), np.int64)
    cedges = cedges[cedges[:, 0] != cedges[:, 1]]
    # Tarjan completes an SCC only after all its successors, so an SCC's
    # successors always have SMALLER ids: ascending id order visits
    # successors before their predecessors.
    by_src: list[list[int]] = [[] for _ in range(C)]
    for a, b in cedges:
        by_src[a].append(int(b))
    for c in range(C):
        for d in by_src[c]:
            reach[c] |= reach[d]
    qa, qb = comp[queries[:, 0]], comp[queries[:, 1]]
    word, bit = qa // 64, (qa % 64).astype(np.uint64)
    reach_refl = (reach[qb, word] >> bit) & np.uint64(1) > 0
    # Same component: reflexive reach is trivially true; the real question
    # is whether the component supports a nonempty return path.
    return np.where(qa == qb, cyclic[qa], reach_refl)


def _union_edges(*parts: np.ndarray) -> np.ndarray:
    """Sorted-unique union of (E, 2) edge arrays — exactly the rows
    ``np.argwhere`` would produce on the OR of the dense matrices."""
    parts = [p for p in parts if len(p)]
    if not parts:
        return np.zeros((0, 2), np.int64)
    cat = np.concatenate(parts)
    return np.unique(cat, axis=0)


def classify_graph_scc(ww, wr, rw, extra, edges=None):
    """(flags, hints) — same contract as ops/closure.classify_graph, via
    sparse host algorithms.

    ``edges`` is an optional precomputed sparse view ({"ww"/"wr"/"rw"/
    "extra": (E, 2) argwhere-ordered rows} — ``TxnGraph.edge_arrays``):
    with it, classification of an n-node graph never scans the dense
    [n, n] matrices (five ``np.argwhere`` passes over 10k-node graphs
    measured ~1.5 s of config 3's 2.65 s — the edge lists are ~37k
    rows)."""
    n = ww.shape[0]
    flags = {"G0": False, "G1c": False, "G-single": False, "G2": False}
    hints = {"G0": None, "G1c": None, "G-single": None, "G2": None}
    if n == 0:
        return flags, hints

    if edges is not None:
        e_ww = _union_edges(edges["ww"], edges["extra"])
        e_wr = np.asarray(edges["wr"])
        e_rw = np.asarray(edges["rw"])
        e_wwr = _union_edges(edges["ww"], edges["wr"], edges["extra"])
    else:
        e_ww = np.argwhere(ww | extra)
        e_wr = np.argwhere(wr)
        e_rw = np.argwhere(rw)
        e_wwr = np.argwhere(ww | wr | extra)

    # G0
    comp_ww = tarjan_scc(n, _adj_lists(n, e_ww))
    hit = _first_edge_in_cycle(e_ww, comp_ww)
    if hit:
        flags["G0"] = True
        hints["G0"] = (hit[0], hit[0])

    # G1c / G-single share the wwr SCCs
    comp_wwr = tarjan_scc(n, _adj_lists(n, e_wwr))
    if len(e_wr):
        same = comp_wwr[e_wr[:, 0]] == comp_wwr[e_wr[:, 1]]
        idx = np.flatnonzero(same)
        if len(idx):
            flags["G1c"] = True
            hints["G1c"] = (int(e_wr[idx[0], 0]), int(e_wr[idx[0], 1]))
    if len(e_rw):
        back = _dag_reach_pairs(n, comp_wwr, e_wwr, e_rw)
        idx = np.flatnonzero(back)
        if len(idx):
            flags["G-single"] = True
            hints["G-single"] = (int(e_rw[idx[0], 0]), int(e_rw[idx[0], 1]))

    # G2 over the full graph
    if edges is not None:
        e_all = _union_edges(e_wwr, e_rw)
    else:
        e_all = np.argwhere(ww | wr | rw | extra)
    comp_all = tarjan_scc(n, _adj_lists(n, e_all))
    if len(e_rw):
        same = comp_all[e_rw[:, 0]] == comp_all[e_rw[:, 1]]
        idx = np.flatnonzero(same)
        if len(idx):
            flags["G2"] = True
            hints["G2"] = (int(e_rw[idx[0], 0]), int(e_rw[idx[0], 1]))
    return flags, hints
