"""Column-native transaction-graph inference (the vectorized elle engine).

The per-op/per-mop Python loops in :mod:`jepsen_tpu.checker.txn_graph`
were the last interpreted hot path in the checker (ROADMAP item 4 —
"the way wgl's pack was before it went column-native").  This module
rebuilds them as flat int64 column operations over numpy:

  * **node extraction** — op-type masks and pair-index gathers over the
    history's SoA columns.  A stored ``history.ColumnHistory`` feeds its
    ``.cols`` arrays straight in (``store.format.read_columns``), so
    checking a disk history never rehydrates op dicts; plain dict
    histories pay one thin column-building pass and then ride the same
    vectorized core.
  * **mop columns** — every micro-op flattened to ``(node, pos, key,
    is_read, value)`` rows with interned key codes; external reads,
    intermediate writes, duplicate detection, and version orders are
    ``np.argsort``/``np.searchsorted`` key-group operations instead of
    dict folds.
  * **pair lookups** — ``(key, value)`` maps (appender / writer / failed
    / intermediate) are packed into single int64 codes and resolved by
    binary search, preserving Python's int equality semantics exactly
    (``True == 1`` included, since bools coerce to the same codes).

Anomaly *emission* stays host-side Python — anomalies are rare, and the
emitted dicts must reference the original op/mop objects so results are
bit-identical with the loop reference (`txn_graph.list_append_graph_loops`
/ ``rw_register_graph_loops``, retained as the differential oracle).
Nodes and per-edge explanations materialize lazily: only ops on a
witness cycle (or in an anomaly) ever build a dict.

Histories whose mop values are not machine-int-packable (strings,
floats, huge ints past the packing range) raise :class:`NotColumnizable`
and the front door in ``txn_graph`` falls back to the loop reference —
identical results, loop-reference speed.
"""

from __future__ import annotations

from collections.abc import Mapping, Sequence

import numpy as np

from jepsen_tpu import history as h
from jepsen_tpu import obs

_I64 = np.int64
_I64_MAX = np.iinfo(np.int64).max
_I64_MIN = np.iinfo(np.int64).min


class NotColumnizable(Exception):
    """This history's values can't ride int64 columns; use the loops."""


# ---------------------------------------------------------------------------
# Small array helpers
# ---------------------------------------------------------------------------


def _ranges(starts: np.ndarray, lens: np.ndarray) -> np.ndarray:
    """Concatenated aranges: ``[s0, s0+l0) ++ [s1, s1+l1) ++ ...``."""
    starts = np.asarray(starts, _I64)
    lens = np.asarray(lens, _I64)
    total = int(lens.sum())
    if total == 0:
        return np.zeros(0, _I64)
    before = np.concatenate(([0], np.cumsum(lens)[:-1]))
    return np.repeat(starts - before, lens) + np.arange(total, dtype=_I64)


def _int_array(vals: list) -> np.ndarray:
    """int64 array from a list of Python ints (NotColumnizable otherwise:
    floats/strings/objects must not silently coerce — 1.5 != 1)."""
    if not vals:
        return np.zeros(0, _I64)
    arr = np.asarray(vals)
    if arr.dtype.kind not in ("i", "u"):
        raise NotColumnizable(f"non-integer values (dtype {arr.dtype})")
    if arr.dtype.kind == "u" and len(arr) and int(arr.max()) > 2**62:
        raise NotColumnizable("unsigned values past the packing range")
    return arr.astype(_I64, copy=False)


def _vals_with_none(raw: list) -> tuple[np.ndarray, np.ndarray]:
    """(int64 array, none-mask) for a value list that may contain None
    (``nil`` mop values); the sentinel is substituted once the global
    value range is known."""
    if not raw:
        return np.zeros(0, _I64), np.zeros(0, bool)
    none = np.fromiter((x is None for x in raw), bool, len(raw))
    filled = [0 if x is None else x for x in raw]
    return _int_array(filled), none


class _ValuePool:
    """Collects every value array that participates in a ``(key, value)``
    identity, then packs (key, value) pairs into single int64 codes.
    ``None`` maps to a sentinel strictly below the observed minimum, so
    it can never collide with a real value."""

    def __init__(self, n_keys: int):
        self.n_keys = max(1, int(n_keys))
        self._arrays: list[tuple[np.ndarray, np.ndarray]] = []

    def add(self, arr: np.ndarray, none_mask: np.ndarray | None = None):
        if none_mask is None:
            none_mask = np.zeros(len(arr), bool)
        self._arrays.append((arr, none_mask))
        return arr, none_mask

    def finalize(self) -> None:
        vmin, vmax = _I64_MAX, _I64_MIN
        for arr, none in self._arrays:
            real = arr[~none] if none.any() else arr
            if len(real):
                vmin = min(vmin, int(real.min()))
                vmax = max(vmax, int(real.max()))
        if vmin > vmax:  # no real values at all
            vmin = vmax = 0
        if vmin <= _I64_MIN + 1:
            raise NotColumnizable("values reach the packing range floor")
        self.none_code = vmin - 1
        self.vmin = self.none_code
        span = vmax - self.vmin + 1
        if span <= 0 or span > (2**62) // self.n_keys:
            raise NotColumnizable("value range too wide to pack with keys")
        self.span = span
        for arr, none in self._arrays:
            if none.any():
                arr[none] = self.none_code

    def pack(self, keys: np.ndarray, vals: np.ndarray) -> np.ndarray:
        """(key code, value) -> one sortable int64."""
        return keys.astype(_I64) * self.span + (vals - self.vmin)


class _PackedMap:
    """Sorted (packed-code -> source-row) map with binary-search lookup.
    ``keep`` selects which duplicate wins — "first" mirrors dict
    ``setdefault`` maps (appender/writer), "last" mirrors plain
    assignment maps (failed/intermediate writes)."""

    def __init__(self, packed: np.ndarray, keep: str = "first"):
        order = np.argsort(packed, kind="stable")
        sp = packed[order]
        if len(sp) == 0:
            self.packed = sp
            self.rows = order
            self.dup_rows = order
            return
        first = np.ones(len(sp), bool)
        first[1:] = sp[1:] != sp[:-1]
        if keep == "first":
            sel = first
        else:
            sel = np.ones(len(sp), bool)
            sel[:-1] = sp[1:] != sp[:-1]
        self.packed = sp[sel]
        self.rows = order[sel]
        #: source rows that lost the "first" race (duplicate detection).
        self.dup_rows = np.sort(order[~first])

    def lookup(self, q: np.ndarray) -> np.ndarray:
        """Source row per query code, -1 when absent."""
        if len(self.packed) == 0:
            return np.full(len(q), -1, _I64)
        pos = np.searchsorted(self.packed, q)
        pos_c = np.minimum(pos, len(self.packed) - 1)
        hit = self.packed[pos_c] == q
        return np.where(hit, self.rows[pos_c], _I64(-1))


# ---------------------------------------------------------------------------
# Node columns (the op-level front end)
# ---------------------------------------------------------------------------

_CODE_OTHER = 4  # op types outside invoke/ok/fail/info (never a node)


def pair_index_codes(type_codes: np.ndarray, proc_codes: np.ndarray) -> np.ndarray:
    """Vectorized ``history.pair_index`` over type/process code columns:
    a completion pairs with its process-group predecessor iff that
    predecessor is an invoke (the open-slot-overwrite semantics of the
    dict walk, proven equivalent: a second invoke overwrites the open
    slot, and any completion consumes it)."""
    n = len(type_codes)
    pair = np.full(n, -1, _I64)
    if n < 2:
        return pair
    order = np.argsort(proc_codes, kind="stable")
    t = type_codes[order]
    p = proc_codes[order]
    link = (p[1:] == p[:-1]) & (t[:-1] == 0) & (t[1:] != 0)
    a = order[:-1][link]
    b = order[1:][link]
    pair[a] = b
    pair[b] = a
    return pair


def _column_value(hist: h.ColumnHistory, i: int):
    """One op's value straight off the columns/sidecar — no op dict."""
    ex = hist.extras.get(i)
    if ex is not None and "value" in ex:
        return ex["value"]
    c = hist.cols
    v = h.decode_register_value(None, int(c["value1"][i]), int(c["value2"][i]))
    if ex is not None and ex.get("value-tuple?") and isinstance(v, list):
        v = tuple(v)
    return v


class NodeColumns:
    """Transaction nodes as flat arrays (complete/invoke op index, ok
    mask, process codes) plus each node's raw txn value.  ``node_op``
    materializes an op dict lazily — witness/anomaly emission only."""

    __slots__ = ("hist", "pair", "complete", "invoke", "ok", "proc",
                 "values", "fail_idx", "_fail_vals")

    def __init__(self, history, pairs=None):
        self.hist = history
        if isinstance(history, h.ColumnHistory):
            self._from_columns(history, pairs)
        else:
            self._from_dicts(history, pairs)

    # -- construction -----------------------------------------------------

    def _from_columns(self, hist: h.ColumnHistory, pairs):
        cols = hist.cols
        n = len(cols["type"])
        type_c = cols["type"].astype(_I64, copy=False)
        proc_c = cols["process"].astype(_I64, copy=True)
        # client test must mirror the materialized view: ONLY the
        # NEMESIS_PID sentinel (-1) maps back to "nemesis"; any other
        # pid — negative ones included — materializes as an int client
        # (non-int processes ride extras overrides, handled below)
        client = proc_c != int(h.NEMESIS_PID)
        over_t = [i for i, ex in hist.extras.items() if "type" in ex]
        over_p = [i for i, ex in hist.extras.items() if "process" in ex]
        if over_t:
            type_c = type_c.copy()
            type_c[np.asarray(over_t, _I64)] = _CODE_OTHER
        if over_p:
            # non-int process overrides: never client, and each distinct
            # value gets a fresh code so pair matching can't merge them
            idx = np.asarray(over_p, _I64)
            client[idx] = False
            base = int(proc_c.max()) + 1 if n else 0
            codes: dict = {}
            for i in over_p:
                key = repr(hist.extras[i]["process"])
                proc_c[i] = base + codes.setdefault(key, len(codes))
        self._finish(type_c, proc_c, client, pairs,
                     lambda i: _column_value(hist, i))

    def _from_dicts(self, history, pairs):
        n = len(history)
        type_c = np.empty(n, _I64)
        proc_c = np.empty(n, _I64)
        client = np.empty(n, bool)
        vals: list = [None] * n
        codes: dict = {}
        tcodes = h.TYPE_CODES
        for i, o in enumerate(history):
            type_c[i] = tcodes.get(o["type"], _CODE_OTHER)
            p = o["process"]
            client[i] = isinstance(p, int)
            try:
                proc_c[i] = codes.setdefault(p, len(codes))
            except TypeError:  # unhashable process: its own group
                proc_c[i] = codes.setdefault(repr(p), len(codes))
            vals[i] = o.get("value")
        self._finish(type_c, proc_c, client, pairs, lambda i: vals[i])

    def _finish(self, type_c, proc_c, client, pairs, value_at):
        if pairs is not None:
            self.pair = np.asarray(pairs, _I64)
        else:
            self.pair = pair_index_codes(type_c, proc_c)
        sel = client & ((type_c == 1) | (type_c == 3))  # ok | info
        ci = np.flatnonzero(sel).astype(_I64)
        inv = self.pair[ci]
        self.complete = ci
        self.invoke = np.where(inv != -1, inv, ci)
        self.ok = type_c[ci] == 1
        self.proc = proc_c[ci]
        values = []
        for k in range(len(ci)):
            i = int(ci[k])
            v = value_at(i)
            if not self.ok[k] and v is None:
                j = int(self.pair[i])
                if j != -1:
                    v = value_at(j)
            values.append(v)
        self.values = values
        self.fail_idx = np.flatnonzero(client & (type_c == 2)).astype(_I64)
        self._fail_vals = None

    # -- lazy op access ---------------------------------------------------

    @property
    def n(self) -> int:
        return len(self.complete)

    def node_op(self, i: int) -> dict:
        ci = int(self.complete[i])
        op = self.hist[ci]
        if not self.ok[i] and op.get("value") is None:
            j = int(self.pair[ci])
            if j != -1:
                op = {**op, "value": self.hist[j].get("value")}
        return op

    def fail_values(self) -> list:
        """Each client fail op's value (for failed-write maps)."""
        if self._fail_vals is None:
            if isinstance(self.hist, h.ColumnHistory):
                self._fail_vals = [
                    _column_value(self.hist, int(i)) for i in self.fail_idx
                ]
            else:
                self._fail_vals = [
                    self.hist[int(i)].get("value") for i in self.fail_idx
                ]
        return self._fail_vals


class LazyNodes(Sequence):
    """``TxnGraph.nodes`` as a lazily-materializing sequence: node ``i``
    builds its :class:`txn_graph.TxnNode` (and its op dict) only when a
    witness/anomaly path touches it."""

    def __init__(self, nc: NodeColumns):
        self._nc = nc
        self._cache: dict[int, object] = {}

    def __len__(self) -> int:
        return self._nc.n

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(len(self)))]
        n = len(self)
        i = int(i)
        if i < 0:
            i += n
        if not 0 <= i < n:
            raise IndexError(i)
        nd = self._cache.get(i)
        if nd is None:
            from jepsen_tpu.checker.txn_graph import TxnNode

            nc = self._nc
            nd = TxnNode(
                id=i,
                op=nc.node_op(i),
                invoke_index=int(nc.invoke[i]),
                complete_index=int(nc.complete[i]),
                ok=bool(nc.ok[i]),
            )
            self._cache[i] = nd
        return nd


# ---------------------------------------------------------------------------
# Mop columns
# ---------------------------------------------------------------------------


class MopColumns:
    """Every micro-op of every node, flattened: ``(node, pos, key code,
    is_read, is_append)`` plus raw write values (ints/None enforced)."""

    __slots__ = ("node", "pos", "key", "isread", "isappend",
                 "w_rows", "w_raw", "key_objs", "n_keys")

    def __init__(self, nc: NodeColumns):
        m_node: list = []
        m_pos: list = []
        m_key: list = []
        m_isread: list = []
        m_isapp: list = []
        w_rows: list = []
        w_raw: list = []
        keys: dict = {}
        key_objs: list = []
        row = 0
        for i, v in enumerate(nc.values):
            for pos, mop in enumerate(v or ()):
                f, k = mop[0], mop[1]
                try:
                    kc = keys.get(k)
                except TypeError:
                    raise NotColumnizable("unhashable mop key")
                if kc is None:
                    kc = keys[k] = len(key_objs)
                    key_objs.append(k)
                m_node.append(i)
                m_pos.append(pos)
                m_key.append(kc)
                rd = f == "r"
                m_isread.append(rd)
                m_isapp.append(f == "append")
                if not rd:
                    w_rows.append(row)
                    w_raw.append(mop[2])
                row += 1
        self.node = np.asarray(m_node, _I64)
        self.pos = np.asarray(m_pos, _I64)
        self.key = np.asarray(m_key, _I64)
        self.isread = np.asarray(m_isread, bool)
        self.isappend = np.asarray(m_isapp, bool)
        self.w_rows = np.asarray(w_rows, _I64)
        self.w_raw = w_raw
        self.key_objs = key_objs
        self.n_keys = len(key_objs)

    def ext_read_rows(self) -> np.ndarray:
        """Mop rows that are EXTERNAL reads (first touch of their key in
        their txn; ``txn.ext_reads`` semantics), ascending row order."""
        if len(self.node) == 0:
            return np.zeros(0, _I64)
        order = np.lexsort((self.pos, self.key, self.node))
        first = np.ones(len(order), bool)
        first[1:] = ~(
            (self.node[order][1:] == self.node[order][:-1])
            & (self.key[order][1:] == self.key[order][:-1])
        )
        rows = order[first & self.isread[order]]
        rows.sort()
        return rows

    def repeat_read_nodes(self, ok: np.ndarray) -> np.ndarray:
        """Ok nodes with a read of an already-touched key — the only
        candidates for internal anomalies (superset; the host check
        decides).  Sorted ascending (the reference's node order)."""
        if len(self.node) == 0:
            return np.zeros(0, _I64)
        order = np.lexsort((self.pos, self.key, self.node))
        again = np.zeros(len(order), bool)
        again[1:] = (
            (self.node[order][1:] == self.node[order][:-1])
            & (self.key[order][1:] == self.key[order][:-1])
        )
        cand = np.unique(self.node[order[again & self.isread[order]]])
        return cand[ok[cand]]

    def consecutive_writes(self) -> tuple[np.ndarray, np.ndarray]:
        """(from_row, to_row) for in-txn consecutive writes to one key —
        ``_intermediate_writes`` rows: observing ``from``'s value
        without ``to``'s is G1b."""
        w = np.flatnonzero(~self.isread)
        if len(w) < 2:
            return np.zeros(0, _I64), np.zeros(0, _I64)
        order = np.lexsort((self.pos[w], self.key[w], self.node[w]))
        ws = w[order]
        adj = (self.node[ws][1:] == self.node[ws][:-1]) & (
            self.key[ws][1:] == self.key[ws][:-1]
        )
        return ws[:-1][adj], ws[1:][adj]


def _failed_write_rows(nc: NodeColumns, mc: MopColumns, fname: str):
    """(op index, key code, raw value) rows for client FAIL ops' write
    mops (``_failed_writes`` semantics).  Keys no node ever touched are
    dropped — no read can observe them, so they never match."""
    f_ops: list = []
    f_key: list = []
    f_raw: list = []
    key_index = {}
    for c, k in enumerate(mc.key_objs):
        try:
            key_index[k] = c
        except TypeError:
            raise NotColumnizable("unhashable mop key")
    for fi, fv in zip(nc.fail_idx, nc.fail_values()):
        for mop in fv or ():
            if mop[0] == fname:
                try:
                    code = key_index.get(mop[1], -1)
                except TypeError:
                    raise NotColumnizable("unhashable mop key")
                if code == -1:
                    continue
                f_ops.append(int(fi))
                f_key.append(code)
                f_raw.append(mop[2])
    return f_ops, f_key, f_raw


# ---------------------------------------------------------------------------
# Lazy per-edge explanations
# ---------------------------------------------------------------------------


class LazyExplanations(Mapping):
    """``TxnGraph.explanations`` backed by edge-id arrays: ``get((et, i,
    j))`` binary-searches the winner table for that edge type and renders
    the prose on demand — no per-edge closures, identical text to the
    loop reference's lambdas.  Payload columns are renderer-specific row
    indices into the builder's column state."""

    def __init__(self, n: int, nodes: LazyNodes):
        self._n = max(1, int(n))
        self._nodes = nodes
        #: et -> (sorted eid array, payload row arrays tuple, render fn)
        self._tables: dict[str, tuple] = {}

    def add_table(self, et: str, eids: np.ndarray, payload: tuple, render):
        order = np.argsort(eids, kind="stable")
        self._tables[et] = (
            eids[order], tuple(p[order] for p in payload), render,
        )

    def _find(self, key):
        if not (isinstance(key, tuple) and len(key) == 3):
            return None
        et, i, j = key
        tab = self._tables.get(et)
        if tab is None:
            return None
        eids, payload, render = tab
        q = int(i) * self._n + int(j)
        pos = int(np.searchsorted(eids, q))
        if pos >= len(eids) or int(eids[pos]) != q:
            return None
        return render(int(i), int(j), *(int(p[pos]) for p in payload))

    def get(self, key, default=None):
        v = self._find(key)
        return default if v is None else v

    def __getitem__(self, key):
        v = self._find(key)
        if v is None:
            raise KeyError(key)
        return v

    def __contains__(self, key):
        return self._find(key) is not None

    def __len__(self):
        return sum(len(t[0]) for t in self._tables.values())

    def __iter__(self):
        for et, (eids, _p, _r) in self._tables.items():
            for e in eids:
                yield (et, int(e) // self._n, int(e) % self._n)


def _keep_last(eids: np.ndarray) -> np.ndarray:
    """Indices of the LAST occurrence per edge id (the loop reference's
    dict assignment overwrites; occurrence order must already be the
    loop's iteration order)."""
    if len(eids) == 0:
        return np.zeros(0, _I64)
    order = np.argsort(eids, kind="stable")
    se = eids[order]
    last = np.ones(len(se), bool)
    last[:-1] = se[1:] != se[:-1]
    return order[last]


def _edge_pairs(eids: np.ndarray, n: int) -> np.ndarray:
    """Unique sorted (i, j) rows from edge ids — the ``np.argwhere``
    order, without scanning a dense matrix."""
    if len(eids) == 0:
        return np.zeros((0, 2), _I64)
    u = np.unique(eids)
    return np.stack([u // n, u % n], axis=1)


def _read_key_ranks(r_key: np.ndarray) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(unique key codes sorted, appearance rank per unique key, rank per
    read) — ``reads_by_key`` iterates keys in first-appearance order."""
    uk, ufirst = np.unique(r_key, return_index=True)
    rank = np.empty(len(uk), _I64)
    rank[np.argsort(ufirst, kind="stable")] = np.arange(len(uk), dtype=_I64)
    r_rank = rank[np.searchsorted(uk, r_key)]
    return uk, rank, r_rank


# ---------------------------------------------------------------------------
# list-append inference
# ---------------------------------------------------------------------------


def list_append_graph_columns(history, additional_graphs=(), pairs=None):
    """Vectorized ``txn_graph.list_append_graph`` — identical nodes,
    edges, anomalies, and explanation prose (differential-tested against
    the loop reference)."""
    from jepsen_tpu.checker import txn_graph as tg

    with obs.span("elle.nodes", workload="list-append"):
        nc = NodeColumns(history, pairs)
        n = nc.n
        nodes = LazyNodes(nc)
        mc = MopColumns(nc)
        ok = nc.ok

        # external reads of OK nodes, with their element lists flattened
        er = mc.ext_read_rows()
        er = er[ok[mc.node[er]]]
        r_node = mc.node[er]
        r_key = mc.key[er]
        r_list: list = []
        flat: list = []
        for row in er:
            v = nc.values[int(mc.node[row])][int(mc.pos[row])][2]
            lst = list(v or [])
            r_list.append(lst)
            flat.extend(lst)
        r_len = np.asarray([len(x) for x in r_list], _I64)
        r_off = np.concatenate(([0], np.cumsum(r_len)))[:-1] if len(r_list) \
            else np.zeros(0, _I64)

        pool = _ValuePool(mc.n_keys)
        e_val, _ = pool.add(_int_array(flat))
        w_val, w_none = pool.add(*_vals_with_none(mc.w_raw))
        # failed client writes ((key, value) -> op; "append" mops only)
        f_ops, f_key, f_raw = _failed_write_rows(nc, mc, fname="append")
        f_val, f_none = pool.add(*_vals_with_none(f_raw))
        pool.finalize()
        f_key_arr = np.asarray(f_key, _I64)
        f_packed = pool.pack(f_key_arr, f_val) if len(f_val) else \
            np.zeros(0, _I64)
        failed = _PackedMap(f_packed, keep="last")

    anomalies: dict[str, list] = {}

    def add_anom(name, item):
        anomalies.setdefault(name, []).append(item)

    with obs.span("elle.anomalies", workload="list-append"):
        # -- internal (ok nodes that re-read a touched key; host check)
        cand = mc.repeat_read_nodes(ok)
        for i in cand:
            for a in tg._internal_anomalies_append(nodes[int(i)]):
                add_anom("internal", a)

        # -- appender map + duplicate appends (all nodes, scan order)
        ap = np.flatnonzero(mc.isappend)
        ap_in_w = np.searchsorted(mc.w_rows, ap)  # appends ⊆ writes
        ap_packed = pool.pack(mc.key[ap], w_val[ap_in_w])
        appender = _PackedMap(ap_packed, keep="first")
        ap_node = mc.node[ap]

        def _appender_node(rows):
            """appender row -> node id (-1 when absent)."""
            if len(ap_node) == 0:
                return np.full(len(rows), -1, _I64)
            return np.where(rows >= 0, ap_node[np.maximum(rows, 0)], _I64(-1))

        for d in appender.dup_rows:
            row = int(ap[d])
            first = int(ap_node[appender.lookup(ap_packed[d : d + 1])[0]])
            mop = nc.values[int(mc.node[row])][int(mc.pos[row])]
            add_anom(
                "duplicate-elements",
                {"key": mop[1], "element": mop[2],
                 "ops": [nodes[first].op, nodes[int(mc.node[row])].op]},
            )

        # -- intermediate writes (non-final in-txn writes; last-wins map)
        iw_from, iw_to = mc.consecutive_writes()
        iw_packed = pool.pack(
            mc.key[iw_from], w_val[np.searchsorted(mc.w_rows, iw_from)]
        )
        inter = _PackedMap(iw_packed, keep="last")
        iw_node = mc.node[iw_from]
        iw_next = w_val[np.searchsorted(mc.w_rows, iw_to)]

        # -- G1a / G1b over read contents (flat element occurrences)
        E = len(e_val)
        if E:
            e_read = np.repeat(np.arange(len(r_list), dtype=_I64), r_len)
            e_pos = np.arange(E, dtype=_I64) - r_off[e_read]
            e_packed = pool.pack(r_key[e_read], e_val)
            uk, k_rank, r_rank = _read_key_ranks(r_key)

            g1a = failed.lookup(e_packed)
            g1a_idx = np.flatnonzero(g1a >= 0)
            if len(g1a_idx):
                order = np.lexsort(
                    (e_pos[g1a_idx], e_read[g1a_idx],
                     r_rank[e_read[g1a_idx]])
                )
                for x in g1a_idx[order]:
                    ri = int(e_read[x])
                    fr = int(g1a[x])
                    add_anom(
                        "G1a",
                        {"op": nodes[int(r_node[ri])].op,
                         "key": mc.key_objs[int(r_key[ri])],
                         "element": r_list[ri][int(e_pos[x])],
                         "writer": nc.hist[int(f_ops[fr])]},
                    )

            g1b = inter.lookup(e_packed)
            hit = np.flatnonzero(g1b >= 0)
            if len(hit):
                has_next = e_pos[hit] + 1 < r_len[e_read[hit]]
                nxt = np.where(
                    has_next, e_val[np.minimum(hit + 1, E - 1)], _I64(0)
                )
                want = iw_next[g1b[hit]]
                flag = hit[~(has_next & (nxt == want))]
                order = np.lexsort(
                    (e_pos[flag], e_read[flag], r_rank[e_read[flag]])
                )
                for x in flag[order]:
                    ri = int(e_read[x])
                    add_anom(
                        "G1b",
                        {"op": nodes[int(r_node[ri])].op,
                         "key": mc.key_objs[int(r_key[ri])],
                         "element": r_list[ri][int(e_pos[x])],
                         "writer": nodes[int(iw_node[g1b[x]])].op},
                    )
        else:
            e_read = np.zeros(0, _I64)
            e_pos = np.zeros(0, _I64)
            uk, k_rank, r_rank = _read_key_ranks(r_key)

    ww = np.zeros((n, n), dtype=bool)
    wr = np.zeros((n, n), dtype=bool)
    rw = np.zeros((n, n), dtype=bool)
    expl = LazyExplanations(n, nodes)
    edge_out: dict[str, np.ndarray] = {}
    NK = len(uk)

    with obs.span("elle.edges", workload="list-append"):
        # -- version order per key: the longest read wins; prefix check
        if NK:
            korder = np.lexsort((np.arange(len(r_key)), -r_len, r_rank))
            kfirst = np.ones(len(korder), bool)
            kfirst[1:] = r_rank[korder][1:] != r_rank[korder][:-1]
            longest_ri = np.empty(NK, _I64)
            longest_ri[r_rank[korder[kfirst]]] = korder[kfirst]
            key_off = r_off[longest_ri]
            key_len = r_len[longest_ri]
            kcode_by_rank = np.empty(NK, _I64)
            kcode_by_rank[k_rank] = uk

            if len(e_val):
                lpos = key_off[r_rank[e_read]] + e_pos
                mismatch = e_val != e_val[lpos]
                bad_reads = np.unique(e_read[mismatch])
            else:
                bad_reads = np.zeros(0, _I64)
            bad_key = np.zeros(NK, bool)
            bad_key[r_rank[bad_reads]] = True
            if len(bad_reads):
                order = np.argsort(r_rank[bad_reads], kind="stable")
                for ri in bad_reads[order]:
                    ri = int(ri)
                    add_anom(
                        "incompatible-order",
                        {"key": mc.key_objs[int(r_key[ri])],
                         "read": r_list[ri],
                         "longest": r_list[int(longest_ri[r_rank[ri]])],
                         "op": nodes[int(r_node[ri])].op},
                    )
            good = np.flatnonzero(~bad_key)  # ascending key rank

            # -- ww: consecutive observed appends in each version order
            pair_cnt = np.maximum(key_len[good] - 1, 0)
            pa = _ranges(key_off[good], pair_cnt)
            occ_rank = np.repeat(good, pair_cnt)
            na = _appender_node(
                appender.lookup(pool.pack(kcode_by_rank[occ_rank], e_val[pa]))
            )
            nb = _appender_node(
                appender.lookup(
                    pool.pack(kcode_by_rank[occ_rank], e_val[pa + 1])
                )
            )
            ok_pair = (na >= 0) & (nb >= 0) & (na != nb)
            na, nb = na[ok_pair], nb[ok_pair]
            occ_rank_ww = occ_rank[ok_pair]
            occ_pos = (pa - key_off[occ_rank])[ok_pair]
            ww[na, nb] = True
            ww_eid = na * n + nb
            win = _keep_last(ww_eid)
            expl.add_table(
                "ww", ww_eid[win], (occ_rank_ww[win], occ_pos[win]),
                _render_ww_append(nodes, mc.key_objs, kcode_by_rank,
                                  r_list, longest_ri),
            )
            edge_out["ww"] = _edge_pairs(ww_eid, n)

            # -- wr / rw per read of a good key
            rr = np.flatnonzero(~bad_key[r_rank])  # reads of good keys
            rr = rr[np.argsort(r_rank[rr], kind="stable")]  # key-major
            nz = rr[r_len[rr] > 0]
            last = e_val[r_off[nz] + r_len[nz] - 1]
            wn = _appender_node(
                appender.lookup(pool.pack(r_key[nz], last))
            )
            okw = (wn >= 0) & (wn != r_node[nz])
            wr_i, wr_j = wn[okw], r_node[nz][okw]
            wr[wr_i, wr_j] = True
            wr_eid = wr_i * n + wr_j
            win = _keep_last(wr_eid)
            expl.add_table(
                "wr", wr_eid[win], (nz[okw][win],),
                _render_wr_append(nodes, mc.key_objs, r_key, r_list),
            )
            edge_out["wr"] = _edge_pairs(wr_eid, n)

            beyond = rr[r_len[rr] < key_len[r_rank[rr]]]
            nv = e_val[key_off[r_rank[beyond]] + r_len[beyond]]
            nx = _appender_node(
                appender.lookup(pool.pack(r_key[beyond], nv))
            )
            okr = (nx >= 0) & (nx != r_node[beyond])
            rw_i, rw_j = r_node[beyond][okr], nx[okr]
            rw[rw_i, rw_j] = True
            rw_eid = rw_i * n + rw_j
            win = _keep_last(rw_eid)
            expl.add_table(
                "rw", rw_eid[win], (beyond[okr][win],),
                _render_rw_append(nodes, mc.key_objs, r_key, r_list,
                                  longest_ri, r_rank),
            )
            edge_out["rw"] = _edge_pairs(rw_eid, n)
        else:
            for et in ("ww", "wr", "rw"):
                edge_out[et] = np.zeros((0, 2), _I64)

        extra = _extra_columns(nc, additional_graphs, n)
        edge_out["extra"] = (
            np.argwhere(extra) if extra.any() else np.zeros((0, 2), _I64)
        )

    return tg.TxnGraph(
        nodes=nodes, ww=ww, wr=wr, rw=rw, extra=extra,
        explanations=expl, anomalies=anomalies, edges=edge_out,
    )


def _extra_columns(nc: NodeColumns, additional_graphs, n: int) -> np.ndarray:
    extra = np.zeros((n, n), dtype=bool)
    for g in additional_graphs:
        if g == "realtime":
            comp = np.where(nc.ok, nc.complete, _I64_MAX)
            extra |= comp[:, None] < nc.invoke[None, :]
        elif g == "process":
            if n:
                order = np.lexsort((nc.invoke, nc.proc))
                same = nc.proc[order][1:] == nc.proc[order][:-1]
                extra[order[:-1][same], order[1:][same]] = True
        else:
            raise ValueError(f"unknown additional graph {g!r}")
    return extra


# -- explanation renderers (prose byte-identical to the loop lambdas) -------


def _tname(nodes, i: int) -> str:
    nd = nodes[i]
    return f"T{nd.op.get('index', nd.id)}"


def _render_ww_append(nodes, key_objs, kcode_by_rank, r_list, longest_ri):
    def render(i, j, rank, pos):
        k = key_objs[int(kcode_by_rank[rank])]
        order = r_list[int(longest_ri[rank])]
        a, b = order[pos], order[pos + 1]
        return (
            f"{_tname(nodes, i)} appended {a!r} to {k!r} ([:append {k!r} {a!r}]) "
            f"and {_tname(nodes, j)} appended {b!r} immediately after it in "
            f"{k!r}'s version order {order!r}"
        )

    return render


def _render_wr_append(nodes, key_objs, r_key, r_list):
    def render(i, j, ri):
        k = key_objs[int(r_key[ri])]
        lst = r_list[ri]
        return (
            f"{_tname(nodes, j)}'s read of {k!r} ([:r {k!r} {lst!r}]) observed "
            f"{lst[-1]!r} as its final element, which {_tname(nodes, i)} "
            f"appended ([:append {k!r} {lst[-1]!r}])"
        )

    return render


def _render_rw_append(nodes, key_objs, r_key, r_list, longest_ri, r_rank):
    def render(i, j, ri):
        k = key_objs[int(r_key[ri])]
        lst = r_list[ri]
        order = r_list[int(longest_ri[int(r_rank[ri])])]
        nv = order[len(lst)]
        return (
            f"{_tname(nodes, i)}'s read of {k!r} ([:r {k!r} {lst!r}]) did not "
            f"observe {nv!r}, which {_tname(nodes, j)} appended next "
            f"in the version order ([:append {k!r} {nv!r}])"
        )

    return render


# ---------------------------------------------------------------------------
# rw-register inference
# ---------------------------------------------------------------------------


def rw_register_graph_columns(history, additional_graphs=(),
                              sequential_keys=False, linearizable_keys=False,
                              pairs=None):
    """Vectorized ``txn_graph.rw_register_graph`` (same differential
    contract as the list-append engine)."""
    from jepsen_tpu.checker import txn_graph as tg

    with obs.span("elle.nodes", workload="rw-register"):
        nc = NodeColumns(history, pairs)
        n = nc.n
        nodes = LazyNodes(nc)
        mc = MopColumns(nc)
        ok = nc.ok

        # external reads (ok nodes): scalar values
        er = mc.ext_read_rows()
        er = er[ok[mc.node[er]]]
        r_node = mc.node[er]
        r_key = mc.key[er]
        r_raw = [nc.values[int(mc.node[x])][int(mc.pos[x])][2] for x in er]

        pool = _ValuePool(mc.n_keys)
        r_val, r_none = pool.add(*_vals_with_none(r_raw))
        w_val, _w_none = pool.add(*_vals_with_none(mc.w_raw))
        f_ops, f_key, f_raw = _failed_write_rows(nc, mc, fname="w")
        f_val, _f_none = pool.add(*_vals_with_none(f_raw))
        pool.finalize()
        failed = _PackedMap(
            pool.pack(np.asarray(f_key, _I64), f_val) if len(f_val)
            else np.zeros(0, _I64),
            keep="last",
        )

    anomalies: dict[str, list] = {}

    def add_anom(name, item):
        anomalies.setdefault(name, []).append(item)

    ww = np.zeros((n, n), dtype=bool)
    wr = np.zeros((n, n), dtype=bool)
    rw = np.zeros((n, n), dtype=bool)
    expl = LazyExplanations(n, nodes)
    edge_out: dict[str, np.ndarray] = {
        et: np.zeros((0, 2), _I64) for et in ("ww", "wr", "rw")
    }

    with obs.span("elle.anomalies", workload="rw-register"):
        # -- internal
        for i in mc.repeat_read_nodes(ok):
            for a in tg._internal_anomalies_wr(nodes[int(i)]):
                add_anom("internal", a)

        # -- writer map (final external writes) + duplicate-writes.
        # ext_writes insertion order = (node, FIRST write pos of key);
        # its value = the LAST write.
        w = mc.w_rows
        ew_first = np.zeros(0, _I64)
        ew_last = np.zeros(0, _I64)
        if len(w):
            order = np.lexsort((mc.pos[w], mc.key[w], mc.node[w]))
            ws = w[order]
            first = np.ones(len(ws), bool)
            first[1:] = ~(
                (mc.node[ws][1:] == mc.node[ws][:-1])
                & (mc.key[ws][1:] == mc.key[ws][:-1])
            )
            last = np.ones(len(ws), bool)
            last[:-1] = first[1:]
            ef, el = ws[first], ws[last]
            ins = np.argsort(ef, kind="stable")  # (node, first-pos) order
            ew_first, ew_last = ef[ins], el[ins]
        ew_key = mc.key[ew_first] if len(ew_first) else np.zeros(0, _I64)
        ew_node = mc.node[ew_first] if len(ew_first) else np.zeros(0, _I64)
        ew_val = (
            w_val[np.searchsorted(w, ew_last)] if len(ew_last)
            else np.zeros(0, _I64)
        )
        ew_val_obj = [mc.w_raw[int(np.searchsorted(w, x))] for x in ew_last]
        ew_packed = pool.pack(ew_key, ew_val)
        writer = _PackedMap(ew_packed, keep="first")
        for d in writer.dup_rows:
            d = int(d)
            firstrow = int(writer.lookup(ew_packed[d : d + 1])[0])
            add_anom(
                "duplicate-writes",
                {"key": mc.key_objs[int(ew_key[d])], "value": ew_val_obj[d],
                 "ops": [nodes[int(ew_node[firstrow])].op,
                         nodes[int(ew_node[d])].op]},
            )

        # -- intermediate writes (non-final in-txn writes; last wins)
        iw_from, _iw_to = mc.consecutive_writes()
        inter = _PackedMap(
            pool.pack(mc.key[iw_from], w_val[np.searchsorted(w, iw_from)])
            if len(iw_from) else np.zeros(0, _I64),
            keep="last",
        )
        iw_node = mc.node[iw_from] if len(iw_from) else np.zeros(0, _I64)

        # -- per-read G1a / G1b / wr (global read order; None skipped).
        # r_packed covers ALL reads (None rides the sentinel code) so the
        # version-order pass can look nil reads up too.
        live = np.flatnonzero(~r_none)
        r_packed = (
            pool.pack(r_key, r_val) if len(r_val) else np.zeros(0, _I64)
        )
        g1a = failed.lookup(r_packed[live]) if len(live) else np.zeros(0, _I64)
        g1b = inter.lookup(r_packed[live]) if len(live) else np.zeros(0, _I64)
        wrow = writer.lookup(r_packed[live]) if len(live) else np.zeros(0, _I64)
        # anomalies are rare — loop only over the hits (read order; a
        # G1a read emits no G1b and, below, no wr edge)
        for x in np.flatnonzero((g1a >= 0) | (g1b >= 0)):
            ri = int(live[x])
            if g1a[x] >= 0:
                add_anom(
                    "G1a",
                    {"op": nodes[int(r_node[ri])].op,
                     "key": mc.key_objs[int(r_key[ri])],
                     "value": r_raw[ri],
                     "writer": nc.hist[int(f_ops[int(g1a[x])])]},
                )
            else:
                add_anom(
                    "G1b",
                    {"op": nodes[int(r_node[ri])].op,
                     "key": mc.key_objs[int(r_key[ri])],
                     "value": r_raw[ri],
                     "writer": nodes[int(iw_node[int(g1b[x])])].op},
                )
        # wr edges, fully vectorized: a live read whose value has a
        # final writer other than itself — unless G1a aborted it
        if len(live) and len(ew_node):
            wn_nodes = ew_node[np.maximum(wrow, 0)]
            ok_wr = (g1a < 0) & (wrow >= 0) & (wn_nodes != r_node[live])
            sel = np.flatnonzero(ok_wr)  # ascending = global read order
            wi = wn_nodes[sel]
            wj = r_node[live[sel]]
            wri = live[sel]
        else:
            wi = wj = wri = np.zeros(0, _I64)
        wr[wi, wj] = True
        wr_eid = wi * n + wj
        win = _keep_last(wr_eid)
        expl.add_table(
            "wr", wr_eid[win], (wri[win],),
            _render_wr_register(nodes, mc.key_objs, r_key, r_raw),
        )
        edge_out["wr"] = _edge_pairs(wr_eid, n)

    with obs.span("elle.edges", workload="rw-register"):
        if (sequential_keys or linearizable_keys) and len(ew_first):
            if (ew_val == pool.none_code).any():
                # A FINAL None write makes the reference's version order
                # contain None twice ([None] prefix + the written nil),
                # with dict-overwrite semantics on pos_of — a corner the
                # loop reference handles exactly; route it there.
                raise NotColumnizable(
                    "nil final write under per-key version orders"
                )
            sort_key = (
                nc.complete[ew_node] if linearizable_keys
                else nc.invoke[ew_node]
            )
            kept = np.sort(writer.rows)  # writer-map insertion order
            kk = ew_key[kept]
            uk, ufirst = np.unique(kk, return_index=True)
            krank_of = np.empty(len(uk), _I64)
            krank_of[np.argsort(ufirst, kind="stable")] = np.arange(
                len(uk), dtype=_I64
            )
            kranks = krank_of[np.searchsorted(uk, kk)]
            # key-major, sort_key-minor, insertion-stable
            order = np.lexsort(
                (np.arange(len(kept)), sort_key[kept], kranks)
            )
            srows = kept[order]
            sranks = kranks[order]
            NKw = len(uk)
            cnt = np.bincount(sranks, minlength=NKw).astype(_I64)
            off = np.concatenate(([0], np.cumsum(cnt)))[:-1]

            # ww: consecutive writes in each key's version order
            pair_cnt = np.maximum(cnt - 1, 0)
            pa = _ranges(off, pair_cnt)
            na = ew_node[srows[pa]]
            nb = ew_node[srows[pa + 1]]
            okp = na != nb
            na, nb, pa_ok = na[okp], nb[okp], pa[okp]
            ww[na, nb] = True
            ww_eid = na * n + nb
            win = _keep_last(ww_eid)
            expl.add_table(
                "ww", ww_eid[win], (pa_ok[win],),
                _render_ww_register(nodes, mc.key_objs, ew_key, ew_val_obj,
                                    srows),
            )
            edge_out["ww"] = _edge_pairs(ww_eid, n)

            # rw: each read (None included) against its key's order
            in_keys = np.searchsorted(uk, r_key)
            in_keys_ok = (in_keys < len(uk))
            if len(r_key):
                in_keys_ok &= uk[np.minimum(in_keys, len(uk) - 1)] == r_key
            rd = np.flatnonzero(in_keys_ok)
            rd_rank = krank_of[in_keys[rd]] if len(rd) else np.zeros(0, _I64)
            # position in [None] + values: None -> 0; else writer row pos
            srow_pos = np.empty(len(ew_first), _I64)
            srow_pos[srows] = np.arange(len(srows), dtype=_I64)
            wrow_rd = writer.lookup(r_packed[rd]) if len(rd) else \
                np.zeros(0, _I64)
            p = np.full(len(rd), -1, _I64)
            p[r_none[rd]] = 0
            hitw = np.flatnonzero(wrow_rd >= 0)
            if len(hitw):
                p[hitw] = srow_pos[wrow_rd[hitw]] - off[rd_rank[hitw]] + 1
            valid = (p >= 0) & (p < cnt[rd_rank])
            rd, p, rd_rank = rd[valid], p[valid], rd_rank[valid]
            # iterate keys in by_key order, reads in global order per key
            order = np.lexsort((rd, rd_rank))
            rd, p, rd_rank = rd[order], p[order], rd_rank[order]
            nxrow = srows[off[rd_rank] + p]
            nx = ew_node[nxrow]
            okr = nx != r_node[rd]
            rw_i = r_node[rd[okr]]
            rw_j = nx[okr]
            rw[rw_i, rw_j] = True
            rw_eid = rw_i * n + rw_j
            win = _keep_last(rw_eid)
            expl.add_table(
                "rw", rw_eid[win], (rd[okr][win], nxrow[okr][win]),
                _render_rw_register(nodes, mc.key_objs, r_key, r_raw,
                                    ew_val_obj),
            )
            edge_out["rw"] = _edge_pairs(rw_eid, n)

        extra = _extra_columns(nc, additional_graphs, n)
        edge_out["extra"] = (
            np.argwhere(extra) if extra.any() else np.zeros((0, 2), _I64)
        )

    return tg.TxnGraph(
        nodes=nodes, ww=ww, wr=wr, rw=rw, extra=extra,
        explanations=expl, anomalies=anomalies, edges=edge_out,
    )


def _render_wr_register(nodes, key_objs, r_key, r_raw):
    def render(i, j, ri):
        k = key_objs[int(r_key[ri])]
        v = r_raw[ri]
        return (
            f"{_tname(nodes, j)}'s read of {k!r} ([:r {k!r} {v!r}]) observed the "
            f"value {_tname(nodes, i)} wrote ([:w {k!r} {v!r}])"
        )

    return render


def _render_ww_register(nodes, key_objs, ew_key, ew_val_obj, srows):
    def render(i, j, pa):
        ra, rb = int(srows[pa]), int(srows[pa + 1])
        k = key_objs[int(ew_key[ra])]
        a, b = ew_val_obj[ra], ew_val_obj[rb]
        return (
            f"{_tname(nodes, i)} wrote {k!r} = {a!r} ([:w {k!r} {a!r}]) and "
            f"{_tname(nodes, j)} overwrote it with {b!r} ([:w {k!r} {b!r}]) "
            f"in {k!r}'s version order"
        )

    return render


def _render_rw_register(nodes, key_objs, r_key, r_raw, ew_val_obj):
    def render(i, j, ri, nxrow):
        k = key_objs[int(r_key[ri])]
        v = r_raw[ri]
        nv = ew_val_obj[int(nxrow)]
        return (
            f"{_tname(nodes, i)}'s read of {k!r} ([:r {k!r} {v!r}]) did "
            f"not observe {nv!r}, which {_tname(nodes, j)} "
            f"wrote next in the version order "
            f"([:w {k!r} {nv!r}])"
        )

    return render
