"""Timeline checker: a static HTML gantt of operations per process.

Mirrors ``jepsen.checker.timeline`` (reference:
jepsen/src/jepsen/checker/timeline.clj): pairs invocations with their
completions (timeline.clj:38), renders one column per process with
color-coded op bars, and caps rendering at 10,000 ops so massive histories
stay usable (timeline.clj:12-14).  Output goes to ``timeline.html`` in the
checker's subdirectory; the result map is always valid.
"""

from __future__ import annotations

import html as html_mod
from pathlib import Path
from typing import Mapping, Sequence

from jepsen_tpu import history as h
from jepsen_tpu import store
from jepsen_tpu.checker import Checker

#: timeline.clj:12-14
OP_LIMIT = 10_000

TYPE_COLORS = {"ok": "#B3F3B5", "info": "#F2F3B3", "fail": "#F3B3B3"}


def _pairs(history: Sequence[Mapping]):
    pair = h.pair_index(history)
    out = []
    for i, o in enumerate(history):
        if h.is_invoke(o):
            j = int(pair[i])
            out.append((o, history[j] if j != -1 else None))
    return out


def render_html(test: Mapping, history: Sequence[Mapping]) -> str:
    history = list(history)[: 2 * OP_LIMIT]
    pairs = _pairs(history)[:OP_LIMIT]
    procs = sorted(
        {str(o["process"]) for o, _ in pairs}, key=lambda p: (p == "nemesis", p)
    )
    if not pairs:
        return "<html><body>empty history</body></html>"
    t0 = min(o.get("time", 0) for o, _ in pairs)
    t1 = max(
        (c or o).get("time", 0) for o, c in pairs
    )
    span = max(1, t1 - t0)
    height = 800
    col_w = 130

    def y_of(t):
        return 40 + (t - t0) / span * (height - 60)

    bars = []
    for o, c in pairs:
        x = 10 + procs.index(str(o["process"])) * col_w
        y0 = y_of(o.get("time", t0))
        y1 = y_of((c or o).get("time", t1 if c is None else 0)) if c else height - 20
        typ = c["type"] if c else "info"
        color = TYPE_COLORS.get(typ, "#ddd")
        label = f"{o.get('f')} {o.get('value')!r} → {typ}" + (
            f" {c.get('value')!r}" if c and c.get("value") is not None else ""
        )
        bars.append(
            f"<div class='op' title='{html_mod.escape(label)}' "
            f"style='left:{x}px;top:{y0:.1f}px;height:{max(3, y1 - y0):.1f}px;"
            f"width:{col_w - 10}px;background:{color}'>"
            f"{html_mod.escape(str(o.get('f')))}</div>"
        )
    heads = "".join(
        f"<div class='head' style='left:{10 + i * col_w}px'>process {html_mod.escape(p)}</div>"
        for i, p in enumerate(procs)
    )
    return (
        "<html><head><style>"
        "body{font-family:sans-serif;position:relative}"
        ".head{position:absolute;top:10px;font-weight:bold}"
        ".op{position:absolute;font-size:9px;overflow:hidden;"
        "border:1px solid #999;border-radius:2px;padding:1px}"
        "</style></head><body>"
        f"{heads}{''.join(bars)}"
        f"<div style='position:absolute;top:{height}px'>&nbsp;</div>"
        "</body></html>"
    )


class Timeline(Checker):
    def check(self, test, history, opts):
        out = {"valid?": True}
        doc = render_html(test, [o for o in history if o.get("process") != h.NEMESIS or True])
        try:
            d = store.test_dir(test)
            sub = opts.get("subdirectory") if opts else None
            d = d / sub if sub else d
            d.mkdir(parents=True, exist_ok=True)
            (Path(d) / "timeline.html").write_text(doc)
            out["file"] = str(Path(d) / "timeline.html")
        except (KeyError, OSError, TypeError):
            # No store dir configured (e.g. bare checker unit tests): return
            # the html inline instead.
            out["html"] = doc
        return out


def timeline_checker() -> Checker:
    return Timeline()
