"""Elle-equivalent transactional consistency checkers.

The reference delegates transactional anomaly detection to the external
`elle 0.1.3` library through thin adapters (jepsen/src/jepsen/tests/cycle/
append.clj, wr.clj).  This module is the native rebuild: dependency-graph
inference happens host-side (jepsen_tpu.checker.txn_graph — the
vectorized column-native engine by default, with the loop reference as
fallback/oracle; see txn_columns.py), cycle
classification routes to the measured-fastest backend (CYCLE_BACKEND —
host sparse SCC by default after the round-5 chip measurements; batched
boolean matrix powering on the TPU MXU via jepsen_tpu.ops.closure as the
explicit opt-in and the multi-chip mesh-sharded path), and witness cycles
for explanations are recovered by BFS over the host adjacency.

Result shape follows elle's: ``{"valid?": bool, "anomaly-types": [...],
"anomalies": {type: [explanation, ...]}, "not": [models ruled out],
"also-not": [stronger models implied ruled out]}``.  The anomaly vocabulary
is the reference's documented set (tests/cycle/wr.clj:30-46): G0, G1a, G1b,
G1c, G-single, G2, internal — plus list-append's duplicate-elements and
incompatible-order.
"""

from __future__ import annotations

from typing import Any, Mapping, Sequence

import numpy as np

from jepsen_tpu import obs, store
from jepsen_tpu.checker import Checker
from jepsen_tpu.checker import txn_graph as tg
from jepsen_tpu.obs import provenance as _prov
from jepsen_tpu.ops import closure as cl

# ---------------------------------------------------------------------------
# Consistency-model hierarchy (elle.consistency-model's lattice, rebuilt
# from the Adya / Cerone model relationships it encodes)
# ---------------------------------------------------------------------------

#: anomaly → weakest consistency models it rules out.  Our G2 evidence is
#: item anti-dependency cycles (G2-item), which Adya's PL-2.99 already
#: proscribes — so it rules out repeatable-read, and serializable /
#: strict-serializable follow through the lattice.
ANOMALY_RULES_OUT = {
    "G0": ["read-uncommitted"],
    "duplicate-elements": ["read-uncommitted"],
    "duplicate-writes": ["read-uncommitted"],
    "incompatible-order": ["read-uncommitted"],
    "G1a": ["read-committed"],
    "G1b": ["read-committed"],
    "G1c": ["read-committed"],
    "internal": ["read-atomic"],
    "G-single": ["consistent-view", "snapshot-isolation"],
    "G2": ["repeatable-read", "serializable"],
}

#: DIRECT weaker→stronger edges; STRONGER_MODELS below is the transitive
#: closure (computed, so adding a model can't silently break the
#: closure).  Chains follow Adya's PL hierarchy (thesis Fig. 4-3) on
#: one side — read-committed → {cursor-stability, monotonic-view};
#: PL-2L → PL-MSR / PL-CV → PL-FCV → PL-SI; PL-FCV → PL-3U
#: (update-serializable) → PL-3 — and the atomic-snapshot family on the
#: other (monotonic-atomic-view → read-atomic → causal →
#: parallel-snapshot-isolation → snapshot-isolation), meeting at
#: serializable; session-strengthened variants (Daudjee & Salem)
#: interpose between the snapshot/serializable levels and
#: strict-serializable at the top.
_STRONGER_DIRECT = {
    # Daudjee & Salem session ladders exist at every isolation level
    # ("Lazy Database Replication with Ordering Guarantees" for SI,
    # "Maintaining Transaction Isolation Guarantees ..." for RC): the
    # strong-session-X / strong-X variants add per-session then global
    # real-time ordering to X, and the ladders are pointwise ordered
    # (X <= Y implies strong-session-X <= strong-session-Y etc.).
    "read-uncommitted": ["read-committed", "strong-session-read-uncommitted"],
    "strong-session-read-uncommitted": [
        "strong-read-uncommitted", "strong-session-read-committed",
    ],
    "strong-read-uncommitted": ["strong-read-committed"],
    "read-committed": [
        "cursor-stability", "monotonic-atomic-view", "monotonic-view",
        "strong-session-read-committed",
    ],
    "strong-session-read-committed": [
        "strong-read-committed", "strong-session-snapshot-isolation",
    ],
    "strong-read-committed": ["strong-snapshot-isolation"],
    "cursor-stability": ["repeatable-read"],
    # Adya PL-2L: reads observe a monotonically growing prefix of commits
    "monotonic-view": ["monotonic-snapshot-read", "consistent-view"],
    # Adya PL-MSR: reads are snapshots that advance monotonically
    "monotonic-snapshot-read": ["snapshot-isolation"],
    "monotonic-atomic-view": ["read-atomic", "repeatable-read"],
    # Adya PL-CV → PL-FCV → PL-SI
    "consistent-view": ["forward-consistent-view"],
    "forward-consistent-view": ["snapshot-isolation", "update-serializable"],
    # Adya PL-3U: serializable with respect to update transactions
    "update-serializable": ["serializable"],
    "read-atomic": ["causal"],
    # Cerone et al.'s atomic-visibility chain (A Framework for
    # Transactional Consistency Models with Atomic Visibility): RA ⊂
    # causal ⊂ {prefix, PSI} ⊂ SI — prefix and PSI are incomparable
    # siblings between causal and snapshot-isolation
    "causal": ["parallel-snapshot-isolation", "prefix"],
    "prefix": ["snapshot-isolation"],
    "parallel-snapshot-isolation": ["snapshot-isolation"],
    "repeatable-read": ["serializable"],
    # PL-SI sits below PL-3 in Adya's proscribed-phenomena ordering, and
    # below its own session-strengthened ladder (Daudjee & Salem:
    # per-session real-time order, then global real-time order)
    "snapshot-isolation": ["serializable", "strong-session-snapshot-isolation"],
    "strong-session-snapshot-isolation": [
        "strong-snapshot-isolation", "strong-session-serializable",
    ],
    "strong-snapshot-isolation": ["strict-serializable"],
    "serializable": ["strong-session-serializable"],
    "strong-session-serializable": ["strict-serializable"],
    "strict-serializable": [],
}


def _transitive_closure(direct: Mapping) -> dict:
    out: dict[str, list] = {}
    for start in direct:
        seen: set[str] = set()
        stack = list(direct[start])
        while stack:
            x = stack.pop()
            if x in seen:
                continue
            seen.add(x)
            stack.extend(direct.get(x, ()))
        out[start] = sorted(seen)
    return out


#: model → strictly stronger models (transitively closed) — ruling out a
#: model also rules these out.
STRONGER_MODELS = _transitive_closure(_STRONGER_DIRECT)

#: Which anomalies each requested headline anomaly expands to
#: (tests/cycle/wr.clj:43-46: "G2 implies G-single and G1c; G1 implies G1a,
#: G1b, and G1c; G1c implies G0").
ANOMALY_EXPANSION = {
    "G2": ["G2", "G-single", "G1c", "G0"],
    "G-single": ["G-single", "G1c", "G0"],
    "G1": ["G1a", "G1b", "G1c", "G0"],
    "G1c": ["G1c", "G0"],
}


def expand_anomalies(requested: Sequence[str]) -> set[str]:
    out: set[str] = set()
    for a in requested:
        out.update(ANOMALY_EXPANSION.get(a, [a]))
    return out


def models_ruled_out(anomaly_types: Sequence[str]) -> tuple[list, list]:
    """(not, also-not): weakest models ruled out, and the stronger models
    those imply are ruled out too."""
    out: set[str] = set()
    for a in anomaly_types:
        out.update(ANOMALY_RULES_OUT.get(a, []))
    # Keep only the weakest: drop any model implied by another in the set.
    implied: set[str] = set()
    for m in out:
        implied.update(STRONGER_MODELS[m])
    weakest = sorted(out - implied)
    also = sorted((implied | out) - set(weakest))
    return weakest, also


# ---------------------------------------------------------------------------
# Witness-cycle recovery (host-side, from the device-computed closure)
# ---------------------------------------------------------------------------


def _shortest_path(adj: np.ndarray, src: int, dst: int) -> list[int] | None:
    """BFS shortest path src→dst over a bool adjacency matrix."""
    n = adj.shape[0]
    if src == dst:
        return [src]
    prev = np.full(n, -1, dtype=np.int64)
    frontier = [src]
    seen = {src}
    while frontier:
        nxt = []
        for u in frontier:
            for v in np.flatnonzero(adj[u]):
                v = int(v)
                if v not in seen:
                    seen.add(v)
                    prev[v] = u
                    if v == dst:
                        path = [dst]
                        while path[-1] != src:
                            path.append(int(prev[path[-1]]))
                        return path[::-1]
                    nxt.append(v)
        frontier = nxt
    return None


def _find_cycle_through_edge(
    graph_adj: np.ndarray, a: int, b: int, edge_adj: np.ndarray | None = None
) -> list[int] | None:
    """A cycle using edge a→b: b→a path (over ``graph_adj``) + the edge.

    The hinted edge must exist host-side in ``edge_adj`` (default: the
    path graph; G-single/G2 pass the rw matrix since their edge is not in
    the return-path graph) — a stale device hint must surface as
    unwitnessed, never as a fabricated cycle."""
    if not (edge_adj if edge_adj is not None else graph_adj)[a, b]:
        return None
    back = _shortest_path(graph_adj, b, a)
    if back is None:
        return None
    return [a] + back


def _edge_type(g: tg.TxnGraph, i: int, j: int) -> str:
    if g.ww[i, j]:
        return "ww"
    if g.wr[i, j]:
        return "wr"
    if g.rw[i, j]:
        return "rw"
    return "rt"


def _explain_cycle(g: tg.TxnGraph, cycle: list[int]) -> dict:
    """Render a node cycle into an elle-style explanation."""
    if len(cycle) > 1 and cycle[0] == cycle[-1]:
        # recovery paths come back closed ([a, …, a]); the step zip
        # re-closes the cycle itself, so drop the duplicate endpoint
        cycle = cycle[:-1]
    steps = []
    for i, j in zip(cycle, cycle[1:] + [cycle[0]]):
        et = _edge_type(g, i, j)
        steps.append(
            {
                "type": et,
                "from": g.nodes[i].op,
                "to": g.nodes[j].op,
                "explanation": g.explain(et, i, j),
            }
        )
    return {"cycle": [g.nodes[i].op for i in cycle], "steps": steps}


def _diag_cycle_at(adj_parts: np.ndarray, v: int) -> list[int] | None:
    """A cycle through node v (the device flagged closure[v, v]), or None
    when the host adjacency has no such cycle — a stale/mismatched hint
    must surface as unwitnessed, not as a fabricated witness."""
    if adj_parts[v, v]:
        return [v]
    for u in np.flatnonzero(adj_parts[v]):
        c = _find_cycle_through_edge(adj_parts, v, int(u))
        if c is not None:
            return c
    return None


# ---------------------------------------------------------------------------
# Checkers
# ---------------------------------------------------------------------------


def _merge_flags(g: tg.TxnGraph, flags: dict, hints: dict, requested) -> dict:
    """Merge device cycle flags+hints with inference anomalies into an
    elle-style result, recovering witness cycles by host BFS over the
    (sparse, host-resident) adjacency — nothing O(n²) crosses the device
    boundary."""
    wanted = expand_anomalies(requested)
    anomalies: dict[str, list] = {k: v for k, v in g.anomalies.items() if k in wanted}
    # A device flag asserts a cycle exists; host BFS recovers the witness.
    # If recovery fails (stale/empty hint, adjacency mismatch), the flag
    # must still surface — never a clean True over a flagged graph.
    unwitnessed: list[str] = []
    # The dense unions below are only for witness BFS: on a 10k-node
    # graph each one is a 100M-entry boolean scan, so a clean (unflagged)
    # graph must never pay for them.
    if g.n and any(flags[nm] for nm in ("G0", "G1c", "G-single", "G2")):
        any_adj = g.ww | g.wr | g.extra
        full_adj = any_adj | g.rw
        if flags["G0"] and "G0" in wanted:
            cyc = _diag_cycle_at(g.ww | g.extra, hints["G0"][0]) if hints["G0"] else None
            if cyc:
                anomalies.setdefault("G0", []).append(_explain_cycle(g, cyc))
            else:
                unwitnessed.append("G0")
        for name, graph_adj, edge_adj, gate in (
            ("G1c", any_adj, any_adj, True),
            ("G-single", any_adj, g.rw, True),
            ("G2", full_adj, g.rw, not flags["G-single"]),
        ):
            if flags[name] and gate and name in wanted:
                cyc = (
                    _find_cycle_through_edge(graph_adj, *hints[name], edge_adj=edge_adj)
                    if hints[name]
                    else None
                )
                if cyc:
                    anomalies.setdefault(name, []).append(_explain_cycle(g, cyc))
                else:
                    unwitnessed.append(name)

    types = sorted(anomalies)
    not_, also_not = models_ruled_out(types)
    out: dict[str, Any] = {"valid?": not anomalies}
    if anomalies:
        out.update(
            {
                "anomaly-types": types,
                "anomalies": anomalies,
                "not": not_,
                "also-not": also_not,
            }
        )
    if unwitnessed:
        out["unwitnessed-flags"] = sorted(set(unwitnessed))
        if not anomalies:
            out["valid?"] = "unknown"
            out["cause"] = (
                "device flagged cycle(s) "
                f"({', '.join(out['unwitnessed-flags'])}) but witness "
                "recovery found no cycle — flag and host graph disagree"
            )
    return out


#: Above this many nodes a graph NEVER classifies on the dense MXU
#: closure (O(n³ log n) vs Tarjan's O(V+E); measured r03: 10k-node dense
#: closure ~34 s vs Tarjan ~0.5 s) — even under ``backend="device"``.
SCC_THRESHOLD = 1024

#: Default cycle-classification backend.  Round-5 chip-day measurement
#: (tools/ crossover sweep, PERF.md "Elle"): host SCC wins at EVERY
#: single-chip shape, batched or not — 1024×48-txn graphs 0.96 s host
#: vs 3.4 s device, 64×700-txn 1.2 s vs 10.5 s — sparse O(V+E) with no
#: tunnel round-trips beats the dense closure throughout, so the
#: competition routes to the host by default.  The device kernels
#: remain as an explicit backend ("device") and as the mesh-sharded
#: closure path for giant graphs across a multi-chip mesh
#: (ops/closure.transitive_closure_sharded, dryrun-validated).
CYCLE_BACKEND = "host"


def _device_classify(n: int, backend: str | None) -> bool:
    b = backend or CYCLE_BACKEND
    if b not in ("host", "device"):
        raise ValueError(f"unknown cycle backend {b!r}; expected 'host' or 'device'")
    return b == "device" and n <= SCC_THRESHOLD


def check_graph(
    g: tg.TxnGraph, requested: Sequence[str], backend: str | None = None
) -> dict:
    """Classify cycles + merge inference anomalies into an elle-style
    result.  Backend picked by measurement, the way the reference's
    competition checker picks algorithms (checker.clj:199-203); see
    CYCLE_BACKEND."""
    if not g.n:
        return _merge_flags(g, dict(cl._EMPTY_FLAGS), dict(cl._EMPTY_HINTS), requested)
    if _device_classify(g.n, backend):
        with obs.span("elle.scc", nodes=g.n, backend="device"):
            flags, hints = cl.classify_graph(g.ww, g.wr, g.rw, g.extra)
    else:
        from jepsen_tpu.checker.scc import classify_graph_scc

        # the sparse edge view skips argwhere over the dense matrices
        # (the measured bulk of classification at 10k nodes)
        with obs.span("elle.scc", nodes=g.n, backend="host"):
            flags, hints = classify_graph_scc(
                g.ww, g.wr, g.rw, g.extra, edges=g.edge_arrays()
            )
    return _merge_flags(g, flags, hints, requested)


def check_graphs(
    graphs: Sequence[tg.TxnGraph],
    requested: Sequence[str],
    backend: str | None = None,
) -> list[dict]:
    """Classify MANY graphs (the per-key scale-out path).  Default
    backend is the host SCC loop (measured fastest at every single-chip
    shape — see CYCLE_BACKEND); ``backend="device"`` runs the bucketed
    vmapped MXU closures (ops.closure.classify_graphs) instead."""
    results: list = [None] * len(graphs)
    dev_idx = [i for i, g in enumerate(graphs) if _device_classify(g.n, backend)]
    if dev_idx:
        # routed per graph: an oversized graph (> SCC_THRESHOLD) goes
        # host without cancelling the device opt-in for the others
        dev_out = cl.classify_graphs(
            [(graphs[i].ww, graphs[i].wr, graphs[i].rw, graphs[i].extra)
             for i in dev_idx]
        )
        for i, r in zip(dev_idx, dev_out):
            results[i] = r
    if len(dev_idx) < len(graphs):
        from jepsen_tpu.checker.scc import classify_graph_scc

        with obs.span(
            "elle.scc", graphs=len(graphs) - len(dev_idx), backend="host"
        ):
            for i, g in enumerate(graphs):
                if results[i] is None:
                    results[i] = classify_graph_scc(
                        g.ww, g.wr, g.rw, g.extra, edges=g.edge_arrays()
                    )
    return [
        _merge_flags(g, flags, hints, requested)
        for g, (flags, hints) in zip(graphs, results)
    ]


# ---------------------------------------------------------------------------
# elle/ output directory (anomaly explanation files)
# ---------------------------------------------------------------------------


def _render_op(op: Mapping) -> str:
    return (
        f"{{:index {op.get('index')}, :process {op.get('process')}, "
        f":type :{op.get('type')}, :f :{op.get('f')}, :value {op.get('value')!r}}}"
    )


def render_anomaly(name: str, item) -> str:
    """One anomaly instance as elle-style prose (elle writes files like
    elle/G1c.txt with 'Let's consider the following transaction cycle'
    sections; SURVEY.md §2.3)."""
    if isinstance(item, Mapping) and "cycle" in item:
        lines = ["Let's consider the following transaction cycle:", ""]
        for op in item["cycle"]:
            lines.append("  " + _render_op(op))
        lines.append("  (and back to the start)")
        lines.append("")
        lines.append("Each step in the cycle:")
        for s in item.get("steps", ()):
            lines.append(f"  - [{s['type']}] {s['explanation']}")
        return "\n".join(lines)
    if isinstance(item, Mapping):
        lines = []
        for k, v in item.items():
            if isinstance(v, Mapping) and "type" in v and "f" in v:
                lines.append(f"  :{k} {_render_op(v)}")
            elif (
                isinstance(v, Sequence)
                and not isinstance(v, (str, bytes))
                and v
                and all(isinstance(x, Mapping) and "type" in x for x in v)
            ):
                lines.append(f"  :{k}")
                lines.extend(f"    {_render_op(x)}" for x in v)
            else:
                lines.append(f"  :{k} {v!r}")
        return "\n".join(lines)
    return f"  {item!r}"


def write_anomaly_dir(test, result: Mapping, opts=None, dirname: str = "elle"):
    """Write one explanation file per anomaly type under the test's store
    directory (the reference's elle output dir: elle emits anomaly
    explanations into ``elle/``, served alongside the other artifacts by
    jepsen.web).  Returns the directory, or None when no store is
    configured or the result is clean."""
    anomalies = result.get("anomalies")
    if not anomalies:
        return None
    try:
        d = store.test_dir(test)
    except (KeyError, TypeError):
        return None  # bare unit-test maps have no store coordinates
    sub = (opts or {}).get("subdirectory")
    if sub:
        d = d / sub
    d = d / dirname
    d.mkdir(parents=True, exist_ok=True)
    for name, items in anomalies.items():
        n = len(items)
        chunks = [f"{n} {name} anomal{'y' if n == 1 else 'ies'}"]
        for i, item in enumerate(items, 1):
            chunks.append(f"--- {name} #{i} ---\n{render_anomaly(name, item)}")
        (d / f"{name}.txt").write_text("\n\n".join(chunks) + "\n", encoding="utf-8")
    return d


DEFAULT_ANOMALIES = ["G2", "G1a", "G1b", "internal"]  # tests/cycle/wr.clj:46


class _ElleChecker(Checker):
    """Shared artifact plumbing for the elle-style checkers."""

    #: Graph workloads have no padded-kernel geometry to share, so the
    #: check service must never pack them into a geometry bucket —
    #: admission routes them to the host side lane instead of letting
    #: them stall packable ladder work (ROADMAP item 4: elle got no
    #: cross-request batching by accident; this makes it explicit).
    geometry_batchable = False

    def batch_key(self) -> tuple:
        """Column-shape compatibility key for the serve graph lane (the
        graph analogue of ``parallel.batch.bucket_geometry``): queued
        requests whose checkers share this key are served by ONE
        ``check_batch`` call — one batched inference pass plus one
        host-SCC sweep — instead of per-request checks."""
        return (type(self).__name__,)

    def write_artifacts(self, test, result, opts=None):
        """Render the elle/ anomaly-explanation directory for a stored
        run (called per key by independent.checker on the batch path)."""
        try:
            write_anomaly_dir(test, result, opts)
        except OSError:
            pass

    def _prov_engine(self) -> dict:
        """The engine/backend resolution an evidence bundle records:
        which inference engine actually ran (the instance's pin, or the
        env/default resolution) and the cycle-detection backend."""
        eng = getattr(self, "engine", None)
        if eng is None:
            try:
                eng = tg.resolve_engine(None)
            except ValueError:
                eng = None
        return {
            "engine": "elle", "graph_engine": eng,
            "cycle_backend": getattr(self, "backend", None) or CYCLE_BACKEND,
        }

    def _emit_evidence(self, test, history, res, opts, *,
                       workload: str, source: str = "check") -> None:
        _prov.attach(
            res, [{"event": "elle.check", "workload": workload}],
            engine=self._prov_engine(),
        )
        _prov.emit(test, history, res, source=source,
                   checker=f"elle-{workload}", opts=opts)


class ListAppendChecker(_ElleChecker):
    """Native elle.list-append equivalent (tests/cycle/append.clj:11-22).

    Options:
      anomalies          headline anomalies to report (default catches all)
      additional_graphs  iterable of "realtime" / "process"
      engine             inference engine ("columns"/"loops"; None defers
                         to txn_graph.resolve_engine — vectorized columns
                         by default, loop reference on fallback)
    """

    def __init__(
        self,
        anomalies: Sequence[str] = DEFAULT_ANOMALIES,
        additional_graphs: Sequence[str] = (),
        engine: str | None = None,
    ):
        self.anomalies = list(anomalies) + [
            "duplicate-elements",
            "incompatible-order",
        ]
        self.additional_graphs = tuple(additional_graphs)
        self.engine = engine

    def batch_key(self) -> tuple:
        return (
            type(self).__name__, tuple(self.anomalies),
            self.additional_graphs, self.engine,
        )

    def check(self, test, history, opts):
        g = tg.list_append_graph(
            history, self.additional_graphs, engine=self.engine
        )
        res = check_graph(g, self.anomalies)
        self.write_artifacts(test, res, opts)
        self._emit_evidence(test, history, res, opts, workload="list-append")
        return res

    def check_batch(self, test, histories, opts):
        """Check many histories through the shared batched inference
        pass (one engine resolution + one span; used by
        independent.checker per key and by the CheckService's graph
        lane) followed by one classification sweep."""
        graphs = tg.list_append_graphs(
            histories, self.additional_graphs, engine=self.engine
        )
        outs = check_graphs(graphs, self.anomalies)
        for hh, res in zip(histories, outs):
            self._emit_evidence(test, hh, res, opts,
                                workload="list-append", source="check_batch")
        return outs


class WRRegisterChecker(_ElleChecker):
    """Native elle.rw-register equivalent (tests/cycle/wr.clj:15-46)."""

    def __init__(
        self,
        anomalies: Sequence[str] = DEFAULT_ANOMALIES,
        additional_graphs: Sequence[str] = (),
        sequential_keys: bool = False,
        linearizable_keys: bool = False,
        engine: str | None = None,
    ):
        self.anomalies = list(anomalies) + ["duplicate-writes"]
        self.additional_graphs = tuple(additional_graphs)
        self.sequential_keys = sequential_keys
        self.linearizable_keys = linearizable_keys
        self.engine = engine

    def batch_key(self) -> tuple:
        return (
            type(self).__name__, tuple(self.anomalies),
            self.additional_graphs, self.sequential_keys,
            self.linearizable_keys, self.engine,
        )

    def _graph(self, history):
        return tg.rw_register_graph(
            history,
            self.additional_graphs,
            sequential_keys=self.sequential_keys,
            linearizable_keys=self.linearizable_keys,
            engine=self.engine,
        )

    def check(self, test, history, opts):
        res = check_graph(self._graph(history), self.anomalies)
        self.write_artifacts(test, res, opts)
        self._emit_evidence(test, history, res, opts, workload="wr-register")
        return res

    def check_batch(self, test, histories, opts):
        """Batched per-key form (see ListAppendChecker.check_batch)."""
        graphs = tg.rw_register_graphs(
            histories, self.additional_graphs,
            sequential_keys=self.sequential_keys,
            linearizable_keys=self.linearizable_keys, engine=self.engine,
        )
        outs = check_graphs(graphs, self.anomalies)
        for hh, res in zip(histories, outs):
            self._emit_evidence(test, hh, res, opts,
                                workload="wr-register", source="check_batch")
        return outs


class CycleChecker(_ElleChecker):
    """Cycle detection over an ARBITRARY user relation graph — the
    reference's generic adapter (jepsen/src/jepsen/tests/cycle.clj:10-16,
    reifying a Checker over elle.core/check with a custom analyzer).

    ``analyzer(history)`` returns ``(nodes, relations, explainer)``:

      nodes      list of op dicts (one graph node per entry)
      relations  one of: a ``{name: [n, n] bool ndarray}`` mapping (the
                 scalable form — a 50k-op realtime relation is one
                 vectorized comparison, never a Python edge list), a bare
                 [n, n] ndarray, or an iterable of ``(i, j, name)``
                 tuples for small graphs
      explainer  ``fn(i, j, relation) -> str`` prose for one edge (may be
                 None for a generic rendering)

    Any cycle in the combined relation graph is an anomaly (reported
    under ``"cycle"`` with a recovered witness).  Detection routes like
    the typed checkers: host Tarjan by default (the measured winner at
    every single-chip shape — see CYCLE_BACKEND), the dense MXU closure
    when the device backend is opted in for graphs ≤ SCC_THRESHOLD.

    ``backend`` pins this checker instance's routing ("host"|"device"),
    matching the per-call ``backend`` on check_graph/check_graphs —
    per-instance opt-in without mutating the CYCLE_BACKEND module
    global.  None (the default) defers to CYCLE_BACKEND.
    """

    def __init__(self, analyzer, backend: str | None = None):
        if backend is not None and backend not in ("host", "device"):
            raise ValueError(
                f"unknown cycle backend {backend!r}; expected 'host' or 'device'"
            )
        self.analyzer = analyzer
        self.backend = backend

    def batch_key(self) -> tuple:
        # instances share a serve-lane batch only when they share the
        # SAME analyzer object — its output shape is the compatibility
        # contract, and there is no cheaper identity for a callable
        return (type(self).__name__, id(self.analyzer), self.backend)

    def check(self, test, history, opts):
        res = self._check_one(history)
        self.write_artifacts(test, res, opts)
        self._emit_evidence(test, history, res, opts, workload="cycle")
        return res

    def check_batch(self, test, histories, opts):
        """Shared batched sweep for the serve graph lane: one span, one
        classification loop — instead of the per-request list
        comprehension that rebuilt everything unbatched."""
        with obs.span(
            "elle.infer_batch", histories=len(histories), workload="cycle",
        ):
            outs = [self._check_one(hh) for hh in histories]
        for hh, res in zip(histories, outs):
            self._emit_evidence(test, hh, res, opts,
                                workload="cycle", source="check_batch")
        return outs

    def _check_one(self, history):
        nodes, relations, explainer = self.analyzer(history)
        n = len(nodes)
        adj = np.zeros((n, n), dtype=bool)
        if isinstance(relations, np.ndarray):
            relations = {"rel": relations}
        if isinstance(relations, Mapping):
            for mat in relations.values():
                adj |= np.asarray(mat, dtype=bool)

            def rel_of(a: int, b: int):
                for name, mat in relations.items():
                    if mat[a, b]:
                        return name
                return None
        else:
            rels: dict[tuple[int, int], Any] = {}
            for i, j, r in relations:
                adj[i, j] = True
                rels.setdefault((int(i), int(j)), r)

            def rel_of(a: int, b: int):
                return rels.get((a, b))
        flagged, cycle = self._find_cycle(adj, n, self.backend)
        if not flagged:
            res: dict[str, Any] = {"valid?": True}
        elif cycle is None:
            # never a clean True over a flagged graph (same invariant as
            # _merge_flags): flag and host witness recovery disagree
            res = {
                "valid?": "unknown",
                "unwitnessed-flags": ["cycle"],
                "cause": (
                    "device flagged a cycle but witness recovery found "
                    "none — flag and host graph disagree"
                ),
            }
        else:
            steps = []
            for a, b in zip(cycle, cycle[1:] + [cycle[0]]):
                r = rel_of(a, b)
                prose = None
                if explainer is not None:
                    prose = explainer(a, b, r)
                steps.append(
                    {
                        "type": r,
                        "from": nodes[a],
                        "to": nodes[b],
                        "explanation": prose or f"{r}: node {a} precedes node {b}",
                    }
                )
            res = {
                "valid?": False,
                "anomaly-types": ["cycle"],
                "anomalies": {
                    "cycle": [{"cycle": [nodes[i] for i in cycle], "steps": steps}]
                },
            }
        return res

    @staticmethod
    def _find_cycle(
        adj: np.ndarray, n: int, backend: str | None = None
    ) -> tuple[bool, list[int] | None]:
        """(cycle-flagged, witness-cycle-or-None); the witness node list
        is unclosed.  ``backend`` routes like check_graph's."""
        if n == 0:
            return False, None
        if _device_classify(n, backend):
            zeros = np.zeros_like(adj)
            flags, hints = cl.classify_graph(adj, zeros, zeros, zeros)
            if not flags["G0"]:
                return False, None
            cyc = _diag_cycle_at(adj, hints["G0"][0]) if hints["G0"] else None
        else:
            from jepsen_tpu.checker.scc import _first_edge_in_cycle, tarjan_scc

            edges = np.argwhere(adj)
            comp = tarjan_scc(n, [list(np.flatnonzero(adj[v])) for v in range(n)])
            hit = _first_edge_in_cycle(edges, comp)
            if hit is None:
                return False, None
            cyc = _find_cycle_through_edge(adj, hit[0], hit[1])
        if cyc and len(cyc) > 1 and cyc[0] == cyc[-1]:
            cyc = cyc[:-1]
        return True, cyc


def realtime_analyzer(history):
    """Built-in analyzer: realtime precedence between completed client
    ops (elle.core's realtime graph vocabulary) — op A precedes op B
    when A's completion lands before B's invocation.  One vectorized
    comparison (the same dense form txn_graph.realtime_edges uses), not
    a Python edge list."""
    from jepsen_tpu import history as h

    pairs = h.pair_index(history)
    nodes = []
    inv_pos, comp_pos = [], []
    for i, o in enumerate(history):
        if h.is_invoke(o) and h.is_client_op(o):
            j = int(pairs[i])
            if j != -1 and history[j]["type"] == h.OK:
                nodes.append(history[j])
                inv_pos.append(i)
                comp_pos.append(j)
    inv = np.array(inv_pos, dtype=np.int64)
    comp = np.array(comp_pos, dtype=np.int64)
    adj = comp[:, None] < inv[None, :]

    def explain(a, b, _r):
        return (
            f"op {nodes[a].get('index')} completed before "
            f"op {nodes[b].get('index')} was invoked"
        )

    return nodes, {"realtime": adj}, explain


def cycle_checker(analyzer, backend: str | None = None) -> Checker:
    """The reference's ``jepsen.tests.cycle/checker`` entry point."""
    return CycleChecker(analyzer, backend=backend)


def list_append(**kw) -> Checker:
    return ListAppendChecker(**kw)


def wr_register(**kw) -> Checker:
    return WRRegisterChecker(**kw)
