"""Streaming online checking: verdicts while the test runs.

Every other checker in the package is post-hoc — the history completes,
then the ladder starts, so a linearizability violation committed in
second 3 of a ten-minute run is only reported after the run ends.  The
:class:`StreamingChecker` here consumes an op stream INCREMENTALLY:
each arriving invoke/complete epoch extends the barrier schedule and
advances a carried frontier through the same compiled chunk kernels the
post-hoc chunked path uses (``ops.wgl.scan_barrier_range`` — same
``Bc`` padding rule, same capacity-escalation ladder, same dedup
backends), emitting a verdict THE MOMENT the frontier dies (refuted) or
a constructive witness completes (valid), with an honest
``unknown``-so-far status in between.  Check latency is thereby
measured from the *offending op*, not from end-of-run.

Settlement — why online verdicts equal post-hoc ones
----------------------------------------------------

Mid-stream, an invoke with no completion yet is *pending*: the final
history may complete it ok (it joins the barrier schedule) or never
(it becomes a crashed/info group member).  ``wgl_cpu.prepare`` on the
current prefix necessarily classifies pending ops as crashed — wrong
whenever they later complete ok.  The checker therefore only advances
the frontier through SETTLED barriers: with ``u`` the history position
of the first pending invoke (∞ if none), every event at a position
below ``u`` is final (ok/fail/info classifications never change, and
every pending op's invoke sits at a position ≥ ``u`` by minimality), so
the barrier-table prefix below ``u`` is bit-identical to the one the
eventual full-history pack will build.  Three invariants make the
carried frontier reusable across epochs without rescanning:

* **Barriers are append-only.**  Barriers are ok-returns in position
  order; new completions only append events past every existing one,
  so the settled prefix only grows.
* **Process slots are prefix-stable.**  ``pack`` assigns slots by first
  ok-completing invoke in position order.  A pending op that later
  resolves ok can only add a first-appearance at a position ≥ ``u`` —
  never ahead of any appearance below ``u`` — and the frontier's fok
  bitsets only ever cover ops OPEN at the settled cut, whose invokes
  (hence slots) all sit below ``u``.  Carried fok words therefore need
  no permutation, only zero-padding as the slot-word count ``W``
  grows.
* **Crashed-group columns remap by key.**  The group vocabulary is
  re-derived per epoch (a resolved pending op deletes its provisional
  group; fresh info ops add groups), so carried fired-crashed counts
  are permuted onto the new vocabulary by their ``(f_code, v1, v2)``
  key.  A dropped group's column is provably all-zero — the kernel
  fires crashed ops only against ``grp_open`` counts of settled
  barriers, which count only truly-info ops — and the remap verifies
  that; if the invariant is ever violated the checker falls back to a
  full rescan from barrier 0 (``stream.rescan``), trading latency for
  verdict identity, never correctness.

A frontier death at a settled barrier is FINAL: the killed prefix is a
prefix of the eventual history, and linearizability is prefix-closed,
so the stream is refuted no matter what arrives later (no confirmation
sweep needed on the exact engine — kills are content-decided).  A
``valid`` verdict exists only at :meth:`~StreamingChecker.finalize`,
when every op is classified and the frontier survived the whole
schedule.  Loss (capacity truncation) latches exactly as in
``chunked_analysis``: once lossy, a death degrades to ``unknown``.

Durability: with ``checkpoint_dir`` every accepted epoch persists the
op stream + cursor + carried frontier through the
``store.checkpoint``/``store.durable`` envelope pair
(``stream-checkpoint.json`` + ``.npz``), so a SIGKILL'd stream resumes
mid-history — :func:`StreamingChecker.resume` — and reproduces
verdicts identical to an uninterrupted run (chaos-gated in
``tools/chaos_check.py --stream``).

Telemetry rides the ``stream.*`` family (per-epoch ``stream.epoch``
spans, the terminal ``stream.verdict``); every decision-path entry this
engine records is likewise ``stream.``-prefixed so evidence parity can
strip them (:func:`parity_digest`).
"""

from __future__ import annotations

import logging
import math
import time
import uuid
from typing import Mapping, Sequence

import numpy as np

from jepsen_tpu import history as h
from jepsen_tpu import models as m
from jepsen_tpu import obs
from jepsen_tpu.checker import UNKNOWN
from jepsen_tpu.obs import metrics as _metrics
from jepsen_tpu.obs import provenance as _prov
from jepsen_tpu.ops import wgl
from jepsen_tpu.ops.hashing import resolve_dedup_backend

logger = logging.getLogger(__name__)

#: decision-path event prefixes with no post-hoc counterpart: the
#: streaming engine's own trajectory and the serving layer's stream
#: admissions.  :func:`parity_digest` strips both.
_ADMISSION_PREFIXES = ("stream.", "serve.")


def parity_digest(bundle: Mapping) -> str:
    """The cross-engine evidence digest the differential suite compares.

    Returns the bundle's stability-core digest with the stream/serve
    admission events stripped from the decision path and the
    engine-trajectory sections (remaining path entries, ``engine``,
    ``config``) zeroed: those record HOW a verdict was produced and
    legitimately differ between the streaming epoch scan and the
    post-hoc ladder (the loadgen evidence-parity check zeroes
    ``config`` between its arms for the same reason).  What survives —
    history fingerprint, verdict, cause, model, checker, and the
    constructive witness — must be bit-identical between a streamed
    and a post-hoc check of the same history.
    """
    b = dict(bundle)
    # strip admission events; what that leaves is engine trajectory
    # (ladder rungs vs epoch scans) — engine-dependent by construction,
    # so it is zeroed along with `engine` and `config`
    b["decision_path"] = []
    b["engine"] = {}
    b["config"] = {}
    return _prov.bundle_digest(b)


def _remap_fcr(
    fcr: np.ndarray,
    old_keys: Sequence[tuple],
    new_keys: Sequence[tuple],
    G_new: int,
) -> tuple[np.ndarray, bool]:
    """Permute carried fired-crashed-count columns onto a new group
    vocabulary by ``(f_code, v1, v2)`` key; new groups zero-fill.
    Returns ``(remapped, violated)`` — ``violated`` means a DROPPED
    group's column held a nonzero count, which the settlement invariant
    rules out; the caller must rescan from barrier 0."""
    out = np.zeros((fcr.shape[0], G_new), np.int16)
    new_idx = {k: i for i, k in enumerate(new_keys)}
    violated = False
    for j, key in enumerate(old_keys):
        if j >= fcr.shape[1]:
            break
        col = fcr[:, j]
        i = new_idx.get(key)
        if i is None:
            violated |= bool(np.any(col))
            continue
        out[:, i] = col
    return out, violated


class StreamingChecker:
    """Incremental linearizability checker over an op stream.

    ``feed(ops)`` appends arriving invoke/complete ops and advances the
    carried frontier through every newly SETTLED barrier; it returns the
    stream's status doc (``valid?`` stays ``"unknown"`` until a verdict
    exists).  ``finalize()`` classifies any still-pending invokes as
    crashed (exactly what the post-hoc checker does to a stored history)
    and returns the knossos-shaped result.  Once a verdict is emitted
    the stream is TERMINAL: further feeds are accepted but change
    nothing (the verdict stands — refutation is prefix-closed).

    Scan parameters mirror ``ops.wgl.analysis``: ``capacity`` is the
    per-chunk escalation ladder, ``rounds`` the closure depth,
    ``dedup_backend`` the per-round dedup backend (sort/bucket/pallas —
    resolved exactly as post-hoc), ``spill`` slices an overflowing
    carried frontier through the kernel instead of truncating.  The
    checker compiles no kernel geometry the post-hoc chunked path
    wouldn't: epoch scans reuse the same jitted chunk kernel.

    NOTE the cost model: each epoch re-packs the FULL current prefix
    (O(n) host work per epoch — the scan itself only pays the new
    barriers).  Feed in batches; the serving layer's NDJSON ingestion
    does.
    """

    def __init__(
        self,
        model: m.Model,
        *,
        capacity: int | Sequence[int] = (64, 256),
        rounds: int = 8,
        chunk_barriers: int = 512,
        fast: bool = False,
        dedup_backend: str | None = None,
        spill: bool = False,
        max_groups: int = 64,
        max_procs: int = 128,
        checkpoint_dir=None,
        stream_id: str | None = None,
        checker: str = "linearizable",
    ):
        self.model = model
        self.caps = (
            [int(capacity)] if isinstance(capacity, int)
            else [int(c) for c in capacity]
        )
        self.rounds = int(rounds)
        self.chunk_barriers = int(chunk_barriers)
        self.fast = bool(fast)
        self.dedup = resolve_dedup_backend(dedup_backend)
        self.spill = bool(spill)
        self.max_groups = int(max_groups)
        self.max_procs = int(max_procs)
        self.checkpoint_dir = checkpoint_dir
        self.stream_id = stream_id or uuid.uuid4().hex[:16]
        self.checker_name = checker

        self._history: list[dict] = []
        self._frontier: tuple | None = None  # (state, fok, fcr) host arrays
        self._gkeys: list[tuple] = []  # fcr column keys (f_code, v1, v2)
        self._advanced = 0  # settled barriers the frontier has passed
        self._pending = 0
        self._cap_idx = 0
        self._lossy = False
        self._verified = 0
        self._launches = 0
        self._peak = 1
        self._epochs = 0
        self._rescans = 0
        self._result: dict | None = None
        self._detect: dict | None = None
        self._traj: list[dict] = []
        self._finalized = False
        self._t0 = time.perf_counter()

    # ------------------------------------------------------------------
    # Public surface
    # ------------------------------------------------------------------

    @property
    def terminal(self) -> bool:
        """A verdict exists (possibly before the stream ends)."""
        return self._result is not None

    @property
    def result(self) -> dict | None:
        return self._result

    @property
    def ops_consumed(self) -> int:
        """Ops accepted so far — a resuming feeder continues from here."""
        return len(self._history)

    @property
    def epochs(self) -> int:
        """Feed epochs processed so far."""
        return self._epochs

    @property
    def rescans(self) -> int:
        """Full from-barrier-0 rescans forced by a settlement-invariant
        violation (``stream.rescan``)."""
        return self._rescans

    @property
    def frontier_rows(self) -> int:
        """Rows in the carried frontier right now (0 before the first
        settled barrier)."""
        if self._frontier is None:
            return 0
        return int(self._frontier[0].shape[0])

    @property
    def detection(self) -> dict | None:
        """Violation-detection metadata when a verdict fired mid-stream:
        ops seen at detection, the killed barrier/op position, and the
        wall-clock latency from the offending epoch's arrival."""
        return dict(self._detect) if self._detect else None

    def feed(self, ops: Sequence[Mapping]) -> dict:
        """Append arriving ops (one epoch) and advance through every
        newly settled barrier.  Returns :meth:`status`.  Never raises on
        checker trouble — an undecidable stream degrades to a terminal
        ``unknown`` with a ``cause``, like every other engine."""
        ops = [dict(o) for o in ops]
        if self._result is not None:
            # Terminal latch: refutation is prefix-closed and a valid
            # finalize already consumed the whole stream — late ops are
            # recorded for the status doc but never change the verdict.
            self._history.extend(ops)
            return self.status()
        if ops:
            self._history.extend(ops)
            obs.counter("stream.ops", len(ops), stream=self.stream_id)
            self._advance(final=False)
            self._save_ck()
        return self.status()

    def finalize(self) -> dict:
        """End of stream: classify still-pending invokes as crashed
        (info) — exactly the post-hoc treatment of a stored history —
        advance through the remaining schedule, and return the
        knossos-shaped result.  Idempotent."""
        if self._result is None:
            self._finalized = True
            self._advance(final=True)
            if self._result is None:
                # survived the whole schedule with everything classified:
                # any surviving config is a constructive witness (sound
                # even after loss, as in chunked_analysis)
                self._terminal({"valid?": True}, barrier=None)
            self._save_ck()
        return self._result

    def status(self) -> dict:
        """The honest unknown-so-far status doc."""
        res = self._result or {}
        out = {
            "valid?": res.get("valid?", UNKNOWN),
            "terminal?": self._result is not None,
            "stream-id": self.stream_id,
            "ops": len(self._history),
            "pending": self._pending,
            "settled-barriers": self._advanced,
            "epochs": self._epochs,
            "lossy?": self._lossy,
        }
        if self._detect:
            out["detection"] = dict(self._detect)
        if res.get("cause") is not None:
            out["cause"] = res["cause"]
        return out

    def evidence(self, *, trace_id=None) -> dict | None:
        """Build the stream's evidence bundle (terminal streams only —
        there is no verdict to bundle before that).  The bundle's
        engine-independent core digests identically to the post-hoc
        path's on the same history (:func:`parity_digest`)."""
        if self._result is None:
            return None
        try:
            return _prov.build_bundle(
                history=self._history, result=self._result,
                source="stream", model=self.model,
                checker=self.checker_name, trace_id=trace_id,
                bundle_id=self.stream_id,
            )
        except Exception as e:  # noqa: BLE001 — evidence never loses verdicts
            logger.warning("stream evidence bundle build failed: %s", e)
            obs.counter("provenance.emit_error", error=type(e).__name__)
            return None

    # ------------------------------------------------------------------
    # Epoch advance
    # ------------------------------------------------------------------

    def _pv(self, event: str, **attrs) -> None:
        if len(self._traj) < _prov.MAX_PATH:
            self._traj.append({"event": event, **attrs})

    def _stats(self) -> dict:
        return {
            "frontier-peak": self._peak, "capacity": self.caps[self._cap_idx],
            "lossy?": self._lossy, "epochs": self._epochs,
            "launches": self._launches,
            "verified-barriers": self._verified,
            "settled-barriers": self._advanced,
        }

    def _terminal(self, res: dict, *, barrier: int | None) -> None:
        res = dict(res)
        res.setdefault("kernel", self._stats())
        v = res.get("valid?")
        self._pv(
            "stream.verdict", verdict=_prov.verdict_str(v),
            barrier=barrier, final=self._finalized,
        )
        _prov.attach(
            res, self._traj,
            engine={
                "engine": "streaming", "dedup_backend": self.dedup,
                "spill": self.spill, "fast": self.fast,
            },
            config={
                "capacity": self.caps, "rounds": self.rounds,
                "chunk_barriers": self.chunk_barriers, "fast": self.fast,
            },
        )
        self._result = res
        obs.span_event(
            "stream.verdict", time.perf_counter() - self._t0,
            verdict=_prov.verdict_str(v), ops=len(self._history),
            epochs=self._epochs, settled=self._advanced,
            final=self._finalized, stream=self.stream_id,
        )

    def _refute_or_unknown(self, packed: dict, gb: int) -> None:
        op_pos = int(packed["bar_opid"][gb])
        op = self._history[op_pos]
        stats = self._stats()
        stats["bar-opid"] = op_pos  # positional id for stop_at_index
        stats["witnessed-barriers"] = gb
        if self._lossy:
            self._pv("stream.lossy-death", barrier=gb)
            self._terminal({
                "valid?": UNKNOWN,
                "cause": "frontier capacity or closure rounds exhausted",
                "op": op, "kernel": stats,
            }, barrier=gb)
            return
        self._pv("stream.refuted", barrier=gb, provisional=self.fast)
        res = {"valid?": False, "op": op, "kernel": stats}
        if self.fast:
            res["provisional?"] = True  # hash-decided kills
        self._detect = {
            "ops": len(self._history), "barrier": gb, "op-position": op_pos,
            "seconds": time.perf_counter() - self._t0,
            "epoch_seconds": time.perf_counter() - self._t_epoch,
        }
        # Detect latency = wall from the offending epoch's ARRIVAL, not
        # from stream open — the quantity a streaming deployment cares
        # about ("how long after the bad op landed did we know?").
        _metrics.observe("serve.stream_detect_latency_seconds",
                         self._detect["epoch_seconds"])
        self._terminal(res, barrier=gb)

    def _advance(self, final: bool) -> None:
        self._t_epoch = time.perf_counter()
        self._epochs += 1
        history = self._history
        try:
            packed_raw = wgl.pack(self.model, history)
        except wgl.NotTensorizable as e:
            self._terminal(
                {"valid?": UNKNOWN, "cause": f"not tensorizable: {e}"},
                barrier=None)
            return
        pairs = h.pair_index(history)

        # Settlement cursor: position of the first pending invoke.
        u: float = math.inf
        pending = 0
        for i, op in enumerate(history):
            if (h.is_invoke(op) and h.is_client_op(op)
                    and int(pairs[i]) == -1):
                pending += 1
                if u is math.inf:
                    u = i
        self._pending = pending
        B = packed_raw["B"]
        bar_opid = packed_raw["bar_opid"]
        if final or u is math.inf:
            S = B
        else:
            S = 0
            for b in range(B):
                if int(pairs[int(bar_opid[b])]) < u:
                    S += 1
                else:
                    break

        def _epoch_span(scanned: int, rows: int) -> None:
            obs.span_event(
                "stream.epoch", time.perf_counter() - self._t_epoch,
                ops=len(history), pending=pending, settled=S,
                scanned=scanned, frontier_rows=rows,
                epoch=self._epochs, stream=self.stream_id,
            )

        if B == 0 or S <= self._advanced:
            _epoch_span(0, 0 if self._frontier is None
                        else int(self._frontier[0].shape[0]))
            return
        if packed_raw["G"] > self.max_groups:
            self._terminal({
                "valid?": UNKNOWN,
                "cause": (f"{packed_raw['G']} crashed-op groups exceeds "
                          f"{self.max_groups}"),
            }, barrier=None)
            return
        if packed_raw["P"] > self.max_procs:
            self._terminal({
                "valid?": UNKNOWN,
                "cause": (f"{packed_raw['P']} process slots exceeds "
                          f"{self.max_procs}"),
            }, barrier=None)
            return

        # Re-bucket: keep B for range indexing (the chunked convention).
        packed = wgl.pad_packed(packed_raw, B=B)
        P, G, W = packed["P"], packed["G"], packed["W"]
        grp_f, grp_v1, grp_v2 = packed_raw["grp"]
        new_keys = [
            (int(grp_f[k]), int(grp_v1[k]), int(grp_v2[k]))
            for k in range(packed_raw["G"])
        ]

        if self._frontier is None:
            f_state = np.array([packed["init_state"]], np.int32)
            f_fok = np.zeros((1, W), np.uint32)
            f_fcr = np.zeros((1, G), np.int16)
        else:
            f_state, f_fok, f_fcr = self._frontier
            if f_fok.shape[1] < W:  # slots are prefix-stable: pad only
                pad = np.zeros((f_fok.shape[0], W - f_fok.shape[1]),
                               np.uint32)
                f_fok = np.concatenate([f_fok, pad], axis=1)
            f_fcr, violated = _remap_fcr(f_fcr, self._gkeys, new_keys, G)
            if violated:
                # Settlement invariant violated (should be unreachable):
                # rescan from barrier 0 — latency, never a wrong verdict.
                obs.counter("stream.rescan", stream=self.stream_id)
                self._rescans += 1
                self._pv("stream.rescan", barrier=self._advanced)
                logger.warning(
                    "stream %s: dropped crashed-group column was nonzero; "
                    "rescanning from barrier 0", self.stream_id)
                self._advanced = 0
                self._verified = 0
                f_state = np.array([packed["init_state"]], np.int32)
                f_fok = np.zeros((1, W), np.uint32)
                f_fcr = np.zeros((1, G), np.int16)

        self._pv("stream.epoch", ops=len(history), settled=S,
                 from_barrier=self._advanced)
        r = wgl.scan_barrier_range(
            packed, (f_state, f_fok, f_fcr), self._advanced, S,
            capacities=self.caps, rounds=self.rounds,
            chunk_barriers=self.chunk_barriers, cap_idx=self._cap_idx,
            lossy=self._lossy, fast=self.fast, dedup_backend=self.dedup,
            spill=self.spill,
            on_event=lambda ev, **a: self._pv("stream." + ev, **a),
        )
        self._launches += r["launches"]
        self._peak = max(self._peak, r["peak"])
        self._cap_idx = r["cap_idx"]
        self._lossy = r["lossy"]
        self._frontier = r["frontier"]
        self._gkeys = new_keys
        if r["error"] is not None:
            _epoch_span(S - self._advanced,
                        int(self._frontier[0].shape[0]))
            self._terminal({
                "valid?": UNKNOWN,
                "cause": f"device launch failed: {r['error']}",
            }, barrier=self._advanced)
            return
        if r["failed_barrier"] is not None:
            _epoch_span(r["failed_barrier"] - self._advanced, 0)
            self._refute_or_unknown(packed, r["failed_barrier"])
            return
        if not self._lossy:
            # verified counts loss-free barriers, as in chunked_analysis
            self._verified = S
        scanned = S - self._advanced
        self._advanced = S
        _epoch_span(scanned, int(self._frontier[0].shape[0]))

    # ------------------------------------------------------------------
    # Checkpoint / resume
    # ------------------------------------------------------------------

    def _ck_config(self) -> dict:
        return {
            "model": getattr(self.model, "name", None),
            "stream_id": self.stream_id,
            "capacity": self.caps, "rounds": self.rounds,
            "chunk_barriers": self.chunk_barriers, "fast": self.fast,
            "dedup": self.dedup, "spill": self.spill,
            "max_groups": self.max_groups, "max_procs": self.max_procs,
            "checker": self.checker_name,
        }

    def _save_ck(self) -> str | None:
        """Persist the stream cursor + carried frontier; a save failure
        is logged and never fails the check."""
        if self.checkpoint_dir is None:
            return None
        from jepsen_tpu.store import checkpoint as _ckpt

        frontier = self._frontier
        if frontier is None:
            frontier = (np.zeros(0, np.int32), np.zeros((0, 1), np.uint32),
                        np.zeros((0, 1), np.int16))
        try:
            p = _ckpt.save_stream(
                self.checkpoint_dir, config=self._ck_config(),
                ops=self._history, advanced=self._advanced,
                cap_idx=self._cap_idx, frontier=frontier,
                group_keys=self._gkeys, lossy=self._lossy,
                verified=self._verified, launches=self._launches,
                epochs=self._epochs, result=self._result,
            )
            return str(p)
        except Exception:  # noqa: BLE001 — recovery aid, not verdict input
            logger.warning("couldn't write stream checkpoint to %s",
                           self.checkpoint_dir, exc_info=True)
            obs.counter("fault.checkpoint.error")
            return None

    @classmethod
    def resume(cls, checkpoint_dir, model: m.Model) -> "StreamingChecker":
        """Reconstruct a SIGKILL'd stream from its checkpoint pair.  The
        SAVED config wins over caller arguments (verdict identity
        requires the original scan parameters; same contract as the
        ladder checkpoint), but ``model`` must match the saved model
        name — resuming against a different model could only produce
        wrong verdicts, so that raises ``CheckpointError``.  Re-feed
        from :attr:`ops_consumed`; duplicate re-feeds of already
        consumed ops are the CALLER's responsibility to avoid (the
        serving layer's ``seq`` offsets make re-feeds idempotent)."""
        from jepsen_tpu.store import checkpoint as _ckpt

        saved = _ckpt.load_stream(checkpoint_dir)
        cfg = saved["config"]
        want = cfg.get("model")
        have = getattr(model, "name", None)
        if want is not None and want != have:
            raise _ckpt.CheckpointError(
                f"stream checkpoint was written for model {want!r}, "
                f"resume offered {have!r}",
                {"artifact": _ckpt.KIND_STREAM, "reason": "model-mismatch"})
        sc = cls(
            model,
            capacity=cfg.get("capacity") or (64, 256),
            rounds=cfg.get("rounds") or 8,
            chunk_barriers=cfg.get("chunk_barriers") or 512,
            fast=bool(cfg.get("fast")),
            dedup_backend=cfg.get("dedup"),
            spill=bool(cfg.get("spill")),
            max_groups=cfg.get("max_groups") or 64,
            max_procs=cfg.get("max_procs") or 128,
            checkpoint_dir=checkpoint_dir,
            stream_id=cfg.get("stream_id"),
            checker=cfg.get("checker") or "linearizable",
        )
        sc._history = [dict(o) for o in saved["ops"]]
        st, fo, fc = saved["frontier"]
        if st.shape[0]:
            sc._frontier = (
                np.asarray(st, np.int32), np.asarray(fo, np.uint32),
                np.asarray(fc, np.int16),
            )
        sc._gkeys = [tuple(k) for k in saved["group_keys"]]
        sc._advanced = saved["advanced"]
        sc._cap_idx = saved["cap_idx"]
        sc._lossy = saved["lossy"]
        sc._verified = saved["verified"]
        sc._launches = saved["launches"]
        sc._epochs = saved["epochs"]
        sc._result = saved["result"]
        obs.span_event(
            "fault.checkpoint.load", 0.0, barrier=sc._advanced,
            rows=int(st.shape[0]), stream=True,
            complete=sc._result is not None,
        )
        sc._pv("stream.resumed", barrier=sc._advanced,
               ops=len(sc._history))
        return sc


def stream_check(
    model: m.Model,
    history: Sequence[Mapping],
    *,
    feed_ops: int = 8,
    checkpoint_dir=None,
    resume: bool = False,
    **kw,
) -> tuple[dict, "StreamingChecker"]:
    """Replay a stored history through a :class:`StreamingChecker` in
    ``feed_ops``-sized epochs and finalize — the replayed-stream entry
    point (``tools/loadgen.py --stream``, the chaos kill/resume gate,
    the differential suite).  With ``resume`` and an existing stream
    checkpoint, the stream is reconstructed first and feeding continues
    from its consumed-op count (a SIGKILL'd replay reproduces
    uninterrupted verdicts).  Returns ``(result, checker)``."""
    history = h.materialize(history)
    sc: StreamingChecker | None = None
    if resume and checkpoint_dir is not None:
        from jepsen_tpu.store import checkpoint as _ckpt

        if _ckpt.stream_exists(checkpoint_dir):
            try:
                sc = StreamingChecker.resume(checkpoint_dir, model)
            except _ckpt.CheckpointError as e:
                logger.warning(
                    "unreadable stream checkpoint in %s (%s); "
                    "streaming fresh", checkpoint_dir, e)
                obs.counter("fault.checkpoint.mismatch", reason="unreadable")
    if sc is None:
        sc = StreamingChecker(model, checkpoint_dir=checkpoint_dir, **kw)
    at = sc.ops_consumed
    while at < len(history):
        # feed to the end even after a verdict latches (terminal feeds
        # are cheap no-ops): evidence parity with the post-hoc path
        # requires the stream to have consumed the SAME history
        sc.feed(history[at:at + max(1, int(feed_ops))])
        at = sc.ops_consumed
    return sc.finalize(), sc
