"""Render a failed linearizability analysis as SVG.

The reference renders ``linear.svg`` for failed analyses via
knossos.linear.report (checker.clj:207-210): a per-process timeline of
the operations around the failure, with the operation that could not be
linearized highlighted.  This is that artifact, self-contained SVG (no
graphviz): ops as horizontal bars in their [invoke, complete] windows,
the failing op in red, its concurrent ops shaded, a caption explaining
the verdict.
"""

from __future__ import annotations

from typing import Mapping, Sequence

from jepsen_tpu import history as h

BAR_H = 18
ROW_GAP = 8
LEFT = 90
WIDTH = 900
TOP = 48

TYPE_FILL = {h.OK: "#81BF67", h.INFO: "#FFA400", h.FAIL: "#FF1E90"}


def _pairs(history: Sequence[Mapping]):
    """(invoke, completion|None) pairs in invocation order, built from
    history.pair_index (the shared knossos-equivalent matcher)."""
    pair = h.pair_index(history)
    out = []
    for i, o in enumerate(history):
        if o.get("process") == h.NEMESIS or o["type"] != h.INVOKE:
            continue
        j = int(pair[i])
        out.append([o, history[j] if j >= 0 else None])
    return out


def render_failure(
    history: Sequence[Mapping],
    failing_op: Mapping | None,
    cause: str = "",
    window: int = 24,
) -> str:
    """SVG of the ops around ``failing_op`` (the op the search could not
    linearize), one row per process, failure in red, ops concurrent with
    it hatched."""
    pairs = _pairs(history)
    fail_idx = failing_op.get("index") if failing_op else None
    # Focus window: pairs whose invoke index is near the failure.
    if fail_idx is not None:
        center = next(
            (k for k, (inv, comp) in enumerate(pairs)
             if inv.get("index") == fail_idx or (comp or {}).get("index") == fail_idx),
            len(pairs) // 2,
        )
    else:
        center = len(pairs) // 2
    lo = max(0, center - window // 2)
    view = pairs[lo : lo + window]
    if not view:
        return "<svg xmlns='http://www.w3.org/2000/svg' width='10' height='10'/>"

    t0 = min(p[0].get("time", 0) for p in view)
    t1 = max(((p[1] or p[0]).get("time", 0) for p in view), default=t0 + 1)
    t1 = max(t1, t0 + 1)
    procs = sorted({p[0]["process"] for p in view}, key=str)
    rows = {p: i for i, p in enumerate(procs)}

    def px(t):
        return LEFT + (t - t0) / (t1 - t0) * (WIDTH - LEFT - 20)

    fail_inv = fail_comp = None
    for inv, comp in view:
        if fail_idx is not None and (
            inv.get("index") == fail_idx or (comp or {}).get("index") == fail_idx
        ):
            fail_inv, fail_comp = inv, comp

    height = TOP + len(procs) * (BAR_H + ROW_GAP) + 40
    e = [
        f'<svg xmlns="http://www.w3.org/2000/svg" width="{WIDTH}" height="{height}" '
        f'font-family="Helvetica,Arial,sans-serif" font-size="11">',
        f'<rect width="{WIDTH}" height="{height}" fill="white"/>',
        f'<text x="{LEFT}" y="18" font-size="13" font-weight="bold">'
        f"linearizability failure</text>",
        f'<text x="{LEFT}" y="34" fill="#666">'
        f'{_esc(cause) or "no linearization orders this op"}</text>',
    ]
    for p, i in rows.items():
        y = TOP + i * (BAR_H + ROW_GAP)
        e.append(f'<text x="6" y="{y + BAR_H - 5}" fill="#333">proc {p}</text>')
    for inv, comp in view:
        i = rows[inv["process"]]
        y = TOP + i * (BAR_H + ROW_GAP)
        x0 = px(inv.get("time", 0))
        x1 = px((comp or inv).get("time", 0)) if comp else px(t1)
        x1 = max(x1, x0 + 3)
        is_fail = fail_inv is inv
        concurrent = (
            fail_inv is not None
            and not is_fail
            and inv.get("time", 0) <= (fail_comp or {"time": t1}).get("time", t1)
            and (comp or {"time": t1}).get("time", t1) >= fail_inv.get("time", 0)
        )
        fill = "#D0021B" if is_fail else TYPE_FILL.get((comp or {}).get("type"), "#BBB")
        opacity = "1.0" if is_fail else ("0.9" if concurrent else "0.45")
        e.append(
            f'<rect x="{x0:.1f}" y="{y}" width="{x1 - x0:.1f}" height="{BAR_H}" '
            f'rx="3" fill="{fill}" fill-opacity="{opacity}"'
            + (' stroke="#900" stroke-width="2"' if is_fail else "")
            + "/>"
        )
        label = f"{inv.get('f')} {inv.get('value')!r}"
        if comp and comp.get("value") != inv.get("value"):
            label += f" → {comp.get('value')!r}"
        e.append(
            f'<text x="{x0 + 3:.1f}" y="{y + BAR_H - 5}" fill="#111" '
            f'font-size="10">{_esc(label[:48])}</text>'
        )
    e.append(
        f'<text x="{LEFT}" y="{height - 10}" fill="#666">red = op with no legal '
        f"linearization; saturated = concurrent with it; type colors: "
        f"ok green / info orange / fail pink</text>"
    )
    e.append("</svg>")
    return "\n".join(e)


def _esc(s: str) -> str:
    return s.replace("&", "&amp;").replace("<", "&lt;").replace(">", "&gt;")
