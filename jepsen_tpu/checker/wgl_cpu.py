"""CPU-reference linearizability checkers (Wing–Gong–Lowe family).

This is the rebuild's equivalent of Knossos (`knossos.wgl/analysis`,
`knossos.linear/analysis`, called from the reference at
jepsen/src/jepsen/checker.clj:199-203): the single-host oracle the TPU
kernel (jepsen_tpu.ops.wgl) is differentially tested against, and the
"Knossos-JVM-equivalent" baseline for BASELINE.md config 1.

Two engines over the same prepared event stream:

  * ``dfs_analysis`` — depth-first search with a visited-set cache, the
    moral equivalent of knossos's WGL: on valid histories the greedy path
    ("fire the returning op first") usually walks straight through in
    O(n·branching); invalid or adversarial histories backtrack, bounded by
    ``max_visited``.
  * ``sweep_analysis`` — breadth-style configuration-set sweep with
    domination pruning; this is the exact algorithm the TPU kernel
    vectorizes, kept on CPU as its semantics oracle.

Shared op semantics (knossos convention, load-bearing for correctness —
SURVEY.md §7 "hard parts" #5):

  * ``ok``   — definitely happened; must linearize between call and return;
  * ``fail`` — definitely did not happen; removed from the search entirely;
  * ``info`` — indeterminate; *may* linearize anywhere after its call, or
    never: it stays open forever, multiplying the branching factor;
  * crashed ops whose ``f`` is pure (state-preserving, e.g. reads) are
    dropped: linearizing them never changes any state, so they cannot
    affect the verdict.

Two structural optimizations make the search tractable (both shared with
the TPU kernel):

1. **Crashed-op canonicalization.**  Open crashed ops with identical
   ``(f, value)`` are interchangeable — both may fire at any future point —
   so fired crashed ops are tracked as a multiset of (f, value) *groups*,
   not identities.  A 50k-op history with 15k crashed writes over V values
   contributes V fire-groups, not 2^15k subsets (BASELINE config 5's
   worst case).
2. **Barrier compression (just-in-time linearization).**  Linearization
   points are only chosen at return barriers: once the returning op is
   fired, the search advances instead of speculatively firing more open
   ops — any deferred op can equally fire at the next barrier, so nothing
   reachable is lost.

Both engines answer ``"unknown"`` on resource exhaustion — never a wrong
verdict.
"""

from __future__ import annotations

import bisect
from typing import Any, Sequence

from jepsen_tpu import history as h
from jepsen_tpu import models as m
from jepsen_tpu import obs

#: fs that never change model state; crashed ops with these fs are dropped.
PURE_FS = {
    "register": {"read"},
    "cas-register": {"read"},
    "counter": {"read"},
}

CALL = 0
RET = 1


def _canon_value(v) -> Any:
    return tuple(v) if isinstance(v, list) else v


def prepare(model: m.Model, history: Sequence[dict]):
    """Reduce a history to the event stream the searches consume.

    Returns ``(events, eff_ops, crashed)``: events are ``(kind, op_index)``
    pairs in true history order; ``eff_ops[i]`` is the *effective* op for
    model stepping — the invoke op carrying its completion's value when the
    completion is ok (knossos.history/complete semantics: reads invoke with
    nil and learn their value on completion); ``crashed`` is the set of op
    ids that never definitely completed.
    """
    history = h.materialize(history)
    pairs = h.pair_index(history)
    pure = PURE_FS.get(getattr(model, "name", None), set())
    order: list[tuple[int, int, int]] = []  # (history position, kind, op id)
    eff_ops: dict[int, dict] = {}
    crashed: set[int] = set()
    for i, op in enumerate(history):
        if not h.is_invoke(op) or not h.is_client_op(op):
            continue
        j = int(pairs[i])
        completion = history[j] if j != -1 else None
        ctype = completion["type"] if completion is not None else h.INFO
        if ctype == h.FAIL:
            continue  # definitely didn't happen
        if ctype == h.INFO and op["f"] in pure:
            continue  # crashed pure op can never matter
        eff = op
        if ctype == h.OK and completion.get("value") is not None and op.get("value") != completion["value"]:
            eff = {**op, "value": completion["value"]}
        eff_ops[i] = eff
        order.append((i, CALL, i))
        if ctype == h.OK:
            order.append((j, RET, i))
        else:
            crashed.add(i)
    order.sort()
    return [(kind, i) for _, kind, i in order], eff_ops, crashed


def _barrier_snapshots(events, eff_ops, crashed):
    """For each return event, snapshot the open ok ops and open crashed
    group counts at that point.  Returns (barriers, group_ops) where
    barriers is a list of (event_pos, op_id, open_ok tuple, open_crashed
    tuple of ((f, value), count)) and group_ops maps group -> effective op.

    ``open_ok`` stays sorted by construction — CALL events arrive in
    position order and an op's id IS its invoke position, so appends are
    monotone — instead of re-sorting at every barrier; and group tuples
    use stable insertion order — a per-barrier ``sorted(..., key=repr)``
    cost the pack of a 100k-op history ~0.9 s for an ordering nothing
    relies on (consumers key groups through their own index maps)."""
    open_ok: list[int] = []
    open_crashed: dict[tuple, int] = {}
    group_ops: dict[tuple, dict] = {}
    barriers = []
    for pos, (kind, i) in enumerate(events):
        if kind == CALL:
            if i in crashed:
                g = (eff_ops[i]["f"], _canon_value(eff_ops[i]["value"]))
                open_crashed[g] = open_crashed.get(g, 0) + 1
                group_ops[g] = eff_ops[i]
            else:
                open_ok.append(i)  # monotone: sorted by construction
        else:
            barriers.append(
                (pos, i, tuple(open_ok), tuple(open_crashed.items()))
            )
            k = bisect.bisect_left(open_ok, i)
            if k < len(open_ok) and open_ok[k] == i:
                del open_ok[k]
    return barriers, group_ops


# ---------------------------------------------------------------------------
# DFS engine (knossos-equivalent; the CPU performance baseline)
# ---------------------------------------------------------------------------


def dfs_analysis(
    model: m.Model,
    history: Sequence[dict],
    max_visited: int = 5_000_000,
) -> dict:
    """Decide linearizability by depth-first search over configurations.

    A node is ``(barrier_index, state, fired_ok, fired_crashed)``.  At each
    barrier the returning op must be fired; if it already is, we advance
    (barrier compression); otherwise we branch over firing any available
    open op, greedy-first.  A visited cache makes re-exploration O(1).

    Returns knossos-shaped maps: ``{"valid?": True}``, or ``{"valid?":
    False, "op": ..., "configs": [...]}`` with the furthest barrier op
    reached, or ``{"valid?": "unknown", "cause": ...}`` past the node
    budget.
    """
    with obs.span("wgl_cpu.dfs") as sp:
        stats: dict = {}
        out = _dfs_analysis(model, history, max_visited, stats)
        sp.set(valid=out.get("valid?"), **stats)
        return out


def _dfs_analysis(model, history, max_visited, stats: dict) -> dict:
    events, eff_ops, crashed = prepare(model, history)
    barriers, group_ops = _barrier_snapshots(events, eff_ops, crashed)
    n_barriers = len(barriers)
    if n_barriers == 0:
        return {"valid?": True, "configs": [{"model": model}]}

    # Fired-crash multisets as fixed-vocabulary count tuples (same form
    # as the sweep): node keys hash without the per-successor
    # sorted-by-repr canonicalization this replaced.
    groups, gidx, group_op_list, empty = _group_vocab(group_ops)
    max_visited = _g_scaled(max_visited, len(groups))
    start = (0, model, frozenset(), empty)
    stack = [start]
    visited = {start}
    deepest = 0
    deepest_sample: list = []

    while stack:
        b, state, fok, fcr = stack.pop()
        if b >= n_barriers:
            stats.update(visited=len(visited), barriers=n_barriers)
            return {"valid?": True, "configs": [{"model": state}]}
        if b > deepest:
            deepest = b
            deepest_sample = [(state, fok, fcr)]
        _pos, i, open_ok, open_crashed = barriers[b]

        if i in fok:
            # Barrier satisfied: strip i and advance.
            nxt = (b + 1, state, fok - {i}, fcr)
            if nxt not in visited:
                visited.add(nxt)
                stack.append(nxt)
            continue

        succs = []
        # Fire another open ok op (enabling move).
        for j in open_ok:
            if j in fok or j == i:
                continue
            s2 = state.step(eff_ops[j])
            if not m.is_inconsistent(s2):
                succs.append((b, s2, fok | {j}, fcr))
        # Fire one crashed op from an available group.
        for g, open_count in open_crashed:
            gi = gidx[g]
            if fcr[gi] >= open_count:
                continue
            s2 = state.step(group_op_list[gi])
            if not m.is_inconsistent(s2):
                fcr2 = fcr[:gi] + (fcr[gi] + 1,) + fcr[gi + 1 :]
                succs.append((b, s2, fok, fcr2))
        # Fire the returning op itself — pushed last so DFS tries it first.
        s2 = state.step(eff_ops[i])
        if not m.is_inconsistent(s2):
            succs.append((b, s2, fok | {i}, fcr))

        for nxt in succs:
            if nxt not in visited:
                visited.add(nxt)
                stack.append(nxt)
        if len(visited) > max_visited:
            stats.update(visited=len(visited), barriers=n_barriers, deepest=deepest)
            return {
                "valid?": "unknown",
                "cause": f"visited more than {max_visited} configurations",
                "op": history[barriers[deepest][1]],
            }

    stats.update(visited=len(visited), barriers=n_barriers, deepest=deepest)
    return {
        "valid?": False,
        "op": history[barriers[deepest][1]],
        "configs": [
            {"model": st, "pending": sorted(set(barriers[deepest][2]) - fok)}
            for st, fok, fcr in deepest_sample[:10]
        ],
    }


def greedy_walk(model: m.Model, history: Sequence[dict],
                max_steps: int | None = None,
                record: list | None = None) -> bool | None:
    """Speculative single-config greedy walk — the host-side counterpart
    of the ladder's rung-0 greedy kernel (one beam lane, returning-op
    first, no backtracking).  Returns ``True`` when the walk completes:
    that is a full linearization, i.e. a constructive witness, so the
    verdict is EXACT.  Returns ``None`` when the walk sticks (no
    greedy-consistent move, or ``max_steps`` fired) — the caller must
    escalate; a stuck walk never refutes, because only search proves
    absence of witnesses.

    This is the serving layer's interactive fast path: ~microseconds per
    small history, no kernel launch, so it cannot contend with a ladder
    mid-rung for the device (or, on the CPU backend, for host cores).

    ``record``, when given, receives the fired *effective* ops in fire
    order — on a ``True`` return it is the full linearization, the
    constructive witness the provenance layer embeds in evidence
    bundles (obs.provenance re-steps it during ``verify``).
    """
    events, eff_ops, crashed = prepare(model, history)
    barriers, group_ops = _barrier_snapshots(events, eff_ops, crashed)
    n_barriers = len(barriers)
    if n_barriers == 0:
        return True
    groups, gidx, group_op_list, empty = _group_vocab(group_ops)
    # Every fired op strictly grows fok or a crashed count, both bounded,
    # so the walk terminates without the cap; the cap bounds worst-case
    # latency anyway (this path sits under an interactive SLO).
    cap = max_steps if max_steps is not None else 4 * len(history) + 64
    state, fok, fcr = model, frozenset(), empty
    b = steps = 0
    with obs.span("wgl_cpu.greedy_walk") as sp:
        while b < n_barriers:
            _pos, i, open_ok, open_crashed = barriers[b]
            if i in fok:
                fok = fok - {i}
                b += 1
                continue
            steps += 1
            if steps > cap:
                sp.set(completed=False, steps=steps)
                return None
            # Greedy: fire the returning op itself first.
            s2 = state.step(eff_ops[i])
            if not m.is_inconsistent(s2):
                state, fok = s2, fok | {i}
                if record is not None:
                    record.append(eff_ops[i])
                continue
            # Enabling move: the first consistent open ok op, else the
            # first available crashed group (same legality and order the
            # DFS branches over — we just never come back).
            for j in open_ok:
                if j in fok or j == i:
                    continue
                s2 = state.step(eff_ops[j])
                if not m.is_inconsistent(s2):
                    state, fok = s2, fok | {j}
                    if record is not None:
                        record.append(eff_ops[j])
                    break
            else:
                for g, open_count in open_crashed:
                    k = gidx[g]
                    if fcr[k] >= open_count:
                        continue
                    s2 = state.step(group_op_list[k])
                    if not m.is_inconsistent(s2):
                        state = s2
                        fcr = fcr[:k] + (fcr[k] + 1,) + fcr[k + 1:]
                        if record is not None:
                            record.append(group_op_list[k])
                        break
                else:
                    sp.set(completed=False, steps=steps)
                    return None  # stuck: every greedy move is inconsistent
        sp.set(completed=True, steps=steps)
    return True


# ---------------------------------------------------------------------------
# Configuration-set sweep (the TPU kernel's semantics oracle)
# ---------------------------------------------------------------------------


def _group_vocab(group_ops):
    """Fixed group vocabulary shared by both engines: (groups, gidx,
    group_op_list, zero-count tuple).  Count tuples are O(G) per config,
    so the engines scale their exploration budgets by G (see callers) —
    a group-heavy history answers "unknown" early instead of chewing
    through gigabytes of wide tuples."""
    groups = list(group_ops)
    gidx = {g: k for k, g in enumerate(groups)}
    group_op_list = [group_ops[g] for g in groups]
    return groups, gidx, group_op_list, (0,) * len(groups)


def _g_scaled(budget: int, n_groups: int, floor: int = 10_000) -> int:
    """Cap a visited/config budget so total tuple storage stays bounded
    (~50M counts) however wide the group vocabulary is."""
    if n_groups <= 64:
        return budget
    return max(floor, min(budget, 50_000_000 // n_groups))


def _tuple_dominates(a: tuple, b: tuple) -> bool:
    """a ≤ b pointwise over fixed-vocabulary count tuples."""
    for x, y in zip(a, b):
        if x > y:
            return False
    return True


class _Antichain:
    """Minimal fired-crashed multisets for one (state, fired_ok) class.

    A config that fired *fewer* crashed ops dominates one that fired more:
    every continuation of the bigger set is available to the smaller one
    (crashed ops carry no obligations), so only the minimal antichain needs
    exploring.  Multisets are count tuples over the sweep's fixed group
    vocabulary — pointwise compares on tuples run ~2.5x faster than the
    dict form this replaced (the confirmation sweeps' hot loop)."""

    __slots__ = ("items",)

    def __init__(self):
        self.items: list[tuple] = []

    def add(self, fcr: tuple) -> bool:
        for it in self.items:
            if _tuple_dominates(it, fcr):
                return False
        self.items = [it for it in self.items if not _tuple_dominates(fcr, it)]
        self.items.append(fcr)
        return True


def sweep_analysis(
    model: m.Model,
    history: Sequence[dict],
    max_configs: int = 200_000,
    stop_at_index: int | None = None,
    stats: dict | None = None,
) -> dict:
    """Exhaustive configuration-set sweep with domination pruning — the
    algorithm the TPU kernel vectorizes (jepsen_tpu.ops.wgl), kept on CPU
    as its differential-testing oracle.

    ``stop_at_index`` bounds a refutation-confirmation run to the prefix
    ending at the device's failure barrier (the returning op's history
    index): a genuine refutation dies by that barrier, so sweeping past
    it is wasted work.  Surviving past it means the device refutation was
    a hash-collision artifact — returned as "unknown" (the prefix proves
    nothing about the suffix).

    ``stats``: an optional dict the sweep fills with its work counters
    (barriers, groups, configs_explored, peak_configs) — the same
    attributes the telemetry span carries; bench.py's fixed-work metric
    reads configs_explored from it."""
    with obs.span("wgl_cpu.sweep") as sp:
        st: dict = {} if stats is None else stats
        out = _sweep_analysis(model, history, max_configs, stop_at_index, st)
        sp.set(valid=out.get("valid?"), **st)
        return out


def _sweep_analysis(model, history, max_configs, stop_at_index, stats: dict) -> dict:
    events, eff_ops, crashed = prepare(model, history)
    barriers, group_ops = _barrier_snapshots(events, eff_ops, crashed)
    # Fixed group vocabulary: all groups are known after the snapshots,
    # so fired-crash multisets become count TUPLES indexed by group.
    groups, gidx, group_op_list, zero = _group_vocab(group_ops)
    max_configs = _g_scaled(max_configs, len(groups))

    # configs: (state, fok) -> antichain of fired-crashed count tuples
    configs: dict[tuple, _Antichain] = {}
    ac = _Antichain()
    ac.add(zero)
    configs[(model, frozenset())] = ac
    explored = 0  # closure work across barriers (telemetry)
    peak = 1      # peak per-barrier frontier occupancy (telemetry)
    stats.update(barriers=len(barriers), groups=len(groups))

    for _pos, i, open_ok, open_crashed in barriers:
        bar_open = [(gidx[g], c) for g, c in open_crashed]
        # Closure under firing, with domination pruning.
        work = [(st, fok, fcr) for (st, fok), a in configs.items() for fcr in a.items]
        seen: dict[tuple, _Antichain] = {}
        for st, fok, fcr in work:
            seen.setdefault((st, fok), _Antichain()).add(fcr)
        count = len(work)
        while work:
            state, fok, fcr = work.pop()
            cands = []
            for j in open_ok:
                if j in fok:
                    continue
                s2 = state.step(eff_ops[j])
                if not m.is_inconsistent(s2):
                    cands.append((s2, fok | {j}, fcr))
            for gi, open_count in bar_open:
                if fcr[gi] >= open_count:
                    continue
                s2 = state.step(group_op_list[gi])
                if not m.is_inconsistent(s2):
                    fcr2 = fcr[:gi] + (fcr[gi] + 1,) + fcr[gi + 1 :]
                    cands.append((s2, fok, fcr2))
            for s2, fok2, fcr2 in cands:
                a = seen.setdefault((s2, fok2), _Antichain())
                if a.add(fcr2):
                    work.append((s2, fok2, fcr2))
                    count += 1
                    if count > max_configs:
                        stats.update(
                            configs_explored=explored + count,
                            peak_configs=max(peak, count),
                        )
                        return {
                            "valid?": "unknown",
                            "cause": f"configuration set exceeded {max_configs}",
                            "op": history[i],
                        }
        explored += count
        peak = max(peak, count)
        stats.update(configs_explored=explored, peak_configs=peak)
        # Keep configs that fired i; retire i.
        configs = {}
        for (st, fok), a in seen.items():
            if i in fok:
                tgt = configs.setdefault((st, fok - {i}), _Antichain())
                for fcr in a.items:
                    tgt.add(fcr)
        if not configs:
            return {
                "valid?": False,
                "op": history[i],
                "configs": [
                    {"model": st, "pending": sorted(set(open_ok) - fok)}
                    for (st, fok) in list(seen)[:10]
                ],
            }
        if stop_at_index is not None and i == stop_at_index:
            # Barriers are ordered by return position, not op id, so the
            # bound is the IDENTITY of the device's failure barrier (both
            # sides name it by the returning op's history index).
            return {
                "valid?": "unknown",
                "cause": "confirmation prefix survived past the device failure point",
                "op": history[i],
            }
    return {"valid?": True, "configs": [{"model": st} for (st, _fok) in list(configs)[:10]]}


#: Default engine, reference-equivalent ("wgl" algorithm).
analysis = dfs_analysis


# ---------------------------------------------------------------------------
# Independent brute-force oracle (for validating the oracles themselves)
# ---------------------------------------------------------------------------


def brute_analysis(model: m.Model, history: Sequence[dict]) -> dict:
    """Tiny-history oracle: enumerate every linearization order consistent
    with real-time precedence and check sequential legality.  Exponential —
    differential-test use only (≲ 12 ops)."""
    events, eff_ops, _crashed = prepare(model, history)
    call_pos: dict[int, int] = {}
    ret_pos: dict[int, int] = {}
    for pos, (kind, i) in enumerate(events):
        if kind == CALL:
            call_pos[i] = pos
        else:
            ret_pos[i] = pos
    ids = sorted(call_pos)
    must = [i for i in ids if i in ret_pos]  # ok ops must appear

    # At each step, the next linearized op must be callable before the
    # earliest unlinearized return: if ret(j) < call(i), j precedes i in
    # every legal order.
    def search(state, done: frozenset) -> bool:
        remaining_must = [i for i in must if i not in done]
        if not remaining_must:
            return True
        barrier = min(ret_pos[i] for i in remaining_must)
        for i in ids:
            if i in done:
                continue
            if call_pos[i] > barrier:
                continue
            s2 = state.step(eff_ops[i])
            if m.is_inconsistent(s2):
                continue
            if search(s2, done | {i}):
                return True
        return False

    return {"valid?": search(model, frozenset())}
