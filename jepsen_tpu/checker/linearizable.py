"""The linearizable checker front-end (reference: checker.clj:185-216).

Chooses an analysis backend by ``algorithm`` the way the reference chooses
between knossos's ``:linear``/``:wgl``/``competition`` engines:

  * ``"wgl"``          — the CPU DFS oracle (jepsen_tpu.checker.wgl_cpu);
  * ``"sweep"``        — the CPU configuration-set sweep (the TPU kernel's
    semantics oracle);
  * ``"tpu"``          — the chunked exact device engine (jepsen_tpu.ops.
    wgl.analysis: carried-frontier chunk scans, content-decided kills);
  * ``"competition"``  — the measured-fastest ladder, mirroring
    knossos.competition's race semantics with a deterministic order
    instead of racing threads: (0) the DEVICE greedy witness walk
    (wgl.greedy_analysis) — one config, no frontier buffers; most valid
    histories (including the 10k-op register that exhausts every
    fixed-capacity beam) resolve here in one scan; (1) the async beam
    kernel at an escalating capacity ladder — a surviving frontier is a
    constructive witness (True), a lossless death is confirmed against
    the exact CPU sweep bounded to the failure prefix; (2) on
    "unknown", the greedy CPU DFS; (3) still unknown → the chunked
    exact device engine, whose refutations are final and whose stats
    quantify the verified prefix.

On failure, ``final-paths`` / ``configs`` are truncated to 10 entries, as
the reference does because writing them out "can take *hours*"
(checker.clj:213-216).
"""

from __future__ import annotations

from typing import Mapping

from jepsen_tpu import models as m
from jepsen_tpu.checker import Checker, UNKNOWN
from jepsen_tpu.checker import wgl_cpu
from jepsen_tpu.obs import provenance as _prov


def _resolve_model(model) -> m.Model:
    if isinstance(model, str):
        return m.model(model)
    return model


class Linearizable(Checker):
    def __init__(self, opts: Mapping):
        if "model" not in opts or opts["model"] is None:
            raise ValueError(
                f"the linearizable checker requires a model, got {opts.get('model')!r}"
            )
        self.model = _resolve_model(opts["model"])
        self.algorithm = opts.get("algorithm", "competition")
        self.kernel_opts = dict(opts.get("kernel-opts", {}))

    def _analyze(self, history, deadline=None):
        if self.algorithm == "wgl":
            return _prov.attach(
                wgl_cpu.dfs_analysis(self.model, history),
                [{"event": "engine.dfs"}], engine={"engine": "wgl-dfs"})
        if self.algorithm == "sweep":
            return _prov.attach(
                wgl_cpu.sweep_analysis(self.model, history),
                [{"event": "engine.sweep"}], engine={"engine": "wgl-sweep"})
        from jepsen_tpu.ops import wgl as wgl_tpu

        if self.algorithm == "tpu":
            return wgl_tpu.analysis(self.model, history, deadline=deadline,
                                    **self.kernel_opts)
        if self.algorithm == "competition":
            return self._competition(history, wgl_tpu, deadline)
        raise ValueError(f"unknown linearizability algorithm {self.algorithm!r}")

    def _competition(self, history, wgl_tpu, deadline=None):
        """Fast engines first, exact ones on demand (see module doc).

        Tunables ride ``kernel-opts``: ``async-capacity`` sizes the beam
        ladder (the chunked engine's own ``capacity`` escalation ladder
        is a separate knob, forwarded untouched), ``confirm-max-configs``
        bounds the refutation-confirmation sweep (same default as
        parallel.batch_analysis's confirm_max_configs)."""
        path: list[dict] = []  # the decision-path trail (obs.provenance)

        def _fin(res, engine_name):
            """Attach the engine-fallback trail before a result leaves
            the competition — the evidence bundle's decision path."""
            return _prov.attach(res, path, engine={"engine": engine_name})

        if deadline is not None and deadline.expired():
            # the budget was spent before this key's check began (e.g. by
            # earlier keys of an independent checker): degrade attributably
            path.append({"event": "fault.deadline", "at": "pre-check"})
            return _fin({
                "valid?": UNKNOWN,
                "cause": "deadline-exceeded: check budget exhausted",
            }, "competition")
        ladder = self.kernel_opts.get("async-capacity", (256, 1024))
        if isinstance(ladder, int):
            ladder = (ladder,)
        confirm_cap = self.kernel_opts.get("confirm-max-configs", 2_000_000)
        # Rung 0: the greedy witness walk — one config, no frontier
        # buffers, resolves most valid histories (incl. the 10k-op
        # register that exhausts every fixed-capacity beam) in one scan.
        # ``greedy-first: False`` in kernel-opts disables it (mirror of
        # batch_analysis's greedy_first knob).
        if self.kernel_opts.get("greedy-first", True):
            g = wgl_tpu.greedy_analysis(self.model, history)
            if g["valid?"] is True:
                path.append({"event": "engine.greedy", "outcome": "valid"})
                return _fin(g, "greedy")
            if "not tensorizable" in str(g.get("cause", "")):
                path.append({"event": "engine.greedy",
                             "outcome": "not-tensorizable"})
                path.append({"event": "cpu-fallback", "engine": "dfs"})
                return _fin(wgl_cpu.analysis(self.model, history), "wgl-dfs")
            path.append({"event": "engine.greedy", "outcome": "stuck"})
        for cap in ladder:
            a = wgl_tpu.analysis_async(self.model, history, capacity=int(cap))
            path.append({"event": "async.capacity", "capacity": int(cap),
                         "outcome": _prov.verdict_str(a["valid?"])})
            if a["valid?"] is True:
                return _fin(a, "async")
            if a["valid?"] is False:
                # fast-engine kills are hash-decided: confirm on the
                # exact sweep, bounded to the failure prefix.  The bound
                # is the POSITIONAL op id from the kernel stats — the
                # op's "index" FIELD can differ from its position on
                # user-supplied histories, silently unbounding the sweep
                # (advisor r4).
                stop = a.get("kernel", {}).get("bar-opid")
                c = wgl_cpu.sweep_analysis(
                    self.model, history, max_configs=confirm_cap, stop_at_index=stop
                )
                path.append({"event": "confirm.sweep",
                             "outcome": _prov.verdict_str(c["valid?"])})
                if c["valid?"] is False:
                    return _fin({**a, "confirmed?": True}, "async")
                if c["valid?"] is True:
                    # hash-collision artifact: the sweep wins
                    return _fin(c, "wgl-sweep")
                break  # inconclusive: escalate to the oracles
            if "not tensorizable" in str(a.get("cause", "")):
                # no tensor form: every device rung would fail the same
                # way — the CPU oracle is the only engine
                path.append({"event": "cpu-fallback", "engine": "dfs"})
                return _fin(wgl_cpu.analysis(self.model, history), "wgl-dfs")
        if deadline is not None and deadline.expired():
            # the CPU DFS and the exact device ladder are the expensive
            # oracles; past the budget they degrade to an attributable
            # unknown instead of running unbounded
            path.append({"event": "fault.deadline", "at": "pre-oracle"})
            return _fin({
                "valid?": UNKNOWN,
                "cause": "deadline-exceeded: check budget exhausted before "
                         "the exact oracles",
            }, "competition")
        dfs = wgl_cpu.analysis(self.model, history)
        path.append({"event": "engine.dfs",
                     "outcome": _prov.verdict_str(dfs["valid?"])})
        if dfs["valid?"] != UNKNOWN:
            return _fin(dfs, "wgl-dfs")
        # the exact device engine: final refutations, quantified prefix;
        # uses its own (chunked) capacity ladder from kernel_opts
        opts = {k: v for k, v in self.kernel_opts.items()
                if k not in ("async-capacity", "confirm-max-configs")}
        path.append({"event": "route.chunked-exact"})
        a = wgl_tpu.analysis(self.model, history, deadline=deadline, **opts)
        if a["valid?"] == UNKNOWN and "not tensorizable" in str(a.get("cause", "")):
            # keep the DFS's informative unknown (budget + op)
            return _fin(dfs, "wgl-dfs")
        return _fin(a, "chunked-exact")

    @staticmethod
    def _truncate(a: Mapping) -> dict:
        out = dict(a)
        if "final-paths" in out:
            out["final-paths"] = list(out["final-paths"])[:10]
        if "configs" in out:
            out["configs"] = list(out["configs"])[:10]
        return out

    def check(self, test, history, opts):
        out = self._truncate(
            self._analyze(history, deadline=(opts or {}).get("deadline"))
        )
        if out.get("valid?") is False:
            self._render_failure(test, history, out, opts)
        _prov.emit(test, history, out, source="check", model=self.model,
                   checker="linearizable", opts=opts)
        return out

    @staticmethod
    def _render_failure(test, history, result, opts):
        """Write linear.svg next to the results — the reference renders
        the failed linearization via knossos.linear.report
        (checker.clj:207-210)."""
        from jepsen_tpu import store
        from jepsen_tpu.checker.linear_svg import render_failure

        if not (test.get("name") and test.get("start-time-str")):
            return  # no store configured (bare checker unit tests)
        svg = render_failure(history, result.get("op"), result.get("cause", ""))
        try:
            d = store.test_dir(test)
            sub = (opts or {}).get("subdirectory")
            d = d / sub if sub else d
            d.mkdir(parents=True, exist_ok=True)
            (d / "linear.svg").write_text(svg)
            result["svg"] = str(d / "linear.svg")
        except OSError:
            pass  # store dir not writable

    def check_batch(self, test, histories, opts):
        """Check many subhistories in ONE vmapped kernel ladder (used by
        independent.checker: per-key shards become the batch axis —
        BASELINE config 4's shape).  CPU algorithms just loop."""
        if self.algorithm in ("wgl", "sweep"):
            # headless: no per-key linear.svg (they would all land on the
            # same path and overwrite each other; independent.checker
            # writes per-key artifacts itself)
            outs = [self._truncate(self._analyze(hh)) for hh in histories]
            for hh, out in zip(histories, outs):
                _prov.emit(test, hh, out, source="check_batch",
                           model=self.model, checker="linearizable",
                           opts=opts)
            return outs
        from jepsen_tpu.parallel import batch_analysis

        # kernel-opts is shaped for wgl.analysis; forward only the keys
        # batch_analysis shares (capacity ladder, rounds, exact stage).
        batch_kw = {
            k: v
            for k, v in self.kernel_opts.items()
            if k in ("capacity", "rounds", "mesh", "exact_escalation", "engine")
        }
        # Fault-tolerance keys ride the CHECKER OPTS (core.analyze fills
        # them from the test map / CLI): the ladder checkpoints into the
        # run's store dir, honors the shared deadline, and resumes when
        # asked (jepsen_tpu.parallel.batch_analysis docstring).
        opts = opts or {}
        results = batch_analysis(
            self.model,
            histories,
            cpu_fallback=(self.algorithm == "competition"),
            deadline=opts.get("deadline"),
            checkpoint_dir=opts.get("checkpoint-dir"),
            resume=bool(opts.get("resume?")),
            **batch_kw,
        )
        outs = [self._truncate(r) for r in results]
        # Rung admission can grow the result list past the input
        # histories; emit bundles for the caller-supplied ones (joiner
        # verdicts are the admission hook's to bundle — the serving
        # layer does so per request).
        for hh, out in zip(histories, outs):
            _prov.emit(test, hh, out, source="check_batch",
                       model=self.model, checker="linearizable", opts=opts)
        return outs


def linearizable(opts: Mapping) -> Checker:
    return Linearizable(opts)
