"""Latency / rate observability: quantiles, graphs, nemesis shading.

Mirrors ``jepsen.checker.perf`` (reference: jepsen/src/jepsen/checker/
perf.clj): time-bucketed latency quantiles (perf.clj:21-85), per-(f, type)
rate series (perf.clj:110-130), and nemesis-interval shading behind the
curves.  The reference shells out to gnuplot; TPU hosts don't carry it, so
this renders self-contained SVG directly — same artifacts (latency-raw,
latency-quantiles, rate), zero external processes.

The ``perf()`` composite checker (checker.clj:797-829) writes all three
graphs into the test's store directory and always reports valid.
"""

from __future__ import annotations

import math
from pathlib import Path
from typing import Mapping, Sequence

from jepsen_tpu import history as h
from jepsen_tpu import store
from jepsen_tpu.checker import Checker, checker as as_checker
from jepsen_tpu.checker.linear_svg import _esc
from jepsen_tpu.utils import nemesis_intervals

DEFAULT_QUANTILES = (0.5, 0.95, 0.99, 1.0)

TYPE_COLORS = {h.OK: "#81BF67", h.INFO: "#FFA400", h.FAIL: "#FF1E90"}
SERIES_COLORS = [
    "#1F77B4", "#FF7F0E", "#2CA02C", "#D62728", "#9467BD",
    "#8C564B", "#E377C2", "#7F7F7F", "#BCBD22", "#17BECF",
]


# ---------------------------------------------------------------------------
# Data shaping (perf.clj:21-130)
# ---------------------------------------------------------------------------


def bucket_scale(dt: float, b: int) -> float:
    """The time at the center of bucket b, seconds (perf.clj:21-33)."""
    return (b + 0.5) * dt


def bucket_time(dt: float, t: float) -> int:
    return int(t // dt)


def buckets(dt: float, points: Sequence[tuple]) -> dict:
    """Group (time, value) points into dt-second buckets
    (perf.clj:35-49)."""
    out: dict = {}
    for t, v in points:
        out.setdefault(bucket_time(dt, t), []).append(v)
    return out


def quantile(sorted_xs: Sequence[float], q: float) -> float:
    """Nearest-rank quantile of an already-sorted sequence
    (perf.clj:51-60)."""
    if not sorted_xs:
        raise ValueError("quantile of empty sequence")
    i = min(len(sorted_xs) - 1, max(0, math.ceil(q * len(sorted_xs)) - 1))
    return sorted_xs[i]


def latencies_to_quantiles(dt: float, qs: Sequence[float], points: Sequence[tuple]) -> dict:
    """{q: [(bucket-center-time, latency)]} per bucket (perf.clj:62-85)."""
    bs = {b: sorted(vs) for b, vs in buckets(dt, points).items()}
    return {
        q: [(bucket_scale(dt, b), quantile(vs, q)) for b, vs in sorted(bs.items())]
        for q in qs
    }


def invoke_latencies(history: Sequence[dict]) -> list[dict]:
    """Completed client ops with ``time`` (s) of invocation and ``latency``
    (ms), tagged by f and completion type (perf.clj:87-108 invokes-by-*)."""
    out = []
    for o in h.history_to_latencies(history):
        if "latency" in o and o["process"] != h.NEMESIS:
            out.append(
                {
                    "time": (o["time"] - o["latency"]) / 1e9,
                    "latency": o["latency"] / 1e6,
                    "f": o["f"],
                    "type": o["type"],
                }
            )
    return out


def rates(history: Sequence[dict], dt: float = 10.0) -> dict:
    """{(f, type): [(bucket-center, ops/sec)]} for client completions
    (perf.clj:110-130)."""
    series: dict = {}
    for o in history:
        if o["process"] == h.NEMESIS or o["type"] == h.INVOKE:
            continue
        series.setdefault((o["f"], o["type"]), []).append((o["time"] / 1e9, 1))
    return {
        key: [(bucket_scale(dt, b), len(vs) / dt) for b, vs in sorted(buckets(dt, pts).items())]
        for key, pts in series.items()
    }


def nemesis_regions(test: Mapping, history: Sequence[dict]) -> list[dict]:
    """Shaded [t0, t1] regions per nemesis family, from the test's
    ``plot.nemeses`` hints (the packages' perf maps,
    nemesis/combined.clj:8-15) or a start/stop default
    (perf.clj:132-175)."""
    specs = (test.get("plot") or {}).get("nemeses")
    if not specs:
        specs = [{"name": "nemesis", "start": {"start"}, "stop": {"stop"}, "color": "#B3BFFF"}]
    end = max((o["time"] for o in history), default=0) / 1e9
    out = []
    for spec in specs:
        for start_op, stop_op in nemesis_intervals(
            history, start_fs=tuple(spec.get("start", ())), stop_fs=tuple(spec.get("stop", ()))
        ):
            out.append(
                {
                    "t0": start_op["time"] / 1e9,
                    "t1": (stop_op["time"] / 1e9) if stop_op else end,
                    "color": spec.get("color", "#B3BFFF"),
                    "name": spec.get("name", "nemesis"),
                }
            )
    return out


# ---------------------------------------------------------------------------
# SVG rendering
# ---------------------------------------------------------------------------


class SvgPlot:
    """A small axes-and-series SVG canvas (the gnuplot role)."""

    W, H = 900, 440
    ML, MR, MT, MB = 70, 160, 30, 50

    def __init__(self, title: str, xlabel: str, ylabel: str, log_y: bool = False):
        self.title = title
        self.xlabel = xlabel
        self.ylabel = ylabel
        self.log_y = log_y
        self.xmin = self.xmax = self.ymin = self.ymax = None
        self._series: list = []  # (kind, label, color, points)
        self._regions: list = []

    # -- data ---------------------------------------------------------------

    def _see(self, x, y):
        self.xmin = x if self.xmin is None else min(self.xmin, x)
        self.xmax = x if self.xmax is None else max(self.xmax, x)
        if not self.log_y or y > 0:
            self.ymin = y if self.ymin is None else min(self.ymin, y)
            self.ymax = y if self.ymax is None else max(self.ymax, y)

    def line(self, label: str, points: Sequence[tuple], color: str):
        for x, y in points:
            self._see(x, y)
        self._series.append(("line", label, color, list(points)))

    def scatter(self, label: str, points: Sequence[tuple], color: str):
        for x, y in points:
            self._see(x, y)
        self._series.append(("scatter", label, color, list(points)))

    def region(self, t0: float, t1: float, color: str, name: str):
        self._regions.append((t0, t1, color, name))

    # -- projection ---------------------------------------------------------

    def _px(self, x: float) -> float:
        x0, x1 = self.xmin, self.xmax
        if x1 == x0:
            x1 = x0 + 1
        return self.ML + (x - x0) / (x1 - x0) * (self.W - self.ML - self.MR)

    def _py(self, y: float) -> float:
        y0, y1 = self.ymin, self.ymax
        if self.log_y:
            y0 = math.log10(max(y0, 1e-6))
            y1 = math.log10(max(y1, 1e-6))
            y = math.log10(max(y, 1e-6))
        if y1 == y0:
            y1 = y0 + 1
        return self.H - self.MB - (y - y0) / (y1 - y0) * (self.H - self.MT - self.MB)

    def _ticks(self, lo: float, hi: float, n: int = 6) -> list[float]:
        if hi <= lo:
            return [lo]
        step = 10 ** math.floor(math.log10((hi - lo) / n))
        for mult in (1, 2, 5, 10):
            if (hi - lo) / (step * mult) <= n:
                step *= mult
                break
        first = math.ceil(lo / step) * step
        out = []
        t = first
        while t <= hi + 1e-12:
            out.append(round(t, 10))
            t += step
        return out

    def _y_ticks(self) -> list[float]:
        if not self.log_y:
            return self._ticks(self.ymin, self.ymax)
        lo = math.floor(math.log10(max(self.ymin, 1e-6)))
        hi = math.ceil(math.log10(max(self.ymax, 1e-6)))
        return [10.0**e for e in range(int(lo), int(hi) + 1)]

    # -- output -------------------------------------------------------------

    def render(self) -> str:
        if self.xmin is None:
            self.xmin, self.xmax, self.ymin, self.ymax = 0, 1, 0, 1
        if self.ymin is None:
            self.ymin, self.ymax = (0.1, 1) if self.log_y else (0, 1)
        e: list[str] = []
        e.append(
            f'<svg xmlns="http://www.w3.org/2000/svg" width="{self.W}" height="{self.H}" '
            f'font-family="Helvetica,Arial,sans-serif" font-size="11">'
        )
        e.append(f'<rect width="{self.W}" height="{self.H}" fill="white"/>')
        plot_x0, plot_y0 = self.ML, self.MT
        plot_w = self.W - self.ML - self.MR
        plot_h = self.H - self.MT - self.MB
        for t0, t1, color, _name in self._regions:
            x0 = max(plot_x0, min(self._px(t0), plot_x0 + plot_w))
            x1 = max(plot_x0, min(self._px(t1), plot_x0 + plot_w))
            if x1 > x0:
                e.append(
                    f'<rect x="{x0:.1f}" y="{plot_y0}" width="{x1 - x0:.1f}" '
                    f'height="{plot_h}" fill="{color}" fill-opacity="0.35"/>'
                )
        for tx in self._ticks(self.xmin, self.xmax):
            px = self._px(tx)
            e.append(
                f'<line x1="{px:.1f}" y1="{plot_y0}" x2="{px:.1f}" y2="{plot_y0 + plot_h}" '
                f'stroke="#DDD" stroke-width="1"/>'
            )
            e.append(
                f'<text x="{px:.1f}" y="{plot_y0 + plot_h + 16}" text-anchor="middle">{tx:g}</text>'
            )
        for ty in self._y_ticks():
            py = self._py(ty)
            if py < plot_y0 - 1 or py > plot_y0 + plot_h + 1:
                continue
            e.append(
                f'<line x1="{plot_x0}" y1="{py:.1f}" x2="{plot_x0 + plot_w}" y2="{py:.1f}" '
                f'stroke="#DDD" stroke-width="1"/>'
            )
            e.append(
                f'<text x="{plot_x0 - 6}" y="{py + 4:.1f}" text-anchor="end">{ty:g}</text>'
            )
        e.append(
            f'<rect x="{plot_x0}" y="{plot_y0}" width="{plot_w}" height="{plot_h}" '
            f'fill="none" stroke="#333"/>'
        )
        for kind, _label, color, pts in self._series:
            if not pts:
                continue
            if kind == "line":
                path = " ".join(f"{self._px(x):.1f},{self._py(y):.1f}" for x, y in pts)
                e.append(
                    f'<polyline points="{path}" fill="none" stroke="{color}" stroke-width="1.5"/>'
                )
            else:
                for x, y in pts:
                    e.append(
                        f'<circle cx="{self._px(x):.1f}" cy="{self._py(y):.1f}" r="1.6" '
                        f'fill="{color}" fill-opacity="0.6"/>'
                    )
        # legend
        ly = plot_y0 + 4
        lx = plot_x0 + plot_w + 12
        seen = set()
        for kind, label, color, _pts in self._series:
            if label in seen:
                continue
            seen.add(label)
            e.append(f'<rect x="{lx}" y="{ly - 8}" width="10" height="10" fill="{color}"/>')
            e.append(f'<text x="{lx + 14}" y="{ly + 1}">{_esc(label)}</text>')
            ly += 16
        for _t0, _t1, color, name in {(None, None, r[2], r[3]) for r in self._regions}:
            e.append(
                f'<rect x="{lx}" y="{ly - 8}" width="10" height="10" fill="{color}" fill-opacity="0.35"/>'
            )
            e.append(f'<text x="{lx + 14}" y="{ly + 1}">{_esc(name)}</text>')
            ly += 16
        e.append(
            f'<text x="{(plot_x0 + plot_w / 2):.0f}" y="16" text-anchor="middle" '
            f'font-size="13" font-weight="bold">{_esc(self.title)}</text>'
        )
        e.append(
            f'<text x="{(plot_x0 + plot_w / 2):.0f}" y="{self.H - 12}" '
            f'text-anchor="middle">{_esc(self.xlabel)}</text>'
        )
        e.append(
            f'<text x="16" y="{(plot_y0 + plot_h / 2):.0f}" text-anchor="middle" '
            f'transform="rotate(-90 16 {(plot_y0 + plot_h / 2):.0f})">{_esc(self.ylabel)}</text>'
        )
        e.append("</svg>")
        return "\n".join(e)


def _shade(plot: SvgPlot, test, history):
    for r in nemesis_regions(test, history):
        plot.region(r["t0"], r["t1"], r["color"], r["name"])


def point_graph(test: Mapping, history: Sequence[dict], opts=None) -> str:
    """Raw latency scatter, colored by completion type
    (perf.clj point-graph!)."""
    plot = SvgPlot(f"{test.get('name', 'test')} latencies", "time (s)", "latency (ms)", log_y=True)
    _shade(plot, test, history)
    by_type: dict = {}
    for o in invoke_latencies(history):
        by_type.setdefault(o["type"], []).append((o["time"], max(o["latency"], 1e-3)))
    for typ, pts in sorted(by_type.items()):
        plot.scatter(typ, pts, TYPE_COLORS.get(typ, "#888"))
    return plot.render()


def quantiles_graph(
    test: Mapping,
    history: Sequence[dict],
    opts=None,
    qs: Sequence[float] = DEFAULT_QUANTILES,
    dt: float = 10.0,
) -> str:
    """Latency quantile lines per time bucket (perf.clj quantiles-graph!)."""
    plot = SvgPlot(
        f"{test.get('name', 'test')} latency quantiles", "time (s)", "latency (ms)", log_y=True
    )
    _shade(plot, test, history)
    pts = [(o["time"], max(o["latency"], 1e-3)) for o in invoke_latencies(history)]
    for i, (q, series) in enumerate(sorted(latencies_to_quantiles(dt, qs, pts).items())):
        plot.line(f"p{int(q * 100)}", series, SERIES_COLORS[i % len(SERIES_COLORS)])
    return plot.render()


def rate_graph(test: Mapping, history: Sequence[dict], opts=None, dt: float = 10.0) -> str:
    """Completion rate per (f, type) (perf.clj rate-graph!)."""
    plot = SvgPlot(f"{test.get('name', 'test')} rate", "time (s)", "ops/sec")
    _shade(plot, test, history)
    for i, ((f, typ), series) in enumerate(sorted(rates(history, dt).items(), key=repr)):
        plot.line(f"{f} {typ}", series, SERIES_COLORS[i % len(SERIES_COLORS)])
    return plot.render()


def _write(test, opts, name: str, svg: str, out: dict):
    try:
        d = store.test_dir(test)
        sub = (opts or {}).get("subdirectory")
        d = d / sub if sub else d
        d.mkdir(parents=True, exist_ok=True)
        path = Path(d) / name
        path.write_text(svg)
        out.setdefault("files", []).append(str(path))
    except (KeyError, OSError, TypeError):
        out.setdefault("svgs", {})[name] = svg


@as_checker
def _latency_graph(test, history, opts):
    out: dict = {"valid?": True}
    _write(test, opts, "latency-raw.svg", point_graph(test, history, opts), out)
    _write(test, opts, "latency-quantiles.svg", quantiles_graph(test, history, opts), out)
    return out


@as_checker
def _rate_graph(test, history, opts):
    out: dict = {"valid?": True}
    _write(test, opts, "rate.svg", rate_graph(test, history, opts), out)
    return out


def latency_graph() -> Checker:
    """Latency graphs checker (checker.clj:797-808)."""
    return _latency_graph


def rate_graph_checker() -> Checker:
    """Rate graph checker (checker.clj:810-819)."""
    return _rate_graph


def perf(opts: Mapping | None = None) -> Checker:
    """Composite perf checker: latency + rate graphs
    (checker.clj:821-829)."""
    from jepsen_tpu.checker import compose

    return compose({"latency-graph": latency_graph(), "rate-graph": rate_graph_checker()})


# ---------------------------------------------------------------------------
# Telemetry-backed checker-time artifact
# ---------------------------------------------------------------------------

VALID_BAR_COLORS = {True: "#81BF67", False: "#FF1E90", "unknown": "#FFA400"}


def checker_time_svg(rows: Sequence[tuple]) -> str:
    """Horizontal bar chart of per-checker ``check()`` wall time, colored
    by verdict.  ``rows`` is ``[(name, seconds, valid), ...]`` — the
    telemetry recording's ``checker.check`` spans."""
    rows = sorted(rows, key=lambda r: -r[1])
    bar_h, gap, ml, mr, mt = 22, 6, 170, 90, 40
    w = 760
    h = mt + len(rows) * (bar_h + gap) + 16
    vmax = max((r[1] for r in rows), default=1.0) or 1.0
    plot_w = w - ml - mr
    e = [
        f'<svg xmlns="http://www.w3.org/2000/svg" width="{w}" height="{h}" '
        f'font-family="Helvetica,Arial,sans-serif" font-size="11">',
        f'<rect width="{w}" height="{h}" fill="white"/>',
        f'<text x="{w / 2:.0f}" y="18" text-anchor="middle" font-size="13" '
        f'font-weight="bold">checker time (telemetry)</text>',
    ]
    for i, (name, seconds, valid) in enumerate(rows):
        y = mt + i * (bar_h + gap)
        bw = max(1.0, seconds / vmax * plot_w)
        color = VALID_BAR_COLORS.get(valid, "#888")
        e.append(
            f'<text x="{ml - 8}" y="{y + bar_h - 7}" text-anchor="end">'
            f"{_esc(str(name))}</text>"
        )
        e.append(
            f'<rect x="{ml}" y="{y}" width="{bw:.1f}" height="{bar_h}" '
            f'fill="{color}"/>'
        )
        e.append(
            f'<text x="{ml + bw + 6:.1f}" y="{y + bar_h - 7}">'
            f"{seconds:.3f}s</text>"
        )
    e.append("</svg>")
    return "\n".join(e)


def checker_times_from_events(events: Sequence[Mapping]) -> list[tuple]:
    """Aggregate a telemetry event stream's checker.check spans into
    ``(name, total seconds, last verdict)`` rows."""
    agg: dict = {}
    for ev in events:
        if ev.get("type") != "span" or ev.get("name") != "checker.check":
            continue
        attrs = ev.get("attrs") or {}
        name = str(attrs.get("checker", "?"))
        sec, valid = agg.get(name, (0.0, None))
        agg[name] = (sec + float(ev.get("dur") or 0.0), attrs.get("valid", valid))
    return [(n, s, v) for n, (s, v) in agg.items()]


def write_checker_times(test: Mapping, events: Sequence[Mapping], opts=None):
    """Write ``checker-times.svg`` into the test's store dir — the
    telemetry-backed "where did analysis time go" artifact, next to the
    latency/rate graphs.  Returns the path, or None without data/store."""
    rows = checker_times_from_events(events)
    if not rows or not (test.get("name") and test.get("start-time-str")):
        return None
    try:
        d = store.test_dir(test)
        sub = (opts or {}).get("subdirectory")
        d = d / sub if sub else d
        d.mkdir(parents=True, exist_ok=True)
        path = Path(d) / "checker-times.svg"
        path.write_text(checker_time_svg(rows))
        return path
    except OSError:
        return None
