"""The reference's fold-style checkers: set, set-full, queue, total-queue,
unique-ids, counter, log-file-pattern (checker.clj:218-881).

These are cheap O(n) host-side folds; they pin the result-map vocabulary the
TPU checkers must also speak.
"""

from __future__ import annotations

import dataclasses
import math
import re
from collections import Counter as Multiset
from pathlib import Path
from typing import Any

from jepsen_tpu import history as h
from jepsen_tpu import models
from jepsen_tpu.checker import Checker, UNKNOWN, merge_valid
from jepsen_tpu.utils import integer_interval_set_str, real_pmap


class SetChecker(Checker):
    """:add ops followed by a final :read of the whole set
    (checker.clj:240-291): every acknowledged add must be present, and
    nothing may appear that was never attempted."""

    def check(self, test, history, opts):
        attempts = {o["value"] for o in history if h.is_invoke(o) and o["f"] == "add"}
        adds = {o["value"] for o in history if h.is_ok(o) and o["f"] == "add"}
        final_read = None
        for o in history:
            if h.is_ok(o) and o["f"] == "read":
                final_read = o["value"]
        if final_read is None:
            return {"valid?": UNKNOWN, "error": "Set was never read"}
        final = set(final_read)
        ok = final & attempts
        unexpected = final - attempts
        lost = adds - final
        recovered = ok - adds
        return {
            "valid?": not lost and not unexpected,
            "attempt-count": len(attempts),
            "acknowledged-count": len(adds),
            "ok-count": len(ok),
            "lost-count": len(lost),
            "recovered-count": len(recovered),
            "unexpected-count": len(unexpected),
            "ok": integer_interval_set_str(ok),
            "lost": integer_interval_set_str(lost),
            "unexpected": integer_interval_set_str(unexpected),
            "recovered": integer_interval_set_str(recovered),
        }


def set_checker() -> Checker:
    return SetChecker()


# ---------------------------------------------------------------------------
# set-full: per-element lifecycle analysis (checker.clj:294-592)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _Element:
    """Lifecycle state of one element (checker.clj:313-338):
    known = the op that first proved the element exists (add completion or
    first observing read); last_present/last_absent = the latest read
    *invocations* that did/didn't observe it."""

    element: Any
    known: dict | None = None
    last_present: dict | None = None
    last_absent: dict | None = None

    def on_add_complete(self, op):
        if op["type"] == h.OK and self.known is None:
            self.known = op

    def on_read_present(self, inv, op):
        if self.known is None:
            self.known = op
        if self.last_present is None or self.last_present["index"] < inv["index"]:
            self.last_present = inv

    def on_read_absent(self, inv, op):
        if self.last_absent is None or self.last_absent["index"] < inv["index"]:
            self.last_absent = inv


def _idx(op, default=-1):
    return op["index"] if op is not None else default


def _element_results(e: _Element) -> dict:
    """checker.clj:346-405: classify one element as stable/lost/never-read
    and compute its stabilization/loss latency."""
    stable = e.last_present is not None and _idx(e.last_absent) < _idx(e.last_present)
    lost = (
        e.known is not None
        and e.last_absent is not None
        and _idx(e.last_present) < _idx(e.last_absent)
        and _idx(e.known) < _idx(e.last_absent)
    )
    known_time = e.known["time"] if e.known else None
    stable_time = (e.last_absent["time"] + 1 if e.last_absent else 0) if stable else None
    lost_time = (e.last_present["time"] + 1 if e.last_present else 0) if lost else None
    to_ms = lambda ns: int(ns // 1_000_000)
    return {
        "element": e.element,
        "outcome": "stable" if stable else ("lost" if lost else "never-read"),
        "stable-latency": to_ms(max(0, stable_time - known_time)) if stable else None,
        "lost-latency": to_ms(max(0, lost_time - known_time)) if lost else None,
        "known": e.known,
        "last-absent": e.last_absent,
    }


def frequency_distribution(points, values) -> dict | None:
    """Percentiles (0–1) of a collection (checker.clj:407-419)."""
    s = sorted(values)
    if not s:
        return None
    n = len(s)
    return {p: s[min(n - 1, int(math.floor(n * p)))] for p in points}


class SetFullChecker(Checker):
    """Rigorous per-element set analysis (checker.clj:421-592).

    Tracks, for every added element, when it became known, the last read
    that saw it and the last that didn't; classifies each as stable / lost /
    never-read and reports stabilization latency quantiles.  With
    ``linearizable=True`` stale (eventually-visible) elements also fail.

    Note: the reference's duplicate detection (checker.clj:560-566) compares
    ``(< v 1)`` and so never fires; we implement the evident intent
    (multiplicity > 1 in a single read)."""

    def __init__(self, linearizable: bool = False):
        self.linearizable = linearizable

    def check(self, test, history, opts):
        elements: dict[Any, _Element] = {}
        reads: dict[Any, dict] = {}  # process -> read invocation
        dups: dict[Any, int] = {}
        for op in history:
            if not h.is_client_op(op):
                continue
            f, v, p = op["f"], op["value"], op["process"]
            if f == "add":
                if h.is_invoke(op):
                    elements.setdefault(v, _Element(v))
                elif v in elements:
                    elements[v].on_add_complete(op)
            elif f == "read":
                t = op["type"]
                if t == h.INVOKE:
                    reads[p] = op
                elif t == h.FAIL:
                    reads.pop(p, None)
                elif t == h.OK:
                    inv = reads.get(p)
                    if inv is None:
                        continue
                    counts = Multiset(v)
                    for k, c in counts.items():
                        if c > 1:
                            dups[k] = max(dups.get(k, 0), c)
                    present = set(v)
                    for el, state in elements.items():
                        if el in present:
                            state.on_read_present(inv, op)
                        else:
                            state.on_read_absent(inv, op)
        rs = [_element_results(e) for _, e in sorted(elements.items(), key=lambda kv: str(kv[0]))]
        outcomes: dict[str, list] = {}
        for r in rs:
            outcomes.setdefault(r["outcome"], []).append(r)
        stable = outcomes.get("stable", [])
        lost = outcomes.get("lost", [])
        never_read = outcomes.get("never-read", [])
        stale = [r for r in stable if r["stable-latency"] and r["stable-latency"] > 0]
        if lost:
            valid = False
        elif not stable:
            valid = UNKNOWN
        elif self.linearizable and stale:
            valid = False
        else:
            valid = True
        points = [0, 0.5, 0.95, 0.99, 1]
        out = {
            "valid?": (valid if not dups else False),
            "attempt-count": len(rs),
            "stable-count": len(stable),
            "lost-count": len(lost),
            "lost": sorted(r["element"] for r in lost),
            "never-read-count": len(never_read),
            "never-read": sorted(r["element"] for r in never_read),
            "stale-count": len(stale),
            "stale": sorted(r["element"] for r in stale),
            "worst-stale": sorted(stale, key=lambda r: -r["stable-latency"])[:8],
            "duplicated-count": len(dups),
            "duplicated": dict(sorted(dups.items())),
        }
        sl = [r["stable-latency"] for r in rs if r["stable-latency"] is not None]
        ll = [r["lost-latency"] for r in rs if r["lost-latency"] is not None]
        if sl:
            out["stable-latencies"] = frequency_distribution(points, sl)
        if ll:
            out["lost-latencies"] = frequency_distribution(points, ll)
        return out


def set_full(linearizable: bool = False) -> Checker:
    return SetFullChecker(linearizable)


# ---------------------------------------------------------------------------
# Queues
# ---------------------------------------------------------------------------


class QueueChecker(Checker):
    """Fold a queue model over enqueue-invokes + dequeue-oks
    (checker.clj:218-238): every dequeue must come from somewhere."""

    def __init__(self, model: models.Model):
        self.model = model

    def check(self, test, history, opts):
        m = self.model
        for op in history:
            take = (h.is_invoke(op) if op["f"] == "enqueue" else h.is_ok(op) if op["f"] == "dequeue" else False)
            if take:
                m = m.step(op)
                if models.is_inconsistent(m):
                    return {"valid?": False, "error": m.msg}
        return {"valid?": True, "final-queue": m}


def queue(model: models.Model) -> Checker:
    return QueueChecker(model)


def expand_queue_drain_ops(history) -> list:
    """Expand ok :drain ops (value = list of elements) into synthetic
    dequeue invoke/ok pairs (checker.clj:594-626)."""
    out = []
    for op in history:
        if op["f"] != "drain":
            out.append(op)
        elif h.is_invoke(op) or h.is_fail(op):
            continue
        elif h.is_ok(op):
            for element in op["value"]:
                out.append({**op, "type": h.INVOKE, "f": "dequeue", "value": None})
                out.append({**op, "type": h.OK, "f": "dequeue", "value": element})
        else:
            raise ValueError(f"can't handle a crashed drain operation: {op!r}")
    return out


class TotalQueueChecker(Checker):
    """What goes in must come out — multiset accounting over enqueues and
    dequeues, requires a draining read (checker.clj:628-687)."""

    def check(self, test, history, opts):
        history = expand_queue_drain_ops(history)
        attempts = Multiset(o["value"] for o in history if h.is_invoke(o) and o["f"] == "enqueue")
        enqueues = Multiset(o["value"] for o in history if h.is_ok(o) and o["f"] == "enqueue")
        dequeues = Multiset(o["value"] for o in history if h.is_ok(o) and o["f"] == "dequeue")
        ok = dequeues & attempts
        unexpected = Multiset({k: c for k, c in dequeues.items() if k not in attempts})
        duplicated = dequeues - attempts - unexpected
        lost = enqueues - dequeues
        recovered = ok - enqueues
        return {
            "valid?": not lost and not unexpected,
            "attempt-count": sum(attempts.values()),
            "acknowledged-count": sum(enqueues.values()),
            "ok-count": sum(ok.values()),
            "unexpected-count": sum(unexpected.values()),
            "duplicated-count": sum(duplicated.values()),
            "lost-count": sum(lost.values()),
            "recovered-count": sum(recovered.values()),
            "lost": dict(lost),
            "unexpected": dict(unexpected),
            "duplicated": dict(duplicated),
            "recovered": dict(recovered),
        }


def total_queue() -> Checker:
    return TotalQueueChecker()


class UniqueIdsChecker(Checker):
    """A unique-id generator must emit distinct values (checker.clj:689-734)."""

    def check(self, test, history, opts):
        attempted = sum(1 for o in history if h.is_invoke(o) and o["f"] == "generate")
        acks = [o["value"] for o in history if h.is_ok(o) and o["f"] == "generate"]
        counts = Multiset(acks)
        dups = {k: c for k, c in counts.items() if c > 1}
        rng = [min(acks), max(acks)] if acks else [None, None]
        worst = dict(sorted(dups.items(), key=lambda kv: -kv[1])[:48])
        return {
            "valid?": not dups,
            "attempted-count": attempted,
            "acknowledged-count": len(acks),
            "duplicated-count": len(dups),
            "duplicated": worst,
            "range": rng,
        }


def unique_ids() -> Checker:
    return UniqueIdsChecker()


class CounterChecker(Checker):
    """Monotonic counter bounds check (checker.clj:737-795): every read must
    fall between the sum of acknowledged adds (lower) and the sum of
    attempted adds (upper) as they stood over the read's window."""

    def check(self, test, history, opts):
        pairs = h.pair_index(history)
        lower = 0
        upper = 0
        pending_reads: dict[Any, list] = {}  # process -> [lower, read-value]
        reads = []
        for i, op in enumerate(history):
            f, t, p = op["f"], op["type"], op["process"]
            if f == "read":
                if t == h.INVOKE:
                    # Value observed at completion (the reference pre-fills
                    # it via knossos.history/complete; we use the pair index).
                    j = int(pairs[i])
                    v = history[j]["value"] if j != -1 and history[j]["type"] == h.OK else None
                    pending_reads[p] = [lower, v]
                elif t == h.OK:
                    r = pending_reads.pop(p, None)
                    if r is not None:
                        reads.append([r[0], r[1], upper])
            elif f == "add":
                if t == h.INVOKE:
                    assert op["value"] >= 0, "counter checker assumes non-negative adds"
                    # Skip adds that definitely failed (reference drops
                    # :fails? invocations after history/complete).
                    j = int(pairs[i])
                    if not (j != -1 and history[j]["type"] == h.FAIL):
                        upper += op["value"]
                elif t == h.OK:
                    lower += op["value"]
        errors = [r for r in reads if not (r[0] <= r[1] <= r[2])]
        return {"valid?": not errors, "reads": reads, "errors": errors}


def counter() -> Checker:
    return CounterChecker()


class LogFilePattern(Checker):
    """Grep each node's downloaded log for a pattern; matches fail the test
    (checker.clj:839-881).  Searches ``<store-dir>/<node>/<filename>``;
    the store directory comes from ``test["dir"]`` or ``opts["dir"]``."""

    def __init__(self, pattern: str, filename: str):
        self.pattern = re.compile(pattern)
        self.filename = filename

    def check(self, test, history, opts):
        base = opts.get("dir") or test.get("dir")
        if base is None:
            from jepsen_tpu import store

            base = store.test_path(test)
        matches = []

        def search(node):
            path = Path(base) / str(node) / self.filename
            if not path.exists():
                return []
            found = []
            with open(path, errors="replace") as fh:
                for line in fh:
                    if self.pattern.search(line):
                        found.append({"node": node, "line": line.rstrip("\n")})
            return found

        for result in real_pmap(search, list(test.get("nodes", []))):
            matches.extend(result)
        return {"valid?": not matches, "count": len(matches), "matches": matches}


def log_file_pattern(pattern: str, filename: str) -> Checker:
    return LogFilePattern(pattern, filename)
