"""Small shared utilities (reference: jepsen/src/jepsen/util.clj, 945 LoC).

Only the pieces the rebuild actually needs; concurrency helpers follow the
reference's semantics (real-pmap's "most interesting exception" selection,
util.clj:65-77) on Python threads.
"""

from __future__ import annotations

import concurrent.futures
import random
import threading
import time as _time
from typing import Any, Callable, Iterable, Sequence


class JepsenTimeout(Exception):
    """Raised when `timeout` expires (reference: util.clj:370 returns a
    default instead; we raise and let callers catch)."""


def real_pmap(f: Callable, xs: Sequence) -> list:
    """Apply ``f`` to every element on its own thread and wait for all.

    Mirrors ``jepsen.util/real-pmap`` (util.clj:65-77): unlike a pooled map,
    every element gets a real thread (node fan-out must not deadlock behind a
    small pool).  If several threads throw, the "most interesting" exception
    wins: the first non-interrupt error, matching the reference's
    real-pmap-helper selection.
    """
    if not xs:
        return []
    results: list[Any] = [None] * len(xs)
    errors: list[BaseException | None] = [None] * len(xs)

    def run(i, x):
        try:
            results[i] = f(x)
        except BaseException as e:  # noqa: BLE001 - must capture to re-raise
            errors[i] = e

    threads = [threading.Thread(target=run, args=(i, x), daemon=True) for i, x in enumerate(xs)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    interesting = [e for e in errors if e is not None and not isinstance(e, KeyboardInterrupt)]
    if interesting:
        raise interesting[0]
    for e in errors:
        if e is not None:
            raise e
    return results


def bounded_pmap(f: Callable, xs: Sequence, limit: int | None = None) -> list:
    """Pooled parallel map (dom-top bounded-pmap equivalent; used by
    independent/checker, independent.clj:285-307)."""
    if not xs:
        return []
    limit = limit or max(2, (len(xs) + 1) // 2)
    with concurrent.futures.ThreadPoolExecutor(max_workers=limit) as ex:
        return list(ex.map(f, xs))


def majority(n: int) -> int:
    """Smallest majority of n (util.clj:84): majority(5) = 3, majority(4) = 3."""
    return n // 2 + 1


def random_nonempty_subset(coll: Sequence, rng: random.Random | None = None) -> list:
    """A random non-empty subset (util.clj:45)."""
    rng = rng or random
    coll = list(coll)
    k = rng.randint(1, len(coll))
    return rng.sample(coll, k)


# ---------------------------------------------------------------------------
# Time
# ---------------------------------------------------------------------------

_relative_origin = threading.local()


def linear_time_nanos() -> int:
    """Monotonic nanoseconds (util.clj:328)."""
    return _time.monotonic_ns()


class relative_time:
    """Context manager establishing a nanosecond time origin for a test run
    (util.clj:337-348 with-relative-time).  Process-global, like the
    reference's var."""

    origin: int | None = None

    def __enter__(self):
        relative_time.origin = linear_time_nanos()
        return self

    def __exit__(self, *exc):
        relative_time.origin = None
        return False


def relative_time_nanos() -> int:
    origin = relative_time.origin
    if origin is None:
        raise RuntimeError("relative_time_nanos called outside relative_time scope")
    return linear_time_nanos() - origin


def timeout(seconds: float, f: Callable, *args, default=JepsenTimeout):
    """Run ``f`` with a wall-clock budget on a helper thread (util.clj:370).

    Returns ``f()``'s value, or ``default`` if it is not the JepsenTimeout
    class, else raises JepsenTimeout.  The worker thread is abandoned (Python
    threads can't be killed), matching the reference's interrupt-besteffort
    semantics closely enough for harness use.
    """
    with concurrent.futures.ThreadPoolExecutor(max_workers=1) as ex:
        fut = ex.submit(f, *args)
        try:
            return fut.result(timeout=seconds)
        except concurrent.futures.TimeoutError:
            fut.cancel()
            if default is JepsenTimeout:
                raise JepsenTimeout(f"timed out after {seconds}s") from None
            return default


def await_fn(
    f: Callable,
    retry_interval: float = 1.0,
    log_interval: float = 10.0,
    timeout_s: float = 60.0,
    log_message: str | None = None,
):
    """Invoke ``f`` until it stops throwing, then return its value
    (util.clj:383-424).  Raises JepsenTimeout when the budget expires."""
    deadline = _time.monotonic() + timeout_s
    last_log = _time.monotonic()
    while True:
        try:
            return f()
        except Exception as e:  # noqa: BLE001
            now = _time.monotonic()
            if now + retry_interval > deadline:
                raise JepsenTimeout(f"await_fn timed out: {e}") from e
            if log_message and now - last_log >= log_interval:
                last_log = now
            _time.sleep(retry_interval)


def with_retry(f: Callable, retries: int = 5, backoff: float = 0.1):
    """Call ``f`` with up to ``retries`` retries and fixed backoff
    (dom-top with-retry as used by control/retry.clj:15-33)."""
    err: Exception | None = None
    for _ in range(retries + 1):
        try:
            return f()
        except Exception as e:  # noqa: BLE001
            err = e
            _time.sleep(backoff)
    raise err  # type: ignore[misc]


def fixed_point(f: Callable, x):
    """Iterate f until a fixed point (util.clj:927)."""
    while True:
        x2 = f(x)
        if x2 == x:
            return x
        x = x2


# ---------------------------------------------------------------------------
# History-adjacent helpers
# ---------------------------------------------------------------------------


def nemesis_intervals(history: Iterable[dict], start_fs=("start",), stop_fs=("stop",)) -> list[tuple]:
    """Pair nemesis start/stop completions into [start-op, stop-op] intervals
    (util.clj:736-783).  Open intervals get a None stop."""
    from jepsen_tpu import history as h

    intervals: list[tuple] = []
    open_ops: list[dict] = []
    for o in history:
        if o["process"] != h.NEMESIS or o["type"] != h.INFO and o["type"] != h.OK:
            continue
        if o["f"] in start_fs:
            open_ops.append(o)
        elif o["f"] in stop_fs:
            for s in open_ops:
                intervals.append((s, o))
            open_ops = []
    intervals.extend((s, None) for s in open_ops)
    return intervals


def integer_interval_set_str(xs: Iterable[int]) -> str:
    """Compact string for an integer set: #{1-3 5} (util.clj:629)."""
    xs = sorted(set(xs))
    if not xs:
        return "#{}"
    parts = []
    lo = prev = xs[0]
    for x in xs[1:]:
        if x == prev + 1:
            prev = x
            continue
        parts.append(f"{lo}" if lo == prev else f"{lo}-{prev}")
        lo = prev = x
    parts.append(f"{lo}" if lo == prev else f"{lo}-{prev}")
    return "#{" + " ".join(parts) + "}"
