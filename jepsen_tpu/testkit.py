"""Self-test scaffolding: a no-op base test map and an in-memory backend.

Mirrors ``jepsen.tests`` (reference: jepsen/src/jepsen/tests.clj): the
``noop_test`` base map (tests.clj:14-26), plus an in-memory ``AtomDB`` /
``AtomClient`` CAS register over a lock-guarded cell (tests.clj:29-67).
Combined with the dummy remote (control layer), the *entire* pipeline —
interpreter, history, checker, store — runs on one machine with no cluster
(SURVEY.md §4.3; core_test.clj:62-120 is the pattern).
"""

from __future__ import annotations

import threading
from typing import Any, Mapping

from jepsen_tpu import client as jclient


def noop_test(**overrides) -> dict:
    """A test map with everything stubbed (tests.clj:14-26)."""
    base: dict[str, Any] = {
        "name": "noop",
        "nodes": ["n1", "n2", "n3", "n4", "n5"],
        "concurrency": 5,
        "client": jclient.noop(),
        "nemesis": None,
        "generator": None,
        "checker": None,
        "os": None,
        "db": None,
        "ssh": {"dummy?": True},
        "start-time": None,
    }
    base.update(overrides)
    return base


class AtomCell:
    """The shared 'database': one lock-guarded value (tests.clj:29-34)."""

    def __init__(self, value=None):
        self.lock = threading.Lock()
        self.value = value

    def read(self):
        with self.lock:
            return self.value

    def write(self, v):
        with self.lock:
            self.value = v
            return True

    def cas(self, old, new) -> bool:
        with self.lock:
            if self.value == old:
                self.value = new
                return True
            return False


class AtomClient(jclient.Client):
    """CAS-register client over an AtomCell (tests.clj:36-67).

    Ops: {:f :read} / {:f :write, :value v} / {:f :cas, :value [old new]}.
    """

    reusable = False

    def __init__(self, cell: AtomCell):
        self.cell = cell
        self.opened = False
        #: bookkeeping asserted by tests (core_test.clj:62-120)
        self.stats = {"opens": 0, "closes": 0}

    def open(self, test, node):
        c = type(self)(self.cell)  # subclass-friendly: wrappers survive open
        c.stats = self.stats
        c.opened = True
        self.stats["opens"] += 1
        return c

    def invoke(self, test, op):
        f = op["f"]
        if f == "read":
            return {**op, "type": "ok", "value": self.cell.read()}
        if f == "write":
            self.cell.write(op["value"])
            return {**op, "type": "ok"}
        if f == "cas":
            old, new = op["value"]
            ok = self.cell.cas(old, new)
            return {**op, "type": "ok" if ok else "fail"}
        raise ValueError(f"atom client doesn't understand :f {f!r}")

    def close(self, test):
        if self.opened:
            self.stats["closes"] += 1
            self.opened = False


def atom_client(initial=None) -> AtomClient:
    return AtomClient(AtomCell(initial))
