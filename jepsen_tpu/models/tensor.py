"""Vectorized model step functions for the TPU checker kernels.

The CPU oracle models (jepsen_tpu.models) are arbitrary Python objects; the
TPU WGL kernel needs models expressed as pure jnp functions over packed
int32 state (SURVEY.md §7 hard-part #2):

    step(state, f, v1, v2) -> (state', legal)

operating elementwise on arbitrary-shaped arrays, where ``f`` is a
model-specific small-int code and ``v1``/``v2`` are the packed value
columns (jepsen_tpu.history.NIL for absent).  State must fit an int32
scalar: registers/mutex/counter trivially, the fifo queue via a bounded
packed encoding gated by a precheck (histories outside its envelope —
and models with genuinely unbounded state like the unordered queue —
fall back to the CPU oracle through the "competition" front-end).
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax.numpy as jnp
import numpy as np

from jepsen_tpu.history import NIL

INT_NIL = int(NIL)


@dataclasses.dataclass(frozen=True)
class TensorModel:
    """A vectorizable model: f-code vocabulary + elementwise step fn."""

    name: str
    f_codes: dict  # f name -> small int code
    step: Callable  # (state, f, v1, v2) -> (state', legal)
    encode_state: Callable  # python model instance -> int32 initial state
    #: optional: raise ValueError when a history's ops don't fit this
    #: model's packed-state representation (callers translate to
    #: NotTensorizable and fall back to the CPU oracle)
    precheck: Callable | None = None


def _encode_register_state(model) -> int:
    v = getattr(model, "value", None)
    return INT_NIL if v is None else int(v)


def _register_step(state, f, v1, v2):
    """register/cas-register step. f: 0=read, 1=write, 2=cas.

    A read of NIL (value unknown) is always legal and leaves state alone; a
    read of v requires state == v.  cas [old, new] requires state == old.
    """
    is_read = f == 0
    is_write = f == 1
    is_cas = f == 2
    read_legal = (v1 == INT_NIL) | (state == v1)
    cas_legal = state == v1
    legal = jnp.where(is_read, read_legal, jnp.where(is_cas, cas_legal, is_write))
    state2 = jnp.where(is_write, v1, jnp.where(is_cas & cas_legal, v2, state))
    return state2, legal


def _plain_register_step(state, f, v1, v2):
    state2, legal = _register_step(state, f, v1, v2)
    return state2, legal & (f != 2)  # no cas on the plain register


def _mutex_step(state, f, v1, v2):
    """mutex step. f: 0=acquire, 1=release. state: 0 free, 1 locked."""
    is_acq = f == 0
    legal = jnp.where(is_acq, state == 0, state == 1)
    state2 = jnp.where(legal, jnp.where(is_acq, 1, 0), state)
    return state2, legal


def _counter_step(state, f, v1, v2):
    """counter step. f: 0=read, 1=add. NIL-state counters start at 0."""
    is_read = f == 0
    legal = jnp.where(is_read, (v1 == INT_NIL) | (state == v1), v1 >= 0)
    state2 = jnp.where(is_read, state, state + jnp.where(v1 == INT_NIL, 0, v1))
    return state2, legal


def _encode_mutex_state(model) -> int:
    return 1 if getattr(model, "locked", False) else 0


def _encode_counter_state(model) -> int:
    return int(getattr(model, "value", 0) or 0)


# ---------------------------------------------------------------------------
# FIFO queue: the whole queue packed into one int32.
#
# Layout: bits [0..2] = length (0..7 — the field is 3 bits, which is
# exactly why the capacity is 7); slot i (head = slot 0) at bits
# [3 + 3i .. 5 + 3i], storing value+1 (so 0 = empty).  Capacity 7 slots,
# values 0..6 — histories that can't fit (checked by _fifo_precheck)
# refuse to tensorize and fall back to the CPU oracle, so a packed-state
# overflow can never refute a valid history.
# ---------------------------------------------------------------------------

FIFO_CAP = 7
FIFO_MAX_VALUE = 6


def _fifo_step(state, f, v1, v2):
    """fifo-queue step. f: 0=enqueue, 1=dequeue (of the observed head)."""
    length = state & 7
    vals = state >> 3  # stored v+1, head in the low 3 bits
    head = vals & 7
    is_enq = f == 0
    enq_legal = (length < FIFO_CAP) & (v1 >= 0) & (v1 <= FIFO_MAX_VALUE)
    enq_vals = vals | ((v1 + 1) << (3 * length))
    enq_state = (enq_vals << 3) | (length + 1)
    deq_legal = (length > 0) & (head == v1 + 1)
    deq_state = ((vals >> 3) << 3) | jnp.maximum(length - 1, 0)
    legal = jnp.where(is_enq, enq_legal, deq_legal)
    state2 = jnp.where(is_enq & enq_legal, enq_state,
                       jnp.where(~is_enq & deq_legal, deq_state, state))
    return state2, legal


def _encode_fifo_state(model) -> int:
    items = tuple(getattr(model, "items", ()) or ())
    if len(items) > FIFO_CAP:
        raise ValueError(f"initial queue longer than {FIFO_CAP}")
    state = len(items)
    for i, v in enumerate(items):
        if not isinstance(v, int) or not 0 <= v <= FIFO_MAX_VALUE:
            raise ValueError(f"queue value {v!r} outside 0..{FIFO_MAX_VALUE}")
        state |= (v + 1) << (3 + 3 * i)
    return state


def _fifo_precheck(model, ops):
    """Sound tensorization gate: every value must fit 0..6, and the queue
    can never need more than FIFO_CAP slots in ANY linearization — bounded
    by initial length + total enqueues (dequeues only shrink it)."""
    items = tuple(getattr(model, "items", ()) or ())
    enqueues = 0
    for op in ops:
        v = op.get("value")
        if not isinstance(v, int) or isinstance(v, bool) or not 0 <= v <= FIFO_MAX_VALUE:
            raise ValueError(f"queue value {v!r} outside 0..{FIFO_MAX_VALUE}")
        if op["f"] == "enqueue":
            enqueues += 1
    if len(items) + enqueues > FIFO_CAP:
        raise ValueError(
            f"{len(items)} initial + {enqueues} enqueued items exceed the "
            f"packed capacity {FIFO_CAP}"
        )


REGISTRY = {
    "cas-register": TensorModel(
        "cas-register",
        {"read": 0, "write": 1, "cas": 2},
        _register_step,
        _encode_register_state,
    ),
    "register": TensorModel(
        "register",
        {"read": 0, "write": 1},
        _plain_register_step,
        _encode_register_state,
    ),
    "mutex": TensorModel(
        "mutex", {"acquire": 0, "release": 1}, _mutex_step, _encode_mutex_state
    ),
    "counter": TensorModel(
        "counter", {"read": 0, "add": 1}, _counter_step, _encode_counter_state
    ),
    "fifo-queue": TensorModel(
        "fifo-queue",
        {"enqueue": 0, "dequeue": 1},
        _fifo_step,
        _encode_fifo_state,
        precheck=_fifo_precheck,
    ),
}


def tensor_model_for(model) -> TensorModel | None:
    return REGISTRY.get(getattr(model, "name", None))
