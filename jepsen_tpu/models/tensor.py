"""Vectorized model step functions for the TPU checker kernels.

The CPU oracle models (jepsen_tpu.models) are arbitrary Python objects; the
TPU WGL kernel needs models expressed as pure jnp functions over packed
int32 state (SURVEY.md §7 hard-part #2):

    step(state, f, v1, v2) -> (state', legal)

operating elementwise on arbitrary-shaped arrays, where ``f`` is a
model-specific small-int code and ``v1``/``v2`` are the packed value
columns (jepsen_tpu.history.NIL for absent).  Models whose state doesn't
fit an int32 scalar (queues) are not tensorizable here; the linearizable
front-end's "competition" algorithm falls back to the CPU oracle for them.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax.numpy as jnp
import numpy as np

from jepsen_tpu.history import NIL

INT_NIL = int(NIL)


@dataclasses.dataclass(frozen=True)
class TensorModel:
    """A vectorizable model: f-code vocabulary + elementwise step fn."""

    name: str
    f_codes: dict  # f name -> small int code
    step: Callable  # (state, f, v1, v2) -> (state', legal)
    encode_state: Callable  # python model instance -> int32 initial state


def _encode_register_state(model) -> int:
    v = getattr(model, "value", None)
    return INT_NIL if v is None else int(v)


def _register_step(state, f, v1, v2):
    """register/cas-register step. f: 0=read, 1=write, 2=cas.

    A read of NIL (value unknown) is always legal and leaves state alone; a
    read of v requires state == v.  cas [old, new] requires state == old.
    """
    is_read = f == 0
    is_write = f == 1
    is_cas = f == 2
    read_legal = (v1 == INT_NIL) | (state == v1)
    cas_legal = state == v1
    legal = jnp.where(is_read, read_legal, jnp.where(is_cas, cas_legal, is_write))
    state2 = jnp.where(is_write, v1, jnp.where(is_cas & cas_legal, v2, state))
    return state2, legal


def _plain_register_step(state, f, v1, v2):
    state2, legal = _register_step(state, f, v1, v2)
    return state2, legal & (f != 2)  # no cas on the plain register


def _mutex_step(state, f, v1, v2):
    """mutex step. f: 0=acquire, 1=release. state: 0 free, 1 locked."""
    is_acq = f == 0
    legal = jnp.where(is_acq, state == 0, state == 1)
    state2 = jnp.where(legal, jnp.where(is_acq, 1, 0), state)
    return state2, legal


def _counter_step(state, f, v1, v2):
    """counter step. f: 0=read, 1=add. NIL-state counters start at 0."""
    is_read = f == 0
    legal = jnp.where(is_read, (v1 == INT_NIL) | (state == v1), v1 >= 0)
    state2 = jnp.where(is_read, state, state + jnp.where(v1 == INT_NIL, 0, v1))
    return state2, legal


def _encode_mutex_state(model) -> int:
    return 1 if getattr(model, "locked", False) else 0


def _encode_counter_state(model) -> int:
    return int(getattr(model, "value", 0) or 0)


REGISTRY = {
    "cas-register": TensorModel(
        "cas-register",
        {"read": 0, "write": 1, "cas": 2},
        _register_step,
        _encode_register_state,
    ),
    "register": TensorModel(
        "register",
        {"read": 0, "write": 1},
        _plain_register_step,
        _encode_register_state,
    ),
    "mutex": TensorModel(
        "mutex", {"acquire": 0, "release": 1}, _mutex_step, _encode_mutex_state
    ),
    "counter": TensorModel(
        "counter", {"read": 0, "add": 1}, _counter_step, _encode_counter_state
    ),
}


def tensor_model_for(model) -> TensorModel | None:
    return REGISTRY.get(getattr(model, "name", None))
