"""Consistency models: pure state machines histories are checked against.

Equivalent of ``knossos.model`` (dep of the reference, used at
checker.clj:233 and tests/linearizable_register.clj:38): a model's
``step(op)`` returns the successor model, or an ``Inconsistent`` describing
why the op is illegal from this state.

Models are immutable and hashable — WGL configuration dedup relies on
structural equality.  The TPU kernels don't use these objects; they use the
vectorized step functions in ``jepsen_tpu.models.tensor`` (registered under
the same names), with these as the differential-testing oracle.
"""

from __future__ import annotations

import dataclasses
from typing import Any, ClassVar


@dataclasses.dataclass(frozen=True)
class Inconsistent:
    msg: str

    def step(self, op) -> "Inconsistent":
        return self


def inconsistent(msg: str) -> Inconsistent:
    return Inconsistent(msg)


def is_inconsistent(m) -> bool:
    return isinstance(m, Inconsistent)


class Model:
    """Base model protocol. Subclasses are frozen dataclasses."""

    name: ClassVar[str] = "model"

    def step(self, op) -> "Model | Inconsistent":
        raise NotImplementedError


@dataclasses.dataclass(frozen=True)
class Register(Model):
    """A read/write register (knossos.model/register)."""

    value: Any = None
    name: ClassVar[str] = "register"

    def step(self, op):
        f, v = op["f"], op["value"]
        if f == "write":
            return Register(v)
        if f == "read":
            if v is None or v == self.value:
                return self
            return inconsistent(f"can't read {v!r} from register {self.value!r}")
        raise ValueError(f"register cannot handle op f={f!r}")


@dataclasses.dataclass(frozen=True)
class CASRegister(Model):
    """A register supporting read/write/cas ops; cas value is [old, new]
    (knossos.model/cas-register)."""

    value: Any = None
    name: ClassVar[str] = "cas-register"

    def step(self, op):
        f, v = op["f"], op["value"]
        if f == "write":
            return CASRegister(v)
        if f == "cas":
            if v is None:
                return inconsistent("cas with nil value")
            old, new = v
            if old == self.value:
                return CASRegister(new)
            return inconsistent(f"can't CAS {self.value!r} from {old!r} to {new!r}")
        if f == "read":
            if v is None or v == self.value:
                return self
            return inconsistent(f"can't read {v!r} from register {self.value!r}")
        raise ValueError(f"cas-register cannot handle op f={f!r}")


@dataclasses.dataclass(frozen=True)
class Mutex(Model):
    """A single mutex with acquire/release (knossos.model/mutex)."""

    locked: bool = False
    name: ClassVar[str] = "mutex"

    def step(self, op):
        f = op["f"]
        if f == "acquire":
            if self.locked:
                return inconsistent("cannot acquire a locked mutex")
            return Mutex(True)
        if f == "release":
            if not self.locked:
                return inconsistent("cannot release a free mutex")
            return Mutex(False)
        raise ValueError(f"mutex cannot handle op f={f!r}")


@dataclasses.dataclass(frozen=True)
class UnorderedQueue(Model):
    """A queue where dequeues may come back in any order
    (knossos.model/unordered-queue).  State is a multiset held as a sorted
    tuple of (value, count) pairs to stay hashable."""

    pairs: tuple = ()
    name: ClassVar[str] = "unordered-queue"

    def _counts(self) -> dict:
        return dict(self.pairs)

    @staticmethod
    def _of(counts: dict) -> "UnorderedQueue":
        return UnorderedQueue(tuple(sorted((k, v) for k, v in counts.items() if v > 0)))

    def step(self, op):
        f, v = op["f"], op["value"]
        counts = self._counts()
        if f == "enqueue":
            counts[v] = counts.get(v, 0) + 1
            return self._of(counts)
        if f == "dequeue":
            if counts.get(v, 0) > 0:
                counts[v] -= 1
                return self._of(counts)
            return inconsistent(f"can't dequeue {v!r}: not in queue")
        raise ValueError(f"unordered-queue cannot handle op f={f!r}")


@dataclasses.dataclass(frozen=True)
class FIFOQueue(Model):
    """A strictly-ordered queue (knossos.model/fifo-queue)."""

    items: tuple = ()
    name: ClassVar[str] = "fifo-queue"

    def step(self, op):
        f, v = op["f"], op["value"]
        if f == "enqueue":
            return FIFOQueue(self.items + (v,))
        if f == "dequeue":
            if not self.items:
                return inconsistent(f"can't dequeue {v!r} from empty queue")
            if self.items[0] != v:
                return inconsistent(f"expected head {self.items[0]!r}, dequeued {v!r}")
            return FIFOQueue(self.items[1:])
        raise ValueError(f"fifo-queue cannot handle op f={f!r}")


@dataclasses.dataclass(frozen=True)
class MonotonicCounter(Model):
    """A counter where reads must observe a value ≥ the last read and ≤ the
    number of completed increments — a simple model for grow-only counters."""

    value: int = 0
    name: ClassVar[str] = "counter"

    def step(self, op):
        f, v = op["f"], op["value"]
        if f == "add":
            return MonotonicCounter(self.value + v)
        if f == "read":
            if v is None or v == self.value:
                return self
            return inconsistent(f"can't read {v!r} from counter {self.value!r}")
        raise ValueError(f"counter cannot handle op f={f!r}")


#: Registry by name — mirrors the reference's practice of choosing models by
#: keyword in workload options.
REGISTRY = {
    "register": Register,
    "cas-register": CASRegister,
    "mutex": Mutex,
    "unordered-queue": UnorderedQueue,
    "fifo-queue": FIFOQueue,
    "counter": MonotonicCounter,
}


def model(name: str, *args, **kwargs) -> Model:
    return REGISTRY[name](*args, **kwargs)
