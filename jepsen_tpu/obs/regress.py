"""Performance-regression observatory: run ledger, noise-aware gating,
stage-level attribution.

Every perf claim in this repo used to live in PERF.md prose and
write-only BENCH_r0*.json snapshots — nothing could say "PR N regressed
stage X by Y% beyond noise".  This module is the machinery that can:

  * **Run ledger** — an append-only JSONL file (default
    ``store/perf-ledger.jsonl``; ``JEPSEN_TPU_PERF_LEDGER`` env or a
    path argument override, the value ``0``/``off`` disables writes)
    where every ``bench.py``, ``tools/loadgen.py`` and
    ``tools/check_tier1_budget.py`` invocation appends one record:
    git sha, machine fingerprint (jax/jaxlib versions, backend, device
    kind, CPU model, host), headline metrics, and a per-stage rollup
    extracted from the run's telemetry summary (ladder stage times,
    dedup rounds, confirm-queue latency, serve occupancy/latency,
    spill counters).

  * **Noise-aware comparison** — ``compare_records`` judges the newest
    record against the ledger history *on the same fingerprint* with a
    MAD-based noise band per metric: regression (or improvement) is
    flagged only beyond the band, so the deterministic ``fixed_work``
    metric (±0.7 % run to run) gates tightly while wall-clock ratios
    (±20 %) need a real shift to trip.  Metric direction (lower- vs
    higher-is-better) is inferred from the name (``metric_direction``).

  * **Stage attribution** — when a headline regresses, ``diff_stage
    _tables`` names the top regressing spans between the two runs'
    telemetry stage rollups: the answer to "what got slower" is a stage
    name, not a bisect.  ``tools/trace_summarize.py --diff`` and
    ``tools/perfwatch.py compare`` share this code.

  * **Competition records** — ``run_competition`` runs a pinned
    fixed-work ladder workload once per value of an axis (e.g.
    ``dedup_backend`` = ``sort`` vs ``bucket``), judges the head-to-head
    with the same noise-band math over the per-value repeat times, and
    writes a reproducible verdict record into the ledger — routing
    flips become recorded comparisons instead of PERF.md paragraphs.

Import-light by design: stdlib only at module import (jax / git are
touched lazily inside ``fingerprint()`` / ``git_info()``), so the
budget-gate and web paths never drag the checker stack in.
"""

from __future__ import annotations

import hashlib
import json
import os
import platform
import socket
import statistics
import subprocess
import sys
import time
from pathlib import Path
from typing import Callable, Iterable, Mapping, Sequence

__all__ = [
    "ENV_LEDGER", "SCHEMA", "append_record", "attribution", "compare_records",
    "diff_stage_tables", "fingerprint", "fingerprint_key", "format_comparison",
    "format_stage_diff", "gate", "git_info", "ledger_path", "make_record",
    "metric_direction", "noise_band", "publish_gauges", "read_records",
    "read_records_checked", "run_competition", "stage_rollup",
]

ENV_LEDGER = "JEPSEN_TPU_PERF_LEDGER"
SCHEMA = 1

#: ledger path values that mean "don't write a ledger at all".
_OFF = {"0", "off", "false", "no", "none", ""}

# ---------------------------------------------------------------------------
# Fingerprint: which machine/toolchain produced a number.  Noise baselines
# only make sense within one fingerprint — a chip run and a CPU fallback
# run of the same sha are different experiments, and the BENCH_r0*.json
# trajectory couldn't tell them apart without parsing warning text.
# ---------------------------------------------------------------------------

#: fingerprint fields that define the comparison group (git sha is
#: deliberately NOT one of them: the whole point is comparing shas).
_KEY_FIELDS = ("jax", "jaxlib", "backend", "device_kind", "device_count",
               "cpu", "host")


def _cpu_model() -> str:
    try:
        with open("/proc/cpuinfo", encoding="utf-8", errors="replace") as fh:
            for line in fh:
                if line.lower().startswith("model name"):
                    return line.split(":", 1)[1].strip()
    except OSError:
        pass
    return platform.processor() or platform.machine() or "?"


def fingerprint(*, probe_devices: bool = True) -> dict:
    """The machine/toolchain identity a perf number belongs to: jax +
    jaxlib versions, active backend and device kind/count, CPU model,
    host, python.  Works (with ``backend: "none"``) when jax is absent
    or refuses to initialize — the budget gate must never crash on it.
    ``probe_devices=False`` skips ``jax.devices()`` entirely (backend
    ``"unprobed"``): callers that must not initialize a backend — the
    bench's outage path, where the probe already established the tunnel
    is down and an in-process device call could hang."""
    fp: dict = {
        "host": socket.gethostname(),
        "cpu": _cpu_model(),
        "python": platform.python_version(),
    }
    try:
        import jax
        import jaxlib

        fp["jax"] = getattr(jax, "__version__", "?")
        fp["jaxlib"] = getattr(jaxlib, "__version__", "?")
        if not probe_devices:
            fp["backend"] = "unprobed"
            return fp
        try:
            devs = jax.devices()
            fp["backend"] = jax.default_backend()
            fp["device_kind"] = devs[0].device_kind if devs else "?"
            fp["device_count"] = len(devs)
        except Exception:  # noqa: BLE001 — backend init can fail (tunnel)
            fp["backend"] = "uninitialized"
    except Exception:  # noqa: BLE001 — jax absent entirely
        fp["backend"] = "none"
    return fp


def fingerprint_key(fp: Mapping) -> str:
    """A stable 12-hex grouping key over the comparison-defining fields
    (git sha excluded — records from different PRs on the same machine
    and toolchain share a key; that sharing IS the baseline)."""
    basis = {k: fp.get(k) for k in _KEY_FIELDS}
    blob = json.dumps(basis, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()[:12]


def git_info() -> dict:
    """``{"sha": ..., "dirty": bool}`` for the working tree (best
    effort; ``{"sha": "unknown"}`` outside a repo or without git)."""
    try:
        sha = subprocess.run(
            ["git", "rev-parse", "HEAD"], capture_output=True, text=True,
            timeout=10,
        ).stdout.strip()
        if not sha:
            return {"sha": "unknown"}
        dirty = bool(subprocess.run(
            ["git", "status", "--porcelain", "--untracked-files=no"],
            capture_output=True, text=True, timeout=10,
        ).stdout.strip())
        return {"sha": sha, "dirty": dirty}
    except Exception:  # noqa: BLE001 — git missing/hung must not break a run
        return {"sha": "unknown"}


# ---------------------------------------------------------------------------
# The ledger
# ---------------------------------------------------------------------------


def ledger_path(path: str | os.PathLike | None = None,
                store_dir: str | os.PathLike | None = None) -> Path | None:
    """Resolve the ledger file: explicit path > ``JEPSEN_TPU_PERF_LEDGER``
    env > ``<store_dir or 'store'>/perf-ledger.jsonl``.  ``None`` when
    writes are disabled (env/arg set to ``0``/``off``/...)."""
    if path is None:
        path = os.environ.get(ENV_LEDGER)
    if path is not None:
        if str(path).strip().lower() in _OFF:
            return None
        return Path(path)
    return Path(store_dir or "store") / "perf-ledger.jsonl"


def make_record(kind: str, metrics: Mapping[str, float], *,
                stages: Mapping[str, float] | None = None,
                axes: Mapping[str, str] | None = None,
                extra: Mapping | None = None,
                fp: Mapping | None = None) -> dict:
    """Assemble a ledger record: schema + timestamps + git + fingerprint
    (computed when not supplied) around the caller's metrics/stages."""
    fp = dict(fp) if fp is not None else fingerprint()
    rec: dict = {
        "schema": SCHEMA,
        "kind": str(kind),
        "ts": round(time.time(), 3),
        "git": git_info(),
        "fingerprint": fp,
        "fingerprint_key": fingerprint_key(fp),
        "metrics": {str(k): v for k, v in dict(metrics).items()
                    if isinstance(v, (int, float)) and not isinstance(v, bool)},
    }
    if stages:
        rec["stages"] = {str(k): round(float(v), 6)
                         for k, v in dict(stages).items()}
    if axes:
        rec["axes"] = {str(k): str(v) for k, v in dict(axes).items()}
    if extra:
        rec["extra"] = dict(extra)
    return rec


def _append_seam(step: str, path) -> None:
    """The ledger half of the crashpoint-audit seam (the file-level
    counterpart of ``store._write_seam``): announces each append step
    through ``faults.INJECT`` so tools/crashpoint.py can kill a child
    mid-append and prove the reader's torn-line tolerance.  Lazy import
    keeps this module stdlib-only at import time."""
    from jepsen_tpu import faults

    hook = faults.INJECT
    if hook is not None:
        hook({"what": "ledger.append", "step": step, "path": str(path)}, 0)


def append_record(record: Mapping, path: str | os.PathLike | None = None,
                  store_dir: str | os.PathLike | None = None) -> Path | None:
    """Append one record line to the ledger (fsync'd — the ledger is the
    durable trajectory; a crashed run must not lose its number).  Each
    line is SEALED with a per-record CRC32 (``durable.seal_line``), so
    bit rot and hand-edits are detected at read, not just torn tails.
    Returns the path written, or None when the ledger is disabled.
    Raises on IO failure — producers that must never fail wrap this
    themselves."""
    from jepsen_tpu.store import durable as _durable

    from jepsen_tpu import store as _store

    p = ledger_path(path, store_dir)
    if p is None:
        return None
    p.parent.mkdir(parents=True, exist_ok=True)
    # Canonicalize BEFORE sealing: the CRC is computed over _jsonable
    # output, so the bytes on disk must be that same structure — a
    # value json.dumps would coerce differently (np.int64, set) would
    # otherwise seal a line that fails its own checksum on every read.
    # No default= here on purpose: after _jsonable nothing should need
    # one, and a silent str() coercion would be exactly that bug back.
    sealed = _durable.seal_line(_store._jsonable(dict(record)))
    line = json.dumps(sealed, separators=(",", ":"))
    with open(p, "a", encoding="utf-8") as fh:
        fh.write(line + "\n")
        fh.flush()
        _append_seam("post-write", p)
        os.fsync(fh.fileno())
        _append_seam("post-fsync", p)
    return p


def read_records_checked(
        path: str | os.PathLike | None = None,
        store_dir: str | os.PathLike | None = None) -> tuple[list[dict], int]:
    """``(records, skipped)``: all VERIFIED ledger records oldest first,
    plus how many lines were dropped — torn tails, junk, and sealed
    lines whose per-record CRC no longer matches (bit rot / hand
    edits).  Legacy unsealed lines still count as records.  The skipped
    count is the honesty contract (parity with
    ``obs.trace.read_jsonl_events``): a reader that silently drops
    lines turns a corrupt trajectory into a convincing one.  A nonzero
    count also emits ``durable.ledger_skipped``."""
    p = ledger_path(path, store_dir)
    if p is None or not p.is_file():
        return [], 0
    out: list[dict] = []
    skipped = 0
    try:
        text = p.read_text(encoding="utf-8", errors="replace")
    except OSError:
        return [], 0
    from jepsen_tpu.store import durable as _durable

    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            skipped += 1
            continue
        if not (isinstance(rec, dict) and rec.get("kind")):
            skipped += 1
            continue
        ok, _legacy = _durable.check_line(rec)
        if not ok:
            skipped += 1
            continue
        rec.pop("crc", None)
        out.append(rec)
    from jepsen_tpu import obs as _obs

    # a GAUGE, not a counter — the same ledger is read many times per
    # process (publish_gauges per scrape, gate, list) and an
    # accumulating counter would report reads x skipped — and emitted
    # unconditionally so a repaired/rotated ledger resets the reading
    # to 0 instead of alerting on stale corruption forever
    _obs.gauge("durable.ledger_skipped", skipped, path=str(p))
    return out, skipped


def read_records(path: str | os.PathLike | None = None,
                 store_dir: str | os.PathLike | None = None) -> list[dict]:
    """All verified ledger records, oldest first (the records half of
    ``read_records_checked`` — callers that surface the skipped count
    use that instead)."""
    return read_records_checked(path, store_dir)[0]


# ---------------------------------------------------------------------------
# Telemetry stage rollup: the per-stage table a record carries, extracted
# from an obs.summary dict (the telemetry.json shape).
# ---------------------------------------------------------------------------


def stage_rollup(summary: Mapping | None) -> tuple[dict, dict]:
    """``(stages, metrics)`` extracted from a telemetry summary dict.

    ``stages`` maps span names to total seconds: one entry per ladder
    rung (``ladder[<stage>] <engine>@<capacity>``) plus every rolled-up
    span (phases, confirm device/drain, serve.batch, checker.check, ...)
    — the table ``diff_stage_tables`` attributes regressions over.
    ``metrics`` carries the scalar side channels worth trending on their
    own: serve occupancy and latency means, confirm-queue latency, dedup
    per-round timings, and the spill counters."""
    stages: dict[str, float] = {}
    metrics: dict[str, float] = {}
    if not summary:
        return stages, metrics
    for i, row in enumerate(summary.get("ladder") or []):
        name = (f"ladder[{row.get('stage', i)}] "
                f"{row.get('engine', '?')}@{row.get('capacity', '?')}")
        try:
            stages[name] = stages.get(name, 0.0) + float(row.get("seconds") or 0)
        except (TypeError, ValueError):
            continue
    for name, s in (summary.get("spans") or {}).items():
        # ladder.stage's total duplicates the per-rung rows above, but a
        # summary without a ladder table (partial stream) still gets it
        if name == "ladder.stage" and any(k.startswith("ladder[") for k in stages):
            continue
        try:
            stages[str(name)] = float(s.get("total_s") or 0)
        except (TypeError, ValueError, AttributeError):
            continue
    # critical-path seconds per span (obs.critpath, embedded in every
    # telemetry.json): the ledger then trends what BOUNDS wall clock,
    # not just inclusive time — a stage that grew but slid off the
    # critical path is a different story from one that grew on it.
    cp = summary.get("critpath") or {}
    for row in cp.get("spans") or []:
        try:
            stages[f"critpath[{row['span']}]"] = float(row.get("cp_s") or 0)
        except (TypeError, ValueError, KeyError):
            continue
    if isinstance(cp.get("total_s"), (int, float)):
        metrics["critpath_total_s"] = float(cp["total_s"])
    for d in summary.get("dedup") or []:
        key = (f"dedup[{d.get('backend', '?')}@{d.get('candidates', '?')}]"
               "_per_round_us")
        try:
            metrics[key] = float(d.get("per_round_us") or 0)
        except (TypeError, ValueError):
            continue
    serve = summary.get("serve") or {}
    for k, out in (("avg_occupancy", "serve_occupancy"),
                   ("continuous_occupancy", "serve_continuous_occupancy"),
                   ("avg_padding_waste", "serve_padding_waste")):
        if isinstance(serve.get(k), (int, float)):
            metrics[out] = float(serve[k])
    for k in ("admission", "request"):
        lat = serve.get(k)
        if isinstance(lat, Mapping) and isinstance(lat.get("mean_s"), (int, float)):
            metrics[f"serve_{k}_mean_s"] = float(lat["mean_s"])
    gauges = summary.get("gauges") or {}
    if isinstance(gauges.get("confirm.queue_latency_s"), (int, float)):
        metrics["confirm_queue_latency_s"] = float(
            gauges["confirm.queue_latency_s"])
    for k, v in (summary.get("memory") or {}).items():
        if isinstance(v, (int, float)):
            metrics[f"memory_{k}"] = float(v)
    return stages, metrics


# ---------------------------------------------------------------------------
# Noise-aware comparison
# ---------------------------------------------------------------------------

#: name fragments that mark a metric higher-is-better; checked before the
#: lower-is-better default so "configs_per_s" doesn't read as a time.
_HIGHER_BETTER = ("per_s", "per_sec", "_rps", "ops_s", "occupancy",
                  "vs_baseline", "throughput", "speedup", "headroom")


def metric_direction(name: str) -> int:
    """+1 when larger values are better (throughput, occupancy), -1 when
    smaller values are (seconds, latencies, waste, bytes — the default:
    everything in a stage table is a time)."""
    n = str(name).lower()
    if any(f in n for f in _HIGHER_BETTER):
        return 1
    return -1


def noise_band(values: Sequence[float], *, k_sigma: float = 4.0,
               rel_floor: float = 0.02) -> float:
    """Half-width of the noise band around the history median: ``k_sigma``
    robust standard deviations (MAD × 1.4826), floored at ``rel_floor``
    of the median's magnitude so a short or perfectly-repeating history
    (MAD 0) doesn't flag timer jitter.  With the defaults a metric whose
    run-to-run noise is ~0.7 % (``fixed_work``) gets a ~4 % band — an
    injected 10 % regression trips it, two clean runs don't."""
    vals = [float(v) for v in values]
    if not vals:
        return 0.0
    med = statistics.median(vals)
    mad = statistics.median(abs(v - med) for v in vals)
    return max(k_sigma * 1.4826 * mad, rel_floor * abs(med))


def _history_values(history: Iterable[Mapping], metric: str) -> list[float]:
    out = []
    for rec in history:
        v = (rec.get("metrics") or {}).get(metric)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            out.append(float(v))
    return out


def compare_records(new: Mapping, history: Sequence[Mapping], *,
                    k_sigma: float = 4.0, rel_floor: float = 0.02,
                    metrics: Sequence[str] | None = None) -> list[dict]:
    """Judge every metric of ``new`` against the same-fingerprint
    ``history`` (older records, same kind).  One row per metric:

      {"metric", "new", "median", "n", "band", "delta_pct",
       "status": "ok" | "regressed" | "improved" | "no-history"}

    ``delta_pct`` is signed new-vs-median; status crosses the noise band
    in the metric's bad (``regressed``) or good (``improved``) direction.
    """
    rows: list[dict] = []
    new_metrics = new.get("metrics") or {}
    names = list(metrics) if metrics else sorted(new_metrics)
    for name in names:
        v = new_metrics.get(name)
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            continue
        hist = _history_values(history, name)
        row: dict = {"metric": name, "new": round(float(v), 6), "n": len(hist)}
        if not hist:
            row.update(median=None, band=None, delta_pct=None,
                       status="no-history")
            rows.append(row)
            continue
        med = statistics.median(hist)
        band = noise_band(hist, k_sigma=k_sigma, rel_floor=rel_floor)
        delta = float(v) - med
        row["median"] = round(med, 6)
        row["band"] = round(band, 6)
        row["delta_pct"] = round(100.0 * delta / med, 2) if med else None
        direction = metric_direction(name)
        if band <= 0:
            # an all-zero history (median 0, MAD 0) carries no noise
            # scale at all — flagging a microscopic absolute change
            # (padding waste 0.0 -> 0.0001) would be the false positive
            # the band math exists to prevent
            row["status"] = "ok"
            rows.append(row)
            continue
        if delta * direction < -band:
            row["status"] = "regressed"
        elif delta * direction > band:
            row["status"] = "improved"
        else:
            row["status"] = "ok"
        rows.append(row)
    return rows


def latest_and_history(records: Sequence[Mapping], kind: str) -> tuple[
        dict | None, list[dict]]:
    """The newest record of ``kind`` plus its comparison history: older
    records of the same kind, fingerprint key AND axes (a chaos-seeded
    or hostile-geometry loadgen run is a different experiment from the
    clean one), outage records excluded (a value-0 tunnel-down bench is
    not a baseline)."""
    of_kind = [r for r in records
               if r.get("kind") == kind and not r.get("outage")]
    if not of_kind:
        return None, []
    newest = of_kind[-1]
    key = newest.get("fingerprint_key")
    axes = newest.get("axes") or {}
    return newest, [
        r for r in of_kind[:-1]
        if r.get("fingerprint_key") == key and (r.get("axes") or {}) == axes
    ]


def format_comparison(kind: str, newest: Mapping | None,
                      rows: Sequence[Mapping]) -> str:
    """The compare/gate table as text (perfwatch + docker/bin/test log)."""
    if newest is None:
        return f"[{kind}] no ledger records\n"
    git = (newest.get("git") or {}).get("sha", "?")[:10]
    head = (f"[{kind}] newest {git} on {newest.get('fingerprint_key')} "
            f"vs {max((r.get('n') or 0) for r in rows) if rows else 0} "
            "prior same-fingerprint record(s)")
    lines = [head]
    if not rows:
        lines.append("  (no numeric metrics)")
        return "\n".join(lines) + "\n"
    wm = max(len("metric"), *(len(str(r["metric"])) for r in rows))
    lines.append(f"  {'metric'.ljust(wm)}  {'new':>12}  {'median':>12}  "
                 f"{'band':>10}  {'delta%':>8}  status")
    for r in rows:
        med = "-" if r.get("median") is None else f"{r['median']:.6g}"
        band = "-" if r.get("band") is None else f"±{r['band']:.4g}"
        dp = "-" if r.get("delta_pct") is None else f"{r['delta_pct']:+.2f}"
        mark = {"regressed": " <-- REGRESSED",
                "improved": " (improved)"}.get(r["status"], "")
        lines.append(f"  {str(r['metric']).ljust(wm)}  {r['new']:>12.6g}  "
                     f"{med:>12}  {band:>10}  {dp:>8}  {r['status']}{mark}")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# Stage attribution: "what got slower" should be a stage name, not a bisect
# ---------------------------------------------------------------------------


def diff_stage_tables(a: Mapping[str, float], b: Mapping[str, float], *,
                      min_delta_s: float = 0.0) -> list[dict]:
    """Diff two flat ``{span: seconds}`` stage tables (``stage_rollup``
    output, or a ledger record's ``stages``): one row per span present in
    either, sorted by signed delta descending (top regressing spans
    first — B minus A, so positive = slower in B).  Spans absent on one
    side diff against 0 (a stage that appeared is itself the story)."""
    rows: list[dict] = []
    for name in sorted(set(a) | set(b)):
        av = float(a.get(name) or 0.0)
        bv = float(b.get(name) or 0.0)
        delta = bv - av
        if abs(delta) < min_delta_s:
            continue
        rows.append({
            "span": name,
            "a_s": round(av, 6),
            "b_s": round(bv, 6),
            "delta_s": round(delta, 6),
            "delta_pct": round(100.0 * delta / av, 2) if av else None,
        })
    rows.sort(key=lambda r: -r["delta_s"])
    return rows


def attribution(new: Mapping, old: Mapping, top: int = 5) -> list[dict]:
    """Top regressing spans between two ledger records' stage tables
    (new slower = positive delta first)."""
    return diff_stage_tables(
        old.get("stages") or {}, new.get("stages") or {}
    )[:top]


def format_stage_diff(rows: Sequence[Mapping], *, a_label: str = "A",
                      b_label: str = "B") -> str:
    """The attribution table as text (perfwatch, trace_summarize --diff)."""
    if not rows:
        return "(no stage data on both sides)\n"
    wm = max(len("span"), *(len(str(r["span"])) for r in rows))
    lines = [f"{'span'.ljust(wm)}  {a_label + ' (s)':>12}  "
             f"{b_label + ' (s)':>12}  {'delta (s)':>12}  delta%"]
    for r in rows:
        dp = "-" if r.get("delta_pct") is None else f"{r['delta_pct']:+.1f}"
        lines.append(f"{str(r['span']).ljust(wm)}  {r['a_s']:>12.6g}  "
                     f"{r['b_s']:>12.6g}  {r['delta_s']:>+12.6g}  {dp}")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# The gate
# ---------------------------------------------------------------------------


def gate(records: Sequence[Mapping], *, kinds: Sequence[str] | None = None,
         k_sigma: float = 4.0, rel_floor: float = 0.02,
         metrics: Sequence[str] | None = None) -> tuple[bool, str]:
    """``(ok, report)``: for each record kind present (or ``kinds``),
    compare its newest record against the same-fingerprint history and
    flag regressions beyond the noise band.  ``ok`` is False when any
    metric regressed; the report carries the full comparison tables plus
    stage attribution for regressed kinds (both runs must carry stage
    rollups for that)."""
    if kinds is None:
        seen: list[str] = []
        for r in records:
            k = r.get("kind")
            if k and k not in seen and k != "compete":
                seen.append(k)
        kinds = seen
    ok = True
    parts: list[str] = []
    for kind in kinds:
        newest, history = latest_and_history(records, kind)
        rows = [] if newest is None else compare_records(
            newest, history, k_sigma=k_sigma, rel_floor=rel_floor,
            metrics=metrics,
        )
        parts.append(format_comparison(kind, newest, rows))
        regressed = [r for r in rows if r["status"] == "regressed"]
        if regressed:
            ok = False
            if newest is not None and history:
                att = attribution(newest, history[-1])
                if att:
                    parts.append("  top moving spans (prior -> new):")
                    parts.append("  " + format_stage_diff(
                        att, a_label="prior", b_label="new",
                    ).replace("\n", "\n  ").rstrip() + "\n")
    if not parts:
        parts.append("(empty ledger — nothing to gate)\n")
    return ok, "\n".join(parts)


# ---------------------------------------------------------------------------
# Competition: a recorded, reproducible head-to-head along one axis
# ---------------------------------------------------------------------------


def _default_runner(axis: str, *, histories: int = 6, ops: int = 30,
                    procs: int = 3, capacity: Sequence[int] = (64, 256),
                    repeats: int = 3) -> Callable[[str], list[float]]:
    """The built-in fixed-work competition workload: a pinned batch of
    register histories (same seeds every run, 1-in-3 corrupted so the
    refutation path is in the measurement) through the production ladder
    at suite-shared shapes.  The axis value is applied via its env var
    (``dedup_backend`` -> ``JEPSEN_TPU_DEDUP_BACKEND`` — the same
    resolver every engine already reads), one warm pass absorbs
    compiles, then ``repeats`` timed passes return their wall times."""
    sys.path.insert(0, str(Path(__file__).resolve().parents[2] / "tools"))
    from genhist import corrupt, valid_register_history

    from jepsen_tpu import models as m
    from jepsen_tpu.parallel import batch_analysis

    model = m.CASRegister(None)
    hists = []
    for i in range(histories):
        hh = valid_register_history(ops, procs, seed=1000 + i, info_rate=0.1)
        if i % 3 == 2:
            hh = corrupt(hh, seed=1000 + i)
        hists.append(hh)
    env_var = "JEPSEN_TPU_" + axis.upper()
    caps = tuple(capacity)

    def _mesh_run(value: str) -> list[float]:
        # mesh-size axis (round 12): the value is a DEVICE COUNT, not an
        # env knob — the same pinned workload through the ladder with the
        # batch lane-sharded over an n-device mesh and the fused-kernel
        # backend (the mesh-spanning wide stage is what the axis
        # measures).  Needs the devices to exist before jax init (the
        # caller sets --xla_force_host_platform_device_count for the
        # virtual dev loop).
        import jax

        from jepsen_tpu.parallel import batch as _batch

        n_dev = int(value)
        if n_dev > len(jax.devices()):
            raise ValueError(
                f"mesh_devices={n_dev} but only {len(jax.devices())} jax "
                "devices are visible (set "
                "XLA_FLAGS=--xla_force_host_platform_device_count=N for "
                "the virtual dev loop)"
            )
        mesh = _batch.make_mesh(n_dev) if n_dev > 1 else None
        kw = dict(mesh=mesh, dedup_backend="pallas")
        batch_analysis(model, hists, capacity=caps, **kw)  # warm
        times = []
        for _ in range(max(1, repeats)):
            t0 = time.perf_counter()
            batch_analysis(model, hists, capacity=caps, **kw)
            times.append(time.perf_counter() - t0)
        return times

    def run(value: str) -> list[float]:
        if axis == "mesh_devices":
            return _mesh_run(value)
        old = os.environ.get(env_var)
        os.environ[env_var] = str(value)
        try:
            batch_analysis(model, hists, capacity=caps)  # warm (compiles)
            times = []
            for _ in range(max(1, repeats)):
                t0 = time.perf_counter()
                batch_analysis(model, hists, capacity=caps)
                times.append(time.perf_counter() - t0)
            return times
        finally:
            if old is None:
                os.environ.pop(env_var, None)
            else:
                os.environ[env_var] = old

    return run


def run_competition(axis: str, values: Sequence[str], *,
                    runner: Callable[[str], list[float]] | None = None,
                    repeats: int = 3, k_sigma: float = 4.0,
                    rel_floor: float = 0.02,
                    workload: Mapping | None = None) -> dict:
    """Head-to-head along ``axis``: run the pinned workload per value,
    pick the winner by median wall time, and judge decisiveness with the
    same noise band the gate uses (the winner must clear the loser's
    band AND its own).  Returns a ``kind: "compete"`` ledger record —
    the caller appends it.  ``runner(value) -> [seconds, ...]`` overrides
    the built-in workload (tests use this; chip rounds use the default).
    """
    if len({str(v) for v in values}) < 2:
        raise ValueError("competition needs at least two DISTINCT axis "
                         "values")
    wl = dict(workload or {})
    if runner is None:
        runner = _default_runner(axis, repeats=repeats, **wl)
    results: dict[str, dict] = {}
    for v in values:
        times = [float(t) for t in runner(str(v))]
        results[str(v)] = {
            "times_s": [round(t, 6) for t in times],
            "median_s": round(statistics.median(times), 6),
            "band_s": round(noise_band(times, k_sigma=k_sigma,
                                       rel_floor=rel_floor), 6),
        }
    ranked = sorted(results.items(), key=lambda kv: kv[1]["median_s"])
    winner, runner_up = ranked[0], ranked[1]
    gap = runner_up[1]["median_s"] - winner[1]["median_s"]
    decisive = gap > max(winner[1]["band_s"], runner_up[1]["band_s"])
    margin_pct = (100.0 * gap / runner_up[1]["median_s"]
                  if runner_up[1]["median_s"] else 0.0)
    verdict = {
        "axis": axis,
        "values": [str(v) for v in values],
        "results": results,
        "winner": winner[0],
        "decisive": decisive,
        "margin_pct": round(margin_pct, 2),
        "workload": wl or "default fixed-work ladder",
    }
    if "pallas" in verdict["values"] or axis == "mesh_devices":
        # Honest separation of chip records from CPU-interpret ones: a
        # pallas competitor that ran under the Pallas interpreter must
        # never pass for a chip measurement when the flip decision
        # reads the ledger (the fingerprint separates machines; this
        # separates execution modes on the SAME machine).  The
        # mesh_devices axis always runs the pallas backend, so it gets
        # the same stamp.
        try:
            from jepsen_tpu.ops import wide_kernel

            verdict["pallas_interpret"] = bool(wide_kernel.interpret_default())
        except Exception:  # noqa: BLE001 — never lose a record to a tag
            pass
    return make_record("compete", {"compete_margin_pct": round(margin_pct, 2)},
                       axes={axis: winner[0]}, extra=verdict)


# ---------------------------------------------------------------------------
# Live-gauge publication: the last run's headline numbers in /metrics
# ---------------------------------------------------------------------------

#: per-path (mtime, size) guard so /metrics scrapes don't re-read an
#: unchanged ledger.
_PUBLISH_CACHE: dict[str, tuple[int, int]] = {}
#: per-path (kind, metric) pairs currently exported, so a newest record
#: that DROPS a metric retracts the stale series instead of leaving an
#: older run's value rendering under the same labels.
_PUBLISHED: dict[str, set[tuple[str, str]]] = {}
#: per-path newest-record ts by kind: the age gauge must keep advancing
#: on every scrape even while the ledger file is unchanged (that growing
#: age is the gauge's entire purpose — "no perf record in N days").
_PUBLISH_TS: dict[str, dict[str, float]] = {}


def _publish_ages(obs_metrics, key: str) -> None:
    now = time.time()
    for kind, ts in _PUBLISH_TS.get(key, {}).items():
        obs_metrics.set_gauge("perf.headline_age_seconds",
                              round(max(0.0, now - ts), 1), kind=kind)


def publish_gauges(path: str | os.PathLike | None = None,
                   store_dir: str | os.PathLike | None = None) -> bool:
    """Push the newest ledger record's metrics per kind into the live
    Prometheus registry as ``jepsen_tpu_perf_headline{kind=,metric=}``
    gauges (plus ``..._perf_headline_age_seconds``), so a serving
    process's /metrics carries the last recorded perf trajectory point.
    Cheap to call per scrape: re-reads only when the file changed.
    Series the newest records no longer carry are retracted — a mixed
    scrape of values from different runs would be worse than none."""
    p = ledger_path(path, store_dir)
    if p is None or not p.is_file():
        return False
    try:
        st = p.stat()
        sig = (st.st_mtime_ns, st.st_size)
    except OSError:
        return False
    from jepsen_tpu.obs import metrics as obs_metrics

    key = str(p)
    if _PUBLISH_CACHE.get(key) == sig:
        # the VALUE gauges are unchanged, but the ages keep growing
        _publish_ages(obs_metrics, key)
        return True
    records = read_records(p)
    newest_by_kind: dict[str, dict] = {}
    for r in records:
        if not r.get("outage"):
            newest_by_kind[str(r.get("kind"))] = r
    published: set[tuple[str, str]] = set()
    ts_by_kind: dict[str, float] = {}
    for kind, rec in newest_by_kind.items():
        for name, v in (rec.get("metrics") or {}).items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                obs_metrics.set_gauge("perf.headline", v,
                                      kind=kind, metric=name)
                published.add((kind, name))
        ts = rec.get("ts")
        if isinstance(ts, (int, float)):
            ts_by_kind[kind] = float(ts)
            published.add((kind, "__age__"))
    for kind, name in _PUBLISHED.get(key, set()) - published:
        if name == "__age__":
            obs_metrics.REGISTRY.remove("perf.headline_age_seconds",
                                        kind=kind)
        else:
            obs_metrics.REGISTRY.remove("perf.headline",
                                        kind=kind, metric=name)
    _PUBLISHED[key] = published
    _PUBLISH_TS[key] = ts_by_kind
    _PUBLISH_CACHE[key] = sig
    _publish_ages(obs_metrics, key)
    return True
