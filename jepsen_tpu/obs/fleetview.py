"""The fleet flight recorder: one trace, one timeline, one metrics plane.

PR 18's FleetRouter made the checker a multi-process fleet, but every
observability surface stayed strictly per-process: each replica has its
own recorder stream, its own Prometheus registry, its own SLO burn
engine.  This module is the cross-process glue:

  * **Metrics federation** — ``federate()`` takes the router process's
    own exposition plus one raw scrape per replica and re-exports every
    replica series with a ``replica=`` label, alongside
    ``jepsen_tpu_fleet_*`` rollups: counters and histogram buckets SUM
    across replicas (a fleet processed the union of the work), gauges
    are deliberately NOT summed (two replicas at queue depth 3 are not
    a fleet at depth 6 in any operationally useful sense — the labeled
    per-replica series carry them instead).
  * **Fleet-level SLO burn** — ``FederatedRegistry`` is a read-only,
    ``obs.metrics.Registry``-shaped view (``get`` /
    ``histogram_buckets``) over parsed replica scrapes plus an optional
    live base registry, so the stock ``serve.slo.SloEngine`` runs
    UNCHANGED on fleet-aggregate bad/total counts: a one-replica
    brownout burns the fleet budget proportionally to its traffic share
    instead of only tripping that replica's local alert.
  * **Timeline merging** — ``merge_trace_events()`` clock-aligns N
    recorder streams on their ``meta`` t0-epoch headers
    (obs.trace.align_streams) and emits ONE Perfetto document: one
    process group per stream (router + each replica, named and
    pid-renumbered so same-host pids can't collide), counter tracks per
    replica, and a request's router-side ``fleet.route`` span linked to
    its replica-side ``serve.request`` span by the shared trace id.

Stdlib-only, like the rest of ``obs`` — the web layer serves
``federate()`` output from ``GET /metrics`` when a fleet is mounted,
and ``tools/trace_export.py --fleet``/multi-path ``trace_summarize``
drive the merger offline.
"""

from __future__ import annotations

import math
from typing import Iterable, Mapping

from jepsen_tpu.obs import metrics as _metrics
from jepsen_tpu.obs.trace import align_streams, to_trace_events

__all__ = [
    "FederatedRegistry",
    "FleetSlo",
    "federate",
    "merge_trace_events",
    "parse_exposition",
]

#: fleet rollup series are the original family with this prefix swapped
#: in for ``jepsen_tpu_`` (``jepsen_tpu_serve_submitted_total`` →
#: ``jepsen_tpu_fleet_serve_submitted_total``).
ROLLUP_PREFIX = "jepsen_tpu_fleet_"


# ---------------------------------------------------------------------------
# Prometheus text-exposition parsing (the inverse of metrics.render())
# ---------------------------------------------------------------------------


def _parse_labels(s: str) -> tuple[tuple[str, str], ...]:
    """``k="v",k2="v2"`` → label pairs, undoing ``_escape_label``."""
    out: list[tuple[str, str]] = []
    i, n = 0, len(s)
    while i < n:
        eq = s.index("=", i)
        key = s[i:eq].strip().strip(",")
        i = eq + 1
        if i >= n or s[i] != '"':
            raise ValueError(f"malformed label value at {s[i:]!r}")
        i += 1
        buf: list[str] = []
        while i < n:
            c = s[i]
            if c == "\\" and i + 1 < n:
                nxt = s[i + 1]
                buf.append({"n": "\n"}.get(nxt, nxt))
                i += 2
                continue
            if c == '"':
                i += 1
                break
            buf.append(c)
            i += 1
        out.append((key, "".join(buf)))
        while i < n and s[i] in ", ":
            i += 1
    return tuple(out)


def parse_exposition(text: str) -> dict:
    """Parse Prometheus text format 0.0.4 (the subset
    ``obs.metrics.Registry.render`` emits) into ``{"types": {family:
    kind}, "samples": [(name, labels, value), ...]}`` with labels as a
    tuple of pairs in source order.  Unparseable lines are counted in
    ``"skipped"``, not fatal — a half-written scrape from a dying
    replica must not take the federation down with it."""
    types: dict[str, str] = {}
    samples: list[tuple[str, tuple, float]] = []
    skipped = 0
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3]
            continue
        try:
            if "{" in line:
                name, rest = line.split("{", 1)
                lbl, _, val = rest.rpartition("}")
                labels = _parse_labels(lbl)
            else:
                name, _, val = line.partition(" ")
                labels = ()
            samples.append((name.strip(), labels, float(val)))
        except (ValueError, IndexError):
            skipped += 1
    return {"types": types, "samples": samples, "skipped": skipped}


def _family_of(name: str, types: Mapping[str, str]) -> tuple[str, str]:
    """``(family, kind)`` for one sample name.  Histogram samples carry
    ``_bucket``/``_sum``/``_count`` suffixes over a base-family TYPE;
    counter TYPE lines already include the ``_total`` suffix."""
    if name in types:
        return name, types[name]
    for suf in ("_bucket", "_sum", "_count"):
        if name.endswith(suf):
            base = name[: -len(suf)]
            if types.get(base) == "histogram":
                return base, "histogram"
    if name.endswith("_total"):
        return name, "counter"
    return name, types.get(name, "gauge")


def _rollup_name(name: str) -> str:
    base = (name[len(_metrics._PREFIX):]
            if name.startswith(_metrics._PREFIX) else name)
    return ROLLUP_PREFIX + base


def _num(v: float) -> str:
    if isinstance(v, float) and v.is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


# ---------------------------------------------------------------------------
# Metrics federation (router GET /metrics)
# ---------------------------------------------------------------------------


def federate(base_text: str, scrapes: Mapping[str, str], *,
             errors: Mapping[str, str] | None = None) -> str:
    """One exposition for the whole fleet.

    ``base_text`` is the router process's own registry render (router
    counters, and — for in-process LocalReplicas — the shared process
    registry their observations already merged into); it passes through
    unlabeled.  ``scrapes`` maps replica name → that replica's raw
    ``GET /metrics`` text; every one of its samples is re-exported with
    a ``replica="<name>"`` label.  On top, ``jepsen_tpu_fleet_*``
    rollups aggregate ACROSS the scrapes: counters sum, histogram
    bucket/sum/count triples sum bucket-wise (what makes a fleet-level
    latency SLO expressible), gauges are not rolled up.  ``errors``
    (replica → reason) marks failed scrapes: the replica gets
    ``jepsen_tpu_fleet_scrape_up{replica=...} 0`` instead of silently
    vanishing from the page."""
    errors = dict(errors or {})
    families: dict[str, dict] = {}   # family -> {"kind": k, "rows": [str]}

    def fam(family: str, kind: str) -> list[str]:
        f = families.get(family)
        if f is None:
            f = families[family] = {"kind": kind, "rows": []}
        return f["rows"]

    base = parse_exposition(base_text)
    for name, labels, value in base["samples"]:
        family, kind = _family_of(name, base["types"])
        fam(family, kind).append(
            f"{name}{_metrics._labels_str(labels)} {_num(value)}")

    # rollup accumulators, keyed on (rollup family, sample suffix,
    # replica-stripped labels)
    roll_counters: dict[tuple, float] = {}
    roll_hists: dict[tuple, float] = {}
    roll_kinds: dict[str, str] = {}

    for rep in sorted(scrapes):
        parsed = parse_exposition(scrapes[rep])
        for name, labels, value in parsed["samples"]:
            family, kind = _family_of(name, parsed["types"])
            labeled = tuple(
                [(k, v) for k, v in labels if k != "replica"]
                + [("replica", rep)])
            # histograms keep le LAST so the bucket rows stay shaped
            # like the registry's own render
            if labels and labels[-1][0] == "le":
                labeled = tuple(
                    [(k, v) for k, v in labeled if k != "le"]
                    + [("le", dict(labels)["le"])])
            fam(family, kind).append(
                f"{name}{_metrics._labels_str(labeled)} {_num(value)}")
            if family.startswith(ROLLUP_PREFIX):
                continue  # a replica that is itself a router: its
                # fleet-level series don't re-roll
            bare = tuple((k, v) for k, v in labels if k != "replica")
            if kind == "counter":
                key = (_rollup_name(family), bare)
                roll_counters[key] = roll_counters.get(key, 0.0) + value
                roll_kinds[_rollup_name(family)] = "counter"
            elif kind == "histogram":
                suffix = name[len(family):]
                key = (_rollup_name(family), suffix, bare)
                roll_hists[key] = roll_hists.get(key, 0.0) + value
                roll_kinds[_rollup_name(family)] = "histogram"
            # gauges: intentionally no rollup (see module docstring)

    for (family, labels), value in sorted(roll_counters.items()):
        fam(family, "counter").append(
            f"{family}{_metrics._labels_str(labels)} {_num(value)}")
    for (family, suffix, labels), value in sorted(roll_hists.items()):
        fam(family, "histogram").append(
            f"{family}{suffix}{_metrics._labels_str(labels)} {_num(value)}")

    up_rows = fam(ROLLUP_PREFIX + "scrape_up", "gauge")
    for rep in sorted(set(scrapes) | set(errors)):
        ok = 0 if rep in errors else 1
        up_rows.append(
            f"{ROLLUP_PREFIX}scrape_up{{replica=\"{rep}\"}} {ok}")
    fam(ROLLUP_PREFIX + "scrape_errors", "gauge").append(
        f"{ROLLUP_PREFIX}scrape_errors {len(errors)}")

    lines: list[str] = []
    for family in sorted(families):
        f = families[family]
        lines.append(f"# TYPE {family} {f['kind']}")
        lines.extend(f["rows"])
    return "\n".join(lines) + ("\n" if lines else "")


# ---------------------------------------------------------------------------
# Fleet-level SLO burn: a registry-shaped view over replica scrapes
# ---------------------------------------------------------------------------


class FederatedRegistry:
    """A read-only ``obs.metrics.Registry`` lookalike whose series are
    the SUM across parsed per-replica scrapes (plus an optional live
    base registry for the in-process side).  Only the two methods the
    SLO burn engine reads are implemented — ``get`` and
    ``histogram_buckets`` — which is exactly what lets the stock
    ``SloEngine`` compute FLEET burn with zero changes: its windowed
    good/bad deltas ride on aggregated cumulative counts.

    ``get`` on a gauge returns the MEAN across sources (a floor SLO on
    occupancy should read fleet-average capacity use, proportional to
    the brownout's share — the same proportionality rule as the
    bad-count aggregation)."""

    def __init__(self, base=None):
        self._base = base
        self._sources: dict[str, dict] = {}

    def update(self, scrapes: Mapping[str, str]) -> None:
        """Replace the parsed view with fresh raw scrapes (replica name
        → exposition text).  A replica absent from ``scrapes`` drops
        out of the aggregate — a fenced replica stops contributing to
        fleet burn the moment the router stops scraping it."""
        sources: dict[str, dict] = {}
        for rep, text in scrapes.items():
            parsed = parse_exposition(text)
            counters: dict[tuple, float] = {}
            gauges: dict[tuple, float] = {}
            hists: dict[tuple, dict] = {}
            for name, labels, value in parsed["samples"]:
                family, kind = _family_of(name, parsed["types"])
                bare = tuple(sorted(
                    (k, v) for k, v in labels if k not in ("le",)))
                if kind == "counter":
                    counters[(family, bare)] = value
                elif kind == "histogram":
                    h = hists.setdefault(
                        (family, bare),
                        {"le": {}, "sum": 0.0, "count": 0.0})
                    if name.endswith("_bucket"):
                        le = dict(labels).get("le", "+Inf")
                        h["le"][float(le)] = value
                    elif name.endswith("_sum"):
                        h["sum"] = value
                    elif name.endswith("_count"):
                        h["count"] = value
                else:
                    gauges[(family, bare)] = value
            sources[str(rep)] = {
                "counters": counters, "gauges": gauges, "hists": hists,
            }
        self._sources = sources

    def get(self, name: str, **labels):
        prom = _metrics.metric_name(name)
        lk = _metrics._labels_key(labels)
        ckey = (prom if prom.endswith("_total") else prom + "_total", lk)
        csum, hits = 0.0, 0
        gvals: list[float] = []
        for src in self._sources.values():
            if ckey in src["counters"]:
                csum += src["counters"][ckey]
                hits += 1
            elif (prom, lk) in src["gauges"]:
                gvals.append(src["gauges"][(prom, lk)])
        base_v = self._base.get(name, **labels) if self._base else None
        if hits:
            return csum + (float(base_v) if base_v is not None else 0.0)
        if gvals:
            if base_v is not None:
                gvals.append(float(base_v))
            return sum(gvals) / len(gvals)
        return base_v

    def histogram_buckets(self, name: str, **labels) -> dict | None:
        prom = _metrics.metric_name(name)
        lk = _metrics._labels_key(labels)
        bounds: set[float] = set()
        views: list[dict] = []
        for src in self._sources.values():
            h = src["hists"].get((prom, lk))
            if h is not None and h["le"]:
                views.append(h)
                bounds.update(b for b in h["le"] if math.isfinite(b))
        base_h = (self._base.histogram_buckets(name, **labels)
                  if self._base else None)
        if base_h is not None:
            bounds.update(base_h["bounds"])
        if not views and base_h is None:
            return None
        ordered = tuple(sorted(bounds))
        buckets = [0.0] * (len(ordered) + 1)
        total_count, total_sum = 0.0, 0.0
        for h in views:
            # cumulative le rows → per-bucket counts on the union grid
            prev = 0.0
            cum_by_le = dict(sorted(h["le"].items()))
            inf_cum = cum_by_le.get(math.inf, h["count"])
            for i, b in enumerate(ordered):
                cum = cum_by_le.get(b, prev)
                buckets[i] += max(0.0, cum - prev)
                prev = max(prev, cum)
            buckets[-1] += max(0.0, inf_cum - prev)
            total_count += h["count"]
            total_sum += h["sum"]
        if base_h is not None:
            idx = {b: i for i, b in enumerate(ordered)}
            for b, cnt in zip(base_h["bounds"], base_h["buckets"]):
                buckets[idx[b]] += cnt
            buckets[-1] += base_h["buckets"][-1]
            total_count += base_h["count"]
            total_sum += base_h["sum"]
        return {"bounds": ordered, "buckets": buckets,
                "count": total_count, "sum": total_sum}


class FleetSlo:
    """Fleet-level SLO burn: the stock ``serve.slo.SloEngine`` running
    over a ``FederatedRegistry``.  Construct it BEFORE traffic (the
    engine's construction-time baseline is what keeps pre-existing
    cumulative counts from reading as instantaneous burn) and call
    ``evaluate(scrapes)`` with fresh per-replica raw expositions on
    each pass — the router does this from ``alerts()``."""

    def __init__(self, specs=None, *, base_registry=None,
                 fast_window_s: float = 300.0,
                 slow_window_s: float = 3600.0):
        from jepsen_tpu.serve import slo as _slo  # lazy: obs must not
        # import serve at module load (layering)
        self.registry = FederatedRegistry(base=base_registry)
        self.engine = _slo.SloEngine(
            specs, registry=self.registry,
            fast_window_s=fast_window_s, slow_window_s=slow_window_s)

    def evaluate(self, scrapes: Mapping[str, str], now=None) -> list:
        self.registry.update(scrapes)
        return self.engine.evaluate(now)

    def alerts(self) -> dict:
        return self.engine.alerts()


# ---------------------------------------------------------------------------
# Merged Perfetto timeline (one process group per recorder stream)
# ---------------------------------------------------------------------------


def merge_trace_events(streams: Iterable) -> dict:
    """N recorder streams → one Perfetto document.

    ``streams``: ``(label, events)`` or ``(label, events, skipped)``
    per recorder (the router's plus one per replica).  Each stream is
    clock-aligned on its ``meta`` t0 epoch (``align_streams``), then
    rendered through the stock single-stream converter and rewritten
    into its own process group: a synthetic pid per stream (recorder
    pids can collide across hosts and a dead replica's pid can be
    reused), the process named ``<label> (host, pid N)``, and every
    timestamp shifted by the stream's epoch offset.  Request lanes,
    device lanes, stream lanes, and counter tracks all stay per-stream
    — counter tracks per replica fall out of the process split.  The
    cross-process request story lives in the trace ids: a hop-spanning
    request's ``fleet.route`` (router group) and ``serve.request``
    (replica group) rows share one ``args.trace``, and
    ``otherData.cross_process_traces`` lists them."""
    aligned, info = align_streams(streams)
    out: list[dict] = []
    skipped = 0
    groups = []
    for i, a in enumerate(aligned):
        doc = to_trace_events(a["events"], skipped_lines=a["skipped"])
        pid = i + 1
        meta = a["meta"]
        pname = (f"{a['label']} ({meta.get('host', '?')}, "
                 f"pid {meta.get('pid', '?')})")
        off_us = a["offset_s"] * 1e6
        for row in doc["traceEvents"]:
            row = dict(row)
            row["pid"] = pid
            if row.get("ph") == "M" and row.get("name") == "process_name":
                row["args"] = {"name": pname}
            if "ts" in row:
                row["ts"] = round(row["ts"] + off_us, 1)
            out.append(row)
        out.append({"ph": "M", "name": "process_sort_index", "pid": pid,
                    "args": {"sort_index": i}})
        skipped += a["skipped"]
        groups.append({
            "label": a["label"], "pid": pid, "host": meta.get("host"),
            "recorder_pid": meta.get("pid"),
            "t0": meta.get("t0", meta.get("wall-clock")),
            "offset_s": a["offset_s"],
            "requests": doc["otherData"]["requests"],
            "devices": doc["otherData"]["devices"],
            "streams": doc["otherData"].get("streams", 0),
        })
    return {
        "traceEvents": out,
        "displayTimeUnit": "ms",
        "otherData": {
            "t0": info["t0"],
            "processes": groups,
            "offsets": info["offsets"],
            "missing_t0": info["missing_t0"],
            "cross_process_traces": info["cross_process_traces"],
            "residual_skew_s": info["residual_skew_s"],
            "skew_pairs": info["skew_pairs"],
            "requests": sum(g["requests"] for g in groups),
            "devices": sum(g["devices"] for g in groups),
            "streams": sum(g["streams"] for g in groups),
            "skipped_lines": skipped,
        },
    }
