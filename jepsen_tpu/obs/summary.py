"""Roll a telemetry event stream up into the ``telemetry.json`` summary.

The summary is the artifact the web UI's run page, ``bench.py``'s JSON
line, and ``tools/trace_summarize.py`` all render: a fixed shape that
later perf PRs report against.

  {"version": 1,
   "wall_s":   <last event end, seconds since recording start>,
   "phases":   [{"phase", "wall_s", "count"}, ...]      # phase.* spans
   "checkers": [{"checker", "seconds", "count", "valid"}, ...]
   "serve":    {"batches", "requests", "batch_wall_s", "avg_batch_requests",
                "avg_occupancy", "avg_padding_waste",
                "continuous_occupancy", "rungs", "rung_joined",  # rung-
                                       # boundary admission (continuous
                                       # batching; PR 6)
                "admission": {"count", "mean_s", "max_s"},
                "request":   {"count", "mean_s", "max_s"},
                "request_by_class": {tier: {"count", "mean_s", "max_s"}},
                "fastpath_resolved", "fastpath_escalated",
                "submitted", "completed", "rejected", "expired", "drained"}
                                                        # serve.* events
   "fleet":    {"routed", "spilled", "parked", "fenced", "resubmitted",
                "rollouts", "replicas", "replicas_healthy",
                "rollout": {"count", "total_s", "max_s"}}
                               # fleet.* events (the front-door router,
                               # jepsen_tpu.serve.fleet): placement +
                               # spill volume, fence/resubmission churn,
                               # and zero-downtime rollout spans
   "streams":  {"opened", "closed", "rejected", "ops", "rescans",
                "epochs": {"count", "total_s", "max_s"},
                "session": {"count", "total_s", "max_s"},
                "verdicts": {verdict: count}}
                               # stream.* events (checker.streaming +
                               # the serving layer's stream sessions):
                               # online-checking volume, epoch scan
                               # time, and mid-stream verdict census
   "ladder":   [{"stage", "engine", "capacity", "lanes", "seconds",
                 "resolved", "refuted", "unknowns_remaining",
                 "launches", "compile_launches", "compile_s",
                 "execute_s", "peak_frontier", "lossy", "dedup"}, ...]
   "dedup":    [{"backend", "candidates", "capacity", "probes",
                 "per_round_us", "interpret"?}, ...]    # dedup.round spans
                               # ("interpret" only on pallas probes: True
                               # marks interpreter-mode timings that must
                               # never compare against chip rows)
   "elle":     [{"stage", "seconds", "count", "max_s"}, ...]
                               # elle.* inference substage spans (nodes /
                               # anomalies / edges / scc / infer_batch —
                               # the column-native inference pipeline)
   "memory":   {"device_bytes_peak", "spill_rows", "spill_bytes",
                "spill_merges", "factorizations", "undecidable",
                "oom_spills"}          # bounded-memory layer (ops.spill)
   "faults":   [{"fault", "count", "seconds", "detail"}, ...]  # fault.* events
   "critpath": {"wall_s", "total_s",
                "spans": [{"span", "cp_s", "count", "total_s",
                           "slack_s"}, ...]}
                               # critical-path rollup (obs.critpath):
                               # what bounds wall clock, ranked — the
                               # perf ledger records cp seconds per
                               # stage, not just inclusive time
   "telemetry": {"skipped_lines"}  # truncated/corrupt jsonl lines the
                               # tolerant reader dropped (present only
                               # when nonzero)
   "counters": {name: total}
   "gauges":   {name: last value}
   "spans":    {name: {"count", "total_s", "max_s"}}}

The ladder table mirrors parallel.batch_analysis's capacity-escalation
stages: one row per rung with the quantities the beam-search literature
instruments (frontier occupancy, truncation/loss, per-stage utilization)
plus the compile-vs-execute split ("compile_s" sums launches that hit a
fresh (engine, shape) bucket — compile + first execute; "execute_s" sums
warm launches).

The faults table aggregates every ``fault.*`` event the fault-tolerance
layer emits (jepsen_tpu.faults / parallel.batch): launch retries, OOM
lane halvings, degraded launches, checkpoint saves/loads, confirmation
resubmits, and deadline trips — one row per fault kind with its count,
total seconds (for the span-shaped ones, e.g. checkpoint writes), and
the last event's detail attributes.

The serve section aggregates the check-serving subsystem's ``serve.*``
events (jepsen_tpu.serve): shared-batch count/occupancy/padding waste
from ``serve.batch`` spans, admission-wait and end-to-end request
latency from ``serve.admission``/``serve.request`` span events, and the
admission counters (submitted/completed/rejected/expired/drained).
Empty dict when a run never touched the service.

The fleet section aggregates the front-door router's ``fleet.*`` events
(jepsen_tpu.serve.fleet): routing volume (``fleet.routed`` summed over
replica labels) vs load-spill (``fleet.spilled``) and no-replica parking
(``fleet.parked``), failure-containment churn (``fleet.fenced``,
``fleet.resubmitted``), rollout counts/spans, and the last-seen replica
census gauges.  Empty dict for single-service runs.
"""

from __future__ import annotations

from typing import Iterable, Mapping

#: ladder.stage span attributes copied verbatim into the stage table.
_STAGE_KEYS = (
    "resolved", "refuted", "unknowns_remaining", "launches",
    "compile_launches", "compile_s", "execute_s", "peak_frontier", "lossy",
    "dedup", "degraded", "device_bytes_peak",
    # fused-kernel rungs (dedup backend "pallas"): static routing
    # verdict + the kernel's tile/VMEM occupancy + execution mode —
    # the rows the chip-day flip decision reads next to the compete
    # ledger record
    "pallas_routed", "pallas_tile", "pallas_vmem_bytes", "pallas_interpret",
    # mesh-spanning rungs (round 12): device count + the VMEM budget the
    # routing gate compared against + whether the mesh stage could lift
    # a rung the single-device budget spilled
    "mesh_devices", "pallas_vmem_budget_bytes", "pallas_mesh_feasible",
)


def _r(x: float) -> float:
    return round(float(x), 6)


#: attrs copied (last write wins) into a fault row's "detail" string.
_FAULT_DETAIL_KEYS = (
    "what", "engine", "capacity", "stage", "lanes", "lanes_from", "lanes_to",
    "at", "unresolved", "error", "reason", "history", "barrier",
)


def _fault_detail(attrs: Mapping) -> str:
    parts = [f"{k}={attrs[k]}" for k in _FAULT_DETAIL_KEYS if k in attrs]
    return " ".join(parts)


def summarize(events: Iterable[Mapping], *, skipped_lines: int = 0) -> dict:
    events = list(events)
    spans: dict[str, dict] = {}
    phases: list[dict] = []
    phase_by_name: dict[str, dict] = {}
    checkers: dict[str, dict] = {}
    ladder: list[dict] = []
    dedup: dict[tuple, dict] = {}
    faults: dict[str, dict] = {}
    counters: dict[str, float] = {}
    gauges: dict[str, object] = {}
    serve_batch = {"count": 0, "requests": 0, "wall": 0.0, "occ": 0.0,
                   "waste": 0.0}
    serve_lat = {
        "serve.admission": {"count": 0, "total": 0.0, "max": 0.0},
        "serve.request": {"count": 0, "total": 0.0, "max": 0.0},
    }
    #: per-latency-class end-to-end latency (serve.request "tier" attr).
    serve_class: dict[str, dict] = {}
    #: continuous-batching accumulators: per-rung occupancy is averaged
    #: weighted by rung count (serve.batch spans carry the per-ladder
    #: mean + rung count; joiners admitted at rung boundaries).
    serve_cont = {"rungs": 0, "occ": 0.0, "joined": 0}
    #: bounded-memory accumulators (frontier.* counters/events + the
    #: device.buffer_bytes gauge's MAX — the gauges section keeps only
    #: the last write, which understates a run's true high-water mark).
    mem = {"device_bytes_peak": 0, "undecidable": 0}
    #: verdict-provenance accumulators (the provenance.* counter family:
    #: evidence bundles emitted per source/verdict + emission errors).
    prov = {"bundles": 0, "emit_errors": 0, "by_source": {}, "by_verdict": {}}
    #: streaming-verdict census (stream.verdict span events).
    stream_verdicts: dict[str, int] = {}
    wall = 0.0

    def _fault_row(name: str) -> dict:
        return faults.setdefault(
            name, {"fault": name[len("fault."):], "count": 0, "seconds": 0.0,
                   "detail": ""}
        )
    for ev in events:
        et = ev.get("type")
        t = float(ev.get("t") or 0.0)
        if et == "span":
            name = str(ev.get("name"))
            dur = float(ev.get("dur") or 0.0)
            wall = max(wall, t + dur)
            s = spans.setdefault(name, {"count": 0, "total_s": 0.0, "max_s": 0.0})
            s["count"] += 1
            s["total_s"] += dur
            s["max_s"] = max(s["max_s"], dur)
            attrs = ev.get("attrs") or {}
            if name.startswith("phase."):
                p = phase_by_name.get(name)
                if p is None:
                    p = phase_by_name[name] = {
                        "phase": name[len("phase."):], "wall_s": 0.0, "count": 0,
                    }
                    phases.append(p)  # first-seen order = lifecycle order
                p["wall_s"] += dur
                p["count"] += 1
                if ev.get("err"):
                    p["error"] = ev["err"]
            elif name == "checker.check":
                cn = str(attrs.get("checker", "?"))
                c = checkers.setdefault(
                    cn, {"checker": cn, "seconds": 0.0, "count": 0, "valid": None}
                )
                c["seconds"] += dur
                c["count"] += 1
                if "valid" in attrs:
                    c["valid"] = attrs["valid"]
                if ev.get("err"):
                    c["error"] = ev["err"]
            elif name == "ladder.stage":
                row = {
                    "stage": attrs.get("stage"),
                    "engine": attrs.get("engine"),
                    "capacity": attrs.get("capacity"),
                    "lanes": attrs.get("lanes"),
                    "seconds": _r(dur),
                }
                for k in _STAGE_KEYS:
                    if k in attrs:
                        row[k] = attrs[k]
                ladder.append(row)
            elif name in ("dedup.round", "dedup.mesh_round"):
                # per-round dedup timing probes (ops.hashing.
                # dedup_round_probe / sharded.mesh_round_probe): one
                # table row per (backend, shape, mesh width), averaging
                # repeated probes
                key = (
                    attrs.get("backend"), attrs.get("candidates"),
                    attrs.get("capacity"), attrs.get("mesh_devices"),
                )
                d = dedup.setdefault(key, {
                    "backend": attrs.get("backend"),
                    "candidates": attrs.get("candidates"),
                    "capacity": attrs.get("capacity"),
                    "probes": 0, "_total_us": 0.0,
                })
                if attrs.get("mesh_devices") is not None:
                    d["mesh_devices"] = int(attrs["mesh_devices"])
                d["probes"] += 1
                d["_total_us"] += float(attrs.get("per_round_us") or dur * 1e6)
                if "interpret" in attrs:
                    # pallas probes tag their execution mode so interpret
                    # rows never read as chip rows in the rollup
                    d["interpret"] = bool(attrs["interpret"])
            elif name == "serve.batch":
                serve_batch["count"] += 1
                serve_batch["requests"] += int(attrs.get("requests") or 0)
                serve_batch["wall"] += dur
                serve_batch["occ"] += float(attrs.get("occupancy") or 0.0)
                serve_batch["waste"] += float(attrs.get("padding_waste") or 0.0)
                rungs = int(attrs.get("rungs") or 0)
                if rungs and attrs.get("continuous_occupancy") is not None:
                    serve_cont["rungs"] += rungs
                    serve_cont["occ"] += (
                        float(attrs["continuous_occupancy"]) * rungs
                    )
                serve_cont["joined"] += int(attrs.get("joined") or 0)
            elif name == "stream.verdict":
                v = str(attrs.get("verdict") or "?")
                stream_verdicts[v] = stream_verdicts.get(v, 0) + 1
            elif name in serve_lat:
                sl = serve_lat[name]
                sl["count"] += 1
                sl["total"] += dur
                sl["max"] = max(sl["max"], dur)
                if name == "serve.request" and attrs.get("tier"):
                    sc = serve_class.setdefault(
                        str(attrs["tier"]),
                        {"count": 0, "total": 0.0, "max": 0.0},
                    )
                    sc["count"] += 1
                    sc["total"] += dur
                    sc["max"] = max(sc["max"], dur)
            if name.startswith("fault."):
                f = _fault_row(name)
                f["count"] += 1
                f["seconds"] += dur
                if attrs:
                    f["detail"] = _fault_detail(attrs)
        elif et == "counter":
            wall = max(wall, t)
            name = str(ev.get("name"))
            counters[name] = counters.get(name, 0) + (ev.get("n") or 1)
            if name == "provenance.bundle":
                n = ev.get("n") or 1
                a = ev.get("attrs") or {}
                prov["bundles"] += n
                src = str(a.get("source") or "?")
                prov["by_source"][src] = prov["by_source"].get(src, 0) + n
                vd = str(a.get("verdict") or "?")
                prov["by_verdict"][vd] = prov["by_verdict"].get(vd, 0) + n
            elif name == "provenance.emit_error":
                prov["emit_errors"] += ev.get("n") or 1
            if name.startswith("fault."):
                f = _fault_row(name)
                f["count"] += ev.get("n") or 1
                if ev.get("attrs"):
                    f["detail"] = _fault_detail(ev["attrs"])
        elif et == "gauge":
            wall = max(wall, t)
            name = str(ev.get("name"))
            gauges[name] = ev.get("value")
            if name == "device.buffer_bytes":
                try:
                    mem["device_bytes_peak"] = max(
                        mem["device_bytes_peak"], int(ev.get("value") or 0))
                except (TypeError, ValueError):
                    pass
        elif et == "event":
            wall = max(wall, t)
            name = str(ev.get("name"))
            if name == "frontier.undecidable":
                mem["undecidable"] += 1
            if name.startswith("fault."):
                f = _fault_row(name)
                f["count"] += 1
                if ev.get("attrs"):
                    f["detail"] = _fault_detail(ev["attrs"])
    for p in phases:
        p["wall_s"] = _r(p["wall_s"])
    out_checkers = sorted(checkers.values(), key=lambda c: -c["seconds"])
    for c in out_checkers:
        c["seconds"] = _r(c["seconds"])
    ladder.sort(key=lambda r: (r["stage"] is None, r["stage"]))
    out_dedup = []
    for d in dedup.values():
        d["per_round_us"] = round(d.pop("_total_us") / max(1, d["probes"]), 1)
        out_dedup.append(d)
    out_dedup.sort(key=lambda d: (str(d["backend"]), d["candidates"] or 0))
    for name, s in spans.items():
        s["total_s"] = _r(s["total_s"])
        s["max_s"] = _r(s["max_s"])
    out_faults = [faults[k] for k in sorted(faults)]
    for f in out_faults:
        f["seconds"] = _r(f["seconds"])
    serve: dict = {}
    if serve_batch["count"]:
        nb = serve_batch["count"]
        serve.update(
            batches=nb,
            requests=serve_batch["requests"],
            batch_wall_s=_r(serve_batch["wall"]),
            avg_batch_requests=round(serve_batch["requests"] / nb, 2),
            avg_occupancy=round(serve_batch["occ"] / nb, 4),
            avg_padding_waste=round(serve_batch["waste"] / nb, 4),
        )
    if serve_cont["rungs"]:
        serve["continuous_occupancy"] = round(
            serve_cont["occ"] / serve_cont["rungs"], 4
        )
        serve["rungs"] = serve_cont["rungs"]
    if serve_cont["joined"]:
        serve["rung_joined"] = serve_cont["joined"]
    for span_name, out_key in (("serve.admission", "admission"),
                               ("serve.request", "request")):
        sl = serve_lat[span_name]
        if sl["count"]:
            serve[out_key] = {
                "count": sl["count"],
                "mean_s": _r(sl["total"] / sl["count"]),
                "max_s": _r(sl["max"]),
            }
    if serve_class:
        serve["request_by_class"] = {
            tier: {
                "count": sc["count"],
                "mean_s": _r(sc["total"] / sc["count"]),
                "max_s": _r(sc["max"]),
            }
            for tier, sc in sorted(serve_class.items())
        }
    memory: dict = {}
    mem_counters = {
        "spill_rows": "frontier.spill_rows",
        "spill_bytes": "frontier.spill_bytes",
        "spill_merges": "frontier.spill_merges",
        "factorizations": "frontier.factorizations",
        "oom_spills": "fault.oom.spill",
    }
    for out_key, cname in mem_counters.items():
        if cname in counters:
            memory[out_key] = counters[cname]
    if mem["device_bytes_peak"]:
        memory["device_bytes_peak"] = mem["device_bytes_peak"]
    if mem["undecidable"]:
        memory["undecidable"] = mem["undecidable"]
    elle = [
        {"stage": name[len("elle."):], "seconds": s["total_s"],
         "count": s["count"], "max_s": s["max_s"]}
        for name, s in spans.items() if name.startswith("elle.")
    ]
    for cname in ("submitted", "completed", "rejected", "expired", "drained",
                  "fastpath_resolved", "fastpath_escalated",
                  "graphs", "graph_batches",
                  # self-healing layer (serve.health)
                  "quarantined", "quarantine_hit", "breaker_rejected",
                  "breaker_opened", "watchdog_trip", "journal_replayed",
                  "placement_replaced", "drain_error"):
        if f"serve.{cname}" in counters:
            serve[cname] = counters[f"serve.{cname}"]
    fleet: dict = {}
    for cname in ("routed", "spilled", "parked", "fenced", "resubmitted",
                  "rollouts"):
        if f"fleet.{cname}" in counters:
            fleet[cname] = counters[f"fleet.{cname}"]
    for gname in ("replicas", "replicas_healthy"):
        if f"fleet.{gname}" in gauges:
            fleet[gname] = gauges[f"fleet.{gname}"]
    if "fleet.rollout" in spans:
        ro = spans["fleet.rollout"]
        fleet["rollout"] = {"count": ro["count"], "total_s": ro["total_s"],
                            "max_s": ro["max_s"]}
    streams: dict = {}
    for cname, out_key in (("stream.opened", "opened"),
                           ("stream.closed", "closed"),
                           ("stream.rejected", "rejected"),
                           ("stream.ops", "ops"),
                           ("stream.rescan", "rescans")):
        if cname in counters:
            streams[out_key] = counters[cname]
    for sname, out_key in (("stream.epoch", "epochs"),
                           ("stream.session", "session")):
        if sname in spans:
            sp = spans[sname]
            streams[out_key] = {"count": sp["count"],
                                "total_s": sp["total_s"],
                                "max_s": sp["max_s"]}
    if stream_verdicts:
        streams["verdicts"] = dict(sorted(stream_verdicts.items()))
    from jepsen_tpu.obs import critpath as _critpath

    out = {
        "version": 1,
        "wall_s": _r(wall),
        "phases": phases,
        "checkers": out_checkers,
        "serve": serve,
        "fleet": fleet,
        "streams": streams,
        "ladder": ladder,
        "dedup": out_dedup,
        "elle": elle,
        "memory": memory,
        "provenance": (
            {k: v for k, v in prov.items() if v}
            if prov["bundles"] or prov["emit_errors"] else {}
        ),
        "faults": out_faults,
        "critpath": _critpath.critpath_rollup(events),
        "counters": counters,
        "gauges": gauges,
        "spans": spans,
    }
    if skipped_lines:
        out["telemetry"] = {"skipped_lines": int(skipped_lines)}
    return out


# ---------------------------------------------------------------------------
# Text rendering (tools/trace_summarize.py and profile scripts)
# ---------------------------------------------------------------------------


def _mb(b) -> str:
    """Bytes as a compact MB cell ('' when the stage never sampled;
    sub-0.1MB footprints keep three decimals so CPU-backend samples
    don't render as an ambiguous 0.0)."""
    if not b:
        return ""
    mb = float(b) / 1e6
    return str(round(mb, 1 if mb >= 0.1 else 3))


def _fmt_row(cells, widths) -> str:
    return "  ".join(str(c).ljust(w) for c, w in zip(cells, widths)).rstrip()


def _table(headers: list[str], rows: list[list]) -> str:
    widths = [
        max(len(str(h)), *(len(str(r[i])) for r in rows)) if rows else len(str(h))
        for i, h in enumerate(headers)
    ]
    lines = [_fmt_row(headers, widths), _fmt_row(["-" * w for w in widths], widths)]
    lines += [_fmt_row(r, widths) for r in rows]
    return "\n".join(lines)


def format_summary(summary: Mapping) -> str:
    """Human-readable phase / checker / ladder tables for a summary dict."""
    parts: list[str] = [f"telemetry summary (wall {summary.get('wall_s', 0)} s)"]
    if summary.get("phases"):
        parts.append("\nphases:")
        parts.append(_table(
            ["phase", "wall_s", "count"],
            [[p["phase"], p["wall_s"], p["count"]] for p in summary["phases"]],
        ))
    if summary.get("checkers"):
        parts.append("\ncheckers:")
        parts.append(_table(
            ["checker", "seconds", "count", "valid?"],
            [[c["checker"], c["seconds"], c["count"], c.get("valid")]
             for c in summary["checkers"]],
        ))
    if summary.get("serve"):
        s = summary["serve"]
        parts.append("\ncheck service:")
        rows = [[k, s[k]] for k in (
            "batches", "requests", "batch_wall_s", "avg_batch_requests",
            "avg_occupancy", "avg_padding_waste", "continuous_occupancy",
            "rungs", "rung_joined", "fastpath_resolved",
            "fastpath_escalated", "submitted", "completed",
            "rejected", "expired", "drained") if k in s]
        for key, label in (("admission", "admission wait"),
                           ("request", "request latency")):
            if key in s:
                lat = s[key]
                rows.append([f"{label} mean_s", lat["mean_s"]])
                rows.append([f"{label} max_s", lat["max_s"]])
        for tier, lat in (s.get("request_by_class") or {}).items():
            rows.append([f"request[{tier}] mean_s", lat["mean_s"]])
            rows.append([f"request[{tier}] max_s", lat["max_s"]])
        parts.append(_table(["serve", "value"], rows))
    if summary.get("fleet"):
        fle = summary["fleet"]
        parts.append("\nfleet (front-door router):")
        rows = [[k, fle[k]] for k in (
            "routed", "spilled", "parked", "fenced", "resubmitted",
            "rollouts", "replicas", "replicas_healthy") if k in fle]
        if "rollout" in fle:
            rows.append(["rollout total_s", fle["rollout"]["total_s"]])
            rows.append(["rollout max_s", fle["rollout"]["max_s"]])
        parts.append(_table(["fleet", "value"], rows))
    if summary.get("streams"):
        st = summary["streams"]
        parts.append("\nstreams (online checking):")
        rows = [[k, st[k]] for k in (
            "opened", "closed", "rejected", "ops", "rescans") if k in st]
        for key, label in (("epochs", "epoch"), ("session", "session")):
            if key in st:
                rows.append([f"{label} count", st[key]["count"]])
                rows.append([f"{label} total_s", st[key]["total_s"]])
                rows.append([f"{label} max_s", st[key]["max_s"]])
        for vd, n in (st.get("verdicts") or {}).items():
            rows.append([f"verdict[{vd}]", n])
        parts.append(_table(["stream", "value"], rows))
    if summary.get("ladder"):
        headers = ["stage", "engine", "capacity", "lanes", "seconds",
                   "resolved", "refuted", "unknowns", "launches",
                   "compile_s", "execute_s", "peak", "lossy", "dedup",
                   "dev_MB"]
        rows = []
        for r in summary["ladder"]:
            rows.append([
                r.get("stage"), r.get("engine"), r.get("capacity"),
                r.get("lanes"), r.get("seconds"), r.get("resolved", ""),
                r.get("refuted", ""), r.get("unknowns_remaining", ""),
                r.get("launches", ""), r.get("compile_s", ""),
                r.get("execute_s", ""), r.get("peak_frontier", ""),
                r.get("lossy", ""), r.get("dedup", ""),
                _mb(r.get("device_bytes_peak")),
            ])
        parts.append("\nladder stages:")
        parts.append(_table(headers, rows))
    if summary.get("dedup"):
        parts.append("\ndedup rounds (per-round probe, per backend; "
                     "interp=True marks Pallas-interpreter timings):")
        parts.append(_table(
            ["backend", "candidates", "capacity", "probes", "per_round_us",
             "interp"],
            [[d.get("backend"), d.get("candidates"), d.get("capacity"),
              d.get("probes"), d.get("per_round_us"),
              d.get("interpret", "")]
             for d in summary["dedup"]],
        ))
    if summary.get("elle"):
        parts.append("\nelle inference (column-native substages):")
        parts.append(_table(
            ["stage", "seconds", "count", "max_s"],
            [[e.get("stage"), e.get("seconds"), e.get("count"),
              e.get("max_s")] for e in summary["elle"]],
        ))
    if summary.get("memory"):
        mm = summary["memory"]
        parts.append("\nmemory (host spill / factorization / device peak):")
        rows = [[k, mm[k]] for k in (
            "device_bytes_peak", "spill_rows", "spill_bytes", "spill_merges",
            "factorizations", "oom_spills", "undecidable") if k in mm]
        parts.append(_table(["memory", "value"], rows))
    if summary.get("provenance"):
        pv = summary["provenance"]
        parts.append("\nverdict provenance (evidence bundles emitted):")
        rows = [["bundles", pv.get("bundles", 0)]]
        for src, n in sorted((pv.get("by_source") or {}).items()):
            rows.append([f"bundles[{src}]", n])
        for vd, n in sorted((pv.get("by_verdict") or {}).items()):
            rows.append([f"verdict[{vd}]", n])
        if pv.get("emit_errors"):
            rows.append(["emit_errors", pv["emit_errors"]])
        parts.append(_table(["provenance", "value"], rows))
    if summary.get("critpath", {}).get("spans"):
        cp = summary["critpath"]
        parts.append(
            f"\ncritical path ({cp.get('total_s', 0)} s on-path of "
            f"{cp.get('wall_s', 0)} s wall):")
        parts.append(_table(
            ["span", "critpath_s", "inclusive_s", "count", "slack_s"],
            [[r.get("span"), r.get("cp_s"), r.get("total_s"),
              r.get("count"), r.get("slack_s")]
             for r in cp["spans"]],
        ))
    if summary.get("telemetry", {}).get("skipped_lines"):
        parts.append(
            f"\ntelemetry: {summary['telemetry']['skipped_lines']} "
            "malformed jsonl line(s) skipped")
    if summary.get("faults"):
        parts.append("\nfaults (retries / degradations / checkpoints / deadline):")
        parts.append(_table(
            ["fault", "count", "seconds", "detail"],
            [[f.get("fault"), f.get("count"), f.get("seconds", ""),
              f.get("detail", "")] for f in summary["faults"]],
        ))
    if summary.get("counters"):
        parts.append("\ncounters:")
        parts.append(_table(
            ["counter", "total"],
            [[k, v] for k, v in sorted(summary["counters"].items())],
        ))
    if summary.get("gauges"):
        parts.append("\ngauges:")
        parts.append(_table(
            ["gauge", "value"],
            [[k, v] for k, v in sorted(summary["gauges"].items())],
        ))
    return "\n".join(parts) + "\n"
