"""Flight analyzer: latency decomposition, critical-path extraction,
and per-device bubble attribution over a telemetry event stream.

The span stream (PRs 1/5) records WHAT happened; this module answers
WHY a request took what it took and WHERE the idle time lives — the
analysis layer between ``telemetry.jsonl`` and a scheduling decision:

  * ``decompose_requests(events)`` — reconstruct each request's
    lifecycle from its trace id (admission → class-queue wait →
    pack/rung-join wait → shared-launch residence → confirm/demux
    tail) into a ``{stage: seconds}`` breakdown whose sum reconciles
    EXACTLY with the recorded ``serve.request`` end-to-end latency
    (the residual the span algebra can't attribute is named
    ``other_s``, never silently dropped).
  * ``critical_path(events)`` — over the run's span DAG (interval
    containment + the parent links ``obs.Ctx`` propagation records),
    the chain of span segments that bounds wall clock, per-span
    critical seconds (self time on the path, children excluded), and
    per-span slack (how much later a span could have finished without
    moving wall clock).  Total critical seconds ≤ run wall clock by
    construction.
  * ``device_timeline(events)`` — per-device busy/idle fractions and
    the bubble ratio from device-attributed launch spans
    (``ladder.launch``/``ladder.stage`` carry a ``devices`` attr; a
    ``lane_shard`` placement stamps every member device), plus an
    imbalance figure (max − min busy fraction) — the number the
    continuous-batching scheduler and the chip round are tuned
    against.

Stdlib-only and pure over event dicts: ``obs.summary`` embeds the
critical-path rollup in every ``telemetry.json``,
``tools/trace_summarize.py`` renders all three tables, and
``obs.regress.stage_rollup`` ships critical-path seconds per stage
into the perf ledger.
"""

from __future__ import annotations

from typing import Iterable, Mapping

__all__ = [
    "LAUNCH_SPANS", "Span", "critical_path", "decompose_requests",
    "device_timeline", "extract_spans", "format_critpath",
    "format_devices", "format_requests",
]

#: span names that represent a request's shared-launch residence, in
#: the order the decomposition searches them (a request rides exactly
#: one of these per lifecycle).
LAUNCH_SPANS = ("serve.batch", "serve.fastpath", "serve.graph_batch",
                "serve.graph")

#: interval tolerance: event ``t``/``dur`` are QUANTIZED to 1 µs by the
#: recorder (round(x, 6)), so containment/ordering comparisons must
#: absorb up to ~1 µs of rounding slop on each endpoint — a tolerance
#: below the quantization would misread genuinely nested spans as
#: concurrent roots and corrupt the attribution the perf ledger trends.
#: (Sub-2 µs segments fall below timestamp resolution and are dropped.)
_EPS = 2e-6


class Span:
    """One span instance from the stream (events carry name-based
    parent links only; instances are resolved by interval
    containment)."""

    __slots__ = ("name", "t", "dur", "end", "parent", "attrs", "trace",
                 "thread", "children", "cp_s", "slack_s")

    def __init__(self, ev: Mapping):
        self.name = str(ev.get("name"))
        self.t = float(ev.get("t") or 0.0)
        self.dur = max(0.0, float(ev.get("dur") or 0.0))
        self.end = self.t + self.dur
        self.parent = ev.get("parent")
        self.attrs = ev.get("attrs") or {}
        self.trace = ev.get("trace")
        self.thread = ev.get("thread")
        self.children: list[Span] = []
        self.cp_s = 0.0        # seconds on the critical path (self time)
        self.slack_s = None    # filled by critical_path

    def __repr__(self):  # pragma: no cover — debugging aid
        return f"Span({self.name!r}, t={self.t:.6f}, dur={self.dur:.6f})"


def extract_spans(events: Iterable[Mapping]) -> list["Span"]:
    """Every span-shaped event as a ``Span``, stream order preserved."""
    return [Span(ev) for ev in events if ev.get("type") == "span"]


# ---------------------------------------------------------------------------
# Per-request latency decomposition
# ---------------------------------------------------------------------------


def decompose_requests(events: Iterable[Mapping]) -> dict[str, dict]:
    """``{trace_id: {route_s, queue_s, pack_s, launch_s, confirm_s,
    other_s, total_s, tier, verdict, launch_span}}`` for every request
    whose end-to-end ``serve.request`` span landed in the stream.

    Stage algebra (every request's stages SUM to its ``total_s``):

      * ``route_s``   — the router hop (fleet deployments only): the
        ``fleet.route`` span stamped with this trace starts at router
        admission; the replica-side ``serve.request`` span starts at
        replica accept.  With the two recorder streams clock-aligned
        (obs.trace.align_streams), the gap between those starts IS the
        hop cost, and ``total_s`` grows by exactly it — reconciling
        router-side and replica-side stamps instead of trusting either
        alone.  0 when no route span carries the trace (single-process
        runs, or the router stream wasn't merged in).
      * ``queue_s``   — the ``serve.admission`` span: submit → picked
        into a wave/batch (the class-queue wait; a rung joiner's
        admission ends at its join boundary).
      * ``pack_s``    — picked → the shared launch span's start
        (service-side packing / placement / feeder overhead).
      * ``launch_s``  — residence inside the shared launch span
        (``serve.batch`` / ``serve.fastpath`` / ``serve.graph*``),
        clipped to the request's own lifetime.
      * ``confirm_s`` — the post-launch tail: the request outlived the
        shared span (confirmation drain, late demux).
      * ``other_s``   — the residual the spans above don't cover
        (e.g. a request resolved with no launch span at all: trivial
        fast paths, quarantine hits, queue expiry).
    """
    spans = extract_spans(events)
    requests: dict[str, Span] = {}
    admissions: dict[str, Span] = {}
    routes: dict[str, Span] = {}
    #: trace id -> the launch spans stamped with it (one indexing pass:
    #: the per-request loop must not scan every launch's member list —
    #: long recordings carry thousands of both).
    launches_by_tid: dict[str, list[Span]] = {}
    for s in spans:
        if s.name == "serve.request" and isinstance(s.trace, str):
            requests[s.trace] = s
        elif s.name == "serve.admission" and isinstance(s.trace, str):
            admissions[s.trace] = s
        elif s.name == "fleet.route" and isinstance(s.trace, str):
            # earliest route attempt wins: resubmission re-routes open
            # later and must not shrink the measured hop
            if s.trace not in routes or s.t < routes[s.trace].t:
                routes[s.trace] = s
        elif s.name in LAUNCH_SPANS:
            members = s.trace if s.trace is not None else ()
            if isinstance(members, str):
                members = (members,)
            extra = (s.attrs or {}).get("trace_ids") or ()
            seen = set()
            for tid in list(members) + list(extra):
                if isinstance(tid, str) and tid not in seen:
                    seen.add(tid)
                    launches_by_tid.setdefault(tid, []).append(s)
    out: dict[str, dict] = {}
    for tid, req in requests.items():
        total = req.dur
        t_sub, t_done = req.t, req.end
        # router hop: route span start (router admission) → request
        # span start (replica accept).  Only a route that genuinely
        # precedes the request counts — a negative gap is residual
        # clock skew the alignment already reported, not a stage.
        route = 0.0
        rt = routes.get(tid)
        if rt is not None and rt.t <= t_sub + _EPS:
            route = max(0.0, t_sub - rt.t)
            total += route
        adm = admissions.get(tid)
        queue = min(total, adm.dur) if adm is not None else 0.0
        t_picked = t_sub + queue
        # the launch span this request rode: the first one stamped with
        # its trace that overlaps its post-queue lifetime
        ride = None
        for ls in launches_by_tid.get(tid, ()):
            if ls.end > t_picked - _EPS and ls.t < t_done + _EPS:
                if ride is None or ls.t < ride.t:
                    ride = ls
        pack = launch = confirm = 0.0
        if ride is not None:
            l_start = max(t_picked, ride.t)
            l_end = min(ride.end, t_done)
            pack = max(0.0, min(ride.t, t_done) - t_picked)
            launch = max(0.0, l_end - l_start)
            confirm = max(0.0, t_done - max(ride.end, t_picked))
        other = total - (route + queue + pack + launch + confirm)
        if other < 0:
            # float rounding (event "t"/"dur" are rounded to µs): fold
            # the deficit back into the launch residence so the stages
            # still sum exactly
            launch = max(0.0, launch + other)
            other = 0.0
        row = {
            "route_s": round(route, 6),
            "queue_s": round(queue, 6),
            "pack_s": round(pack, 6),
            "launch_s": round(launch, 6),
            "confirm_s": round(confirm, 6),
            "other_s": round(other, 6),
            "total_s": round(total, 6),
            "tier": (req.attrs or {}).get("tier"),
            "verdict": (req.attrs or {}).get("verdict"),
            "launch_span": ride.name if ride is not None else None,
        }
        out[tid] = row
    return out


# ---------------------------------------------------------------------------
# Critical-path extraction
# ---------------------------------------------------------------------------


#: span names EXCLUDED from the critical-path structure: per-request
#: lifecycle measurements (serve.request covers submit→resolve and
#: would swallow the execution spans it merely re-measures — the
#: decomposition is their consumer, not the path).
_PATH_EXCLUDE = {"serve.request", "serve.admission", "fleet.route",
                 "fleet.resubmit", "fleet.spill"}


def _build_forest(spans: list[Span]) -> list[Span]:
    """Nest span instances by INTERVAL CONTAINMENT WITHIN A THREAD (a
    stack sweep over start-sorted spans per thread group, O(n log n)):
    a span's parent is the smallest open same-thread span whose
    interval contains it.  A single thread's overlapping spans are
    always genuinely nested; same-interval spans on DIFFERENT threads
    are concurrent work (parallel arms, confirm drains, graph-pool
    tasks) that must never be charged inside each other — they stay
    roots and the backward sweep arbitrates between them.  The
    recorded name-based parent links are cross-thread breadcrumbs, not
    timing structure.  Events without a ``thread`` stamp (pre-analyzer
    recordings) fall back to one containment-only group."""
    groups: dict[object, list[Span]] = {}
    for s in spans:
        groups.setdefault(s.thread, []).append(s)
    roots: list[Span] = []
    for members in groups.values():
        ordered = sorted(members, key=lambda s: (s.t, -s.dur))
        stack: list[Span] = []
        for s in ordered:
            while stack and stack[-1].end < s.end - _EPS:
                stack.pop()
            if stack and stack[-1].t <= s.t + _EPS \
                    and stack[-1].end + _EPS >= s.end:
                stack[-1].children.append(s)
            else:
                roots.append(s)
            stack.append(s)
    return roots


def _sweep(candidates: list[Span], t_lo: float, t_hi: float,
           segments: list[tuple[Span, float, float, float]]) -> float:
    """Backward critical-path sweep over ``[t_lo, t_hi]``: starting
    from the window's end, repeatedly pick the span that finished last
    at/closest before the cursor (among covering spans, the
    latest-STARTING one — the deepest active work), put its on-path
    segment on the chain, and jump the cursor to that span's start.
    Gaps (no span active) advance past silently — they are the
    enclosing scope's self time.  Each chosen segment recurses into the
    span's children; the child-covered seconds ride in the segment
    tuple so self time needs no quadratic post-pass.  Returns the
    seconds this level's segments cover (the caller's child coverage).

    O(n log n): candidates enter a start-keyed heap as the cursor
    crosses their end (end-sorted walk), and a span whose start the
    cursor has passed can never be eligible again, so every span is
    pushed and popped at most once.  This runs inside every
    ``summarize()``/``Recorder.close()`` — long recordings carry tens
    of thousands of spans."""
    import heapq

    cands = sorted(
        (s for s in candidates if s.end > t_lo + _EPS and s.t < t_hi - _EPS),
        key=lambda s: s.end,
    )
    heap: list[tuple[float, int, Span]] = []  # (-start, seq, span)
    i = len(cands) - 1
    seq = 0
    cursor = t_hi
    covered = 0.0
    while cursor > t_lo + _EPS:
        while i >= 0 and cands[i].end >= cursor - _EPS:
            heapq.heappush(heap, (-cands[i].t, seq, cands[i]))
            seq += 1
            i -= 1
        while heap and -heap[0][0] >= cursor - _EPS:
            heapq.heappop(heap)  # started at/after the cursor: done
        if not heap:
            if i < 0:
                break  # pure gap back to t_lo: scope self time
            cursor = cands[i].end  # jump the gap to the next span's end
            continue
        best = heap[0][2]
        seg_hi = min(best.end, cursor)
        seg_lo = max(best.t, t_lo)
        if seg_hi > seg_lo + _EPS:
            child_cov = (
                _sweep(best.children, seg_lo, seg_hi, segments)
                if best.children else 0.0
            )
            segments.append((best, seg_lo, seg_hi, child_cov))
            covered += seg_hi - seg_lo
        cursor = seg_lo
    return covered


def critical_path(events: Iterable[Mapping]) -> dict:
    """The run's critical path:

      {"wall_s": <last span end>,
       "total_s": <sum of on-path self seconds, ≤ wall_s>,
       "path": [{"span", "t", "end", "cp_s"}, ...],  # chain, time order
       "by_span": {name: {"cp_s", "count", "total_s"}},  # ranked
       "slack": {name: max slack seconds for off-path instances}}

    ``cp_s`` per segment is the segment's SELF time: the part of its
    on-path interval its own on-path children don't cover — so summing
    ``cp_s`` over the path (or ``by_span``) never double-counts nested
    spans and never exceeds wall clock.  ``slack`` estimates how much
    later an off-path span could have finished before it would have
    touched the path (the gap to the next on-path segment start, or to
    the end of the run)."""
    spans = [s for s in extract_spans(events)
             if s.name not in _PATH_EXCLUDE]
    if not spans:
        return {"wall_s": 0.0, "total_s": 0.0, "path": [], "by_span": {},
                "slack": {}}
    roots = _build_forest(spans)
    t_lo = min(s.t for s in spans)
    wall = max(s.end for s in spans)
    segments: list[tuple[Span, float, float, float]] = []
    _sweep(roots, t_lo, wall, segments)
    path = []
    on_path: set[int] = set()
    by_span: dict[str, dict] = {}
    total = 0.0
    for s, lo, hi, child_cov in segments:
        self_s = max(0.0, (hi - lo) - child_cov)
        s.cp_s += self_s
        on_path.add(id(s))
        total += self_s
        path.append({"span": s.name, "t": round(lo, 6), "end": round(hi, 6),
                     "cp_s": round(self_s, 6)})
        row = by_span.setdefault(
            s.name, {"cp_s": 0.0, "count": 0, "total_s": 0.0})
        row["cp_s"] += self_s
        row["count"] += 1
    path.sort(key=lambda seg: seg["t"])
    for s in spans:
        row = by_span.get(s.name)
        if row is not None:
            row["total_s"] += s.dur
    # slack for off-path spans: gap to the next on-path segment start
    starts = sorted(seg["t"] for seg in path)
    slack: dict[str, float] = {}
    for s in spans:
        if id(s) in on_path:
            s.slack_s = 0.0
            continue
        nxt = next((t for t in starts if t >= s.end - _EPS), wall)
        s.slack_s = max(0.0, nxt - s.end)
        if s.name not in slack or s.slack_s > slack[s.name]:
            slack[s.name] = round(s.slack_s, 6)
    for row in by_span.values():
        row["cp_s"] = round(row["cp_s"], 6)
        row["total_s"] = round(row["total_s"], 6)
    return {
        "wall_s": round(wall - t_lo, 6),
        "total_s": round(min(total, wall - t_lo), 6),
        "path": path,
        "by_span": dict(sorted(by_span.items(),
                               key=lambda kv: -kv[1]["cp_s"])),
        "slack": slack,
    }


# ---------------------------------------------------------------------------
# Per-device timeline + bubble attribution
# ---------------------------------------------------------------------------

#: span names whose ``devices`` attr places device work on the timeline.
_DEVICE_SPANS = ("ladder.launch", "sharded.lane_launch", "sharded.launch")


def _union_seconds(intervals: list[tuple[float, float]]) -> float:
    if not intervals:
        return 0.0
    intervals.sort()
    total = 0.0
    lo, hi = intervals[0]
    for a, b in intervals[1:]:
        if a > hi:
            total += hi - lo
            lo, hi = a, b
        else:
            hi = max(hi, b)
    return total + (hi - lo)


def span_devices(span: Mapping | Span) -> list[int]:
    """The device ids a span's work ran on (``devices`` list or a
    single ``device`` attr), [] when unattributed."""
    attrs = span.attrs if isinstance(span, Span) else (
        span.get("attrs") or {})
    devs = attrs.get("devices")
    if devs is None and attrs.get("device") is not None:
        devs = [attrs["device"]]
    if devs is None:
        return []
    out = []
    for d in devs if isinstance(devs, (list, tuple)) else [devs]:
        try:
            out.append(int(d))
        except (TypeError, ValueError):
            continue
    return out


def device_timeline(events: Iterable[Mapping]) -> dict:
    """Per-device busy/idle/bubble fractions over the observed device
    window:

      {"window_s": <first device-span start → last end>,
       "devices": {id: {"busy_s", "idle_s", "busy_frac", "idle_frac",
                        "launches"}},
       "bubble_ratio": <mean idle fraction>,
       "imbalance": <max − min busy fraction>}

    Busy time per device is the interval UNION of the launch spans
    attributed to it (overlapping launches never double-count), so
    ``busy_frac + idle_frac == 1`` per device by construction.  The
    bubble ratio is the device-mean idle fraction — on a single-bucket
    load it equals 1 − occupancy, which is what the live
    ``serve_device_bubble_ratio`` gauge asserts against."""
    per_dev: dict[int, list[tuple[float, float]]] = {}
    counts: dict[int, int] = {}
    t_lo, t_hi = None, None
    for ev in events:
        if ev.get("type") != "span" or ev.get("name") not in _DEVICE_SPANS:
            continue
        devs = span_devices(ev)
        if not devs:
            continue
        t = float(ev.get("t") or 0.0)
        end = t + max(0.0, float(ev.get("dur") or 0.0))
        t_lo = t if t_lo is None else min(t_lo, t)
        t_hi = end if t_hi is None else max(t_hi, end)
        for d in devs:
            per_dev.setdefault(d, []).append((t, end))
            counts[d] = counts.get(d, 0) + 1
    if not per_dev:
        return {"window_s": 0.0, "devices": {}, "bubble_ratio": None,
                "imbalance": None}
    window = max(_EPS, t_hi - t_lo)
    devices: dict[int, dict] = {}
    fracs = []
    for d in sorted(per_dev):
        busy = min(window, _union_seconds(per_dev[d]))
        frac = busy / window
        fracs.append(frac)
        devices[d] = {
            "busy_s": round(busy, 6),
            "idle_s": round(window - busy, 6),
            "busy_frac": round(frac, 6),
            "idle_frac": round(1.0 - frac, 6),
            "launches": counts[d],
        }
    return {
        "window_s": round(window, 6),
        "devices": devices,
        "bubble_ratio": round(1.0 - sum(fracs) / len(fracs), 6),
        "imbalance": round(max(fracs) - min(fracs), 6),
    }


# ---------------------------------------------------------------------------
# Summary embedding + text rendering (obs.summary / trace_summarize)
# ---------------------------------------------------------------------------


def critpath_rollup(events: Iterable[Mapping], top: int = 16) -> dict:
    """The compact critical-path section ``telemetry.json`` carries:
    total on-path seconds, wall, and the top spans by critical seconds
    (with slack for the off-path view)."""
    cp = critical_path(events)
    rows = [
        {"span": name, "cp_s": row["cp_s"], "count": row["count"],
         "total_s": row["total_s"],
         "slack_s": cp["slack"].get(name, 0.0)}
        for name, row in list(cp["by_span"].items())[:top]
    ]
    return {"wall_s": cp["wall_s"], "total_s": cp["total_s"], "spans": rows}


def _fmt_table(headers: list[str], rows: list[list]) -> str:
    widths = [
        max(len(str(h)), *(len(str(r[i])) for r in rows)) if rows
        else len(str(h))
        for i, h in enumerate(headers)
    ]
    def line(cells):
        return "  ".join(
            str(c).ljust(w) for c, w in zip(cells, widths)).rstrip()
    return "\n".join(
        [line(headers), line(["-" * w for w in widths])]
        + [line(r) for r in rows])


def format_requests(decomp: Mapping[str, Mapping]) -> str:
    """The per-request decomposition as a text table (trace_summarize
    --requests)."""
    if not decomp:
        return "(no serve.request spans in this stream)\n"
    rows = [
        [tid, d.get("tier") or "", d.get("route_s", 0.0), d["queue_s"],
         d["pack_s"], d["launch_s"], d["confirm_s"], d["other_s"],
         d["total_s"], d.get("verdict") or ""]
        for tid, d in sorted(decomp.items(),
                             key=lambda kv: -kv[1]["total_s"])
    ]
    return _fmt_table(
        ["trace", "tier", "route_s", "queue_s", "pack_s", "launch_s",
         "confirm_s", "other_s", "total_s", "verdict"], rows) + "\n"


def format_critpath(cp: Mapping) -> str:
    """The critical-path rollup as a text table (trace_summarize
    --critpath)."""
    spans = cp.get("spans") or [
        {"span": n, **row, "slack_s": (cp.get("slack") or {}).get(n, 0.0)}
        for n, row in (cp.get("by_span") or {}).items()
    ]
    head = (f"critical path: {cp.get('total_s', 0)} s on-path of "
            f"{cp.get('wall_s', 0)} s wall\n")
    if not spans:
        return head + "(no spans)\n"
    rows = [
        [r["span"], r["cp_s"], r.get("total_s", ""), r.get("count", ""),
         r.get("slack_s", "")]
        for r in spans
    ]
    return head + _fmt_table(
        ["span", "critpath_s", "inclusive_s", "count", "slack_s"],
        rows) + "\n"


def format_devices(tl: Mapping) -> str:
    """The per-device timeline as a text table (trace_summarize
    --devices)."""
    devices = tl.get("devices") or {}
    if not devices:
        return "(no device-attributed spans in this stream)\n"
    rows = [
        [d, row["busy_s"], row["idle_s"], row["busy_frac"],
         row["idle_frac"], row["launches"]]
        for d, row in sorted(devices.items())
    ]
    return (
        f"device window {tl['window_s']} s — bubble ratio "
        f"{tl['bubble_ratio']}, imbalance {tl['imbalance']}\n"
        + _fmt_table(
            ["device", "busy_s", "idle_s", "busy_frac", "idle_frac",
             "launches"], rows) + "\n")
