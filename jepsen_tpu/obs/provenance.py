"""Verdict provenance: durable evidence bundles + audit verify/replay.

A verdict from this codebase can be produced along very different — and
differently trustworthy — execution paths: three dedup backends, two
elle engines, OOM halving, spill recovery, poison bisection, deadline
degradation and quarantine all alter what actually ran.  This module
gives every verdict (valid / refuted / unknown; one-shot and served) a
single machine-checkable artifact recording *how* it was produced:

* **bundle** — a ``store.durable``-enveloped record holding the history
  fingerprint (``store.checkpoint.fingerprint``), the engine/backend
  resolution (engine, ``dedup_backend``, elle engine, pallas interpret
  flag), the per-rung **decision path** (ladder trajectory, OOM
  halvings, spill retries, confirmations, fallbacks, fault events), the
  witness or refutation payload, the effective config, a machine
  fingerprint, and the linked trace id.
* **digest** — a sha256 over the bundle's *stability core* (fingerprint,
  verdict, decision path, engine, config, witness) with volatile
  attributes stripped — so the same history checked along the same
  decision path yields the same digest whether it was served in a batch
  or replayed sequentially (the loadgen parity cross-check).
* **verify** — structural audit: envelope CRC, digest recompute, and
  witness re-validation against the model (a claimed linearization must
  actually step; a claimed cycle must actually cycle).
* **replay** — re-run the history pinned to the recorded engine /
  backend / config and assert verdict identity.

Producers record path entries via :func:`attach` (pure dict merge, no
I/O) and persist via :func:`emit` / :func:`write_bundle`; both are
best-effort by contract — provenance must never lose a verdict.

Telemetry family ``provenance.*``: ``provenance.bundle`` counts
emissions (attrs ``source``, ``verdict``), ``provenance.emit_error``
counts swallowed emission failures.
"""

from __future__ import annotations

import hashlib
import json
import logging
import uuid
from pathlib import Path
from typing import Mapping, Sequence

from jepsen_tpu import obs

logger = logging.getLogger(__name__)

#: durable envelope kind + payload schema version for evidence bundles.
KIND_BUNDLE = "evidence-bundle"

#: embed the raw history in the bundle when it has at most this many
#: ops (verify/replay then need no sibling files); larger histories
#: keep only the fingerprint and op count.
MAX_EMBED_OPS = 4096

#: decision-path entries kept per bundle; overflow is truncated with a
#: marker entry (a pathological retry loop must not grow an unbounded
#: artifact).
MAX_PATH = 128

#: skip constructive-witness extraction (the greedy re-walk) past this
#: many ops — the walk is linear but the bundle write sits on the
#: serving path.
WITNESS_WALK_MAX_OPS = 2048

#: attribute names stripped (recursively) from the digest's stability
#: core: timings, lane widths, buffer peaks, machine/trace identity —
#: everything that varies between a served batch member and the same
#: history replayed sequentially along the same decision path.
_DIGEST_STRIP = frozenset({
    "seconds", "latency", "lanes", "lanes_from", "lanes_to", "launches",
    "padded", "trace_id", "trace", "machine", "id", "digest", "source",
    "joined_at_rung", "frontier-peak", "peak_frontier", "chunks",
    "spill-rows", "spill-bytes", "device_bytes_peak", "queue_latency_s",
    "history_ops", "svg", "evidence",
    # confirm.resolved's mode (worker vs device-sweep) records which
    # confirm pool happened to be free, not what was decided.
    "mode",
})


def _register() -> None:
    from jepsen_tpu.store import durable

    durable.register_kind(KIND_BUNDLE, 1)


_register()


# ---------------------------------------------------------------------------
# Fingerprints
# ---------------------------------------------------------------------------


def history_fingerprint(history) -> str:
    """The canonical content fingerprint of one history — the same
    sha256 the checkpoint layer keys resume-safety on."""
    from jepsen_tpu.store import checkpoint as _ckpt

    return _ckpt.fingerprint([history])


_MACHINE: dict | None = None


def machine_fingerprint() -> dict:
    """Host/toolchain fingerprint (cached; never probes a device
    backend — same convention as graftlint and the bench outage
    path)."""
    global _MACHINE
    if _MACHINE is None:
        try:
            from jepsen_tpu.obs import regress

            _MACHINE = dict(regress.fingerprint(probe_devices=False))
        except Exception:  # noqa: BLE001 — fingerprinting is best-effort
            _MACHINE = {"host": "unknown"}
    return dict(_MACHINE)


def verdict_str(v) -> str:
    """Canonical verdict string: True → "true", False → "false",
    anything else (UNKNOWN, None, "unknown") → "unknown"."""
    if v is True:
        return "true"
    if v is False:
        return "false"
    return "unknown"


# ---------------------------------------------------------------------------
# Decision-path attachment (pure dict plumbing; producers call this)
# ---------------------------------------------------------------------------


def attach(result: dict, path: Sequence[Mapping] | None = None, *,
           engine: Mapping | None = None,
           config: Mapping | None = None) -> dict:
    """Merge decision-path provenance into a result dict (in place).

    ``path`` entries are prepended before any entries already on the
    result (an outer ladder's events precede the chunked escalation's
    own trajectory).  ``engine``/``config`` fill only missing keys —
    the innermost producer knows its resolution best.  Idempotent for a
    fixed ``path`` list: callers re-attach freely at every notify
    point, the LAST attach before the result leaves the producer wins.
    """
    prov = result.get("provenance")
    existing = list(prov.get("path", ())) if isinstance(prov, Mapping) else []
    new = [dict(e) for e in (path or ())]
    # idempotence: drop the existing prefix if it is exactly a prior
    # attach of the same (possibly shorter) producer list
    if new and existing[: len(new)] == new:
        merged = existing
    else:
        seen = {json.dumps(e, sort_keys=True, default=str) for e in new}
        merged = new + [
            e for e in existing
            if json.dumps(e, sort_keys=True, default=str) not in seen
        ]
    if len(merged) > MAX_PATH:
        merged = merged[:MAX_PATH] + [
            {"event": "path.truncated", "dropped": len(merged) - MAX_PATH}
        ]
    out = {"path": merged}
    eng = dict(prov.get("engine", ())) if isinstance(prov, Mapping) else {}
    for k, v in (engine or {}).items():
        eng.setdefault(k, v)
    if eng:
        out["engine"] = eng
    cfg = dict(prov.get("config", ())) if isinstance(prov, Mapping) else {}
    for k, v in (config or {}).items():
        cfg.setdefault(k, v)
    if cfg:
        out["config"] = cfg
    result["provenance"] = out
    return result


class PathRecorder:
    """A bounded per-verdict decision-path accumulator.  ``add`` is
    cheap and never raises; ``entries`` hands the list to
    :func:`attach`."""

    __slots__ = ("entries",)

    def __init__(self):
        self.entries: list[dict] = []

    def add(self, event: str, **attrs) -> None:
        if len(self.entries) >= MAX_PATH:
            return
        e = {"event": str(event)}
        e.update(attrs)
        self.entries.append(e)


# ---------------------------------------------------------------------------
# Bundle construction
# ---------------------------------------------------------------------------


def _extract_witness(model, history, result: Mapping) -> dict | None:
    """The constructive payload that makes a verdict auditable.

    * valid (linearizable): re-run the host greedy walk recording the
      fired effective ops — a full linearization order verify can step.
    * refuted (linearizable): the barrier op the kernel killed on.
    * refuted (elle): the anomaly cycles (each step chains to the
      next; verify checks closure).
    """
    v = result.get("valid?")
    if v is False:
        if result.get("anomalies"):
            return {"type": "cycle", "anomalies": result["anomalies"]}
        if result.get("op") is not None:
            return {"type": "refutation", "op": result["op"]}
        return None
    if v is not True:
        return None
    if result.get("anomaly-types") is not None or model is None:
        return None  # elle valid: absence of cycles has no walk
    if history is None or len(history) > WITNESS_WALK_MAX_OPS:
        return None
    try:
        from jepsen_tpu.checker import wgl_cpu

        order: list[dict] = []
        ok = wgl_cpu.greedy_walk(model, history, record=order)
        if ok is True:
            return {"type": "linearization", "order": order}
    except Exception:  # noqa: BLE001 — witness extraction is best-effort
        logger.debug("witness extraction failed", exc_info=True)
    return None


def _strip(x):
    if isinstance(x, Mapping):
        return {
            str(k): _strip(v) for k, v in x.items()
            if str(k) not in _DIGEST_STRIP
        }
    if isinstance(x, (list, tuple)):
        return [_strip(v) for v in x]
    return x


def _stable_cause(cause) -> str | None:
    """Causes sometimes embed run-local paths ("resumable checkpoint:
    /tmp/..."); the digest keeps only the stable prefix."""
    if cause is None:
        return None
    return str(cause).split("; resumable checkpoint:", 1)[0]


def bundle_digest(payload: Mapping) -> str:
    """sha256 over the bundle's stability core — same history + same
    decision path ⇒ same digest, wherever it ran."""
    from jepsen_tpu.store import durable

    core = {
        "history_fingerprint": payload.get("history_fingerprint"),
        "verdict": payload.get("verdict"),
        "cause": _stable_cause(payload.get("cause")),
        "model": payload.get("model"),
        "checker": payload.get("checker"),
        "decision_path": _strip(payload.get("decision_path") or []),
        "engine": _strip(payload.get("engine") or {}),
        "config": _strip(payload.get("config") or {}),
        "witness": _strip(payload.get("witness") or {}),
    }
    return hashlib.sha256(durable.canonical_bytes(core)).hexdigest()


def build_bundle(*, history, result: Mapping, source: str,
                 model=None, checker: str | None = None,
                 trace_id=None, config: Mapping | None = None,
                 extra_path: Sequence[Mapping] | None = None,
                 bundle_id: str | None = None) -> dict:
    """Assemble one evidence-bundle payload (no I/O).

    ``result`` may carry a ``provenance`` block from :func:`attach`;
    ``extra_path`` entries (the serving layer's admission/fastpath/
    bisect events) are prepended before it.  The returned payload's
    ``digest`` field is the stability-core digest.
    """
    prov = result.get("provenance") or {}
    path = [dict(e) for e in (extra_path or ())]
    path += [dict(e) for e in prov.get("path", ())]
    engine = dict(prov.get("engine", ()))
    cfg = dict(config or prov.get("config", ()))
    cfg.pop("fingerprint", None)  # batch-level; not per-history-stable
    v = result.get("valid?")
    payload = {
        "id": bundle_id or uuid.uuid4().hex[:16],
        "source": str(source),
        "model": getattr(model, "name", None) if model is not None else None,
        "checker": checker,
        "history_fingerprint": history_fingerprint(history)
        if history is not None else None,
        "history_ops": len(history) if history is not None else None,
        "verdict": verdict_str(v),
        "cause": result.get("cause"),
        "decision_path": path,
        "engine": engine,
        "config": cfg,
        "witness": _extract_witness(model, history, result),
        "machine": machine_fingerprint(),
        "trace_id": trace_id,
    }
    if history is not None and len(history) <= MAX_EMBED_OPS:
        payload["history"] = [dict(op) for op in history]
    payload["digest"] = bundle_digest(payload)
    return payload


# ---------------------------------------------------------------------------
# Persistence
# ---------------------------------------------------------------------------


def write_bundle(directory, payload: Mapping) -> Path | None:
    """Durably persist one bundle as ``<dir>/<id>.json`` (enveloped:
    CRC + kind + version).  Best-effort: failures count
    ``provenance.emit_error`` and return None, never raise — an
    evidence write must not lose the verdict it documents."""
    from jepsen_tpu.store import durable

    try:
        d = Path(directory)
        d.mkdir(parents=True, exist_ok=True)
        path = d / f"{payload['id']}.json"
        durable.write_record(path, KIND_BUNDLE, payload)
        obs.counter("provenance.bundle", source=payload.get("source"),
                    verdict=payload.get("verdict"))
        return path
    except Exception as e:  # noqa: BLE001 — see docstring
        logger.warning("evidence bundle write failed: %s", e)
        obs.counter("provenance.emit_error", error=type(e).__name__)
        return None


def read_bundle(path) -> dict:
    """Read + verify one bundle envelope.  Raises
    ``store.durable.DurableError`` (machine-readable ``.report``) on a
    corrupt/tampered envelope — the file is quarantined aside."""
    from jepsen_tpu.store import durable

    return durable.read_verified(path, KIND_BUNDLE).payload


def iter_bundles(run_dir):
    """Yield ``(path, payload)`` for every readable bundle under a run
    directory's ``evidence/`` folder (corrupt ones are skipped with a
    warning — they are already quarantined aside)."""
    from jepsen_tpu.store import durable

    d = Path(run_dir)
    ev = d / "evidence" if (d / "evidence").is_dir() else d
    for p in sorted(ev.glob("*.json")):
        try:
            yield p, read_bundle(p)
        except durable.DurableError as e:
            logger.warning("skipping corrupt bundle %s: %s", p, e)


def emit(test: Mapping | None, history, result: dict, *, source: str,
         model=None, checker: str | None = None,
         config: Mapping | None = None, opts: Mapping | None = None,
         trace_id=None) -> dict | None:
    """Checker-level emission: build a bundle for ``result`` and write
    it under the run's store dir (``<test-dir>/evidence/<id>.json``).
    Mirrors ``_render_failure``'s guard — a bare unit-test checker with
    no store coordinates records nothing (but the in-memory provenance
    stays on the result).  Sets ``result["evidence"] = {id, digest,
    path}`` on success; never raises."""
    try:
        bundle = build_bundle(
            history=history, result=result, source=source, model=model,
            checker=checker, config=config, trace_id=trace_id,
        )
    except Exception as e:  # noqa: BLE001 — provenance never loses verdicts
        logger.warning("evidence bundle build failed: %s", e)
        obs.counter("provenance.emit_error", error=type(e).__name__)
        return None
    test = test or {}
    if not (test.get("name") and test.get("start-time-str")):
        return bundle  # no store configured (bare checker unit tests)
    from jepsen_tpu import store

    d = store.test_dir(test)
    sub = (opts or {}).get("subdirectory")
    d = d / sub if sub else d
    path = write_bundle(d / "evidence", bundle)
    if path is not None:
        result["evidence"] = {
            "id": bundle["id"], "digest": bundle["digest"],
            "path": str(path),
        }
    return bundle


# ---------------------------------------------------------------------------
# Verify: structural audit + witness re-validation
# ---------------------------------------------------------------------------


def _check_linearization(model, history, order: Sequence[Mapping]) -> list[str]:
    """Re-step the model through a claimed linearization.  Checks (a)
    every step is consistent, and (b) the order fires exactly the
    effective ops ``prepare`` derives from the history — a forged or
    truncated walk fails one of the two."""
    from jepsen_tpu import models as m
    from jepsen_tpu.checker import wgl_cpu

    errors: list[str] = []
    state = model
    for n, op in enumerate(order):
        state = state.step(op)
        if m.is_inconsistent(state):
            errors.append(
                f"witness step {n} inconsistent: f={op.get('f')!r} "
                f"value={op.get('value')!r} ({state.msg})"
            )
            return errors
    _events, eff_ops, crashed = wgl_cpu.prepare(model, history)

    def _key(op):
        return (op.get("f"), json.dumps(op.get("value"), sort_keys=True,
                                        default=str))

    want: dict = {}
    want_ok: dict = {}
    got: dict = {}
    for i, op in eff_ops.items():
        want[_key(op)] = want.get(_key(op), 0) + 1
        if i not in crashed:
            want_ok[_key(op)] = want_ok.get(_key(op), 0) + 1
    for op in order:
        got[_key(op)] = got.get(_key(op), 0) + 1
    # Crashed ops may legitimately be absent (a linearization need not
    # fire an op that never definitely completed), but every ok op MUST
    # fire and nothing may fire more often than the history offers — a
    # forged or truncated walk fails one of the two bounds.
    for k, n in got.items():
        if n > want.get(k, 0):
            errors.append(f"witness fires op {k} {n}x but history has "
                          f"{want.get(k, 0)}")
    for k, n in want_ok.items():
        if got.get(k, 0) < n:
            errors.append(
                f"witness omits completed op {k} ({got.get(k, 0)} fired, "
                f"{n} required)"
            )
    return errors


def _check_cycle(anomalies: Mapping) -> list[str]:
    """A claimed cycle must actually cycle: every step's ``to`` is the
    next step's ``from`` and the last closes back to the first."""
    errors: list[str] = []
    for name, cycles in (anomalies or {}).items():
        for ci, c in enumerate(cycles or ()):
            steps = c.get("steps") or []
            if not steps:
                errors.append(f"anomaly {name}[{ci}]: no steps")
                continue
            for si, st in enumerate(steps):
                nxt = steps[(si + 1) % len(steps)]
                if st.get("to") != nxt.get("from"):
                    errors.append(
                        f"anomaly {name}[{ci}]: step {si} does not chain "
                        f"(to != next.from) — the claimed cycle does not "
                        "cycle"
                    )
                    break
            cyc = c.get("cycle")
            if cyc and len(cyc) != len(steps):
                errors.append(
                    f"anomaly {name}[{ci}]: {len(cyc)} ops vs "
                    f"{len(steps)} steps"
                )
    return errors


_REQUIRED = ("id", "source", "verdict", "history_fingerprint",
             "decision_path", "engine", "digest")


def verify_bundle(bundle, *, path=None) -> dict:
    """Structurally audit one bundle; returns a machine-readable report
    ``{"ok": bool, "checks": [...], "errors": [...]}``.  ``bundle`` is
    a payload dict or a path (then the envelope CRC is checked first
    and a tampered envelope fails with the durable layer's report)."""
    from jepsen_tpu import models as m
    from jepsen_tpu.store import durable

    checks: list[str] = []
    errors: list[str] = []
    report = {"ok": False, "checks": checks, "errors": errors}
    if not isinstance(bundle, Mapping):
        path = bundle
        try:
            bundle = read_bundle(path)
        except durable.DurableError as e:
            errors.append(f"envelope: {e}")
            report["envelope"] = e.report
            return report
        checks.append("envelope-crc")
    for k in _REQUIRED:
        if bundle.get(k) in (None, ""):
            errors.append(f"missing required field: {k}")
    if errors:
        return report
    checks.append("required-fields")
    if bundle_digest(bundle) != bundle["digest"]:
        errors.append("digest mismatch: stability core was altered after "
                      "the digest was computed")
        return report
    checks.append("digest")
    history = bundle.get("history")
    if history is not None:
        if history_fingerprint(history) != bundle["history_fingerprint"]:
            errors.append("history fingerprint mismatch: embedded history "
                          "was altered")
            return report
        checks.append("history-fingerprint")
    witness = bundle.get("witness")
    if witness:
        wt = witness.get("type")
        if wt == "linearization":
            if bundle.get("model") and history is not None:
                model = m.model(bundle["model"])
                errs = _check_linearization(
                    model, history, witness.get("order") or ())
                if errs:
                    errors.extend(errs)
                    return report
                checks.append("witness-linearization")
            else:
                checks.append("witness-linearization-skipped")
        elif wt == "cycle":
            errs = _check_cycle(witness.get("anomalies") or {})
            if errs:
                errors.extend(errs)
                return report
            checks.append("witness-cycle")
        elif wt == "refutation":
            op = witness.get("op")
            if history is not None and op is not None:
                fv = (op.get("f"), op.get("process"))
                if not any((o.get("f"), o.get("process")) == fv
                           for o in history):
                    errors.append("refutation op not present in history")
                    return report
            checks.append("witness-refutation")
    elif bundle["verdict"] == "false":
        errors.append("refuted verdict carries no witness payload")
        return report
    report["ok"] = True
    return report


# ---------------------------------------------------------------------------
# Replay: re-run pinned to the recorded engine/backend/config
# ---------------------------------------------------------------------------


def replay_bundle(bundle, *, deadline_zero_on_deadline_path: bool = True) -> dict:
    """Re-run the bundled history pinned to the recorded engine /
    backend / config and compare verdicts.  Returns ``{"ok", "verdict",
    "replayed", "pinned", "errors"}``; ``ok`` means verdict identity.

    A bundle whose decision path records a deadline trip replays under
    a zero budget (``faults.Deadline(0.0)``) so the degraded-unknown
    outcome is deterministic rather than racing the original timeout.
    """
    from jepsen_tpu import faults
    from jepsen_tpu import models as m
    from jepsen_tpu.store import durable

    errors: list[str] = []
    out = {"ok": False, "verdict": None, "replayed": None, "pinned": {},
           "errors": errors}
    if not isinstance(bundle, Mapping):
        try:
            bundle = read_bundle(bundle)
        except durable.DurableError as e:
            errors.append(f"envelope: {e}")
            out["envelope"] = e.report
            return out
    out["verdict"] = bundle.get("verdict")
    history = bundle.get("history")
    if history is None:
        errors.append("history not embedded (too large); replay needs the "
                      "original run artifacts")
        return out
    engine = bundle.get("engine") or {}
    cfg = bundle.get("config") or {}
    checker = bundle.get("checker") or ""
    path_events = {e.get("event") for e in bundle.get("decision_path") or ()}
    deadline = None
    if deadline_zero_on_deadline_path and any(
            str(ev).startswith("fault.deadline") for ev in path_events):
        deadline = faults.Deadline(0.0)
    out["pinned"] = {"engine": engine, "config": cfg,
                     "zero_deadline": deadline is not None}
    try:
        if checker.startswith("elle") or engine.get("engine") == "elle":
            replayed = _replay_elle(bundle, history, engine)
        else:
            model = m.model(bundle["model"]) if bundle.get("model") else None
            if model is None:
                errors.append("no model recorded; cannot replay")
                return out
            from jepsen_tpu.parallel import batch_analysis

            kw = {}
            if cfg.get("capacity"):
                kw["capacity"] = tuple(int(c) for c in cfg["capacity"])
            if cfg.get("exact_escalation") is not None:
                kw["exact_escalation"] = tuple(
                    int(c) for c in cfg["exact_escalation"])
            for k in ("rounds", "engine", "greedy_first", "carry_frontier",
                      "confirm_refutations", "frontier_budget_mb"):
                if cfg.get(k) is not None:
                    kw[k] = cfg[k]
            if engine.get("dedup_backend"):
                kw["dedup_backend"] = engine["dedup_backend"]
            replayed = batch_analysis(
                model, [history], cpu_fallback=deadline is None,
                deadline=deadline, **kw,
            )[0]
    except Exception as e:  # noqa: BLE001 — report, don't crash the audit
        errors.append(f"replay raised: {e!r}")
        return out
    out["replayed"] = verdict_str(replayed.get("valid?"))
    if out["replayed"] != bundle.get("verdict"):
        errors.append(
            f"verdict mismatch: bundle says {bundle.get('verdict')!r}, "
            f"replay under the pinned engine/config produced "
            f"{out['replayed']!r}"
        )
        return out
    out["ok"] = True
    return out


def _replay_elle(bundle: Mapping, history, engine: Mapping) -> dict:
    """Rebuild the recorded elle checker and re-check."""
    from jepsen_tpu.checker import elle

    checker = bundle.get("checker") or ""
    eng = engine.get("graph_engine") or engine.get("elle_engine")
    if "cycle" in checker:
        # CycleChecker wraps a user-supplied analyzer callable — not
        # serializable, so a cycle bundle can only be verified
        # (witness re-validation), not replayed.
        raise ValueError(
            "elle-cycle bundles record a user analyzer callable that "
            "cannot be reconstructed; use `evidence.py verify` instead"
        )
    if "wr-register" in checker:
        chk = elle.WRRegisterChecker(engine=eng)
    else:
        chk = elle.ListAppendChecker(engine=eng)
    return chk.check({}, history, {})
