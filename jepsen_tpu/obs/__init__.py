"""Unified telemetry: structured spans + metrics for the run/check pipeline.

The harness's observability seam (ROADMAP "makes a hot path measurably
faster" enabler): a zero-dependency structured-event API that every layer
emits through —

  * ``span(name, **attrs)``   — a context manager timing a region with
    monotonic wall times; spans nest (the enclosing span becomes the
    ``parent``) and accept late attributes via ``.set(...)``;
  * ``counter(name, n)``      — a monotonically accumulated count;
  * ``gauge(name, value)``    — a point-in-time measurement;
  * ``event(name, **attrs)``  — a bare structured event.

Events stream append-only into ``telemetry.jsonl`` in the active
recording directory (one JSON object per line, crash-readable at any
point, like ``history.jsonl``; opening a new recording replaces a prior
stream), and on close a rolled-up ``telemetry.json`` lands next to it
(per-phase wall time, per-checker time + verdict, the ladder-stage
table — see ``obs.summary``).

The API is PROCESS-GLOBAL with a no-op fast path: when no recording is
active, ``span()`` returns a shared singleton and ``counter``/``gauge``
return immediately after one global read — the interpreter and kernel
hot loops pay ~nothing when telemetry is off, so call sites never need
their own guards.

Two service-grade extensions (the check-serving pipeline's regime):

  * **Trace context** — ``new_trace_id()`` mints a request trace id;
    ``capture()`` snapshots the current thread's span context (parent
    span name + trace) into a picklable ``Ctx`` and ``attach(ctx)``
    installs it on ANOTHER thread (or later on the same one), so
    parent links and trace ids survive the admission → scheduler →
    demux thread hops and the confirm-pool submit/drain boundary.
    While a trace is attached, every emitted event carries a top-level
    ``"trace"`` field (a single id, or the list of member ids on
    shared-batch work).
  * **Live metrics mirror** — when ``obs.metrics.MIRROR`` is enabled
    (a serving process), ``counter``/``gauge`` also land in the
    process-global Prometheus registry (``jepsen_tpu.obs.metrics``),
    independent of any per-run recording.  ``observing()`` reports
    whether EITHER sink is live, for call sites whose sampling itself
    costs something (device-memory reads).

Toggles: the test-map key ``"telemetry?"`` (set by the CLI's
``--telemetry/--no-telemetry``) wins; otherwise the env var
``JEPSEN_TPU_TELEMETRY`` (``0``/``false``/``off`` disable); default ON
for ``run``/``analyze``.  ``core.run_test`` opens the recording into the
run's store directory alongside ``jepsen.log``.
"""

from __future__ import annotations

import contextlib
import json
import os
import socket
import threading
import time
import uuid
from pathlib import Path
from typing import Any, Mapping

from jepsen_tpu.obs import metrics as _metrics
from jepsen_tpu.obs.summary import summarize

__all__ = [
    "ENV_VAR", "Ctx", "Recorder", "active", "attach", "capture", "counter",
    "enabled_for", "env_enabled", "event", "gauge", "new_trace_id",
    "observing", "recording", "span", "span_event", "summarize",
]

ENV_VAR = "JEPSEN_TPU_TELEMETRY"

_FALSY = {"0", "false", "no", "off"}

#: the active recorder; None is the disabled fast path.
_RECORDER: "Recorder | None" = None

_STACK = threading.local()  # per-thread open-span stack (for parent links)


def env_enabled(default: bool = True) -> bool:
    """The JEPSEN_TPU_TELEMETRY env toggle (bench/tools entry points)."""
    v = os.environ.get(ENV_VAR)
    if v is None:
        return default
    return v.strip().lower() not in _FALSY


def enabled_for(test: Mapping | None) -> bool:
    """Resolve the toggle for a test map: ``"telemetry?"`` wins, then the
    env var, then the default (on for run/analyze)."""
    if test is not None:
        v = test.get("telemetry?")
        if v is not None:
            return bool(v)
    return env_enabled(True)


def active() -> "Recorder | None":
    """The currently-installed recorder, or None."""
    return _RECORDER


def observing() -> bool:
    """Whether ANY sink is live — a recording or the live metrics
    mirror.  The gate for call sites whose sampling itself costs
    something (e.g. device-memory reads at stage boundaries)."""
    return _RECORDER is not None or _metrics.MIRROR


# ---------------------------------------------------------------------------
# Trace context: ids + the cross-thread/process handoff
# ---------------------------------------------------------------------------


def new_trace_id() -> str:
    """A fresh request trace id (16 hex chars)."""
    return uuid.uuid4().hex[:16]


class Ctx:
    """A picklable span-context snapshot: the parent span name and the
    active trace (one id, or a list of member ids for shared-batch
    work).  Produced by ``capture()``, installed by ``attach()``."""

    __slots__ = ("parent", "trace")

    def __init__(self, parent: str | None = None, trace=None):
        self.parent = parent
        self.trace = trace

    def __repr__(self):
        return f"Ctx(parent={self.parent!r}, trace={self.trace!r})"


def capture(*, trace=None, parent: str | None = None) -> Ctx:
    """Snapshot the current thread's span context for a later
    ``attach()`` on another thread (or after a queue/process hop).
    ``trace``/``parent`` override the captured values — the serving
    layer captures at admission with ``trace=<the request's id>``."""
    if parent is None:
        stack = getattr(_STACK, "spans", None)
        parent = stack[-1].name if stack else getattr(_STACK, "parent", None)
    if trace is None:
        trace = getattr(_STACK, "trace", None)
    return Ctx(parent, trace)


@contextlib.contextmanager
def attach(ctx: Ctx | None = None, *, trace=None, parent: str | None = None):
    """Install a captured context on THIS thread: spans opened inside
    parent to ``ctx.parent`` (when they have no enclosing local span)
    and every event emitted inside carries ``ctx.trace``.  Nests —
    the previous context is restored on exit.  Works with no recorder
    installed (the thread-local write is ~free), so call sites don't
    need their own telemetry guards."""
    if ctx is None:
        ctx = Ctx(parent, trace)
    else:
        ctx = Ctx(
            ctx.parent if parent is None else parent,
            ctx.trace if trace is None else trace,
        )
    prev_parent = getattr(_STACK, "parent", None)
    prev_trace = getattr(_STACK, "trace", None)
    _STACK.parent = ctx.parent
    _STACK.trace = ctx.trace
    try:
        yield ctx
    finally:
        _STACK.parent = prev_parent
        _STACK.trace = prev_trace


def _stamp(ev: dict) -> dict:
    """Attach the thread's active trace (if any) to an outgoing event."""
    tr = getattr(_STACK, "trace", None)
    if tr is not None:
        ev["trace"] = tr
    return ev


def _stamp_thread(ev: dict) -> dict:
    """Attach the emitting thread's id to a span event.  The flight
    analyzer (obs.critpath) nests span instances by interval
    containment WITHIN a thread — a single thread's overlapping spans
    are always genuinely nested, while same-interval spans on
    different threads are concurrent work that must never be charged
    inside each other."""
    ev["thread"] = threading.get_ident()
    return ev


class Recorder:
    """Appends events to ``<dir>/telemetry.jsonl``; ``close()`` rolls them
    up into ``<dir>/telemetry.json``.  Thread-safe (checkers run composed
    in a thread pool).

    A new recording TRUNCATES any previous telemetry.jsonl in the
    directory: the jsonl is the rollup's source of truth, so re-analyzing
    a stored run must replace the stream, not append a second one the
    summarizer would double-count."""

    def __init__(self, directory: Path | str):
        self.dir = Path(directory)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.path = self.dir / "telemetry.jsonl"
        self.events: list[dict] = []
        self.summary: dict | None = None
        self._t0 = time.monotonic()
        self._lock = threading.Lock()
        self._fh = open(self.path, "w", encoding="utf-8")
        # t0 is the wall-clock epoch the monotonic event offsets hang off
        # (every event's "t" is seconds after it): epoch = t0 + t.  With
        # pid + host in the header, traces from different processes,
        # machines, and runs can be time-aligned (trace_export uses it).
        t0 = time.time()
        try:
            host = socket.gethostname()
        except OSError:  # pragma: no cover — hostname lookup failed
            host = "?"
        self.emit({"type": "meta", "version": 1, "wall-clock": t0,
                   "t0": t0, "pid": os.getpid(), "host": host})

    def now(self) -> float:
        """Seconds since the recording opened (monotonic)."""
        return time.monotonic() - self._t0

    def emit(self, ev: dict) -> None:
        line = json.dumps(ev, separators=(",", ":"), default=str)
        with self._lock:
            self.events.append(ev)
            self._fh.write(line + "\n")
            # per-line flush: subprocess replicas never close their
            # recorder (they die by signal), and the fleet timeline
            # merger reads the N jsonl streams LIVE — a block-buffered
            # stream would trail reality by up to one stdio buffer
            self._fh.flush()

    def close(self) -> dict:
        with self._lock:
            self._fh.flush()
            self._fh.close()
        self.summary = summarize(self.events)
        tmp = self.dir / "telemetry.json.tmp"
        tmp.write_text(json.dumps(self.summary, indent=1, default=str))
        os.replace(tmp, self.dir / "telemetry.json")
        return self.summary


@contextlib.contextmanager
def recording(directory: Path | str | None, *, enabled: bool = True):
    """Install a process-global recorder writing into ``directory``.

    Nesting passes through: when a recording is already active (run_test's
    covers analyze's), the inner call yields the outer recorder and closes
    nothing — spans just keep accumulating into the one file.  With
    ``enabled=False`` (or no directory) nothing is installed and nothing
    is written.
    """
    global _RECORDER
    if not enabled or directory is None:
        yield _RECORDER
        return
    if _RECORDER is not None:
        yield _RECORDER
        return
    r = Recorder(directory)
    _RECORDER = r
    try:
        yield r
    finally:
        _RECORDER = None
        r.close()


class _NoopSpan:
    """The disabled fast path: one shared instance, no state, no writes."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        return self


NOOP_SPAN = _NoopSpan()


class _Span:
    __slots__ = ("_r", "name", "attrs", "_start")

    def __init__(self, r: Recorder, name: str, attrs: dict):
        self._r = r
        self.name = name
        self.attrs = attrs
        self._start = 0.0

    def set(self, **attrs):
        """Attach attributes discovered mid-span (verdicts, counts)."""
        self.attrs.update(attrs)
        return self

    def __enter__(self):
        stack = getattr(_STACK, "spans", None)
        if stack is None:
            stack = _STACK.spans = []
        stack.append(self)
        self._start = self._r.now()
        return self

    def __exit__(self, exc_type, exc, tb):
        dur = self._r.now() - self._start
        stack = getattr(_STACK, "spans", None)
        if stack and stack[-1] is self:
            stack.pop()
        # parent: the enclosing local span, else an attach()ed handoff
        # context's parent (the cross-thread link)
        parent = stack[-1].name if stack else getattr(_STACK, "parent", None)
        ev: dict[str, Any] = _stamp_thread(_stamp({
            "type": "span", "name": self.name, "t": round(self._start, 6),
            "dur": round(dur, 6),
        }))
        if parent is not None:
            ev["parent"] = parent
        if exc_type is not None:
            ev["err"] = exc_type.__name__
        if self.attrs:
            ev["attrs"] = self.attrs
        self._r.emit(ev)
        return False


def span(name: str, **attrs):
    """Time a region: ``with obs.span("phase.analyze", n=3) as sp: ...``.
    Returns the shared no-op singleton when telemetry is off."""
    r = _RECORDER
    if r is None:
        return NOOP_SPAN
    return _Span(r, name, attrs)


def span_event(name: str, seconds: float, **attrs) -> None:
    """Emit an already-measured span directly (for regions with multiple
    exit paths where a context manager would force restructuring).  The
    event is identical to a ``span()`` one, minus the parent link."""
    r = _RECORDER
    if r is None:
        return
    now = r.now()
    ev: dict[str, Any] = _stamp_thread(_stamp({
        "type": "span", "name": name,
        "t": round(max(0.0, now - seconds), 6), "dur": round(seconds, 6),
    }))
    if attrs:
        ev["attrs"] = attrs
    r.emit(ev)


def counter(name: str, n: int = 1, **attrs) -> None:
    """Accumulate a count (summed per name in the summary).  Also feeds
    the live Prometheus registry when its mirror is on — by NAME only
    (attrs would be unbounded label cardinality)."""
    r = _RECORDER
    if _metrics.MIRROR:
        _metrics.REGISTRY.inc(name, n)
    if r is None:
        return
    ev: dict[str, Any] = _stamp({"type": "counter", "name": name,
                                 "t": round(r.now(), 6), "n": n})
    if attrs:
        ev["attrs"] = attrs
    r.emit(ev)


def gauge(name: str, value, **attrs) -> None:
    """Record a point-in-time value (last write per name wins in the
    summary; every sample stays in the JSONL).  Numeric values also
    feed the live Prometheus registry when its mirror is on."""
    r = _RECORDER
    if _metrics.MIRROR:
        _metrics.REGISTRY.set(name, value)
    if r is None:
        return
    ev: dict[str, Any] = _stamp({"type": "gauge", "name": name,
                                 "t": round(r.now(), 6), "value": value})
    if attrs:
        ev["attrs"] = attrs
    r.emit(ev)


def event(name: str, **attrs) -> None:
    """A bare structured event (kept in the JSONL, not summarized)."""
    r = _RECORDER
    if r is None:
        return
    ev: dict[str, Any] = _stamp({"type": "event", "name": name,
                                 "t": round(r.now(), 6)})
    if attrs:
        ev["attrs"] = attrs
    r.emit(ev)
