"""Unified telemetry: structured spans + metrics for the run/check pipeline.

The harness's observability seam (ROADMAP "makes a hot path measurably
faster" enabler): a zero-dependency structured-event API that every layer
emits through —

  * ``span(name, **attrs)``   — a context manager timing a region with
    monotonic wall times; spans nest (the enclosing span becomes the
    ``parent``) and accept late attributes via ``.set(...)``;
  * ``counter(name, n)``      — a monotonically accumulated count;
  * ``gauge(name, value)``    — a point-in-time measurement;
  * ``event(name, **attrs)``  — a bare structured event.

Events stream append-only into ``telemetry.jsonl`` in the active
recording directory (one JSON object per line, crash-readable at any
point, like ``history.jsonl``; opening a new recording replaces a prior
stream), and on close a rolled-up ``telemetry.json`` lands next to it
(per-phase wall time, per-checker time + verdict, the ladder-stage
table — see ``obs.summary``).

The API is PROCESS-GLOBAL with a no-op fast path: when no recording is
active, ``span()`` returns a shared singleton and ``counter``/``gauge``
return immediately after one global read — the interpreter and kernel
hot loops pay ~nothing when telemetry is off, so call sites never need
their own guards.

Toggles: the test-map key ``"telemetry?"`` (set by the CLI's
``--telemetry/--no-telemetry``) wins; otherwise the env var
``JEPSEN_TPU_TELEMETRY`` (``0``/``false``/``off`` disable); default ON
for ``run``/``analyze``.  ``core.run_test`` opens the recording into the
run's store directory alongside ``jepsen.log``.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from pathlib import Path
from typing import Any, Mapping

from jepsen_tpu.obs.summary import summarize

__all__ = [
    "ENV_VAR", "Recorder", "active", "counter", "enabled_for",
    "env_enabled", "event", "gauge", "recording", "span", "span_event",
    "summarize",
]

ENV_VAR = "JEPSEN_TPU_TELEMETRY"

_FALSY = {"0", "false", "no", "off"}

#: the active recorder; None is the disabled fast path.
_RECORDER: "Recorder | None" = None

_STACK = threading.local()  # per-thread open-span stack (for parent links)


def env_enabled(default: bool = True) -> bool:
    """The JEPSEN_TPU_TELEMETRY env toggle (bench/tools entry points)."""
    v = os.environ.get(ENV_VAR)
    if v is None:
        return default
    return v.strip().lower() not in _FALSY


def enabled_for(test: Mapping | None) -> bool:
    """Resolve the toggle for a test map: ``"telemetry?"`` wins, then the
    env var, then the default (on for run/analyze)."""
    if test is not None:
        v = test.get("telemetry?")
        if v is not None:
            return bool(v)
    return env_enabled(True)


def active() -> "Recorder | None":
    """The currently-installed recorder, or None."""
    return _RECORDER


class Recorder:
    """Appends events to ``<dir>/telemetry.jsonl``; ``close()`` rolls them
    up into ``<dir>/telemetry.json``.  Thread-safe (checkers run composed
    in a thread pool).

    A new recording TRUNCATES any previous telemetry.jsonl in the
    directory: the jsonl is the rollup's source of truth, so re-analyzing
    a stored run must replace the stream, not append a second one the
    summarizer would double-count."""

    def __init__(self, directory: Path | str):
        self.dir = Path(directory)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.path = self.dir / "telemetry.jsonl"
        self.events: list[dict] = []
        self.summary: dict | None = None
        self._t0 = time.monotonic()
        self._lock = threading.Lock()
        self._fh = open(self.path, "w", encoding="utf-8")
        self.emit({"type": "meta", "version": 1, "wall-clock": time.time(),
                   "pid": os.getpid()})

    def now(self) -> float:
        """Seconds since the recording opened (monotonic)."""
        return time.monotonic() - self._t0

    def emit(self, ev: dict) -> None:
        line = json.dumps(ev, separators=(",", ":"), default=str)
        with self._lock:
            self.events.append(ev)
            self._fh.write(line + "\n")

    def close(self) -> dict:
        with self._lock:
            self._fh.flush()
            self._fh.close()
        self.summary = summarize(self.events)
        tmp = self.dir / "telemetry.json.tmp"
        tmp.write_text(json.dumps(self.summary, indent=1, default=str))
        os.replace(tmp, self.dir / "telemetry.json")
        return self.summary


@contextlib.contextmanager
def recording(directory: Path | str | None, *, enabled: bool = True):
    """Install a process-global recorder writing into ``directory``.

    Nesting passes through: when a recording is already active (run_test's
    covers analyze's), the inner call yields the outer recorder and closes
    nothing — spans just keep accumulating into the one file.  With
    ``enabled=False`` (or no directory) nothing is installed and nothing
    is written.
    """
    global _RECORDER
    if not enabled or directory is None:
        yield _RECORDER
        return
    if _RECORDER is not None:
        yield _RECORDER
        return
    r = Recorder(directory)
    _RECORDER = r
    try:
        yield r
    finally:
        _RECORDER = None
        r.close()


class _NoopSpan:
    """The disabled fast path: one shared instance, no state, no writes."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        return self


NOOP_SPAN = _NoopSpan()


class _Span:
    __slots__ = ("_r", "name", "attrs", "_start")

    def __init__(self, r: Recorder, name: str, attrs: dict):
        self._r = r
        self.name = name
        self.attrs = attrs
        self._start = 0.0

    def set(self, **attrs):
        """Attach attributes discovered mid-span (verdicts, counts)."""
        self.attrs.update(attrs)
        return self

    def __enter__(self):
        stack = getattr(_STACK, "spans", None)
        if stack is None:
            stack = _STACK.spans = []
        stack.append(self)
        self._start = self._r.now()
        return self

    def __exit__(self, exc_type, exc, tb):
        dur = self._r.now() - self._start
        stack = getattr(_STACK, "spans", None)
        if stack and stack[-1] is self:
            stack.pop()
        parent = stack[-1].name if stack else None
        ev: dict[str, Any] = {
            "type": "span", "name": self.name, "t": round(self._start, 6),
            "dur": round(dur, 6),
        }
        if parent is not None:
            ev["parent"] = parent
        if exc_type is not None:
            ev["err"] = exc_type.__name__
        if self.attrs:
            ev["attrs"] = self.attrs
        self._r.emit(ev)
        return False


def span(name: str, **attrs):
    """Time a region: ``with obs.span("phase.analyze", n=3) as sp: ...``.
    Returns the shared no-op singleton when telemetry is off."""
    r = _RECORDER
    if r is None:
        return NOOP_SPAN
    return _Span(r, name, attrs)


def span_event(name: str, seconds: float, **attrs) -> None:
    """Emit an already-measured span directly (for regions with multiple
    exit paths where a context manager would force restructuring).  The
    event is identical to a ``span()`` one, minus the parent link."""
    r = _RECORDER
    if r is None:
        return
    now = r.now()
    ev: dict[str, Any] = {
        "type": "span", "name": name,
        "t": round(max(0.0, now - seconds), 6), "dur": round(seconds, 6),
    }
    if attrs:
        ev["attrs"] = attrs
    r.emit(ev)


def counter(name: str, n: int = 1, **attrs) -> None:
    """Accumulate a count (summed per name in the summary)."""
    r = _RECORDER
    if r is None:
        return
    ev: dict[str, Any] = {"type": "counter", "name": name,
                          "t": round(r.now(), 6), "n": n}
    if attrs:
        ev["attrs"] = attrs
    r.emit(ev)


def gauge(name: str, value, **attrs) -> None:
    """Record a point-in-time value (last write per name wins in the
    summary; every sample stays in the JSONL)."""
    r = _RECORDER
    if r is None:
        return
    ev: dict[str, Any] = {"type": "gauge", "name": name,
                          "t": round(r.now(), 6), "value": value}
    if attrs:
        ev["attrs"] = attrs
    r.emit(ev)


def event(name: str, **attrs) -> None:
    """A bare structured event (kept in the JSONL, not summarized)."""
    r = _RECORDER
    if r is None:
        return
    ev: dict[str, Any] = {"type": "event", "name": name,
                          "t": round(r.now(), 6)}
    if attrs:
        ev["attrs"] = attrs
    r.emit(ev)
