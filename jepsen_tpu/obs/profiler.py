"""A bounded ``jax.profiler`` capture hook for long-lived processes.

A serving process can't be restarted under a profiler every time an
operator wants a device timeline, and an unattended ``start_trace``
left running fills a disk.  This hook wraps the profiler in a
start/stop pair that is:

  * **bounded** — every capture auto-stops after ``max_seconds`` (a
    watchdog timer), so a forgotten start can cost at most one window;
  * **exclusive** — one capture at a time; a second start reports the
    running one instead of corrupting it;
  * **lazy** — jax is imported only when a capture actually starts, so
    mounting the hook costs nothing (web.py serves plain stores without
    dragging in the accelerator stack).

Wired up by ``jepsen-tpu serve --profile-dir DIR`` and driven over HTTP
(``POST /profile/start`` with an optional ``{"seconds": n}`` body,
``POST /profile/stop``, ``GET /profile`` for status).  Captures land in
timestamped subdirectories of ``DIR``; view them with TensorBoard's
profile plugin or ``xprof``.
"""

from __future__ import annotations

import threading
import time
from pathlib import Path

__all__ = ["ProfilerHook"]


def _trace_api():
    """(start_trace, stop_trace) — a seam so tests can drive the hook
    without paying a real profiler capture."""
    import jax.profiler

    return jax.profiler.start_trace, jax.profiler.stop_trace


class ProfilerHook:
    """One process's profiler control surface (module doc)."""

    def __init__(self, directory: str | Path, max_seconds: float = 120.0):
        self.dir = Path(directory)
        self.max_seconds = float(max_seconds)
        self._lock = threading.Lock()
        self._timer: threading.Timer | None = None
        self._active_dir: str | None = None
        self._gen = 0  # capture generation; stale watchdogs no-op on it
        self._t_start = 0.0
        self._deadline = 0.0

    def status(self) -> dict:
        with self._lock:
            return self._status_locked()

    def _status_locked(self) -> dict:
        out = {
            "profiling": self._active_dir is not None,
            "dir": str(self.dir),
            "max_seconds": self.max_seconds,
        }
        if self._active_dir is not None:
            out["capture_dir"] = self._active_dir
            out["elapsed_s"] = round(time.monotonic() - self._t_start, 3)
            out["auto_stop_in_s"] = round(
                max(0.0, self._deadline - time.monotonic()), 3)
        return out

    def start(self, seconds: float | None = None) -> dict:
        """Start a capture bounded at ``min(seconds, max_seconds)``;
        idempotent-ish: a second start while one is running returns the
        running capture's status with ``"error"`` set."""
        with self._lock:
            if self._active_dir is not None:
                return {**self._status_locked(),
                        "error": "capture already running"}
            bound = self.max_seconds
            if seconds is not None:
                try:
                    bound = min(float(seconds), self.max_seconds)
                except (TypeError, ValueError):
                    return {**self._status_locked(),
                            "error": f"bad seconds value {seconds!r}"}
            bound = max(0.1, bound)
            capture_dir = self.dir / time.strftime("profile-%Y%m%dT%H%M%S")
            try:
                capture_dir.mkdir(parents=True, exist_ok=True)
                start_trace, _stop = _trace_api()
                start_trace(str(capture_dir))
            except Exception as e:  # noqa: BLE001 — surface, don't crash
                return {**self._status_locked(),
                        "error": f"profiler start failed: {e!r}"}
            self._active_dir = str(capture_dir)
            self._gen += 1
            self._t_start = time.monotonic()
            self._deadline = self._t_start + bound
            # The watchdog is pinned to THIS capture's generation: a
            # timer that fires concurrently with a manual stop (cancel()
            # can't recall a callback already blocked on the lock) must
            # not kill the NEXT capture an operator starts meanwhile.
            self._timer = threading.Timer(bound, self.stop,
                                          kwargs={"gen": self._gen})
            self._timer.daemon = True
            self._timer.start()
            out = self._status_locked()
            out["seconds"] = bound
            return out

    def stop(self, gen: int | None = None) -> dict:
        """Stop the running capture; a stop with nothing running is a
        no-op status report.  ``gen`` is the watchdog's capture
        generation — a stale watchdog (its capture already stopped
        manually) no-ops instead of truncating a newer capture."""
        with self._lock:
            if self._active_dir is None:
                return self._status_locked()
            if gen is not None and gen != self._gen:
                return self._status_locked()  # stale watchdog
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
            capture_dir = self._active_dir
            elapsed = round(time.monotonic() - self._t_start, 3)
            try:
                _start, stop_trace = _trace_api()
                stop_trace()
            except Exception as e:  # noqa: BLE001 — a failed stop must
                # still clear the state or the hook wedges shut
                self._active_dir = None
                return {**self._status_locked(),
                        "error": f"profiler stop failed: {e!r}",
                        "capture_dir": capture_dir}
            self._active_dir = None
            out = self._status_locked()
            out["stopped"] = {"capture_dir": capture_dir,
                              "elapsed_s": elapsed}
            return out
