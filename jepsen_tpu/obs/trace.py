"""Convert a telemetry event stream into Chrome/Perfetto trace-event JSON.

The ``telemetry.jsonl`` stream is already span-shaped (monotonic start +
duration, parent links, trace ids); this module maps it onto the Chrome
trace-event format (the JSON Perfetto and ``chrome://tracing`` load):

  * one LANE (tid) per request trace id — every event stamped with that
    single ``"trace"`` carries the request's journey (HTTP admission →
    queued wait → demux) on its own row, named after the id;
  * one LANE per DEVICE — device-attributed LAUNCH spans
    (``ladder.launch``, ``sharded.launch``, ``sharded.lane_launch``;
    the lane-shard placement stamps every member device) render once
    per device on a ``device N`` row with a stable
    ``thread_sort_index``, so a multi-device run reads as a per-chip
    timeline instead of interleaved garbage (``ladder.stage`` carries
    the attr too but stays on the ladder lane — its launches already
    paint the device lanes);
  * a shared **ladder** lane (tid 0) for remaining process/shared-batch
    spans (``serve.batch``, confirmation drains) — their member trace
    ids ride along in ``args`` so a lane's request can be found from
    the shared span and vice versa;
  * counter tracks (``ph: "C"``) for the live gauges (queue depth —
    total AND one track per latency class (``serve.queue_depth.*``),
    unknowns remaining, device buffer bytes), on their own dedicated
    lane instead of the device lane.

Timestamps are microseconds since the recording opened; the header
``meta`` event's ``t0`` epoch (obs.Recorder) is preserved in
``otherData`` so traces from different processes can be aligned, and
``otherData.skipped_lines`` reports truncated/corrupt jsonl lines the
tolerant reader dropped.

Stdlib-only: the web UI (``GET /trace/<run>``) and
``tools/trace_export.py`` both import this.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Iterable, Mapping

from jepsen_tpu.obs.critpath import span_devices as _span_devices

__all__ = ["align_streams", "merge_aligned_events", "read_jsonl_events",
           "to_trace_events"]

#: gauges worth a Perfetto counter track (point samples over time).
_COUNTER_GAUGES = {
    "serve.queue_depth",
    "ladder.unknowns_remaining",
    "device.buffer_bytes",
    "confirm.queue_latency_s",
    "serve.rung_occupancy",
}

#: gauge-name prefixes that are counter-track families (one track per
#: member name — the latency-class queue lanes).
_COUNTER_PREFIXES = ("serve.queue_depth.",)

_LADDER_TID = 0
#: the dedicated counter-track lane.
_COUNTER_TID = 1
#: device lanes: tid = _DEVICE_TID_BASE + device id.
_DEVICE_TID_BASE = 1000
#: request lanes start here (arrival order).
_REQUEST_TID_BASE = 2000
#: stream lanes (one per live stream session id) start here.
_STREAM_TID_BASE = 3000

#: span names eligible for per-device rendering (device-attributed
#: launches; ladder.stage stays on the ladder lane — its launches
#: already render per device and duplicating the enclosing stage would
#: double-paint the timeline).
_DEVICE_SPAN_NAMES = {"ladder.launch", "sharded.lane_launch",
                      "sharded.launch"}


def read_jsonl_events(path: Path | str) -> tuple[list[dict], int]:
    """Tolerant ``telemetry.jsonl`` reader: a crashed process may leave
    the LAST line truncated mid-write — skip unparseable lines instead
    of failing the whole stream.  Returns ``(events, skipped)`` so the
    skip count travels with the data (``trace_summarize`` reports it on
    stderr and as ``telemetry.skipped_lines`` in the summary).  Raises
    ``FileNotFoundError`` for a missing file and ``ValueError`` when
    not a single line parses (a clearly-not-telemetry input deserves a
    loud error, not an empty trace)."""
    path = Path(path)
    text = path.read_text()
    events: list[dict] = []
    skipped = 0
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            ev = json.loads(line)
        except ValueError:
            skipped += 1
            continue
        if isinstance(ev, dict):
            events.append(ev)
        else:
            skipped += 1
    if not events and skipped:
        raise ValueError(
            f"{path}: no parseable telemetry events "
            f"({skipped} malformed line(s))"
        )
    return events, skipped


def _stream_meta(events: Iterable[Mapping]) -> dict:
    return next((e for e in events if e.get("type") == "meta"), {})


#: router-side spans whose start must precede the replica-side request
#: span under the same trace — the ordering invariant clock alignment
#: is supposed to restore (align_streams measures its violations as
#: residual skew).
_ROUTER_REQUEST_SPANS = ("fleet.route", "fleet.resubmit")
_REPLICA_REQUEST_SPANS = ("serve.request", "serve.admission")


def align_streams(streams: Iterable) -> tuple[list[dict], dict]:
    """Clock-align N recorder streams onto one common epoch.

    Each recorder's event ``t`` fields are monotonic offsets from ITS
    OWN open; the ``meta`` header's ``t0`` epoch (obs.Recorder) is what
    makes them comparable: epoch time = t0 + t.  This rebases every
    stream onto the EARLIEST t0 (offset = t0_i - min t0) — the fix for
    the old single-recorder assumption where merging streams with
    differing ``t0`` silently interleaved unrelated clocks.

    ``streams``: iterable of ``(label, events)`` or ``(label, events,
    skipped)``.  Returns ``(aligned, info)``:

      * ``aligned`` — one dict per stream: ``label``, ``meta``,
        ``offset_s`` (seconds added to every event ``t``), ``skipped``,
        and ``events`` (rebased COPIES; the input is not mutated).
      * ``info`` — ``t0`` (the common epoch), ``offsets`` per label,
        ``missing_t0`` (labels aligned at offset 0 because their meta
        header carried no epoch), ``cross_process_traces`` (trace ids
        whose events landed in more than one stream — the hop-spanning
        requests), and ``residual_skew_s``: the largest POST-ALIGNMENT
        causality violation between a router-side ``fleet.route``/
        ``fleet.resubmit`` span and the same trace's replica-side
        ``serve.request`` start (0.0 when the epochs agree; wall clocks
        are not monotonic across hosts, so the residue is reported, not
        hidden).
    """
    rows: list[dict] = []
    for s in streams:
        label, events = s[0], list(s[1])
        skipped = int(s[2]) if len(s) > 2 else 0
        meta = _stream_meta(events)
        t0 = meta.get("t0", meta.get("wall-clock"))
        rows.append({"label": str(label), "meta": meta, "skipped": skipped,
                     "t0": float(t0) if t0 is not None else None,
                     "raw": events})
    known = [r["t0"] for r in rows if r["t0"] is not None]
    ref = min(known) if known else 0.0
    missing = [r["label"] for r in rows if r["t0"] is None]

    aligned: list[dict] = []
    trace_streams: dict[str, set[int]] = {}
    route_starts: dict[str, float] = {}   # trace -> earliest router span t
    request_starts: dict[str, float] = {}  # trace -> earliest replica span t
    for i, r in enumerate(rows):
        off = (r["t0"] - ref) if r["t0"] is not None else 0.0
        events = []
        for ev in r["raw"]:
            if "t" in ev:
                ev = {**ev, "t": round(float(ev["t"] or 0.0) + off, 6)}
            events.append(ev)
            tr = ev.get("trace")
            if isinstance(tr, str):
                trace_streams.setdefault(tr, set()).add(i)
                if ev.get("type") == "span":
                    name, t = str(ev.get("name")), float(ev.get("t") or 0.0)
                    if name in _ROUTER_REQUEST_SPANS:
                        route_starts[tr] = min(
                            route_starts.get(tr, t), t)
                    elif name in _REPLICA_REQUEST_SPANS:
                        request_starts[tr] = min(
                            request_starts.get(tr, t), t)
        aligned.append({"label": r["label"], "meta": r["meta"],
                        "offset_s": round(off, 6), "skipped": r["skipped"],
                        "events": events})

    skew = 0.0
    pairs = 0
    for tr, t_route in route_starts.items():
        t_req = request_starts.get(tr)
        if t_req is None or len(trace_streams.get(tr, ())) < 2:
            continue
        pairs += 1
        # the route span opens before the replica accepts; a replica
        # span that reads as STARTING EARLIER is clock skew
        skew = max(skew, t_route - t_req)
    info = {
        "t0": ref if known else None,
        "offsets": {a["label"]: a["offset_s"] for a in aligned},
        "missing_t0": missing,
        "cross_process_traces": sorted(
            tr for tr, ss in trace_streams.items() if len(ss) > 1),
        "residual_skew_s": round(max(0.0, skew), 6),
        "skew_pairs": pairs,
    }
    return aligned, info


def merge_aligned_events(aligned: Iterable[Mapping]) -> list[dict]:
    """One time-ordered event list from ``align_streams`` output — what
    the summarizer and the per-request decomposition consume.  Only the
    reference stream's ``meta`` header survives (a merged stream has
    exactly one epoch; N meta rows would re-introduce the ambiguity the
    alignment just removed)."""
    aligned = list(aligned)
    merged: list[dict] = []
    kept_meta = False
    for a in sorted(aligned, key=lambda a: a.get("offset_s") or 0.0):
        for ev in a["events"]:
            if ev.get("type") == "meta":
                if kept_meta:
                    continue
                kept_meta = True
            merged.append(ev)
    merged.sort(key=lambda ev: (ev.get("type") != "meta",
                                float(ev.get("t") or 0.0)))
    return merged


def _us(t) -> float:
    return round(float(t or 0.0) * 1e6, 1)


def to_trace_events(events: Iterable[Mapping], *,
                    skipped_lines: int = 0) -> dict:
    """Map a telemetry event stream to ``{"traceEvents": [...]}``
    (Chrome trace-event JSON; Perfetto-loadable)."""
    events = list(events)
    meta = next((e for e in events if e.get("type") == "meta"), {})
    pid = int(meta.get("pid") or 1)
    out: list[dict] = [
        {"ph": "M", "name": "process_name", "pid": pid,
         "args": {"name": f"jepsen-tpu ({meta.get('host', '?')})"}},
        {"ph": "M", "name": "thread_name", "pid": pid, "tid": _LADDER_TID,
         "args": {"name": "ladder/shared"}},
        # stable ordering: ladder lane on top, then one lane per device,
        # then the counter tracks, requests below in arrival order
        {"ph": "M", "name": "thread_sort_index", "pid": pid,
         "tid": _LADDER_TID, "args": {"sort_index": -1000}},
        {"ph": "M", "name": "thread_name", "pid": pid, "tid": _COUNTER_TID,
         "args": {"name": "counters"}},
        {"ph": "M", "name": "thread_sort_index", "pid": pid,
         "tid": _COUNTER_TID, "args": {"sort_index": -100}},
    ]
    lanes: dict[str, int] = {}
    device_lanes: dict[int, int] = {}
    stream_lanes: dict[str, int] = {}

    def stream_lane(sid: str) -> int:
        """One lane per live stream session: the ``stream.*`` spans
        (epoch advances, verdict latches, session wall) render as a
        per-stream timeline instead of riding the session's request
        lane."""
        tid = stream_lanes.get(sid)
        if tid is None:
            tid = stream_lanes[sid] = _STREAM_TID_BASE + len(stream_lanes)
            out.append({
                "ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
                "args": {"name": f"stream {sid}"},
            })
            out.append({
                "ph": "M", "name": "thread_sort_index", "pid": pid,
                "tid": tid, "args": {"sort_index": -500 + len(stream_lanes)},
            })
        return tid

    def lane_of(trace) -> int:
        """tid for one request's lane; shared (list) traces and
        untraced events ride the ladder lane."""
        if not isinstance(trace, str):
            return _LADDER_TID
        tid = lanes.get(trace)
        if tid is None:
            tid = lanes[trace] = _REQUEST_TID_BASE + len(lanes)
            out.append({
                "ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
                "args": {"name": f"request {trace}"},
            })
        return tid

    def device_lane(dev: int) -> int:
        tid = device_lanes.get(dev)
        if tid is None:
            tid = device_lanes[dev] = _DEVICE_TID_BASE + dev
            out.append({
                "ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
                "args": {"name": f"device {dev}"},
            })
            out.append({
                "ph": "M", "name": "thread_sort_index", "pid": pid,
                "tid": tid, "args": {"sort_index": -900 + dev},
            })
        return tid

    for ev in events:
        et = ev.get("type")
        tr = ev.get("trace")
        if et == "span":
            name = str(ev.get("name"))
            args = dict(ev.get("attrs") or {})
            if tr is not None:
                args["trace"] = tr
            if ev.get("parent"):
                args["parent"] = ev["parent"]
            if ev.get("err"):
                args["err"] = ev["err"]
            sid = args.get("stream")
            tid = (stream_lane(str(sid))
                   if name.startswith("stream.") and sid is not None
                   else lane_of(tr))
            row = {
                "ph": "X", "name": name, "pid": pid,
                "tid": tid, "ts": _us(ev.get("t")),
                "dur": max(1.0, _us(ev.get("dur"))), "args": args,
            }
            devs = (_span_devices(ev)
                    if name in _DEVICE_SPAN_NAMES else [])
            if devs:
                # device-attributed launches render once per member
                # device — the per-chip timeline
                for d in devs:
                    out.append({**row, "tid": device_lane(d)})
            else:
                out.append(row)
        elif et == "gauge":
            name = str(ev.get("name"))
            v = ev.get("value")
            track = (name in _COUNTER_GAUGES
                     or name.startswith(_COUNTER_PREFIXES))
            if track and isinstance(v, (int, float)):
                out.append({
                    "ph": "C", "name": name, "pid": pid,
                    "tid": _COUNTER_TID,
                    "ts": _us(ev.get("t")), "args": {"value": v},
                })
        elif et == "event":
            name = str(ev.get("name"))
            args = dict(ev.get("attrs") or {})
            if tr is not None:
                args["trace"] = tr
            sid = args.get("stream")
            tid = (stream_lane(str(sid))
                   if name.startswith("stream.") and sid is not None
                   else lane_of(tr))
            out.append({
                "ph": "i", "name": name, "pid": pid,
                "tid": tid, "ts": _us(ev.get("t")), "s": "t",
                "args": args,
            })
        # counters are cumulative noise at trace zoom; the summary has them
    return {
        "traceEvents": out,
        "displayTimeUnit": "ms",
        "otherData": {
            "t0": meta.get("t0", meta.get("wall-clock")),
            "host": meta.get("host"),
            "pid": meta.get("pid"),
            "requests": len(lanes),
            "devices": len(device_lanes),
            "streams": len(stream_lanes),
            "skipped_lines": int(skipped_lines),
        },
    }
