"""Convert a telemetry event stream into Chrome/Perfetto trace-event JSON.

The ``telemetry.jsonl`` stream is already span-shaped (monotonic start +
duration, parent links, trace ids); this module maps it onto the Chrome
trace-event format (the JSON Perfetto and ``chrome://tracing`` load):

  * one LANE (tid) per request trace id — every event stamped with that
    single ``"trace"`` carries the request's journey (HTTP admission →
    queued wait → demux) on its own row, named after the id;
  * a shared **device/ladder** lane (tid 0) for spans that belong to
    the whole process or a shared batch (``ladder.stage``,
    ``serve.batch``, confirmation drains) — their member trace ids ride
    along in ``args`` so a lane's request can be found from the shared
    span and vice versa;
  * counter tracks (``ph: "C"``) for the live gauges (queue depth,
    unknowns remaining, device buffer bytes), so occupancy and memory
    are plotted against the spans that caused them.

Timestamps are microseconds since the recording opened; the header
``meta`` event's ``t0`` epoch (obs.Recorder) is preserved in
``otherData`` so traces from different processes can be aligned.

Stdlib-only: the web UI (``GET /trace/<run>``) and
``tools/trace_export.py`` both import this.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Iterable, Mapping

__all__ = ["read_jsonl_events", "to_trace_events"]

#: gauges worth a Perfetto counter track (point samples over time).
_COUNTER_GAUGES = {
    "serve.queue_depth",
    "ladder.unknowns_remaining",
    "device.buffer_bytes",
    "confirm.queue_latency_s",
}

_DEVICE_TID = 0


def read_jsonl_events(path: Path | str) -> list[dict]:
    """Tolerant ``telemetry.jsonl`` reader: a crashed process may leave
    the LAST line truncated mid-write — skip unparseable lines instead
    of failing the whole stream.  Raises ``FileNotFoundError`` for a
    missing file and ``ValueError`` when not a single line parses (a
    clearly-not-telemetry input deserves a loud error, not an empty
    trace)."""
    path = Path(path)
    text = path.read_text()
    events: list[dict] = []
    skipped = 0
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            ev = json.loads(line)
        except ValueError:
            skipped += 1
            continue
        if isinstance(ev, dict):
            events.append(ev)
        else:
            skipped += 1
    if not events and skipped:
        raise ValueError(
            f"{path}: no parseable telemetry events "
            f"({skipped} malformed line(s))"
        )
    if skipped:
        events.append({"type": "meta", "skipped-lines": skipped})
    return events


def _us(t) -> float:
    return round(float(t or 0.0) * 1e6, 1)


def to_trace_events(events: Iterable[Mapping]) -> dict:
    """Map a telemetry event stream to ``{"traceEvents": [...]}``
    (Chrome trace-event JSON; Perfetto-loadable)."""
    events = list(events)
    meta = next((e for e in events if e.get("type") == "meta"), {})
    pid = int(meta.get("pid") or 1)
    out: list[dict] = [
        {"ph": "M", "name": "process_name", "pid": pid,
         "args": {"name": f"jepsen-tpu ({meta.get('host', '?')})"}},
        {"ph": "M", "name": "thread_name", "pid": pid, "tid": _DEVICE_TID,
         "args": {"name": "device/ladder"}},
        # keep the device lane on top, requests below in arrival order
        {"ph": "M", "name": "thread_sort_index", "pid": pid,
         "tid": _DEVICE_TID, "args": {"sort_index": -1}},
    ]
    lanes: dict[str, int] = {}

    def lane_of(trace) -> int:
        """tid for one request's lane; shared (list) traces and
        untraced events ride the device lane."""
        if not isinstance(trace, str):
            return _DEVICE_TID
        tid = lanes.get(trace)
        if tid is None:
            tid = lanes[trace] = len(lanes) + 1
            out.append({
                "ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
                "args": {"name": f"request {trace}"},
            })
        return tid

    for ev in events:
        et = ev.get("type")
        tr = ev.get("trace")
        if et == "span":
            args = dict(ev.get("attrs") or {})
            if tr is not None:
                args["trace"] = tr
            if ev.get("parent"):
                args["parent"] = ev["parent"]
            if ev.get("err"):
                args["err"] = ev["err"]
            out.append({
                "ph": "X", "name": str(ev.get("name")), "pid": pid,
                "tid": lane_of(tr), "ts": _us(ev.get("t")),
                "dur": max(1.0, _us(ev.get("dur"))), "args": args,
            })
        elif et == "gauge":
            name = str(ev.get("name"))
            v = ev.get("value")
            if name in _COUNTER_GAUGES and isinstance(v, (int, float)):
                out.append({
                    "ph": "C", "name": name, "pid": pid, "tid": _DEVICE_TID,
                    "ts": _us(ev.get("t")), "args": {"value": v},
                })
        elif et == "event":
            args = dict(ev.get("attrs") or {})
            if tr is not None:
                args["trace"] = tr
            out.append({
                "ph": "i", "name": str(ev.get("name")), "pid": pid,
                "tid": lane_of(tr), "ts": _us(ev.get("t")), "s": "t",
                "args": args,
            })
        # counters are cumulative noise at trace zoom; the summary has them
    return {
        "traceEvents": out,
        "displayTimeUnit": "ms",
        "otherData": {
            "t0": meta.get("t0", meta.get("wall-clock")),
            "host": meta.get("host"),
            "pid": meta.get("pid"),
            "requests": len(lanes),
        },
    }
