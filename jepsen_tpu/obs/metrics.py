"""Process-global LIVE metrics: the service-level counterpart of a run's
``telemetry.jsonl``.

A recording observes one run and dies with it; a serving process
(``jepsen-tpu serve --check``) needs metrics that exist for the life of
the PROCESS and can be scraped while requests are in flight.  This
module is that registry: counters, gauges, and fixed-bucket histograms,
rendered as Prometheus text exposition (``GET /metrics`` in
``jepsen_tpu.web``).

Two feeds populate it:

  * the **obs mirror** — when ``MIRROR`` is on (``enable_mirror()``,
    flipped by ``CheckService.start()`` and ``web.make_server``), every
    ``obs.counter``/``obs.gauge`` call also lands here under its event
    name (``serve.queue_depth`` → ``jepsen_tpu_serve_queue_depth``), so
    the fault/retry/cache counters the pipeline already emits surface
    with zero extra call sites;
  * **explicit calls** — the serving layer records what spans can't
    mirror: admission/end-to-end latency histograms, per-batch
    occupancy and padding waste, verdict counts by outcome
    (``inc``/``set_gauge``/``observe`` below, gated on the same MIRROR
    flag so a library user who never serves pays nothing).

The self-healing layer (``jepsen_tpu.serve.health``) feeds its own
``serve_*`` fault series through here (some via the obs mirror, some
explicit): ``serve_quarantined_total`` /
``serve_quarantine_hit_total`` / ``serve_poison_isolated_total`` /
``serve_poison_bisect_launches_total`` (poison quarantine),
``serve_breaker_rejected_total`` / ``serve_breaker_opened_total`` and the
``serve_breaker_open`` gauge (circuit breaker),
``serve_watchdog_trips_total`` (hung-launch watchdog),
``serve_devices_lost_total`` + the ``serve_placement_devices`` gauge
(device-loss re-placement), ``serve_journal_replayed_total`` (crash-safe
restart), ``serve_idempotent_hits_total`` (duplicate submits served
from the idempotency map instead of re-run), and
``serve_drain_errors_total`` /
``serve_placement_probe_errors_total`` (previously-swallowed drain and
parity-probe failures, now counted).

The durable-record layer (``jepsen_tpu.store.durable``) feeds through
the obs mirror: ``jepsen_tpu_durable_corrupt_total`` (artifacts
quarantined aside), ``jepsen_tpu_durable_migrated_total`` (old-format
payloads upgraded at read), ``jepsen_tpu_durable_tmp_swept_total``
(orphaned ``*.tmp`` reclamation), and the
``jepsen_tpu_durable_ledger_skipped`` gauge (perf-ledger lines
currently dropped by the per-record checksum reader — a gauge, not a
counter, because the same ledger is read many times per process).

The bounded-memory layer (``jepsen_tpu.ops.spill``) feeds through the
obs mirror: ``jepsen_tpu_frontier_spill_rows_total`` /
``jepsen_tpu_frontier_spill_bytes_total`` (host-spilled frontier
volume), ``jepsen_tpu_frontier_spill_merges_total`` (LSH-bucketed
recombines), ``jepsen_tpu_frontier_factorizations_total`` (crashed-op
groups factored away), ``jepsen_tpu_frontier_undecidable_total``
(honest-exhaustion reports, explicit — events don't mirror), and
``jepsen_tpu_fault_oom_spill_total`` (OOM launches recovered by
spilling device memory instead of halving work).

Import-light by design (stdlib only — obs and faults import this
module, and both must stay jax-free).  Everything is thread-safe; label
sets are expected to be tiny (verdict, fault kind), never unbounded
(no trace ids or error strings as labels).
"""

from __future__ import annotations

import math
import re
import threading
from typing import Mapping

__all__ = [
    "LATENCY_BUCKETS", "MIRROR", "REGISTRY", "Registry", "enable_mirror",
    "inc", "metric_name", "observe", "render", "set_gauge",
]

#: whether the live registry is fed at all (see module doc).  One module
#: attribute read on the obs fast path when everything is off.
MIRROR = False

#: default histogram bounds: request latencies from sub-ms admission
#: waits to multi-minute ladder runs.  +Inf is implicit.
LATENCY_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0,
)

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")
_PREFIX = "jepsen_tpu_"


def metric_name(name: str) -> str:
    """An obs event name as a Prometheus metric name:
    ``serve.queue_depth`` → ``jepsen_tpu_serve_queue_depth``."""
    n = _NAME_RE.sub("_", str(name))
    if not n.startswith(_PREFIX):
        n = _PREFIX + n
    return n


def _escape_label(v) -> str:
    return (
        str(v).replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")
    )


def _labels_key(labels: Mapping) -> tuple:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _labels_str(key: tuple) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{_escape_label(v)}"' for k, v in key)
    return "{" + inner + "}"


class Registry:
    """Thread-safe counters / gauges / histograms, keyed on
    ``(name, sorted-label-pairs)``."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[tuple[str, tuple], float] = {}
        self._gauges: dict[tuple[str, tuple], float] = {}
        # (name, labels) -> {"bounds": tuple, "buckets": [int]*len+1,
        #                    "sum": float, "count": int}
        self._hists: dict[tuple[str, tuple], dict] = {}

    def inc(self, name: str, n: float = 1, **labels) -> None:
        key = (metric_name(name), _labels_key(labels))
        with self._lock:
            self._counters[key] = self._counters.get(key, 0) + n

    def set(self, name: str, value, **labels) -> None:
        if isinstance(value, bool):
            value = int(value)
        if not isinstance(value, (int, float)):
            return  # gauges mirror arbitrary obs values; only numbers scrape
        key = (metric_name(name), _labels_key(labels))
        with self._lock:
            self._gauges[key] = value

    def observe(self, name: str, value: float, *, buckets=LATENCY_BUCKETS,
                **labels) -> None:
        key = (metric_name(name), _labels_key(labels))
        with self._lock:
            h = self._hists.get(key)
            if h is None:
                h = self._hists[key] = {
                    "bounds": tuple(buckets),
                    "buckets": [0] * (len(buckets) + 1),
                    "sum": 0.0, "count": 0,
                }
            i = 0
            for i, b in enumerate(h["bounds"]):
                if value <= b:
                    break
            else:
                i = len(h["bounds"])
            h["buckets"][i] += 1
            h["sum"] += value
            h["count"] += 1

    def remove(self, name: str, **labels) -> None:
        """Drop one series (all three families) so a stale value stops
        rendering — used when a publisher's source no longer carries a
        previously-exported label set (e.g. obs.regress headline gauges
        after the newest ledger record drops a metric)."""
        key = (metric_name(name), _labels_key(labels))
        with self._lock:
            self._counters.pop(key, None)
            self._gauges.pop(key, None)
            self._hists.pop(key, None)

    def get(self, name: str, **labels):
        """A counter or gauge's current value (tests, the web panel);
        None when the series doesn't exist."""
        key = (metric_name(name), _labels_key(labels))
        with self._lock:
            if key in self._counters:
                return self._counters[key]
            return self._gauges.get(key)

    def histogram(self, name: str, **labels) -> dict | None:
        key = (metric_name(name), _labels_key(labels))
        with self._lock:
            h = self._hists.get(key)
            return None if h is None else {
                "count": h["count"], "sum": h["sum"],
            }

    def histogram_buckets(self, name: str, **labels) -> dict | None:
        """The full bucket view (bounds + per-bucket counts + count/sum)
        — what the SLO burn-rate engine reads to split a latency
        histogram into good/bad at a threshold.  None when the series
        doesn't exist."""
        key = (metric_name(name), _labels_key(labels))
        with self._lock:
            h = self._hists.get(key)
            return None if h is None else {
                "bounds": tuple(h["bounds"]),
                "buckets": list(h["buckets"]),
                "count": h["count"], "sum": h["sum"],
            }

    def snapshot(self) -> dict:
        """A JSONable dump: {"counters": {...}, "gauges": {...},
        "histograms": {name: {"count", "sum", "mean"}}}."""
        with self._lock:
            out = {
                "counters": {
                    k + _labels_str(lk): v
                    for (k, lk), v in sorted(self._counters.items())
                },
                "gauges": {
                    k + _labels_str(lk): v
                    for (k, lk), v in sorted(self._gauges.items())
                },
                "histograms": {
                    k + _labels_str(lk): {
                        "count": h["count"], "sum": round(h["sum"], 6),
                        "mean": round(h["sum"] / h["count"], 6)
                        if h["count"] else None,
                    }
                    for (k, lk), h in sorted(self._hists.items())
                },
            }
        return out

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()

    def render(self) -> str:
        """Prometheus text exposition format (0.0.4): counters get a
        ``_total`` suffix, histograms the ``_bucket``/``_sum``/``_count``
        triple with cumulative ``le`` buckets."""
        lines: list[str] = []
        with self._lock:
            by_family: dict[str, list[str]] = {}

            def fam(name: str, kind: str) -> list[str]:
                rows = by_family.get(name)
                if rows is None:
                    rows = by_family[name] = [f"# TYPE {name} {kind}"]
                return rows

            for (name, lk), v in sorted(self._counters.items()):
                n = name if name.endswith("_total") else name + "_total"
                fam(n, "counter").append(f"{n}{_labels_str(lk)} {_num(v)}")
            for (name, lk), v in sorted(self._gauges.items()):
                fam(name, "gauge").append(f"{name}{_labels_str(lk)} {_num(v)}")
            for (name, lk), h in sorted(self._hists.items()):
                rows = fam(name, "histogram")
                cum = 0
                for b, cnt in zip(h["bounds"], h["buckets"]):
                    cum += cnt
                    lb = _labels_str(lk + (("le", _num(b)),))
                    rows.append(f"{name}_bucket{lb} {cum}")
                cum += h["buckets"][-1]
                lb = _labels_str(lk + (("le", "+Inf"),))
                rows.append(f"{name}_bucket{lb} {cum}")
                rows.append(f"{name}_sum{_labels_str(lk)} {_num(h['sum'])}")
                rows.append(f"{name}_count{_labels_str(lk)} {h['count']}")
            for name in sorted(by_family):
                lines.extend(by_family[name])
        return "\n".join(lines) + ("\n" if lines else "")


def _num(v) -> str:
    if isinstance(v, float):
        if math.isinf(v):
            return "+Inf" if v > 0 else "-Inf"
        if v == int(v) and abs(v) < 1e15:
            return str(int(v))
        return repr(v)
    return str(v)


#: THE process-global registry /metrics renders.
REGISTRY = Registry()


def enable_mirror(on: bool = True) -> None:
    """Turn the live registry's feeds on (module doc).  Idempotent;
    flipped by ``CheckService.start()`` and ``web.make_server``."""
    global MIRROR
    MIRROR = bool(on)


def inc(name: str, n: float = 1, **labels) -> None:
    """Explicit labeled counter; no-op unless the registry is enabled."""
    if MIRROR:
        REGISTRY.inc(name, n, **labels)


def set_gauge(name: str, value, **labels) -> None:
    if MIRROR:
        REGISTRY.set(name, value, **labels)


def observe(name: str, value: float, **labels) -> None:
    """Explicit histogram observation (latencies, ratios); no-op unless
    the registry is enabled."""
    if MIRROR:
        REGISTRY.observe(name, value, **labels)


def render() -> str:
    return REGISTRY.render()
