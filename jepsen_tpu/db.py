"""DB automation: installing, starting, and wrecking the system under test.

Mirrors ``jepsen.db`` (reference: jepsen/src/jepsen/db.clj): the ``DB``
lifecycle protocol (db.clj:11-16), optional capability mix-ins ``Process``
(start!/kill!, db.clj:18-24), ``Pause`` (pause!/resume!, db.clj:26-29),
``Primary`` (db.clj:31-38), ``LogFiles`` (db.clj:40-47); the
``cycle_db`` teardown→setup sequence with setup retries (db.clj:117-158);
and the tcpdump-capture DB (db.clj:49-115).
"""

from __future__ import annotations

import logging
from typing import Mapping, Sequence

from jepsen_tpu import control

logger = logging.getLogger(__name__)


class DB:
    """Core lifecycle (db.clj:11-16).  Methods receive (test, node,
    session)."""

    def setup(self, test, node, session) -> None:
        """Install and start the database."""

    def teardown(self, test, node, session) -> None:
        """Tear down and destroy all traces of the database."""

    # -- capability probes --------------------------------------------------

    def log_files(self, test, node) -> Sequence[str]:
        """Paths of log files to download after the run (db.clj:40-47)."""
        return []

    # Process (db.clj:18-24): override both to advertise the capability.
    def start(self, test, node, session):
        raise NotImplementedError

    def kill(self, test, node, session):
        raise NotImplementedError

    # Pause (db.clj:26-29)
    def pause(self, test, node, session):
        raise NotImplementedError

    def resume(self, test, node, session):
        raise NotImplementedError

    # Primary (db.clj:31-38)
    def primaries(self, test) -> Sequence[str]:
        raise NotImplementedError

    def setup_primary(self, test, node, session):
        """One-time setup executed on the first primary only."""
        raise NotImplementedError


def supports(db: DB, method: str) -> bool:
    """Did the subclass actually implement this optional capability?"""
    return getattr(type(db), method, None) is not getattr(DB, method, None)


class NoopDB(DB):
    """No database at all (for stub tests)."""


def noop() -> DB:
    return NoopDB()


class SetupFailed(Exception):
    pass


def cycle_db(test: Mapping, retries: int = 3):
    """Tear down then set up the DB on all nodes, retrying setup failures
    (db.clj:117-158).  Also runs setup_primary on the first primary when
    the DB supports Primary (db.clj:141-146)."""
    db: DB = test["db"]
    for attempt in range(retries):
        try:
            control.on_nodes(test, db.teardown)
            control.on_nodes(test, db.setup)
            if supports(db, "setup_primary"):
                prims = list(db.primaries(test)) if supports(db, "primaries") else []
                primary = prims[0] if prims else (test["nodes"] or [None])[0]
                if primary is not None:
                    control.on_nodes(test, db.setup_primary, nodes=[primary])
            return
        except SetupFailed:
            if attempt == retries - 1:
                raise
            logger.warning("db setup failed; retrying (%d/%d)", attempt + 1, retries)


class TcpdumpDB(DB):
    """Capture packets on each node for the duration of the test
    (db.clj:49-115).  Wrap it in your test's db via ``compose``. """

    def __init__(self, filter_expr: str = "", pcap_path: str = "/tmp/jepsen/trace.pcap"):
        self.filter_expr = filter_expr
        self.pcap_path = pcap_path
        self.pidfile = pcap_path + ".pid"

    def setup(self, test, node, session):
        from jepsen_tpu.control import util as cu

        with session.su():
            session.exec("mkdir", "-p", "/tmp/jepsen")
            cu.start_daemon(
                session, "tcpdump", "-w", self.pcap_path,
                *(self.filter_expr.split() if self.filter_expr else []),
                pidfile=self.pidfile, logfile="/tmp/jepsen/tcpdump.log",
            )

    def teardown(self, test, node, session):
        from jepsen_tpu.control import util as cu

        with session.su():
            cu.stop_daemon(session, self.pidfile)
            session.exec_result("rm", "-f", self.pcap_path)

    def log_files(self, test, node):
        return [self.pcap_path]


class ComposedDB(DB):
    """Run several DBs' lifecycles together (setup in order, teardown in
    reverse)."""

    def __init__(self, dbs: Sequence[DB]):
        self.dbs = list(dbs)

    def setup(self, test, node, session):
        for d in self.dbs:
            d.setup(test, node, session)

    def teardown(self, test, node, session):
        for d in reversed(self.dbs):
            d.teardown(test, node, session)

    def log_files(self, test, node):
        out = []
        for d in self.dbs:
            out.extend(d.log_files(test, node))
        return out


def compose(dbs: Sequence[DB]) -> DB:
    return ComposedDB(dbs)
