"""Remote protocol: pluggable transports for running commands on db nodes.

Mirrors ``jepsen.control.core`` (reference:
jepsen/src/jepsen/control/core.clj:7-58): a Remote can connect to a host,
execute shell actions, and copy files both ways.  Four interchangeable
implementations, like the reference's clj-ssh/sshj/docker/k8s set:

  DummyRemote   — records actions, runs nothing (control.clj:40; wired via
                  ``{"dummy?": True}`` ssh opts, cli.clj:233) — the backend
                  for self-tests
  LocalRemote   — runs actions as local subprocesses (fills the niche of
                  the reference's docker/k8s remotes for single-machine
                  integration tests)
  SshRemote     — shells out to ``ssh``/``scp`` (the reference deliberately
                  shells out for scp too: JVM SSH is orders of magnitude
                  slower, control/scp.clj:1-9)
  DockerRemote  — ``docker exec`` / ``docker cp`` (control/docker.clj)

An *action* is a dict: ``{"cmd": str, "in": stdin-str?, "dir": cwd?,
"sudo": user?, "env": {k: v}?}``.  Results merge in ``out``, ``err``,
``exit``.  Nonzero exits raise ``RemoteExecError`` unless
``check=False`` (control/core.clj:155-171 throw-on-nonzero-exit).
"""

from __future__ import annotations

import dataclasses
import shlex
import subprocess
import time
from typing import Any, Mapping, Sequence

DEFAULT_TIMEOUT_S = 600.0


class RemoteError(Exception):
    """Connection-level failure (the reference's ::ssh-failed)."""


class RemoteExecError(Exception):
    """A command exited nonzero (control/core.clj:155-171 ::nonzero-exit)."""

    def __init__(self, host, action, result):
        self.host = host
        self.action = action
        self.result = result
        super().__init__(
            f"command on {host} exited {result.get('exit')}: "
            f"{action.get('cmd')!r}\nstdout: {result.get('out', '')[:2000]}\n"
            f"stderr: {result.get('err', '')[:2000]}"
        )


def escape(args: Sequence[Any]) -> str:
    """Build a safely-quoted shell command from argument fragments
    (control/core.clj:67-110).  ``Lit`` fragments pass through unquoted."""
    parts = []
    for a in args:
        if isinstance(a, Lit):
            parts.append(a.s)
        else:
            parts.append(shlex.quote(str(a)))
    return " ".join(parts)


@dataclasses.dataclass(frozen=True)
class Lit:
    """An unescaped shell literal (e.g. ``Lit('|')``, ``Lit('2>&1')``) —
    the reference's ``c/lit``."""

    s: str


def wrap_sudo(action: Mapping) -> Mapping:
    """Rewrite an action to run under sudo -u <user>
    (control/core.clj:142-153).  ``-n`` (never prompt) rather than the
    reference's ``-S``: the action's stdin is user payload (e.g. tee'd file
    content), not a password, and a prompting sudo must fail loudly."""
    sudo = action.get("sudo")
    if not sudo:
        return action
    cmd = f"sudo -n -u {shlex.quote(str(sudo))} bash -c {shlex.quote(action['cmd'])}"
    return {**action, "cmd": cmd, "sudo": None}


def wrap_cd(action: Mapping) -> Mapping:
    d = action.get("dir")
    if not d:
        return action
    return {**action, "cmd": f"cd {shlex.quote(str(d))} && {action['cmd']}", "dir": None}


def wrap_env(action: Mapping) -> Mapping:
    env = action.get("env")
    if not env:
        return action
    prefix = " ".join(f"{k}={shlex.quote(str(v))}" for k, v in env.items())
    return {**action, "cmd": f"env {prefix} {action['cmd']}", "env": None}


def full_cmd(action: Mapping) -> str:
    return wrap_sudo(wrap_cd(wrap_env(action)))["cmd"]


class Remote:
    """Transport protocol (control/core.clj:7-58)."""

    def connect(self, conn_spec: Mapping) -> "Remote":
        """Return a connected copy bound to conn_spec ({host, port, user,
        password?, private-key-path?, container?})."""
        raise NotImplementedError

    def execute(self, action: Mapping) -> dict:
        raise NotImplementedError

    def upload(self, local_paths, remote_path) -> None:
        raise NotImplementedError

    def download(self, remote_paths, local_path) -> None:
        raise NotImplementedError

    def disconnect(self) -> None:
        pass


class DummyRemote(Remote):
    """Does nothing, remembers everything (control.clj:40 dummy remote).

    ``handler(action) -> result-dict`` lets tests script responses.
    """

    def __init__(self, handler=None):
        self.handler = handler
        self.host = None
        self.history: list = []

    def connect(self, conn_spec):
        r = DummyRemote(self.handler)
        r.host = conn_spec.get("host")
        r.history = self.history  # shared log across nodes, like one test run
        return r

    def execute(self, action):
        self.history.append({"host": self.host, **action})
        if self.handler is not None:
            res = self.handler(action) or {}
        else:
            res = {}
        return {"out": "", "err": "", "exit": 0, **res}

    def upload(self, local_paths, remote_path):
        self.history.append(
            {"host": self.host, "upload": list(map(str, _as_list(local_paths))), "to": str(remote_path)}
        )

    def download(self, remote_paths, local_path):
        self.history.append(
            {"host": self.host, "download": list(map(str, _as_list(remote_paths))), "to": str(local_path)}
        )


def _as_list(x):
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


class LocalRemote(Remote):
    """Run actions as local subprocesses — a real backend for
    single-machine integration tests (the role the reference's docker
    environment plays, docker/README.md)."""

    def __init__(self, timeout: float = DEFAULT_TIMEOUT_S):
        self.timeout = timeout
        self.host = None

    def connect(self, conn_spec):
        r = LocalRemote(self.timeout)
        r.host = conn_spec.get("host", "local")
        return r

    def execute(self, action):
        cmd = full_cmd(action)
        try:
            p = subprocess.run(
                ["bash", "-c", cmd],
                input=action.get("in"),
                capture_output=True,
                text=True,
                timeout=action.get("timeout", self.timeout),
            )
        except subprocess.TimeoutExpired as e:
            raise RemoteError(f"local command timed out: {cmd!r}") from e
        return {"out": p.stdout, "err": p.stderr, "exit": p.returncode}

    def upload(self, local_paths, remote_path):
        self.execute({"cmd": escape(["cp", "-r", *_as_list(local_paths), remote_path])})

    def download(self, remote_paths, local_path):
        self.execute({"cmd": escape(["cp", "-r", *_as_list(remote_paths), local_path])})


SSH_BASE_OPTS = [
    "-o", "StrictHostKeyChecking=no",
    "-o", "UserKnownHostsFile=/dev/null",
    "-o", "LogLevel=ERROR",
    "-o", "ServerAliveInterval=25",
]


class SshRemote(Remote):
    """OpenSSH-subprocess remote (the role of control/clj_ssh.clj+scp.clj).

    conn_spec keys: host, port (22), user ("root"), private-key-path,
    password (unsupported — use keys or an agent, like CI does).
    """

    def __init__(self, timeout: float = DEFAULT_TIMEOUT_S):
        self.timeout = timeout
        self.spec: dict = {}

    def connect(self, conn_spec):
        r = SshRemote(self.timeout)
        r.spec = dict(conn_spec)
        # Fail fast if unreachable, mirroring connect-time errors.
        try:
            res = r.execute({"cmd": "true", "timeout": conn_spec.get("connect-timeout", 30)})
        except RemoteError:
            raise
        if res["exit"] != 0:
            raise RemoteError(f"ssh to {conn_spec.get('host')} failed: {res['err']}")
        return r

    def _ssh_opts(self):
        o = list(SSH_BASE_OPTS)
        if self.spec.get("port"):
            o += ["-p", str(self.spec["port"])]
        if self.spec.get("private-key-path"):
            o += ["-i", str(self.spec["private-key-path"])]
        return o

    def _target(self):
        user = self.spec.get("user", "root")
        return f"{user}@{self.spec['host']}"

    def execute(self, action):
        cmd = full_cmd(action)
        argv = ["ssh", *self._ssh_opts(), self._target(), cmd]
        try:
            p = subprocess.run(
                argv,
                input=action.get("in"),
                capture_output=True,
                text=True,
                timeout=action.get("timeout", self.timeout),
            )
        except subprocess.TimeoutExpired as e:
            raise RemoteError(f"ssh command timed out on {self.spec.get('host')}") from e
        if p.returncode == 255:
            # OpenSSH reserves 255 for transport errors.
            raise RemoteError(f"ssh transport to {self.spec.get('host')} failed: {p.stderr}")
        return {"out": p.stdout, "err": p.stderr, "exit": p.returncode}

    def _scp_opts(self):
        o = [x for x in SSH_BASE_OPTS]
        if self.spec.get("port"):
            o += ["-P", str(self.spec["port"])]
        if self.spec.get("private-key-path"):
            o += ["-i", str(self.spec["private-key-path"])]
        return o

    def upload(self, local_paths, remote_path):
        argv = ["scp", "-r", *self._scp_opts(), *map(str, _as_list(local_paths)),
                f"{self._target()}:{remote_path}"]
        p = subprocess.run(argv, capture_output=True, text=True, timeout=self.timeout)
        if p.returncode != 0:
            raise RemoteError(f"scp upload failed: {p.stderr}")

    def download(self, remote_paths, local_path):
        argv = ["scp", "-r", *self._scp_opts(),
                *[f"{self._target()}:{r}" for r in _as_list(remote_paths)], str(local_path)]
        p = subprocess.run(argv, capture_output=True, text=True, timeout=self.timeout)
        if p.returncode != 0:
            raise RemoteError(f"scp download failed: {p.stderr}")


class DockerRemote(Remote):
    """``docker exec`` remote (control/docker.clj): conn_spec host is the
    container name/id (or set ``container``)."""

    def __init__(self, timeout: float = DEFAULT_TIMEOUT_S):
        self.timeout = timeout
        self.container = None

    def connect(self, conn_spec):
        r = DockerRemote(self.timeout)
        r.container = conn_spec.get("container") or conn_spec.get("host")
        return r

    def execute(self, action):
        cmd = full_cmd(action)
        argv = ["docker", "exec", "-i", str(self.container), "bash", "-c", cmd]
        try:
            p = subprocess.run(
                argv, input=action.get("in"), capture_output=True, text=True,
                timeout=action.get("timeout", self.timeout),
            )
        except subprocess.TimeoutExpired as e:
            raise RemoteError(f"docker exec timed out in {self.container}") from e
        return {"out": p.stdout, "err": p.stderr, "exit": p.returncode}

    def upload(self, local_paths, remote_path):
        for lp in _as_list(local_paths):
            p = subprocess.run(["docker", "cp", str(lp), f"{self.container}:{remote_path}"],
                               capture_output=True, text=True, timeout=self.timeout)
            if p.returncode != 0:
                raise RemoteError(f"docker cp failed: {p.stderr}")

    def download(self, remote_paths, local_path):
        for rp in _as_list(remote_paths):
            p = subprocess.run(["docker", "cp", f"{self.container}:{rp}", str(local_path)],
                               capture_output=True, text=True, timeout=self.timeout)
            if p.returncode != 0:
                raise RemoteError(f"docker cp failed: {p.stderr}")


class K8sRemote(Remote):
    """``kubectl exec`` remote (control/k8s.clj): conn_spec host is the
    pod name; ``namespace`` and ``container`` narrow the target."""

    def __init__(self, timeout: float = DEFAULT_TIMEOUT_S):
        self.timeout = timeout
        self.pod = None
        self.namespace = None
        self.container = None

    def _kubectl(self, *args) -> list:
        argv = ["kubectl"]
        if self.namespace:
            argv += ["-n", str(self.namespace)]
        argv += list(args)
        return argv

    def connect(self, conn_spec):
        r = K8sRemote(self.timeout)
        r.pod = conn_spec.get("pod") or conn_spec.get("host")
        r.namespace = conn_spec.get("namespace")
        r.container = conn_spec.get("container")
        return r

    def execute(self, action):
        cmd = full_cmd(action)
        argv = self._kubectl("exec", "-i", str(self.pod))
        if self.container:
            argv += ["-c", str(self.container)]
        argv += ["--", "bash", "-c", cmd]
        try:
            p = subprocess.run(
                argv, input=action.get("in"), capture_output=True, text=True,
                timeout=action.get("timeout", self.timeout),
            )
        except subprocess.TimeoutExpired as e:
            raise RemoteError(f"kubectl exec timed out in {self.pod}") from e
        return {"out": p.stdout, "err": p.stderr, "exit": p.returncode}

    def _cp(self, src, dest):
        extra = ["-c", str(self.container)] if self.container else []
        p = subprocess.run(self._kubectl("cp", *extra, str(src), str(dest)),
                           capture_output=True, text=True, timeout=self.timeout)
        if p.returncode != 0:
            raise RemoteError(f"kubectl cp failed: {p.stderr}")

    def upload(self, local_paths, remote_path):
        for lp in _as_list(local_paths):
            self._cp(lp, f"{self.pod}:{remote_path}")

    def download(self, remote_paths, local_path):
        for rp in _as_list(remote_paths):
            self._cp(f"{self.pod}:{rp}", local_path)


class RetryRemote(Remote):
    """Wrap a remote, retrying transport failures with backoff
    (control/retry.clj:15-33; 5 tries, ~100 ms)."""

    def __init__(self, remote: Remote, tries: int = 5, backoff: float = 0.1):
        self.remote = remote
        self.tries = tries
        self.backoff = backoff
        self.spec: dict = {}

    def connect(self, conn_spec):
        r = RetryRemote(self.remote, self.tries, self.backoff)
        r.spec = dict(conn_spec)
        r.remote = self._retry(lambda: self.remote.connect(conn_spec))
        return r

    def _retry(self, f):
        last = None
        for i in range(self.tries):
            try:
                return f()
            except RemoteError as e:
                last = e
                time.sleep(self.backoff * (1 + i))
        raise last

    def execute(self, action):
        return self._retry(lambda: self.remote.execute(action))

    def upload(self, local_paths, remote_path):
        return self._retry(lambda: self.remote.upload(local_paths, remote_path))

    def download(self, remote_paths, local_path):
        return self._retry(lambda: self.remote.download(remote_paths, local_path))

    def disconnect(self):
        self.remote.disconnect()
