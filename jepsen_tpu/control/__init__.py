"""Control facade: sessions, exec sugar, and parallel per-node fan-out.

Mirrors ``jepsen.control`` (reference: jepsen/src/jepsen/control.clj).  The
reference threads state through dynamic vars (*host*, *session*, *sudo*,
control.clj:39-53); here a ``Session`` object carries the same state
explicitly, which plays nicer with Python threads.

  session = control.session(test, "n1")
  session.exec("echo", "hi")            -> "hi"        (control.clj:151)
  with session.su():  ...               sudo root      (control.clj:215)
  with session.cd("/tmp"): ...                         (control.clj:203)
  control.on_nodes(test, fn)            -> {node: fn(test, node, session)}
                                        parallel, control.clj:272-311

Backend selection mirrors cli.clj:233 / control.clj:35-37: the test map's
``ssh`` opts pick the transport (``{"dummy?": True}`` → DummyRemote), or a
``remote`` key supplies a Remote instance directly.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Any, Callable, Mapping

from jepsen_tpu.control.core import (
    DockerRemote,
    DummyRemote,
    K8sRemote,
    Lit,
    LocalRemote,
    Remote,
    RemoteError,
    RemoteExecError,
    RetryRemote,
    SshRemote,
    escape,
)
from jepsen_tpu.utils import real_pmap

__all__ = [
    "DockerRemote", "DummyRemote", "K8sRemote", "Lit", "LocalRemote", "Remote",
    "RemoteError", "RemoteExecError", "RetryRemote", "SshRemote",
    "Session", "escape", "base_remote", "session", "on_nodes", "on_many",
    "with_sessions",
]


def base_remote(test: Mapping) -> Remote:
    """Choose the transport from the test map (control.clj:35-37,
    cli.clj:233)."""
    if test.get("remote") is not None:
        return test["remote"]
    ssh = test.get("ssh") or {}
    if ssh.get("dummy?"):
        return DummyRemote()
    if ssh.get("local?"):
        return LocalRemote()
    if ssh.get("docker?"):
        return DockerRemote()
    if ssh.get("k8s?"):
        return K8sRemote()
    return RetryRemote(SshRemote())


class Session:
    """A connected control channel to one node."""

    def __init__(self, remote: Remote, node: str, ssh_opts: Mapping | None = None):
        self.remote = remote
        self.node = node
        self.ssh_opts = dict(ssh_opts or {})
        self._sudo: str | None = None
        self._dir: str | None = None

    # -- exec ---------------------------------------------------------------

    def exec_result(self, *args, stdin=None, timeout=None, env=None) -> dict:
        """Run a command, returning the full {out, err, exit} result."""
        action: dict[str, Any] = {"cmd": escape(args)}
        if stdin is not None:
            action["in"] = stdin
        if self._sudo:
            action["sudo"] = self._sudo
        if self._dir:
            action["dir"] = self._dir
        if timeout is not None:
            action["timeout"] = timeout
        if env:
            action["env"] = env
        return self.remote.execute(action)

    def exec(self, *args, check=True, **kw) -> str:
        """Run a command, returning trimmed stdout; raise on nonzero exit
        (control.clj:151-157 + control/core.clj:155-171)."""
        res = self.exec_result(*args, **kw)
        if check and res.get("exit", 0) != 0:
            raise RemoteExecError(self.node, {"cmd": escape(args)}, res)
        return (res.get("out") or "").strip()

    # -- file transfer ------------------------------------------------------

    def upload(self, local_paths, remote_path):
        self.remote.upload(local_paths, remote_path)

    def download(self, remote_paths, local_path):
        self.remote.download(remote_paths, local_path)

    def write_file(self, content: str, remote_path: str):
        """Write a string to a remote file via stdin (control/util.clj:88)."""
        self.exec("tee", remote_path, stdin=content)

    # -- modifiers ----------------------------------------------------------

    @contextlib.contextmanager
    def su(self, user: str = "root"):
        """sudo block (control.clj:215-218)."""
        prev = self._sudo
        self._sudo = user
        try:
            yield self
        finally:
            self._sudo = prev

    @contextlib.contextmanager
    def cd(self, directory: str):
        """working-directory block (control.clj:203-213)."""
        prev = self._dir
        self._dir = directory
        try:
            yield self
        finally:
            self._dir = prev

    def disconnect(self):
        self.remote.disconnect()


def session(test: Mapping, node: str) -> Session:
    """Connect a session to node (control.clj:226-234)."""
    ssh = dict(test.get("ssh") or {})
    spec = {"host": node, **{k: v for k, v in ssh.items() if k not in ("dummy?", "local?", "docker?")}}
    remote = base_remote(test).connect(spec)
    return Session(remote, node, ssh)


_sessions_lock = threading.Lock()


def sessions(test: Mapping) -> dict:
    """The test's session cache {node: Session}; missing nodes connect in
    parallel (core.clj:275-295 with-sessions + real-pmap)."""
    with _sessions_lock:
        cache = test.get("sessions")
        if cache is None:
            cache = {}
            test["sessions"] = cache  # type: ignore[index]
        missing = [n for n in (test.get("nodes") or []) if n not in cache]
    if missing:
        connected = real_pmap(lambda n: (n, session(test, n)), missing)
        with _sessions_lock:
            for n, s in connected:
                cache.setdefault(n, s)
    return cache


@contextlib.contextmanager
def with_sessions(test: Mapping):
    """Connect sessions to every node; disconnect on exit."""
    try:
        yield sessions(test)
    finally:
        cache = test.get("sessions") or {}
        for s in cache.values():
            try:
                s.disconnect()
            except Exception:  # noqa: BLE001
                pass
        if "sessions" in test:
            test["sessions"] = None  # type: ignore[index]


def on_nodes(test: Mapping, f: Callable, nodes=None) -> dict:
    """Run ``f(test, node, session)`` on every node in parallel; returns
    {node: result} (control.clj:272-311 via real-pmap)."""
    nodes = list(nodes if nodes is not None else (test.get("nodes") or []))
    sess = sessions(test)
    results = real_pmap(lambda n: (n, f(test, n, sess[n])), nodes)
    return dict(results)


def on_many(test: Mapping, nodes, f: Callable) -> dict:
    return on_nodes(test, f, nodes)
