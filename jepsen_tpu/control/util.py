"""Install/daemon utilities on top of the control layer.

Mirrors ``jepsen.control.util`` (reference:
jepsen/src/jepsen/control/util.clj, 403 LoC): port waiting, tmp files,
cached downloads, archive installation, daemon supervision, grepkill.
All functions take a connected ``Session`` as their first argument.
"""

from __future__ import annotations

import base64
import random
import shlex
import time
from typing import Mapping

from jepsen_tpu.control import Lit, Session
from jepsen_tpu.control.core import RemoteExecError

WGET_CACHE_DIR = "/tmp/jepsen/wget-cache"


def exists(s: Session, path: str) -> bool:
    """Does a file exist? (control/util.clj:38-44)."""
    return s.exec_result("test", "-e", path).get("exit") == 0


def file_p(s: Session, path: str) -> bool:
    return s.exec_result("test", "-f", path).get("exit") == 0


def await_tcp_port(
    s: Session,
    port: int,
    timeout: float = 60.0,
    interval: float = 0.5,
    max_interval: float = 2.0,
):
    """Block until something listens on port (control/util.clj:14-30).

    A hung connect attempt (packets dropped — exactly the conditions this
    harness creates) counts as "not listening yet", not a transport error.

    Polls at ``interval`` for the first few probes (a freshly exec'd
    daemon usually listens within a second, the harness pays this wait
    per node per db cycle, and backing off too early costs real seconds
    across a suite), then doubles up to ``max_interval`` with jitter
    (each sleep is 0.5–1.0x the nominal delay, so many nodes awaiting
    the same slow daemon don't probe in lockstep).  On timeout the
    ``TimeoutError`` names the LAST probe failure — "connection
    refused" vs a dead control session vs a nonzero exec are very
    different debugging starts."""
    from jepsen_tpu.control.core import RemoteError

    deadline = time.monotonic() + timeout
    delay = interval
    attempt = 0
    last_err = "no probe completed"
    while True:
        try:
            r = s.exec_result(
                "bash", "-c", f"exec 3<>/dev/tcp/localhost/{int(port)}", timeout=5
            )
            if r.get("exit") == 0:
                return
            err = (r.get("err") or r.get("out") or "").strip()
            last_err = f"probe exit {r.get('exit')}" + (f": {err}" if err else "")
        except RemoteError as e:
            last_err = f"{type(e).__name__}: {e}"
        now = time.monotonic()
        if now > deadline:
            raise TimeoutError(
                f"nothing listening on {s.node}:{port} after {timeout}s "
                f"(last probe: {last_err})"
            )
        time.sleep(min(delay, max(0.0, deadline - now)) * (0.5 + 0.5 * random.random()))
        attempt += 1
        if attempt >= 3:  # back off only once the fast path has clearly missed
            delay = min(delay * 2, max_interval)


def tmp_file(s: Session, suffix: str = "") -> str:
    """Create a remote temp file, returning its path (control/util.clj:63-76)."""
    return s.exec("mktemp", f"--suffix={suffix}" if suffix else "--tmpdir=/tmp")


def tmp_dir(s: Session) -> str:
    """(control/util.clj:78-86)."""
    return s.exec("mktemp", "-d")


def wget(s: Session, url: str, dest: str | None = None, force: bool = False) -> str:
    """Download url on the node, returning the local path
    (control/util.clj:133-160)."""
    name = url.rstrip("/").rsplit("/", 1)[-1]
    dest = dest or name
    if force:
        s.exec_result("rm", "-f", dest)
    if not exists(s, dest):
        s.exec("wget", "-q", "-O", dest, url)
    return dest


def cached_wget(s: Session, url: str, force: bool = False) -> str:
    """Download via a persistent on-node cache keyed by the (base64) url
    (control/util.clj:162-197)."""
    key = base64.urlsafe_b64encode(url.encode()).decode().rstrip("=")
    path = f"{WGET_CACHE_DIR}/{key}"
    s.exec("mkdir", "-p", WGET_CACHE_DIR)
    if force:
        s.exec_result("rm", "-f", path)
    if not exists(s, path):
        s.exec("wget", "-q", "-O", path, url)
    return path


def install_archive(s: Session, url: str, dest: str, force: bool = False):
    """Download and unpack a tarball/zip into dest, stripping a single
    top-level directory if present (control/util.clj:199-275)."""
    if exists(s, dest) and not force:
        return dest
    archive = cached_wget(s, url, force=force)
    s.exec("rm", "-rf", dest)
    s.exec("mkdir", "-p", dest)
    if url.endswith(".zip"):
        tmp = tmp_dir(s)
        s.exec("unzip", "-qq", archive, "-d", tmp)
        _promote_single_dir(s, tmp, dest)
    else:
        # tar auto-detects compression with -a? use -xf which handles gz/bz2/xz
        tmp = tmp_dir(s)
        s.exec("tar", "-xf", archive, "-C", tmp)
        _promote_single_dir(s, tmp, dest)
    return dest


def _promote_single_dir(s: Session, tmp: str, dest: str):
    entries = [e for e in s.exec("ls", "-A", tmp).splitlines() if e]
    if len(entries) == 1:
        s.exec("bash", "-c", f"mv {shlex.quote(tmp)}/{shlex.quote(entries[0])}/* {shlex.quote(dest)}/ 2>/dev/null || mv {shlex.quote(tmp)}/{shlex.quote(entries[0])} {shlex.quote(dest)}")
    else:
        s.exec("bash", "-c", f"mv {shlex.quote(tmp)}/* {shlex.quote(dest)}/")
    s.exec_result("rm", "-rf", tmp)


def signal(s: Session, pattern: str, sig: str):
    """Send a signal to matching processes (control/util.clj:399-403).
    ``--`` ends option parsing so patterns that start with a dash (e.g.
    a daemon's ``--flag value`` command-line tail) match instead of
    erroring as unknown pkill options."""
    s.exec_result("pkill", f"-{sig}", "-f", "--", pattern)


def grepkill(s: Session, pattern: str, sig: str = "KILL"):
    """Kill processes matching pattern (control/util.clj:286-308)."""
    signal(s, pattern, sig)


def start_daemon(
    s: Session,
    binary: str,
    *args,
    pidfile: str,
    logfile: str,
    chdir: str | None = None,
    env: Mapping | None = None,
    make_pidfile: bool = True,
):
    """Start a long-running process under a pidfile, surviving the control
    session (control/util.clj:310-367, which uses start-stop-daemon; we use
    setsid+nohup for portability to minimal images)."""
    if daemon_running(s, pidfile):
        return "already-running"
    envs = ""
    if env:
        envs = " ".join(f"{k}={shlex.quote(str(v))}" for k, v in env.items()) + " "
    cd = f"cd {shlex.quote(chdir)} && " if chdir else ""
    cmd = " ".join([shlex.quote(str(binary)), *[shlex.quote(str(a)) for a in args]])
    s.exec(
        "bash", "-c",
        f"{cd}{envs}setsid nohup {cmd} >> {shlex.quote(logfile)} 2>&1 < /dev/null & "
        + (f"echo $! > {shlex.quote(pidfile)}" if make_pidfile else "true"),
    )
    return "started"


def daemon_running(s: Session, pidfile: str) -> bool:
    """Is the pidfile's process alive? (control/util.clj:369-397)."""
    r = s.exec_result(
        "bash", "-c", f"test -f {shlex.quote(pidfile)} && kill -0 $(cat {shlex.quote(pidfile)})"
    )
    return r.get("exit") == 0


def stop_daemon(s: Session, pidfile: str, signal: str = "TERM", timeout: float = 30.0):
    """Stop the pidfile's process, escalating to KILL
    (control/util.clj:340-367)."""
    if not daemon_running(s, pidfile):
        s.exec_result("rm", "-f", pidfile)
        return "not-running"
    s.exec_result("bash", "-c", f"kill -{signal} $(cat {shlex.quote(pidfile)})")
    deadline = time.monotonic() + timeout
    while daemon_running(s, pidfile):
        if time.monotonic() > deadline:
            s.exec_result("bash", "-c", f"kill -KILL $(cat {shlex.quote(pidfile)})")
            break
        time.sleep(0.2)
    s.exec_result("rm", "-f", pidfile)
    return "stopped"
