"""Client protocol: how the harness talks to the system under test.

Mirrors ``jepsen.client`` (reference: jepsen/src/jepsen/client.clj:9-27):
a client has a five-phase lifecycle —

  open(test, node)      -> a *connected* copy of this client bound to node
  setup(test)           -> one-time data setup (schemas, tables)
  invoke(test, op)      -> perform op, return its completion op
  teardown(test)        -> undo setup
  close(test)           -> release connections

``invoke`` MUST return a completion of the same op: same :f, same :process,
:type ∈ {ok, fail, info} (enforced by ValidatingClient, client.clj:64-109).
A client marked ``reusable`` survives process crashes without being
reopened (client.clj:29-34, interpreter.clj:33-67).
"""

from __future__ import annotations

import copy
from typing import Any, Mapping


class Client:
    """Base client. Subclasses override what they need; defaults are no-ops
    that return self/op unchanged."""

    #: If True, the interpreter reuses this client across process crashes
    #: instead of close!/open! cycling it (client.clj:29-34).
    reusable = False

    def open(self, test: Mapping, node: str) -> "Client":
        """Return a connected copy bound to node. Must not mutate self.

        Overrides should construct the copy via ``type(self)(...)``, never
        a hard-coded class: the interpreter reopens clients on process
        crashes, and a hard-coded class silently discards subclass
        behavior (wrappers, keyed variants) at every reopen.
        """
        return copy.copy(self)

    def setup(self, test: Mapping) -> None:
        pass

    def invoke(self, test: Mapping, op: Mapping) -> Mapping:
        raise NotImplementedError

    def teardown(self, test: Mapping) -> None:
        pass

    def close(self, test: Mapping) -> None:
        pass


class NoopClient(Client):
    """Does nothing; every op succeeds (client.clj:46-62)."""

    reusable = True

    def invoke(self, test, op):
        return {**op, "type": "ok"}


def noop() -> Client:
    return NoopClient()


class ValidatingClient(Client):
    """Wraps a client, enforcing the completion invariants
    (client.clj:64-109): completion has the same :f and :process as the
    invocation and a legal completion :type."""

    def __init__(self, client: Client):
        self.client = client

    @property
    def reusable(self):  # type: ignore[override]
        return self.client.reusable

    def open(self, test, node):
        return ValidatingClient(self.client.open(test, node))

    def setup(self, test):
        self.client.setup(test)

    def invoke(self, test, op):
        comp = self.client.invoke(test, op)
        problems = []
        if not isinstance(comp, Mapping):
            problems.append(f"completion should be a map, was {comp!r}")
        else:
            if comp.get("type") not in ("ok", "fail", "info"):
                problems.append(f"bad completion :type {comp.get('type')!r}")
            if comp.get("f") != op.get("f"):
                problems.append(
                    f"completion :f {comp.get('f')!r} != invocation :f {op.get('f')!r}"
                )
            if comp.get("process") != op.get("process"):
                problems.append(
                    f"completion :process {comp.get('process')!r} != "
                    f"invocation :process {op.get('process')!r}"
                )
        if problems:
            raise ValueError(f"invalid completion {comp!r} for {op!r}: {problems}")
        return comp

    def teardown(self, test):
        self.client.teardown(test)

    def close(self, test):
        self.client.close(test)


def validate(client: Client) -> Client:
    return ValidatingClient(client)


def closable(c: Any) -> bool:
    return isinstance(c, Client)
