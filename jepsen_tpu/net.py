"""Network manipulation: partitions, latency, loss.

Mirrors ``jepsen.net`` (reference: jepsen/src/jepsen/net.clj): the ``Net``
protocol — drop!/heal!/slow!/flaky!/fast! — plus the iptables
implementation with the batched ``drop_all`` fast path for whole grudge
maps (net.clj:58-111), and tc/netem for delay and loss (net.clj:71-89).

All methods act over the control layer; ``NoopNet`` is the dummy used with
the dummy remote in self-tests.
"""

from __future__ import annotations

from typing import Mapping

from jepsen_tpu import control


class Net:
    """Protocol (net.clj:15-26)."""

    def drop(self, test, src, dest):
        """Cut traffic from src to dest (one direction)."""
        raise NotImplementedError

    def drop_all(self, test, grudge: Mapping):
        """Apply a whole grudge map {node: set-of-nodes-to-refuse} in one
        batched pass (net.clj:88-111 PartitionAll)."""
        raise NotImplementedError

    def heal(self, test):
        raise NotImplementedError

    def slow(self, test, mean_ms: float = 50.0, variance_ms: float = 10.0):
        raise NotImplementedError

    def flaky(self, test):
        raise NotImplementedError

    def fast(self, test):
        raise NotImplementedError


class NoopNet(Net):
    """Records calls; does nothing. For dummy-remote self-tests."""

    def __init__(self):
        self.log: list = []
        self.grudge: Mapping | None = None

    def drop(self, test, src, dest):
        self.log.append(("drop", src, dest))

    def drop_all(self, test, grudge):
        self.log.append(("drop-all", grudge))
        self.grudge = grudge

    def heal(self, test):
        self.log.append(("heal",))
        self.grudge = None

    def slow(self, test, mean_ms=50.0, variance_ms=10.0):
        self.log.append(("slow", mean_ms))

    def flaky(self, test):
        self.log.append(("flaky",))

    def fast(self, test):
        self.log.append(("fast",))


def _ip_of(session: control.Session, node: str, cache: dict) -> str:
    """Resolve a node name to an IP on the node (control/net.clj:19-40,
    memoized)."""
    if node not in cache:
        out = session.exec("getent", "ahosts", node).splitlines()
        cache[node] = out[0].split()[0] if out else node
    return cache[node]


class IptablesNet(Net):
    """iptables/tc implementation (net.clj:58-111)."""

    def __init__(self):
        self._ip_cache: dict = {}

    def _sessions(self, test):
        return control.sessions(test)

    def drop(self, test, src, dest):
        s = self._sessions(test)[dest]
        with s.su():
            ip = _ip_of(s, src, self._ip_cache)
            s.exec("iptables", "-A", "INPUT", "-s", ip, "-j", "DROP", "-w")

    def drop_all(self, test, grudge):
        def apply_one(test_, node, s):
            cut = grudge.get(node) or ()
            if not cut:
                return
            with s.su():
                ips = [_ip_of(s, other, self._ip_cache) for other in sorted(cut)]
                # One batched rule per node (net.clj:88-111).
                s.exec(
                    "iptables", "-A", "INPUT", "-s", ",".join(ips), "-j", "DROP", "-w"
                )

        control.on_nodes(test, apply_one, nodes=list(grudge))

    def heal(self, test):
        def heal_one(test_, node, s):
            with s.su():
                s.exec("iptables", "-F", "-w")
                s.exec("iptables", "-X", "-w")

        control.on_nodes(test, heal_one)

    def slow(self, test, mean_ms=50.0, variance_ms=10.0):
        def slow_one(test_, node, s):
            with s.su():
                s.exec(
                    "tc", "qdisc", "add", "dev", "eth0", "root", "netem",
                    "delay", f"{mean_ms}ms", f"{variance_ms}ms", "distribution", "normal",
                )

        control.on_nodes(test, slow_one)

    def flaky(self, test):
        def flaky_one(test_, node, s):
            with s.su():
                s.exec(
                    "tc", "qdisc", "add", "dev", "eth0", "root", "netem",
                    "loss", "20%", "75%",
                )

        control.on_nodes(test, flaky_one)

    def fast(self, test):
        def fast_one(test_, node, s):
            with s.su():
                s.exec_result("tc", "qdisc", "del", "dev", "eth0", "root")

        control.on_nodes(test, fast_one)


def iptables() -> Net:
    return IptablesNet()


def noop() -> Net:
    return NoopNet()
