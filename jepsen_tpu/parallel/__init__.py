"""Device-mesh parallelism for the checker phase.

The reference has no NCCL/MPI analogue — its scaling axes are worker
concurrency and keyspace sharding (SURVEY.md §2.5).  In the rebuild those
become jax.sharding axes:

  * ``histories`` (data parallel): independent per-key histories — the
    reference's ``independent/concurrent-generator`` keyspace shards
    (independent.clj:103-238) — are packed to common shapes, stacked, and
    checked by one vmapped kernel sharded across the mesh (BASELINE
    config 4: 1024 recorded histories across a v5e-8 slice).

  * ``frontier`` (context parallel): ONE history's configuration frontier
    sharded across devices with hash-routed all_to_all exchanges and psum
    verdict merges (jepsen_tpu.parallel.sharded).

Collectives ride ICI via XLA's partitioner; there is nothing NCCL-like to
port (SURVEY.md §5 'distributed communication backend').
"""

from jepsen_tpu.parallel.batch import batch_analysis, make_mesh
from jepsen_tpu.parallel.sharded import sharded_analysis

__all__ = ["batch_analysis", "make_mesh", "sharded_analysis"]
