"""Frontier-sharded linearizability search: one history across many chips.

The batched path (jepsen_tpu.parallel.batch) scales across *histories*;
this module scales across the *configuration frontier of a single
history* — the rebuild's context-parallel axis (SURVEY.md §2.5 item 5,
§5 'long-context': the WGL frontier is the sequence dimension).  Each
device owns F/D frontier rows.  Per closure round:

  1. local expansion (same move algebra as jepsen_tpu.ops.wgl);
  2. hash-routed exchange: every candidate row is routed to device
     ``hash(state, fok) % D`` via ``lax.all_to_all`` over the mesh axis,
     so equal configurations always land on the same device;
  3. local sort-based dedup/domination/truncation (jepsen_tpu.ops.hashing)
     — globally exact because of the routing invariant;
  4. ``lax.psum`` of content fingerprints/overflow for a global fixpoint
     and loss decision (uniform across devices, so the while_loop agrees).

Barrier filtering is local; survival is decided by a psum'd global alive
count.  Soundness matches the single-device kernel: True is always a
constructive witness; False only when no loss occurred anywhere.

Reference seam: jepsen drives knossos thread-parallel inside one JVM
(jepsen/src/jepsen/checker.clj:185-216); the rebuild's equivalent of
"more cores" is more chips on the ICI mesh.
"""

from __future__ import annotations

import functools
import time
from typing import Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from jepsen_tpu import _platform, obs
from jepsen_tpu import models as m
from jepsen_tpu.ops import wgl
from jepsen_tpu.ops import wide_kernel
from jepsen_tpu.ops import spill as spill_mod
from jepsen_tpu.ops.hashing import frontier_update, hash_rows

I32 = jnp.int32
U32 = jnp.uint32


def _route(axis: str, D: int, C: int, state, fok, fcr, alive, cost):
    """Exchange candidate rows so each lands on device hash % D.

    Builds D fixed-capacity buckets (top-C per target by cost), swaps them
    with ``all_to_all``, and returns the received [D*C] rows plus a local
    overflow flag (some bucket spilled)."""
    n = state.shape[0]
    w = fok.shape[1]
    g = fcr.shape[1]
    class_cols = [state] + [fok[:, k] for k in range(w)]
    h = hash_rows(class_cols, 0x5EED_0D15)
    target = (h % U32(D)).astype(I32)
    dead = (~alive).astype(U32)
    iota = jnp.arange(n, dtype=I32)
    sd, st_t, sc, sidx = jax.lax.sort(
        (dead, target.astype(U32), cost.astype(U32), iota), num_keys=3
    )
    # counts/starts per target among alive rows, in sorted coordinates
    onehot = (st_t[:, None] == jnp.arange(D, dtype=U32)[None, :]) & (sd == 0)[:, None]
    counts = onehot.sum(axis=0).astype(I32)
    starts = jnp.concatenate([jnp.zeros(1, I32), jnp.cumsum(counts)[:-1]])
    pos = jnp.arange(n, dtype=I32)
    rank = pos - starts[st_t.astype(I32) % D]
    keep = (sd == 0) & (rank >= 0) & (rank < C)
    spill = ((counts > C).any()) | False
    flat = jnp.where(keep, st_t.astype(I32) * C + rank, D * C)  # D*C = drop slot
    rows_state = state[sidx]
    rows_fok = fok[sidx]
    rows_fcr = fcr[sidx]
    rows_cost = cost[sidx]

    def scatter(col, fill):
        out = jnp.full((D * C + 1,) + col.shape[1:], fill, col.dtype)
        return out.at[flat].set(col)[: D * C]

    b_state = scatter(rows_state, 0).reshape(D, C)
    b_fok = scatter(rows_fok, U32(0)).reshape(D, C, w)
    b_fcr = scatter(rows_fcr, 0).reshape(D, C, g)
    b_alive = jnp.zeros(D * C + 1, bool).at[flat].set(keep)[: D * C].reshape(D, C)
    b_cost = scatter(rows_cost, 0).reshape(D, C)

    x = lambda a: jax.lax.all_to_all(a, axis, split_axis=0, concat_axis=0, tiled=True)
    r_state = x(b_state).reshape(D * C)
    r_fok = x(b_fok).reshape(D * C, w)
    r_fcr = x(b_fcr).reshape(D * C, g)
    r_alive = x(b_alive).reshape(D * C)
    r_cost = x(b_cost).reshape(D * C)
    return r_state, r_fok, r_fcr, r_alive, r_cost, spill


def _run_core_sharded(
    axis,
    D,
    step,
    Fl,
    R,
    P_,
    G,
    W,
    init_state,
    bar_active,
    bar_f,
    bar_v1,
    bar_v2,
    bar_slot,
    mov_f,
    mov_v1,
    mov_v2,
    mov_open,
    grp_f,
    grp_v1,
    grp_v2,
    grp_open,
    slot_lane,
    slot_onehot,
):
    """Per-device body (under shard_map): scan the sharded frontier over
    all barriers.  Fl = per-device frontier capacity; bucket capacity
    C = 2*Fl bounds the exchange."""
    C = 2 * Fl
    eye_g = jnp.eye(G, dtype=I32)
    slot_mask = slot_onehot.sum(axis=1)

    def expand_round(val):
        state, fok, fcr, alive, r, changed, lossy, fp, xs = val
        (xmov_f, xmov_v1, xmov_v2, xmov_open, xgrp_open) = xs
        cat_state, cat_fok, cat_fcr, cat_alive, cost = wgl.expand_candidates(
            step, eye_g, slot_lane, slot_mask, slot_onehot,
            state, fok, fcr, alive,
            xmov_f, xmov_v1, xmov_v2, xmov_open,
            grp_f, grp_v1, grp_v2, xgrp_open,
        )
        # Route every candidate (parents included) to its hash-owner.
        r_state, r_fok, r_fcr, r_alive, r_cost, spill = _route(
            axis, D, C, cat_state, cat_fok, cat_fcr, cat_alive, cost
        )
        state2, fok2, fcr2, alive2, ovf, fp_local = frontier_update(
            r_state, r_fok, r_fcr, r_alive, r_cost, Fl
        )
        fp2 = jax.lax.psum(fp_local, axis)
        lossy2 = jax.lax.psum((ovf | spill).astype(I32), axis) > 0
        changed2 = ~(fp2 == fp).all()
        return (state2, fok2, fcr2, alive2, r + 1, changed2, lossy | lossy2, fp2, xs)

    def round_cond(val):
        _s, _fo, _fc, _a, r, changed, _l, _fp, _xs = val
        return (r < R) & changed

    def barrier(carry, xs):
        state, fok, fcr, alive, failed_at, lossy, peak = carry
        b_idx, active, xbar_slot, xmov_f, xmov_v1, xmov_v2, xmov_open, xgrp_open = xs
        done = (failed_at >= 0) | ~active

        def process(_):
            xs_inner = (xmov_f, xmov_v1, xmov_v2, xmov_open, xgrp_open)
            fp0 = jnp.full(3, jnp.uint32(0xFFFFFFFF))
            s2, fo2, fc2, a2, _r, changed, lossy2, _fp, _ = jax.lax.while_loop(
                round_cond,
                expand_round,
                (state, fok, fcr, alive, jnp.int32(0), jnp.bool_(True), lossy, fp0, xs_inner),
            )
            lossy3 = lossy2 | changed
            lane = xbar_slot // 32
            bitmask = (U32(1) << (xbar_slot % 32).astype(U32))
            lane_vals = jnp.take(fo2, lane[None], axis=1)[:, 0]
            a3 = a2 & ((lane_vals & bitmask) != 0)
            clear = jnp.where(jnp.arange(W) == lane, bitmask, U32(0))
            fo3 = fo2 & ~clear[None, :]
            n_alive = jax.lax.psum(a3.sum(), axis)
            dead = n_alive == 0
            failed2 = jnp.where(dead, b_idx, failed_at)
            peak2 = jnp.maximum(peak, n_alive)
            return (s2, fo3, fc2, a3, failed2, lossy3, peak2)

        def skip(_):
            return (state, fok, fcr, alive, failed_at, lossy, peak)

        return jax.lax.cond(done, skip, process, None), None

    state0 = jnp.full((Fl,), init_state, I32)
    fok0 = jnp.zeros((Fl, W), U32)
    fcr0 = jnp.zeros((Fl, G), I32)
    # Only one device starts with the (single) initial configuration; the
    # first exchange hash-routes it to its owner.
    me = jax.lax.axis_index(axis)
    alive0 = jnp.zeros((Fl,), bool).at[0].set(me == 0)
    carry0 = (state0, fok0, fcr0, alive0, jnp.int32(-1), jnp.bool_(False), jnp.int32(1))
    xs = (
        jnp.arange(bar_f.shape[0], dtype=I32),
        bar_active,
        bar_slot,
        mov_f,
        mov_v1,
        mov_v2,
        mov_open,
        grp_open,
    )
    (state, fok, fcr, alive, failed_at, lossy, peak), _ = jax.lax.scan(barrier, carry0, xs)
    any_alive = jax.lax.psum(alive.any().astype(I32), axis) > 0
    return any_alive, failed_at, lossy, peak


#: (mesh id, step, Fl, R, P, G, W) -> compiled sharded runner.
_SHARDED_RUNNERS: dict = {}

#: (runner, mesh, replicated, n_out) -> lane-sharded compiled wrapper.
_LANE_SHARDED: dict = {}


def lane_shard(fn, mesh: Mesh, *, n_args: int, replicated: Sequence[int] = (),
               n_out: int = 1):
    """Lane-parallel placement for a batched (vmapped) kernel runner:
    shard every argument's LEADING batch axis across ``mesh``'s one
    axis (arguments listed in ``replicated`` broadcast whole), run
    ``fn`` on each device's lane shard, and concatenate the ``n_out``
    outputs back on that axis.  Built on the ``_platform.shard_map``
    shim — the same seam every frontier-sharded kernel in this module
    compiles through — so the serving layer's launch placement and the
    single-history sharded checker ride one jax-API compatibility
    point.  The caller pads the batch axis to a mesh multiple
    (``parallel.batch.padded_batch`` with a mesh does)."""
    key = (fn, mesh, tuple(replicated), int(n_args), int(n_out))
    if key not in _LANE_SHARDED:
        axis = mesh.axis_names[0]
        rep = set(replicated)
        in_specs = tuple(
            P() if i in rep else P(axis) for i in range(n_args)
        )
        out_specs = (
            tuple(P(axis) for _ in range(n_out)) if n_out > 1 else P(axis)
        )
        compiled = jax.jit(_platform.shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        ))
        from jepsen_tpu.parallel.batch import mesh_device_ids

        dev_ids = mesh_device_ids(mesh)

        def wrapper(*args, _compiled=compiled, _devs=dev_ids):
            # Device-attributed placement telemetry: every lane-sharded
            # dispatch stamps its member devices so the per-device
            # timeline (obs.critpath.device_timeline) and the Perfetto
            # device lanes can attribute the work.  One module-attr
            # read when telemetry is off.  The observed path BLOCKS on
            # the outputs: jax dispatch is async, and a span that
            # closed at dispatch would record microseconds for a
            # seconds-long launch — busy_frac ≈ 0 on a real chip, the
            # exact number the timeline exists to get right.
            if not obs.observing():
                return _compiled(*args)
            t0 = time.perf_counter()
            out = jax.block_until_ready(_compiled(*args))
            obs.span_event("sharded.lane_launch", time.perf_counter() - t0,
                           devices=_devs)
            return out

        _LANE_SHARDED[key] = wrapper
    return _LANE_SHARDED[key]


def forget_mesh(mesh: Mesh) -> int:
    """Evict every cached runner compiled for ``mesh`` (device-loss
    re-placement: a shrunk-away mesh's compiled wrappers pin references
    to the lost devices and could never launch again anyway) — the
    lane-sharded wrappers, the sharded-frontier runners, AND the
    mesh-kernel runners (engine + eager update).  Returns the number of
    cache entries dropped."""
    n = 0
    for cache in (_LANE_SHARDED, _SHARDED_RUNNERS, _MESH_RUNNERS,
                  _MESH_UPDATE_RUNNERS):
        dead = [k for k in cache if any(v is mesh for v in k)]
        for k in dead:
            del cache[k]
        n += len(dead)
    return n


def _sharded_runner(mesh: Mesh, step, Fl: int, R: int, P_: int, G: int, W: int):
    axis = mesh.axis_names[0]
    D = mesh.devices.size
    key = (mesh, step, Fl, R, P_, G, W)
    if key not in _SHARDED_RUNNERS:
        core = functools.partial(_run_core_sharded, axis, D, step, Fl, R, P_, G, W)
        fn = _platform.shard_map(
            core,
            mesh=mesh,
            in_specs=(P(),) * 16,
            out_specs=(P(), P(), P(), P()),
            check_vma=False,
        )
        _SHARDED_RUNNERS[key] = jax.jit(fn)
    return _SHARDED_RUNNERS[key]


def sharded_analysis(
    model: m.Model,
    history: Sequence[dict],
    mesh: Mesh,
    capacity: int | Sequence[int] = (1024, 8192),
    rounds: int = 8,
    max_groups: int = 64,
    max_procs: int = 128,
) -> dict:
    """Decide linearizability of ONE history with the frontier sharded
    across ``mesh``.  ``capacity`` is the *total* frontier size (split
    evenly over devices); a sequence widens iteratively like
    jepsen_tpu.ops.wgl.analysis."""
    D = mesh.devices.size
    try:
        packed = wgl.pack(model, history)
    except wgl.NotTensorizable as e:
        return {"valid?": "unknown", "cause": f"not tensorizable: {e}"}
    if packed["B"] == 0:
        return {"valid?": True}
    if packed["G"] > max_groups:
        return {"valid?": "unknown", "cause": f"{packed['G']} crashed-op groups exceeds {max_groups}"}
    if packed["P"] > max_procs:
        return {"valid?": "unknown", "cause": f"{packed['P']} process slots exceeds {max_procs}"}
    packed = wgl.pad_packed(packed)

    capacities = [capacity] if isinstance(capacity, int) else list(capacity)
    result = None
    from jepsen_tpu.parallel.batch import mesh_device_ids

    dev_ids = mesh_device_ids(mesh)
    for cap in capacities:
        Fl = max(8, (int(cap) + D - 1) // D)
        runner = _sharded_runner(
            mesh, packed["step"], Fl, int(rounds), packed["P"], packed["G"], packed["W"]
        )
        with obs.span("sharded.launch", devices=dev_ids, capacity=Fl * D):
            valid, failed_at, lossy, peak = runner(
                packed["init_state"],
                packed["bar_active"],
                *packed["bar"],
                *packed["mov"],
                *packed["grp"],
                packed["grp_open"],
                jnp.asarray(packed["slot_lane"]),
                jnp.asarray(packed["slot_onehot"]),
            )
            # block INSIDE the span: dispatch is async, and the span
            # must cover device execution, not the enqueue
            jax.block_until_ready((valid, failed_at, lossy, peak))
        valid = bool(valid)
        failed_at = int(failed_at)
        lossy = bool(lossy)
        stats = {
            "frontier-peak": int(peak),
            "capacity": Fl * D,
            "devices": D,
            "lossy?": lossy,
        }
        if failed_at < 0 and valid:
            return {"valid?": True, "kernel": stats}
        op = history[int(packed["bar_opid"][failed_at])] if failed_at >= 0 else None
        if not lossy:
            return {"valid?": False, "op": op, "kernel": stats}
        result = {
            "valid?": "unknown",
            "cause": "frontier capacity or closure rounds exhausted",
            "op": op,
            "kernel": stats,
        }
    return result


# ---------------------------------------------------------------------------
# Mesh-kernel engine: the fused Pallas wide stage spanning the whole mesh
# ---------------------------------------------------------------------------


def _run_core_mesh(
    axis,
    D,
    step,
    Fl,
    R,
    P_,
    G,
    W,
    window,
    interp,
    init_state,
    bar_active,
    bar_f,
    bar_v1,
    bar_v2,
    bar_slot,
    mov_f,
    mov_v1,
    mov_v2,
    mov_open,
    grp_f,
    grp_v1,
    grp_v2,
    grp_open,
    slot_lane,
    slot_onehot,
):
    """Per-device body (under shard_map) of the MESH-KERNEL engine:
    ``_run_core_sharded``'s scan skeleton with steps 2–4 (all_to_all
    exchange + sort-based local update + fingerprint fixpoint) replaced
    by ONE ``wide_kernel.mesh_frontier_update`` — remote-DMA routing and
    the fused dedup/domination/compaction kernel, with the fast engine's
    child-no-growth fixpoint (psum'd, so the while_loop agrees across
    shards).  Fl = per-device frontier capacity."""
    eye_g = jnp.eye(G, dtype=I32)
    slot_mask = slot_onehot.sum(axis=1)

    def expand_round(val):
        state, fok, fcr, alive, r, changed, lossy, xs = val
        (xmov_f, xmov_v1, xmov_v2, xmov_open, xgrp_open) = xs
        cat_state, cat_fok, cat_fcr, cat_alive, cost = wgl.expand_candidates(
            step, eye_g, slot_lane, slot_mask, slot_onehot,
            state, fok, fcr, alive,
            xmov_f, xmov_v1, xmov_v2, xmov_open,
            grp_f, grp_v1, grp_v2, xgrp_open,
        )
        state2, fok2, fcr2, alive2, ovf, _fp, child = (
            wide_kernel.mesh_frontier_update(
                axis, D, cat_state, cat_fok, cat_fcr, cat_alive, cost, Fl,
                window=window, n_parents=Fl,
                max_count=xmov_f.shape[-1] + 1, interpret=interp,
            )
        )
        # ovf is already psum'd global; growth must be too, or shards
        # would disagree on the while_loop predicate.
        grew = jax.lax.psum((alive2 & child).any().astype(I32), axis) > 0
        return (state2, fok2, fcr2, alive2, r + 1, grew, lossy | ovf, xs)

    def round_cond(val):
        _s, _fo, _fc, _a, r, changed, _l, _xs = val
        return (r < R) & changed

    def barrier(carry, xs):
        state, fok, fcr, alive, failed_at, lossy, peak = carry
        b_idx, active, xbar_slot, xmov_f, xmov_v1, xmov_v2, xmov_open, xgrp_open = xs
        done = (failed_at >= 0) | ~active

        def process(_):
            xs_inner = (xmov_f, xmov_v1, xmov_v2, xmov_open, xgrp_open)
            s2, fo2, fc2, a2, _r, changed, lossy2, _ = jax.lax.while_loop(
                round_cond,
                expand_round,
                (state, fok, fcr, alive, jnp.int32(0), jnp.bool_(True), lossy, xs_inner),
            )
            lossy3 = lossy2 | changed
            lane = xbar_slot // 32
            bitmask = (U32(1) << (xbar_slot % 32).astype(U32))
            lane_vals = jnp.take(fo2, lane[None], axis=1)[:, 0]
            a3 = a2 & ((lane_vals & bitmask) != 0)
            clear = jnp.where(jnp.arange(W) == lane, bitmask, U32(0))
            fo3 = fo2 & ~clear[None, :]
            n_alive = jax.lax.psum(a3.sum(), axis)
            dead = n_alive == 0
            failed2 = jnp.where(dead, b_idx, failed_at)
            peak2 = jnp.maximum(peak, n_alive)
            return (s2, fo3, fc2, a3, failed2, lossy3, peak2)

        def skip(_):
            return (state, fok, fcr, alive, failed_at, lossy, peak)

        return jax.lax.cond(done, skip, process, None), None

    state0 = jnp.full((Fl,), init_state, I32)
    fok0 = jnp.zeros((Fl, W), U32)
    fcr0 = jnp.zeros((Fl, G), I32)
    me = jax.lax.axis_index(axis)
    alive0 = jnp.zeros((Fl,), bool).at[0].set(me == 0)
    carry0 = (state0, fok0, fcr0, alive0, jnp.int32(-1), jnp.bool_(False), jnp.int32(1))
    xs = (
        jnp.arange(bar_f.shape[0], dtype=I32),
        bar_active,
        bar_slot,
        mov_f,
        mov_v1,
        mov_v2,
        mov_open,
        grp_open,
    )
    (state, fok, fcr, alive, failed_at, lossy, peak), _ = jax.lax.scan(barrier, carry0, xs)
    any_alive = jax.lax.psum(alive.any().astype(I32), axis) > 0
    return any_alive, failed_at, lossy, peak


#: (mesh, step, Fl, R, P, G, W, window, interpret) -> mesh-kernel runner.
_MESH_RUNNERS: dict = {}

#: (mesh, n, w, g, capacity, window, max_count, interpret, fcr dtype)
#: -> eager global-table mesh update (tests/probes).
_MESH_UPDATE_RUNNERS: dict = {}


def _mesh_runner(mesh: Mesh, step, Fl: int, R: int, P_: int, G: int, W: int,
                 window: int, interp: bool):
    axis = mesh.axis_names[0]
    D = mesh.devices.size
    key = (mesh, step, Fl, R, P_, G, W, window, interp)
    if key not in _MESH_RUNNERS:
        core = functools.partial(
            _run_core_mesh, axis, D, step, Fl, R, P_, G, W, window, interp
        )
        fn = _platform.shard_map(
            core,
            mesh=mesh,
            in_specs=(P(),) * 16,
            out_specs=(P(), P(), P(), P()),
            check_vma=False,
        )
        _MESH_RUNNERS[key] = jax.jit(fn)
    return _MESH_RUNNERS[key]


def mesh_update(mesh: Mesh, state, fok, fcr, alive, cost, capacity: int, *,
                window: int = 4, n_parents: int | None = None,
                max_count: int | None = None,
                interpret: bool | None = None):
    """Eager global-table entry to the mesh-spanning fused stage (tests,
    probes, differential suites): shard the [n] candidate table row-wise
    across ``mesh``, run ``wide_kernel.mesh_frontier_update`` per shard,
    and return the concatenated global outputs (state', fok', fcr',
    alive', overflowed, fp, child).  ``capacity`` is GLOBAL (split
    evenly).  Alive rows land in their class-hash owner's block, so
    POSITIONS are not comparable to the single-device kernel; the
    surviving content set, the child bits, ``overflowed`` and the
    order-insensitive ``fp`` are — that is the cross-path differential
    contract."""
    D = int(mesh.devices.size)
    axis = mesh.axis_names[0]
    n = int(state.shape[0])
    w, g = int(fok.shape[1]), int(fcr.shape[1])
    cap_d = int(capacity) // D
    if interpret is None:
        interpret = wide_kernel.interpret_default()
    mc = None if max_count is None else int(max_count)
    key = (mesh, n, w, g, int(capacity), int(window), mc, bool(interpret),
           str(jnp.asarray(fcr).dtype))
    if key not in _MESH_UPDATE_RUNNERS:

        def body(st, fo, fc, al, ch):
            return wide_kernel.mesh_frontier_update(
                axis, D, st, fo, fc, al, jnp.zeros_like(st), cap_d,
                window=int(window), max_count=mc,
                interpret=bool(interpret), child=ch != 0,
            )

        fn = _platform.shard_map(
            body,
            mesh=mesh,
            in_specs=(P(axis),) * 5,
            out_specs=(P(axis),) * 4 + (P(), P()) + (P(axis),),
            check_vma=False,
        )
        _MESH_UPDATE_RUNNERS[key] = jax.jit(fn)
    if n_parents is not None:
        child = jnp.arange(n, dtype=I32) >= np.int32(int(n_parents))
    else:
        child = jnp.zeros((n,), bool)
    return _MESH_UPDATE_RUNNERS[key](
        jnp.asarray(state), jnp.asarray(fok), jnp.asarray(fcr),
        jnp.asarray(alive), child.astype(I32),
    )


def mesh_round_probe(mesh: Mesh, capacity: int, P_: int, G: int, W: int = 1,
                     rounds: int = 3, seed: int = 0, emit: bool = True) -> dict:
    """Measure per-round mesh-stage time at a rung's GLOBAL candidate
    shape — the mesh counterpart of ``hashing.dedup_round_probe``, one
    ``dedup.mesh_round`` span (attrs: mesh_devices, candidates,
    capacity, rounds, per_round_us, interpret — interpret-mode CPU
    probes never pass for chip measurements).  Returns
    ``{"mesh": seconds per round, "occupancy": mesh_occupancy dict}``;
    an infeasible shape bumps the ``dedup.mesh_fallback`` counter and
    returns without timing (the engines would have routed it away too)."""
    from jepsen_tpu.ops import hashing as hx

    D = int(mesh.devices.size)
    occ = wide_kernel.mesh_occupancy(
        int(capacity), P_, G, W=W, max_count=P_ + 1, devices=D
    )
    if not occ["feasible"]:
        obs.counter("dedup.mesh_fallback", capacity=int(capacity),
                    mesh_devices=D)
        return {"mesh": None, "occupancy": occ}
    state, fok, fcr, alive = hx.probe_candidates(int(capacity), P_, G, W, seed)
    n = int(state.shape[0])
    args = (jnp.asarray(state), jnp.asarray(fok), jnp.asarray(fcr),
            jnp.asarray(alive), jnp.zeros((n,), I32))
    out = mesh_update(mesh, *args, int(capacity), window=4,
                      n_parents=int(capacity), max_count=P_ + 1)
    jax.block_until_ready(out)  # compile outside the timed window
    t0 = time.perf_counter()
    for _ in range(max(1, int(rounds))):
        out = mesh_update(mesh, *args, int(capacity), window=4,
                          n_parents=int(capacity), max_count=P_ + 1)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / max(1, int(rounds))
    if emit:
        obs.span_event(
            "dedup.mesh_round", dt, backend="pallas", mesh_devices=D,
            candidates=n, capacity=int(capacity), rounds=int(rounds),
            per_round_us=round(dt * 1e6, 1), interpret=occ["interpret"],
        )
    return {"mesh": dt, "occupancy": occ}


def _mesh_rung_geometry(cap: int, D: int, packed: dict) -> tuple[int, int, bool]:
    """(Fl, max_count, feasible) for one ladder rung of ``cap`` total
    rows on a ``D``-device mesh: Fl is the per-device frontier slice,
    rounded up to the fused kernel's 64-row granule."""
    Fl = max(8, (int(cap) + D - 1) // D)
    Fl = ((Fl + 63) // 64) * 64
    max_count = int(packed["mov"][0].shape[-1]) + 1
    n_loc = Fl * (1 + int(packed["P"]) + int(packed["G"]))
    feasible = wide_kernel.mesh_feasible(
        D * n_loc, D * Fl, max_count, D,
        w=int(packed["W"]), g=int(packed["G"]),
    )
    return Fl, max_count, feasible


def mesh_kernel_analysis(
    model: m.Model,
    history: Sequence[dict],
    mesh: Mesh,
    capacity: int | Sequence[int] = (8192,),
    rounds: int = 8,
    window: int = 4,
    max_groups: int = 64,
    max_procs: int = 128,
) -> dict:
    """Decide ONE history with the mesh-spanning fused Pallas wide stage
    — the whole frontier update (hash routing over remote DMA + fused
    dedup/domination/compaction) as one kernel program across every
    device of ``mesh``.  ``capacity`` is the TOTAL frontier size per
    rung (split evenly; the per-device VMEM model is what makes rungs
    beyond the single-chip ceiling feasible here).

    Fast-path semantics: kills are hash-decided, so a False verdict is
    marked ``provisional?`` exactly like ``wgl.analysis(fast=True)`` —
    callers confirm refutations before reporting them.  True is a
    constructive witness (always sound); an exhausted ladder returns an
    ``unknown`` whose undecidability report cites the MESH capacity
    (devices × per-device rows).

    Static fallback: a mesh with <2 devices or an infeasible
    geometry/VMEM shape routes to the single-device pallas ladder
    (``wgl.analysis`` with ``dedup_backend="pallas"``, which itself
    falls back to bucket/sort) — the device-loss path after
    ``Placement.shrink_to`` lands here with verdicts unchanged."""
    D = int(mesh.devices.size)
    try:
        packed = wgl.pack(model, history)
    except wgl.NotTensorizable as e:
        return {"valid?": "unknown", "cause": f"not tensorizable: {e}"}
    if packed["B"] == 0:
        return {"valid?": True}
    if packed["G"] > max_groups:
        return {"valid?": "unknown", "cause": f"{packed['G']} crashed-op groups exceeds {max_groups}"}
    if packed["P"] > max_procs:
        return {"valid?": "unknown", "cause": f"{packed['P']} process slots exceeds {max_procs}"}
    packed = wgl.pad_packed(packed)

    capacities = [capacity] if isinstance(capacity, int) else list(capacity)
    interp = wide_kernel.interpret_default()
    infeasible = D < 2 or any(
        not _mesh_rung_geometry(cap, D, packed)[2] for cap in capacities
    )
    if infeasible:
        obs.counter("dedup.mesh_fallback", mesh_devices=D,
                    capacity=int(max(capacities)))
        return wgl.analysis(
            model, history, capacity=tuple(int(c) for c in capacities),
            rounds=int(rounds), max_groups=max_groups, max_procs=max_procs,
            fast=True, dedup_backend="pallas",
        )

    from jepsen_tpu.parallel.batch import mesh_device_ids

    dev_ids = mesh_device_ids(mesh)
    result = None
    for cap in capacities:
        Fl, _mc, _ok = _mesh_rung_geometry(cap, D, packed)
        runner = _mesh_runner(
            mesh, packed["step"], Fl, int(rounds), packed["P"], packed["G"],
            packed["W"], int(window), bool(interp),
        )
        with obs.span("sharded.mesh_launch", devices=dev_ids, mesh_devices=D,
                      capacity=Fl * D, per_device_capacity=Fl,
                      interpret=bool(interp)):
            valid, failed_at, lossy, peak = runner(
                packed["init_state"],
                packed["bar_active"],
                *packed["bar"],
                *packed["mov"],
                *packed["grp"],
                packed["grp_open"],
                jnp.asarray(packed["slot_lane"]),
                jnp.asarray(packed["slot_onehot"]),
            )
            jax.block_until_ready((valid, failed_at, lossy, peak))
        valid = bool(valid)
        failed_at = int(failed_at)
        lossy = bool(lossy)
        stats = {
            "frontier-peak": int(peak),
            "capacity": Fl * D,
            "per-device-capacity": Fl,
            "devices": D,
            "mesh_devices": D,
            "lossy?": lossy,
            "interpret": bool(interp),
            "failed-at": failed_at,
        }
        if failed_at < 0 and valid:
            return {"valid?": True, "kernel": stats}
        op = history[int(packed["bar_opid"][failed_at])] if failed_at >= 0 else None
        if not lossy:
            # hash-decided kills: provisional, same contract as the
            # single-device fast path (callers confirm before reporting)
            return {"valid?": False, "op": op, "kernel": stats,
                    "provisional?": True}
        result = {
            "valid?": "unknown",
            "op": op,
            "kernel": stats,
        }
    rep = spill_mod.undecidability_report(
        capacity=int(max(capacities)),
        frontier_rows=stats["capacity"],
        peak_frontier=stats["frontier-peak"],
        barrier=failed_at if failed_at >= 0 else int(packed["B"]),
        barriers_total=int(packed["B"]),
        mesh_devices=D,
        per_device_rows=stats["per-device-capacity"],
        reason="mesh-capacity",
    )
    result["undecidability"] = rep
    result["cause"] = spill_mod.undecidable_cause(rep)
    return result
