"""Frontier-sharded linearizability search: one history across many chips.

The batched path (jepsen_tpu.parallel.batch) scales across *histories*;
this module scales across the *configuration frontier of a single
history* — the rebuild's context-parallel axis (SURVEY.md §2.5 item 5,
§5 'long-context': the WGL frontier is the sequence dimension).  Each
device owns F/D frontier rows.  Per closure round:

  1. local expansion (same move algebra as jepsen_tpu.ops.wgl);
  2. hash-routed exchange: every candidate row is routed to device
     ``hash(state, fok) % D`` via ``lax.all_to_all`` over the mesh axis,
     so equal configurations always land on the same device;
  3. local sort-based dedup/domination/truncation (jepsen_tpu.ops.hashing)
     — globally exact because of the routing invariant;
  4. ``lax.psum`` of content fingerprints/overflow for a global fixpoint
     and loss decision (uniform across devices, so the while_loop agrees).

Barrier filtering is local; survival is decided by a psum'd global alive
count.  Soundness matches the single-device kernel: True is always a
constructive witness; False only when no loss occurred anywhere.

Reference seam: jepsen drives knossos thread-parallel inside one JVM
(jepsen/src/jepsen/checker.clj:185-216); the rebuild's equivalent of
"more cores" is more chips on the ICI mesh.
"""

from __future__ import annotations

import functools
import time
from typing import Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from jepsen_tpu import _platform, obs
from jepsen_tpu import models as m
from jepsen_tpu.ops import wgl
from jepsen_tpu.ops.hashing import frontier_update, hash_rows

I32 = jnp.int32
U32 = jnp.uint32


def _route(axis: str, D: int, C: int, state, fok, fcr, alive, cost):
    """Exchange candidate rows so each lands on device hash % D.

    Builds D fixed-capacity buckets (top-C per target by cost), swaps them
    with ``all_to_all``, and returns the received [D*C] rows plus a local
    overflow flag (some bucket spilled)."""
    n = state.shape[0]
    w = fok.shape[1]
    g = fcr.shape[1]
    class_cols = [state] + [fok[:, k] for k in range(w)]
    h = hash_rows(class_cols, 0x5EED_0D15)
    target = (h % U32(D)).astype(I32)
    dead = (~alive).astype(U32)
    iota = jnp.arange(n, dtype=I32)
    sd, st_t, sc, sidx = jax.lax.sort(
        (dead, target.astype(U32), cost.astype(U32), iota), num_keys=3
    )
    # counts/starts per target among alive rows, in sorted coordinates
    onehot = (st_t[:, None] == jnp.arange(D, dtype=U32)[None, :]) & (sd == 0)[:, None]
    counts = onehot.sum(axis=0).astype(I32)
    starts = jnp.concatenate([jnp.zeros(1, I32), jnp.cumsum(counts)[:-1]])
    pos = jnp.arange(n, dtype=I32)
    rank = pos - starts[st_t.astype(I32) % D]
    keep = (sd == 0) & (rank >= 0) & (rank < C)
    spill = ((counts > C).any()) | False
    flat = jnp.where(keep, st_t.astype(I32) * C + rank, D * C)  # D*C = drop slot
    rows_state = state[sidx]
    rows_fok = fok[sidx]
    rows_fcr = fcr[sidx]
    rows_cost = cost[sidx]

    def scatter(col, fill):
        out = jnp.full((D * C + 1,) + col.shape[1:], fill, col.dtype)
        return out.at[flat].set(col)[: D * C]

    b_state = scatter(rows_state, 0).reshape(D, C)
    b_fok = scatter(rows_fok, U32(0)).reshape(D, C, w)
    b_fcr = scatter(rows_fcr, 0).reshape(D, C, g)
    b_alive = jnp.zeros(D * C + 1, bool).at[flat].set(keep)[: D * C].reshape(D, C)
    b_cost = scatter(rows_cost, 0).reshape(D, C)

    x = lambda a: jax.lax.all_to_all(a, axis, split_axis=0, concat_axis=0, tiled=True)
    r_state = x(b_state).reshape(D * C)
    r_fok = x(b_fok).reshape(D * C, w)
    r_fcr = x(b_fcr).reshape(D * C, g)
    r_alive = x(b_alive).reshape(D * C)
    r_cost = x(b_cost).reshape(D * C)
    return r_state, r_fok, r_fcr, r_alive, r_cost, spill


def _run_core_sharded(
    axis,
    D,
    step,
    Fl,
    R,
    P_,
    G,
    W,
    init_state,
    bar_active,
    bar_f,
    bar_v1,
    bar_v2,
    bar_slot,
    mov_f,
    mov_v1,
    mov_v2,
    mov_open,
    grp_f,
    grp_v1,
    grp_v2,
    grp_open,
    slot_lane,
    slot_onehot,
):
    """Per-device body (under shard_map): scan the sharded frontier over
    all barriers.  Fl = per-device frontier capacity; bucket capacity
    C = 2*Fl bounds the exchange."""
    C = 2 * Fl
    eye_g = jnp.eye(G, dtype=I32)
    slot_mask = slot_onehot.sum(axis=1)

    def expand_round(val):
        state, fok, fcr, alive, r, changed, lossy, fp, xs = val
        (xmov_f, xmov_v1, xmov_v2, xmov_open, xgrp_open) = xs
        cat_state, cat_fok, cat_fcr, cat_alive, cost = wgl.expand_candidates(
            step, eye_g, slot_lane, slot_mask, slot_onehot,
            state, fok, fcr, alive,
            xmov_f, xmov_v1, xmov_v2, xmov_open,
            grp_f, grp_v1, grp_v2, xgrp_open,
        )
        # Route every candidate (parents included) to its hash-owner.
        r_state, r_fok, r_fcr, r_alive, r_cost, spill = _route(
            axis, D, C, cat_state, cat_fok, cat_fcr, cat_alive, cost
        )
        state2, fok2, fcr2, alive2, ovf, fp_local = frontier_update(
            r_state, r_fok, r_fcr, r_alive, r_cost, Fl
        )
        fp2 = jax.lax.psum(fp_local, axis)
        lossy2 = jax.lax.psum((ovf | spill).astype(I32), axis) > 0
        changed2 = ~(fp2 == fp).all()
        return (state2, fok2, fcr2, alive2, r + 1, changed2, lossy | lossy2, fp2, xs)

    def round_cond(val):
        _s, _fo, _fc, _a, r, changed, _l, _fp, _xs = val
        return (r < R) & changed

    def barrier(carry, xs):
        state, fok, fcr, alive, failed_at, lossy, peak = carry
        b_idx, active, xbar_slot, xmov_f, xmov_v1, xmov_v2, xmov_open, xgrp_open = xs
        done = (failed_at >= 0) | ~active

        def process(_):
            xs_inner = (xmov_f, xmov_v1, xmov_v2, xmov_open, xgrp_open)
            fp0 = jnp.full(3, jnp.uint32(0xFFFFFFFF))
            s2, fo2, fc2, a2, _r, changed, lossy2, _fp, _ = jax.lax.while_loop(
                round_cond,
                expand_round,
                (state, fok, fcr, alive, jnp.int32(0), jnp.bool_(True), lossy, fp0, xs_inner),
            )
            lossy3 = lossy2 | changed
            lane = xbar_slot // 32
            bitmask = (U32(1) << (xbar_slot % 32).astype(U32))
            lane_vals = jnp.take(fo2, lane[None], axis=1)[:, 0]
            a3 = a2 & ((lane_vals & bitmask) != 0)
            clear = jnp.where(jnp.arange(W) == lane, bitmask, U32(0))
            fo3 = fo2 & ~clear[None, :]
            n_alive = jax.lax.psum(a3.sum(), axis)
            dead = n_alive == 0
            failed2 = jnp.where(dead, b_idx, failed_at)
            peak2 = jnp.maximum(peak, n_alive)
            return (s2, fo3, fc2, a3, failed2, lossy3, peak2)

        def skip(_):
            return (state, fok, fcr, alive, failed_at, lossy, peak)

        return jax.lax.cond(done, skip, process, None), None

    state0 = jnp.full((Fl,), init_state, I32)
    fok0 = jnp.zeros((Fl, W), U32)
    fcr0 = jnp.zeros((Fl, G), I32)
    # Only one device starts with the (single) initial configuration; the
    # first exchange hash-routes it to its owner.
    me = jax.lax.axis_index(axis)
    alive0 = jnp.zeros((Fl,), bool).at[0].set(me == 0)
    carry0 = (state0, fok0, fcr0, alive0, jnp.int32(-1), jnp.bool_(False), jnp.int32(1))
    xs = (
        jnp.arange(bar_f.shape[0], dtype=I32),
        bar_active,
        bar_slot,
        mov_f,
        mov_v1,
        mov_v2,
        mov_open,
        grp_open,
    )
    (state, fok, fcr, alive, failed_at, lossy, peak), _ = jax.lax.scan(barrier, carry0, xs)
    any_alive = jax.lax.psum(alive.any().astype(I32), axis) > 0
    return any_alive, failed_at, lossy, peak


#: (mesh id, step, Fl, R, P, G, W) -> compiled sharded runner.
_SHARDED_RUNNERS: dict = {}

#: (runner, mesh, replicated, n_out) -> lane-sharded compiled wrapper.
_LANE_SHARDED: dict = {}


def lane_shard(fn, mesh: Mesh, *, n_args: int, replicated: Sequence[int] = (),
               n_out: int = 1):
    """Lane-parallel placement for a batched (vmapped) kernel runner:
    shard every argument's LEADING batch axis across ``mesh``'s one
    axis (arguments listed in ``replicated`` broadcast whole), run
    ``fn`` on each device's lane shard, and concatenate the ``n_out``
    outputs back on that axis.  Built on the ``_platform.shard_map``
    shim — the same seam every frontier-sharded kernel in this module
    compiles through — so the serving layer's launch placement and the
    single-history sharded checker ride one jax-API compatibility
    point.  The caller pads the batch axis to a mesh multiple
    (``parallel.batch.padded_batch`` with a mesh does)."""
    key = (fn, mesh, tuple(replicated), int(n_args), int(n_out))
    if key not in _LANE_SHARDED:
        axis = mesh.axis_names[0]
        rep = set(replicated)
        in_specs = tuple(
            P() if i in rep else P(axis) for i in range(n_args)
        )
        out_specs = (
            tuple(P(axis) for _ in range(n_out)) if n_out > 1 else P(axis)
        )
        compiled = jax.jit(_platform.shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        ))
        from jepsen_tpu.parallel.batch import mesh_device_ids

        dev_ids = mesh_device_ids(mesh)

        def wrapper(*args, _compiled=compiled, _devs=dev_ids):
            # Device-attributed placement telemetry: every lane-sharded
            # dispatch stamps its member devices so the per-device
            # timeline (obs.critpath.device_timeline) and the Perfetto
            # device lanes can attribute the work.  One module-attr
            # read when telemetry is off.  The observed path BLOCKS on
            # the outputs: jax dispatch is async, and a span that
            # closed at dispatch would record microseconds for a
            # seconds-long launch — busy_frac ≈ 0 on a real chip, the
            # exact number the timeline exists to get right.
            if not obs.observing():
                return _compiled(*args)
            t0 = time.perf_counter()
            out = jax.block_until_ready(_compiled(*args))
            obs.span_event("sharded.lane_launch", time.perf_counter() - t0,
                           devices=_devs)
            return out

        _LANE_SHARDED[key] = wrapper
    return _LANE_SHARDED[key]


def forget_mesh(mesh: Mesh) -> int:
    """Evict every cached runner compiled for ``mesh`` (device-loss
    re-placement: a shrunk-away mesh's compiled wrappers pin references
    to the lost devices and could never launch again anyway).  Returns
    the number of cache entries dropped."""
    dead = [k for k in _LANE_SHARDED if any(v is mesh for v in k)]
    for k in dead:
        del _LANE_SHARDED[k]
    dead_r = [k for k in _SHARDED_RUNNERS if any(v is mesh for v in k)]
    for k in dead_r:
        del _SHARDED_RUNNERS[k]
    return len(dead) + len(dead_r)


def _sharded_runner(mesh: Mesh, step, Fl: int, R: int, P_: int, G: int, W: int):
    axis = mesh.axis_names[0]
    D = mesh.devices.size
    key = (mesh, step, Fl, R, P_, G, W)
    if key not in _SHARDED_RUNNERS:
        core = functools.partial(_run_core_sharded, axis, D, step, Fl, R, P_, G, W)
        fn = _platform.shard_map(
            core,
            mesh=mesh,
            in_specs=(P(),) * 16,
            out_specs=(P(), P(), P(), P()),
            check_vma=False,
        )
        _SHARDED_RUNNERS[key] = jax.jit(fn)
    return _SHARDED_RUNNERS[key]


def sharded_analysis(
    model: m.Model,
    history: Sequence[dict],
    mesh: Mesh,
    capacity: int | Sequence[int] = (1024, 8192),
    rounds: int = 8,
    max_groups: int = 64,
    max_procs: int = 128,
) -> dict:
    """Decide linearizability of ONE history with the frontier sharded
    across ``mesh``.  ``capacity`` is the *total* frontier size (split
    evenly over devices); a sequence widens iteratively like
    jepsen_tpu.ops.wgl.analysis."""
    D = mesh.devices.size
    try:
        packed = wgl.pack(model, history)
    except wgl.NotTensorizable as e:
        return {"valid?": "unknown", "cause": f"not tensorizable: {e}"}
    if packed["B"] == 0:
        return {"valid?": True}
    if packed["G"] > max_groups:
        return {"valid?": "unknown", "cause": f"{packed['G']} crashed-op groups exceeds {max_groups}"}
    if packed["P"] > max_procs:
        return {"valid?": "unknown", "cause": f"{packed['P']} process slots exceeds {max_procs}"}
    packed = wgl.pad_packed(packed)

    capacities = [capacity] if isinstance(capacity, int) else list(capacity)
    result = None
    from jepsen_tpu.parallel.batch import mesh_device_ids

    dev_ids = mesh_device_ids(mesh)
    for cap in capacities:
        Fl = max(8, (int(cap) + D - 1) // D)
        runner = _sharded_runner(
            mesh, packed["step"], Fl, int(rounds), packed["P"], packed["G"], packed["W"]
        )
        with obs.span("sharded.launch", devices=dev_ids, capacity=Fl * D):
            valid, failed_at, lossy, peak = runner(
                packed["init_state"],
                packed["bar_active"],
                *packed["bar"],
                *packed["mov"],
                *packed["grp"],
                packed["grp_open"],
                jnp.asarray(packed["slot_lane"]),
                jnp.asarray(packed["slot_onehot"]),
            )
            # block INSIDE the span: dispatch is async, and the span
            # must cover device execution, not the enqueue
            jax.block_until_ready((valid, failed_at, lossy, peak))
        valid = bool(valid)
        failed_at = int(failed_at)
        lossy = bool(lossy)
        stats = {
            "frontier-peak": int(peak),
            "capacity": Fl * D,
            "devices": D,
            "lossy?": lossy,
        }
        if failed_at < 0 and valid:
            return {"valid?": True, "kernel": stats}
        op = history[int(packed["bar_opid"][failed_at])] if failed_at >= 0 else None
        if not lossy:
            return {"valid?": False, "op": op, "kernel": stats}
        result = {
            "valid?": "unknown",
            "cause": "frontier capacity or closure rounds exhausted",
            "op": op,
            "kernel": stats,
        }
    return result
