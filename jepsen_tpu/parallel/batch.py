"""Batched, mesh-sharded linearizability checking.

The reference keeps per-key linearizability tractable by splitting the
workload into many small independent histories
(jepsen/src/jepsen/independent.clj:2-7, 103-238) and pmapping checkers over
them (independent.clj:285-307, checker.clj:95-97).  Here that becomes the
TPU's favourite shape: pack every history to common (B, P, G) buckets,
stack, and run ONE vmapped kernel over the batch, sharded across the mesh
on a ``histories`` axis.  Throughput scales with chips; each chip sweeps
its shard's frontiers in lockstep.
"""

from __future__ import annotations

import logging
import multiprocessing
import os
import time
from concurrent.futures import ProcessPoolExecutor
from concurrent.futures import TimeoutError as FutureTimeout
from concurrent.futures.process import BrokenProcessPool
from pathlib import Path
from typing import Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from jepsen_tpu import _confirm_worker, faults, obs
from jepsen_tpu import models as m
from jepsen_tpu.checker import wgl_cpu
from jepsen_tpu.obs import provenance as _prov
from jepsen_tpu.ops import hashing, wgl
from jepsen_tpu.store import checkpoint as _ckpt

logger = logging.getLogger(__name__)

#: lazily created, reused across batch_analysis calls (spawn startup is
#: ~seconds; the pool is harmless idle and dies with the process).
#: Workers must never touch the accelerator — the parent owns the TPU —
#: so both the initializer and the task live in the import-light,
#: jax-free module jepsen_tpu._confirm_worker (unpickling a function
#: imports its defining module; this one would drag in the kernels).
_CONFIRM_POOL: ProcessPoolExecutor | None = None

#: one-shot flag for the exact_escalation=None behavior-change warning.
_WARNED_EXACT_DEFAULT = False

#: (step, engine, shape...) buckets already launched this process — a
#: launch whose bucket is fresh pays jit trace+compile (the runner caches
#: in ops/wgl.py key on the same step + static shapes), so its wall time
#: lands in the telemetry stage table's compile_s column (compile + first
#: execute); warm buckets land in execute_s.
_SEEN_SHAPES: set[tuple] = set()


#: dedup shapes already probed this process (the telemetry-gated
#: dedup.round probe at the end of batch_analysis): one probe per shape
#: per process — repeated ladder runs don't re-pay the probe.
_PROBED_DEDUP_SHAPES: set[tuple] = set()


#: exact-engine frontier rows per launch (sub-batch bound; see the stage
#: loop's budget comment — re-measure the true threshold on-chip).
_EXACT_LANE_BUDGET = 16 * 1024

#: fast-engine frontier rows per launch; the carried-frontier variant
#: halves it because the resume snapshot doubles the async kernel's
#: resident per-lane footprint (tests shrink these to force multi-chunk
#: stages on small workloads).
_FAST_LANE_BUDGET = 64 * 1024
_CARRY_LANE_BUDGET = 32 * 1024

#: the padded-geometry bucket tables every batched launch quantizes to
#: (P = slots, G = groups); bucket_geometry is the single source the
#: launch sites AND the serving layer's batch-compatibility key share —
#: two histories with equal bucketed geometry reuse one compiled kernel.
P_BUCKETS = (8, 16, 32, 64, 128)
G_BUCKETS = (4, 8, 16, 32, 64)

#: Continuous ladders: how long a pending member may sit skipped at
#: its rung before that rung preempts lowest-rung-first selection (see
#: the ladder loop).  A TIME bound, and generous on purpose: eager
#: preemption serves NARROW high-rung launches and costs real occupancy
#: (a skipped-launch-count bound of 8 measured 0.69-0.75 against ~0.91
#: on the round-8 acceptance demo, and even 64 still fired — rung-0
#: launches are milliseconds).  Healthy arrival streams pause well
#: inside this bound; a pathological steady stream can no longer defer
#: an escalated member's launch indefinitely.
_STARVE_SECONDS = 5.0


def bucket_geometry(B: int, P: int, G: int) -> tuple[int, int, int]:
    """The padded (B, P, G) bucket a packed history launches at."""
    return (
        wgl.pad_B(B),
        wgl._bucket(P, list(P_BUCKETS)),
        wgl._bucket(G, list(G_BUCKETS)),
    )


def padded_batch(n: int, mesh: Mesh | None = None) -> int:
    """The padded batch-axis size a launch of ``n`` lanes runs at: the
    next power of two (floor 8), rounded up to a mesh multiple — the
    same quantity _launch_impl pads to, exposed so the serving layer can
    report true batch occupancy / padding waste."""
    n_pad = 1 << max(3, (n - 1).bit_length())
    if mesh is not None:
        shard = mesh.devices.size
        n_pad = ((n_pad + shard - 1) // shard) * shard
    return n_pad


def greedy_fastpath(model: m.Model, packed: Sequence[dict],
                    mesh: Mesh | None = None,
                    pad_to: int | None = None) -> list[bool]:
    """One batched greedy witness-walk launch over pre-packed histories
    — the device-batched variant of the interactive fast path (the
    CheckService serves waves with per-request host walks,
    ``wgl_cpu.greedy_walk``; this launch form is for hosts where the
    walk is kernel-bound, and pins the mesh-placement parity contract
    for greedy work).  ``packed`` entries are
    ``wgl.pack`` outputs sharing a geometry bucket; returns one flag per
    entry — True is EXACT (the walk completed: a constructive witness),
    False only means the walk stuck and the caller must escalate that
    history into the beam ladder.  Never refutes.

    The launch stacks to the same ``bucket_geometry``/``padded_batch``
    shapes the ladder's greedy rung uses, so a warm serving process
    re-hits the compiled greedy kernel instead of paying a fast-path
    compile per geometry.  With a ``mesh`` the padded batch axis is
    lane-sharded across its devices (``parallel.sharded.lane_shard``,
    the ``_platform.shard_map`` shim) — placement only; flags are
    device-count independent."""
    B, P, G = bucket_geometry(
        max(p["B"] for p in packed),
        max(p["P"] for p in packed),
        max(p["G"] for p in packed),
    )
    stacked = _stack(packed, B, P, G)
    n = len(packed)
    # ``pad_to`` pins the batch axis to the caller's fixed serving
    # width: every wave size then re-hits ONE compiled greedy kernel.
    n_pad = padded_batch(n, mesh)
    if pad_to is not None and pad_to > n_pad:
        n_pad = int(pad_to)
    n_actives = np.array([p["bar_active"].sum() for p in packed], np.int32)
    if n_pad != n:
        for k in stacked:
            if k in ("slot_lane", "slot_onehot"):
                continue
            stacked[k] = np.concatenate(
                [stacked[k]] + [stacked[k][-1:]] * (n_pad - n), axis=0
            )
        n_actives = np.concatenate(
            [n_actives, np.repeat(n_actives[-1:], n_pad - n)]
        )
    W = (P + 31) // 32
    g_args = [stacked["init_state"], jnp.asarray(n_actives)] + [
        stacked[k] for k in ASYNC_ARG_ORDER[1:]
    ]
    runner = wgl.greedy_runner(packed[0]["step"], B, P, G, W)
    if mesh is not None:
        from jepsen_tpu.parallel import sharded

        # the greedy runner's vmap batches every arg except the shared
        # slot tables (its in_axes: (0,)*14 + (None, None))
        runner = sharded.lane_shard(
            runner, mesh, n_args=len(g_args),
            replicated=(len(g_args) - 2, len(g_args) - 1), n_out=3,
        )
    finished, _stuck_at, _fired = runner(*g_args)
    return [bool(x) for x in np.asarray(finished)[:n]]


def _stays_pending(valid, fat, lossy) -> bool:
    """Whether one lane's (valid, failed_at, lossy) launch outcome leaves
    it PENDING for the next ladder rung — neither resolved True
    (survived all barriers) nor a lossless refutation.  The single
    predicate behind both the snapshot-fetch lane filter and the
    still-classification loop; keep them in sync by keeping them HERE
    (round-5 advisor: the duplicated predicate desyncs silently)."""
    if fat < 0 and valid:
        return False  # resolved True
    if fat >= 0 and not lossy:
        return False  # lossless refutation (final or confirmation-bound)
    return True


def _resolve_confirmation(res: dict, cpu_res: dict) -> dict:
    """Fold an exact-sweep confirmation verdict into the device result
    (shared by the worker and device confirm paths)."""
    if cpu_res["valid?"] is False:
        return {**res, "confirmed?": True}
    if cpu_res["valid?"] is True:
        # the ~1e-13 case: a hash collision killed a live config; the
        # exact sweep's witness wins
        return cpu_res
    return {
        "valid?": "unknown",
        "cause": (
            "device refutation; exact confirmation inconclusive: "
            + str(cpu_res.get("cause", "budget exceeded"))
        ),
        "kernel": res.get("kernel"),
    }


def _default_workers(workers: int | None) -> int:
    return workers or min(8, os.cpu_count() or 1)


def _confirm_pool(workers: int | None) -> ProcessPoolExecutor:
    global _CONFIRM_POOL
    if _CONFIRM_POOL is None:
        _CONFIRM_POOL = ProcessPoolExecutor(
            max_workers=_default_workers(workers),
            mp_context=multiprocessing.get_context("spawn"),
            initializer=_confirm_worker.init,
        )
    return _CONFIRM_POOL


def _reset_confirm_pool() -> None:
    """Drop a broken pool so later calls rebuild it instead of failing."""
    global _CONFIRM_POOL
    if _CONFIRM_POOL is not None:
        _CONFIRM_POOL.shutdown(wait=False, cancel_futures=True)
        _CONFIRM_POOL = None


def warm_confirm_pool(workers: int | None = None) -> None:
    """Spawn the confirmation workers ahead of time (outside any timed
    window): pool startup + worker init cost ~seconds once per process.
    Warm-up failure is non-fatal — batch_analysis degrades per history —
    so a broken pool is dropped, never propagated."""
    try:
        pool = _confirm_pool(workers)
        futs = [
            pool.submit(_confirm_worker.probe_backend)
            for _ in range(_default_workers(workers))
        ]
        for f in futs:
            f.result()
    except Exception:  # noqa: BLE001 — warm-up is best-effort by contract
        _reset_confirm_pool()


def _submit_confirmation(workers: int | None, *args):
    """Submit a confirmation, rebuilding the pool once if it is broken.
    Returns (pool, future) — the pool handle lets the resolution loop
    reset only the pool the failure actually came from — or (None, None)
    when no worker could take the job (the caller degrades that one
    history, not the batch)."""
    for _ in range(2):
        try:
            pool = _confirm_pool(workers)
            return pool, pool.submit(_confirm_worker.confirm_refutation, *args)
        except BrokenProcessPool:
            _reset_confirm_pool()
    return None, None


def _device_oom_spiller(ctx) -> bool:
    """The default OOM spiller (faults.register_oom_spiller): evict the
    cached jitted runners so the backend can release their executables'
    device buffers, then collect.  Only on non-CPU backends — the CPU
    backend has no allocator pressure worth a recompile, and evicting
    the process-shared runner caches there would just slow every later
    ladder (the tier-1 suite shares them)."""
    try:
        if jax.default_backend() == "cpu":
            return False
    except Exception:  # noqa: BLE001 — no backend: nothing to free
        return False
    n = wgl.evict_runner_caches()
    import gc

    gc.collect()
    return n > 0


faults.register_oom_spiller(_device_oom_spiller)


def make_mesh(n_devices: int | None = None, axis: str = "histories") -> Mesh:
    """A 1-D device mesh over the first ``n_devices`` devices."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis,))


def mesh_device_ids(mesh: Mesh | None) -> list[int]:
    """The device ids a placement launches on: the mesh's members in
    lane order, or ``[0]`` (jax's default device) single-device — the
    ONE definition behind every device-attribution site (ladder/launch
    span attrs, the lane-shard wrapper, the serve bubble gauge)."""
    if mesh is None:
        return [0]
    return [int(d.id) for d in mesh.devices.ravel().tolist()]


def _stack(packs: list[dict], B: int, P: int, G: int) -> dict:
    padded = [wgl.pad_packed(p, B=B, P=P, G=G) for p in packs]
    out = {}
    out["init_state"] = np.stack([p["init_state"] for p in padded])
    out["bar_active"] = np.stack([p["bar_active"] for p in padded])
    for i, name in enumerate(["bar_f", "bar_v1", "bar_v2", "bar_slot"]):
        out[name] = np.stack([p["bar"][i] for p in padded])
    for i, name in enumerate(["mov_f", "mov_v1", "mov_v2", "mov_open"]):
        out[name] = np.stack([p["mov"][i] for p in padded])
    for i, name in enumerate(["grp_f", "grp_v1", "grp_v2"]):
        out[name] = np.stack([p["grp"][i] for p in padded])
    out["grp_open"] = np.stack([p["grp_open"] for p in padded])
    out["slot_lane"] = padded[0]["slot_lane"]
    out["slot_onehot"] = padded[0]["slot_onehot"]
    return out


_ARG_ORDER = [
    "init_state", "bar_active", "bar_f", "bar_v1", "bar_v2", "bar_slot",
    "mov_f", "mov_v1", "mov_v2", "mov_open",
    "grp_f", "grp_v1", "grp_v2", "grp_open",
    "slot_lane", "slot_onehot",
]

#: the async kernel replaces bar_active with a per-history n_active scalar
#: (inserted after init_state at the call site).
ASYNC_ARG_ORDER = [k for k in _ARG_ORDER if k != "bar_active"]


def batch_analysis(
    model: m.Model,
    histories: Sequence[Sequence[dict]],
    capacity: int | Sequence[int] = (64, 512, 4096),
    rounds: int = 8,
    mesh: Mesh | None = None,
    cpu_fallback: bool = True,
    exact_escalation: Sequence[int] | None = None,
    engine: str = "async",
    confirm_refutations: bool = True,
    confirm_workers: int | None = None,
    confirm_max_configs: int = 2_000_000,
    carry_frontier: bool = True,
    greedy_first: bool = True,
    dedup_backend: str | None = None,
    checkpoint_dir: str | Path | None = None,
    resume: bool = False,
    deadline=None,
    admission=None,
    frontier_budget_mb: float | None = None,
) -> list[dict]:
    """Check many histories against one model in batched kernel launches.

    ``capacity`` lists the BATCHED (fast-kernel) capacity ladder: each
    stage re-batches only the still-unknown histories, padded to a power
    of two so compiles are reused.  ``engine`` picks the batched kernel:
    "async" (lane-asynchronous barrier stepping — lanes pay their own
    closure depth; the default: with candidate-order truncation it
    matches the sync engine's verdict quality and runs the full ladder
    ~15% faster) or "sync" (the barrier-scan kernel).  ``rounds`` bounds per-barrier
    closure depth on the "sync" engine and the exact escalation stages;
    the async engine's closure budget is its tick budget
    (wgl.async_ticks).

    ``greedy_first`` (default) prepends a capacity-1 greedy witness-walk
    stage (wgl.greedy_runner): most VALID lanes resolve there for the
    cost of one buffer-free scan, so the beam ladder only pays for the
    contested lanes.  The walk never refutes, so soundness is untouched.

    ``True`` verdicts are sound from every stage (a surviving frontier is
    a constructive witness).  The fast engines dedup by 64-bit row hash,
    so their refutations are PROVISIONAL: with ``confirm_refutations``
    (the default, honoring the "never an unconfirmed False" contract)
    each one is confirmed by the exact CPU config-set sweep running in
    worker processes CONCURRENTLY with the remaining device stages — by
    the time the ladder drains, the confirmations have usually finished,
    so soundness costs almost no wall clock.  A sweep that exceeds
    ``confirm_max_configs`` leaves the verdict "unknown" (never a wrong
    False); a sweep that disagrees (the ~1e-13 collision case) wins.
    ``confirm_refutations="device"`` confirms on the ACCELERATOR
    instead: one batched exact-kernel (content-decided kills) launch per
    capacity bucket over the failure prefixes after the ladder drains —
    no CPU sweeps on the happy path, which matters on single-core hosts
    where the worker sweeps time-share the driver's core; the rare
    disagreeing/lossy lane falls back to the bounded CPU sweep.

    Escalation is about CAPACITY: each ladder stage re-runs only the
    still-lossy histories wider — and with ``carry_frontier`` (the
    default, round 5) an async rung RESUMES each straggler from its
    saved exact pre-loss frontier at its failure barrier instead of
    re-running the whole history: the verified prefix is never re-paid,
    and the rung's tick budget shrinks to the max REMAINING barriers.
    Soundness is unchanged (the snapshot is taken before any loss, so
    refutations keep their "no loss anywhere" meaning and are still
    sweep-confirmed).  ``exact_escalation`` optionally appends
    stages on the in-round-domination kernel (frontier_update; ~10x
    slower per lane but content-exact, so its refutations are final);
    wide stages sub-batch automatically.  Behavior change (round 3):
    ``exact_escalation=None`` now means NO exact stages — it used to mean
    one stage at 4x the last batch capacity.  Refutation soundness moved
    to the confirmation sweep, and the wider default batch ladder covers
    the capacity range; but callers with ``cpu_fallback=False`` that
    relied on the implicit exact stage to resolve capacity-bound lanes
    may see more "unknown"s and should pass ``exact_escalation``
    explicitly.  Remaining unknowns fall back to the CPU config-set
    sweep when ``cpu_fallback``.  Returns one knossos-shaped result per
    history, in order.

    ``dedup_backend`` selects the per-round frontier dedup backend for
    every rung — "sort" (multi-key hash sort) or "bucket" (packed radix
    buckets; see jepsen_tpu.ops.hashing).  None resolves through the
    JEPSEN_TPU_DEDUP_BACKEND env var, then the module default.  Verdict
    semantics are backend-independent: fast-engine refutations are
    hash-decided (and confirmed) either way, exact-engine kills are
    content-decided either way.  (The greedy rung walks a single
    configuration — no frontier, nothing to dedup — so the backend
    choice is moot there by construction.)

    Fault tolerance (jepsen_tpu.faults): every device launch runs under
    a retry policy — transient ``XlaRuntimeError``s retry with
    exponential backoff; ``RESOURCE_EXHAUSTED`` halves the sub-batch
    (and the stage lane budget) and relaunches, floor one lane; a
    launch that still fails degrades ONLY its lanes to ``"unknown"``
    with a ``cause`` naming the error, never the whole batch.
    ``checkpoint_dir`` persists the ladder's durable state after every
    stage (jepsen_tpu.store.checkpoint: verdicts so far, the pending
    set, resume frontiers, in-flight confirmation descriptors, the
    RNG-free config); ``resume=True`` reloads it and re-enters the
    ladder at the saved rung — a kill -9 mid-ladder then a resume
    yields verdicts identical to an uninterrupted run.  On resume the
    SAVED config wins over the caller's ladder arguments (verdict
    identity requires the original ladder), and a checkpoint whose
    history fingerprint doesn't match is ignored with a warning.
    ``deadline`` (seconds or a faults.Deadline) bounds wall clock: it
    is polled at stage boundaries; on expiry the ladder checkpoints,
    marks the remaining packs ``unknown`` with cause
    ``deadline-exceeded`` plus a pointer to the checkpoint, and still
    returns a complete result list.

    Bounded memory (round 8): an OOM first tries the registered
    device-memory spillers (``faults.try_oom_spill`` — runner-cache
    eviction on real accelerators) and retries the SAME launch before
    any lane halving, so the sub-batch ladder engages only once spill
    is exhausted.  ``frontier_budget_mb`` (or the
    JEPSEN_TPU_FRONTIER_BUDGET_MB env var) caps the exact engine's
    device frontier working set: the chunked exact paths (unsafe-shape
    lanes and device-confirmation fallbacks) then host-spill overflow
    rows instead of going lossy (``ops.wgl.chunked_analysis``), and a
    history fixed memory still cannot decide returns ``unknown`` with a
    machine-readable undecidability report in its ``cause``
    (``ops.spill.undecidability_report``) — never a bare unknown.

    Continuous batching (``admission``): an object with a
    ``poll(stage=, lanes=)`` method is consulted at every rung boundary
    and may return new histories that JOIN the running ladder — they
    are packed, enter at rung 0 (the greedy walk), and run the same
    rung sequence a one-shot call would, so verdict semantics are
    identical; their results are appended to the returned list in
    admission order (index = ``len(histories)`` at the moment of the
    poll — the caller mirrors that counter to demux).  Lane slots
    recycle naturally: resolved members leave the pending set at the
    same boundaries joiners enter, which is what keeps device occupancy
    high under open arrival (streaming batched beam search,
    arXiv:2010.02164).  Optional hook methods: ``on_result(i, result)``
    is called the moment history ``i``'s verdict is DECIDED (True, or a
    final/confirmed False) so a serving layer can resolve that caller
    mid-ladder; ``on_rung(stage=, engine=, capacity=, lanes=, padded=,
    seconds=)`` reports, after each rung's launches complete, the live
    lanes, the padded lane-slots actually launched, and the rung's
    launch seconds — compile + execute device time, not the stage wall
    — for device-time-weighted occupancy.  A hook may also advertise
    ``pad_lanes``: every rung launch is then padded UP to that fixed
    batch axis (clamped to the stage lane budget), so membership churn
    never changes the compiled kernel shape mid-service.  With an
    admission hook, finished worker confirmations are also drained at
    rung boundaries (refuted requests resolve while the ladder keeps
    running).  The ladder returns when the pending set is empty and a
    poll returned no joiners.
    """
    dedup = hashing.resolve_dedup_backend(dedup_backend)
    histories = list(histories)
    results: list[dict | None] = [None] * len(histories)
    packs: list[dict] = []
    idxs: list[int] = []
    t_pack = time.perf_counter()
    for i, hist in enumerate(histories):
        try:
            p = wgl.pack(model, hist)
        except wgl.NotTensorizable as e:
            results[i] = {"valid?": "unknown", "cause": f"not tensorizable: {e}"}
            continue
        if p["B"] == 0:
            results[i] = {"valid?": True}
        else:
            packs.append(p)
            idxs.append(i)
    obs.span_event(
        "ladder.pack", time.perf_counter() - t_pack,
        histories=len(histories), tensorizable=len(packs),
    )

    if engine not in ("sync", "async"):
        raise ValueError(f"unknown engine {engine!r}; expected 'sync' or 'async'")
    if confirm_refutations not in (True, False, "device"):
        raise ValueError(
            f"unknown confirm_refutations {confirm_refutations!r}; "
            "expected True (worker sweeps), False, or 'device'"
        )
    capacities = [capacity] if isinstance(capacity, int) else list(capacity)
    batch_caps = [int(c) for c in capacities]
    if exact_escalation is None and not cpu_fallback:
        # Behavior changed in round 3 (None used to mean one implicit
        # exact stage at 4x the last batch capacity; now it means none).
        # Callers without the CPU fallback are the ones who can observe
        # the difference — as extra "unknown"s with no runtime signal —
        # so give them one (advisor r4).
        global _WARNED_EXACT_DEFAULT
        if not _WARNED_EXACT_DEFAULT:
            _WARNED_EXACT_DEFAULT = True
            import warnings

            warnings.warn(
                "exact_escalation=None now means NO exact stages (it "
                "used to mean one at 4x the last batch capacity); with "
                "cpu_fallback=False, capacity-bound histories stay "
                "'unknown'. Pass exact_escalation=() to silence, or an "
                "explicit ladder to restore exact stages.",
                stacklevel=2,
            )
    exact_caps = [int(c) for c in (exact_escalation or ())]

    # ------------------------------------------------------------------
    # Checkpoint / resume (jepsen_tpu.store.checkpoint).
    # ------------------------------------------------------------------
    checkpoint_dir = Path(checkpoint_dir) if checkpoint_dir is not None else None
    deadline = faults.Deadline.coerce(deadline)
    deadline_tripped = False
    trip_checkpointed = False  # a resumable trip checkpoint is on disk
    fp_dirty = False  # rung admission grew `histories` since fp was taken
    no_fallback: set[int] = set()  # history idxs the CPU fallback must skip
    start_stage = 0
    restored = None
    fp = None
    if checkpoint_dir is not None or resume:
        fp = _ckpt.fingerprint(histories)
    if resume and checkpoint_dir is not None and _ckpt.exists(checkpoint_dir):
        t_load = time.perf_counter()
        try:
            restored = _ckpt.load(checkpoint_dir)
        except _ckpt.CheckpointError as e:
            # Corrupt pairs were already quarantined aside by the
            # durable layer (with the machine-readable report on
            # e.report); the fresh run below reproduces uninterrupted
            # verdicts, so this degradation is total-recovery.
            logger.warning("unreadable checkpoint in %s (%s); running fresh",
                           checkpoint_dir, e)
            obs.counter("fault.checkpoint.mismatch",
                        reason=getattr(e, "report", None) and
                        e.report.get("reason") or "unreadable",
                        report=getattr(e, "report", None))
        if restored is not None and restored["config"].get("fingerprint") != fp:
            # The stale pair is QUARANTINED aside, not merely ignored: a
            # later --resume against the same dir (now with the matching
            # histories again) must never pick the mismatched state back
            # up, and the checkpoint this fresh run is about to write
            # must not interleave with the old files.
            quarantined = _ckpt.quarantine(checkpoint_dir,
                                           reason="stale-fingerprint")
            logger.warning(
                "checkpoint in %s was written for different histories; "
                "running fresh (resuming against changed inputs could "
                "only produce wrong verdicts); stale files quarantined: "
                "%s", checkpoint_dir, quarantined)
            obs.counter("fault.checkpoint.quarantined",
                        reason="fingerprint", files=quarantined)
            obs.counter("fault.checkpoint.mismatch", reason="fingerprint")
            restored = None
        if restored is not None:
            # The saved config wins: verdict identity requires the
            # original ladder, and the CLI resume path can't know the
            # original kwargs.
            cfg = restored["config"]
            engine = cfg.get("engine", engine)
            batch_caps = [int(c) for c in cfg.get("capacity", batch_caps)]
            exact_caps = [int(c) for c in cfg.get("exact_escalation", exact_caps)]
            rounds = int(cfg.get("rounds", rounds))
            greedy_first = bool(cfg.get("greedy_first", greedy_first))
            carry_frontier = bool(cfg.get("carry_frontier", carry_frontier))
            dedup = cfg.get("dedup", dedup)
            confirm_refutations = cfg.get(
                "confirm_refutations", confirm_refutations)
            frontier_budget_mb = cfg.get(
                "frontier_budget_mb", frontier_budget_mb)
            start_stage = int(restored["stage"])
            obs.span_event(
                "fault.checkpoint.load", time.perf_counter() - t_load,
                stage=start_stage, pending=len(restored["pending"]),
                complete=restored["complete"],
            )
            if restored["complete"]:
                # A finished run's checkpoint: hand back the saved
                # verdicts (idempotent resume; no device work at all).
                # Each verdict's provenance records the restore — a
                # replayed/resumed answer is a different trust path
                # than a fresh device run.
                for i, r in restored["results"].items():
                    if 0 <= i < len(results):
                        if isinstance(r, dict):
                            _prov.attach(
                                r,
                                [{"event": "checkpoint.restored",
                                  "complete": True, "stage": start_stage}],
                                engine={"engine": engine,
                                        "dedup_backend": dedup},
                            )
                        results[i] = r
                return [r if r is not None else {"valid?": "unknown"}
                        for r in results]
    config = {
        "engine": engine, "capacity": list(batch_caps),
        "exact_escalation": list(exact_caps), "rounds": int(rounds),
        "greedy_first": bool(greedy_first),
        "carry_frontier": bool(carry_frontier), "dedup": dedup,
        "confirm_refutations": confirm_refutations, "fingerprint": fp,
        "frontier_budget_mb": frontier_budget_mb,
    }

    # ------------------------------------------------------------------
    # Verdict provenance (obs.provenance): a bounded per-history
    # decision-path trail, attached to every result before it leaves the
    # ladder (both the early _notify demux and the final return), so the
    # caller can emit an evidence bundle recording exactly which rungs,
    # fallbacks, and fault events produced each verdict.
    # ------------------------------------------------------------------
    prov_cfg = {k: v for k, v in config.items() if k != "fingerprint"}
    prov_engine: dict = {"engine": engine, "dedup_backend": dedup,
                        "greedy_first": bool(greedy_first)}
    if dedup == "pallas":
        try:
            from jepsen_tpu.ops import wide_kernel as _wkp

            prov_engine["pallas_interpret"] = bool(_wkp.interpret_default())
        except Exception:  # noqa: BLE001 — provenance must not lose ladders
            pass
    prov_paths: dict[int, list] = {}

    def _pv(i: int, event: str, **attrs) -> None:
        lst = prov_paths.setdefault(i, [])
        if len(lst) < _prov.MAX_PATH:
            lst.append({"event": event, **attrs})

    def _pv_merge(i: int, sub: dict | None) -> None:
        """Fold a nested engine's provenance (chunked_analysis) into
        this history's trail: the ladder's events stay first, the inner
        trajectory follows."""
        if not sub:
            return
        eng = sub.get("engine")
        if eng:
            _pv(i, "engine.nested", **eng)
        lst = prov_paths.setdefault(i, [])
        for e in sub.get("path", ()):
            if len(lst) >= _prov.MAX_PATH:
                break
            lst.append(dict(e))

    def _attach_prov(i: int) -> None:
        r = results[i]
        if isinstance(r, dict):
            _prov.attach(r, prov_paths.get(i, []), engine=prov_engine,
                         config=prov_cfg)

    def _notify(i: int) -> None:
        """Early per-history demux for the rung-admission caller: hand a
        DECIDED verdict (True, or a final False) to the hook the moment
        it is final, instead of at return.  Unknowns are left for the
        return path — some are provisional (the CPU fallback may still
        decide them), and the caller settles every leftover from the
        returned list anyway."""
        if admission is None:
            return
        r = results[i]
        if r is None or r.get("valid?") == "unknown":
            return
        _attach_prov(i)
        try:
            admission.on_result(i, r)
        except Exception:  # noqa: BLE001 — a broken feeder must not
            # lose the ladder; the verdict still lands in the return list
            logger.exception("rung-admission on_result failed (history %d)", i)

    if restored is not None:
        # mid-run resume: every history's trail records that this run
        # continued from a checkpoint rather than starting fresh
        for _pi in range(len(histories)):
            _pv(_pi, "checkpoint.restored", stage=start_stage)

    #: device ids every launch of this ladder runs on (lane-sharded
    #: over the mesh, or jax's default device) — the device-attribution
    #: attr on ladder.launch/ladder.stage spans that obs.critpath's
    #: per-device timeline and the Perfetto device lanes read.
    _dev_ids = mesh_device_ids(mesh)

    #: per-stage launch accounting for the telemetry stage table; "_key"
    #: is the launched (engine, shape) bucket, set at each runner site.
    launch_acc: dict = {}

    def _reset_launch_acc() -> None:
        launch_acc.update(
            launches=0, compile_launches=0, compile_s=0.0, execute_s=0.0,
            device_bytes_peak=0,
        )

    _reset_launch_acc()

    def _launch(st_engine: str, batch_cap: int, sub: list[dict],
                sub_resumes: list[tuple | None] | None = None,
                pad_to: int | None = None, retry: bool = False):
        """Instrumented wrapper over the kernel launch: times the launch,
        classifies it compile (fresh shape bucket) vs execute, samples
        the post-launch device-buffer footprint (the stage's memory
        high-water mark), and emits a ladder.launch telemetry span.
        ``retry`` marks a reduced-size OOM-halved / spill-retry launch —
        excluded from the watchdog's launch-time EWMA baseline
        (faults.record_launch_seconds)."""
        with obs.span(
            "ladder.launch", engine=st_engine, capacity=batch_cap,
            lanes=len(sub), devices=_dev_ids,
        ) as sp:
            t0 = time.perf_counter()
            out = _launch_impl(st_engine, batch_cap, sub, sub_resumes, pad_to)
            dt = time.perf_counter() - t0
            # Feed the process launch-time EWMA the serving layer's
            # hung-launch watchdog derives its wall-clock caps from
            # (reduced retry launches are tagged out of the baseline).
            faults.record_launch_seconds(dt, retry=retry)
            key = launch_acc.pop("_key", None)
            compiled = key is not None and key not in _SEEN_SHAPES
            if key is not None:
                _SEEN_SHAPES.add(key)
            launch_acc["launches"] += 1
            if compiled:
                launch_acc["compile_launches"] += 1
                launch_acc["compile_s"] += dt
            else:
                launch_acc["execute_s"] += dt
            obs.counter(
                "ladder.compile_cache.miss" if compiled
                else "ladder.compile_cache.hit",
                engine=st_engine,
            )
            sp.set(compiled=compiled)
            if obs.observing():
                # Post-launch device footprint: right after a launch is
                # where the stage's buffers (frontier, snapshot, sort
                # scratch) peak host-visibly — the per-stage high-water
                # mark in the telemetry stage table.
                db = wgl.device_buffer_bytes()
                if db is not None and db > launch_acc["device_bytes_peak"]:
                    launch_acc["device_bytes_peak"] = db
        return out

    def _launch_impl(st_engine: str, batch_cap: int, sub: list[dict],
                     sub_resumes: list[tuple | None] | None = None,
                     pad_to: int | None = None):
        """Stack ``sub`` to common bucket shapes and run one vmapped
        kernel launch; returns (valid, failed_at, lossy, peak, snap)
        with host arrays of len(sub).  ``sub_resumes[j]`` optionally
        carries lane j's saved (bsnap, state, fok, fcr, alive) frontier
        from the previous rung — the lane resumes there instead of
        re-running the whole history (round 5: carried-frontier
        escalation).  ``snap`` is the async engine's resume snapshot as
        ON-DEVICE arrays (bsnap, state, fok, fcr, alive), or None: the
        stage loop fetches rows host-side only for lanes that actually
        stay pending AND have a later async rung to resume on — each
        ``np.asarray`` here is a tunnel round-trip, and fetching every
        lane's full padded frontier after every rung was measured at
        ~0.8 s on the bench ladder (chip ablation, round 5)."""
        B, P, G = bucket_geometry(
            max(p["B"] for p in sub),
            max(p["P"] for p in sub),
            max(p["G"] for p in sub),
        )
        stacked = _stack(sub, B, P, G)
        n = len(sub)
        # Pad the batch axis to a power of two (and a mesh multiple) so the
        # vmapped kernel compiles once per bucket, not once per batch size.
        # ``pad_to`` (continuous batching) pins the width HIGHER — every
        # rung of a served ladder launches at one fixed batch axis, so
        # membership churn (joiners, resolved lanes) never changes the
        # compiled shape mid-service: an underfull rung costs padded
        # lanes (~replicated rows), never an XLA compile.
        n_pad = padded_batch(n, mesh)
        if pad_to is not None and pad_to > n_pad:
            n_pad = int(pad_to)
        if n_pad != n:
            for k in stacked:
                if k in ("slot_lane", "slot_onehot"):
                    continue
                reps = np.concatenate(
                    [stacked[k]] + [stacked[k][-1:]] * (n_pad - n), axis=0
                )
                stacked[k] = reps
        args = [stacked[k] for k in _ARG_ORDER]
        if mesh is not None:
            axis = mesh.axis_names[0]
            spec = NamedSharding(mesh, PartitionSpec(axis))
            rep = NamedSharding(mesh, PartitionSpec())
            args = [
                jax.device_put(a, rep if k in ("slot_lane", "slot_onehot") else spec)
                for k, a in zip(_ARG_ORDER, args)
            ]
        W = (P + 31) // 32
        snap = None
        if st_engine == "greedy":
            # Stage 0: the capacity-1 greedy witness walk — resolves most
            # VALID lanes for ~nothing (no frontier buffers, one scan).
            # Never refutes: unresolved lanes report lossy so the stage
            # loop keeps them pending for the beam ladder.
            n_actives = np.array([p["bar_active"].sum() for p in sub], np.int32)
            if n_pad != n:
                n_actives = np.concatenate([n_actives, np.repeat(n_actives[-1:], n_pad - n)])
            by_name = dict(zip(_ARG_ORDER, args))
            # init_state is already stacked/padded/mesh-sharded in args
            g_args = [by_name["init_state"], jnp.asarray(n_actives)] + [
                by_name[k] for k in ASYNC_ARG_ORDER[1:]
            ]
            if mesh is not None:
                axis = mesh.axis_names[0]
                spec = NamedSharding(mesh, PartitionSpec(axis))
                g_args[1] = jax.device_put(np.asarray(g_args[1]), spec)
            launch_acc["_key"] = (sub[0]["step"], "greedy", B, P, G, W, n_pad)
            runner = wgl.greedy_runner(sub[0]["step"], B, P, G, W)
            finished, _stuck_at, _fired = runner(*g_args)
            finished = np.asarray(finished)[:n]
            return (
                finished,
                np.full(n, -1, np.int32),
                ~finished,  # unresolved = lossy -> stays pending
                np.ones(n, np.int32),
                snap,
            )
        if st_engine == "async":
            n_actives = np.array([p["bar_active"].sum() for p in sub], np.int32)
            # Per-lane resume frontiers: fresh single-config at barrier 0,
            # or the saved snapshot re-bucketed to this stage's shapes.
            F = batch_cap
            bptr0, st0, fo0, fc0, al0 = wgl.fresh_frontier(
                n, F, W, G, [p["init_state"] for p in sub]
            )
            if sub_resumes is not None:
                for j, r in enumerate(sub_resumes):
                    if r is None:
                        continue
                    bs, rst, rfo, rfc, ral = wgl.pad_resume(r, F, W, G)
                    bptr0[j], st0[j], fo0[j], fc0[j], al0[j] = bs, rst, rfo, rfc, ral
            # Tick budget from the MAX REMAINING barriers, not the padded
            # B: resumed lanes skip their verified prefix, so the budget
            # (and the stage's worst-case wall clock) shrinks with it.
            b_rem = int(max(1, (n_actives - bptr0[:n]).max()))
            b_rem = 1 << max(5, (b_rem - 1).bit_length())
            T = wgl.async_ticks(min(b_rem, B), batch_cap)
            if n_pad != n:
                n_actives = np.concatenate([n_actives, np.repeat(n_actives[-1:], n_pad - n)])
                reps = n_pad - n
                bptr0 = np.concatenate([bptr0, np.repeat(bptr0[-1:], reps)])
                st0 = np.concatenate([st0, np.repeat(st0[-1:], reps, axis=0)])
                fo0 = np.concatenate([fo0, np.repeat(fo0[-1:], reps, axis=0)])
                fc0 = np.concatenate([fc0, np.repeat(fc0[-1:], reps, axis=0)])
                al0 = np.concatenate([al0, np.repeat(al0[-1:], reps, axis=0)])
            order = ASYNC_ARG_ORDER
            by_name = dict(zip(_ARG_ORDER, args))
            a_args = [jnp.asarray(bptr0), jnp.asarray(st0), jnp.asarray(fo0),
                      jnp.asarray(fc0), jnp.asarray(al0),
                      jnp.asarray(n_actives)] + [by_name[k] for k in order[1:]]
            if mesh is not None:
                axis = mesh.axis_names[0]
                spec = NamedSharding(mesh, PartitionSpec(axis))
                for ai in range(6):
                    a_args[ai] = jax.device_put(np.asarray(a_args[ai]), spec)
            launch_acc["_key"] = (sub[0]["step"], "async", batch_cap, T, B, P, G, W, n_pad, dedup)
            runner = wgl.async_runner(sub[0]["step"], batch_cap, T, B, P, G, W, dedup)
            valid, failed_at, lossy, peak, bsnap, sst, sfo, sfc, sal = runner(*a_args)
            if carry_frontier:
                # keep the snapshot ON-DEVICE; the stage loop fetches
                # only the still-pending rows (and only when a later
                # async rung exists to resume on)
                snap = (bsnap, sst, sfo, sfc, sal)
        elif st_engine == "sync":
            launch_acc["_key"] = (sub[0]["step"], "sync", batch_cap, int(rounds), B, P, G, W, n_pad, dedup)
            runner = wgl.batched_runner(sub[0]["step"], batch_cap, int(rounds), P, G, W, dedup)
            valid, failed_at, lossy, peak = runner(*args)
        else:  # "exact": content-compare dedup/domination — may refute
            launch_acc["_key"] = (sub[0]["step"], "exact", batch_cap, int(rounds), B, P, G, W, n_pad, dedup)
            runner = wgl.exact_batched_runner(sub[0]["step"], batch_cap, int(rounds), P, G, W, dedup)
            valid, failed_at, lossy, peak = runner(*args)
        return (
            np.asarray(valid)[:n],
            np.asarray(failed_at)[:n],
            np.asarray(lossy)[:n],
            np.asarray(peak)[:n],
            snap,
        )

    def _emit_stage(t_stage: float, stage_attrs: dict, **extra) -> None:
        """One ladder.stage telemetry span per rung: wall time, lanes in,
        verdict counts, the stage's compile/execute launch split, and
        its device-memory high-water mark."""
        mem = {}
        if launch_acc.get("device_bytes_peak"):
            mem["device_bytes_peak"] = launch_acc["device_bytes_peak"]
            obs.gauge("device.buffer_bytes", launch_acc["device_bytes_peak"],
                      at="ladder-stage", stage=stage_attrs.get("stage"))
        obs.span_event(
            "ladder.stage", time.perf_counter() - t_stage,
            devices=_dev_ids,
            launches=launch_acc["launches"],
            compile_launches=launch_acc["compile_launches"],
            compile_s=round(launch_acc["compile_s"], 6),
            execute_s=round(launch_acc["execute_s"], 6),
            **mem, **stage_attrs, **extra,
        )

    stages = [(engine, c) for c in batch_caps] + [("exact", c) for c in exact_caps]
    if greedy_first and stages:
        stages = [("greedy", 1)] + stages
    pending = list(range(len(packs)))
    resumes: dict[int, tuple] = {}  # pack idx -> saved resume frontier
    # hist idx -> (pool, future, device result, t, op_pos, obs.Ctx): the
    # Ctx is the span context captured at SUBMIT time, re-attached when
    # the drain resolves the confirmation — trace ids survive the
    # worker-pool process boundary (the worker itself records nothing;
    # its submit/resolve bracket in this process carries the trace).
    confirm_futs: dict = {}
    device_confirms: list[tuple] = []  # (pack idx, failed_at, cap, result)
    confirm_degraded: set[int] = set()  # hist idxs whose confirmation hit the deadline
    if restored is not None:
        # Re-enter the ladder where the checkpoint left it: verdicts so
        # far (including the pending lanes' unknown placeholders), the
        # pending set, and each pending lane's carried-frontier resume
        # snapshot.  In-flight worker confirmations are RESUBMITTED (the
        # old futures died with the old process); queued device
        # confirmations re-queue as they were.
        pack_of = {i: k for k, i in enumerate(idxs)}
        for i, r in restored["results"].items():
            if 0 <= i < len(results):
                results[i] = r
        pending = [pack_of[i] for i in restored["pending"] if i in pack_of]
        for i, fr in restored["resumes"].items():
            if i in pack_of:
                resumes[pack_of[i]] = fr
        for i, info in restored["confirms"].items():
            pool, fut = _submit_confirmation(
                confirm_workers, model, list(histories[i]),
                confirm_max_configs, int(info["op_pos"]),
            )
            obs.counter("confirm.submitted")
            confirm_futs[i] = (
                pool, fut, info["res"], time.perf_counter(),
                int(info["op_pos"]), obs.capture(),
            )
            results[i] = info["res"]
        for e in restored["device_confirms"]:
            if e["i"] in pack_of:
                device_confirms.append(
                    (pack_of[e["i"]], int(e["failed_at"]), int(e["cap"]), e["res"])
                )
                results[e["i"]] = e["res"]

    #: per-pack rung cursor: stages[rungs[k]] is pack k's NEXT rung.
    #: Every initial pack starts (or resumes) at the same rung, so
    #: without rung-boundary admission the loop below walks the ladder
    #: exactly like a uniform per-stage loop; packs admitted mid-ladder
    #: enter at rung 0 and catch up, running the SAME rung sequence a
    #: one-shot call would (continuous batching changes who shares a
    #: launch, never how a history is decided).
    rungs: dict[int, int] = {k: start_stage for k in pending}
    if restored is not None and restored.get("rungs"):
        pack_of = {i: k for k, i in enumerate(idxs)}
        for i, r in restored["rungs"].items():
            if i in pack_of:
                rungs[pack_of[i]] = int(r)

    def _save_checkpoint(next_stage: int, complete: bool = False):
        """Persist the ladder's durable state at a stage boundary; a
        save failure is logged, counted, and never fails the analysis
        (the checkpoint is a recovery aid, not a verdict input)."""
        if checkpoint_dir is None:
            return None
        nonlocal fp_dirty
        if fp_dirty:
            # Rung admission grew the membership since the fingerprint
            # was taken: re-fingerprint the CURRENT histories so a
            # resume over the drained member list (original + joined)
            # matches instead of spuriously running fresh.
            config["fingerprint"] = _ckpt.fingerprint(histories)
            fp_dirty = False
        t0 = time.perf_counter()
        try:
            path = _ckpt.save(
                checkpoint_dir,
                config=config,
                stage=next_stage,
                results={i: r for i, r in enumerate(results) if r is not None},
                pending=[idxs[k] for k in pending],
                confirms={
                    i: {"res": res, "op_pos": op_pos}
                    for i, (_p, _f, res, _t, op_pos, _c) in confirm_futs.items()
                },
                device_confirms=[
                    {"i": idxs[k], "failed_at": fat, "cap": cap, "res": res}
                    for k, fat, cap, res in device_confirms
                ],
                resumes={idxs[k]: resumes[k] for k in pending if k in resumes},
                rungs={idxs[k]: rungs.get(k, next_stage) for k in pending},
                complete=complete,
            )
        except Exception:  # noqa: BLE001 — see docstring
            logger.warning("couldn't write checker checkpoint to %s",
                           checkpoint_dir, exc_info=True)
            obs.counter("fault.checkpoint.error")
            return None
        obs.span_event(
            "fault.checkpoint.save", time.perf_counter() - t0,
            stage=next_stage, pending=len(pending), complete=complete,
        )
        return path

    early_confirmed: set[int] = set()  # resolved at a rung boundary

    def _poll_confirmations() -> None:
        """Rung-boundary confirmation demux (continuous batching): a
        worker sweep that already finished resolves NOW — its caller's
        future settles while the ladder keeps running — instead of at
        the final drain.  Only the clean success path resolves here;
        failed/timed-out futures keep their descriptor so the final
        drain's full retry machinery (pool rebuild, bounded resubmit,
        deadline grace) handles them unchanged."""
        done = [
            i for i, e in confirm_futs.items()
            if e[1] is not None and e[1].done()
            and not e[1].cancelled() and e[1].exception() is None
        ]
        for i in done:
            _pool, fut, dev_res, t_submit, _op_pos, ctx = confirm_futs.pop(i)
            early_confirmed.add(i)
            with obs.attach(ctx):
                obs.gauge(
                    "confirm.queue_latency_s",
                    round(time.perf_counter() - t_submit, 6), history=i,
                )
                results[i] = _resolve_confirmation(dev_res, fut.result())
            _pv(i, "confirm.resolved", mode="worker",
                outcome=_prov.verdict_str(results[i].get("valid?")))
            _notify(i)

    def _poll_admission() -> None:
        """The rung-boundary admission hook (continuous batching): ask
        the caller for new histories to JOIN the running ladder.  Each
        joiner packs here, enters the pending set at rung 0, and is
        assigned result index len(histories) — sequential, so the
        caller can mirror the counter to demux.  A broken hook degrades
        to no joiners, never a lost ladder."""
        nonlocal fp_dirty
        if admission is None:
            return
        min_rung = min((rungs[k] for k in pending), default=0)
        try:
            new_hists = admission.poll(stage=min_rung, lanes=len(pending))
        except Exception:  # noqa: BLE001 — see docstring
            logger.exception(
                "rung-admission poll failed; continuing without joiners")
            new_hists = None
        for hist in new_hists or ():
            i = len(histories)
            histories.append(list(hist))
            results.append(None)
            fp_dirty = True
            try:
                p = wgl.pack(model, histories[i])
            except wgl.NotTensorizable as e:
                results[i] = {
                    "valid?": "unknown", "cause": f"not tensorizable: {e}"}
                continue
            if p["B"] == 0:
                results[i] = {"valid?": True}
                _notify(i)
                continue
            k = len(packs)
            packs.append(p)
            idxs.append(i)
            pending.append(k)
            rungs[k] = 0
            _pv(i, "admission.joined", at_stage=min_rung)
            obs.counter("ladder.rung_admission", stage=min_rung)

    #: Continuous batching pins every rung launch to one fixed batch
    #: axis (the hook advertises its width): joiners and resolved lanes
    #: then recycle slots inside a single compiled shape instead of
    #: walking the ladder through a fresh XLA compile per membership
    #: size (a mid-service async compile measured ~2.5 s on CPU — worse
    #: than the batch it served).
    pad_lanes = getattr(admission, "pad_lanes", None)
    pad_lanes = int(pad_lanes) if pad_lanes else None

    #: OOM halvings shrink the stage lane budget for the REST of the run
    #: (the device that OOM'd once at a shape will OOM again; re-probing
    #: it every stage would pay the fault each time).
    budget_scale = 1.0
    exhausted: list[int] = []  # packs that ran out of rungs unresolved
    #: Lowest-rung-first selection + rung-0 joiner admission could defer
    #: an escalated member forever under a steady arrival stream; a
    #: member skipped for more than _STARVE_SECONDS gets its rung served
    #: next (bounded wait, not strict priority).  Only continuous
    #: ladders need it — without admission the lowest rung drains
    #: monotonically.  k -> perf_counter() of the first skipped launch.
    starve: dict[int, float] = {}
    while pending or admission is not None:
        _poll_admission()
        if admission is not None:
            _poll_confirmations()
        # Members past the last rung leave the ladder (post-ladder
        # unknowns: the exact-confirm/CPU-fallback tail decides them).
        past = [k for k in pending if rungs[k] >= len(stages)]
        if past:
            exhausted.extend(past)
            pending = [k for k in pending if rungs[k] < len(stages)]
        if not pending:
            if admission is not None and confirm_futs:
                # Linger while worker confirmations are in flight: keep
                # demuxing finished confirms early and keep ADMITTING —
                # a joiner arriving during the confirm tail enters rung
                # 0 of THIS ladder instead of seeding a narrow
                # follow-up batch (the tail was measured as a whole
                # second service cycle at ~0.4 occupancy).  Only LIVE
                # futures are worth lingering for: _poll_confirmations
                # demuxes clean successes only, so a dead entry (failed
                # submit left fut=None, or a future holding an
                # exception) would spin this loop forever — those
                # belong to the final drain's retry machinery below, as
                # does everything once the deadline expires.
                live = any(
                    e[1] is not None and not e[1].done()
                    for e in confirm_futs.values()
                )
                if live and (deadline is None or not deadline.expired()):
                    time.sleep(0.001)
                    continue
            break  # ladder drained and the hook (if any) had nothing
        si = min(rungs[k] for k in pending)
        if admission is not None and starve:
            waiting = [k for k in pending if k in starve]
            if waiting:
                k_worst = min(waiting, key=lambda k: starve[k])
                if time.perf_counter() - starve[k_worst] > _STARVE_SECONDS:
                    si = rungs[k_worst]
        group = [k for k in pending if rungs[k] == si]
        rest = [k for k in pending if rungs[k] != si]
        if admission is not None:
            for k in group:
                starve.pop(k, None)
            t_skip = time.perf_counter()
            for k in rest:
                starve.setdefault(k, t_skip)
        st_engine, batch_cap = stages[si]
        if deadline is not None and deadline.expired():
            # Deadline-bounded degradation: checkpoint FIRST (the saved
            # placeholders keep their resumable causes), then mark every
            # remaining pack unknown with an attributable cause plus a
            # pointer to the checkpoint.  The CPU fallback is skipped
            # for these — the budget is spent.
            deadline_tripped = True
            ck = _save_checkpoint(si)
            trip_checkpointed = ck is not None
            obs.event("fault.deadline", at="ladder-stage", stage=si,
                      unresolved=len(pending))
            obs.counter("fault.deadline.trip")
            note = f"; resumable checkpoint: {ck}" if ck else ""
            for k in pending:
                i = idxs[k]
                prev = results[i]
                _pv(i, "fault.deadline", at="ladder-stage", stage=si)
                results[i] = {
                    "valid?": "unknown",
                    "cause": (
                        "deadline-exceeded: check budget exhausted before "
                        f"ladder stage {si}{note}"
                    ),
                }
                if isinstance(prev, dict) and prev.get("kernel"):
                    results[i]["kernel"] = prev["kernel"]
                no_fallback.add(i)
            obs.gauge("ladder.unknowns_remaining", len(pending), final=True)
            pending = []
            break
        _reset_launch_acc()
        t_stage = time.perf_counter()
        stage_attrs = dict(
            stage=si, engine=st_engine, capacity=batch_cap,
            lanes=len(group), dedup=dedup,
        )
        if dedup == "pallas" and st_engine in ("async", "sync") and group:
            # Fused-kernel rungs carry the kernel's tile/VMEM occupancy
            # on their ladder.stage rows (estimate at the rung's widest
            # pack shape — stage_occupancy is pure arithmetic), plus an
            # honest interpret flag so chip rows stay separable.  A
            # rung whose geometry statically routes AWAY from the
            # kernel is counted: silent fallback would read as "the
            # kernel ran" in exactly the stage rows built to decide
            # the chip-day flip.
            from jepsen_tpu.ops import wide_kernel as _wk

            _pP = max(packs[k]["P"] for k in group)
            _pG = max(packs[k]["G"] for k in group)
            _pW = (_pP + 31) // 32
            _occ = _wk.stage_occupancy(batch_cap, _pP, _pG,
                                       max_count=_pP + 1)
            # routed = the full gate (geometry AND the per-launch VMEM
            # working-set model) — a rung the budget spills off the
            # kernel must read as fallback in the stage rows, exactly
            # like a geometry miss
            _routed = _wk.fused_feasible(
                _occ["candidates"], batch_cap, _pP + 1, w=_pW, g=_pG)
            _n_mesh = int(mesh.devices.size) if mesh is not None else 1
            stage_attrs.update(
                pallas_routed=_routed, pallas_tile=_occ["tile"],
                pallas_vmem_bytes=_occ["vmem_bytes"],
                pallas_vmem_budget_bytes=_occ["vmem_budget_bytes"],
                pallas_interpret=_occ["interpret"],
                mesh_devices=_n_mesh,
            )
            if not _routed:
                obs.counter("dedup.pallas_fallback",
                            stage=si, capacity=batch_cap)
                if _n_mesh > 1:
                    # the mesh-spanning stage can still lift this rung:
                    # record whether its per-device VMEM model says so
                    _mocc = _wk.mesh_occupancy(
                        batch_cap, _pP, _pG, W=_pW,
                        max_count=_pP + 1, devices=_n_mesh)
                    stage_attrs.update(
                        pallas_mesh_feasible=_mocc["feasible"])
        # Measured-shape guard (round 5): the batched exact runner
        # faults the TPU worker on long-scan x wide-frontier shapes
        # (boundary table in wgl.exact_scan_safe).  Lanes past the
        # boundary take the chunked exact path — short chunk scans with
        # a carried frontier, same content-decided kills — instead of
        # joining the batched launch.  Unsafe-ness is monotone in
        # capacity, so such a lane is handled ONCE with the full
        # remaining exact ladder (chunked_analysis escalates only the
        # overflowing chunks) and never re-enters a later rung.
        if st_engine == "exact":
            safe = []
            exact_ladder = [c for e, c in stages[si:] if e == "exact"]
            # the launch pads its batch axis to a power of two >= 8, so
            # the guard sees the PADDED lane count the kernel actually
            # holds resident (the fault grid is single-lane; vmap
            # multiplies the live buffers by the lane count)
            n_lanes = min(max(1, _EXACT_LANE_BUDGET // batch_cap), len(group))
            n_lanes = 1 << max(3, (n_lanes - 1).bit_length())
            for k in group:
                if wgl.exact_scan_safe(
                        wgl.pad_B(packs[k]["B"]), batch_cap, n_lanes):
                    safe.append(k)
                    continue
                i = idxs[k]
                _pv(i, "route.chunked-exact", stage=si, capacity=batch_cap)
                r = wgl.chunked_analysis(
                    model, histories[i], packs[k], exact_ladder,
                    rounds=int(rounds), fast=False, dedup_backend=dedup,
                    deadline=deadline, frontier_budget_mb=frontier_budget_mb,
                )
                _pv_merge(i, r.pop("provenance", None)
                          if isinstance(r, dict) else None)
                results[i] = r
                _notify(i)
            group = safe
            if not group:
                _emit_stage(t_stage, stage_attrs, unknowns_remaining=0)
                pending = rest
                continue
        # Bound total frontier rows per launch so wide-capacity stages
        # sub-batch instead of faulting the TPU worker (observed at
        # capacity*lanes ≳ 64k on the exact engine, whose sort and
        # domination buffers are ~10x the fast kernel's per-lane
        # footprint; fast engines get a proportionally larger budget).
        # The carried-frontier snapshot doubles the async kernel's
        # resident per-lane frontier, so its budget halves to keep the
        # old resident bound (re-measure the true threshold on-chip).
        if st_engine == "exact":
            budget = _EXACT_LANE_BUDGET
        elif st_engine == "async" and carry_frontier:
            budget = _CARRY_LANE_BUDGET
        else:
            budget = _FAST_LANE_BUDGET
        # Carried-frontier fetch (round 5): resume snapshots leave the
        # device only for lanes that STAY pending, and only when a later
        # async rung exists to resume them — each lane's pre-loss
        # frontier then seeds the wider rung instead of re-running the
        # whole history from barrier 0.  The fetch happens per chunk,
        # IMMEDIATELY after that chunk's launch (the verdict arrays are
        # host-side by then), so at most one chunk's snapshot is ever
        # device-resident — the lanes budget's resident-row bound holds
        # across sub-batches.  The unconditional full-batch fetch this
        # replaces measured ~0.8 s of tunnel round-trips on the bench
        # ladder (chip ablation, round 5).
        fetch_snaps = (
            st_engine == "async" and carry_frontier
            and any(e == "async" for e, _ in stages[si + 1:])
        )
        lane_out: dict[int, tuple] = {}  # pack idx -> (valid, fat, lossy, peak)
        degraded: list[tuple[int, str]] = []  # (pack idx, cause)

        def _launch_ft(part: list[int], pad_to: int | None = None,
                       retry: bool = False, spilled: bool = False) -> None:
            """Launch one sub-batch under the fault policy: transient
            errors retry with backoff inside faults.call_with_retry; an
            OOM first asks the registered device-memory spillers to free
            something (faults.try_oom_spill — runner-cache eviction on
            real accelerators) and retries the SAME launch once, then
            halves the sub-batch recursively (floor one lane — and the
            stage lane budget shrinks with it, so later chunks don't
            re-probe the fault); a part that still fails degrades ONLY
            its lanes, never the batch.  Spill-retry and halved
            sub-launches run with ``retry=True`` so their reduced sizes
            stay out of the watchdog's launch-time EWMA.  Successful
            parts land their verdicts in lane_out and fetch their
            pending lanes' resume snapshots immediately (at most one
            part's snapshot is ever device-resident, preserving the
            lane budget's resident-row bound)."""
            nonlocal budget_scale
            sub_res = (
                [resumes.get(k) for k in part]
                if (st_engine == "async" and carry_frontier) else None
            )
            ctx = dict(
                what=f"ladder.{st_engine}", stage=si, engine=st_engine,
                capacity=batch_cap, lanes=len(part),
            )
            try:
                out = faults.call_with_retry(
                    lambda: _launch(
                        st_engine, batch_cap, [packs[k] for k in part],
                        sub_res, pad_to, retry,
                    ),
                    ctx,
                )
            except faults.LaunchFailure as lf:
                if lf.kind == "oom" and not spilled and faults.try_oom_spill(ctx):
                    # Spill rung of the OOM ladder: device memory was
                    # freed — retry the SAME shape once at full size
                    # before shrinking any work.
                    obs.counter(
                        "fault.launch.oom_spill_retry", stage=si,
                        engine=st_engine, capacity=batch_cap,
                        lanes=len(part),
                    )
                    for k in part:
                        _pv(idxs[k], "fault.oom-spill-retry", stage=si,
                            engine=st_engine, capacity=batch_cap)
                    _launch_ft(part, pad_to, retry=True, spilled=True)
                    return
                if lf.kind == "oom" and len(part) > 1:
                    mid = (len(part) + 1) // 2
                    budget_scale = max(budget_scale / 2, 1.0 / max(1, budget))
                    obs.counter(
                        "fault.launch.oom_halving", stage=si,
                        engine=st_engine, capacity=batch_cap,
                        lanes_from=len(part), lanes_to=mid,
                    )
                    for k in part:
                        _pv(idxs[k], "fault.oom-halving", stage=si,
                            engine=st_engine, capacity=batch_cap)
                    # Fault path: drop the fixed continuous-batching pad
                    # — replaying the halved part back up to the width
                    # that just OOM'd would re-probe the fault.
                    _launch_ft(part[:mid], retry=True, spilled=spilled)
                    _launch_ft(part[mid:], retry=True, spilled=spilled)
                    return
                cause = faults.describe(lf.cause)
                obs.counter(
                    "fault.launch.degraded", stage=si, engine=st_engine,
                    capacity=batch_cap, lanes=len(part), error=cause,
                )
                for k in part:
                    _pv(idxs[k], "fault.launch-degraded", stage=si,
                        engine=st_engine, capacity=batch_cap, error=cause)
                degraded.extend((k, cause) for k in part)
                return
            v, fat, lz, pk, snap = out
            for j, k in enumerate(part):
                lane_out[k] = (v[j], fat[j], lz[j], pk[j])
            if fetch_snaps and snap is not None:
                local = [
                    jl for jl in range(len(part))
                    if _stays_pending(v[jl], fat[jl], lz[jl])
                ]
                if local:
                    sel = jnp.asarray(np.asarray(local, np.int32))
                    bs, sst, sfo, sfc, sal = jax.device_get(
                        tuple(a[sel] for a in snap)
                    )
                    for t, jl in enumerate(local):
                        resumes[part[jl]] = (
                            int(bs[t]), sst[t], sfo[t], sfc[t], sal[t]
                        )
            del snap, out  # free the device snapshot before the next launch

        # Re-read the (possibly OOM-halved) scale for EVERY chunk: when
        # chunk 1 OOMs, chunks 2..n are sliced at the shrunken budget
        # instead of re-probing the fault at the original width.  The
        # continuous fixed pad is clamped to the chunk lane budget so
        # pinning the shape never exceeds the resident-row bound.
        s0 = 0
        launched_pad = 0
        while s0 < len(group):
            lanes_cap = max(1, int(budget * budget_scale) // batch_cap)
            part = group[s0 : s0 + lanes_cap]
            pad_to = (
                min(pad_lanes, padded_batch(lanes_cap, mesh))
                if pad_lanes is not None else None
            )
            # the launch pads to MAX(natural pad, pinned pad) — mirror
            # that here so reported slots never undercount live lanes
            launched_pad += max(pad_to or 0, padded_batch(len(part), mesh))
            _launch_ft(part, pad_to)
            s0 += lanes_cap
        if admission is not None and hasattr(admission, "on_rung"):
            # Post-stage occupancy report: the lanes that were live, the
            # padded lane-slots the kernel actually launched (the fixed
            # continuous width when pinned), and the rung's LAUNCH
            # seconds (compile + execute, from the launch accounting —
            # not the stage wall, which also counts host-side packing
            # and demux the device never saw) — so the caller can
            # weight occupancy by device time instead of counting a
            # 2 ms underfull greedy launch the same as a 300 ms
            # full-width beam rung.
            try:
                admission.on_rung(
                    stage=si, engine=st_engine, capacity=batch_cap,
                    lanes=len(group), padded=launched_pad,
                    seconds=launch_acc["compile_s"] + launch_acc["execute_s"],
                )
            except Exception:  # noqa: BLE001 — telemetry-only hook
                logger.exception("rung-admission on_rung failed")
        for k, cause in degraded:
            # a failed launch costs exactly its own lanes: each degrades
            # to unknown with the error named, and (when enabled) the
            # CPU fallback below still gets a chance to decide it
            results[idxs[k]] = {
                "valid?": "unknown",
                "cause": f"device launch failed: {cause}",
            }
        still = []
        n_true = n_refuted = 0
        peak_max = 0
        n_lossy = 0
        for k in group:
            if k not in lane_out:
                continue  # degraded this stage; its result is set above
            valid_k, fat_k, lossy_k, peak_k = lane_out[k]
            i = idxs[k]
            stats = {"frontier-peak": int(peak_k), "capacity": batch_cap, "lossy?": bool(lossy_k)}
            peak_max = max(peak_max, int(peak_k))
            n_lossy += bool(lossy_k)
            # the SAME predicate the snapshot fetch filtered on — a lane
            # fetched there is exactly a lane classified pending here
            pending_lane = _stays_pending(valid_k, fat_k, lossy_k)
            if not pending_lane and fat_k < 0:
                n_true += 1
                _pv(i, "ladder.stage", stage=si, engine=st_engine,
                    capacity=batch_cap, outcome="valid")
                results[i] = {"valid?": True, "kernel": stats}
                _notify(i)
            elif not pending_lane:
                n_refuted += 1
                op_pos = int(packs[k]["bar_opid"][int(fat_k)])
                op = histories[i][op_pos]
                res = {"valid?": False, "op": op, "kernel": stats}
                _pv(i, "ladder.stage", stage=si, engine=st_engine,
                    capacity=batch_cap, outcome="refuted",
                    confirm=("none" if st_engine == "exact"
                             or not confirm_refutations
                             else str(confirm_refutations)))
                if st_engine == "exact" or not confirm_refutations:
                    # content-decided kills (or the caller opted out):
                    # the refutation is final
                    results[i] = res
                    _notify(i)
                elif confirm_refutations == "device":
                    # confirm on the accelerator: queue for one batched
                    # exact-kernel launch over the failure prefix after
                    # the ladder drains (no CPU sweeps at all on the
                    # happy path — the drain tail was the 1-core host's
                    # serial sweeps)
                    device_confirms.append((k, int(fat_k), batch_cap, res))
                    results[i] = res  # placeholder; resolved below
                else:
                    # fast-engine refutation: hash-dedup could in
                    # principle have killed a distinct config, so the
                    # exact CPU sweep confirms it — in a worker
                    # process, concurrent with the remaining stages.
                    # op_pos (the positional id, same identity the sweep
                    # enumerates) bounds the sweep to the failure prefix.
                    pool, fut = _submit_confirmation(
                        confirm_workers, model, list(histories[i]),
                        confirm_max_configs, op_pos,
                    )
                    obs.counter("confirm.submitted")
                    confirm_futs[i] = (
                        pool, fut, res, time.perf_counter(), op_pos,
                        obs.capture(),
                    )
                    results[i] = res  # placeholder; resolved below
            else:
                still.append(k)
                _pv(i, "ladder.stage", stage=si, engine=st_engine,
                    capacity=batch_cap, outcome="pending",
                    lossy=bool(lossy_k))
                results[i] = {
                    "valid?": "unknown",
                    "cause": "frontier capacity or closure rounds exhausted",
                    "kernel": stats,
                }
        for k in still:
            rungs[k] = si + 1
        pending = sorted(rest + still)
        _emit_stage(
            t_stage, stage_attrs, resolved=n_true, refuted=n_refuted,
            unknowns_remaining=len(still), peak_frontier=peak_max,
            lossy=n_lossy, degraded=len(degraded),
        )
        obs.gauge(
            "ladder.unknowns_remaining", len(still), stage=si, capacity=batch_cap
        )
        _save_checkpoint(
            min(rungs[k] for k in pending) if pending else si + 1
        )

    if (exhausted and dedup == "pallas" and mesh is not None
            and mesh.devices.size > 1):
        # Mesh rescue (round 12): before the ladder admits defeat, the
        # exhausted lanes get ONE run of the mesh-SPANNING fused stage —
        # the whole mesh as a single frontier at devices x the top rung
        # (the per-device VMEM model is what makes that capacity
        # feasible where a single chip spills).  True is a constructive
        # witness and lands outright; a refutation is hash-decided like
        # every fast-engine False and is confirmed by the bounded exact
        # sweep before it is reported; an unknown keeps the mesh-capacity
        # undecidability report as its cause.
        from jepsen_tpu.parallel import sharded as _sharded

        _n_mesh = int(mesh.devices.size)
        top_cap = max(batch_caps + exact_caps)
        rescue_cap = top_cap * _n_mesh
        t_rescue = time.perf_counter()
        still_exhausted = []
        for k in exhausted:
            i = idxs[k]
            if deadline is not None and deadline.expired():
                still_exhausted.append(k)
                continue
            _pv(i, "route.mesh-kernel", capacity=rescue_cap,
                mesh_devices=_n_mesh)
            r = _sharded.mesh_kernel_analysis(
                model, histories[i], mesh, capacity=(rescue_cap,),
                rounds=int(rounds),
            )
            if r["valid?"] is True:
                results[i] = r
                no_fallback.add(i)
                _pv(i, "mesh-kernel.resolved", outcome="valid")
                _notify(i)
                continue
            if r["valid?"] is False:
                if not confirm_refutations:
                    # unconfirmed fast-engine False: carries its honest
                    # provisional? flag, same contract as the ladder
                    results[i] = r
                    no_fallback.add(i)
                    _pv(i, "mesh-kernel.resolved",
                        outcome="refuted-provisional")
                    _notify(i)
                    continue
                fat = int(r.get("kernel", {}).get("failed-at", -1))
                op_pos = (int(packs[k]["bar_opid"][fat])
                          if fat >= 0 else None)
                cpu_res = wgl_cpu.sweep_analysis(
                    model, histories[i],
                    max_configs=confirm_max_configs,
                    stop_at_index=op_pos,
                )
                results[i] = _resolve_confirmation(r, cpu_res)
                decided = results[i].get("valid?") != "unknown"
                _pv(i, "mesh-kernel.resolved" if decided
                    else "mesh-kernel.unconfirmed",
                    outcome=_prov.verdict_str(results[i].get("valid?")))
                if decided:
                    no_fallback.add(i)
                    _notify(i)
                    continue
                still_exhausted.append(k)
                continue
            # unknown even at mesh capacity: the mesh-capacity
            # undecidability report becomes the attributable cause
            if r.get("cause"):
                results[i] = r
            _pv(i, "mesh-kernel.exhausted")
            still_exhausted.append(k)
        obs.span_event(
            "ladder.mesh_rescue", time.perf_counter() - t_rescue,
            capacity=rescue_cap, mesh_devices=_n_mesh,
            lanes=len(exhausted),
            resolved=len(exhausted) - len(still_exhausted),
        )
        exhausted = still_exhausted

    if exhausted:
        # The lanes the whole ladder failed to resolve: close the
        # documented "extra unknowns with no runtime signal" gap — a final
        # gauge plus an attributable cause in each unknown result (these
        # are exactly the lanes a pre-round-3 implicit exact stage might
        # have resolved when cpu_fallback is off).
        obs.gauge("ladder.unknowns_remaining", len(exhausted), final=True)
        if exact_caps:
            note = (
                f"capacity ladder {tuple(batch_caps)} and exact escalation "
                f"{tuple(exact_caps)} exhausted"
            )
        else:
            note = (
                f"capacity ladder {tuple(batch_caps)} exhausted with no "
                "exact-escalation stages (exact_escalation=None means none "
                "since round 3)"
            )
        for k in exhausted:
            i = idxs[k]
            _pv(i, "ladder.exhausted")
            r = results[i]
            if r is not None and r.get("valid?") == "unknown" and r.get("cause"):
                r["cause"] = f"{r['cause']}; {note}"

    device_resolved: set[int] = set()

    def _finish_confirmation(k: int, fat: int, res: dict, exact_died: bool) -> None:
        """Resolve one device-mode confirmation: an exact lossless death
        makes the refutation final; otherwise (collision artifact or
        loss) the bounded CPU sweep decides (shared by the batched
        launch and the unsafe-shape chunked fallback).  A deadline that
        expires mid-confirmation degrades to unknown instead of
        starting a sweep the budget can no longer cover."""
        nonlocal deadline_tripped
        i = idxs[k]
        device_resolved.add(i)
        if exact_died:
            res["confirmed?"] = True
            _pv(i, "confirm.device", outcome="refuted-final")
            results[i] = res
            _notify(i)
            return
        if deadline is not None and deadline.expired():
            deadline_tripped = True
            _pv(i, "fault.deadline", at="device-confirm")
            results[i] = {
                "valid?": "unknown",
                "cause": ("device refutation; deadline-exceeded before "
                          "exact confirmation"),
                "kernel": res.get("kernel"),
            }
            return
        op_pos = int(packs[k]["bar_opid"][fat])
        cpu_res = wgl_cpu.sweep_analysis(
            model, histories[i], max_configs=confirm_max_configs,
            stop_at_index=op_pos,
        )
        results[i] = _resolve_confirmation(res, cpu_res)
        _pv(i, "confirm.resolved", mode="device-sweep",
            outcome=_prov.verdict_str(results[i].get("valid?")))
        _notify(i)

    if device_confirms and deadline is not None and deadline.expired():
        # The budget died before the exact confirmations ran: an
        # unconfirmed fast-engine False must never be reported, so each
        # one degrades to unknown.  The descriptors are in the
        # checkpoint — a resume finishes the confirmations.  A stage
        # trip already saved a resumable checkpoint (which INCLUDES
        # these descriptors); overwriting it here would bake the
        # pending lanes' deadline causes in as final results and
        # destroy their resume frontiers.
        deadline_tripped = True
        if not trip_checkpointed:
            ck = _save_checkpoint(len(stages))
            trip_checkpointed = ck is not None
        else:
            ck = _ckpt.json_path(checkpoint_dir) if checkpoint_dir else None
        obs.event("fault.deadline", at="device-confirm",
                  unresolved=len(device_confirms))
        obs.counter("fault.deadline.trip")
        note = f"; resumable checkpoint: {ck}" if ck else ""
        for k, _fat, _cap, res in device_confirms:
            _pv(idxs[k], "fault.deadline", at="device-confirm")
            results[idxs[k]] = {
                "valid?": "unknown",
                "cause": (
                    "device refutation; deadline-exceeded before exact "
                    f"confirmation{note}"
                ),
                "kernel": res.get("kernel"),
            }
            no_fallback.add(idxs[k])
        device_confirms = []
    if device_confirms:
        # One batched exact-engine launch per capacity bucket over the
        # failure PREFIXES: content-decided kills make a lossless exact
        # death a FINAL refutation.  The fast engine refuted losslessly,
        # so (modulo the ~1e-13 hash-collision case) the true frontier
        # fit its capacity; a surviving or lossy exact run IS that rare
        # case and falls back to the exact CPU sweep.
        _reset_launch_acc()
        t_conf = time.perf_counter()
        by_cap: dict[int, list[tuple]] = {}
        for k, fat, cap, res in device_confirms:
            by_cap.setdefault(cap, []).append((k, fat, res))
        for cap, group in sorted(by_cap.items()):
            masked = []
            safe_group = []
            lanes_cap = max(1, _EXACT_LANE_BUDGET // cap)
            n_lanes = min(lanes_cap, len(group))
            n_lanes = 1 << max(3, (n_lanes - 1).bit_length())
            for k, fat, res in group:
                p = dict(packs[k])
                act = p["bar_active"].copy()
                act[fat + 1 :] = False  # refutation needs only the prefix
                p["bar_active"] = act
                if wgl.exact_scan_safe(wgl.pad_B(p["B"]), cap, n_lanes):
                    safe_group.append((k, fat, res))
                    masked.append(p)
                    continue
                # Past the exact runner's measured fault boundary (see
                # wgl.exact_scan_safe): confirm via the chunked exact
                # path — short chunk scans, same content-decided kills.
                # An exact no-loss death anywhere in the prefix is a
                # final refutation; a surviving or lossy chunked run is
                # the collision/loss case, resolved like the batched
                # launch below.
                _pv(idxs[k], "confirm.chunked-exact", capacity=cap)
                r = wgl.chunked_analysis(
                    model, histories[idxs[k]], p, [cap], rounds=int(rounds),
                    fast=False, dedup_backend=dedup, deadline=deadline,
                    frontier_budget_mb=frontier_budget_mb,
                )
                _finish_confirmation(k, fat, res, r["valid?"] is False)
            group = safe_group
            for s0 in range(0, len(group), lanes_cap):
                sub = masked[s0 : s0 + lanes_cap]
                ctx = dict(what="ladder.confirm", engine="exact",
                           capacity=cap, lanes=len(sub))
                try:
                    gvalid, gfailed, glossy, _pk, _rs = faults.call_with_retry(
                        lambda: _launch("exact", cap, sub), ctx
                    )
                except faults.LaunchFailure as lf:
                    # no halving here: the bounded CPU sweep is the
                    # natural degradation for a failed confirm launch —
                    # it decides each refutation exactly, just slower
                    obs.counter(
                        "fault.launch.degraded", what="ladder.confirm",
                        capacity=cap, lanes=len(sub),
                        error=faults.describe(lf.cause),
                    )
                    for (k, fat, res) in group[s0 : s0 + lanes_cap]:
                        _finish_confirmation(k, fat, res, False)
                    continue
                for (k, fat, res), v, f2, lz in zip(
                    group[s0 : s0 + lanes_cap], gvalid, gfailed, glossy
                ):
                    _finish_confirmation(k, fat, res, f2 >= 0 and not lz)
        obs.span_event(
            "ladder.confirm.device", time.perf_counter() - t_conf,
            refutations=len(device_confirms), launches=launch_acc["launches"],
        )
        device_confirms = []  # resolved; keep them out of later checkpoints

    if cpu_fallback:
        t_fb = time.perf_counter()
        n_fb = 0
        for i, r in enumerate(results):
            if deadline is not None and deadline.expired():
                # The budget is spent: the remaining unknowns keep their
                # attributable causes instead of starting CPU sweeps the
                # deadline can no longer cover.
                if not deadline_tripped:
                    deadline_tripped = True
                    obs.counter("fault.deadline.trip")
                    obs.event("fault.deadline", at="cpu-fallback")
                break
            if (r is not None and r["valid?"] == "unknown"
                    and i not in confirm_futs and i not in device_resolved
                    and i not in early_confirmed and i not in no_fallback):
                # The config-set sweep, not the DFS: DFS backtracking goes
                # exponential on exactly the histories that overflow the
                # kernel (info-heavy invalid ones); the sweep is the same
                # frontier algorithm the kernel runs and degrades linearly.
                n_fb += 1
                results[i] = wgl_cpu.sweep_analysis(model, histories[i])
                _pv(i, "cpu-fallback", engine="sweep",
                    outcome=_prov.verdict_str(results[i].get("valid?")))
                _notify(i)
        if n_fb:
            obs.span_event(
                "ladder.cpu-fallback", time.perf_counter() - t_fb, histories=n_fb
            )

    def _degrade_confirmation(i: int, dev_res: dict, e: BaseException) -> None:
        """A confirmation worker died (twice, after the bounded
        resubmit): degrade THIS history only, never the batch.  With
        cpu_fallback (and budget left) the sweep re-runs in-process —
        if the worker died because the sweep itself raises
        deterministically (model bug, malformed history), the re-run
        raises the SAME error and still degrades this history alone
        (advisor r4)."""
        _pv(i, "confirm.degraded", error=type(e).__name__)
        if cpu_fallback and not (deadline is not None and deadline.expired()):
            try:
                results[i] = wgl_cpu.sweep_analysis(
                    model, histories[i], max_configs=confirm_max_configs
                )
                return
            except Exception as e2:  # noqa: BLE001
                results[i] = {
                    "valid?": "unknown",
                    "cause": (
                        "device refutation; confirmation sweep raised: "
                        f"{e2!r}"
                    ),
                    "kernel": dev_res.get("kernel"),
                }
                return
        results[i] = {
            "valid?": "unknown",
            "cause": f"device refutation; confirmation worker failed: {e!r}",
            "kernel": dev_res.get("kernel"),
        }

    t_drain = time.perf_counter()
    for i, (pool, fut, dev_res, t_submit, op_pos, ctx) in confirm_futs.items():
        with obs.attach(ctx):
            # The re-attached submit-time context: every event this
            # resolution emits carries the originating trace, even
            # though the sweep itself ran in a worker process.
            resubmitted = False
            while True:
                try:
                    if fut is None:
                        raise BrokenProcessPool(
                            "no confirmation worker available")
                    timeout = None
                    if deadline is not None:
                        # leave a small grace so nearly-done sweeps land;
                        # a timeout degrades this history alone (the
                        # checkpoint kept its descriptor for a resume)
                        timeout = max(5.0, deadline.remaining())
                    cpu_res = fut.result(timeout=timeout)
                    break
                except FutureTimeout:
                    deadline_tripped = True
                    confirm_degraded.add(i)
                    obs.counter("fault.deadline.trip")
                    obs.event("fault.deadline", at="confirm-drain", history=i)
                    _pv(i, "fault.deadline", at="confirm-drain")
                    results[i] = {
                        "valid?": "unknown",
                        "cause": (
                            "device refutation; deadline-exceeded before the "
                            "confirmation sweep finished"
                        ),
                        "kernel": dev_res.get("kernel"),
                    }
                    cpu_res = None
                    break
                except BrokenProcessPool:
                    # Reset only the pool the failure came from, and only
                    # while it is still installed: a stale future's error
                    # must not shut down a healthy rebuilt pool that other
                    # histories' confirmations are running on.
                    if pool is not None and pool is _CONFIRM_POOL:
                        _reset_confirm_pool()
                    if not resubmitted:
                        # The in-flight task died WITH the pool: one bounded
                        # resubmit against the rebuilt pool before degrading
                        # (a broken pool is usually one bad worker, not a
                        # deterministic task failure).
                        resubmitted = True
                        obs.counter("fault.confirm.resubmit", history=i)
                        _pv(i, "confirm.resubmit")
                        pool, fut = _submit_confirmation(
                            confirm_workers, model, list(histories[i]),
                            confirm_max_configs, op_pos,
                        )
                        continue
                    cpu_res = _degrade_confirmation(
                        i, dev_res,
                        BrokenProcessPool("confirmation worker failed twice"),
                    )
                    break
                except Exception as e:  # noqa: BLE001 — a dead worker must
                    # not lose the other histories' verdicts; this one only
                    cpu_res = _degrade_confirmation(i, dev_res, e)
                    break
            if cpu_res is None:
                continue
            # Queue latency: submit-to-resolution — how much of the sweep
            # ran concurrently with the remaining ladder stages vs in the
            # drain.
            obs.gauge(
                "confirm.queue_latency_s",
                round(time.perf_counter() - t_submit, 6), history=i,
            )
            results[i] = _resolve_confirmation(dev_res, cpu_res)
            # mode stays "worker" whether the future was harvested early
            # or in this drain: harvest TIMING is scheduling noise, and
            # digest parity compares decision paths across runs (the
            # drain itself is on the ladder.confirm.drain span).
            _pv(i, "confirm.resolved", mode="worker",
                outcome=_prov.verdict_str(results[i].get("valid?")))
        _notify(i)
    if confirm_futs:
        obs.span_event(
            "ladder.confirm.drain", time.perf_counter() - t_drain,
            confirmations=len(confirm_futs),
        )

    if packs and batch_caps and obs.active() is not None:
        # Per-round dedup timing for this run's first-rung candidate
        # shape, BOTH backends (one dedup.round span each): the sort-vs-
        # bucket comparison the kernel rounds themselves can't emit
        # (they run inside a jitted scan), surfaced in telemetry.json's
        # "dedup" table and tools/trace_summarize.py.  Telemetry-gated
        # AND once per shape per process: a couple ms, never a
        # recurring tax on long runs.
        pP = wgl._bucket(max(p["P"] for p in packs), list(P_BUCKETS))
        pG = wgl._bucket(max(p["G"] for p in packs), list(G_BUCKETS))
        shape = (batch_caps[0], pP, pG)
        if shape not in _PROBED_DEDUP_SHAPES:
            _PROBED_DEDUP_SHAPES.add(shape)
            t_probe = time.perf_counter()
            hashing.dedup_round_probe(batch_caps[0], pP, pG, (pP + 31) // 32)
            obs.span_event(
                "ladder.dedup-probe", time.perf_counter() - t_probe,
                capacity=batch_caps[0], active_backend=dedup,
            )
    if checkpoint_dir is not None and not trip_checkpointed:
        # Final checkpoint: "complete" unless a deadline trip left
        # resumable work (degraded confirmations keep their descriptors
        # so a resume can finish them; a complete checkpoint makes a
        # later resume idempotent — saved verdicts, no device work).
        # Skipped when a trip already wrote its resumable checkpoint —
        # overwriting it would destroy exactly the state a resume needs.
        confirm_futs = {
            i: t for i, t in confirm_futs.items() if i in confirm_degraded
        }
        _save_checkpoint(
            len(stages),
            complete=not deadline_tripped and not confirm_degraded,
        )
    out = [r if r is not None else {"valid?": "unknown"} for r in results]
    for i, r in enumerate(out):
        _prov.attach(r, prov_paths.get(i, []), engine=prov_engine,
                     config=prov_cfg)
    return out
