"""Batched, mesh-sharded linearizability checking.

The reference keeps per-key linearizability tractable by splitting the
workload into many small independent histories
(jepsen/src/jepsen/independent.clj:2-7, 103-238) and pmapping checkers over
them (independent.clj:285-307, checker.clj:95-97).  Here that becomes the
TPU's favourite shape: pack every history to common (B, P, G) buckets,
stack, and run ONE vmapped kernel over the batch, sharded across the mesh
on a ``histories`` axis.  Throughput scales with chips; each chip sweeps
its shard's frontiers in lockstep.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from jepsen_tpu import models as m
from jepsen_tpu.checker import wgl_cpu
from jepsen_tpu.ops import wgl


def make_mesh(n_devices: int | None = None, axis: str = "histories") -> Mesh:
    """A 1-D device mesh over the first ``n_devices`` devices."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis,))


def _stack(packs: list[dict], B: int, P: int, G: int) -> dict:
    padded = [wgl.pad_packed(p, B=B, P=P, G=G) for p in packs]
    out = {}
    out["init_state"] = np.stack([p["init_state"] for p in padded])
    out["bar_active"] = np.stack([p["bar_active"] for p in padded])
    for i, name in enumerate(["bar_f", "bar_v1", "bar_v2", "bar_slot"]):
        out[name] = np.stack([p["bar"][i] for p in padded])
    for i, name in enumerate(["mov_f", "mov_v1", "mov_v2", "mov_open"]):
        out[name] = np.stack([p["mov"][i] for p in padded])
    for i, name in enumerate(["grp_f", "grp_v1", "grp_v2"]):
        out[name] = np.stack([p["grp"][i] for p in padded])
    out["grp_open"] = np.stack([p["grp_open"] for p in padded])
    out["slot_lane"] = padded[0]["slot_lane"]
    out["slot_onehot"] = padded[0]["slot_onehot"]
    return out


_ARG_ORDER = [
    "init_state", "bar_active", "bar_f", "bar_v1", "bar_v2", "bar_slot",
    "mov_f", "mov_v1", "mov_v2", "mov_open",
    "grp_f", "grp_v1", "grp_v2", "grp_open",
    "slot_lane", "slot_onehot",
]

#: the async kernel replaces bar_active with a per-history n_active scalar
#: (inserted after init_state at the call site).
ASYNC_ARG_ORDER = [k for k in _ARG_ORDER if k != "bar_active"]


def batch_analysis(
    model: m.Model,
    histories: Sequence[Sequence[dict]],
    capacity: int | Sequence[int] = (64, 512),
    rounds: int = 8,
    mesh: Mesh | None = None,
    cpu_fallback: bool = True,
    exact_escalation: Sequence[int] | None = None,
    engine: str = "async",
) -> list[dict]:
    """Check many histories against one model in batched kernel launches.

    ``capacity`` lists the BATCHED (fast-kernel) capacity ladder: each
    stage re-batches only the still-unknown histories, padded to a power
    of two so compiles are reused.  ``engine`` picks the batched kernel:
    "async" (lane-asynchronous barrier stepping — lanes pay their own
    closure depth; the default: with candidate-order truncation it
    matches the sync engine's verdict quality and runs the full ladder
    ~15% faster) or "sync" (the barrier-scan kernel).  ``rounds`` bounds per-barrier
    closure depth on the "sync" engine and the exact escalation stage;
    the async engine's closure budget is its tick budget
    (wgl.async_ticks).  Histories still lossy after the last
    batched stage escalate one-by-one through the exact single-history
    kernel (``exact_escalation`` capacities; default one stage at 4x the
    last batch capacity; pass () to disable), then — when
    ``cpu_fallback`` — to the CPU config-set sweep.  Returns one
    knossos-shaped result per history, in order.
    """
    results: list[dict | None] = [None] * len(histories)
    packs: list[dict] = []
    idxs: list[int] = []
    for i, hist in enumerate(histories):
        try:
            p = wgl.pack(model, hist)
        except wgl.NotTensorizable as e:
            results[i] = {"valid?": "unknown", "cause": f"not tensorizable: {e}"}
            continue
        if p["B"] == 0:
            results[i] = {"valid?": True}
        else:
            packs.append(p)
            idxs.append(i)

    if engine not in ("sync", "async"):
        raise ValueError(f"unknown engine {engine!r}; expected 'sync' or 'async'")
    capacities = [capacity] if isinstance(capacity, int) else list(capacity)
    batch_caps, exact_caps = [int(c) for c in capacities], []
    if exact_escalation is None:
        exact_caps = [4 * batch_caps[-1]] if batch_caps else []
    elif exact_escalation:
        exact_caps = [int(c) for c in exact_escalation]
    pending = list(range(len(packs)))
    for batch_cap in batch_caps:
        if not pending:
            break
        sub = [packs[k] for k in pending]
        B = 1 << max(6, (max(p["B"] for p in sub) - 1).bit_length())
        P = wgl._bucket(max(p["P"] for p in sub), [8, 16, 32, 64, 128])
        G = wgl._bucket(max(p["G"] for p in sub), [4, 8, 16, 32, 64])
        stacked = _stack(sub, B, P, G)
        n = len(sub)
        # Pad the batch axis to a power of two (and a mesh multiple) so the
        # vmapped kernel compiles once per bucket, not once per batch size.
        n_pad = 1 << max(3, (n - 1).bit_length())
        if mesh is not None:
            shard = mesh.devices.size
            n_pad = ((n_pad + shard - 1) // shard) * shard
        if n_pad != n:
            for k in stacked:
                if k in ("slot_lane", "slot_onehot"):
                    continue
                reps = np.concatenate(
                    [stacked[k]] + [stacked[k][-1:]] * (n_pad - n), axis=0
                )
                stacked[k] = reps
        args = [stacked[k] for k in _ARG_ORDER]
        if mesh is not None:
            axis = mesh.axis_names[0]
            spec = NamedSharding(mesh, PartitionSpec(axis))
            rep = NamedSharding(mesh, PartitionSpec())
            args = [
                jax.device_put(a, rep if k in ("slot_lane", "slot_onehot") else spec)
                for k, a in zip(_ARG_ORDER, args)
            ]
        W = (P + 31) // 32
        if engine == "async":
            T = wgl.async_ticks(B)
            n_actives = np.array([p["bar_active"].sum() for p in sub], np.int32)
            if n_pad != n:
                n_actives = np.concatenate([n_actives, np.repeat(n_actives[-1:], n_pad - n)])
            order = ASYNC_ARG_ORDER
            by_name = dict(zip(_ARG_ORDER, args))
            a_args = [by_name["init_state"], jnp.asarray(n_actives)] + [
                by_name[k] for k in order[1:]
            ]
            if mesh is not None:
                axis = mesh.axis_names[0]
                spec = NamedSharding(mesh, PartitionSpec(axis))
                a_args[1] = jax.device_put(np.asarray(a_args[1]), spec)
            runner = wgl.async_runner(sub[0]["step"], batch_cap, T, B, P, G, W)
            valid, failed_at, lossy, peak = runner(*a_args)
        else:
            runner = wgl.batched_runner(sub[0]["step"], batch_cap, int(rounds), P, G, W)
            valid, failed_at, lossy, peak = runner(*args)
        valid = np.asarray(valid)[:n]
        failed_at = np.asarray(failed_at)[:n]
        lossy = np.asarray(lossy)[:n]
        peak = np.asarray(peak)[:n]
        still = []
        for j, k in enumerate(pending):
            i = idxs[k]
            stats = {"frontier-peak": int(peak[j]), "capacity": batch_cap, "lossy?": bool(lossy[j])}
            if failed_at[j] < 0 and valid[j]:
                results[i] = {"valid?": True, "kernel": stats}
            elif failed_at[j] >= 0 and not lossy[j]:
                op = histories[i][int(packs[k]["bar_opid"][int(failed_at[j])])]
                results[i] = {"valid?": False, "op": op, "kernel": stats}
            else:
                still.append(k)
                results[i] = {
                    "valid?": "unknown",
                    "cause": "frontier capacity or closure rounds exhausted",
                    "kernel": stats,
                }
        pending = still
    # Whatever survives every batched stage escalates one-by-one through
    # the EXACT single-history kernel (cost-prioritized truncation, full
    # domination) — knossos-style competition, against frontier sizes.
    for k in pending:
        i = idxs[k]
        if exact_caps:
            results[i] = wgl.analysis(
                model, histories[i], capacity=exact_caps, rounds=rounds
            )

    if cpu_fallback:
        for i, r in enumerate(results):
            if r is not None and r["valid?"] == "unknown":
                # The config-set sweep, not the DFS: DFS backtracking goes
                # exponential on exactly the histories that overflow the
                # kernel (info-heavy invalid ones); the sweep is the same
                # frontier algorithm the kernel runs and degrades linearly.
                results[i] = wgl_cpu.sweep_analysis(model, histories[i])
    return [r if r is not None else {"valid?": "unknown"} for r in results]
