"""Microbenchmark dedup primitives on the TPU at WGL frontier shapes."""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

L = int(sys.argv[1]) if len(sys.argv) > 1 else 256  # vmap lanes (histories)
N = int(sys.argv[2]) if len(sys.argv) > 2 else 1088  # candidate rows
T = int(sys.argv[3]) if len(sys.argv) > 3 else 256  # hash-table slots


def timeit(name, fn, *args):
    out = fn(*args)
    jax.block_until_ready(out)
    ts = []
    for _ in range(5):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        ts.append(time.perf_counter() - t0)
    print(f"{name:34s} {min(ts)*1e3:8.2f} ms")
    return out


key = jax.random.PRNGKey(0)
dead = jax.random.bernoulli(key, 0.5, (L, N)).astype(jnp.uint32)
h1 = jax.random.randint(key, (L, N), 0, 1 << 30).astype(jnp.uint32)
h2 = jax.random.randint(key, (L, N), 0, 1 << 30).astype(jnp.uint32)
cost = jax.random.randint(key, (L, N), 0, 1000).astype(jnp.uint32)
iota = jnp.broadcast_to(jnp.arange(N, dtype=jnp.int32), (L, N))


@jax.jit
def sort4(dead, h1, h2, cost, iota):
    return jax.vmap(lambda *a: jax.lax.sort(a, num_keys=4))(dead, h1, h2, cost, iota)


@jax.jit
def sort2(h1, iota):
    return jax.vmap(lambda *a: jax.lax.sort(a, num_keys=1))(h1, iota)


@jax.jit
def scatter_min(h1, cost):
    slot = (h1 % T).astype(jnp.int32)
    packed = (cost << 12) | (jnp.arange(N, dtype=jnp.uint32) & 0xFFF)

    def one(slot, packed):
        return jnp.full((T,), jnp.uint32(0xFFFFFFFF)).at[slot].min(packed)

    return jax.vmap(one)(slot, packed)


@jax.jit
def onehot_min(h1, cost):
    slot = (h1 % T).astype(jnp.int32)

    def one(slot, cost):
        oh = slot[:, None] == jnp.arange(T)[None, :]
        return jnp.where(oh, cost[:, None], jnp.uint32(0xFFFFFFFF)).min(axis=0)

    return jax.vmap(one)(slot, cost)


@jax.jit
def gather_back(table, h1):
    slot = (h1 % T).astype(jnp.int32)
    return jax.vmap(lambda t, s: t[s])(table, slot)


@jax.jit
def cumsum_compact(dead, h1):
    keep = dead == 0

    def one(keep, vals):
        pos = jnp.where(keep, jnp.cumsum(keep) - 1, N)
        return jnp.zeros((N + 1,), vals.dtype).at[pos].set(vals)[:N]

    return jax.vmap(one)(keep, h1)


print(f"devices={jax.devices()} L={L} N={N} T={T}")
timeit("4-key sort (5 operands)", sort4, dead, h1, h2, cost, iota)
timeit("1-key sort (2 operands)", sort2, h1, iota)
tab = timeit("scatter-min into T slots", scatter_min, h1, cost)
timeit("one-hot min reduce [N,T]", onehot_min, h1, cost)
timeit("gather table back", gather_back, tab, h1)
timeit("cumsum compaction scatter", cumsum_compact, dead, h1)
