"""Bisect where the batched WGL kernel's time goes."""

import functools
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax
import jax.numpy as jnp
import numpy as np

from genhist import corrupt, valid_register_history
from jepsen_tpu import models as m
from jepsen_tpu.ops import wgl
from jepsen_tpu.ops.hashing import frontier_update
from jepsen_tpu.parallel import batch as pbatch

I32 = jnp.int32
U32 = jnp.uint32


def timeit(name, fn, *args, reps=3):
    out = fn(*args)
    jax.block_until_ready(out)
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        ts.append(time.perf_counter() - t0)
    print(f"{name:44s} {min(ts)*1e3:9.2f} ms")
    return out


model = m.CASRegister(None)
hists = []
for i in range(256):
    hh = valid_register_history(40, 4, seed=i, info_rate=0.1)
    if i % 5 == 4:
        hh = corrupt(hh, seed=i)
    hists.append(hh)
packs = [wgl.pack(model, hh) for hh in hists]
B, P, G = 64, 8, 8
W = 1
stacked = pbatch._stack(packs, B, P, G)
args = [stacked[k] for k in pbatch._ARG_ORDER]
step = packs[0]["step"]
F = 64
R = 8


def variant(R_, n_sorts=None, window=None, do_dominate=None):
    core = functools.partial(wgl._run_core, step, F, R_, P, G, W)
    axes = (0,) * 14 + (None, None)
    return jax.jit(jax.vmap(core, in_axes=axes))


print(f"devices={jax.devices()}")
for R_ in (8, 4, 2, 1):
    timeit(f"full kernel R={R_}", variant(R_), *args)


# Scan skeleton: barrier loop with NO while_loop — single expand+update.
def skeleton(init_state, bar_active, bar_f, bar_v1, bar_v2, bar_slot,
             mov_f, mov_v1, mov_v2, mov_open, grp_f, grp_v1, grp_v2,
             grp_open, slot_lane, slot_onehot):
    eye_g = jnp.eye(G, dtype=I32)
    slot_mask = slot_onehot.sum(axis=1)

    def barrier(carry, xs):
        state, fok, fcr, alive = carry
        xbar_slot, xmov_f, xmov_v1, xmov_v2, xmov_open, xgrp_open = xs
        cat = wgl.expand_candidates(
            step, eye_g, slot_lane, slot_mask, slot_onehot,
            state, fok, fcr, alive,
            xmov_f, xmov_v1, xmov_v2, xmov_open,
            grp_f, grp_v1, grp_v2, xgrp_open,
        )
        s2, fo2, fc2, a2, ovf, fp = frontier_update(*cat, F)
        return (s2, fo2, fc2, a2), ovf

    state0 = jnp.full((F,), init_state, I32)
    fok0 = jnp.zeros((F, W), U32)
    fcr0 = jnp.zeros((F, G), I32)
    alive0 = jnp.zeros((F,), bool).at[0].set(True)
    xs = (bar_slot, mov_f, mov_v1, mov_v2, mov_open, grp_open)
    (state, fok, fcr, alive), ovf = jax.lax.scan(barrier, (state0, fok0, fcr0, alive0), xs)
    return alive.any(), ovf.any()


sk = jax.jit(jax.vmap(skeleton, in_axes=(0,) * 14 + (None, None)))
timeit("scan skeleton: 64 barriers x 1 round", sk, *args)


# While-loop-free kernel: fixed 2 rounds per barrier, cond replaced by mask.
def fixed2(init_state, bar_active, bar_f, bar_v1, bar_v2, bar_slot,
           mov_f, mov_v1, mov_v2, mov_open, grp_f, grp_v1, grp_v2,
           grp_open, slot_lane, slot_onehot):
    eye_g = jnp.eye(G, dtype=I32)
    slot_mask = slot_onehot.sum(axis=1)

    def barrier(carry, xs):
        state, fok, fcr, alive, failed_at = carry
        b_idx, active, xbar_slot, xmov_f, xmov_v1, xmov_v2, xmov_open, xgrp_open = xs
        for _ in range(2):
            cat = wgl.expand_candidates(
                step, eye_g, slot_lane, slot_mask, slot_onehot,
                state, fok, fcr, alive,
                xmov_f, xmov_v1, xmov_v2, xmov_open,
                grp_f, grp_v1, grp_v2, xgrp_open,
            )
            state, fok, fcr, alive, ovf, fp = frontier_update(*cat, F)
        lane = xbar_slot // 32
        bitmask = (U32(1) << (xbar_slot % 32).astype(U32))
        lane_vals = jnp.take(fok, lane[None], axis=1)[:, 0]
        a3 = alive & ((lane_vals & bitmask) != 0)
        clear = jnp.where(jnp.arange(W) == lane, bitmask, U32(0))
        fo3 = fok & ~clear[None, :]
        dead = ~a3.any()
        failed2 = jnp.where(dead & (failed_at < 0) & active, b_idx, failed_at)
        return (state, fo3, fcr, a3, failed2), None

    state0 = jnp.full((F,), init_state, I32)
    fok0 = jnp.zeros((F, W), U32)
    fcr0 = jnp.zeros((F, G), I32)
    alive0 = jnp.zeros((F,), bool).at[0].set(True)
    xs = (jnp.arange(B, dtype=I32), bar_active, bar_slot, mov_f, mov_v1,
          mov_v2, mov_open, grp_open)
    (state, fok, fcr, alive, failed_at), _ = jax.lax.scan(
        barrier, (state0, fok0, fcr0, alive0, jnp.int32(-1)), xs
    )
    return alive.any(), failed_at


fx = jax.jit(jax.vmap(fixed2, in_axes=(0,) * 14 + (None, None)))
timeit("no-while kernel: 64 barriers x 2 rounds", fx, *args)
