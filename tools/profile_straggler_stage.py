"""What's the cheapest way to resolve the ladder's stragglers?

Isolates the histories still unknown after (128, 512) and times variant
final stages: async/sync engines at 1024/2048/4096, and per-history
chunked_analysis with carried frontiers.
"""

import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from genhist import corrupt, valid_register_history
from jepsen_tpu import models as m
from jepsen_tpu.ops import wgl
from jepsen_tpu.parallel import batch as pbatch

N, OPS, PROCS, INFO, NV, CORR = 128, 100, 8, 0.3, 8, 4


def main():
    model = m.CASRegister(None)
    hists = []
    for i in range(N):
        hh = valid_register_history(OPS, PROCS, seed=i, info_rate=INFO, n_values=NV)
        if i % CORR == CORR - 1:
            hh = corrupt(hh, seed=i)
        hists.append(hh)

    base = pbatch.batch_analysis(
        model, hists, capacity=(128, 512), cpu_fallback=False,
        exact_escalation=(), confirm_refutations=False,
    )
    strag = [hh for hh, r in zip(hists, base) if r["valid?"] == "unknown"]
    print(f"{len(strag)} stragglers after (128, 512)")

    which = sys.argv[1:] or None
    for label, fn in [
        ("async cap1024 (batched)", lambda: pbatch.batch_analysis(
            model, strag, capacity=(1024,), cpu_fallback=False,
            exact_escalation=(), confirm_refutations=False)),
        ("async cap2048 (batched)", lambda: pbatch.batch_analysis(
            model, strag, capacity=(2048,), cpu_fallback=False,
            exact_escalation=(), confirm_refutations=False)),
        ("sync cap2048 (batched)", lambda: pbatch.batch_analysis(
            model, strag, capacity=(2048,), cpu_fallback=False,
            exact_escalation=(), confirm_refutations=False, engine="sync")),
        ("async cap4096 (batched)", lambda: pbatch.batch_analysis(
            model, strag, capacity=(4096,), cpu_fallback=False,
            exact_escalation=(), confirm_refutations=False)),
        ("chunked (512,2048,4096) cb=16 per hist", lambda: [
            wgl.analysis(model, hh, capacity=(512, 2048, 4096), chunk_barriers=16)
            for hh in strag]),
        ("chunked (512,2048,4096) cb=8 per hist", lambda: [
            wgl.analysis(model, hh, capacity=(512, 2048, 4096), chunk_barriers=8)
            for hh in strag]),
    ]:
        if which and not any(w in label for w in which):
            continue
        rs = fn()  # warm
        best = None
        for _ in range(2):
            t0 = time.perf_counter()
            rs = fn()
            best = min(best or 9e9, time.perf_counter() - t0)
        unk = sum(1 for r in rs if r["valid?"] == "unknown")
        print(f"{label:42s} {best*1e3:8.1f} ms  unknowns={unk}")


if __name__ == "__main__":
    main()
