"""Multi-lane exact fault sweep: measure the batched-exact fault grid.

The round-5 fault boundary (``ops/wgl.py:exact_scan_safe``) was
measured on SINGLE-lane launches; the multi-lane guard is a lanes x
capacity PRODUCT-MODEL inference with no multi-lane fault point
confirming it — conservative by construction, and the cost is routing:
mid-size batched-exact launches that may in fact be safe get re-routed
to the chunked path (PERF.md round 6 "exact_scan_safe lane-count
conservatism").  This tool runs the deferred measurement: a grid of
(lanes x capacity x barriers) REAL batched-exact launches, each in its
own subprocess (a genuine TPU-worker fault kills the child, never the
sweep), recording pass/fault per cell into a JSON artifact whose
schema ``ops/wgl.py:validate_exact_grid`` owns.  Point
``JEPSEN_TPU_EXACT_GRID`` at the artifact and ``exact_scan_safe``
routes by MEASURED cells first (fault-domination beats pass-domination
beats the product model) — the chip-day win-back is one sweep plus one
env var.

  # the chip sweep (sized like the round-5 single-lane grid, x lanes):
  python tools/fault_sweep.py --lanes 1,8,32 --capacity 512,1024,2048 \\
      --barriers 2048,4096,8192 --out store/exact-grid.json

  # CI/CPU: validate schema + routing without launching anything
  python tools/fault_sweep.py --dry-run

Each cell launches ``lanes`` copies of one ``barriers``-op valid
register history through ``wgl.exact_batched_runner`` at ``capacity``
(the exact kernel shape the guard protects).  Cell outcomes: ``ok``
(clean exit), ``fault`` (crash/abort — the measurement), or a timeout
(recorded as a fault, conservatively, with ``timeout: true``).  The
artifact carries the machine fingerprint (obs.regress), so CPU-run
grids can never masquerade as chip measurements when routing reads
them.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))
sys.path.insert(0, str(ROOT / "tools"))


def _build_cell_args(lanes: int, capacity: int, barriers: int):
    """Pack a ``barriers``-op valid register history and stack ``lanes``
    copies at the cell's geometry — exactly what a batched-exact ladder
    stage would launch (parallel.batch._stack at the bucketed shapes,
    batch axis padded like _launch_impl pads it)."""
    from genhist import valid_register_history

    from jepsen_tpu import models as m
    from jepsen_tpu.ops import wgl
    from jepsen_tpu.parallel import batch as pbatch

    hist = valid_register_history(int(barriers), 4, seed=1234)
    packed = wgl.pack(m.CASRegister(None), hist)
    B, P, G = pbatch.bucket_geometry(packed["B"], packed["P"], packed["G"])
    stacked = pbatch._stack([packed] * int(lanes), B, P, G)
    return stacked, B, P, G


def run_cell(lanes: int, capacity: int, barriers: int, rounds: int = 8,
             telemetry_dir: str | None = None) -> int:
    """Execute ONE grid cell in-process (the subprocess entry): build
    the launch, run it to completion, exit 0.  A TPU-worker fault
    kills this process — the parent records the cell as a fault.
    With ``telemetry_dir`` the cell records its span stream there
    (obs.recording), so the parent can fold each cell's stage rollup
    into the grid artifact."""
    import contextlib

    import jax.numpy as jnp  # noqa: F401 — initialize the backend here

    from jepsen_tpu import obs
    from jepsen_tpu.ops import wgl
    from jepsen_tpu.parallel.batch import _ARG_ORDER

    rec = (obs.recording(telemetry_dir) if telemetry_dir
           else contextlib.nullcontext())
    with rec:
        stacked, B, P, G = _build_cell_args(lanes, capacity, barriers)
        W = (P + 31) // 32
        runner = wgl.exact_batched_runner(
            _step_of(stacked), int(capacity), int(rounds), P, G, W
        )
        args = [stacked[k] for k in _ARG_ORDER]
        with obs.span("fault_sweep.cell", lanes=int(lanes),
                      capacity=int(capacity), barriers=int(barriers)):
            valid, _failed_at, _lossy, _peak = runner(*args)
            valid.block_until_ready()
    print(f"cell ok: lanes={lanes} capacity={capacity} barriers={barriers} "
          f"valid={[bool(v) for v in valid][:4]}...")
    return 0


def _cell_telemetry(cell: dict, cell_dir: Path) -> None:
    """Fold a finished cell's recorded telemetry into its grid entry:
    the raw ``telemetry.jsonl`` path (flight-analyzer input — the
    sweep's JSON artifact indexes every child stream) and the per-cell
    stage rollup (span name -> seconds, obs.regress.stage_rollup), so
    a faulting cell's last recorded stage is visible WITHOUT replaying
    the child.  Best-effort: a cell that died before its recorder
    flushed simply carries no rollup."""
    jsonl = cell_dir / "telemetry.jsonl"
    if jsonl.is_file():
        cell["telemetry"] = str(jsonl)
    summary_p = cell_dir / "telemetry.json"
    summary = None
    if summary_p.is_file():
        try:
            summary = json.loads(summary_p.read_text())
        except (OSError, ValueError):
            summary = None
    elif jsonl.is_file():
        # the child faulted before Recorder.close() rolled the stream
        # up — roll up whatever lines made it to disk
        try:
            from jepsen_tpu.obs.summary import summarize
            from jepsen_tpu.obs.trace import read_jsonl_events

            events, _skipped = read_jsonl_events(jsonl)
            summary = summarize(events)
        except Exception:  # noqa: BLE001 — telemetry stays best-effort
            summary = None
    if summary is not None:
        try:
            from jepsen_tpu.obs import regress

            stages, metrics = regress.stage_rollup(summary)
            cell["stages"] = {k: round(v, 6) for k, v in stages.items()}
            if metrics:
                cell["stage_metrics"] = {
                    k: round(v, 6) for k, v in metrics.items()
                }
        except Exception:  # noqa: BLE001 — telemetry stays best-effort
            pass


def _step_of(stacked) -> object:
    """The packed step function is per-model, not per-lane: recover it
    the way the ladder does (pack() attaches it)."""
    from jepsen_tpu import models as m
    from jepsen_tpu.models import tensor as tmodels

    return tmodels.tensor_model_for(m.CASRegister(None)).step


def _machine_fingerprint() -> dict:
    try:
        from jepsen_tpu.obs import regress

        return regress.fingerprint()
    except Exception:  # noqa: BLE001 — a grid without a fingerprint is
        # still valid; routing never reads it (humans and PERF.md do)
        return {}


def sweep(lanes_list, caps, bars, out_path: Path, timeout_s: float,
          rounds: int = 8) -> dict:
    """Run the full grid, one subprocess per cell, and write the
    artifact after EVERY cell (a crashed sweep loses nothing)."""
    cells = []
    grid = {
        "version": 1,
        "kind": "exact-fault-grid",
        "ts": time.time(),
        "fingerprint": _machine_fingerprint(),
        "workload": {"model": "cas-register", "rounds": int(rounds)},
        "cells": cells,
    }
    total = len(lanes_list) * len(caps) * len(bars)
    tele_root = out_path.parent / (out_path.stem + "-telemetry")
    i = 0
    for lanes in lanes_list:
        for cap in caps:
            for B in bars:
                i += 1
                print(f"[{i}/{total}] lanes={lanes} capacity={cap} "
                      f"barriers={B} ...", flush=True)
                t0 = time.time()
                cell = {"lanes": int(lanes), "capacity": int(cap),
                        "barriers": int(B)}
                cell_dir = tele_root / f"l{lanes}-c{cap}-b{B}"
                cell_dir.mkdir(parents=True, exist_ok=True)
                try:
                    proc = subprocess.run(
                        [sys.executable, str(Path(__file__).resolve()),
                         "--run-cell", f"{lanes},{cap},{B}",
                         "--rounds", str(rounds),
                         "--telemetry-dir", str(cell_dir)],
                        timeout=timeout_s, capture_output=True, text=True,
                    )
                    cell["ok"] = proc.returncode == 0
                    if proc.returncode != 0:
                        cell["exit_code"] = proc.returncode
                        cell["stderr_tail"] = (proc.stderr or "")[-500:]
                except subprocess.TimeoutExpired:
                    # a hung worker is indistinguishable from a wedged
                    # fault from the router's seat: conservative fault
                    cell["ok"] = False
                    cell["timeout"] = True
                cell["seconds"] = round(time.time() - t0, 2)
                # the child's span stream + stage rollup ride the cell:
                # a fault's last recorded stage is in the artifact
                _cell_telemetry(cell, cell_dir)
                cells.append(cell)
                out_path.parent.mkdir(parents=True, exist_ok=True)
                out_path.write_text(json.dumps(grid, indent=1),
                                    encoding="utf-8")
                print(f"    -> {'ok' if cell['ok'] else 'FAULT'} "
                      f"({cell['seconds']}s)", flush=True)
    print(f"grid written: {out_path} ({len(cells)} cells)")
    return grid


def dry_run() -> int:
    """CPU validation of the artifact schema and the routing override,
    launch-free: write a tiny grid with KNOWN verdicts, point
    ``JEPSEN_TPU_EXACT_GRID`` at it, and assert ``exact_scan_safe``
    honors measured cells over the product model (both directions)
    plus falls back where the grid is silent."""
    import tempfile

    from jepsen_tpu.ops import wgl

    grid = {
        "version": 1,
        "kind": "exact-fault-grid",
        "fingerprint": _machine_fingerprint(),
        "cells": [
            # a measured PASS the product model would conservatively
            # refuse (the exact win-back this tool exists for):
            {"lanes": 8, "capacity": 1024, "barriers": 2048, "ok": True},
            # a measured FAULT the product model would allow — on an
            # axis combination INCOMPARABLE to the pass cell (monotone
            # consistency: a fault below a pass would be noise):
            {"lanes": 64, "capacity": 64, "barriers": 1024, "ok": False},
        ],
    }
    wgl.validate_exact_grid(grid)  # schema self-check
    for bad, defect in [
        ({}, "object"),
        ({"version": 2, "kind": "exact-fault-grid", "cells": [{}]}, "version"),
        ({"version": 1, "kind": "exact-fault-grid", "cells": []}, "cells"),
        ({"version": 1, "kind": "exact-fault-grid",
          "cells": [{"lanes": 1, "capacity": 1, "barriers": 1, "ok": "y"}]},
         "ok"),
    ]:
        try:
            wgl.validate_exact_grid(bad)
        except ValueError:
            pass
        else:
            print(f"dry-run FAILED: invalid grid accepted ({defect})",
                  file=sys.stderr)
            return 1
    with tempfile.TemporaryDirectory() as td:
        path = Path(td) / "grid.json"
        path.write_text(json.dumps(grid), encoding="utf-8")
        old = os.environ.get(wgl.EXACT_GRID_ENV)
        os.environ[wgl.EXACT_GRID_ENV] = str(path)
        try:
            checks = [
                # measured pass dominates: product model says False
                # (8 lanes x 1024 cap x 2048 B = 16M rows), grid says ok
                (wgl.exact_scan_safe(2048, 1024, lanes=8), True,
                 "measured pass honored"),
                # dominated by the pass cell too (componentwise <=)
                (wgl.exact_scan_safe(1024, 512, lanes=4), True,
                 "pass-domination honored"),
                # measured fault dominates a LARGER query the product
                # model would have allowed (rows < 8M, B < 4096)
                (wgl.exact_scan_safe(1024, 64, lanes=64), False,
                 "measured fault honored"),
                # uncovered query falls back to the product model
                (wgl.exact_scan_safe(8192, 64, lanes=1), False,
                 "product-model fallback (B >= 8192)"),
                (wgl.exact_scan_safe(128, 64, lanes=1), True,
                 "product-model fallback (small shape)"),
            ]
        finally:
            if old is None:
                os.environ.pop(wgl.EXACT_GRID_ENV, None)
            else:
                os.environ[wgl.EXACT_GRID_ENV] = old
    rc = 0
    for got, want, what in checks:
        status = "ok" if got == want else "FAILED"
        print(f"  {status}: {what} (got {got}, want {want})")
        if got != want:
            rc = 1
    # an invalid file must warn-and-fall-back, never crash the router
    with tempfile.TemporaryDirectory() as td:
        path = Path(td) / "bad.json"
        path.write_text("{not json", encoding="utf-8")
        os.environ[wgl.EXACT_GRID_ENV] = str(path)
        try:
            import warnings as _w

            with _w.catch_warnings():
                _w.simplefilter("ignore")
                ok = wgl.exact_scan_safe(128, 64) is True
        finally:
            os.environ.pop(wgl.EXACT_GRID_ENV, None)
    print(f"  {'ok' if ok else 'FAILED'}: invalid grid file falls back "
          "to the product model")
    rc = rc or (0 if ok else 1)
    print("dry-run " + ("OK" if rc == 0 else "FAILED"))
    return rc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--lanes", default="1,8,32",
                    help="comma-separated lane counts (default 1,8,32)")
    ap.add_argument("--capacity", default="512,1024,2048",
                    help="comma-separated capacities (default 512,1024,2048)")
    ap.add_argument("--barriers", default="2048,4096,8192",
                    help="comma-separated barrier counts "
                         "(default 2048,4096,8192)")
    ap.add_argument("--out", default="store/exact-grid.json",
                    help="grid artifact path (default store/exact-grid.json)")
    ap.add_argument("--timeout-s", type=float, default=600.0,
                    help="per-cell wall-clock bound; expiry records a "
                         "conservative fault (default 600)")
    ap.add_argument("--rounds", type=int, default=8,
                    help="exact-engine closure rounds per barrier (default 8)")
    ap.add_argument("--dry-run", action="store_true",
                    help="validate schema + exact_scan_safe routing on "
                         "CPU, no launches")
    ap.add_argument("--run-cell", default=None, metavar="L,C,B",
                    help="(internal) run one cell in-process and exit")
    ap.add_argument("--telemetry-dir", default=None,
                    help="(internal) record the cell's span stream here")
    a = ap.parse_args(argv)
    if a.run_cell:
        lanes, cap, bars = (int(x) for x in a.run_cell.split(","))
        return run_cell(lanes, cap, bars, rounds=a.rounds,
                        telemetry_dir=a.telemetry_dir)
    if a.dry_run:
        return dry_run()
    lanes_list = [int(x) for x in a.lanes.split(",") if x]
    caps = [int(x) for x in a.capacity.split(",") if x]
    bars = [int(x) for x in a.barriers.split(",") if x]
    sweep(lanes_list, caps, bars, Path(a.out), a.timeout_s, rounds=a.rounds)
    return 0


if __name__ == "__main__":
    sys.exit(main())
