#!/usr/bin/env python
"""Guard the tier-1 suite's wall-clock budget.

The tier-1 suite (``python -m pytest tests/ -q``) runs under a hard
870 s cap; the suite already sits at ~780-850 s, so a handful of
carelessly-added compile geometries can silently blow it.  This script
turns "recorded suite time" into an exit code so CI fails LOUDLY and
names the slowest tests instead:

    python -m pytest tests/ | tee tier1.log     # --durations=25 is in
                                                # pyproject addopts
    python tools/check_tier1_budget.py tier1.log

It parses pytest's final summary line ("... in 812.34s (0:13:32)")
and, when the log carries a ``slowest durations`` block, echoes the
top entries in the failure message so the offender is named in the CI
output.  ``--seconds`` bypasses log parsing for drivers that timed the
suite themselves.  Budget: ``--budget`` > ``JEPSEN_TPU_TIER1_BUDGET_S``
env > 850 (headroom under the 870 s cap).

Two structural guards ride along with the wall-clock check:

  * REQUIRED FILES — tier-1 runs with
    ``--continue-on-collection-errors``, so a syntax error in a new
    test file silently shrinks the suite instead of failing it.  Every
    file in ``REQUIRED_FILES`` must appear in the parsed log (so its
    tests ran and its durations land in the report) or the gate fails.
  * GEOMETRY AUDIT — each distinct ``capacity=(...)`` tuple is a rung
    compile; the suite stays under budget by SHARING compile
    geometries across files (conftest's 8-device mesh + the common
    ``(64, 256)`` service shape).  The files in ``GEOMETRY_AUDITED``
    are AST-scanned for capacity literals; any tuple no OTHER tier-1
    test file uses is a fresh compile cache entry the whole suite pays
    for, and the gate fails loudly naming it.
"""

from __future__ import annotations

import argparse
import ast
import json
import os
import re
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

DEFAULT_BUDGET_S = 850.0

#: files whose tests MUST have run (collection errors are non-fatal in
#: tier-1, so a broken import would otherwise vanish silently).
REQUIRED_FILES = ("tests/test_streaming.py", "tests/test_fleetview.py")

#: new test files whose compile geometries must already be paid for by
#: the rest of the suite (see the geometry audit in the docstring).
GEOMETRY_AUDITED = ("tests/test_streaming.py", "tests/test_fleetview.py")

#: pytest's terminal summary: "= 123 passed, 2 skipped in 812.34s (0:13:32) ="
_SUMMARY_RE = re.compile(r"\bin (\d+(?:\.\d+)?)s(?: \(\d+:\d+(?::\d+)?\))?\s*=*\s*$")
#: a "slowest durations" table row: "12.34s call     tests/test_x.py::test_y"
_DURATION_RE = re.compile(r"^\s*(\d+\.\d+)s\s+(?:call|setup|teardown)\s+(\S+)")


def parse_log(text: str) -> tuple[float | None, list[tuple[float, str]]]:
    """(recorded suite seconds, [(seconds, test id), ...] slowest-first).

    The summary is searched from the end so an embedded sub-pytest run
    (some tier-1 tests shell out to pytest) can't shadow the real one.
    """
    seconds = None
    for line in reversed(text.splitlines()):
        m = _SUMMARY_RE.search(line)
        if m:
            seconds = float(m.group(1))
            break
    durations = [
        (float(m.group(1)), m.group(2))
        for line in text.splitlines()
        if (m := _DURATION_RE.match(line))
    ]
    durations.sort(reverse=True)
    return seconds, durations


def capacity_literals(path: Path) -> set[tuple[int, ...]]:
    """Every compile geometry a test file pins statically: int-tuple
    values of ``capacity=`` keywords, ``CAP``-named module constants,
    and ``"capacity"``/``"stream-capacity"`` dict entries."""
    try:
        tree = ast.parse(path.read_text(encoding="utf-8"), filename=str(path))
    except (OSError, SyntaxError):
        return set()

    def tup(node: ast.expr) -> tuple[int, ...] | None:
        if isinstance(node, (ast.Tuple, ast.List)) and node.elts and all(
                isinstance(e, ast.Constant) and isinstance(e.value, int)
                for e in node.elts):
            return tuple(e.value for e in node.elts)
        return None

    out: set[tuple[int, ...]] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            for kw in node.keywords:
                if kw.arg == "capacity" and (t := tup(kw.value)):
                    out.add(t)
        elif isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and "CAP" in tgt.id.upper() \
                        and (t := tup(node.value)):
                    out.add(t)
        elif isinstance(node, ast.Dict):
            for k, v in zip(node.keys, node.values):
                if isinstance(k, ast.Constant) and isinstance(k.value, str) \
                        and "capacity" in k.value and (t := tup(v)):
                    out.add(t)
    return out


def geometry_audit(tests_dir: Path) -> list[str]:
    """Problem strings for every audited file that pins a compile
    geometry no other tier-1 test file uses (a fresh rung compile the
    suite's budget was not paying for)."""
    audited = {tests_dir.parent / f for f in GEOMETRY_AUDITED}
    shared: set[tuple[int, ...]] = set()
    for p in sorted(tests_dir.glob("test_*.py")):
        if p not in audited:
            shared |= capacity_literals(p)
    problems = []
    for p in sorted(audited):
        if not p.exists():
            continue  # REQUIRED_FILES covers absence via the run log
        for cap in sorted(capacity_literals(p) - shared):
            problems.append(
                f"{p.relative_to(tests_dir.parent)} pins capacity "
                f"{cap}, which no other tier-1 test file compiles — "
                "use a suite-shared geometry (e.g. (64, 256)) or move "
                "the test behind the slow marker")
    return problems


def missing_required(text: str) -> list[str]:
    """REQUIRED_FILES that never appear in the suite log (collection
    error or deletion — either way their tests silently didn't run)."""
    return [f for f in REQUIRED_FILES if f not in text]


def append_ledger(seconds: float, budget: float,
                  durations: list[tuple[float, str]],
                  ledger: str | None = None) -> None:
    """Record this suite run in the perf-regression ledger (kind
    ``tier1``): wall seconds + the top-25 test durations.  The budget
    gate trips only at the 850 s cliff; the ledger is what makes the
    CREEP toward it visible — ``perfwatch compare`` flags a suite-time
    shift beyond the same-machine noise band long before the gate does.
    Best-effort: a ledger failure must never change this gate's verdict.
    """
    try:
        from jepsen_tpu.obs import regress

        # One stage row per test nodeid, SUMMED over pytest's separate
        # call/setup/teardown duration rows (the shared compile fixtures
        # are exactly the slow setups here — last-write-wins would drop
        # the call row and blind the creep attribution to it).
        per_test: dict[str, float] = {}
        for secs, test in durations:
            per_test[test] = per_test.get(test, 0.0) + secs
        top = dict(sorted(per_test.items(), key=lambda kv: -kv[1])[:25])
        record = regress.make_record(
            "tier1",
            {"tier1_wall_s": round(float(seconds), 2),
             "tier1_headroom_s": round(budget - float(seconds), 2)},
            # the suite's own slowest tests double as its stage table, so
            # a flagged creep names the moving tests via attribution
            stages=top,
            extra={"budget_s": budget},
            fp=regress.fingerprint(probe_devices=False),
        )
        regress.append_record(record, ledger)
    except Exception as e:  # noqa: BLE001 — never fail the gate on this
        print(f"warning: perf-ledger append failed: {e}", file=sys.stderr)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("log", nargs="?", default="-",
                    help="pytest output to parse ('-'/omitted: stdin)")
    ap.add_argument("--seconds", type=float, default=None,
                    help="recorded suite seconds (skips log parsing)")
    ap.add_argument("--budget", type=float, default=None,
                    help="budget in seconds (default: "
                         "$JEPSEN_TPU_TIER1_BUDGET_S or 850)")
    ap.add_argument("--json", action="store_true",
                    help="emit one machine-readable JSON line (seconds, "
                         "budget, headroom, ok, slowest tests) instead of "
                         "prose — for the docker test entrypoint and CI "
                         "dashboards; the exit code contract is unchanged")
    ap.add_argument("--ledger", default=None,
                    help="perf-ledger path for the suite-time record "
                         "(default: $JEPSEN_TPU_PERF_LEDGER, else "
                         "store/perf-ledger.jsonl; 'off' disables)")
    a = ap.parse_args(argv)

    budget = a.budget
    if budget is None:
        budget = float(os.environ.get("JEPSEN_TPU_TIER1_BUDGET_S",
                                      DEFAULT_BUDGET_S))

    durations: list[tuple[float, str]] = []
    structural: list[str] = []
    if a.seconds is not None:
        seconds = a.seconds
    else:
        text = (sys.stdin.read() if a.log == "-"
                else open(a.log, encoding="utf-8", errors="replace").read())
        seconds, durations = parse_log(text)
        if seconds is None:
            if a.json:
                print(json.dumps({
                    "metric": "tier1_budget", "ok": False,
                    "error": "no pytest summary line found",
                    "budget_s": budget,
                }))
            else:
                print("check_tier1_budget: no pytest summary line found "
                      f"in {a.log!r} (did the suite crash?)", file=sys.stderr)
            return 2
        structural += [
            f"required test file {f} appears nowhere in the suite log "
            "(collection error? its tests did not run)"
            for f in missing_required(text)
        ]

    structural += geometry_audit(
        Path(__file__).resolve().parent.parent / "tests")

    append_ledger(seconds, budget, durations, a.ledger)

    if a.json:
        ok = seconds <= budget and not structural
        print(json.dumps({
            "metric": "tier1_budget",
            "ok": ok,
            "seconds": round(seconds, 2),
            "budget_s": budget,
            "headroom_s": round(budget - seconds, 2),
            "structural": structural,
            "slowest": [
                {"seconds": secs, "test": test}
                for secs, test in durations[:10]
            ],
        }))
        return 0 if ok else 1

    for p in structural:
        print(f"tier-1 STRUCTURAL: {p}", file=sys.stderr)

    if seconds <= budget:
        if structural:
            return 1
        print(f"tier-1 budget OK: {seconds:.1f}s <= {budget:.0f}s "
              f"({budget - seconds:.1f}s headroom)")
        return 0

    print(f"tier-1 BUDGET EXCEEDED: {seconds:.1f}s > {budget:.0f}s",
          file=sys.stderr)
    if durations:
        print("slowest recorded tests:", file=sys.stderr)
        for secs, test in durations[:10]:
            print(f"  {secs:8.2f}s  {test}", file=sys.stderr)
    else:
        print("(re-run with --durations=25 — tier-1's pyproject addopts "
              "include it — to see the slowest tests here)", file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())
