"""Load generator for the check-serving subsystem (jepsen_tpu.serve).

Replays generated register histories against a ``CheckService`` at
configurable concurrency and reports throughput + p50/p95/p99 latency
(PER LATENCY CLASS — interactive-tier SLO stats are reported separately
from the batch tier), verdict parity against the sequential one-shot
``batch_analysis`` baseline (what each caller would pay without the
service), and the backpressure contract (a full queue rejects with
retry-after instead of buffering unboundedly).

Arrival patterns (the adversarial-load slice of ROADMAP item 5b):

  open     each tenant streams its share then collects (the proxy-in-
           front-of-many-users shape)
  closed   one in-flight request per tenant
  poisson  open arrival on an exponential inter-arrival clock (--rate)
  burst    alternating full-concurrency bursts and idle gaps
           (--burst-idle-ms) — the worst case for window-then-launch
           batching, the motivating case for rung-boundary admission
  diurnal  poisson with the rate swept sinusoidally between 20% and
           100% of --rate over the run (a compressed day)

``--size-mix "30:0.8,8:0.2"`` draws each request's history size from a
weighted ops-count mix; ``--interactive-max-ops N`` submits requests of
at most N ops with ``class_="interactive"`` (the greedy fast-path
tier).  ``--min-occupancy`` / ``--slo-interactive-p50-ms`` turn the
ISSUE's acceptance gates into exit-code assertions:

    # the PR 6 acceptance demo (8 open-arrival tenants; >=96 requests
    # keeps the queue populated so rung occupancy is measured, not noise):
    python tools/loadgen.py --cpu --requests 96 --concurrency 8 \\
        --max-batch 16 --size-mix 30:0.75,8:0.25 --interactive-max-ops 10 \\
        --min-occupancy 0.8 --slo-interactive-p50-ms 20

``--geometry-spread hostile`` (ROADMAP 5b's last scenario) replaces
the uniform geometry with a worst-case padding-waste mix: request
geometries cycle through (ops, procs) pairs chosen to land in FOUR
different padded (B, P, G) compile buckets with per-bucket counts below
the padded-batch floor of 8 — so cross-request batching can never fill
a launch and every batch pays maximal padding waste.  The generator
computes its own expected-minimum waste from the ACTUAL per-bucket
counts (``parallel.batch.bucket_geometry``/``padded_batch`` — the same
functions the scheduler keys on) and exits 1 unless (a) the service's
measured average padding waste is at least that bound (batching across
buckets would be a correctness bug, not a win) and (b) the live
``jepsen_tpu_serve_batch_padding_waste`` gauge equals
``1 - jepsen_tpu_serve_batch_occupancy`` (the gauge identity).

``--chaos-seed N`` runs the SERVICE arm under a deterministic seeded
fault schedule (``faults.inject_scope`` + ``seeded_injector``) — the
chaos-under-load composition: parity then means clean-verdict-or-
attributable-unknown with the degraded fraction bounded by
``--max-degraded``, while the /metrics consistency checks stay on.

Both modes are warmed (one untimed pass each) so the comparison is
launch-vs-launch, not compile-vs-cache.  Exits 1 on a verdict parity
mismatch, a missing backpressure rejection, a violated SLO/occupancy
gate, or (service mode) a live ``/metrics`` scrape whose
queue/occupancy/counter series disagree with the generator's own
request accounting — the observability layer is load-tested alongside
the thing it observes.
"""

from __future__ import annotations

import argparse
import json
import math
import random
import sys
import threading
import time
import urllib.request
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def _evidence_parity(baseline_bundles: dict, served_bundles: dict,
                     verdicts: list, baseline_verdicts: list):
    """Served-vs-sequential evidence-digest parity over the sampled
    requests: the same history through the same decision path must
    produce the same stability-core digest whether it was checked by
    the service or by a one-shot ``batch_analysis`` call.

    Normalization before comparing: the serving layer's own admission
    events (``serve.*``) are stripped from the served path (they have
    no sequential counterpart), and the config section is zeroed (the
    two arms legitimately run under different batch configs).  A path
    that still differs is NOT a failure — the service may batch/route
    differently — but the first diverging step is named for diagnosis.
    The hard failure is same-path-different-digest: the decision trail
    claims the runs were identical while the evidence core disagrees.

    Returns ``(summary_dict, failures)`` where each failure message
    names the diverging decision step."""
    from jepsen_tpu.obs import provenance

    def norm_path(bundle, *, served):
        path = bundle.get("decision_path") or []
        if served:
            path = [e for e in path
                    if not str(e.get("event", "")).startswith("serve.")]
        return path

    def core_digest(bundle, path):
        b = dict(bundle)
        b["decision_path"] = path
        b["config"] = {}
        return provenance.bundle_digest(b)

    checked = same_path = matched = 0
    diverged: list[dict] = []
    failures: list[str] = []
    for i in sorted(set(baseline_bundles) & set(served_bundles)):
        if verdicts[i] != baseline_verdicts[i]:
            continue  # verdict-parity / chaos logic owns flips
        bb, sb = baseline_bundles[i], served_bundles[i]
        bp = norm_path(bb, served=False)
        sp = norm_path(sb, served=True)
        checked += 1
        b_ev = [str(e.get("event")) for e in bp]
        s_ev = [str(e.get("event")) for e in sp]
        if b_ev != s_ev:
            k = next((j for j in range(min(len(b_ev), len(s_ev)))
                      if b_ev[j] != s_ev[j]),
                     min(len(b_ev), len(s_ev)))
            diverged.append({
                "request": i, "step": k,
                "sequential": b_ev[k] if k < len(b_ev) else None,
                "served": s_ev[k] if k < len(s_ev) else None,
            })
            continue
        same_path += 1
        bd, sd = core_digest(bb, bp), core_digest(sb, sp)
        if bd == sd:
            matched += 1
            continue
        sbp = provenance._strip(bp)
        ssp = provenance._strip(sp)
        k = next((j for j in range(len(sbp)) if sbp[j] != ssp[j]), None)
        where = (
            f"decision step {k} ({b_ev[k]}): sequential={sbp[k]} "
            f"served={ssp[k]}" if k is not None
            else "outside the decision path (engine/witness/cause)"
        )
        failures.append(
            f"request {i}: same decision path but digest {bd[:12]} != "
            f"{sd[:12]} — diverges at {where}")
    summary = {"checked": checked, "same_path": same_path,
               "digest_match": matched, "diverged_paths": len(diverged)}
    if diverged:
        summary["first_divergences"] = diverged[:4]
    return summary, failures


def _pct(xs: list[float], p: float) -> float:
    if not xs:
        return 0.0
    xs = sorted(xs)
    k = min(len(xs) - 1, max(0, round(p / 100 * (len(xs) - 1))))
    return xs[k]


def _parse_size_mix(spec: str) -> list[tuple[int, float]]:
    """``"30:0.8,8:0.2"`` -> [(30, 0.8), (8, 0.2)] (weights normalized)."""
    mix = []
    for part in spec.split(","):
        ops, _, w = part.partition(":")
        mix.append((int(ops), float(w or 1.0)))
    total = sum(w for _, w in mix) or 1.0
    return [(o, w / total) for o, w in mix]


def _draw_sizes(mix: list[tuple[int, float]], n: int, rng: random.Random) -> list[int]:
    return [
        rng.choices([o for o, _ in mix], weights=[w for _, w in mix])[0]
        for _ in range(n)
    ]


def _arrival_schedule(mode: str, n: int, rate: float,
                      rng: random.Random, *, concurrency: int,
                      burst_idle_ms: float) -> list[float] | None:
    """Per-request submit offsets (seconds from load start), or None for
    the legacy as-fast-as-possible open/closed modes."""
    if mode in ("open", "closed"):
        return None
    t, out = 0.0, []
    if mode == "poisson":
        for _ in range(n):
            t += rng.expovariate(rate)
            out.append(t)
    elif mode == "burst":
        # full-concurrency bursts separated by idle gaps: the pattern
        # that leaves a window-then-launch scheduler either waiting or
        # launching half-empty
        i = 0
        while i < n:
            for _ in range(min(concurrency, n - i)):
                out.append(t)
                i += 1
            t += burst_idle_ms / 1000.0
    else:  # diurnal: sinusoidal rate sweep, 20%..100% of --rate
        for k in range(n):
            phase = 2 * math.pi * k / max(1, n)
            r = rate * (0.6 - 0.4 * math.cos(phase))  # 0.2r .. 1.0r
            t += rng.expovariate(max(1e-6, r))
            out.append(t)
    return out


def _parse_prom(text: str) -> dict[str, float]:
    """Prometheus text -> {name{labels}: value} (enough of the format
    for the consistency assertions; histogram buckets included)."""
    out: dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            key, val = line.rsplit(" ", 1)
            out[key] = float(val)
        except ValueError:
            continue
    return out


class MetricsScraper:
    """Polls GET /metrics during the load phase (its own thread),
    recording queue-depth samples and the last full parse."""

    def __init__(self, port: int, period_s: float = 0.1):
        self.url = f"http://127.0.0.1:{port}/metrics"
        self.period_s = period_s
        self.samples: list[float] = []  # queue_depth over time
        self.scrapes = 0
        self.last: dict[str, float] = {}
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def scrape(self) -> dict[str, float]:
        with urllib.request.urlopen(self.url, timeout=10) as r:
            assert r.headers["Content-Type"].startswith("text/plain"), (
                "metrics endpoint must serve Prometheus text, got "
                f"{r.headers['Content-Type']}"
            )
            parsed = _parse_prom(r.read().decode())
        self.scrapes += 1
        self.last = parsed
        return parsed

    def _loop(self):
        while not self._stop.is_set():
            try:
                parsed = self.scrape()
                d = parsed.get("jepsen_tpu_serve_queue_depth")
                if d is not None:
                    self.samples.append(d)
            except Exception:  # noqa: BLE001 — scrape gaps are fine
                pass
            self._stop.wait(self.period_s)

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=5)


def _scrape_text(url: str) -> str:
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.read().decode()


def _federation_parity(fed_text: str, direct: dict[str, str]
                       ) -> tuple[int, list[str]]:
    """Direct-vs-federated scrape parity (obs.fleetview.federate).

    For every sample in a replica's OWN ``/metrics`` text, the router's
    federated exposition must carry the same series with a
    ``replica="<name>"`` label: counters with the EXACT same value
    (they are monotonic, so an idle replica's two scrapes can't
    legitimately differ), gauges at least present (their values move
    between the two scrapes by design).  Returns ``(series_checked,
    failure_messages)``."""
    from jepsen_tpu.obs import fleetview

    fed = fleetview.parse_exposition(fed_text)
    fed_map = {(name, tuple(sorted(labels))): value
               for name, labels, value in fed["samples"]}
    checked = 0
    fails: list[str] = []
    for rep, text in direct.items():
        parsed = fleetview.parse_exposition(text)
        for name, labels, value in parsed["samples"]:
            family, kind = fleetview._family_of(name, parsed["types"])
            key = (name, tuple(sorted(
                [(k, v) for k, v in labels if k != "replica"]
                + [("replica", rep)])))
            got = fed_map.get(key)
            checked += 1
            if got is None:
                fails.append(f"{rep}: {name}{dict(labels)} missing from "
                             "the federated exposition")
            elif kind != "gauge" and abs(got - value) > 1e-9:
                fails.append(f"{rep}: {name}{dict(labels)} federated "
                             f"{got} != direct {value}")
    return checked, fails


def _rollup_consistency(fed_text: str) -> tuple[int, list[str]]:
    """Internal consistency of ONE federated exposition (valid even
    mid-load: ``federate()`` computes its rollups from the same scrape
    texts it re-exports labeled): every ``jepsen_tpu_fleet_*`` counter
    rollup must equal the sum of its ``replica=``-labeled series, and
    no replica GAUGE family may have been rolled up (two replicas at
    queue depth 3 are not a fleet at depth 6).  Returns
    ``(rollups_checked, failure_messages)``."""
    from jepsen_tpu.obs import fleetview

    fed = fleetview.parse_exposition(fed_text)
    types = fed["types"]
    fed_map = {(name, tuple(sorted(labels))): value
               for name, labels, value in fed["samples"]}
    sums: dict[tuple, float] = {}
    gauge_rollups_banned: set[str] = set()
    for name, labels, value in fed["samples"]:
        family, kind = fleetview._family_of(name, types)
        if family.startswith(fleetview.ROLLUP_PREFIX):
            continue
        if dict(labels).get("replica") is None:
            continue  # the router's own unlabeled passthrough
        bare = tuple(sorted((k, v) for k, v in labels
                            if k not in ("replica", "le")))
        if kind == "counter":
            sums[(fleetview._rollup_name(family), bare)] = (
                sums.get((fleetview._rollup_name(family), bare), 0.0)
                + value)
        elif kind == "gauge":
            gauge_rollups_banned.add(fleetview._rollup_name(family))
    fails: list[str] = []
    for (rname, bare), expect in sorted(sums.items()):
        got = fed_map.get((rname, bare))
        if got is None:
            fails.append(f"rollup {rname}{dict(bare)} missing")
        elif abs(got - expect) > 1e-9:
            fails.append(f"rollup {rname}{dict(bare)} = {got} != "
                         f"sum of labeled series {expect}")
    for rname in sorted(gauge_rollups_banned):
        if rname in types:
            fails.append(f"gauge family was rolled up: {rname} "
                         "(gauges must not sum across replicas)")
    return len(sums), fails


#: the fleet round's geometry mix: small (ops, procs) pairs spanning
#: several padded (B, P, G) compile buckets so affinity routing has
#: DISTINCT keys to spread over the replicas (one uniform geometry
#: would hash the whole workload onto a single owner and measure
#: nothing but spill).  Deliberately small histories: post-warm launch
#: compute must stay well under the injected launch latency, or the
#: round measures 1-core compute serialization instead of the overlap
#: of device waits (large-ops histories blow up frontier compute at
#: unlucky seeds).  Rendezvous ownership over a 3-replica fleet is
#: lumpy at this key count — the power-of-two spill is what levels it,
#: which is the point: the round measures routing + spill, not a
#: hand-balanced assignment.
FLEET_GEOMETRY = [(20, 3), (20, 6), (40, 6), (40, 12),
                  (60, 12), (60, 24), (30, 5), (50, 10)]


def fleet_round(a) -> int:
    """``--replicas N``: the fleet-federation round (serve.fleet).

    Two sub-rounds, one shared workload drawn from ``FLEET_GEOMETRY``:
    (A) throughput — the SAME workload through one service, then
    through an N-replica fleet behind the affinity router, both under
    identical injected launch latency (``--inject-latency-ms``, default
    250 here) modeling device-bound launches: on a 1-core host the
    replicas overlap device WAITS, not python — exactly the resource a
    fleet multiplies; gate: fleet/single > ``--fleet-min-speedup`` with
    verdict parity, plus the per-replica occupancy breakdown; (B)
    failover — a subprocess worker replica joins, takes its rendezvous
    share, and is SIGKILLed mid-load: every request must settle exactly
    once (router ``duplicate_settles`` == 0, scraped
    ``jepsen_tpu_fleet_resubmitted_total`` == the router's own count,
    idempotent hits bounded by resubmissions) with verdicts identical
    to sub-round A.  Exit 1 on any gate; a passing round appends a
    fingerprinted ``kind:"fleet"`` perf-ledger record."""
    import contextlib
    import signal
    import tempfile

    from genhist import valid_register_history

    from jepsen_tpu import faults, web
    from jepsen_tpu.obs import metrics as obs_metrics
    from jepsen_tpu.obs import regress
    from jepsen_tpu.serve import CheckService
    from jepsen_tpu.serve import fleet as fl

    obs_metrics.enable_mirror()
    capacity = tuple(int(c) for c in a.capacity.split(",") if c)
    inject_s = (a.inject_latency_ms or 200.0) / 1000.0
    # scale the offered load to the fleet: N replicas need ~6 in-flight
    # each to stay fed (a closed loop sized for one service leaves
    # replicas idle and measures starvation), and enough requests that
    # the drain tail is a small fraction of the run
    n = max(a.requests, 20 * a.replicas)
    conc = max(a.concurrency, 6 * a.replicas)
    # all-VALID histories: a corrupted history pays the refutation
    # ladder (~1-2s of real, GIL-serialized compute vs ~2ms for a valid
    # one), which measures the checker's escalation policy, not the
    # fleet's routing — chaos_check --fleet owns corrupt-verdict parity
    hists = []
    for i in range(n):
        ops, procs = FLEET_GEOMETRY[i % len(FLEET_GEOMETRY)]
        hists.append(valid_register_history(ops, procs, seed=a.seed + i,
                                            info_rate=a.info_rate))
    keys = {fl.affinity_key(h) for h in hists}
    print(f"fleet round: {n} requests over {len(keys)} affinity keys, "
          f"{a.replicas} replicas, concurrency {conc}, "
          f"{inject_s * 1000:.0f}ms/lane injected launch latency "
          "(both arms)")

    base = Path(tempfile.mkdtemp(prefix="loadgen-fleet-"))
    svc_opts = dict(
        # max_batch pinned to the padded-batch floor (8): every launch
        # then runs at the SAME n_pad per bucket, so the sequential
        # warm pass covers every shape the measured pass can hit — an
        # uncapped batch drifts across power-of-two n_pad buckets and
        # pays ~1s mid-measurement recompiles in whichever arm happens
        # to form the unwarmed size
        capacity=capacity, max_batch=8, max_queue=a.max_queue,
        batch_window_s=a.batch_window_ms / 1000.0,
        # one-shot batches in BOTH arms: the continuous engine re-fires
        # the launch hook per ladder rung with the full lane count, so
        # under injected per-lane latency it multiplies the modeled
        # device time by a joiner-dependent factor — noise that swamps
        # the arm comparison this round exists to make
        continuous=False, warm_pool=False,
        confirm_refutations=False, exact_escalation=(),
    )

    def mk(name):
        # shared idempotency only — no admission journal: the round's
        # failover guarantee rides on claims + resubmission, and every
        # journal append is an fsync added to BOTH arms' request path
        return CheckService(
            idempotency_dir=base / "idem", idempotency_shared=True,
            quarantine_dir=base / "quar", **svc_opts,
        ).start()

    def sleeper(info, attempt, _s=inject_s):
        # per-LANE, not per-launch: device time grows with batch rows,
        # so queueing everything on one box must not amortize the
        # modeled launch away (a fixed per-launch sleep would reward
        # the single service for batching and measure that, not the
        # fleet's overlap of device waits)
        if str(info.get("what", "")).startswith("serve.batch"):
            time.sleep(_s * max(1, int(info.get("lanes") or 1)))

    def drive(submit):
        """Closed-loop measured pass; returns (wall_s, verdicts)."""
        verdicts: list = [None] * n
        idx_lock = threading.Lock()
        next_idx = [0]

        def worker():
            while True:
                with idx_lock:
                    i = next_idx[0]
                    if i >= n:
                        return
                    next_idx[0] += 1
                verdicts[i] = submit(i).result(timeout=600)["valid?"]

        t0 = time.perf_counter()
        ths = [threading.Thread(target=worker) for _ in range(conc)]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        return time.perf_counter() - t0, verdicts

    rc = 0
    out: dict = {"requests": n, "replicas": a.replicas,
                 "affinity_keys": len(keys),
                 "inject_latency_ms": inject_s * 1000.0}

    # ---- sub-round A1: single service under injected launch latency
    solo = mk("solo")
    for h in hists:  # sequential warm: singleton batches at n_pad=8,
        # the exact shape every measured launch runs at (jit cache is
        # process-global, so this warm covers the fleet arm too)
        solo.submit(h, client="warm").result(timeout=600)
    with faults.inject_scope(sleeper):
        wall_1, single_verdicts = drive(
            lambda i: solo.submit(hists[i], client="loadgen"))
    solo.shutdown(drain=False)
    out["single"] = {"wall_s": round(wall_1, 3),
                     "throughput_rps": round(n / wall_1, 2)}
    print(f"single:     {out['single']}")

    # ---- sub-round A2: the N-replica fleet, same workload + latency.
    # spill_depth_frac=0 keeps the power-of-two comparison always on:
    # the owner still wins warm-cache ties, but a backlogged owner
    # sheds to its second choice — the load-balancing half of the
    # routing story (the in-process replicas share one jit cache, so a
    # spilled request never pays a fresh compile mid-measurement).
    # mint_keys=False: sub-round A measures routing, not durable-claim
    # fsyncs (the solo arm pays none either); sub-round B passes
    # explicit per-request keys, which is what its exactly-once
    # accounting rides on
    router = fl.FleetRouter(spill_depth_frac=0.0, load_hint_age_s=0.02,
                            mint_keys=False,
                            successor_factory=lambda nm, old: mk(nm))
    for i in range(a.replicas):
        router.add_local(f"r{i}", mk(f"r{i}"))
    router.start()
    srv = web.make_server("127.0.0.1", 0, fleet=router)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    scraper = MetricsScraper(srv.server_address[1])
    try:
        for f in [router.submit(h, client="warm") for h in hists]:
            f.result(timeout=600)
        with faults.inject_scope(sleeper):
            wall_f, fleet_verdicts = drive(
                lambda i: router.submit(hists[i], client="loadgen"))
        st = router.stats()
        speedup = round((n / wall_f) / (n / wall_1), 2)
        # per-replica occupancy breakdown: who served what, how full
        per = {}
        for name, row in st["replicas"].items():
            s = row.get("stats") or {}
            per[name] = {
                "completed": s.get("completed"),
                "batches": s.get("batches"),
                "avg_occupancy": s.get("avg_occupancy"),
            }
        out["fleet"] = {
            "wall_s": round(wall_f, 3),
            "throughput_rps": round(n / wall_f, 2),
            "speedup": speedup,
            "routed": st["totals"]["routed"],
            "spilled": st["totals"]["spilled"],
            "per_replica": per,
        }
        print(f"fleet:      {out['fleet']}")
        if fleet_verdicts != single_verdicts:
            print("FLEET PARITY MISMATCH:",
                  list(zip(single_verdicts, fleet_verdicts)),
                  file=sys.stderr)
            rc = 1
        if speedup <= a.fleet_min_speedup:
            print(f"FLEET SPEEDUP BELOW GATE: {speedup}x <= "
                  f"{a.fleet_min_speedup}x", file=sys.stderr)
            rc = 1

        # ---- sub-round B: SIGKILL a worker replica mid-load
        print("failover:   spawning a subprocess worker replica")
        scrape_0 = scraper.scrape()
        wname = next(nm for nm in (f"w{i}" for i in range(64))
                     if any(fl._rendezvous(
                         k, [nm] + [f"r{i}" for i in range(a.replicas)]
                     )[0] == nm for k in keys))
        wopts = dict(svc_opts, capacity=list(capacity),
                     exact_escalation=[],
                     journal_dir=str(base / f"journal-{wname}"),
                     idempotency_dir=str(base / "idem"),
                     idempotency_shared=True,
                     quarantine_dir=str(base / "quar"))
        proc, url = fl.spawn_replica(wname, opts=wopts)
        router.add_replica(fl.HttpReplica(wname, url))
        # Federation parity while the worker is still idle: the
        # router's /metrics must re-export the worker's every series
        # under replica="<name>" with counter values EXACTLY equal to a
        # direct scrape, and the jepsen_tpu_fleet_* rollups must equal
        # the sum of the labeled series they aggregate.
        fed_text = _scrape_text(
            f"http://127.0.0.1:{srv.server_address[1]}/metrics")
        checked, par_fails = _federation_parity(
            fed_text, {wname: _scrape_text(url + "/metrics")})
        r_checked, roll_fails = _rollup_consistency(fed_text)
        out["federation"] = {"series_checked": checked,
                             "rollups_checked": r_checked,
                             "failures": len(par_fails) + len(roll_fails)}
        print(f"federation: {out['federation']}")
        for msg in (par_fails + roll_fails)[:8]:
            print(f"FEDERATION MISMATCH: {msg}", file=sys.stderr)
        if par_fails or roll_fails:
            rc = 1
        resolved = [0]
        res_lock = threading.Lock()

        def stamp(fut):
            with res_lock:
                resolved[0] += 1

        futs = []
        for i, h in enumerate(hists):
            f = router.submit(h, client="failover",
                              idempotency_key=f"lg-failover-{i}")
            f.add_done_callback(stamp)
            futs.append(f)
        time.sleep(0.3)
        proc.send_signal(signal.SIGKILL)
        failover_verdicts = [f.result(timeout=600)["valid?"]
                             for f in futs]
        tot = router.stats()["totals"]
        scrape_1 = scraper.scrape()

        def psum(parsed, name):
            # labeled series parse as 'name{labels}'; sum the family
            return sum(v for k, v in parsed.items()
                       if k == name or k.startswith(name + "{"))

        resub_scraped = (
            psum(scrape_1, "jepsen_tpu_fleet_resubmitted_total")
            - psum(scrape_0, "jepsen_tpu_fleet_resubmitted_total"))
        hits_delta = (
            psum(scrape_1, "jepsen_tpu_serve_idempotent_hits_total")
            - psum(scrape_0, "jepsen_tpu_serve_idempotent_hits_total"))
        resub_router = tot["resubmitted"] - st["totals"]["resubmitted"]
        out["failover"] = {
            "fenced": tot["fenced"],
            "resubmitted": resub_router,
            "resubmitted_scraped": resub_scraped,
            "idempotent_hits": hits_delta,
            "duplicate_settles": tot["duplicate_settles"],
            "resolved": resolved[0],
        }
        print(f"failover:   {out['failover']}")
        if failover_verdicts != single_verdicts:
            print("FAILOVER PARITY MISMATCH: a SIGKILLed replica "
                  "changed verdicts", file=sys.stderr)
            rc = 1
        if resolved[0] != n:
            print(f"LOST REQUESTS: {n - resolved[0]} futures never "
                  "resolved", file=sys.stderr)
            rc = 1
        if tot["duplicate_settles"] != 0:
            print(f"DOUBLE-SERVE: {tot['duplicate_settles']} requests "
                  "settled twice", file=sys.stderr)
            rc = 1
        if resub_scraped != resub_router:
            print(f"RESUBMISSION ACCOUNTING MISMATCH: scraped "
                  f"{resub_scraped} != router {resub_router}",
                  file=sys.stderr)
            rc = 1
        if hits_delta > resub_router:
            print(f"IDEMPOTENT-HIT OVERCOUNT: {hits_delta} hits > "
                  f"{resub_router} resubmissions — a duplicate "
                  "attached more than once", file=sys.stderr)
            rc = 1
    finally:
        with contextlib.suppress(Exception):
            proc.kill()
        scraper.stop()
        srv.shutdown()
        srv.server_close()
        router.shutdown()

    if rc == 0:
        try:
            metrics = {
                "fleet_rps": out["fleet"]["throughput_rps"],
                "single_rps": out["single"]["throughput_rps"],
                "fleet_speedup": out["fleet"]["speedup"],
                "resubmitted": float(out["failover"]["resubmitted"]),
                "duplicate_settles":
                    float(out["failover"]["duplicate_settles"]),
            }
            axes = {"replicas": str(a.replicas),
                    "inject_latency_ms": str(inject_s * 1000.0)}
            regress.append_record(
                regress.make_record("fleet", metrics, axes=axes))
        except Exception as e:  # noqa: BLE001 — never fail the run here
            print(f"warning: perf-ledger append failed: {e}",
                  file=sys.stderr)

    print(json.dumps({"loadgen": out}))
    return rc


def fleetview_round(a) -> int:
    """``--fleetview``: the fleet flight-recorder round (obs.fleetview).

    Two SUBPROCESS worker replicas behind the front-door router — each
    recording telemetry to its own directory, the router recording its
    own stream — with ``w1`` under injected launch latency (default 4s:
    a one-replica brownout) and a tight fleet latency SLO
    (threshold 2.5s) on the router.  Gates, exit 1 on any:

      * **federation parity** — every series in each worker's direct
        ``/metrics`` scrape appears in the router's federated
        exposition under ``replica=`` with exactly-equal counters, and
        the ``jepsen_tpu_fleet_*`` counter rollups equal the sum of
        their labeled series (checked both on a scrape taken MID-load
        and idle after the drain); no gauge family is rolled up.
      * **fleet burn** — the brownout trips the FLEET-level alert
        (``replica="fleet"`` on GET /alerts) while the healthy
        worker's own local /alerts stay quiet: exactly the one-replica
        brownout story the fleet SLO exists to tell.
      * **one timeline** — GET /fleet announces all three recorder
        streams; merged (``obs.fleetview.merge_trace_events``) they
        must show three process groups and at least one request trace
        spanning the router->replica hop, clock-aligned on the meta
        t0 epochs.
      * **route_s** — every routed result's latency block carries the
        router-admission stage, with the decomposition still summing
        exactly to ``total_s``.
    """
    import contextlib
    import tempfile

    from genhist import valid_register_history

    from jepsen_tpu import obs, web
    from jepsen_tpu.obs import critpath as cpm
    from jepsen_tpu.obs import fleetview
    from jepsen_tpu.obs import metrics as obs_metrics
    from jepsen_tpu.obs.trace import (align_streams, merge_aligned_events,
                                      read_jsonl_events)
    from jepsen_tpu.serve import fleet as fl

    obs_metrics.enable_mirror()
    capacity = tuple(int(c) for c in a.capacity.split(",") if c)
    inject_s = (a.inject_latency_ms or 4000.0) / 1000.0
    base = Path(a.telemetry_dir
                or tempfile.mkdtemp(prefix="loadgen-fleetview-"))
    names = ("w0", "w1")  # w1 is the brownout replica

    # Two geometries, one OWNED by each worker: rendezvous placement
    # over {w0, w1} must split the workload, or the brownout replica
    # would see either all of the traffic or none of it and the round
    # would measure nothing.  The affinity key is geometry-derived, so
    # one probe history per geometry pins the owner for all seeds.
    geoms: list[tuple[int, int]] = []
    owned: set[str] = set()
    for ops, procs in FLEET_GEOMETRY:
        h = valid_register_history(ops, procs, seed=a.seed)
        own = fl._rendezvous(fl.affinity_key(h), list(names))[0]
        if own not in owned:
            owned.add(own)
            geoms.append((ops, procs))
        if len(owned) == len(names):
            break
    assert len(geoms) == 2, "FLEET_GEOMETRY no longer splits over 2 names"

    n = max(a.requests, 24)
    conc = max(a.concurrency, 8)
    hists = []
    for i in range(n):
        ops, procs = geoms[i % len(geoms)]
        hists.append(valid_register_history(ops, procs, seed=a.seed + i,
                                            info_rate=a.info_rate))
    # The fleet SLO: p-high latency at 2.5s.  Post-warm launches on the
    # healthy worker land well under it; the injected brownout lands
    # every w1 request above it, so the fleet's bad fraction is ~w1's
    # traffic share (~1/2) against a 0.25 error budget — burn ~2x.
    slo_spec = [{"name": "fleet-p75", "kind": "latency",
                 "metric": "serve.request_latency_seconds",
                 "threshold_s": 2.5, "target": 0.75}]
    svc_opts = dict(
        capacity=list(capacity), max_batch=8, max_queue=a.max_queue,
        batch_window_s=a.batch_window_ms / 1000.0,
        continuous=False, warm_pool=False,
        confirm_refutations=False, exact_escalation=[],
    )

    print(f"fleetview round: {n} requests over 2 geometries, "
          f"2 subprocess replicas, {inject_s:.1f}s injected launch "
          "latency on w1, fleet SLO threshold 2.5s")
    rc = 0
    out: dict = {"requests": n, "inject_latency_ms": inject_s * 1000.0}
    procs_: dict = {}
    urls: dict[str, str] = {}
    srv = None
    with obs.recording(base / "router"):
        # spill disabled: the brownout must keep owning its share or
        # the router would shed w1's keys to w0 and dilute the burn
        # this round exists to measure
        router = fl.FleetRouter(spill_depth_frac=1e9, spill_burn=1e9,
                                mint_keys=False, slo_specs=slo_spec)
        try:
            for name in names:
                wopts = dict(svc_opts,
                             telemetry_dir=str(base / f"rep-{name}"))
                if name == "w1":
                    wopts["inject_latency_s"] = inject_s
                p, url = fl.spawn_replica(name, opts=wopts)
                procs_[name] = p
                urls[name] = url
                router.add_replica(fl.HttpReplica(name, url))
            router.start()
            srv = web.make_server("127.0.0.1", 0, fleet=router)
            threading.Thread(target=srv.serve_forever, daemon=True).start()
            fed_url = f"http://127.0.0.1:{srv.server_address[1]}"

            # warm one request per geometry (compiles each owner's
            # kernel; w1's pays the injected sleep once, untimed)
            for f in [router.submit(
                    valid_register_history(ops, procs, seed=a.seed + 7919),
                    client="warm") for ops, procs in geoms]:
                f.result(timeout=600)

            # measured load, closed loop; one raw federated scrape is
            # taken MID-load for the structural rollup check
            midload_text: list = [None]

            def _midload_scrape():
                with contextlib.suppress(Exception):
                    midload_text[0] = _scrape_text(fed_url + "/metrics")

            results: list = [None] * n
            idx_lock = threading.Lock()
            next_idx = [0]

            def worker():
                while True:
                    with idx_lock:
                        i = next_idx[0]
                        if i >= n:
                            return
                        next_idx[0] += 1
                    results[i] = router.submit(
                        hists[i], client="loadgen").result(timeout=600)

            timer = threading.Timer(2.0, _midload_scrape)
            timer.start()
            t0 = time.perf_counter()
            ths = [threading.Thread(target=worker) for _ in range(conc)]
            for t in ths:
                t.start()
            for t in ths:
                t.join()
            wall = time.perf_counter() - t0
            timer.cancel()
            out["wall_s"] = round(wall, 3)

            bad_verdicts = sum(1 for r in results
                               if not (r or {}).get("valid?"))
            if bad_verdicts:
                print(f"VERDICT FAILURES: {bad_verdicts} of {n} valid "
                      "histories did not check valid", file=sys.stderr)
                rc = 1

            # ---- gate: route_s in every result's latency block,
            # stages still summing exactly to total_s
            routed_with = 0
            worst_residual = 0.0
            for r in results:
                lat = (r or {}).get("latency") or {}
                if "route_s" not in lat:
                    continue
                routed_with += 1
                parts = sum(lat.get(k, 0.0) for k in (
                    "route_s", "queue_s", "pack_s", "launch_s",
                    "confirm_s", "other_s"))
                worst_residual = max(
                    worst_residual, abs(parts - lat.get("total_s", 0.0)))
            out["route_s"] = {"results_with_route_s": routed_with,
                              "worst_stage_sum_residual":
                                  round(worst_residual, 9)}
            print(f"route_s:    {out['route_s']}")
            if routed_with == 0:
                print("NO route_s: no settled result carried the "
                      "router-admission stage", file=sys.stderr)
                rc = 1
            if worst_residual > 1e-5:  # 6dp rounding on 7 stage fields
                print(f"STAGE SUM BROKEN: route_s joined the latency "
                      f"block but stages miss total_s by "
                      f"{worst_residual}", file=sys.stderr)
                rc = 1

            # ---- gate: federation parity (idle-exact) + rollup
            # consistency on both the mid-load and the idle scrape
            fed_text = _scrape_text(fed_url + "/metrics")
            direct = {nm: _scrape_text(u + "/metrics")
                      for nm, u in urls.items()}
            checked, par_fails = _federation_parity(fed_text, direct)
            roll_fails: list = []
            scrapes_checked = 0
            for label, text in (("idle", fed_text),
                                ("mid-load", midload_text[0])):
                if text is None:
                    continue
                scrapes_checked += 1
                nroll, fails = _rollup_consistency(text)
                roll_fails += [f"[{label}] {m}" for m in fails]
            out["federation"] = {"series_checked": checked,
                                 "scrapes_checked": scrapes_checked,
                                 "failures": len(par_fails)
                                 + len(roll_fails)}
            print(f"federation: {out['federation']}")
            for msg in (par_fails + roll_fails)[:8]:
                print(f"FEDERATION MISMATCH: {msg}", file=sys.stderr)
            if par_fails or roll_fails:
                rc = 1

            # ---- gate: the brownout burns the FLEET budget while the
            # healthy worker's local alerts stay quiet
            alerts = router.alerts()
            fleet_firing = [r for r in (alerts.get("alerts") or [])
                            if r.get("replica") == "fleet"]
            w0_alerts = json.loads(_scrape_text(urls["w0"] + "/alerts"))
            w0_firing = w0_alerts.get("alerts") or []
            out["alerts"] = {
                "fleet_firing": [r.get("slo") for r in fleet_firing],
                "w0_local_firing": [r.get("slo") for r in w0_firing],
            }
            print(f"alerts:     {out['alerts']}")
            if not any(r.get("slo") == "fleet-p75" for r in fleet_firing):
                print("FLEET ALERT DID NOT FIRE: a one-replica brownout "
                      "must burn the fleet budget", file=sys.stderr)
                rc = 1
            if w0_firing:
                print(f"HEALTHY REPLICA ALERTING: w0 local alerts "
                      f"{[r.get('slo') for r in w0_firing]} should be "
                      "quiet", file=sys.stderr)
                rc = 1

            # ---- gate: one merged timeline from the streams GET
            # /fleet announces
            st = router.stats()
            streams = []
            rt = st.get("router_telemetry") or {}
            if rt.get("jsonl"):
                ev, sk = read_jsonl_events(rt["jsonl"])
                streams.append(("router", ev, sk))
            for nm, row in sorted(st["replicas"].items()):
                tele = row.get("telemetry") or {}
                if tele.get("jsonl"):
                    ev, sk = read_jsonl_events(tele["jsonl"])
                    streams.append((nm, ev, sk))
            merged = fleetview.merge_trace_events(streams)
            od = merged["otherData"]
            xpt = od.get("cross_process_traces") or []
            aligned, _ = align_streams(streams)
            decomp = cpm.decompose_requests(merge_aligned_events(aligned))
            routed_rows = sum(1 for d in decomp.values()
                              if d.get("route_s", 0) > 0)
            out["timeline"] = {
                "streams": len(streams),
                "process_groups": len(od["processes"]),
                "cross_process_traces": len(xpt),
                "residual_skew_s": od.get("residual_skew_s"),
                "decomposed_requests_with_route_s": routed_rows,
            }
            print(f"timeline:   {out['timeline']}")
            if len(od["processes"]) < 3:
                print(f"MISSING PROCESS GROUPS: merged timeline has "
                      f"{len(od['processes'])} of 3 recorder streams "
                      "(router + 2 replicas)", file=sys.stderr)
                rc = 1
            if not xpt:
                print("NO CROSS-PROCESS TRACE: no request trace spans "
                      "the router->replica hop", file=sys.stderr)
                rc = 1
            (base / "fleet-trace.json").write_text(
                json.dumps(merged, separators=(",", ":"), default=str))
            print(f"merged timeline -> {base / 'fleet-trace.json'} "
                  "(load at https://ui.perfetto.dev)")
        finally:
            for p in procs_.values():
                with contextlib.suppress(Exception):
                    p.kill()
            if srv is not None:
                srv.shutdown()
                srv.server_close()
            router.shutdown()

    print(json.dumps({"loadgen": out}))
    return rc


def stream_round(a) -> int:
    """``--stream``: the open-arrival streaming round (checker.streaming).

    Replays stored histories as op STREAMS at ``--rate`` ops/s (epochs
    of ``--stream-epoch`` ops) through a ``StreamingChecker``, with
    every ``--corrupt-every``-th history corrupted so some streams
    carry a seeded violation.  For each refuted stream it measures
    VIOLATION-DETECTION latency — wall clock from stream start to the
    mid-stream verdict — against the end-of-run comparator: full
    arrival time plus the measured post-hoc ``batch_analysis`` wall
    (what a post-hoc pipeline would report).  This is the number ISSUE
    19 changes: check latency from the offending op, not from
    end-of-run.

    Gates (exit 1): streaming verdicts identical to post-hoc on every
    history; evidence digests identical after stripping
    admission/decision-path events (``streaming.parity_digest``); mean
    detection latency strictly below mean end-of-run latency on the
    refuted streams.  A passing round appends a fingerprinted
    ``kind:"stream"`` perf-ledger record."""
    from genhist import corrupt, valid_register_history

    from jepsen_tpu import models as m
    from jepsen_tpu.checker import streaming as _streaming
    from jepsen_tpu.obs import provenance, regress
    from jepsen_tpu.parallel import batch_analysis

    capacity = tuple(int(c) for c in a.capacity.split(",") if c)
    model = m.CASRegister(None)
    epoch = max(1, a.stream_epoch)
    rate = max(1.0, a.rate)
    n = a.requests
    hists, bad = [], []
    for i in range(n):
        h = valid_register_history(a.ops, a.procs, seed=a.seed + i,
                                   info_rate=a.info_rate)
        is_bad = bool(a.corrupt_every) and (
            i % a.corrupt_every == a.corrupt_every - 1)
        if is_bad:
            h = corrupt(h, seed=a.seed + i)
        hists.append(h)
        bad.append(is_bad)
    print(f"stream round: {n} histories ({sum(bad)} corrupted), "
          f"{a.ops} ops @ {rate:.0f} ops/s, epoch {epoch}")

    # Post-hoc arm first: the measured per-history check wall is the
    # end-of-run comparator's second term, and running it first warms
    # the chunk kernel so the streaming arm's detection latency isn't
    # 90% first-compile.
    post, post_wall = [], []
    for h in hists:
        t1 = time.perf_counter()
        res = batch_analysis(model, [h], capacity=capacity,
                             confirm_refutations=False)[0]
        post_wall.append(time.perf_counter() - t1)
        post.append(res)

    rc = 0
    det_lat, end_lat, stream_wall = [], [], []
    for i, h in enumerate(hists):
        sc = _streaming.StreamingChecker(model, capacity=capacity)
        t0 = time.perf_counter()
        detected = None
        for j in range(0, len(h), epoch):
            # pace the replay: this epoch's ops "arrive" at j/rate
            due = t0 + j / rate
            now = time.perf_counter()
            if due > now:
                time.sleep(due - now)
            sc.feed(h[j:j + epoch])
            if detected is None and sc.terminal:
                detected = time.perf_counter() - t0
        res = sc.finalize()
        stream_wall.append(time.perf_counter() - t0)
        # what a post-hoc pipeline reports the violation at: the whole
        # stream has to arrive, then the stored history gets checked
        end_of_run = len(h) / rate + post_wall[i]
        if bad[i]:
            det_lat.append(detected if detected is not None
                           else stream_wall[-1])
            end_lat.append(end_of_run)
        want = (post[i].get("valid?"),
                (post[i].get("op") or {}).get("index"))
        got = (res.get("valid?"), (res.get("op") or {}).get("index"))
        if got != want:
            print(f"VERDICT PARITY MISMATCH at history {i}: "
                  f"stream {got} != post-hoc {want}", file=sys.stderr)
            rc = 1
            continue
        bs = sc.evidence()
        bp = provenance.build_bundle(
            history=h, result=post[i], source="posthoc", model=model,
            checker="linearizable")
        if (bs is None or _streaming.parity_digest(bs)
                != _streaming.parity_digest(bp)):
            print(f"EVIDENCE DIGEST MISMATCH at history {i}",
                  file=sys.stderr)
            rc = 1

    out = {
        "streams": n, "corrupted": sum(bad),
        "rate_ops_s": rate, "epoch_ops": epoch,
        "detect_latency_s": round(_pct(det_lat, 50), 4) if det_lat else None,
        "end_of_run_latency_s": (round(_pct(end_lat, 50), 4)
                                 if end_lat else None),
        "stream_wall_s": round(_pct(stream_wall, 50), 4),
        "posthoc_wall_s": round(_pct(post_wall, 50), 4),
    }
    if det_lat:
        mean_det = sum(det_lat) / len(det_lat)
        mean_end = sum(end_lat) / len(end_lat)
        out["detection_speedup"] = round(mean_end / max(mean_det, 1e-9), 2)
        if mean_det >= mean_end:
            print(f"DETECTION NOT EARLY: streaming detected at "
                  f"{mean_det:.3f}s mean, end-of-run would report at "
                  f"{mean_end:.3f}s", file=sys.stderr)
            rc = 1
    if rc == 0:
        try:
            metrics = {
                "detect_latency_s": (sum(det_lat) / len(det_lat)
                                     if det_lat else 0.0),
                "end_of_run_latency_s": (sum(end_lat) / len(end_lat)
                                         if end_lat else 0.0),
                "detection_speedup": out.get("detection_speedup") or 0.0,
                "stream_wall_s": sum(stream_wall) / len(stream_wall),
            }
            axes = {"rate": str(rate), "ops": str(a.ops),
                    "epoch": str(epoch)}
            regress.append_record(
                regress.make_record("stream", metrics, axes=axes))
        except Exception as e:  # noqa: BLE001 — never fail the run here
            print(f"warning: perf-ledger append failed: {e}",
                  file=sys.stderr)
    print(json.dumps({"loadgen": {"stream": out}}))
    return rc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--requests", type=int, default=32)
    ap.add_argument("--concurrency", type=int, default=8)
    ap.add_argument("--ops", type=int, default=30, help="ops per history")
    ap.add_argument("--procs", type=int, default=3)
    ap.add_argument("--info-rate", type=float, default=0.1)
    ap.add_argument("--corrupt-every", type=int, default=4,
                    help="every k-th history is corrupted (0: none)")
    ap.add_argument("--capacity", default="64,256",
                    help="service ladder capacities, comma-separated")
    ap.add_argument("--max-batch", type=int, default=64)
    ap.add_argument("--max-queue", type=int, default=256)
    ap.add_argument("--batch-window-ms", type=float, default=5.0)
    ap.add_argument("--mode", choices=("both", "service", "sequential"),
                    default="both")
    ap.add_argument("--arrival",
                    choices=("open", "closed", "poisson", "burst", "diurnal"),
                    default="open",
                    help="arrival pattern (module docstring): open/closed "
                         "as-fast-as-possible, or a timed schedule "
                         "(poisson/burst/diurnal)")
    ap.add_argument("--rate", type=float, default=50.0,
                    help="target arrival rate (req/s) for poisson/diurnal")
    ap.add_argument("--burst-idle-ms", type=float, default=150.0,
                    help="idle gap between full-concurrency bursts")
    ap.add_argument("--geometry-spread", choices=("uniform", "hostile"),
                    default="uniform",
                    help="'hostile' cycles requests through a worst-case "
                         "padding-waste geometry mix (distinct padded "
                         "(B,P,G) buckets, per-bucket counts < the "
                         "padded-batch floor) and asserts the padding-"
                         "waste gauge against the generator's own "
                         "accounting (module docstring)")
    ap.add_argument("--size-mix", default=None,
                    help='weighted ops-count mix, e.g. "30:0.8,8:0.2" '
                         "(default: every history has --ops ops)")
    ap.add_argument("--interactive-max-ops", type=int, default=0,
                    help="requests with at most this many ops submit as "
                         'class_="interactive" (greedy fast path); 0: all '
                         "batch tier")
    ap.add_argument("--min-occupancy", type=float, default=None,
                    help="exit 1 if the service's continuous (per-rung) "
                         "occupancy lands below this")
    ap.add_argument("--slo-interactive-p50-ms", type=float, default=None,
                    help="exit 1 if the interactive tier's p50 exceeds "
                         "this many milliseconds")
    ap.add_argument("--no-continuous", action="store_true",
                    help="disable rung-boundary admission (A/B against "
                         "window-then-launch batching)")
    ap.add_argument("--chaos-seed", type=int, default=None,
                    help="run the SERVICE arm under a deterministic "
                         "seeded fault schedule (faults.inject_scope + "
                         "seeded_injector: transient launch faults, plus "
                         "OOM halvings on multi-lane launches).  Verdict "
                         "parity then means: clean verdict OR an "
                         "attributable unknown, with the degraded "
                         "fraction bounded by --max-degraded — the "
                         "chaos-under-load contract (ROADMAP 5b)")
    ap.add_argument("--max-degraded", type=float, default=0.0,
                    help="with --chaos-seed: max fraction of requests "
                         "allowed to degrade to an attributable unknown "
                         "before exit 1 (default 0.0 — transient-only "
                         "schedules should degrade nothing)")
    ap.add_argument("--slo-file", default=None, metavar="JSON",
                    help="SLO spec file for the service's live burn-rate "
                         "engine (a JSON list merged over the built-in "
                         "defaults by name; jepsen_tpu/serve/slo.py)")
    ap.add_argument("--inject-latency-ms", type=float, default=0.0,
                    help="inject this much latency into every shared "
                         "batch launch (a deterministic sleeper through "
                         "the faults.inject_scope seam) — the SLO-breach "
                         "smoke: injected latency must trip GET /alerts, "
                         "a clean run must not")
    ap.add_argument("--assert-alert", action="append", default=None,
                    metavar="SLO",
                    help="exit 1 unless this SLO is FIRING on GET "
                         "/alerts after the load (repeatable)")
    ap.add_argument("--assert-no-alerts", action="store_true",
                    help="exit 1 if ANY SLO alert is firing after the "
                         "load (the clean-run acceptance gate)")
    ap.add_argument("--replicas", type=int, default=0,
                    help="run the FLEET round instead: the same workload "
                         "through one service, then through this many "
                         "local replicas behind the affinity router "
                         "(serve.fleet), plus a SIGKILL-failover pass "
                         "with exactly-once accounting")
    ap.add_argument("--fleet-min-speedup", type=float, default=2.5,
                    help="fleet round: exit 1 unless fleet throughput "
                         "exceeds single-service throughput by this "
                         "factor (default 2.5)")
    ap.add_argument("--fleetview", action="store_true",
                    help="fleet flight-recorder round: 2 subprocess "
                         "replicas (one browned out), federated-scrape "
                         "parity, fleet-level burn, and one merged "
                         "clock-aligned timeline; exit 1 on any gate")
    ap.add_argument("--stream", action="store_true",
                    help="run the STREAMING round instead: replay "
                         "stored histories as open-arrival op streams "
                         "through checker.streaming at --rate ops/s, "
                         "measuring violation-detection latency vs the "
                         "end-of-run comparator, with verdict + "
                         "evidence-digest parity gates against "
                         "post-hoc batch_analysis")
    ap.add_argument("--stream-epoch", type=int, default=8,
                    help="ops per streaming feed epoch (smaller epochs "
                         "detect sooner, pay more re-pack host work)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (the conftest dance) — "
                         "use for demos on hosts without a chip")
    ap.add_argument("--telemetry-dir", default=None,
                    help="record obs telemetry (incl. the serve table) here")
    a = ap.parse_args(argv)

    if a.cpu:
        import os

        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=1"
        ).strip()
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")

    if a.stream:
        return stream_round(a)
    if a.fleetview:
        return fleetview_round(a)
    if a.replicas and a.replicas > 1:
        return fleet_round(a)

    from genhist import corrupt, valid_register_history
    from jepsen_tpu import faults, obs
    from jepsen_tpu import models as m
    from jepsen_tpu.obs import metrics as obs_metrics
    from jepsen_tpu.obs import provenance
    from jepsen_tpu.parallel import batch_analysis
    from jepsen_tpu.serve import CheckService, QueueFull

    # Enable the live metrics mirror BEFORE either arm runs: the service
    # arm would flip it on anyway (make_server/start), and the sequential
    # baseline must pay the same per-launch observation cost or the
    # printed speedup stops being launch-vs-launch.
    obs_metrics.enable_mirror()

    capacity = tuple(int(c) for c in a.capacity.split(",") if c)
    model = m.CASRegister(None)
    rng = random.Random(a.seed)
    mix = _parse_size_mix(a.size_mix) if a.size_mix else [(a.ops, 1.0)]
    sizes = _draw_sizes(mix, a.requests, rng)
    classes: list[str | None] = [
        "interactive"
        if a.interactive_max_ops and s <= a.interactive_max_ops else None
        for s in sizes
    ]
    #: hostile padding-waste mix: procs chosen so the packed P crosses
    #: the P_BUCKETS boundaries (8 / 16 / 32 / 64) — four distinct
    #: compile buckets the scheduler can never co-batch; ops = 2x procs
    #: so (nearly) every proc is exercised and P tracks procs.
    HOSTILE_GEOMETRY = [(6, 3), (24, 12), (48, 24), (80, 40)]
    hostile = a.geometry_spread == "hostile"
    if hostile:
        geoms = [HOSTILE_GEOMETRY[i % len(HOSTILE_GEOMETRY)]
                 for i in range(a.requests)]
        sizes = [g[0] for g in geoms]
        classes = [None] * a.requests  # the waste bound is batch-tier math
    hists = []
    for i in range(a.requests):
        procs_i = geoms[i][1] if hostile else a.procs
        # hostile mode pins info_rate 0: crashed ops would perturb P/G
        # and with them the bucket accounting the gate asserts against
        hh = valid_register_history(
            sizes[i], procs_i, seed=a.seed + i,
            info_rate=0.0 if hostile else a.info_rate)
        if (a.corrupt_every and i % a.corrupt_every == a.corrupt_every - 1
                and classes[i] is None):
            # corruption stays on the batch tier: the interactive tier's
            # SLO is defined over small LIKELY-VALID histories
            hh = corrupt(hh, seed=a.seed + i)
        hists.append(hh)
    geometry_acct = None
    if hostile:
        # the generator's own padding-waste accounting, from the same
        # bucketing functions the scheduler keys launches on
        from jepsen_tpu.ops import wgl as _wgl
        from jepsen_tpu.parallel import batch as _pb

        counts: dict = {}
        for hh in hists:
            p = _wgl.pack(model, hh)
            bkt = _pb.bucket_geometry(p["B"], p["P"], p["G"])
            counts[bkt] = counts.get(bkt, 0) + 1
        per_bucket = {str(k): v for k, v in sorted(counts.items())}
        # every batch forms within one bucket, so its size n is at most
        # min(bucket count, max_batch) and its waste at least
        # 1 - n/padded_batch(n); minimize over feasible n per bucket
        def min_waste(c: int) -> float:
            return min(
                1.0 - n / _pb.padded_batch(n)
                for n in range(1, min(c, a.max_batch) + 1)
            )
        expected_min_waste = min(min_waste(c) for c in counts.values())
        geometry_acct = {
            "spread": "hostile", "buckets": len(counts),
            "per_bucket": per_bucket,
            "expected_min_waste": round(expected_min_waste, 4),
        }
        out_note = [c for c in counts.values() if c >= 8]
        if out_note:
            print(f"warning: {len(out_note)} bucket(s) hold >=8 requests; "
                  "the waste bound degrades to 0 there", file=sys.stderr)
    schedule = _arrival_schedule(
        a.arrival, a.requests, a.rate, rng,
        concurrency=a.concurrency, burst_idle_ms=a.burst_idle_ms,
    )

    out: dict = {
        "requests": a.requests, "concurrency": a.concurrency,
        "ops": sorted(set(sizes)) if (a.size_mix or hostile) else a.ops,
        "capacity": list(capacity), "arrival": a.arrival,
        "interactive": sum(c == "interactive" for c in classes),
    }
    if geometry_acct is not None:
        out["geometry"] = geometry_acct
    rc = 0
    baseline_verdicts = None
    # Evidence-digest parity sample: the LAST few requests — the served
    # arm keeps its most recent bundles in the in-memory evidence ring,
    # so sampling from the tail survives large runs.
    prov_sample = set(range(max(0, a.requests - 32), a.requests))
    baseline_bundles: dict[int, dict] = {}
    served_bundles: dict[int, dict] = {}

    import contextlib

    rec_ctx = (
        obs.recording(a.telemetry_dir, enabled=True)
        if a.telemetry_dir else contextlib.nullcontext()
    )
    with rec_ctx as rec:
        if a.mode in ("both", "sequential"):
            # One-shot baseline: each caller pays its own batch_analysis
            # (the pre-serve world).  Warm untimed on one valid AND one
            # refuting history so the measured pass is launch-vs-launch
            # (refutations compile extra rungs + spawn the confirm pool).
            batch_analysis(model, [hists[0]], capacity=capacity)
            if a.corrupt_every and a.corrupt_every <= a.requests:
                batch_analysis(
                    model, [hists[a.corrupt_every - 1]], capacity=capacity)
            lat = []
            t0 = time.perf_counter()
            baseline_verdicts = []
            for i, hh in enumerate(hists):
                t1 = time.perf_counter()
                r = batch_analysis(model, [hh], capacity=capacity)[0]
                lat.append(time.perf_counter() - t1)
                baseline_verdicts.append(r["valid?"])
                if i in prov_sample:
                    try:
                        baseline_bundles[i] = provenance.build_bundle(
                            history=hh, result=r, source="sequential",
                            model=model)
                    except Exception:  # noqa: BLE001 — parity is advisory
                        pass
            wall = time.perf_counter() - t0
            out["sequential"] = {
                "wall_s": round(wall, 3),
                "throughput_rps": round(a.requests / wall, 2),
                "p50_s": round(_pct(lat, 50), 4),
                "p95_s": round(_pct(lat, 95), 4),
                "p99_s": round(_pct(lat, 99), 4),
            }
            print(f"sequential: {out['sequential']}")

        if a.mode in ("both", "service"):
            from jepsen_tpu import web

            svc = CheckService(
                capacity=capacity, max_batch=a.max_batch,
                max_queue=a.max_queue,
                batch_window_s=a.batch_window_ms / 1000.0,
                continuous=not a.no_continuous,
                slo_specs=a.slo_file,
            ).start()
            # Mount the real HTTP app over the service so the load runs
            # with /metrics live — the scrape-vs-accounting consistency
            # check below exercises the whole observability path, not a
            # registry read.
            srv = web.make_server("127.0.0.1", 0, check_service=svc)
            srv_thread = threading.Thread(target=srv.serve_forever, daemon=True)
            srv_thread.start()
            scraper = MetricsScraper(srv.server_address[1])
            # --chaos-seed: the whole service arm (warm + measured) runs
            # under a deterministic injected-fault schedule — the
            # chaos-under-load composition ROADMAP 5b asks for, through
            # the same inject_scope seam tools/chaos_check.py uses.
            chaos_stack = contextlib.ExitStack()
            if a.chaos_seed is not None:
                chaos_stack.enter_context(faults.inject_scope(
                    faults.seeded_injector(
                        a.chaos_seed, transient_rate=0.25, oom_rate=0.1,
                        what="ladder.",
                    )
                ))
            if a.inject_latency_ms:
                # The SLO-breach smoke: a deterministic sleeper on every
                # shared batch launch (the serve-level inject seam), so
                # batch-tier latency blows a tight latency SLO without
                # touching verdict semantics.
                def _latency_injector(info, attempt,
                                      _s=a.inject_latency_ms / 1000.0):
                    if str(info.get("what", "")).startswith("serve.batch"):
                        time.sleep(_s)

                chaos_stack.enter_context(
                    faults.inject_scope(_latency_injector))
            try:
                # warm pass: same histories AND classes, untimed (compile
                # the padded batch + greedy fast-path shapes the measured
                # pass will launch)
                warm = [svc.submit(hh, client="warm", class_=classes[i])
                        for i, hh in enumerate(hists)]
                for f in warm:
                    f.result(timeout=600)
                # Quiesce: early demux resolves futures MID-ladder, so a
                # warm batch can still be finishing (confirm drain, rung
                # accounting) after every warm future is done — wait it
                # out so the snapshots below cleanly separate warm from
                # measured work.
                t_q = time.perf_counter()
                while time.perf_counter() - t_q < 60:
                    st_w = svc.stats()
                    if not st_w["running"] and not st_w["queue_depth"]:
                        break
                    time.sleep(0.005)
                warm_batches = st_w["batches"]
                # rung-occupancy accumulators at the warm/measured
                # boundary: the gate reads the measured-pass DELTA, so
                # one-off compile rungs (a 2+ s single-lane launch the
                # first time a shape is seen) don't poison the steady-
                # state number the SLO is about — same reason both modes
                # warm untimed ("launch-vs-launch, not compile-vs-cache")
                warm_lane_s = st_w["rung_lane_s"]
                warm_slot_s = st_w["rung_slot_s"]
                scraper.start()  # mid-load /metrics sampling starts here

                verdicts: list = [None] * a.requests
                causes: list = [None] * a.requests
                evid: list = [None] * a.requests
                lat: list = [0.0] * a.requests
                done_at: list = [0.0] * a.requests
                retries = [0]
                idx_lock = threading.Lock()
                next_idx = [0]

                def submit_one(i: int, wid: int):
                    t1 = time.perf_counter()
                    while True:
                        try:
                            f = svc.submit(hists[i], client=f"tenant-{wid}",
                                           class_=classes[i])
                            break
                        except QueueFull as e:
                            with idx_lock:
                                retries[0] += 1
                            time.sleep(e.retry_after)

                    def _stamp(fut, i=i):
                        done_at[i] = time.perf_counter()

                    f.add_done_callback(_stamp)
                    return t1, f

                def worker(wid: int):
                    if a.arrival == "closed":
                        # closed loop: one in-flight request per tenant
                        while True:
                            with idx_lock:
                                i = next_idx[0]
                                if i >= a.requests:
                                    return
                                next_idx[0] += 1
                            t1, f = submit_one(i, wid)
                            r = f.result(timeout=600)
                            lat[i] = time.perf_counter() - t1
                            verdicts[i] = r["valid?"]
                            causes[i] = r.get("cause")
                            evid[i] = (r.get("evidence") or {}).get("id")
                    else:
                        # open arrivals: stream this tenant's share
                        # (optionally on the timed --arrival schedule),
                        # then collect — the queue depth is where
                        # cross-request batching engages, and completion
                        # times come from the done-callback stamps so
                        # late collection doesn't inflate latency
                        mine = list(range(wid, a.requests, a.concurrency))
                        futs = []
                        for i in mine:
                            if schedule is not None:
                                delay = t0 + schedule[i] - time.perf_counter()
                                if delay > 0:
                                    time.sleep(delay)
                            futs.append(submit_one(i, wid))
                        for i, (t1, f) in zip(mine, futs):
                            r = f.result(timeout=600)
                            # set_result wakes waiters BEFORE running
                            # done-callbacks, so the stamp can lag this
                            # wake by a beat — an unstamped completion
                            # is timed here, at wake (same instant).
                            lat[i] = (done_at[i] or time.perf_counter()) - t1
                            verdicts[i] = r["valid?"]
                            causes[i] = r.get("cause")
                            evid[i] = (r.get("evidence") or {}).get("id")

                t0 = time.perf_counter()
                threads = [
                    threading.Thread(target=worker, args=(w,))
                    for w in range(a.concurrency)
                ]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
                wall = time.perf_counter() - t0
                # Quiesce again before reading stats: the last batch's
                # rung accounting lands when the LADDER finishes, which
                # can trail the last future (early demux).
                t_q = time.perf_counter()
                while time.perf_counter() - t_q < 60:
                    st = svc.stats()
                    if not st["running"] and not st["queue_depth"]:
                        break
                    time.sleep(0.005)
                d_slot = st["rung_slot_s"] - warm_slot_s
                occ_timed = (
                    round((st["rung_lane_s"] - warm_lane_s) / d_slot, 4)
                    if d_slot > 0 else None
                )
                out["service"] = {
                    "wall_s": round(wall, 3),
                    "throughput_rps": round(a.requests / wall, 2),
                    "p50_s": round(_pct(lat, 50), 4),
                    "p95_s": round(_pct(lat, 95), 4),
                    "p99_s": round(_pct(lat, 99), 4),
                    "batches": st["batches"] - warm_batches,
                    "avg_occupancy": st["avg_occupancy"],
                    "continuous_occupancy": occ_timed,
                    "continuous_occupancy_cumulative":
                        st["continuous_occupancy"],
                    "fastpath_resolved": st["fastpath_resolved"],
                    "escalated": st["escalated"],
                    "queue_full_retries": retries[0],
                }
                # Per-class SLO stats: the interactive tier's latency is
                # reported SEPARATELY from the batch tier — one blended
                # percentile would hide exactly the worst-lane-batch
                # regression latency classes exist to fix.
                by_class: dict = {}
                for i in range(a.requests):
                    tier = classes[i] or "batch"
                    by_class.setdefault(tier, []).append(lat[i])
                out["service"]["classes"] = {
                    tier: {
                        "requests": len(xs),
                        "p50_s": round(_pct(xs, 50), 4),
                        "p95_s": round(_pct(xs, 95), 4),
                    }
                    for tier, xs in sorted(by_class.items())
                }
                print(f"service:    {out['service']}")
                # acceptance gates (ISSUE 6): continuous occupancy and
                # the interactive tier's p50 SLO
                if a.min_occupancy is not None:
                    if occ_timed is None or occ_timed < a.min_occupancy:
                        print(f"OCCUPANCY BELOW GATE: {occ_timed} < "
                              f"{a.min_occupancy}", file=sys.stderr)
                        rc = 1
                if (a.slo_interactive_p50_ms is not None
                        and "interactive" in by_class):
                    p50_ms = _pct(by_class["interactive"], 50) * 1000.0
                    out["service"]["interactive_p50_ms"] = round(p50_ms, 2)
                    if p50_ms > a.slo_interactive_p50_ms:
                        print(f"INTERACTIVE SLO MISS: p50 {p50_ms:.1f}ms > "
                              f"{a.slo_interactive_p50_ms}ms",
                              file=sys.stderr)
                        rc = 1

                # ------------------------------------------------------
                # /metrics consistency: the scraped series must agree
                # with the generator's own accounting and the service's
                # totals — a live dashboard that disagrees with the
                # system it watches is worse than none.
                # ------------------------------------------------------
                scraper.stop()
                m = scraper.scrape()  # final settle scrape
                checks = {
                    # warm + measured, each a.requests submissions
                    "submitted": (
                        m.get("jepsen_tpu_serve_submitted_total"),
                        float(2 * a.requests),
                    ),
                    "completed": (
                        m.get("jepsen_tpu_serve_completed_total"),
                        float(2 * a.requests),
                    ),
                    "rejected": (
                        m.get("jepsen_tpu_serve_rejected_total", 0.0),
                        float(st["rejected"]),
                    ),
                    "request_latency_count": (
                        m.get("jepsen_tpu_serve_request_latency_seconds_count"),
                        float(st["completed"]),
                    ),
                    "queue_depth_settled": (
                        m.get("jepsen_tpu_serve_queue_depth"), 0.0
                    ),
                }
                bad = {k: v for k, v in checks.items() if v[0] != v[1]}
                occ = m.get("jepsen_tpu_serve_batch_occupancy")
                if occ is None or not (0.0 < occ <= 1.0):
                    bad["batch_occupancy"] = (occ, "(0, 1]")
                depth_max = max(scraper.samples, default=0.0)
                if depth_max > a.max_queue:
                    bad["queue_depth_bound"] = (depth_max, a.max_queue)
                out["metrics"] = {
                    "scrapes": scraper.scrapes,
                    "queue_depth_max": depth_max,
                    "queue_depth_samples": len(scraper.samples),
                    "batch_occupancy_last": occ,
                    "consistent": not bad,
                }
                if bad:
                    print(f"METRICS INCONSISTENT: {bad}", file=sys.stderr)
                    rc = 1
                print(f"metrics:    {out['metrics']}")
                # --------------------------------------------------------
                # SLO burn-rate acceptance gates: evaluate once more so
                # the final latency observations are sampled, then read
                # the alert document over the REAL HTTP endpoint — the
                # gate exercises the whole surface an operator's pager
                # would.
                # --------------------------------------------------------
                if a.assert_alert or a.assert_no_alerts:
                    svc.slo.evaluate()
                    alerts_url = (f"http://127.0.0.1:"
                                  f"{srv.server_address[1]}/alerts")
                    with urllib.request.urlopen(alerts_url, timeout=10) as r:
                        alerts_doc = json.loads(r.read())
                    firing = {al["slo"] for al in alerts_doc["alerts"]}
                    out["slo"] = {
                        "firing": sorted(firing),
                        "burn": {
                            s["slo"]: {"fast": s["burn_fast"],
                                       "slow": s["burn_slow"],
                                       "state": s["state"]}
                            for s in alerts_doc["slos"]
                        },
                    }
                    for name in a.assert_alert or []:
                        if name not in firing:
                            print(f"SLO ALERT MISSING: {name!r} did not "
                                  f"fire (firing: {sorted(firing)}; "
                                  f"burns: {out['slo']['burn']})",
                                  file=sys.stderr)
                            rc = 1
                    if a.assert_no_alerts and firing:
                        print(f"UNEXPECTED SLO ALERT(S): {sorted(firing)} "
                              f"(burns: {out['slo']['burn']})",
                              file=sys.stderr)
                        rc = 1
                    print(f"slo:        {out['slo']}")
                if geometry_acct is not None:
                    # hostile-geometry gate: measured waste vs the
                    # generator's own bucket accounting, and the live
                    # waste gauge vs the occupancy gauge identity
                    avg_occ = st["avg_occupancy"] or 0.0
                    measured_waste = round(1.0 - avg_occ, 4)
                    geometry_acct["measured_avg_waste"] = measured_waste
                    bound = geometry_acct["expected_min_waste"]
                    if measured_waste + 1e-9 < bound:
                        print(f"PADDING WASTE BELOW GEOMETRY BOUND: "
                              f"{measured_waste} < {bound} (the scheduler "
                              "batched across geometry buckets?)",
                              file=sys.stderr)
                        rc = 1
                    g_waste = m.get("jepsen_tpu_serve_batch_padding_waste")
                    g_occ = m.get("jepsen_tpu_serve_batch_occupancy")
                    if (g_waste is None or g_occ is None
                            or abs((1.0 - g_occ) - g_waste) > 2e-4):
                        print(f"PADDING-WASTE GAUGE INCONSISTENT: "
                              f"waste={g_waste} occupancy={g_occ}",
                              file=sys.stderr)
                        rc = 1
                    geometry_acct["waste_gauge"] = g_waste
                    print(f"geometry:   {geometry_acct}")
            finally:
                chaos_stack.close()
                scraper.stop()
                srv.shutdown()
                srv.server_close()
                svc.shutdown(drain=False)

            if baseline_verdicts is not None:
                if a.chaos_seed is not None:
                    # Chaos-under-load contract: every verdict is the
                    # clean one OR an attributable unknown, and the
                    # degraded fraction is bounded.  A silent verdict
                    # FLIP is always a failure.
                    degraded = [
                        i for i, (b, v) in enumerate(
                            zip(baseline_verdicts, verdicts))
                        if v != b
                    ]
                    flips = [
                        i for i in degraded
                        if verdicts[i] != "unknown"
                        or not str(causes[i] or "").strip()
                    ]
                    frac = len(degraded) / max(1, a.requests)
                    parity = not flips and frac <= a.max_degraded
                    out["verdict_parity"] = parity
                    out["chaos"] = {
                        "seed": a.chaos_seed,
                        "degraded": len(degraded),
                        "degraded_fraction": round(frac, 4),
                        "max_degraded": a.max_degraded,
                    }
                    if flips:
                        print("CHAOS VERDICT FLIP:",
                              [(i, baseline_verdicts[i], verdicts[i],
                                causes[i]) for i in flips],
                              file=sys.stderr)
                        rc = 1
                    elif frac > a.max_degraded:
                        print(f"CHAOS DEGRADATION OVER BOUND: "
                              f"{frac:.3f} > {a.max_degraded}",
                              file=sys.stderr)
                        rc = 1
                else:
                    parity = verdicts == baseline_verdicts
                    out["verdict_parity"] = parity
                    if not parity:
                        print("PARITY MISMATCH:",
                              list(zip(baseline_verdicts, verdicts)),
                              file=sys.stderr)
                        rc = 1
                # Evidence-digest parity: same history + same decision
                # path must hash to the same stability-core digest in
                # both arms.  The ring outlives shutdown, so late
                # collection is safe.
                for i in sorted(prov_sample):
                    if evid[i]:
                        b = svc.get_evidence(evid[i])
                        if b:
                            served_bundles[i] = b
                ep, ep_fail = _evidence_parity(
                    baseline_bundles, served_bundles,
                    verdicts, baseline_verdicts)
                out["evidence_parity"] = ep
                for msg in ep_fail:
                    print(f"EVIDENCE DIGEST MISMATCH: {msg}",
                          file=sys.stderr)
                    rc = 1
                print(f"evidence:   {ep}")
                out["speedup"] = round(
                    out["service"]["throughput_rps"]
                    / out["sequential"]["throughput_rps"], 2)
                print(f"speedup:    {out['speedup']}x "
                      f"(parity: {out['verdict_parity']})")

        # Backpressure contract: a full queue REJECTS (retry-after), it
        # never buffers unboundedly.  Unstarted service = no drain race.
        # The probe generates its own max_queue+1 histories so a small
        # --requests can't make it a false failure.
        bp = CheckService(capacity=capacity, max_queue=4)
        probe = [
            valid_register_history(a.ops, a.procs, seed=10_000 + i,
                                   info_rate=a.info_rate)
            for i in range(4 + 1)
        ]
        accepted = 0
        rejected = None
        try:
            for hh in probe:
                try:
                    bp.submit(hh, client="flood")
                    accepted += 1
                except QueueFull as e:
                    rejected = round(e.retry_after, 3)
                    break
        finally:
            bp.shutdown(drain=False)
        out["backpressure"] = {
            "max_queue": 4, "accepted": accepted,
            "rejected_with_retry_after_s": rejected,
        }
        if rejected is None:
            print("BACKPRESSURE MISSING: full queue did not reject",
                  file=sys.stderr)
            rc = 1
        print(f"backpressure: {out['backpressure']}")

    if rc == 0:
        # Record the round in the perf-regression ledger (obs.regress):
        # the service/sequential headline numbers plus the telemetry
        # stage rollup when --telemetry-dir captured one.  Axes mark the
        # scenario (arrival pattern, geometry spread, chaos) so
        # perfwatch only baselines like against like.  Failed runs are
        # not recorded — their numbers are evidence for the failure, not
        # a baseline.  Best-effort: ledger IO must not fail the load run.
        try:
            from jepsen_tpu.obs import regress

            metrics: dict = {}
            if "service" in out:
                sv = out["service"]
                metrics.update(
                    service_rps=sv["throughput_rps"],
                    service_p50_s=sv["p50_s"], service_p95_s=sv["p95_s"],
                )
                if sv.get("continuous_occupancy") is not None:
                    metrics["service_occupancy"] = sv["continuous_occupancy"]
                icls = (sv.get("classes") or {}).get("interactive")
                if icls:
                    metrics["interactive_p50_s"] = icls["p50_s"]
            if "sequential" in out:
                metrics["sequential_rps"] = out["sequential"]["throughput_rps"]
            if "speedup" in out:
                metrics["speedup"] = out["speedup"]
            axes = {"arrival": a.arrival, "geometry": a.geometry_spread}
            if a.chaos_seed is not None:
                axes["chaos"] = str(a.chaos_seed)
            if a.inject_latency_ms:
                axes["inject_latency_ms"] = str(a.inject_latency_ms)
            if a.no_continuous:
                axes["continuous"] = "off"
            summary = rec.summary if rec is not None else None
            stages, extra_metrics = regress.stage_rollup(summary)
            metrics.update(extra_metrics)
            regress.append_record(
                regress.make_record("loadgen", metrics, stages=stages,
                                    axes=axes))
        except Exception as e:  # noqa: BLE001 — never fail the run on this
            print(f"warning: perf-ledger append failed: {e}", file=sys.stderr)

    print(json.dumps({"loadgen": out}))
    return rc


if __name__ == "__main__":
    sys.exit(main())
