"""Pretty-print a telemetry.jsonl (or telemetry.json) as summary tables.

The reference consumer of the obs API's on-disk artifacts: point it at a
run's store directory (or either telemetry file directly) and it prints
the same phase / checker / ladder-stage tables the web UI renders.

  python tools/trace_summarize.py store/my-test/latest
  python tools/trace_summarize.py store/my-test/2026.../telemetry.jsonl
  python tools/trace_summarize.py --json telemetry.jsonl   # re-rolled summary
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from jepsen_tpu.obs.summary import format_summary, summarize  # noqa: E402
from jepsen_tpu.obs.trace import read_jsonl_events  # noqa: E402


def load_summary(path: Path) -> dict:
    """Resolve a run dir / telemetry.jsonl / telemetry.json into a summary
    dict.  JSONL is always re-rolled (it is the source of truth; the .json
    rollup may be stale after a crash).  A partially-written JSONL (a
    crashed writer truncates the LAST line mid-write) is read tolerantly
    — parseable lines summarize, the skip is reported on stderr; a file
    with nothing parseable, or a corrupt .json rollup, raises ValueError
    with the path named (main turns that into a clear message + exit 1,
    never a traceback)."""
    path = Path(path)
    if path.is_dir():
        jsonl = path / "telemetry.jsonl"
        rolled = path / "telemetry.json"
        if jsonl.exists():
            path = jsonl
        elif rolled.exists():
            path = rolled
        else:
            raise FileNotFoundError(
                f"no telemetry.jsonl/.json in {path} (was the run recorded "
                "with --no-telemetry?)"
            )
    if path.suffix == ".jsonl":
        events = read_jsonl_events(path)
        skipped = next(
            (e["skipped-lines"] for e in events if "skipped-lines" in e), 0
        )
        if skipped:
            print(
                f"warning: skipped {skipped} malformed line(s) in {path} "
                "(partially-written stream?)",
                file=sys.stderr,
            )
        if not events:
            raise ValueError(f"{path}: empty telemetry stream (the "
                             "recording never wrote its header)")
        return summarize(events)
    try:
        summary = json.loads(path.read_text())
    except ValueError as e:
        raise ValueError(
            f"{path}: not valid JSON ({e}) — if the run crashed "
            "mid-write, point at its telemetry.jsonl instead"
        ) from None
    if not isinstance(summary, dict):
        raise ValueError(f"{path}: expected a telemetry summary object")
    return summary


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="run directory, telemetry.jsonl, or telemetry.json")
    ap.add_argument("--json", action="store_true",
                    help="print the rolled-up summary as JSON instead of tables"
                         " (scripting: jq '.serve', '.ladder[0]', ...)")
    opts = ap.parse_args(argv)
    try:
        summary = load_summary(Path(opts.path))
    except (FileNotFoundError, OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    if opts.json:
        print(json.dumps(summary, indent=1))
    else:
        print(format_summary(summary), end="")
    return 0


if __name__ == "__main__":
    sys.exit(main())
