"""Pretty-print a telemetry.jsonl (or telemetry.json) as summary tables.

The reference consumer of the obs API's on-disk artifacts: point it at a
run's store directory (or either telemetry file directly) and it prints
the same phase / checker / ladder-stage tables the web UI renders.

  python tools/trace_summarize.py store/my-test/latest
  python tools/trace_summarize.py store/my-test/2026.../telemetry.jsonl
  python tools/trace_summarize.py --json telemetry.jsonl   # re-rolled summary
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from jepsen_tpu.obs.summary import format_summary, summarize  # noqa: E402


def load_summary(path: Path) -> dict:
    """Resolve a run dir / telemetry.jsonl / telemetry.json into a summary
    dict.  JSONL is always re-rolled (it is the source of truth; the .json
    rollup may be stale after a crash)."""
    path = Path(path)
    if path.is_dir():
        jsonl = path / "telemetry.jsonl"
        rolled = path / "telemetry.json"
        if jsonl.exists():
            path = jsonl
        elif rolled.exists():
            path = rolled
        else:
            raise FileNotFoundError(f"no telemetry.jsonl/.json in {path}")
    if path.suffix == ".jsonl":
        events = [
            json.loads(line)
            for line in path.read_text().splitlines()
            if line.strip()
        ]
        return summarize(events)
    return json.loads(path.read_text())


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="run directory, telemetry.jsonl, or telemetry.json")
    ap.add_argument("--json", action="store_true",
                    help="print the rolled-up summary as JSON instead of tables")
    opts = ap.parse_args(argv)
    try:
        summary = load_summary(Path(opts.path))
    except (FileNotFoundError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    if opts.json:
        print(json.dumps(summary, indent=1))
    else:
        print(format_summary(summary), end="")
    return 0


if __name__ == "__main__":
    sys.exit(main())
