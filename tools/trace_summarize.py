"""Pretty-print a telemetry.jsonl (or telemetry.json) as summary tables.

The reference consumer of the obs API's on-disk artifacts: point it at a
run's store directory (or either telemetry file directly) and it prints
the same phase / checker / ladder-stage tables the web UI renders.

  python tools/trace_summarize.py store/my-test/latest
  python tools/trace_summarize.py store/my-test/2026.../telemetry.jsonl
  python tools/trace_summarize.py --json telemetry.jsonl   # re-rolled summary
  python tools/trace_summarize.py --diff RUN_A RUN_B       # stage-table diff

``--diff`` answers "what got slower between these two runs": both runs'
stage tables (ladder rungs + rolled-up spans, via
``obs.regress.stage_rollup``) are diffed and printed top-regressing-span
first — the same attribution code ``tools/perfwatch.py`` uses when a
ledger headline regresses.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from jepsen_tpu.obs.summary import format_summary, summarize  # noqa: E402
from jepsen_tpu.obs.trace import read_jsonl_events  # noqa: E402


def load_summary(path: Path) -> dict:
    """Resolve a run dir / telemetry.jsonl / telemetry.json into a summary
    dict.  JSONL is always re-rolled (it is the source of truth; the .json
    rollup may be stale after a crash).  A partially-written JSONL (a
    crashed writer truncates the LAST line mid-write) is read tolerantly
    — parseable lines summarize, the skip is reported on stderr; a file
    with nothing parseable, or a corrupt .json rollup, raises ValueError
    with the path named (main turns that into a clear message + exit 1,
    never a traceback)."""
    path = Path(path)
    if path.is_dir():
        jsonl = path / "telemetry.jsonl"
        rolled = path / "telemetry.json"
        if jsonl.exists():
            path = jsonl
        elif rolled.exists():
            path = rolled
        else:
            raise FileNotFoundError(
                f"no telemetry.jsonl/.json in {path} (was the run recorded "
                "with --no-telemetry?)"
            )
    if path.suffix == ".jsonl":
        events = read_jsonl_events(path)
        skipped = next(
            (e["skipped-lines"] for e in events if "skipped-lines" in e), 0
        )
        if skipped:
            print(
                f"warning: skipped {skipped} malformed line(s) in {path} "
                "(partially-written stream?)",
                file=sys.stderr,
            )
        if not events:
            raise ValueError(f"{path}: empty telemetry stream (the "
                             "recording never wrote its header)")
        return summarize(events)
    try:
        summary = json.loads(path.read_text())
    except ValueError as e:
        raise ValueError(
            f"{path}: not valid JSON ({e}) — if the run crashed "
            "mid-write, point at its telemetry.jsonl instead"
        ) from None
    if not isinstance(summary, dict):
        raise ValueError(f"{path}: expected a telemetry summary object")
    return summary


def diff_summaries(path_a: Path, path_b: Path, *, as_json: bool) -> int:
    """The --diff mode: stage-table diff of two runs, top regressing
    spans (B slower than A) first.  Shares obs.regress's attribution
    code with perfwatch — one definition of "what got slower"."""
    from jepsen_tpu.obs import regress

    rollups = []
    for p in (path_a, path_b):
        stages, metrics = regress.stage_rollup(load_summary(p))
        rollups.append((stages, metrics))
    rows = regress.diff_stage_tables(rollups[0][0], rollups[1][0])
    metric_rows = regress.diff_stage_tables(rollups[0][1], rollups[1][1])
    if as_json:
        print(json.dumps({"stages": rows, "metrics": metric_rows}, indent=1))
        return 0
    print(f"stage diff: A={path_a}  B={path_b}  "
          "(positive delta = slower in B)")
    print(regress.format_stage_diff(rows), end="")
    if metric_rows:
        print("\nside-channel metrics (occupancy, dedup µs, spill, ...):")
        print(regress.format_stage_diff(metric_rows), end="")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", nargs="?", default=None,
                    help="run directory, telemetry.jsonl, or telemetry.json")
    ap.add_argument("--json", action="store_true",
                    help="print the rolled-up summary as JSON instead of tables"
                         " (scripting: jq '.serve', '.ladder[0]', ...)")
    ap.add_argument("--diff", nargs=2, metavar=("RUN_A", "RUN_B"),
                    default=None,
                    help="diff two runs' stage tables instead of "
                         "summarizing one (top regressing spans first)")
    opts = ap.parse_args(argv)
    if (opts.path is None) == (opts.diff is None):
        print("error: give either a run path or --diff RUN_A RUN_B",
              file=sys.stderr)
        return 2
    try:
        if opts.diff:
            return diff_summaries(Path(opts.diff[0]), Path(opts.diff[1]),
                                  as_json=opts.json)
        summary = load_summary(Path(opts.path))
    except (FileNotFoundError, OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    if opts.json:
        print(json.dumps(summary, indent=1))
    else:
        print(format_summary(summary), end="")
    return 0


if __name__ == "__main__":
    sys.exit(main())
