"""Pretty-print a telemetry.jsonl (or telemetry.json) as summary tables.

The reference consumer of the obs API's on-disk artifacts: point it at a
run's store directory (or either telemetry file directly) and it prints
the same phase / checker / ladder-stage tables the web UI renders.

  python tools/trace_summarize.py store/my-test/latest
  python tools/trace_summarize.py store/my-test/2026.../telemetry.jsonl
  python tools/trace_summarize.py --json telemetry.jsonl   # re-rolled summary
  python tools/trace_summarize.py --diff RUN_A RUN_B       # stage-table diff

Give MULTIPLE run paths (a fleet: the router's recording plus each
replica's, as announced by ``GET /fleet``) and the recorder streams are
clock-aligned on their ``meta`` t0 epochs and merged into one stream
before summarizing — per-stream offsets and the residual post-alignment
clock skew are reported first.  Works for the summary tables and for
``--requests``/``--critpath``/``--devices``:

  python tools/trace_summarize.py --requests router-dir rep-a rep-b

Flight-analyzer modes (jepsen_tpu.obs.critpath) — these need the raw
jsonl (span intervals), not the rolled-up .json:

  --requests   per-request latency decomposition: one row per trace id
               (queue / pack / launch / confirm / other seconds, summing
               to the recorded end-to-end latency)
  --critpath   the span critical path: what bounds wall clock, ranked
               by on-path seconds, with per-span slack
  --devices    per-device busy/idle/bubble fractions from the
               device-attributed launch spans
  --perf-record  append a fingerprinted ``kind:"critpath"`` record to
               the perf ledger (obs.regress) timing the analysis pass
               itself, so ``perfwatch gate`` flags analyzer-cost creep

Verdict-provenance mode (jepsen_tpu.obs.provenance):

  --provenance  decision-path audit table over the run's evidence
               bundles (``<run-dir>/evidence/*.json``): one row per
               verdict — source, checker, verdict, engine/backend
               resolution, decision-path length, fault-event count,
               and the stability-core digest — followed by each
               bundle's decision path as a compact arrow chain.

Any combination composes with ``--json`` (one merged JSON object).

``--diff`` answers "what got slower between these two runs": both runs'
stage tables (ladder rungs + rolled-up spans, via
``obs.regress.stage_rollup``) are diffed and printed top-regressing-span
first — the same attribution code ``tools/perfwatch.py`` uses when a
ledger headline regresses.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from jepsen_tpu.obs import critpath as cpm  # noqa: E402
from jepsen_tpu.obs.summary import format_summary, summarize  # noqa: E402
from jepsen_tpu.obs.trace import (  # noqa: E402
    align_streams, merge_aligned_events, read_jsonl_events)


def _resolve(path: Path) -> Path:
    """Run dir → its telemetry file (jsonl preferred: source of truth)."""
    if path.is_dir():
        jsonl = path / "telemetry.jsonl"
        rolled = path / "telemetry.json"
        if jsonl.exists():
            return jsonl
        if rolled.exists():
            return rolled
        raise FileNotFoundError(
            f"no telemetry.jsonl/.json in {path} (was the run recorded "
            "with --no-telemetry?)"
        )
    return path


def load_events(path: Path) -> tuple[list[dict], int]:
    """The raw event stream + skipped-line count (jsonl only — the
    flight-analyzer modes need span intervals the .json rollup doesn't
    keep)."""
    path = _resolve(Path(path))
    if path.suffix != ".jsonl":
        raise ValueError(
            f"{path}: --requests/--critpath/--devices need the raw "
            "telemetry.jsonl (span intervals), not the rolled-up summary"
        )
    events, skipped = read_jsonl_events(path)
    if skipped:
        print(
            f"warning: skipped {skipped} malformed line(s) in {path} "
            "(partially-written stream?)",
            file=sys.stderr,
        )
    if not events:
        raise ValueError(f"{path}: empty telemetry stream (the "
                         "recording never wrote its header)")
    return events, skipped


def _stream_label(path: Path) -> str:
    """A stream's display label: its run directory's name."""
    p = _resolve(Path(path))
    return p.parent.name if p.name.startswith("telemetry") else p.stem


def load_merged_events(paths) -> tuple[list[dict], int, dict]:
    """N recorder streams (router + replicas) clock-aligned on their
    ``meta`` t0 epochs and merged into one event stream.  Returns
    ``(events, skipped, info)`` with ``info`` the alignment report from
    :func:`jepsen_tpu.obs.trace.align_streams` (per-stream offsets,
    cross-process traces, residual skew)."""
    streams = []
    total_skipped = 0
    for p in paths:
        events, skipped = load_events(Path(p))
        streams.append((_stream_label(Path(p)), events, skipped))
        total_skipped += skipped
    aligned, info = align_streams(streams)
    return merge_aligned_events(aligned), total_skipped, info


def print_alignment(info: dict) -> None:
    """The multi-stream alignment report: what offset each recorder got
    and how much clock skew survived it (wall clocks are not monotonic
    across hosts — the residue is reported, never hidden)."""
    offs = ", ".join(f"{label}+{off:.6f}s"
                     for label, off in sorted(info["offsets"].items()))
    print(f"aligned {len(info['offsets'])} recorder stream(s) on t0 epoch "
          f"{info['t0']}: {offs}")
    if info.get("missing_t0"):
        print("warning: no t0 epoch in meta header for "
              f"{', '.join(info['missing_t0'])} (aligned at offset 0)",
              file=sys.stderr)
    xpt = info.get("cross_process_traces") or []
    if xpt:
        print(f"{len(xpt)} request trace(s) span streams")
    skew = info.get("residual_skew_s") or 0.0
    if skew:
        print(f"residual clock skew after alignment: {skew:.6f} s")


def load_summary(path: Path) -> dict:
    """Resolve a run dir / telemetry.jsonl / telemetry.json into a summary
    dict.  JSONL is always re-rolled (it is the source of truth; the .json
    rollup may be stale after a crash).  A partially-written JSONL (a
    crashed writer truncates the LAST line mid-write) is read tolerantly
    — parseable lines summarize, the skip is reported on stderr and as
    the summary's ``telemetry.skipped_lines`` field; a file with nothing
    parseable, or a corrupt .json rollup, raises ValueError with the
    path named (main turns that into a clear message + exit 1, never a
    traceback)."""
    path = _resolve(Path(path))
    if path.suffix == ".jsonl":
        events, skipped = load_events(path)
        return summarize(events, skipped_lines=skipped)
    try:
        summary = json.loads(path.read_text())
    except ValueError as e:
        raise ValueError(
            f"{path}: not valid JSON ({e}) — if the run crashed "
            "mid-write, point at its telemetry.jsonl instead"
        ) from None
    if not isinstance(summary, dict):
        raise ValueError(f"{path}: expected a telemetry summary object")
    return summary


def diff_summaries(path_a: Path, path_b: Path, *, as_json: bool) -> int:
    """The --diff mode: stage-table diff of two runs, top regressing
    spans (B slower than A) first.  Shares obs.regress's attribution
    code with perfwatch — one definition of "what got slower"."""
    from jepsen_tpu.obs import regress

    rollups = []
    for p in (path_a, path_b):
        stages, metrics = regress.stage_rollup(load_summary(p))
        rollups.append((stages, metrics))
    rows = regress.diff_stage_tables(rollups[0][0], rollups[1][0])
    metric_rows = regress.diff_stage_tables(rollups[0][1], rollups[1][1])
    if as_json:
        print(json.dumps({"stages": rows, "metrics": metric_rows}, indent=1))
        return 0
    print(f"stage diff: A={path_a}  B={path_b}  "
          "(positive delta = slower in B)")
    print(regress.format_stage_diff(rows), end="")
    if metric_rows:
        print("\nside-channel metrics (occupancy, dedup µs, spill, ...):")
        print(regress.format_stage_diff(metric_rows), end="")
    return 0


def provenance_table(path: Path, *, as_json: bool) -> int:
    """The --provenance mode: decision-path audit table over a run's
    evidence bundles — the offline twin of the web run page's evidence
    listing.  Corrupt bundles are skipped with a warning (they are
    already quarantined aside by the durable reader); auditing them is
    ``tools/evidence.py verify``'s job."""
    from jepsen_tpu.obs import provenance
    from jepsen_tpu.obs.summary import _table

    p = Path(path)
    run_dir = p if p.is_dir() else p.parent
    doc: list[dict] = []
    rows: list[list] = []
    for bp, b in provenance.iter_bundles(run_dir):
        steps = [str(e.get("event") or "?")
                 for e in (b.get("decision_path") or [])]
        faults = sum(1 for s in steps if s.startswith("fault."))
        eng = b.get("engine") or {}
        eng_s = str(eng.get("engine") or "?")
        for k in ("backend", "graph_engine", "cycle_backend"):
            if eng.get(k):
                eng_s += f"/{eng[k]}"
        doc.append({
            "id": b.get("id"), "source": b.get("source"),
            "checker": b.get("checker"), "verdict": b.get("verdict"),
            "engine": eng, "decision_path": steps, "faults": faults,
            "digest": b.get("digest"), "path": str(bp),
        })
        rows.append([
            str(b.get("id"))[:12], str(b.get("source")),
            str(b.get("checker")), str(b.get("verdict")), eng_s,
            len(steps), faults, str(b.get("digest"))[:12],
        ])
    if as_json:
        print(json.dumps({"provenance": doc}, indent=1, default=str))
        return 0
    if not rows:
        print(f"no evidence bundles under {run_dir}/evidence (run "
              "predates verdict provenance, or nothing was checked?)")
        return 1
    print(f"verdict provenance: {len(rows)} evidence bundle(s) under "
          f"{run_dir}/evidence")
    print(_table(["bundle", "source", "checker", "verdict", "engine",
                  "steps", "faults", "digest"], rows), end="")
    print("\ndecision paths (first 8 steps; tools/evidence.py "
          "verify|replay re-certifies any bundle):")
    for d in doc:
        steps = d["decision_path"]
        tail = f" ..+{len(steps) - 8}" if len(steps) > 8 else ""
        print(f"  {str(d['id'])[:12]}: " + " -> ".join(steps[:8]) + tail)
    return 0


def analyze(path: Path, *, requests: bool, critpath: bool, devices: bool,
            as_json: bool, perf_record: bool,
            events: list | None = None, skipped: int = 0) -> int:
    """The flight-analyzer modes over one run's raw event stream (or a
    pre-merged multi-recorder stream when ``events`` is given)."""
    if events is None:
        events, skipped = load_events(path)
    t0 = time.perf_counter()
    doc: dict = {}
    if requests:
        doc["requests"] = cpm.decompose_requests(events)
    if critpath:
        doc["critpath"] = cpm.critical_path(events)
    if devices:
        doc["devices"] = cpm.device_timeline(events)
    analysis_s = time.perf_counter() - t0
    if skipped:
        doc["telemetry"] = {"skipped_lines": skipped}
    if as_json:
        print(json.dumps(doc, indent=1, default=str))
    else:
        if requests:
            print("per-request latency decomposition:")
            print(cpm.format_requests(doc["requests"]), end="")
        if critpath:
            if requests:
                print()
            print(cpm.format_critpath(doc["critpath"]), end="")
        if devices:
            if requests or critpath:
                print()
            print(cpm.format_devices(doc["devices"]), end="")
    if perf_record:
        # The analyzer's own cost, trended: a kind:"critpath" ledger
        # record so perfwatch gate flags analysis-cost creep the same
        # way it flags ladder-stage creep.  Best-effort by contract.
        try:
            from jepsen_tpu.obs import regress

            metrics = {
                "analysis_s": round(analysis_s, 6),
                "events": float(len(events)),
            }
            cp = doc.get("critpath")
            if cp:
                metrics["critpath_total_s"] = cp["total_s"]
                metrics["critpath_wall_s"] = cp["wall_s"]
            if "requests" in doc:
                metrics["requests"] = float(len(doc["requests"]))
            # probe_devices=False: a pure-host analysis pass must not
            # initialize (or hang on) a device backend for its
            # fingerprint — the same convention as graftlint and the
            # bench outage path.
            regress.append_record(regress.make_record(
                "critpath", metrics,
                fp=regress.fingerprint(probe_devices=False)))
        except Exception as e:  # noqa: BLE001 — ledger IO must not fail
            print(f"warning: perf-ledger append failed: {e}",
                  file=sys.stderr)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", nargs="*", default=None,
                    help="run directory, telemetry.jsonl, or telemetry.json; "
                         "several paths (router + replicas) are clock-"
                         "aligned and merged into one stream first")
    ap.add_argument("--json", action="store_true",
                    help="print the rolled-up summary as JSON instead of tables"
                         " (scripting: jq '.serve', '.ladder[0]', ...)")
    ap.add_argument("--requests", action="store_true",
                    help="per-request latency decomposition from the raw "
                         "jsonl (queue/pack/launch/confirm/other seconds "
                         "per trace id)")
    ap.add_argument("--critpath", action="store_true",
                    help="span critical path: what bounds wall clock, "
                         "ranked, with per-span slack")
    ap.add_argument("--devices", action="store_true",
                    help="per-device busy/idle/bubble timeline from the "
                         "device-attributed launch spans")
    ap.add_argument("--perf-record", action="store_true",
                    help="append a kind:'critpath' perf-ledger record "
                         "timing the analysis pass (perfwatch gates "
                         "analyzer-cost creep)")
    ap.add_argument("--provenance", action="store_true",
                    help="decision-path audit table over the run's "
                         "evidence bundles (evidence/*.json): engine "
                         "resolution, fallbacks, fault events, digest "
                         "per verdict")
    ap.add_argument("--diff", nargs=2, metavar=("RUN_A", "RUN_B"),
                    default=None,
                    help="diff two runs' stage tables instead of "
                         "summarizing one (top regressing spans first)")
    opts = ap.parse_args(argv)
    if bool(opts.path) == (opts.diff is not None):
        print("error: give either a run path or --diff RUN_A RUN_B",
              file=sys.stderr)
        return 2
    if opts.perf_record and not (opts.requests or opts.critpath
                                 or opts.devices):
        # --perf-record times the analysis pass; alone it implies the
        # critical-path mode (silently recording nothing would be worse)
        opts.critpath = True
    analyzer = opts.requests or opts.critpath or opts.devices
    merged = None
    try:
        if opts.diff:
            return diff_summaries(Path(opts.diff[0]), Path(opts.diff[1]),
                                  as_json=opts.json)
        if opts.provenance:
            if len(opts.path) > 1:
                print("error: --provenance reads one run's evidence dir",
                      file=sys.stderr)
                return 2
            return provenance_table(Path(opts.path[0]), as_json=opts.json)
        if len(opts.path) > 1:
            events, skipped, info = load_merged_events(opts.path)
            if not opts.json:
                print_alignment(info)
            merged = (events, skipped)
        if analyzer:
            if merged is not None:
                events, skipped = merged
                return analyze(
                    Path(opts.path[0]), requests=opts.requests,
                    critpath=opts.critpath, devices=opts.devices,
                    as_json=opts.json, perf_record=opts.perf_record,
                    events=events, skipped=skipped,
                )
            return analyze(
                Path(opts.path[0]), requests=opts.requests,
                critpath=opts.critpath, devices=opts.devices,
                as_json=opts.json, perf_record=opts.perf_record,
            )
        if merged is not None:
            events, skipped = merged
            summary = summarize(events, skipped_lines=skipped)
        else:
            summary = load_summary(Path(opts.path[0]))
    except (FileNotFoundError, OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    if opts.json:
        print(json.dumps(summary, indent=1))
    else:
        print(format_summary(summary), end="")
    return 0


if __name__ == "__main__":
    sys.exit(main())
