"""Honest steady-state profiling of the batched WGL kernel on the real chip.

Separates compile time from run time, times each capacity stage at the
measured batch size, and reports per-op throughput. Run on TPU (default
backend) or CPU (JAX_PLATFORMS=cpu).
"""

from __future__ import annotations

import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np

from genhist import corrupt, valid_register_history

import jax

from jepsen_tpu import models as m
from jepsen_tpu.checker import wgl_cpu
from jepsen_tpu.ops import wgl
from jepsen_tpu.parallel import batch as pbatch


def time_runner(runner, args, reps=3):
    out = runner(*args)
    jax.block_until_ready(out)
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = runner(*args)
        jax.block_until_ready(out)
        ts.append(time.perf_counter() - t0)
    return min(ts), out


def main():
    n_hist = int(sys.argv[1]) if len(sys.argv) > 1 else 256
    n_ops = int(sys.argv[2]) if len(sys.argv) > 2 else 40
    procs = int(sys.argv[3]) if len(sys.argv) > 3 else 4
    print(f"devices: {jax.devices()}", file=sys.stderr)
    model = m.CASRegister(None)
    hists = []
    for i in range(n_hist):
        hh = valid_register_history(n_ops, procs, seed=i, info_rate=0.1)
        if i % 5 == 4:
            hh = corrupt(hh, seed=i)
        hists.append(hh)
    total_ops = sum(len(hh) for hh in hists) // 2

    packs = [wgl.pack(model, hh) for hh in hists]
    B = 1 << max(6, (max(p["B"] for p in packs) - 1).bit_length())
    P = wgl._bucket(max(p["P"] for p in packs), [8, 16, 32, 64, 128])
    G = wgl._bucket(max(p["G"] for p in packs), [4, 8, 16, 32, 64])
    print(f"shapes: n={n_hist} B={B} P={P} G={G}", file=sys.stderr)
    t0 = time.perf_counter()
    stacked = pbatch._stack(packs, B, P, G)
    print(f"pack+stack host time: {time.perf_counter()-t0:.3f}s", file=sys.stderr)
    args = [stacked[k] for k in pbatch._ARG_ORDER]

    for cap in (64, 512):
        t0 = time.perf_counter()
        runner = wgl.batched_runner(packs[0]["step"], cap, 8, P, G, (P + 31) // 32)
        out = runner(*args)
        jax.block_until_ready(out)
        compile_s = time.perf_counter() - t0
        best, out = time_runner(runner, args)
        valid, failed_at, lossy, peak = (np.asarray(x) for x in out)
        print(
            f"cap={cap}: compile+first={compile_s:.2f}s steady={best*1e3:.1f}ms"
            f" -> {total_ops/best:,.0f} ops/s  lossy={lossy.sum()}/{n_hist}"
            f" peak_max={peak.max()}",
            file=sys.stderr,
        )

    t0 = time.perf_counter()
    for hh in hists[: min(64, n_hist)]:
        wgl_cpu.dfs_analysis(model, hh)
    cpu_s = (time.perf_counter() - t0) * (n_hist / min(64, n_hist))
    print(
        f"cpu DFS est total: {cpu_s:.2f}s -> {total_ops/cpu_s:,.0f} ops/s",
        file=sys.stderr,
    )


if __name__ == "__main__":
    main()
