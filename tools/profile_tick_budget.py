"""How does the async tick budget trade wall clock vs verdicts?

The vmapped while_loop runs until EVERY lane is done — straggler lanes
(lossy ones grinding toward a True-with-loss or the budget) dictate the
stage. Sweep the budget multiplier and watch time + unknowns.
"""

import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from genhist import corrupt, valid_register_history
from jepsen_tpu import models as m
from jepsen_tpu.ops import wgl
from jepsen_tpu.parallel import batch as pbatch

N, OPS, PROCS, INFO, NV, CORR = 128, 100, 8, 0.3, 8, 4


def main():
    model = m.CASRegister(None)
    hists = []
    for i in range(N):
        hh = valid_register_history(OPS, PROCS, seed=i, info_rate=INFO, n_values=NV)
        if i % CORR == CORR - 1:
            hh = corrupt(hh, seed=i)
        hists.append(hh)

    orig = wgl.async_ticks

    def wide(formula):
        """Vary only the WIDE-stage (cap >= 1024) budget; narrow stages
        keep the tuned default.  With carried frontiers the resumed
        rungs see small remaining-B, so round-4's 'wide needs 2B+64'
        deserves re-measurement."""
        def fn(B, capacity=None):
            if capacity is not None and capacity < 1024:
                return orig(B, capacity)
            return formula(B)
        return fn

    which = sys.argv[1:]
    for label, fn in [
        ("default (narrow 3B/2+32, wide 2B+64)", orig),
        ("all T=B+32", lambda B, capacity=None: B + 32),
        ("all T=3B/2+32", lambda B, capacity=None: (3 * B) // 2 + 32),
        ("all T=3B+64", lambda B, capacity=None: 3 * B + 64),
        ("wide T=B+64", wide(lambda B: B + 64)),
        ("wide T=3B/2+32", wide(lambda B: (3 * B) // 2 + 32)),
    ]:
        if which and not any(w in label for w in which):
            continue
        wgl.async_ticks = fn
        kw = dict(capacity=(128, 512, 2048), cpu_fallback=False,
                  exact_escalation=(), confirm_refutations=False)
        pbatch.batch_analysis(model, hists, **kw)
        best = None
        for _ in range(2):
            t0 = time.perf_counter()
            rs = pbatch.batch_analysis(model, hists, **kw)
            best = min(best or 9e9, time.perf_counter() - t0)
        unk = sum(1 for r in rs if r["valid?"] == "unknown")
        print(f"{label:42s} {best*1e3:8.1f} ms  unknowns={unk}")
    wgl.async_ticks = orig


if __name__ == "__main__":
    main()
