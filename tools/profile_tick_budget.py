"""How does the async tick budget trade wall clock vs verdicts?

The vmapped while_loop runs until EVERY lane is done — straggler lanes
(lossy ones grinding toward a True-with-loss or the budget) dictate the
stage. Sweep the budget multiplier and watch time + unknowns.
"""

import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from genhist import corrupt, valid_register_history
from jepsen_tpu import models as m
from jepsen_tpu.ops import wgl
from jepsen_tpu.parallel import batch as pbatch

N, OPS, PROCS, INFO, NV, CORR = 128, 100, 8, 0.3, 8, 4


def main():
    model = m.CASRegister(None)
    hists = []
    for i in range(N):
        hh = valid_register_history(OPS, PROCS, seed=i, info_rate=INFO, n_values=NV)
        if i % CORR == CORR - 1:
            hh = corrupt(hh, seed=i)
        hists.append(hh)

    orig = wgl.async_ticks
    which = sys.argv[1:]
    for label, fn in [
        ("T=2B+64 (default)", orig),
        ("T=B+32", lambda B: B + 32),
        ("T=3B/2+32", lambda B: (3 * B) // 2 + 32),
        ("T=3B+64", lambda B: 3 * B + 64),
    ]:
        if which and not any(w in label for w in which):
            continue
        wgl.async_ticks = fn
        kw = dict(capacity=(128, 512, 2048), cpu_fallback=False,
                  exact_escalation=(), confirm_refutations=False)
        pbatch.batch_analysis(model, hists, **kw)
        best = None
        for _ in range(2):
            t0 = time.perf_counter()
            rs = pbatch.batch_analysis(model, hists, **kw)
            best = min(best or 9e9, time.perf_counter() - t0)
        unk = sum(1 for r in rs if r["valid?"] == "unknown")
        print(f"{label:42s} {best*1e3:8.1f} ms  unknowns={unk}")
    wgl.async_ticks = orig


if __name__ == "__main__":
    main()
