#!/usr/bin/env python
"""graftlint — the checker stack's static-analysis gate.

    python tools/graftlint.py                 # human output, exit 1 on findings
    python tools/graftlint.py --json          # machine-readable findings
    python tools/graftlint.py --rules lock-guard,telemetry-orphan
    python tools/graftlint.py --no-baseline   # show baselined findings too

Three analyzers (see ``jepsen_tpu/lint/``): trace discipline over the
jit/shard_map launch surface, ``# guarded-by:`` lock discipline over the
serving stack, and telemetry drift against the documented inventories.
Suppressions live in ``.graftlint-baseline.json`` (triaged, one-line
``why`` each) and inline ``# graftlint: disable=<rule>`` comments.

Exit codes: 0 no unsuppressed findings; 1 findings; 2 internal error.

Unless ``--ledger off``, the run appends a ``kind:"lint"`` record (wall
seconds + per-analyzer stage table) to the perf ledger so ``perfwatch
gate`` flags analyzer-cost creep the same way it flags suite-time creep.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from jepsen_tpu.lint import Baseline, load_baseline  # noqa: E402
from jepsen_tpu.lint.runner import ALL_RULES, run_lint  # noqa: E402


def _append_ledger(result, ledger: str | None) -> None:
    """Best-effort ``kind:"lint"`` perf-ledger record (analyzer-cost
    creep shows up in ``perfwatch gate`` next to suite-time creep)."""
    try:
        from jepsen_tpu.obs import regress

        # wall_s is the only GATED metric (lower-better, stage-attributed
        # via the per-analyzer stage table); file/finding counts ride in
        # extra — the repo growing a file must not read as a regression.
        rec = regress.make_record(
            "lint",
            {"wall_s": round(result.wall_s, 3)},
            stages=result.stages,
            extra={"files": result.files,
                   "findings": len(result.findings),
                   "suppressed": len(result.suppressed)},
            fp=regress.fingerprint(probe_devices=False),
        )
        regress.append_record(rec, ledger)
    except Exception as e:  # noqa: BLE001 — the gate must not fail on
        print(f"graftlint: ledger append failed ({e})", file=sys.stderr)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--json", action="store_true",
                    help="emit machine-readable findings on stdout")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule subset "
                         f"(known: {', '.join(sorted(ALL_RULES))})")
    ap.add_argument("--baseline", default=None,
                    help="baseline file (default: .graftlint-baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline (show every finding)")
    ap.add_argument("--ledger", default=None,
                    help="perf-ledger path, or 'off' (default: env/store)")
    ap.add_argument("--root", default=str(REPO), help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    rules = None
    if args.rules:
        rules = {r.strip() for r in args.rules.split(",") if r.strip()}
        unknown = rules - set(ALL_RULES)
        if unknown:
            print(f"graftlint: unknown rule(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2

    root = Path(args.root)
    baseline = (Baseline(None, {}) if args.no_baseline
                else load_baseline(
                    Path(args.baseline) if args.baseline
                    else root / ".graftlint-baseline.json"))
    try:
        result = run_lint(root, rules=rules, baseline=baseline)
    except Exception as e:  # noqa: BLE001 — a crashing linter must be
        # loud and distinguishable from "findings exist"
        print(f"graftlint: internal error: {e!r}", file=sys.stderr)
        return 2

    if args.ledger != "off":
        _append_ledger(result, args.ledger)

    if args.json:
        print(json.dumps(result.as_dict(), indent=1))
    else:
        for f in result.findings:
            print(f.render())
            print(f"    key: {f.key}")
        for key in result.stale_baseline:
            print(f"graftlint: stale baseline entry (no longer fires): {key}",
                  file=sys.stderr)
        print(
            f"graftlint: {len(result.findings)} finding(s) "
            f"({len(result.suppressed)} baselined) over {result.files} "
            f"files in {result.wall_s:.2f}s "
            f"[{' '.join(f'{k}={v:.2f}s' for k, v in result.stages.items())}]"
        )
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
