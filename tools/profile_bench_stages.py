"""Where does the headline bench's steady-state time go?

Replays bench.py's exact workload through batch_analysis with variant
kwargs to isolate the ladder stages and the confirmation drain.  Run on
the real chip.

Reference consumer of the obs telemetry API: each variant's best run is
recorded through jepsen_tpu.obs, and its ladder-stage table (per-rung
wall time, compile/execute split, unknowns remaining) prints below the
headline number — the structured replacement for the ad-hoc timers the
pre-obs version of this script carried.
"""

import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from genhist import corrupt, valid_register_history
from jepsen_tpu import models as m
from jepsen_tpu import obs
from jepsen_tpu.obs.summary import format_summary
from jepsen_tpu.ops import wgl
from jepsen_tpu.parallel import batch as pbatch

N, OPS, PROCS, INFO, NV, CORR = 128, 100, 8, 0.3, 8, 4
CAPS = (128, 512, 2048)

def main():
    model = m.CASRegister(None)
    hists = []
    for i in range(N):
        hh = valid_register_history(OPS, PROCS, seed=i, info_rate=INFO, n_values=NV)
        if i % CORR == CORR - 1:
            hh = corrupt(hh, seed=i)
        hists.append(hh)

    pbatch.warm_confirm_pool()

    t0 = time.perf_counter()
    [wgl.pack(model, hh) for hh in hists]
    print(f"{'pack x128 (host)':42s} {(time.perf_counter()-t0)*1e3:8.1f} ms")

    for label, kw in [
        ("cap128 only", dict(capacity=(128,))),
        ("cap128+512", dict(capacity=(128, 512))),
        ("full ladder + confirm", dict(capacity=CAPS)),
        ("full ladder, no confirmations", dict(capacity=CAPS, confirm_refutations=False)),
    ]:
        kw.setdefault("cpu_fallback", False)
        kw.setdefault("exact_escalation", ())
        pbatch.batch_analysis(model, hists, **kw)  # warm compile
        # JEPSEN_TPU_TELEMETRY=0 keeps even the span emission out of the
        # timed window (same toggle bench.py honors).
        record = obs.env_enabled(True)
        best = None
        best_summary = None
        for _ in range(3):
            d = tempfile.mkdtemp(prefix="profile-stages-") if record else None
            with obs.recording(d, enabled=record) as rec:
                t0 = time.perf_counter()
                rs = pbatch.batch_analysis(model, hists, **kw)
                dt = time.perf_counter() - t0
            if best is None or dt < best:
                best = dt
                best_summary = rec.summary if rec is not None else None
        unk = sum(1 for r in rs if r["valid?"] == "unknown")
        print(f"{label:42s} {best*1e3:8.1f} ms  unknowns={unk}")
        if best_summary and best_summary.get("ladder"):
            print(format_summary({"ladder": best_summary["ladder"],
                                  "wall_s": best_summary["wall_s"]}))


if __name__ == "__main__":
    main()
