"""Synthetic list-append transaction histories (BASELINE config 3 shape:
cockroach-style multi-key append workloads, ≥10k txns).

Serializable by construction: transactions execute atomically in history
order (the server applies each at a point inside its window), so the
dependency graph is acyclic unless ``corrupt_wr`` injects an anomaly.
"""

from __future__ import annotations

import random

from jepsen_tpu import history as h


def append_history(
    n_txns: int,
    n_keys: int = 50,
    n_procs: int = 16,
    mops_per_txn: tuple = (1, 4),
    read_frac: float = 0.5,
    seed: int = 1,
) -> list[dict]:
    rng = random.Random(seed)
    state: dict = {k: [] for k in range(n_keys)}
    next_el: dict = {k: 0 for k in range(n_keys)}
    hist: list[dict] = []
    t = 0
    for i in range(n_txns):
        p = rng.randrange(n_procs)
        n_mops = rng.randint(*mops_per_txn)
        keys = rng.sample(range(n_keys), min(n_mops, n_keys))
        mops = []
        for k in keys:
            if rng.random() < read_frac:
                mops.append(["r", k, None])
            else:
                mops.append(["append", k, next_el[k]])
                next_el[k] += 1
        t += rng.randint(1, 5)
        invoke_mops = [list(m) for m in mops]
        hist.append(h.op(h.INVOKE, p, "txn", invoke_mops, time=t))
        done = []
        for f, k, v in mops:
            if f == "r":
                done.append(["r", k, list(state[k])])
            else:
                state[k].append(v)
                done.append(["append", k, v])
        t += rng.randint(1, 5)
        hist.append(h.op(h.OK, p, "txn", done, time=t))
    return h.index(hist)


def corrupt_wr(history: list[dict], seed: int = 2) -> list[dict]:
    """Swap two adjacent appends' observed orders on one key, injecting an
    incompatible-order / cycle anomaly."""
    rng = random.Random(seed)
    hist = [dict(o) for o in history]
    # find a read whose list has ≥2 elements and reverse its tail pair
    candidates = []
    for i, o in enumerate(hist):
        if o["type"] != h.OK:
            continue
        for m in o["value"]:
            if m[0] == "r" and isinstance(m[2], list) and len(m[2]) >= 2:
                candidates.append(i)
                break
    if not candidates:
        return hist
    i = rng.choice(candidates)
    o = hist[i]
    val = [list(m) for m in o["value"]]
    for m in val:
        if m[0] == "r" and isinstance(m[2], list) and len(m[2]) >= 2:
            m[2] = list(m[2])
            m[2][-1], m[2][-2] = m[2][-2], m[2][-1]
            break
    hist[i] = {**o, "value": val}
    return hist


def tarjan_has_cycle(n: int, edges) -> bool:
    """Iterative Tarjan SCC over an edge list — the elle-JVM-style CPU
    oracle for cycle existence (O(V+E))."""
    adj: list[list[int]] = [[] for _ in range(n)]
    for a, b in edges:
        adj[a].append(b)
    index = [0] * n
    low = [0] * n
    state = [0] * n  # 0 unvisited, 1 on stack, 2 done
    counter = [1]
    stack: list[int] = []
    for root in range(n):
        if state[root]:
            continue
        work = [(root, 0)]
        while work:
            v, pi = work[-1]
            if pi == 0:
                index[v] = low[v] = counter[0]
                counter[0] += 1
                state[v] = 1
                stack.append(v)
            advanced = False
            for j in range(pi, len(adj[v])):
                w = adj[v][j]
                if state[w] == 0:
                    work[-1] = (v, j + 1)
                    work.append((w, 0))
                    advanced = True
                    break
                if state[w] == 1:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if low[v] == index[v]:
                size = 0
                while True:
                    w = stack.pop()
                    state[w] = 2
                    size += 1
                    if w == v:
                        break
                if size > 1:
                    return True
            if work:
                u, _ = work[-1]
                low[u] = min(low[u], low[v])
    # self-loops
    for a, b in edges:
        if a == b:
            return True
    return False
