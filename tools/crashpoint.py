#!/usr/bin/env python
"""Crash-consistency audit for every durable surface.

The checker's own durable state (ladder + chunk + per-stream
checkpoints, the admission journal, drain dirs, the perf ledger, the
idempotency map)
must survive exactly the fault classes this repo exists to inject.
This tool enumerates the (surface x crash-step x corruption-mode)
matrix and drives each surface's CONSUMER through every cell, asserting
one invariant:

    after recovery the verdicts are IDENTICAL to an uninterrupted
    run, or the consumer degrades to a machine-readable corruption
    report — never a wrong verdict, never an unhandled exception.

Crash steps ride the ``faults.INJECT`` seam ``store._atomic_write``
announces (post-tmp / post-fsync / post-rename / pre-dir-fsync): an
injected ``faults.CrashPoint`` dies at the step with NO cleanup, so the
on-disk state is exactly what a SIGKILL there leaves — and one cell per
run uses a REAL SIGKILL in a child process through the same seam to
keep the simulation honest.  Corruption modes (truncate, bitflip, junk,
missing-sibling) synthesize the faults atomic renames can NOT rule out:
bit rot, hand edits, partial copies.

The SIGKILL idempotency round-trip is the serving acceptance cell: a
request submitted with an ``idempotency_key`` into a journaled service,
SIGKILL before it runs, restart, duplicate resubmission — the check
runs EXACTLY once and the duplicate gets the original request id.

Usage:
  python tools/crashpoint.py --matrix     # the full matrix
  python tools/crashpoint.py --smoke      # the docker/bin/test subset
  python tools/crashpoint.py --surface ladder --matrix
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import traceback
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "tools"))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from genhist import corrupt, valid_register_history  # noqa: E402

from jepsen_tpu import faults  # noqa: E402
from jepsen_tpu import models as m  # noqa: E402
from jepsen_tpu.obs import regress  # noqa: E402
from jepsen_tpu.parallel import batch as pb  # noqa: E402
from jepsen_tpu.serve import service as _svc_mod  # noqa: E402
from jepsen_tpu.store import checkpoint as ckpt  # noqa: E402
from jepsen_tpu.store import durable  # noqa: E402

#: the pinned ladder (chaos_check's shapes, so docker runs share warm
#: kernels with the chaos gates that precede this stage).
LADDER = dict(capacity=(8, 64, 512), cpu_fallback=False,
              exact_escalation=(), confirm_refutations=False)

#: the chunk surface's spill-forcing scan (chaos_check.SPILL_LADDER).
CHUNK = dict(capacity=(16,), chunk_barriers=8, spill=True)

#: CheckService kwargs whose launches run the SAME ladder as the
#: baseline (verdict identity is the invariant; a config drift here
#: would fail cells for the wrong reason).
SVC_OPTS = dict(warm_pool=False, **LADDER)

STEPS = ("post-tmp", "post-fsync", "post-rename", "pre-dir-fsync")
MODES = ("truncate", "bitflip", "junk", "missing-sibling")


def build_histories(n: int, ops: int = 30, procs: int = 3,
                    seed0: int = 7000):
    out = []
    for i in range(n):
        h = valid_register_history(ops, procs, seed=seed0 + i,
                                   info_rate=0.35)
        if i % 3 == 2:
            h = corrupt(h, seed=i)
        out.append(h)
    return out


def verdicts(results):
    return [r["valid?"] for r in results]


# ---------------------------------------------------------------------------
# Cell harness
# ---------------------------------------------------------------------------

RESULTS: list[dict] = []


def cell(surface: str, kind: str, label: str, fn) -> bool:
    """Run one matrix cell; the invariant check lives inside ``fn``
    (assertions).  ANY unhandled exception fails the cell — that IS the
    invariant."""
    try:
        fn()
        ok, err = True, None
    except AssertionError as e:
        ok, err = False, f"invariant violated: {e}"
    except BaseException as e:  # noqa: BLE001 — "never an unhandled
        # exception" is the contract being audited
        ok, err = False, f"unhandled {type(e).__name__}: {e}"
        traceback.print_exc()
    RESULTS.append({"surface": surface, "kind": kind, "label": label,
                    "ok": ok, "error": err})
    print(f"  [{'ok' if ok else 'FAIL'}] {surface} / {kind} / {label}"
          + (f" — {err}" if err else ""))
    return ok


def crash_injector(step: str, path_substr: str, nth: int = 1):
    """An INJECT hook that dies (CrashPoint) at the ``nth`` matching
    write-step of a matching path."""
    seen = {"n": 0}

    def inject(ctx, attempt):
        if ctx.get("what") != "store.atomic_write":
            return
        if ctx.get("step") != step:
            return
        if path_substr not in str(ctx.get("path") or ""):
            return
        seen["n"] += 1
        if seen["n"] == nth:
            raise faults.CrashPoint(step, str(ctx.get("path")))

    return inject


def corrupt_file(path: Path, mode: str) -> None:
    data = path.read_bytes()
    if mode == "truncate":
        path.write_bytes(data[: max(1, len(data) // 2)])
    elif mode == "bitflip":
        b = bytearray(data)
        i = int(len(b) * 0.6)
        b[i] ^= 0xFF
        path.write_bytes(bytes(b))
    elif mode == "junk":
        path.write_bytes(b"\x00\xffnot json at all {{{" * 8)
    else:
        raise ValueError(mode)


# ---------------------------------------------------------------------------
# Surface: ladder checkpoint
# ---------------------------------------------------------------------------


def ladder_mid_state(hists, d: Path) -> None:
    """Run the checkpointed ladder killed (CrashPoint) at the 2nd
    json-checkpoint write — leaves a mid-ladder json/npz pair on disk."""
    with faults.inject_scope(
            crash_injector("post-rename", ckpt.CKPT_JSON, nth=2)):
        try:
            pb.batch_analysis(m.CASRegister(None), hists,
                              checkpoint_dir=d, **LADDER)
            raise AssertionError("crash injector never fired")
        except faults.CrashPoint:
            pass


def ladder_cells(hists, baseline, *, smoke: bool) -> None:
    model = m.CASRegister(None)
    steps = STEPS if not smoke else ("post-tmp", "post-rename")
    for step in steps:
        def _run(step=step):
            d = Path(tempfile.mkdtemp(prefix=f"cp-ladder-{step}-"))
            with faults.inject_scope(
                    crash_injector(step, ckpt.CKPT_JSON, nth=2)):
                try:
                    pb.batch_analysis(model, hists, checkpoint_dir=d,
                                      **LADDER)
                    raise AssertionError("crash injector never fired")
                except faults.CrashPoint:
                    pass
            res = pb.batch_analysis(model, hists, checkpoint_dir=d,
                                    resume=True, **LADDER)
            assert verdicts(res) == baseline, \
                f"{verdicts(res)} != {baseline}"

        cell("ladder", "crash-step", step, _run)
    modes = MODES if not smoke else ("truncate", "bitflip",
                                     "missing-sibling")
    for mode in modes:
        def _run(mode=mode):
            d = Path(tempfile.mkdtemp(prefix=f"cp-ladder-{mode}-"))
            ladder_mid_state(hists, d)
            target = d / ckpt.CKPT_JSON
            npz = d / ckpt.CKPT_NPZ
            if mode == "missing-sibling":
                if not npz.exists():
                    return  # no pending lanes this run: cell is vacuous
                npz.unlink()
            else:
                corrupt_file(target, mode)
            res = pb.batch_analysis(model, hists, checkpoint_dir=d,
                                    resume=True, **LADDER)
            assert verdicts(res) == baseline, \
                f"{verdicts(res)} != {baseline}"
            if mode in ("truncate", "junk", "missing-sibling"):
                assert list(d.glob("*.corrupt-*")), \
                    "corrupt artifact was not quarantined aside"

        cell("ladder", "corruption", mode, _run)


#: the child half of the REAL-SIGKILL-at-write-step cell: same pinned
#: workload, an injector that SIGKILLs the process through the
#: _atomic_write seam at the given step of the 2nd checkpoint write.
_KILL_CHILD_SRC = r"""
import os, signal, sys
sys.path.insert(0, {repo!r})
sys.path.insert(0, {tools!r})
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import crashpoint
from jepsen_tpu import faults
from jepsen_tpu import models as m
from jepsen_tpu.parallel import batch as pb
from jepsen_tpu.store import checkpoint as ckpt
seen = {{"n": 0}}
def inject(ctx, attempt):
    if (ctx.get("what") == "store.atomic_write"
            and ctx.get("step") == {step!r}
            and ckpt.CKPT_JSON in str(ctx.get("path") or "")):
        seen["n"] += 1
        if seen["n"] == 2:
            os.kill(os.getpid(), signal.SIGKILL)
hists = crashpoint.build_histories({n})
with faults.inject_scope(inject):
    pb.batch_analysis(m.CASRegister(None), hists,
                      checkpoint_dir={ckpt_dir!r}, **crashpoint.LADDER)
print("CHILD-FINISHED-WITHOUT-KILL")
"""


def sigkill_step_cell(hists, baseline, step: str) -> None:
    def _run():
        d = tempfile.mkdtemp(prefix=f"cp-sigkill-{step}-")
        src = _KILL_CHILD_SRC.format(
            repo=str(REPO), tools=str(REPO / "tools"), step=step,
            n=len(hists), ckpt_dir=d,
        )
        p = subprocess.run(
            [sys.executable, "-c", src], capture_output=True, text=True,
            env=dict(os.environ, JAX_PLATFORMS="cpu"), cwd=str(REPO),
            timeout=600,
        )
        assert p.returncode == -signal.SIGKILL, (
            f"child exited {p.returncode} (expected SIGKILL); stderr "
            f"tail: {p.stderr[-400:]}")
        res = pb.batch_analysis(m.CASRegister(None), hists,
                                checkpoint_dir=d, resume=True, **LADDER)
        assert verdicts(res) == baseline, f"{verdicts(res)} != {baseline}"

    cell("ladder", "real-sigkill", step, _run)


# ---------------------------------------------------------------------------
# Surface: chunk/spill checkpoint
# ---------------------------------------------------------------------------


def chunk_cells(*, smoke: bool) -> None:
    from jepsen_tpu.ops import wgl

    hist = valid_register_history(24, 3, seed=7100, info_rate=0.35)
    model = m.CASRegister(None)
    base = wgl.analysis(model, hist, **CHUNK)["valid?"]
    steps = ("post-rename",) if smoke else STEPS
    for step in steps:
        def _run(step=step):
            d = Path(tempfile.mkdtemp(prefix=f"cp-chunk-{step}-"))
            with faults.inject_scope(
                    crash_injector(step, ckpt.CHUNK_JSON, nth=2)):
                try:
                    wgl.analysis(model, hist, checkpoint_dir=d, **CHUNK)
                    raise AssertionError("crash injector never fired")
                except faults.CrashPoint:
                    pass
            r = wgl.analysis(model, hist, checkpoint_dir=d, resume=True,
                             **CHUNK)
            assert r["valid?"] == base, f"{r['valid?']} != {base}"

        cell("chunk", "crash-step", step, _run)
    modes = ("bitflip",) if smoke else ("truncate", "bitflip", "junk",
                                        "missing-sibling")
    for mode in modes:
        def _run(mode=mode):
            d = Path(tempfile.mkdtemp(prefix=f"cp-chunk-{mode}-"))
            with faults.inject_scope(
                    crash_injector("post-rename", ckpt.CHUNK_JSON, nth=2)):
                try:
                    wgl.analysis(model, hist, checkpoint_dir=d, **CHUNK)
                    raise AssertionError("crash injector never fired")
                except faults.CrashPoint:
                    pass
            if mode == "missing-sibling":
                (d / ckpt.CHUNK_NPZ).unlink()
            else:
                corrupt_file(d / ckpt.CHUNK_JSON, mode)
            r = wgl.analysis(model, hist, checkpoint_dir=d, resume=True,
                             **CHUNK)
            assert r["valid?"] == base, f"{r['valid?']} != {base}"

        cell("chunk", "corruption", mode, _run)


# ---------------------------------------------------------------------------
# Surface: stream checkpoint (checker.streaming)
# ---------------------------------------------------------------------------


def stream_cells(*, smoke: bool) -> None:
    """The per-stream checkpoint pair (STREAM_JSON/STREAM_NPZ, written
    every feed): crash-steps must resume to the uninterrupted verdict;
    corruption must quarantine and stream FRESH to that same verdict —
    a poisoned carried frontier must never decide anything."""
    from jepsen_tpu.checker import streaming as _streaming

    hist = corrupt(valid_register_history(30, 3, seed=7300, info_rate=0.35),
                   seed=2)
    model = m.CASRegister(None)
    cap = LADDER["capacity"]
    base = _streaming.stream_check(model, hist, feed_ops=8,
                                   capacity=cap)[0]["valid?"]

    def crashed_mid_stream(step: str) -> Path:
        """Feed with checkpointing until the injected CrashPoint kills
        the stream at its 2nd checkpoint write."""
        d = Path(tempfile.mkdtemp(prefix=f"cp-stream-{step}-"))
        with faults.inject_scope(
                crash_injector(step, ckpt.STREAM_JSON, nth=2)):
            try:
                _streaming.stream_check(model, hist, feed_ops=8,
                                        capacity=cap, checkpoint_dir=d)
                raise AssertionError("crash injector never fired")
            except faults.CrashPoint:
                pass
        return d

    steps = ("post-rename",) if smoke else STEPS
    for step in steps:
        def _run(step=step):
            d = crashed_mid_stream(step)
            r, _ = _streaming.stream_check(model, hist, feed_ops=8,
                                           capacity=cap, checkpoint_dir=d,
                                           resume=True)
            assert r["valid?"] == base, f"{r['valid?']} != {base}"

        cell("stream", "crash-step", step, _run)

    modes = ("bitflip",) if smoke else ("truncate", "bitflip", "junk",
                                        "missing-sibling")
    for mode in modes:
        def _run(mode=mode):
            d = crashed_mid_stream("post-rename")
            if mode == "missing-sibling":
                (d / ckpt.STREAM_NPZ).unlink()
            else:
                corrupt_file(d / ckpt.STREAM_JSON, mode)
            r, _ = _streaming.stream_check(model, hist, feed_ops=8,
                                           capacity=cap, checkpoint_dir=d,
                                           resume=True)
            assert r["valid?"] == base, f"{r['valid?']} != {base}"
            if mode != "missing-sibling":
                assert list(d.glob("*.corrupt-*")), (
                    "corrupt stream checkpoint was not quarantined")

        cell("stream", "corruption", mode, _run)


# ---------------------------------------------------------------------------
# Surface: admission journal
# ---------------------------------------------------------------------------


def journal_cells(hists, baseline, *, smoke: bool) -> None:
    def make_queue(jdir: str) -> list[str]:
        """A journaled queue nobody ran: submit into a never-started
        service (the scheduler never picks the work up), keep the ids,
        abandon the instance — the journal files ARE the lost queue."""
        svc = _svc_mod.CheckService(journal_dir=jdir, **SVC_OPTS)
        ids = [svc.submit(h).id for h in hists]
        return ids

    def drive(jdir: str) -> dict:
        """A fresh service over the same journal: recover + step until
        the queue drains; returns {req_id: verdict}."""
        svc = _svc_mod.CheckService(journal_dir=jdir, **SVC_OPTS)
        svc.recover()
        for _ in range(64):
            if svc.stats()["queue_depth"] == 0:
                break
            svc.step()
        out = {}
        for rid, req in list(svc._requests.items()):
            out[rid] = (req.result or {}).get("valid?")
        return out

    def _crash_window(leave: str):
        jdir = tempfile.mkdtemp(prefix="cp-journal-")
        ids = make_queue(jdir)
        # synthesize the crash window on the LAST entry: pre-rename
        # steps leave only a torn tmp (no entry), post-rename leaves
        # the complete entry
        lost = []
        if leave in ("post-tmp", "post-fsync"):
            victim = Path(jdir) / f"req-{ids[-1]}.json"
            torn = victim.read_bytes()[:20]
            victim.unlink()
            (Path(jdir) / f"req-{ids[-1]}.json.xyz123.tmp").write_bytes(torn)
            lost = [ids[-1]]
        got = drive(jdir)
        for i, rid in enumerate(ids):
            if rid in lost:
                assert rid not in got, "a torn tmp must not replay"
                continue
            assert got.get(rid) == baseline[i], (
                f"replayed {rid}: {got.get(rid)} != {baseline[i]}")
        # the torn tmp is an orphan the start-time sweep reclaims
        swept = durable.sweep_tmp(jdir, min_age_s=0.0, what="crashpoint")
        assert swept == (1 if lost else 0), (swept, lost)
        assert not list(Path(jdir).glob("*.tmp"))

    steps = ("post-tmp", "post-rename") if smoke else STEPS
    for step in steps:
        cell("journal", "crash-step", step,
             lambda step=step: _crash_window(step))

    modes = ("bitflip",) if smoke else ("truncate", "bitflip", "junk")
    for mode in modes:
        def _run(mode=mode):
            jdir = tempfile.mkdtemp(prefix="cp-journal-")
            ids = make_queue(jdir)
            victim = Path(jdir) / f"req-{ids[0]}.json"
            corrupt_file(victim, mode)
            got = drive(jdir)
            assert list(Path(jdir).glob("*.corrupt-*")), \
                "corrupt journal entry was not quarantined"
            for i, rid in enumerate(ids[1:], start=1):
                assert got.get(rid) == baseline[i], (
                    f"replayed {rid}: {got.get(rid)} != {baseline[i]}")
            assert got.get(ids[0]) is None, \
                "a corrupt entry must not replay (it must quarantine)"

        cell("journal", "corruption", mode, _run)


# ---------------------------------------------------------------------------
# Surface: drain dir
# ---------------------------------------------------------------------------


def drain_cells(hists, baseline, *, smoke: bool) -> None:
    def make_drain() -> Path:
        ddir = Path(tempfile.mkdtemp(prefix="cp-drain-"))
        svc = _svc_mod.CheckService(drain_dir=ddir, **SVC_OPTS)
        for h in hists:
            svc.submit(h)
        svc.shutdown(drain=True)
        return ddir

    def _clean():
        ddir = make_drain()
        out = _svc_mod.resume_drained(ddir, **{
            k: v for k, v in LADDER.items() if k != "capacity"})
        assert out and "results" in out[0], f"no resumable group: {out}"
        got = [r["valid?"] for g in out for r in g["results"]]
        assert sorted(map(str, got)) == sorted(map(str, baseline))

    cell("drain", "crash-step", "post-rename(clean-resume)", _clean)

    modes = ("junk",) if smoke else ("truncate", "bitflip", "junk")
    for mode in modes:
        def _meta(mode=mode):
            ddir = make_drain()
            subs = [p for p in ddir.iterdir() if p.is_dir()]
            corrupt_file(subs[0] / _svc_mod.DRAIN_META, mode)
            out = _svc_mod.resume_drained(ddir, **{
                k: v for k, v in LADDER.items() if k != "capacity"})
            bad = [g for g in out if "error" in g]
            assert bad and bad[0]["error"].get("reason"), (
                "corrupt drain meta must surface a machine-readable "
                f"report, got {out}")

        cell("drain", "corruption", f"meta-{mode}", _meta)

    def _ckpt_corrupt():
        # a corrupt drain CHECKPOINT (meta intact): resume runs fresh —
        # honest full recovery, verdicts identical
        ddir = make_drain()
        subs = [p for p in ddir.iterdir() if p.is_dir()]
        corrupt_file(subs[0] / ckpt.CKPT_JSON, "bitflip")
        out = _svc_mod.resume_drained(ddir, **{
            k: v for k, v in LADDER.items() if k != "capacity"})
        got = [r["valid?"] for g in out for r in g.get("results", [])]
        assert sorted(map(str, got)) == sorted(map(str, baseline))

    cell("drain", "corruption", "checkpoint-bitflip", _ckpt_corrupt)


# ---------------------------------------------------------------------------
# Surface: perf ledger
# ---------------------------------------------------------------------------


def ledger_cells(*, smoke: bool) -> None:
    def fresh(n=3) -> Path:
        p = Path(tempfile.mkdtemp(prefix="cp-ledger-")) / "ledger.jsonl"
        for i in range(n):
            regress.append_record(
                regress.make_record("bench", {"ops_per_s": 100.0 + i},
                                    fp={"backend": "cpu"}),
                p,
            )
        return p

    def _torn_tail():
        p = fresh()
        with open(p, "a", encoding="utf-8") as fh:
            fh.write('{"kind":"bench","metrics":{"ops_per_s"')  # crash here
        recs, skipped = regress.read_records_checked(p)
        assert len(recs) == 3 and skipped == 1, (len(recs), skipped)
        ok, _rep = regress.gate(recs)
        assert ok is True

    cell("ledger", "crash-step", "post-write(torn-tail)", _torn_tail)

    def _bitflip():
        p = fresh()
        lines = p.read_text().splitlines()
        # flip the middle record's metric value out from under its CRC
        mid = lines[1].replace("101.0", "404.25", 1)
        assert mid != lines[1], "workload drifted; fix the cell"
        p.write_text("\n".join([lines[0], mid, lines[2]]) + "\n")
        recs, skipped = regress.read_records_checked(p)
        assert len(recs) == 2 and skipped == 1, (len(recs), skipped)

    cell("ledger", "corruption", "bitflip", _bitflip)

    if not smoke:
        def _junk():
            p = fresh()
            with open(p, "a", encoding="utf-8") as fh:
                fh.write("\x00\xff garbage line\n{}\n")
            recs, skipped = regress.read_records_checked(p)
            assert len(recs) == 3 and skipped == 2, (len(recs), skipped)

        cell("ledger", "corruption", "junk", _junk)


# ---------------------------------------------------------------------------
# The SIGKILL idempotency round trip (serving acceptance cell)
# ---------------------------------------------------------------------------

_IDEM_CHILD_SRC = r"""
import os, signal, sys
sys.path.insert(0, {repo!r})
sys.path.insert(0, {tools!r})
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import crashpoint
from jepsen_tpu.serve import service as svc_mod
hists = crashpoint.build_histories({n})
svc = svc_mod.CheckService(journal_dir={jdir!r}, idempotency_dir={idir!r},
                           **crashpoint.SVC_OPTS)
fut = svc.submit(hists[0], idempotency_key="cp-idem-key")
print("REQ-ID", fut.id, flush=True)
os.kill(os.getpid(), signal.SIGKILL)
"""


def idempotency_cell(hists, baseline) -> None:
    def _run():
        jdir = tempfile.mkdtemp(prefix="cp-idem-j-")
        idir = tempfile.mkdtemp(prefix="cp-idem-i-")
        src = _IDEM_CHILD_SRC.format(
            repo=str(REPO), tools=str(REPO / "tools"), n=len(hists),
            jdir=jdir, idir=idir,
        )
        p = subprocess.run(
            [sys.executable, "-c", src], capture_output=True, text=True,
            env=dict(os.environ, JAX_PLATFORMS="cpu"), cwd=str(REPO),
            timeout=600,
        )
        assert p.returncode == -signal.SIGKILL, (
            f"child exited {p.returncode}; stderr: {p.stderr[-400:]}")
        orig_id = None
        for ln in p.stdout.splitlines():
            if ln.startswith("REQ-ID "):
                orig_id = ln.split()[1]
        assert orig_id, f"child printed no request id: {p.stdout!r}"
        # restart: recover the journal + idempotency map, then the
        # duplicate resubmission must attach to the replayed request
        svc = _svc_mod.CheckService(
            journal_dir=jdir, idempotency_dir=idir, **SVC_OPTS,
        )
        svc.recover()
        fut = svc.submit(hists[0], idempotency_key="cp-idem-key")
        assert fut.id == orig_id, (
            f"duplicate got a fresh id {fut.id} != original {orig_id}")
        for _ in range(32):
            if fut.done():
                break
            svc.step()
        stats = svc.stats()
        assert fut.result(timeout=5)["valid?"] == baseline[0]
        assert stats["idempotent_hits"] == 1, stats["idempotent_hits"]
        assert stats["batches"] <= 1, (
            f"the check ran {stats['batches']} batches — exactly-once "
            "violated")
        # second duplicate AFTER settling: served from the settled
        # entry, still the original id, still no extra run
        fut2 = svc.submit(hists[0], idempotency_key="cp-idem-key")
        assert fut2.id == orig_id
        assert fut2.result(timeout=5)["valid?"] == baseline[0]
        assert svc.stats()["batches"] <= 1

    cell("idempotency", "real-sigkill", "journal+idem round trip", _run)


# ---------------------------------------------------------------------------
# Cross-process double-claim (fleet shared-dir cell)
# ---------------------------------------------------------------------------

_RACE_CHILD_SRC = r"""
import json, os, sys, time
sys.path.insert(0, {repo!r})
from jepsen_tpu.serve.health import IdempotencyMap
imap = IdempotencyMap({idir!r}, shared=True)
# spin-barrier on the go file so both processes hit claim() together
deadline = time.monotonic() + 30
while not os.path.exists({gofile!r}):
    if time.monotonic() > deadline:
        sys.exit("go file never appeared")
    time.sleep(0.0005)
wins = []
for i in range({rounds}):
    prior = imap.claim(f"race-key-{{i}}", f"req-{{os.getpid()}}-{{i}}",
                       fp=f"fp-{{i}}")
    wins.append(prior is None)
print("WINS", json.dumps(wins), flush=True)
"""


def shared_claim_race_cell() -> None:
    """Two PROCESSES pointed at one shared ``--idempotency-dir`` race
    ``claim()`` on the same keys: the advisory per-key file locks must
    yield exactly ONE winner per key (this is what makes fleet failover
    exactly-once — before the locks, claim-before-admit was only
    guarded in-process and both replicas could run the check)."""

    def _run():
        idir = tempfile.mkdtemp(prefix="cp-idem-race-")
        gofile = os.path.join(idir, "..", "cp-race-go-%d" % os.getpid())
        rounds = 16
        src = _RACE_CHILD_SRC.format(
            repo=str(REPO), idir=idir, gofile=gofile, rounds=rounds)
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        kids = [
            subprocess.Popen(
                [sys.executable, "-c", src], stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True, env=env,
                cwd=str(REPO),
            )
            for _ in range(2)
        ]
        import time as _t
        _t.sleep(0.5)  # let both children reach the spin-barrier
        Path(gofile).touch()
        outs = []
        for p in kids:
            out, _ = p.communicate(timeout=120)
            assert p.returncode == 0, f"racer exited {p.returncode}: {out}"
            outs.append(out)
        wins = []
        for out in outs:
            line = next(ln for ln in out.splitlines()
                        if ln.startswith("WINS "))
            wins.append(json.loads(line[len("WINS "):]))
        os.unlink(gofile)
        for i in range(rounds):
            winners = int(wins[0][i]) + int(wins[1][i])
            assert winners == 1, (
                f"key {i}: {winners} winners — cross-process double-claim"
                if winners > 1 else f"key {i}: no winner — claim lost")

    cell("idempotency", "race", "cross-process double-claim", _run)


# ---------------------------------------------------------------------------
# Main
# ---------------------------------------------------------------------------


def run(surfaces, *, smoke: bool, real_sigkill: bool) -> int:
    hists = build_histories(4)
    print(f"crashpoint: baseline over {len(hists)} histories "
          f"(capacity {LADDER['capacity']})")
    baseline = verdicts(
        pb.batch_analysis(m.CASRegister(None), hists, **LADDER))
    print(f"  baseline verdicts: {baseline}")
    if "ladder" in surfaces:
        print("surface: ladder checkpoint")
        ladder_cells(hists, baseline, smoke=smoke)
        if real_sigkill:
            for step in (("post-fsync",) if smoke else STEPS):
                sigkill_step_cell(hists, baseline, step)
    if "chunk" in surfaces:
        print("surface: chunk/spill checkpoint")
        chunk_cells(smoke=smoke)
    if "stream" in surfaces:
        print("surface: stream checkpoint")
        stream_cells(smoke=smoke)
    if "journal" in surfaces:
        print("surface: admission journal")
        journal_cells(hists, baseline, smoke=smoke)
    if "drain" in surfaces:
        print("surface: drain dir")
        drain_cells(hists, baseline, smoke=smoke)
    if "ledger" in surfaces:
        print("surface: perf ledger")
        ledger_cells(smoke=smoke)
    if "idempotency" in surfaces and real_sigkill:
        print("surface: idempotent resubmission (SIGKILL round trip)")
        idempotency_cell(hists, baseline)
    if "idempotency" in surfaces:
        print("surface: idempotency shared-dir claim race")
        shared_claim_race_cell()
    failed = [r for r in RESULTS if not r["ok"]]
    print(f"crashpoint matrix: {len(RESULTS) - len(failed)}/{len(RESULTS)} "
          "cells green")
    for r in failed:
        print(f"  FAILED {r['surface']}/{r['kind']}/{r['label']}: "
              f"{r['error']}", file=sys.stderr)
    return 1 if failed else 0


ALL_SURFACES = ("ladder", "chunk", "stream", "journal", "drain", "ledger",
                "idempotency")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--matrix", action="store_true",
                    help="the full (surface x crash-step x corruption) "
                         "matrix incl. one real SIGKILL child per step")
    ap.add_argument("--smoke", action="store_true",
                    help="the docker/bin/test subset (fewer cells, one "
                         "real SIGKILL child)")
    ap.add_argument("--surface", action="append", default=None,
                    choices=ALL_SURFACES,
                    help="restrict to one or more surfaces (repeatable)")
    ap.add_argument("--no-sigkill", action="store_true",
                    help="skip the real-SIGKILL child cells (pure "
                         "in-process simulation)")
    ap.add_argument("--json", action="store_true",
                    help="print the cell results as JSON at the end")
    a = ap.parse_args(argv)
    smoke = a.smoke or not a.matrix
    surfaces = tuple(a.surface) if a.surface else ALL_SURFACES
    rc = run(surfaces, smoke=smoke, real_sigkill=not a.no_sigkill)
    if a.json:
        print(json.dumps(RESULTS, indent=1))
    return rc


if __name__ == "__main__":
    sys.exit(main())
